# Build/dev targets — parity-plus with the reference Makefile (reference:
# Makefile:1-8 offers only `build` (conda env) and `clean`). This framework's
# dependencies are preinstalled (jax/flax/optax/...); targets cover the dev
# loop the reference lacked: tests, lint, benchmark.

PY ?= python

.PHONY: test test-cpu lint lint-graft lint-baseline knob-check \
  event-check bench bench-tpu report trace-smoke mem-smoke flight-smoke \
  chaos-smoke ingest-smoke serve-smoke cost-smoke stream-smoke \
  bench-diff clean

test:
	$(PY) -m pytest tests/ -x -q

# Same suite on a virtual 8-device CPU mesh (what tests/conftest.py forces);
# alias kept for discoverability on machines with a TPU attached.
test-cpu: test

lint:
	ruff check mpitree_tpu tests bench.py

# JAX-aware invariants ruff cannot see: host-sync (GL01), recompile (GL02),
# collective-axis (GL03), dtype/tiling (GL04), donation (GL05, path-
# sensitive use-after-donate GL08), host-callback (GL06), Pallas hygiene
# with symbolic-dim facts (GL07), project contracts — partition-spec
# conformance (GL09), the typed env-knob registry (GL10), lock discipline
# for the threaded serving tier (GL11), wire/event ledger congruence
# (GL12) — and the GL00 unused-suppression audit. tools/graftlint,
# dataflow-backed
# (interprocedural traced-value propagation). Pure-AST: runs on any CPU
# box, no accelerator (or even jax) needed. `--explain GLnn` prints a
# rule's rationale. Human format here; CI runs --format github against
# the checked-in baseline so only NEW findings fail a build.
lint-graft:
	$(PY) -m tools.graftlint mpitree_tpu --format human \
	  --baseline tools/graftlint/baseline.json

# Regenerate the baseline snapshot after deliberately accepting findings
# (each entry should be a tracked burn-down item, not a dumping ground —
# the live package currently baselines NOTHING and should stay that way).
lint-baseline:
	$(PY) -m tools.graftlint mpitree_tpu \
	  --write-baseline tools/graftlint/baseline.json

# README knob-table drift gate: the table between the knob-table markers
# must match the typed registry (mpitree_tpu/config/knobs.py). After adding
# or editing a Knob, regenerate with `python -m mpitree_tpu.config --write`.
knob-check:
	$(PY) -m mpitree_tpu.config --check

# README events-section drift gate: the tables between the event-table
# markers must match the typed registry (mpitree_tpu/obs/events.py) —
# the same contract as knob-check, for event kinds and decision keys
# (GL12 checks call-site congruence statically). Regenerate with
# `python -m mpitree_tpu.obs --write`.
event-check:
	$(PY) -m mpitree_tpu.obs --check

bench:
	$(PY) bench.py

# Durable TPU capture: run whenever the accelerator tunnel is up; appends a
# timestamped line (device-engine phases, throughput, HBM GB/s vs roofline)
# to the committed BENCH_TPU.jsonl. bench.py embeds the newest line as
# tpu_last_known when its own live probe fails.
bench-tpu:
	$(PY) bench_tpu.py

# Pretty-print the newest BENCH_TPU.jsonl line with each section's embedded
# run-record digest (engine decision + reason, recompiles, psum bytes) —
# the artifact-side view of every estimator's fit_report_.
report:
	$(PY) bench_tpu.py --report

# Observability v2 gate (ISSUE 9): tiny fit+serve -> one Chrome-trace
# JSON -> golden trace-event schema validation (exit non-zero on a
# schema break or a missing span family). CPU-safe, seconds.
trace-smoke:
	$(PY) examples/obs_trace_run.py --smoke \
	  --out /tmp/mpitree_trace_smoke.json

# Observability v3 gate (ISSUE 12): plan -> fit -> ledger present, live
# watermarks bracketed, planner refusal fires on an absurd budget before
# any dispatch. CPU-safe, seconds.
mem-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/obs_memory_run.py

# Observability v4 gate (ISSUE 13): two fits -> flight store -> clean
# twin diffs green -> injected perf regression and a chaos-skewed build
# both refuse (the divergence localized to its level+channel). CPU-safe,
# seconds.
flight-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/obs_flight_run.py

# Resilience v2 gate (ISSUE 14): one fit survives a chaos-injected
# level-kill via the sub-build retry rung (levels >= k re-dispatch,
# fingerprint pinned identical), one survives a clearing OOM via the
# on-device rescue ladder (priced shrink, zero host failover) —
# exit-code-validated. CPU-safe, seconds.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/resilience_run.py

# Out-of-core ingest gate (ISSUE 15): sketch-merge bit-identity ->
# chunked bin -> bounded-RSS streamed fit from mmap'd shards ->
# fingerprint identity vs the in-memory fit across mesh shapes ->
# planner-derived chunk sizing. Exit-code-validated; CPU-safe, ~a minute.
ingest-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/ingest_run.py

# Serving v2 gate (ISSUE 17): publish a quantized (exactness-gated)
# model -> mixed-QoS burst through the continuous-batching scheduler ->
# typed shed without starvation -> chaos blip on the dispatch seam
# requeued + recovered -> merged scheduler/serving metrics asserted.
# Exit-code-validated; CPU-safe, seconds.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/serving_sched_run.py

# Observability v5 gate (ISSUE 18): priced fit -> per-entry utilization
# + roofline verdict + util trace track, honest None on unknown
# platforms, and the evidence loop (seeded flight store flips an auto
# policy with a typed advisor decision; off-gate restores the static
# one). Exit-code-validated; CPU-safe, seconds.
cost-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/obs_cost_run.py

# Streamed-ensemble gate (ISSUE 20): out-of-core boosting (host loop +
# fused scan) and keyed-bootstrap forests fingerprint-identical to their
# in-memory twins, streamed working set chunk-bounded where the
# in-memory twin's is not, refine tail replayed from the chunk stream,
# one-shot iterators through the spill rung. Exit-code-validated;
# CPU-safe, ~a minute.
stream-smoke:
	JAX_PLATFORMS=cpu $(PY) examples/stream_gbdt_run.py

# Regression gate over the committed CPU baselines (tools/benchdiff over
# BENCH_r*.json): newest round vs the previous parseable one, noise
# thresholds seeded from the stored trajectory. Stdlib-only (no jax) —
# CI runs it with --format github so regressions annotate the PR.
bench-diff:
	$(PY) -m tools.benchdiff --bench $(sort $(wildcard BENCH_r*.json))

clean:
	find . -type d \( -name "__pycache__" -o -name ".pytest_cache" \
	  -o -name ".ruff_cache" \) -exec rm -rf {} +
