# Build/dev targets — parity-plus with the reference Makefile (reference:
# Makefile:1-8 offers only `build` (conda env) and `clean`). This framework's
# dependencies are preinstalled (jax/flax/optax/...); targets cover the dev
# loop the reference lacked: tests, lint, benchmark.

PY ?= python

.PHONY: test test-cpu lint bench clean

test:
	$(PY) -m pytest tests/ -x -q

# Same suite on a virtual 8-device CPU mesh (what tests/conftest.py forces);
# alias kept for discoverability on machines with a TPU attached.
test-cpu: test

lint:
	ruff check mpitree_tpu tests bench.py

bench:
	$(PY) bench.py

clean:
	find . -type d \( -name "__pycache__" -o -name ".pytest_cache" \
	  -o -name ".ruff_cache" \) -exec rm -rf {} +
