"""Streamed-fit driver shared by the tree estimators (ISSUE 15).

``DecisionTreeClassifier``/``DecisionTreeRegressor`` delegate here when
``fit`` receives a :class:`~mpitree_tpu.ingest.StreamedDataset`: the
ingest tier sketches + bins + places the matrix chunk-at-a-time
(``mpitree_tpu.ingest``), then the SAME device engines grow the tree
from the pre-placed ``StreamedBinnedData`` — fingerprint-identical to an
in-memory fit of the same rows (pinned in ``tests/test_ingest.py``).

Streamed-path deltas from the in-memory fit, all recorded on the run
record:

- no host tier and no host failover rung (the numpy builder wants a
  host-resident matrix; the ladder keeps retry + OOM rescue — the
  leaf-wise stance);
- the hybrid refine tail gathers its candidates' raw rows by replaying
  the chunk stream once (``ingest.stream.StreamRowProvider``) instead of
  fancy-indexing a matrix that never materializes; multi-host fits stay
  crown-only (each process streams only its own shard);
- device binning is moot (edges come from the sketch pass).
"""

from __future__ import annotations

import numpy as np

from mpitree_tpu.core.builder import BuildConfig, build_tree
from mpitree_tpu.obs import BuildObserver, note_build_path, note_refine
from mpitree_tpu.parallel import mesh as mesh_lib
from mpitree_tpu.resilience import OomRescue, SnapshotSlot, retry_device
from mpitree_tpu.serving.tables import note_serving
from mpitree_tpu.utils.validation import (
    min_child_weight,
    min_decrease_scaled,
    record_sklearn_attributes,
    resolve_refine,
    validate_fit_targets,
    validate_max_leaf_nodes,
    validate_sample_weight,
)


def is_streamed(X, dataset) -> bool:
    """Whether this fit call is a streamed one (``dataset=`` wins; a
    StreamedDataset passed positionally as X also routes here)."""
    from mpitree_tpu.ingest import StreamedDataset

    if dataset is not None and not isinstance(dataset, StreamedDataset):
        raise TypeError(
            "dataset= must be a mpitree_tpu.ingest.StreamedDataset "
            f"(got {type(dataset).__name__}); in-memory fits pass X, y"
        )
    return isinstance(dataset, StreamedDataset) or isinstance(
        X, StreamedDataset
    )


# graftlint: host-fn — estimator orchestration: ingest, validation and
# the retry ladder are deliberate host work
def streamed_fit(est, X, dataset, y=None, sample_weight=None,
                 trace_to=None):
    """Fit ``est`` from a StreamedDataset; returns ``est``."""
    from mpitree_tpu.ingest import StreamedDataset, ingest_dataset

    ds = dataset if isinstance(dataset, StreamedDataset) else X
    if dataset is not None and X is not None:
        raise ValueError("pass the StreamedDataset as X or dataset=, not both")
    if y is not None:
        # Silently training on the dataset's embedded targets while the
        # caller handed different ones would be a wrong model, not an
        # inconvenience.
        raise ValueError(
            "a StreamedDataset carries its own targets; fit(dataset) "
            "takes no separate y — rebuild the dataset with the labels "
            "you want"
        )
    task = est._task
    if task == "regression" and est.criterion not in (
        "squared_error", "mse"
    ):
        raise ValueError(
            f"unknown regression criterion: {est.criterion!r}"
        )
    timer = obs = BuildObserver()
    if trace_to is not None:
        obs.trace_to(trace_to)

    mln = validate_max_leaf_nodes(est)
    # Placement needs the mesh BEFORE binning (chunks land on their
    # slots), so resolve it first — the streamed path is device-only.
    mesh = mesh_lib.resolve_mesh(
        backend=est.backend, n_devices=est.n_devices
    )
    with timer.phase("bin"):
        res = ingest_dataset(
            ds, mesh=mesh, max_bins=est.max_bins, binning=est.binning,
            obs=obs,
        )
    binned = res.binned
    N, F = binned.n_samples, binned.n_features
    note_build_path(
        obs, host=False, backend=est.backend, n_rows=N, n_features=F,
    )
    est.ingest_stats_ = res.stats

    y_enc, classes = validate_fit_targets(res.y, task=task)
    est.n_features_ = F
    est.n_features_in_ = F
    record_sklearn_attributes(
        est, None, F,
        n_classes=None if classes is None else len(classes),
    )
    if classes is not None:
        est.classes_ = classes

    if sample_weight is not None and res.sample_weight is not None:
        raise ValueError(
            "sample weights arrived both per-chunk and as a fit argument; "
            "pick one"
        )
    sw = validate_sample_weight(
        res.sample_weight if sample_weight is None else sample_weight, N
    )
    if task == "classification" and getattr(est, "class_weight", None):
        from mpitree_tpu.utils.validation import apply_class_weight

        sw = apply_class_weight(est.class_weight, y_enc, classes, sw)

    from mpitree_tpu.utils.monotonic import validate_monotonic_cst

    mono = validate_monotonic_cst(
        est.monotonic_cst, F, task=task,
        **({"n_classes": len(classes)} if task == "classification" else {}),
    )
    # The hybrid tail gathers its candidates' RAW rows by replaying the
    # chunk stream once (ingest.stream.StreamRowProvider), so streamed
    # single-tree fits refine exactly like in-memory ones. Multi-host
    # fits cannot (each process streams only its own shard — the gather
    # would miss remote rows): crown-only, recorded as the streamed skip.
    import jax

    multihost = jax.process_count() > 1
    rd, refine, crown_depth = resolve_refine(
        est.max_depth, est.refine_depth,
        n_rows=N, quantized=binned.quantized,
    )
    if multihost or mono is not None or mln is not None:
        rd, refine, crown_depth = None, False, est.max_depth
    note_refine(
        obs, refine=refine, rd=rd, crown_depth=crown_depth,
        refine_depth_param=est.refine_depth,
        constrained=mono is not None, leafwise=mln is not None,
        streamed=multihost,
    )
    cfg = BuildConfig(
        task=task,
        criterion=est.criterion if task == "classification" else "mse",
        max_depth=crown_depth,
        max_leaf_nodes=mln,
        min_samples_split=est.min_samples_split,
        min_child_weight=min_child_weight(
            est.min_weight_fraction_leaf, sw, N, est.min_samples_leaf,
        ),
        min_decrease_scaled=min_decrease_scaled(
            est.min_impurity_decrease, sw, N
        ),
    )
    if task == "classification":
        y_build, refit = y_enc, None
        n_classes = len(classes)
    else:
        est._y_mean = float(y_enc.mean()) if len(y_enc) else 0.0
        y_build = (y_enc - est._y_mean).astype(np.float32)
        refit = y_enc
        n_classes = None

    from mpitree_tpu.ops.sampling import sampler_for

    sampler = sampler_for(
        est.max_features, est.random_state, F,
        splitter=getattr(est, "splitter", "best"),
    )

    slot = SnapshotSlot()
    rescue = OomRescue(obs=obs, snapshot_slot=slot)

    def _dev():
        return build_tree(
            binned, y_build, config=rescue.apply(cfg), mesh=mesh,
            n_classes=n_classes, sample_weight=sw, refit_targets=refit,
            timer=timer, feature_sampler=sampler, mono_cst=mono,
            snapshot_slot=slot, return_leaf_ids=refine,
        )

    # No host rung: the numpy tier wants a host-resident matrix, which a
    # streamed fit never builds — retry + OOM rescue only (the leaf-wise
    # ladder stance; re-streaming into a host matrix would defeat the
    # out-of-core contract).
    out = retry_device(
        _dev, what=f"{type(est).__name__}.fit streamed build",
        obs=obs, resume=slot, rescue=rescue,
    )
    est.tree_, leaf_ids = out if refine else (out, None)
    if refine:
        from mpitree_tpu.core.hybrid_builder import apply_refine

        est.tree_ = apply_refine(
            est.tree_, leaf_ids, res.row_provider(), y_build, cfg=cfg,
            max_depth=est.max_depth, rd=rd, timer=timer,
            n_classes=n_classes, sample_weight=sw, refit_targets=refit,
            feature_sampler=sampler,
        )
    if est.ccp_alpha:
        from mpitree_tpu.utils.pruning import ccp_prune

        with timer.phase("prune"):
            est.tree_ = ccp_prune(est.tree_, est.ccp_alpha, task=task)
    if mono is not None:
        from mpitree_tpu.utils.monotonic import clip_tree_values

        clip_tree_values(est.tree_, mono, task)
    est.fit_stats_ = timer.summary() if timer.enabled else None
    note_serving(obs, [est.tree_])
    est.fit_report_ = obs.report(tree=est.tree_)
    res.close()  # release the spill store, if the ingest opened one
    return est
