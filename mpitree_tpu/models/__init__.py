"""Scikit-learn-compatible estimators backed by the TPU builder."""
