"""Decision-tree classifiers with the reference's estimator surface.

API parity contract (reference: ``mpitree/tree/decision_tree.py``):

- ``DecisionTreeClassifier(max_depth=None, min_samples_split=2)`` keyword-only
  hyperparameters (``:33-35``), sklearn ``BaseEstimator``/``ClassifierMixin``
  inheritance (``:17``) for ``get_params``/``set_params``/``score``;
- ``fit`` sets ``n_features_``, ``classes_``, ``tree_`` (``:184-189``);
- ``predict_proba`` returns **raw class counts**, not normalized
  probabilities (``:192-227``), and ``predict`` is their argmax (``:248``);
- ``export_text(feature_names=, class_names=, precision=)`` renders the
  identical unicode tree (``:250-307``; see ``utils/export.py``);
- stopping rules: purity, all-rows-identical, ``depth == max_depth``,
  ``n_samples < min_samples_split`` (``:118-123``); split-candidate and
  tie-break semantics per ``ops/impurity.py``.

``ParallelDecisionTreeClassifier`` keeps the reference's name and surface
(``:310-317``) but distributes over a TPU device mesh instead of ``mpirun``:
rows are sharded, histograms psum over ICI, and — like the reference, by
design — the fitted tree is identical at every mesh size.
"""

from __future__ import annotations

import jax
import numpy as np
from sklearn.base import BaseEstimator, ClassifierMixin
from sklearn.utils.validation import check_is_fitted

from mpitree_tpu.core.builder import BuildConfig, build_tree, prefer_host_path
from mpitree_tpu.core.host_builder import build_tree_host
from mpitree_tpu.obs import (
    BuildObserver,
    ReportMixin,
    note_build_path,
    note_refine,
)
from mpitree_tpu.ops.binning import bin_for_engine, ensure_host_binned
from mpitree_tpu.ops.predict import (
    device_tree_arrays,
    predict_leaf_ids,
    predict_mesh,
)
from mpitree_tpu.parallel import mesh as mesh_lib
from mpitree_tpu.resilience import (
    OomRescue,
    SnapshotSlot,
    device_failover,
    retry_device,
)
from mpitree_tpu.serving.tables import note_serving
from mpitree_tpu.utils.export import export_tree_text
from mpitree_tpu.utils.importances import feature_importances
from mpitree_tpu.utils.validation import (
    apply_class_weight,
    feature_names_of,
    min_child_weight,
    min_decrease_scaled,
    record_sklearn_attributes,
    validate_fit_data,
    validate_max_leaf_nodes,
    validate_predict_data,
    resolve_refine,
    validate_sample_weight,
)


class _ClassProperty:
    def __init__(self, f):
        self.f = f

    def __get__(self, obj, owner):
        return self.f(owner)


class DecisionTreeClassifier(ClassifierMixin, ReportMixin, BaseEstimator):
    """TPU-native decision-tree classifier (entropy or Gini criterion).

    Parameters
    ----------
    max_depth : int, optional
        Exact-equality depth cutoff, as in the reference
        (``decision_tree.py:121``); ``None`` = unbounded.
    max_leaf_nodes : int, optional
        Grow the tree leaf-wise (best-first) with at most this many
        leaves: each step expands the open leaf with the largest weighted
        impurity decrease (sklearn's best-first semantics, LightGBM's
        ``num_leaves`` playbook), paying one sibling-pair histogram per
        expansion instead of a full-frontier pass per level
        (``core/leafwise_builder.py``). ``None`` (default) grows
        level-wise. Composes with ``max_depth``; requires a device engine
        (no ``backend="host"``) and currently excludes ``max_features``,
        ``splitter="random"``, ``monotonic_cst``, and the hybrid refine
        tail.
    min_samples_split : int, default=2
        Nodes with fewer samples become leaves (``decision_tree.py:122``).
    criterion : {"entropy", "gini"}, default="entropy"
        The reference implements entropy only; Gini is a target capability
        (BASELINE config 2).
    splitter : {"best", "random"}, default="best"
        "random" draws ONE uniform candidate per (node, feature) and keeps
        the best feature (sklearn's extremely-randomized splitter,
        quantized to this framework's candidate grammar: uniform over the
        node's valid candidate bins). Draws derive from path-keyed hashes
        (``ops/sampling.py``), so every engine and mesh size grows the
        identical tree; like per-node ``max_features``, this runs on the
        levelwise device engine and the numpy host tier.
    max_bins : int, default=256
        Candidate-threshold cap per feature in quantile binning.
    binning : {"auto", "exact", "quantile"}, default="auto"
        "exact" reproduces the reference's every-unique-value candidate set.
    max_features : int, float, "sqrt", "log2", or None, default=None
        Per-node random feature subsets, sklearn's grammar
        (``ops/sampling.py``; LightGBM-style no-redraw rule).
    class_weight : "balanced", dict, or None, default=None
        sklearn-style class weighting, composed into the per-sample weights
        feeding the weighted histograms (``utils/validation.py``).
    min_weight_fraction_leaf : float, default=0.0
        sklearn's leaf-weight floor: a split is invalid unless both sides
        carry at least this fraction of the total fit weight.
    min_samples_leaf : int or float, default=1
        sklearn's leaf-size floor (int = rows, float = fraction of rows,
        ceil'd). Counted in weighted rows — identical to sklearn for
        unweighted fits and integer bootstrap multiplicities; diverges
        under fractional sample weights (``utils/validation.py``).
    random_state : int, optional
        Seed for ``max_features`` draws; fits are deterministic either way
        (``None`` reads as seed 0).
    ccp_alpha : float, default=0.0
        Minimal cost-complexity pruning strength (sklearn semantics,
        ``utils/pruning.py``) — applied host-side to the finished tree, so
        every build engine prunes identically.
    monotonic_cst : array-like of int of shape (n_features,), optional
        sklearn's monotonicity constraints (+1 increasing, -1 decreasing,
        0 none; positive-class probability for this binary-only classifier).
        Enforced in split selection on every engine (``utils/monotonic.py``);
        ``predict`` reflects the bound-clipped values. Divergences from
        sklearn, documented: ``predict_proba`` keeps returning RAW counts
        (the reference contract), so the monotone guarantee applies to
        ``predict``; constrained fits skip the hybrid refine tail.
    n_devices : int, "all", or None, default=None
        Data-mesh width; ``None`` = single device.
    backend : str, optional
        ``None`` = auto: small single-device fits run on the vectorized host
        (numpy) builder, larger ones on the default JAX platform. A platform
        name ("tpu", "cpu", ...) forces the device path on that platform;
        ``"host"`` forces the numpy builder.
    refine_depth : int, "auto", or None
        Hybrid build crossover: the device engines grow the tree to this
        depth (wide data-parallel frontiers), then each still-splittable
        leaf is host-finished by the native C++ sweep with **exact local
        candidates** — recovering the accuracy that global quantile bins
        lose in the deep tail (``core/hybrid_builder.py``). ``"auto"``
        (default) engages the hybrid only when quantile binning capped some
        feature's candidate set and targets ~2k-row crown leaves; ``None``
        = single-engine build.
    """

    _task = "classification"

    def __init__(self, *, max_depth=None, max_leaf_nodes=None,
                 min_samples_split=2,
                 criterion="entropy", splitter="best", max_bins=256,
                 binning="auto",
                 max_features=None, class_weight=None,
                 min_weight_fraction_leaf=0.0, min_samples_leaf=1,
                 random_state=None,
                 n_devices=None, backend=None, refine_depth="auto",
                 ccp_alpha=0.0, min_impurity_decrease=0.0,
                 monotonic_cst=None):
        self.max_depth = max_depth
        self.max_leaf_nodes = max_leaf_nodes
        self.min_samples_split = min_samples_split
        self.criterion = criterion
        self.splitter = splitter
        self.max_bins = max_bins
        self.binning = binning
        self.max_features = max_features
        self.class_weight = class_weight
        self.min_weight_fraction_leaf = min_weight_fraction_leaf
        self.min_samples_leaf = min_samples_leaf
        self.random_state = random_state
        self.n_devices = n_devices
        self.backend = backend
        self.refine_depth = refine_depth
        self.ccp_alpha = ccp_alpha
        self.min_impurity_decrease = min_impurity_decrease
        self.monotonic_cst = monotonic_cst

    # -- fitting -----------------------------------------------------------
    def fit(self, X=None, y=None, sample_weight=None, *, trace_to=None,
            dataset=None):
        # Out-of-core streamed fits (ISSUE 15): a StreamedDataset — passed
        # as X or via dataset= — routes through the chunked ingest tier;
        # the raw matrix never materializes on this host.
        from mpitree_tpu.models._streamed import is_streamed, streamed_fit

        if is_streamed(X, dataset):
            return streamed_fit(
                self, X, dataset, y=y, sample_weight=sample_weight,
                trace_to=trace_to,
            )
        names = feature_names_of(X)
        X, y_enc, classes = validate_fit_data(X, y, task="classification")
        self.n_features_ = X.shape[1]
        self.n_features_in_ = X.shape[1]
        self.classes_ = classes
        record_sklearn_attributes(
            self, names, X.shape[1], n_classes=len(classes)
        )

        from mpitree_tpu.utils.monotonic import validate_monotonic_cst

        mono = validate_monotonic_cst(
            self.monotonic_cst, X.shape[1], task="classification",
            n_classes=len(classes),
        )

        mln = validate_max_leaf_nodes(self)

        timer = obs = BuildObserver()
        if trace_to is not None:
            # Chrome-trace timeline (obs/trace.py): a path, or a shared
            # TraceSink covering several fits + serving in one file.
            obs.trace_to(trace_to)
        host = (
            prefer_host_path(*X.shape, self.n_devices, self.backend)
            and mln is None  # best-first growth lives in the device engines
        )
        note_build_path(
            obs, host=host, backend=self.backend,
            n_rows=X.shape[0], n_features=X.shape[1],
        )
        with timer.phase("bin"):
            binned = bin_for_engine(
                X, max_bins=self.max_bins, binning=self.binning,
                device=not host, backend=self.backend,
            )
        sw = validate_sample_weight(sample_weight, X.shape[0])
        sw = apply_class_weight(self.class_weight, y_enc, classes, sw)
        rd, refine, crown_depth = resolve_refine(
            self.max_depth, self.refine_depth,
            n_rows=X.shape[0], quantized=binned.quantized,
        )
        if mono is not None:
            # Constrained fits single-engine the whole depth: the hybrid
            # tail would need crown bounds threaded across the graft seam;
            # constraint semantics take precedence over tail perf here.
            rd, refine, crown_depth = None, False, self.max_depth
        if mln is not None:
            # The leaf budget is global: a host tail re-growing crown
            # leaves would blow past it, so best-first fits single-engine.
            rd, refine, crown_depth = None, False, self.max_depth
        note_refine(
            obs, refine=refine, rd=rd, crown_depth=crown_depth,
            refine_depth_param=self.refine_depth,
            constrained=mono is not None, leafwise=mln is not None,
        )
        cfg = BuildConfig(
            task="classification",
            criterion=self.criterion,
            max_depth=crown_depth,
            max_leaf_nodes=mln,
            min_samples_split=self.min_samples_split,
            min_child_weight=min_child_weight(
                self.min_weight_fraction_leaf, sw, X.shape[0],
                self.min_samples_leaf,
            ),
            min_decrease_scaled=min_decrease_scaled(
                self.min_impurity_decrease, sw, X.shape[0]
            ),
        )
        from mpitree_tpu.ops.sampling import sampler_for

        sampler = sampler_for(
            self.max_features, self.random_state, X.shape[1],
            splitter=getattr(self, "splitter", "best"),
        )
        if host:
            with timer.phase("host_build"):
                res = build_tree_host(
                    binned, y_enc, config=cfg, n_classes=len(classes),
                    sample_weight=sw, return_leaf_ids=refine,
                    feature_sampler=sampler, mono_cst=mono, timer=timer,
                )
                self.tree_, leaf_ids = res if refine else (res, None)
            obs.decision(
                "engine", "host",
                reason=obs.record.decisions["build_path"]["reason"],
            )
        else:
            mesh = mesh_lib.resolve_mesh(
                backend=self.backend, n_devices=self.n_devices
            )

            # Resilience v2 (ISSUE 14): the snapshot slot lets the engine
            # resume a transient failure from the last completed level/
            # expansion; the OOM rescue re-dispatches a shrinkable
            # RESOURCE_EXHAUSTED on-device under a shrunk, re-preflighted
            # plan (rescue.apply below) before the host rung.
            slot = SnapshotSlot()
            rescue = OomRescue(obs=obs, snapshot_slot=slot)

            def _dev():
                res = build_tree(
                    binned, y_enc, config=rescue.apply(cfg), mesh=mesh,
                    n_classes=len(classes), sample_weight=sw, timer=timer,
                    return_leaf_ids=refine, feature_sampler=sampler,
                    mono_cst=mono, snapshot_slot=slot,
                )
                # The build maintains row->leaf ids on device; fetching them
                # here spares the refine a second full-matrix descent (and X
                # upload).
                return res if refine else (res, None)

            def _host():
                # Elastic recovery (utils/elastic.py): the host tier
                # consumes the same binned matrix and produces the identical
                # tree, so a lost accelerator costs wall-clock, not the fit.
                # A device-binned matrix cannot be pulled back from a dead
                # accelerator: re-bin on host (bit-identical by contract).
                obs.event(
                    "device_failover",
                    "device build failed; rebuilding on the host tier",
                )
                binned_h = ensure_host_binned(
                    binned, X, max_bins=self.max_bins, binning=self.binning
                )
                with timer.phase("host_build"):
                    res = build_tree_host(
                        binned_h, y_enc, config=cfg, n_classes=len(classes),
                        sample_weight=sw, return_leaf_ids=refine,
                        feature_sampler=sampler, mono_cst=mono, timer=timer,
                    )
                    return res if refine else (res, None)

            if mln is not None:
                # No host twin for the best-first frontier (the numpy
                # tier grows level-wise only): the ladder keeps its retry
                # rung and stops there — the boosting-round stance.
                self.tree_, leaf_ids = retry_device(
                    _dev,
                    what=f"{type(self).__name__}.fit leaf-wise build",
                    obs=obs, resume=slot, rescue=rescue,
                )
            else:
                self.tree_, leaf_ids = device_failover(
                    _dev, _host,
                    what=f"{type(self).__name__}.fit device build",
                    obs=obs, resume=slot, rescue=rescue,
                )
        if refine:
            from mpitree_tpu.core.hybrid_builder import apply_refine

            self.tree_ = apply_refine(
                self.tree_, leaf_ids, X, y_enc, cfg=cfg,
                max_depth=self.max_depth, rd=rd, timer=timer,
                n_classes=len(classes), sample_weight=sw,
                feature_sampler=sampler,
            )
        if self.ccp_alpha:
            from mpitree_tpu.utils.pruning import ccp_prune

            with timer.phase("prune"):
                self.tree_ = ccp_prune(
                    self.tree_, self.ccp_alpha, task="classification"
                )
        if mono is not None:
            from mpitree_tpu.utils.monotonic import clip_tree_values

            clip_tree_values(self.tree_, mono, "classification")
        self.fit_stats_ = timer.summary() if timer.enabled else None
        # Serving-table notes (mpitree_tpu.serving): what the compiled
        # inference path will flatten this tree into — true descent depth,
        # node count — so the fit record carries the predict-side plan.
        note_serving(obs, [self.tree_])
        # Always-on structured run record (mpitree_tpu.obs): engine
        # decision + reason, counters, compile/collective accounting,
        # typed events; spans/per-level rows under MPITREE_TPU_PROFILE=1.
        self.fit_report_ = obs.report(tree=self.tree_)
        return self

    def cost_complexity_pruning_path(self, X, y, sample_weight=None):
        """sklearn's diagnostic: effective alphas and total leaf
        impurities along the minimal cost-complexity pruning path
        (one shared weakest-link sweep, ``utils/pruning.py``)."""
        from mpitree_tpu.utils.pruning import pruning_path_for

        return pruning_path_for(self, X, y, sample_weight=sample_weight)

    # -- inference ---------------------------------------------------------
    def _leaf_ids(self, X: np.ndarray) -> np.ndarray:
        t = self.tree_
        return np.asarray(predict_leaf_ids(
            X, device_tree_arrays(t), t.max_depth, predict_mesh(self)
        ))

    def predict_proba(self, X):
        """Raw per-class leaf counts — the reference's quirk
        (``decision_tree.py:192-227`` returns occurrences, not probabilities)."""
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        return self.tree_.count[self._leaf_ids(X)]

    def decision_path(self, X):
        """sklearn's ``decision_path``: CSR indicator of the nodes each
        sample traverses (``utils/export.py``)."""
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        from mpitree_tpu.utils.export import tree_decision_path

        return tree_decision_path(self.tree_, self._leaf_ids(X))

    def apply(self, X):
        """sklearn's ``tree.apply``: the leaf index each sample lands in
        (vectorized gather-descent over the struct-of-arrays tree — the
        reference walks a Python recursion per row,
        ``decision_tree.py:208-225``)."""
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        return self._leaf_ids(X).astype(np.int64)

    def predict(self, X):
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        if getattr(self, "monotonic_cst", None) is not None:
            # Constrained fits predict from the bound-CLIPPED leaf labels
            # (clip_tree_values wrote them into tree_.value) — the raw-count
            # argmax below would ignore the clip and can break the monotone
            # guarantee exactly where a bound binds. predict_proba stays on
            # raw counts by reference contract (documented divergence).
            return self.classes_[self.tree_.value[self._leaf_ids(X)]]
        idx = self.tree_.count[self._leaf_ids(X)].argmax(axis=1)
        return self.classes_[idx]

    # -- introspection -----------------------------------------------------
    def export_dot(self, *, feature_names=None, class_names=None,
                   precision=2):
        """Graphviz source of the fitted tree (sklearn's export_graphviz
        idiom; ``utils/export.py``)."""
        check_is_fitted(self)
        from mpitree_tpu.utils.export import export_tree_dot

        return export_tree_dot(
            self.tree_, feature_names=feature_names,
            class_names=class_names, precision=precision,
            task="classification", n_features=self.n_features_,
        )

    def export_text(self, *, feature_names=None, class_names=None, precision=2):
        check_is_fitted(self)
        return export_tree_text(
            self.tree_, feature_names=feature_names, class_names=class_names,
            precision=precision, task="classification",
        )

    @property
    def nodes_(self):
        """Reference-style linked ``Node`` view of the fitted tree."""
        check_is_fitted(self)
        return self.tree_.to_nodes()

    @property
    def feature_importances_(self):
        """Normalized mean-decrease-in-impurity importances (sklearn idiom;
        the reference exposes none)."""
        check_is_fitted(self)
        return feature_importances(
            self.tree_, self.n_features_, criterion=self.criterion,
            task="classification",
        )

    def get_depth(self):
        check_is_fitted(self)
        return self.tree_.max_depth

    def get_n_leaves(self):
        check_is_fitted(self)
        return self.tree_.n_leaves

    def __sklearn_is_fitted__(self):
        return hasattr(self, "tree_")


class ParallelDecisionTreeClassifier(DecisionTreeClassifier):
    """Mesh-parallel classifier — the reference's MPI class, minus ``mpirun``.

    The reference binds ``MPI.COMM_WORLD`` at import time and fans subtree
    tasks over recursively split communicators
    (``decision_tree.py:310-338``). Here ``n_devices`` defaults to every
    visible device: rows shard over the ``data`` mesh axis and per-level
    histograms reduce with ``lax.psum`` over ICI. The fitted tree is
    bit-identical to the single-device build (integer-valued f32 histogram
    sums are order-independent), mirroring the reference's
    every-rank-holds-the-same-tree contract (``:456-475``).

    ``WORLD_RANK``/``WORLD_SIZE`` are kept for source familiarity
    (``:315-317``): process index / local device count. Single-host
    single-process runs see rank 0 — same as the reference's notebook usage.
    """

    def __init__(self, *, max_depth=None, max_leaf_nodes=None,
                 min_samples_split=2,
                 criterion="entropy", splitter="best", max_bins=256,
                 binning="auto",
                 max_features=None, class_weight=None,
                 min_weight_fraction_leaf=0.0, min_samples_leaf=1,
                 random_state=None,
                 n_devices="all", backend=None, refine_depth="auto",
                 ccp_alpha=0.0, min_impurity_decrease=0.0,
                 monotonic_cst=None):
        super().__init__(
            max_depth=max_depth, max_leaf_nodes=max_leaf_nodes,
            min_samples_split=min_samples_split,
            criterion=criterion, splitter=splitter, max_bins=max_bins,
            binning=binning,
            max_features=max_features, class_weight=class_weight,
            min_weight_fraction_leaf=min_weight_fraction_leaf,
            min_samples_leaf=min_samples_leaf, random_state=random_state,
            n_devices=n_devices, backend=backend, refine_depth=refine_depth,
            ccp_alpha=ccp_alpha, min_impurity_decrease=min_impurity_decrease,
            monotonic_cst=monotonic_cst,
        )

    @_ClassProperty
    def WORLD_RANK(cls):
        return jax.process_index()

    @_ClassProperty
    def WORLD_SIZE(cls):
        return len(jax.devices())
