"""Decision-tree regressor with MSE split criterion.

The reference implements no regressor — this is a target capability
(BASELINE config 4: "DecisionTreeRegressor (MSE split criterion) on
California housing") built on the same level-synchronous histogram machinery,
following the reference's estimator idiom (keyword-only hyperparameters,
sklearn mixin inheritance; reference: ``mpitree/tree/decision_tree.py:17,33``).

Split cost is the weighted child variance computed from psum'd
``(w, w*y, w*y^2)`` moment histograms (``ops/impurity.py``); the leaf value is
the node mean. Targets are centered around their global mean before moment
accumulation to keep the f32 ``E[y^2] - E[y]^2`` cancellation benign, and
un-centered on the way out.
"""

from __future__ import annotations

import jax
import numpy as np
from sklearn.base import BaseEstimator, RegressorMixin
from sklearn.utils.validation import check_is_fitted

from mpitree_tpu.core.builder import BuildConfig, build_tree, prefer_host_path
from mpitree_tpu.core.host_builder import build_tree_host
from mpitree_tpu.obs import (
    BuildObserver,
    ReportMixin,
    note_build_path,
    note_refine,
)
from mpitree_tpu.ops.binning import bin_for_engine, ensure_host_binned
from mpitree_tpu.ops.predict import (
    device_tree_arrays,
    predict_leaf_ids,
    predict_mesh,
)
from mpitree_tpu.parallel import mesh as mesh_lib
from mpitree_tpu.resilience import (
    OomRescue,
    SnapshotSlot,
    device_failover,
    retry_device,
)
from mpitree_tpu.serving.tables import note_serving
from mpitree_tpu.utils.export import export_tree_text
from mpitree_tpu.utils.importances import feature_importances
from mpitree_tpu.utils.validation import (
    feature_names_of,
    min_child_weight,
    min_decrease_scaled,
    record_sklearn_attributes,
    validate_fit_data,
    validate_predict_data,
    resolve_refine,
    validate_max_leaf_nodes,
    validate_sample_weight,
)


class DecisionTreeRegressor(RegressorMixin, ReportMixin, BaseEstimator):
    """TPU-native regression tree (squared-error criterion).

    Parameters mirror :class:`DecisionTreeClassifier`; ``criterion`` accepts
    "squared_error" (alias "mse").
    """

    _task = "regression"

    def __init__(self, *, max_depth=None, max_leaf_nodes=None,
                 min_samples_split=2,
                 criterion="squared_error", splitter="best", max_bins=256,
                 binning="auto",
                 max_features=None, min_weight_fraction_leaf=0.0,
                 min_samples_leaf=1, random_state=None,
                 n_devices=None, backend=None, refine_depth="auto",
                 ccp_alpha=0.0, min_impurity_decrease=0.0,
                 monotonic_cst=None):
        self.max_depth = max_depth
        self.max_leaf_nodes = max_leaf_nodes
        self.min_samples_split = min_samples_split
        self.criterion = criterion
        self.splitter = splitter
        self.max_bins = max_bins
        self.binning = binning
        self.max_features = max_features
        self.min_weight_fraction_leaf = min_weight_fraction_leaf
        self.min_samples_leaf = min_samples_leaf
        self.random_state = random_state
        self.n_devices = n_devices
        self.backend = backend
        self.refine_depth = refine_depth
        self.ccp_alpha = ccp_alpha
        self.min_impurity_decrease = min_impurity_decrease
        self.monotonic_cst = monotonic_cst

    def fit(self, X=None, y=None, sample_weight=None, *, trace_to=None,
            dataset=None):
        if self.criterion not in ("squared_error", "mse"):
            raise ValueError(f"unknown regression criterion: {self.criterion!r}")
        # Out-of-core streamed fits (ISSUE 15): a StreamedDataset — passed
        # as X or via dataset= — routes through the chunked ingest tier.
        from mpitree_tpu.models._streamed import is_streamed, streamed_fit

        if is_streamed(X, dataset):
            return streamed_fit(
                self, X, dataset, y=y, sample_weight=sample_weight,
                trace_to=trace_to,
            )
        names = feature_names_of(X)
        X, y64, _ = validate_fit_data(X, y, task="regression")
        self.n_features_ = X.shape[1]
        self.n_features_in_ = X.shape[1]
        record_sklearn_attributes(self, names, X.shape[1])

        y_mean = float(y64.mean()) if len(y64) else 0.0
        self._y_mean = y_mean

        from mpitree_tpu.utils.monotonic import validate_monotonic_cst

        mono = validate_monotonic_cst(
            self.monotonic_cst, X.shape[1], task="regression"
        )

        mln = validate_max_leaf_nodes(self)

        timer = obs = BuildObserver()
        if trace_to is not None:
            # Chrome-trace timeline (obs/trace.py): a path, or a shared
            # TraceSink covering several fits + serving in one file.
            obs.trace_to(trace_to)
        host = (
            prefer_host_path(*X.shape, self.n_devices, self.backend)
            and mln is None  # best-first growth lives in the device engines
        )
        note_build_path(
            obs, host=host, backend=self.backend,
            n_rows=X.shape[0], n_features=X.shape[1],
        )
        with timer.phase("bin"):
            binned = bin_for_engine(
                X, max_bins=self.max_bins, binning=self.binning,
                device=not host, backend=self.backend,
            )
        sw = validate_sample_weight(sample_weight, X.shape[0])
        rd, refine, crown_depth = resolve_refine(
            self.max_depth, self.refine_depth,
            n_rows=X.shape[0], quantized=binned.quantized,
        )
        if mono is not None:
            # Constrained fits single-engine the whole depth: the hybrid
            # tail would need crown bounds threaded across the graft seam;
            # constraint semantics take precedence over tail perf here.
            rd, refine, crown_depth = None, False, self.max_depth
        if mln is not None:
            # The leaf budget is global: a host tail re-growing crown
            # leaves would blow past it, so best-first fits single-engine.
            rd, refine, crown_depth = None, False, self.max_depth
        note_refine(
            obs, refine=refine, rd=rd, crown_depth=crown_depth,
            refine_depth_param=self.refine_depth,
            constrained=mono is not None, leafwise=mln is not None,
        )
        cfg = BuildConfig(
            task="regression",
            criterion="mse",
            max_depth=crown_depth,
            max_leaf_nodes=mln,
            min_samples_split=self.min_samples_split,
            min_child_weight=min_child_weight(
                self.min_weight_fraction_leaf, sw, X.shape[0],
                self.min_samples_leaf,
            ),
            min_decrease_scaled=min_decrease_scaled(
                self.min_impurity_decrease, sw, X.shape[0]
            ),
        )
        y_c = (y64 - y_mean).astype(np.float32)
        from mpitree_tpu.ops.sampling import sampler_for

        sampler = sampler_for(
            self.max_features, self.random_state, X.shape[1],
            splitter=getattr(self, "splitter", "best"),
        )
        if host:
            with timer.phase("host_build"):
                res = build_tree_host(
                    binned, y_c, config=cfg, sample_weight=sw,
                    refit_targets=y64, return_leaf_ids=refine,
                    feature_sampler=sampler, mono_cst=mono, timer=timer,
                )
                self.tree_, leaf_ids = res if refine else (res, None)
            obs.decision(
                "engine", "host",
                reason=obs.record.decisions["build_path"]["reason"],
            )
        else:
            mesh = mesh_lib.resolve_mesh(
                backend=self.backend, n_devices=self.n_devices
            )

            # Resilience v2 (ISSUE 14): sub-build resume + priced OOM
            # rescue, shared with the retry ladder (classifier twin).
            slot = SnapshotSlot()
            rescue = OomRescue(obs=obs, snapshot_slot=slot)

            def _dev():
                res = build_tree(
                    binned, y_c, config=rescue.apply(cfg), mesh=mesh,
                    sample_weight=sw,
                    refit_targets=y64, timer=timer, return_leaf_ids=refine,
                    feature_sampler=sampler, mono_cst=mono,
                    snapshot_slot=slot,
                )
                # Row->leaf ids come straight off the build's device state;
                # a second full-matrix descent would re-upload X for nothing.
                return res if refine else (res, None)

            def _host():
                # Elastic recovery (utils/elastic.py): same binned inputs,
                # identical tree — a lost accelerator costs wall-clock only.
                # A device-binned matrix cannot be pulled back from a dead
                # accelerator: re-bin on host (bit-identical by contract).
                obs.event(
                    "device_failover",
                    "device build failed; rebuilding on the host tier",
                )
                binned_h = ensure_host_binned(
                    binned, X, max_bins=self.max_bins, binning=self.binning
                )
                with timer.phase("host_build"):
                    res = build_tree_host(
                        binned_h, y_c, config=cfg, sample_weight=sw,
                        refit_targets=y64, return_leaf_ids=refine,
                        feature_sampler=sampler, mono_cst=mono, timer=timer,
                    )
                    return res if refine else (res, None)

            if mln is not None:
                # No host twin for the best-first frontier (the numpy
                # tier grows level-wise only): the ladder keeps its retry
                # rung and stops there — the boosting-round stance.
                self.tree_, leaf_ids = retry_device(
                    _dev,
                    what=f"{type(self).__name__}.fit leaf-wise build",
                    obs=obs, resume=slot, rescue=rescue,
                )
            else:
                self.tree_, leaf_ids = device_failover(
                    _dev, _host,
                    what=f"{type(self).__name__}.fit device build",
                    obs=obs, resume=slot, rescue=rescue,
                )
        if refine:
            from mpitree_tpu.core.hybrid_builder import apply_refine

            self.tree_ = apply_refine(
                self.tree_, leaf_ids, X, y_c, cfg=cfg,
                max_depth=self.max_depth, rd=rd, timer=timer,
                sample_weight=sw, refit_targets=y64,
                feature_sampler=sampler,
            )
        if self.ccp_alpha:
            from mpitree_tpu.utils.pruning import ccp_prune

            with timer.phase("prune"):
                self.tree_ = ccp_prune(
                    self.tree_, self.ccp_alpha, task="regression"
                )
        if mono is not None:
            from mpitree_tpu.utils.monotonic import clip_tree_values

            clip_tree_values(self.tree_, mono, "regression")
        self.fit_stats_ = timer.summary() if timer.enabled else None
        # Serving-table notes (mpitree_tpu.serving) + the always-on
        # structured run record (mpitree_tpu.obs).
        note_serving(obs, [self.tree_])
        self.fit_report_ = obs.report(tree=self.tree_)
        return self

    def cost_complexity_pruning_path(self, X, y, sample_weight=None):
        """sklearn's diagnostic: effective alphas and total leaf
        impurities along the minimal cost-complexity pruning path
        (one shared weakest-link sweep, ``utils/pruning.py``)."""
        from mpitree_tpu.utils.pruning import pruning_path_for

        return pruning_path_for(self, X, y, sample_weight=sample_weight)

    def _leaf_ids(self, X: np.ndarray) -> np.ndarray:
        t = self.tree_
        return np.asarray(predict_leaf_ids(
            X, device_tree_arrays(t), t.max_depth, predict_mesh(self)
        ))

    def decision_path(self, X):
        """sklearn's ``decision_path``: CSR indicator of the nodes each
        sample traverses (``utils/export.py``)."""
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        from mpitree_tpu.utils.export import tree_decision_path

        return tree_decision_path(self.tree_, self._leaf_ids(X))

    def apply(self, X):
        """sklearn's ``tree.apply``: the leaf index each sample lands in
        (vectorized gather-descent over the struct-of-arrays tree — the
        reference walks a Python recursion per row,
        ``decision_tree.py:208-225``)."""
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        return self._leaf_ids(X).astype(np.int64)

    def predict(self, X):
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        # count[:, 0] holds the exact f64 node means from the refit pass.
        return self.tree_.count[self._leaf_ids(X), 0]

    def export_dot(self, *, feature_names=None, precision=2):
        """Graphviz source of the fitted tree (``utils/export.py``)."""
        check_is_fitted(self)
        from mpitree_tpu.utils.export import export_tree_dot

        return export_tree_dot(
            self.tree_, feature_names=feature_names, precision=precision,
            task="regression", n_features=self.n_features_,
        )

    def export_text(self, *, feature_names=None, precision=2):
        check_is_fitted(self)
        return export_tree_text(
            self.tree_, feature_names=feature_names, precision=precision,
            task="regression",
        )

    @property
    def feature_importances_(self):
        """Mean-decrease-in-impurity importances from the exact per-node
        variances stored by the f64 refit pass (utils/importances.py)."""
        check_is_fitted(self)
        return feature_importances(
            self.tree_, self.n_features_, task="regression"
        )

    def get_depth(self):
        check_is_fitted(self)
        return self.tree_.max_depth

    def get_n_leaves(self):
        check_is_fitted(self)
        return self.tree_.n_leaves

    def __sklearn_is_fitted__(self):
        return hasattr(self, "tree_")
