"""Bagged random forests — ensemble parallelism over the TPU mesh.

The reference has no ensemble; this is a target capability (BASELINE
config 5: "Bagged random-forest ensemble (N trees sharded across TPU
chips)"). TPU-first formulation: bootstrap resampling never copies rows —
each tree reuses the one HBM-resident binned matrix with an integer
multinomial ``sample_weight`` vector feeding the weighted histogram kernel
(``ops/histogram.py``). Device forests build as ONE tree-sharded program
(``core/fused_builder.build_forest_fused``): the tree axis rides the mesh
with data replicated per device, so T trees on D devices cost
``ceil(T/D)`` sequential builds of wall-clock — the reference's subtree
task-parallelism (``decision_tree.py:446-466``) reborn at ensemble
granularity.

``max_features`` draws random feature subsets; ``max_features_mode``
selects the granularity. ``"node"`` (default) is sklearn's granularity — a
fresh subset at every node, via path-derived hash keys (``ops/sampling.py``)
that make host and device builds grow identical trees; unlike sklearn, a
node whose subset admits no valid split becomes a leaf (LightGBM's
``feature_fraction_bynode`` rule — see ``ops/sampling.py``). ``"tree"``
draws one subset per tree (cheaper: those trees batch into the fused
tree-sharded program; node-sampled trees build per tree on the levelwise
engine, whose host level loop threads the node keys).
"""

from __future__ import annotations

import dataclasses
import warnings

import numpy as np
from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
from sklearn.utils.validation import check_is_fitted

from mpitree_tpu.config import knobs
from mpitree_tpu.core.builder import (
    BuildConfig,
    build_tree,
    integer_weights,
    prefer_host_path,
)
from mpitree_tpu.core.fused_builder import build_forest_fused
from mpitree_tpu.core.host_builder import build_tree_host
from mpitree_tpu.obs import (
    BuildObserver,
    ReportMixin,
    note_build_path,
    note_refine,
    warn_event,
)
from mpitree_tpu.ops.binning import bin_dataset
from mpitree_tpu.ops.sampling import (
    NodeFeatureSampler,
    bootstrap_weights,
    feature_subset,
    n_subspace_features,
    seed_from,
    tree_seed,
)
from mpitree_tpu.parallel import mesh as mesh_lib
from mpitree_tpu.resilience import (
    ForestCheckpoint,
    OomRescue,
    SnapshotSlot,
    device_failover,
    retry_device,
)
from mpitree_tpu.serving.tables import note_serving
from mpitree_tpu.utils.validation import (
    apply_class_weight,
    feature_names_of,
    min_child_weight,
    min_decrease_scaled,
    record_sklearn_attributes,
    resolve_refine,
    validate_fit_data,
    validate_fit_targets,
    validate_predict_data,
    validate_sample_weight,
)


class _TreeList(list):
    """list subclass so the fitted ensemble can anchor weak predict caches
    (plain lists cannot be weak-referenced)."""

    __slots__ = ("__weakref__",)


class _BaseForest(ReportMixin, BaseEstimator):
    def __init__(self, *, n_estimators=10, max_depth=None, min_samples_split=2,
                 max_bins=256, binning="auto", bootstrap=True,
                 max_features=None, max_features_mode="node",
                 oob_score=False, min_weight_fraction_leaf=0.0,
                 min_samples_leaf=1,
                 random_state=None, n_devices=None,
                 backend=None, refine_depth="auto", checkpoint=None,
                 checkpoint_compact_every=None,
                 ccp_alpha=0.0, min_impurity_decrease=0.0,
                 splitter="best", monotonic_cst=None, warm_start=False):
        self.n_estimators = n_estimators
        self.max_depth = max_depth
        self.min_samples_split = min_samples_split
        self.max_bins = max_bins
        self.binning = binning
        self.bootstrap = bootstrap
        self.max_features = max_features
        self.max_features_mode = max_features_mode
        self.oob_score = oob_score
        self.min_weight_fraction_leaf = min_weight_fraction_leaf
        self.min_samples_leaf = min_samples_leaf
        self.random_state = random_state
        self.n_devices = n_devices
        self.backend = backend
        self.refine_depth = refine_depth
        # Optional path for incremental checkpoint/resume of the forest
        # build (resilience.checkpoint: sharded group files + atomic
        # manifest) — the recovery story SURVEY §5 lists as absent from
        # the reference.
        self.checkpoint = checkpoint
        # Compact the checkpoint's shard files once the manifest references
        # this many (resilience.checkpoint.maybe_compact — the gbdt knob,
        # wired for forests too; None = never, forests can still call
        # compact() manually).
        self.checkpoint_compact_every = checkpoint_compact_every
        self.ccp_alpha = ccp_alpha
        self.min_impurity_decrease = min_impurity_decrease
        self.splitter = splitter
        self.monotonic_cst = monotonic_cst
        self.warm_start = warm_start

    def _pop_oob_masks(self):
        """Consume the fit-time bootstrap OOB masks (they must not persist —
        they would pin n_estimators x n_samples of memory on the model)."""
        masks = self._oob_masks
        del self._oob_masks
        return masks

    @staticmethod
    def _warn_partial_oob(seen, obs=None) -> None:
        if not seen.all():
            warn_event(
                obs, "oob_partial",
                "Some inputs do not have OOB scores (too few trees); their "
                "OOB estimates are NaN",
                stacklevel=3,
            )

    @staticmethod
    def _warn_no_oob(obs=None) -> float:
        warn_event(
            obs, "oob_empty",
            "no out-of-bag rows (too few trees); oob_score_ is nan",
            stacklevel=3,
        )
        return float("nan")

    def _warm_start_trees(self):
        """Previously fitted trees to keep, or None (sklearn warm_start).

        Phase A below replays every per-tree RNG draw from the seed, so
        kept trees stay paired with their bootstrap/OOB draws — the same
        replay contract the checkpoint resume relies on, hence the same
        integer-random_state requirement.
        """
        if getattr(self, "warm_start", False) and getattr(
            self, "checkpoint", None
        ):
            # Rejected up front (even on the FIRST fit, before trees_
            # exists): both define where a fit resumes from, and letting
            # the first step succeed would fail the pipeline on step two.
            raise ValueError(
                "warm_start and checkpoint are mutually exclusive: both "
                "define where a fit resumes from"
            )
        if not getattr(self, "warm_start", False) or not hasattr(
            self, "trees_"
        ):
            return None
        import numbers

        if not isinstance(self.random_state, numbers.Integral):
            raise ValueError(
                "warm_start requires a fixed integer random_state so the "
                "continued fit replays the prior trees' bootstrap/feature "
                "draws before drawing new ones"
            )
        prev = list(self.trees_)
        if self.n_estimators < len(prev):
            raise ValueError(
                f"n_estimators={self.n_estimators} must be larger or "
                f"equal to len(trees_)={len(prev)} when warm_start==True"
            )
        if self.n_estimators == len(prev):
            # stacklevel 4: user -> fit -> _fit_forest -> here (one frame
            # deeper than _fit_forest's own checkpoint warning).
            warnings.warn(
                "Warm-start fitting without increasing n_estimators does "
                "not fit new trees.",
                stacklevel=4,
            )
        return prev

    # graftlint: host-fn — streamed-fit preamble: refusals, mesh-first
    # resolve and the two host ingest passes are deliberate host work
    def _open_stream(self, X, dataset, y, *, trace_to=None):
        """Streamed-fit preamble shared by both forest tasks: refusals,
        mesh-first resolve, ingest. Returns ``(IngestResult, mesh)`` with
        ``self._fit_obs`` opened (the ingest decision and memory plan
        already recorded on it)."""
        from mpitree_tpu.ingest import StreamedDataset, ingest_dataset

        ds = dataset if isinstance(dataset, StreamedDataset) else X
        if dataset is not None and X is not None:
            raise ValueError(
                "pass the StreamedDataset as X or dataset=, not both"
            )
        if y is not None:
            raise ValueError(
                "a StreamedDataset carries its own targets; fit(dataset) "
                "takes no separate y — rebuild the dataset with the "
                "labels you want"
            )
        if self.oob_score:
            raise ValueError(
                "oob_score=True needs a raw-X descent over the training "
                "rows, which a streamed fit never materializes — score "
                "on a held-out stream instead"
            )
        obs = self._fit_obs = BuildObserver()
        if trace_to is not None:
            obs.trace_to(trace_to)
        # Placement needs the mesh BEFORE binning (chunks land on their
        # slots), so resolve it first — the streamed path is device-only.
        mesh = mesh_lib.resolve_mesh(
            backend=self.backend, n_devices=self.n_devices
        )
        obs.set_mesh(mesh)
        with obs.span("bin"):
            res = ingest_dataset(
                ds, mesh=mesh, max_bins=self.max_bins,
                binning=self.binning, obs=obs,
            )
        self.ingest_stats_ = res.stats
        return res, mesh

    def _stream_weight(self, res, sample_weight):
        """Merge per-chunk and fit-argument sample weights (at most one)."""
        if sample_weight is not None and res.sample_weight is not None:
            raise ValueError(
                "sample weights arrived both per-chunk and as a fit "
                "argument; pick one"
            )
        return validate_sample_weight(
            res.sample_weight if sample_weight is None else sample_weight,
            res.binned.n_samples,
        )

    def _finish_fit(self):
        """Common fit tail: finalize the observer into the run record."""
        obs = self._fit_obs
        del self._fit_obs
        self.fit_stats_ = obs.summary() if obs.enabled else None
        # Serving-table notes (mpitree_tpu.serving): the flat-table plan
        # the compiled inference path will serve this forest from; then
        # the ensemble run record aggregating per-tree child summaries
        # plus the shared phases/counters/collectives (mpitree_tpu.obs).
        note_serving(obs, self.trees_)
        self.fit_report_ = obs.report(trees=self.trees_)
        return self

    def _fit_forest(self, X, y_enc, *, task, criterion, n_classes=None,
                    refit_targets=None, sample_weight=None, trace_to=None,
                    stream=None):
        streamed = stream is not None
        if streamed:
            # fit() already ran the ingest passes (_open_stream): the
            # matrix is mesh-resident StreamedBinnedData, X is None.
            _res, mesh = stream
            binned = _res.binned
            n, F = binned.n_samples, binned.n_features
        else:
            n, F = X.shape
        if self.oob_score and not self.bootstrap:
            raise ValueError("oob_score=True requires bootstrap=True")
        cce = getattr(self, "checkpoint_compact_every", None)
        if cce is not None and int(cce) < 2:
            # The same grammar as the boosting estimators': fewer than
            # two shards can never compact.
            raise ValueError(
                "checkpoint_compact_every must be >= 2 shards or None, "
                f"got {cce!r}"
            )
        # The ensemble's structured run record (mpitree_tpu.obs): one
        # observer accumulates phases/counters/collectives across every
        # member build; fit() finalizes it into fit_report_ (post-OOB).
        # A streamed fit's observer already exists (the ingest decision
        # and memory plan landed on it during _open_stream).
        if streamed:
            obs = self._fit_obs
        else:
            obs = self._fit_obs = BuildObserver()
            if trace_to is not None:
                # Chrome-trace timeline (obs/trace.py): a path, or a shared
                # TraceSink covering several fits + serving in one file.
                obs.trace_to(trace_to)
        prev_trees = self._warm_start_trees()
        sample_weight = validate_sample_weight(sample_weight, n)
        rng = np.random.default_rng(self.random_state)
        # Keyed counter-based draws (ops/sampling): every per-tree draw a
        # pure function of (seed, tree, row/feature). Always on for
        # streamed fits — a host-RNG replay has no defined order over a
        # chunk stream — and opt-in for in-memory fits, which makes an
        # in-memory fit the fingerprint twin of its streamed form.
        keyed = streamed or bool(knobs.value("MPITREE_TPU_KEYED_BOOTSTRAP"))
        if keyed:
            import numbers

            if self.random_state is not None and not isinstance(
                self.random_state, numbers.Integral
            ):
                raise ValueError(
                    "keyed bootstrap draws (streamed fits and "
                    "MPITREE_TPU_KEYED_BOOTSTRAP=1) are a pure function "
                    "of (seed, tree, row); random_state must be None or "
                    "an int"
                )
            kseed = seed_from(self.random_state)
        if not streamed:
            # Host binning on purpose (vs the tree estimators'
            # bin_for_engine): a forest bins ONCE for T tree builds, so the
            # device-binning win is amortized away, while the host copy
            # feeds every per-tree failover without an ensure-host seam
            # through the tree_b replaces.
            with obs.span("bin"):
                binned = bin_dataset(
                    X, max_bins=self.max_bins, binning=self.binning
                )
        use_host = (
            False if streamed
            else prefer_host_path(n, F, self.n_devices, self.backend)
        )
        note_build_path(
            obs, host=use_host, backend=self.backend,
            n_rows=n, n_features=F,
        )
        if not streamed:
            mesh = None if use_host else mesh_lib.resolve_mesh(
                backend=self.backend, n_devices=self.n_devices
            )
        if mesh is not None:
            obs.set_mesh(mesh)
        if streamed:
            # T hybrid tails would each replay the raw chunk stream once
            # per tree: streamed ensembles stay crown-only, full depth.
            rd, refine, crown_depth = None, False, self.max_depth
        else:
            rd, refine, crown_depth = resolve_refine(
                self.max_depth, self.refine_depth,
                n_rows=n, quantized=binned.quantized,
            )
        from mpitree_tpu.utils.monotonic import validate_monotonic_cst

        mono = validate_monotonic_cst(
            self.monotonic_cst, F, task=task, n_classes=n_classes
        )
        if mono is not None:
            # Single-engine full-depth builds under constraints (same
            # stance as the tree estimators: no hybrid tail).
            rd, refine, crown_depth = None, False, self.max_depth
        note_refine(
            obs, refine=refine, rd=rd, crown_depth=crown_depth,
            refine_depth_param=self.refine_depth,
            constrained=mono is not None, streamed=streamed,
        )
        cfg = BuildConfig(
            task=task, criterion=criterion, max_depth=crown_depth,
            min_samples_split=self.min_samples_split,
            min_child_weight=min_child_weight(
                self.min_weight_fraction_leaf, sample_weight, n,
                self.min_samples_leaf,
            ),
            min_decrease_scaled=min_decrease_scaled(
                self.min_impurity_decrease, sample_weight, n
            ),
        )

        def tree_cfg(w):
            """Per-tree leaf floor, as sklearn computes it: the
            min_weight_fraction_leaf floor reads each tree's COMPOSED
            bootstrap x user weight total, not the base fit weight (the
            two differ only when a user sample_weight rides a bootstrap —
            multinomial totals are exactly n)."""
            if w is sample_weight:
                return cfg
            return dataclasses.replace(
                cfg,
                min_child_weight=min_child_weight(
                    self.min_weight_fraction_leaf, w, n,
                    self.min_samples_leaf,
                ),
                min_decrease_scaled=min_decrease_scaled(
                    self.min_impurity_decrease, w, n
                ),
            )
        k = n_subspace_features(self.max_features, F)
        if self.max_features_mode not in ("node", "tree"):
            raise ValueError(
                f"max_features_mode must be 'node' or 'tree', "
                f"got {self.max_features_mode!r}"
            )
        if self.splitter not in ("best", "random"):
            raise ValueError(
                f"splitter must be 'best' or 'random', got {self.splitter!r}"
            )
        rand_split = self.splitter == "random"
        # sklearn semantics: a fresh feature subset at every NODE
        # (ops/sampling.py). Path-derived node keys make the draws a pure
        # function of tree structure, so node-sampled trees — and
        # splitter="random" trees, whose per-node candidate draws ride the
        # same keys — build in the fused tree-sharded program too (the jnp
        # key arithmetic runs inside its while_loop body).
        node_sampling = self.max_features_mode == "node" and k < F
        if self.bootstrap:
            obs.decision(
                "bootstrap", "keyed" if keyed else "host-rng",
                reason=(
                    "Poisson(1) multiplicities keyed by (seed, tree, row) "
                    "— pure counter draws that any chunking, mesh, or "
                    "resume replays identically (Oza–Russell online "
                    "bagging)" if keyed else
                    "host-RNG multinomial draw (the in-memory default; "
                    "MPITREE_TPU_KEYED_BOOTSTRAP=1 opts into the keyed "
                    "scheme streamed fits always use)"
                ),
            )

        # ---- phase A: every per-tree RNG draw happens up front -----------
        # (bootstrap multiplicities, OOB masks, feature subspaces). The
        # build phase below then only consumes indices — which is what
        # makes checkpoint/resume bit-identical to an uninterrupted fit:
        # a resumed run replays the same draws and skips finished trees.
        tree_w, tree_b, tree_mask, tree_sampler = [], [], [], []
        self._oob_masks = [] if self.oob_score else None
        for i in range(self.n_estimators):
            # Bootstrap multiplicities compose multiplicatively with any
            # user-provided per-sample weights.
            w = sample_weight
            if self.bootstrap:
                boot = (
                    bootstrap_weights(kseed, i, n) if keyed
                    else rng.multinomial(
                        n, np.full(n, 1.0 / n)
                    ).astype(np.float32)
                )
                if self._oob_masks is not None:
                    self._oob_masks.append(boot == 0)
                w = boot if w is None else boot * w
            b = binned
            fmask = None
            sampler = None
            if node_sampling:
                sampler = NodeFeatureSampler(
                    k=k, n_features=F,
                    seed=(tree_seed(kseed, i) if keyed
                          else int(rng.integers(2**32))),
                    random_split=rand_split,
                )
            elif rand_split:
                # max_features_mode="tree" keeps its fixed per-tree subset
                # (the fmask branch below); the sampler only carries the
                # candidate draws.
                sampler = NodeFeatureSampler(
                    k=F, n_features=F,
                    seed=(tree_seed(kseed, i) if keyed
                          else int(rng.integers(2**32))),
                    random_split=True,
                )
            if not node_sampling and k < F:
                keep = (
                    feature_subset(kseed, i, F, k) if keyed
                    else np.sort(rng.choice(F, size=k, replace=False))
                )
                fmask = np.zeros(F, bool)
                fmask[keep] = True
                n_cand = np.zeros_like(binned.n_cand)
                n_cand[keep] = binned.n_cand[keep]
                b = dataclasses.replace(binned, n_cand=n_cand)
            tree_w.append(w)
            tree_b.append(b)
            tree_mask.append(fmask)
            tree_sampler.append(sampler)

        # ---- phase B: grouped builds with failover + checkpointing -------
        def finish(i, tree, ids):
            """Per-tree hybrid refine tail + ccp pruning (final form,
            checkpoint-safe)."""
            if refine:
                from mpitree_tpu.core.hybrid_builder import apply_refine

                tree = apply_refine(
                    tree, ids, X, y_enc, cfg=tree_cfg(tree_w[i]),
                    max_depth=self.max_depth, rd=rd,
                    timer=obs, n_classes=n_classes,
                    sample_weight=tree_w[i], refit_targets=refit_targets,
                    feature_mask=tree_mask[i],
                    feature_sampler=tree_sampler[i],
                )
            if getattr(self, "ccp_alpha", 0.0):
                from mpitree_tpu.utils.pruning import ccp_prune

                tree = ccp_prune(tree, self.ccp_alpha, task=task)
            if mono is not None:
                from mpitree_tpu.utils.monotonic import clip_tree_values

                clip_tree_values(tree, mono, task)
            return tree

        def host_raw(i):
            """The one host-tier build call every path (primary host mode
            and both failover sites) shares: (tree, leaf_ids-or-None)."""
            res = build_tree_host(
                tree_b[i], y_enc, config=tree_cfg(tree_w[i]),
                n_classes=n_classes, sample_weight=tree_w[i],
                refit_targets=refit_targets, return_leaf_ids=refine,
                feature_sampler=tree_sampler[i], mono_cst=mono, timer=obs,
            )
            return res if refine else (res, None)

        def build_one_host(i):
            return finish(i, *host_raw(i))

        def build_one_device(i):
            # levelwise engine / debug mode: per-tree builds keep the
            # instrumentation and determinism checks build_tree wires up.
            # A lost accelerator costs wall-clock, not the fit
            # (utils/elastic.py). Resilience v2: each tree gets a
            # snapshot slot (level-granular resume) and the OOM rescue
            # ladder (classifier wiring, per-tree).
            slot = SnapshotSlot()
            rescue = OomRescue(obs=obs, snapshot_slot=slot)

            def dev():
                res = build_tree(
                    tree_b[i], y_enc,
                    config=rescue.apply(tree_cfg(tree_w[i])), mesh=mesh,
                    n_classes=n_classes, sample_weight=tree_w[i],
                    refit_targets=refit_targets, return_leaf_ids=refine,
                    feature_sampler=tree_sampler[i], mono_cst=mono,
                    timer=obs, snapshot_slot=slot,
                )
                return res if refine else (res, None)

            if streamed:
                # No host rung: the numpy tier wants a host-resident
                # matrix a streamed fit never builds — retry + OOM
                # rescue only (the single-tree streamed ladder stance).
                t, ids = retry_device(
                    dev, what=f"forest tree {i} streamed device build",
                    obs=obs, resume=slot, rescue=rescue,
                )
                return finish(i, t, ids)

            def host():
                obs.event(
                    "device_failover",
                    f"forest tree {i} device build failed; host tier",
                )
                return host_raw(i)

            t, ids = device_failover(
                dev, host,
                what=f"forest tree {i} device build", obs=obs,
                resume=slot, rescue=rescue,
            )
            return finish(i, t, ids)

        def build_group(idxs):
            """Device trees batch into ONE tree-sharded program."""
            ws = np.stack([
                np.ones(n, np.float32) if tree_w[i] is None else tree_w[i]
                for i in idxs
            ])
            cms = np.stack([tree_b[i].candidate_mask() for i in idxs])
            cfgs = [tree_cfg(tree_w[i]) for i in idxs]
            fls = np.asarray(
                [c.min_child_weight for c in cfgs], np.float32
            )
            mids = np.asarray(
                [c.min_decrease_scaled for c in cfgs], np.float32
            )
            rks = np.asarray(
                [0 if tree_sampler[i] is None else tree_sampler[i].root_key()
                 for i in idxs], np.uint32
            )

            # Fused group program: no host boundary to snapshot, but the
            # OOM rescue still applies (a halved chunk / dropped carry
            # re-dispatches the group on-device under the shrunk plan).
            rescue = OomRescue(obs=obs)

            def dev():
                return build_forest_fused(
                    binned, y_enc, config=rescue.apply(cfg), mesh=mesh,
                    weights=ws,
                    cand_masks=cms, n_classes=n_classes,
                    refit_targets=refit_targets,
                    integer_counts=integer_weights(sample_weight),
                    return_leaf_ids=refine, min_child_weights=fls,
                    min_decrease_scaleds=mids,
                    root_keys=rks,
                    sample_k=k if node_sampling else None,
                    random_split=rand_split,
                    mono_cst=mono,
                    timer=obs,
                )

            def host():
                obs.event(
                    "device_failover",
                    "forest group device build failed; host tier",
                )
                out = [host_raw(i) for i in idxs]
                if refine:
                    return [o[0] for o in out], [o[1] for o in out]
                return [o[0] for o in out]

            if streamed:
                res = retry_device(
                    dev, what="forest group streamed device build",
                    obs=obs, rescue=rescue,
                )
            else:
                res = device_failover(
                    dev, host, what="forest group device build", obs=obs,
                    rescue=rescue,
                )
            if refine:
                gtrees, nid_all = res
                return [
                    finish(i, t, ids)
                    for i, t, ids in zip(idxs, gtrees, list(nid_all))
                ]
            return [finish(i, t, None) for i, t in zip(idxs, res)]

        ck = None
        start = 0
        trees: list = []
        if prev_trees is not None:
            start = min(len(prev_trees), self.n_estimators)
            trees = list(prev_trees[:start])
        if getattr(self, "checkpoint", None):
            import numbers

            if not keyed and not isinstance(
                self.random_state, numbers.Integral
            ):
                # Resume replays phase A's draws; with random_state=None
                # (fresh entropy) or a stateful Generator the re-run's
                # draws differ, and resuming would silently mix two
                # forests (and mispair OOB masks with trees). Keyed draws
                # are pure functions of (seed, tree, row) — they replay
                # under any of the seeds the keyed gate admits.
                warn_event(
                    obs, "checkpoint_disabled",
                    "forest checkpointing requires a fixed integer "
                    "random_state so a resumed fit replays the same "
                    "bootstrap/feature draws; checkpoint disabled",
                    stacklevel=3,
                )
            else:
                params = {
                    k_: v for k_, v in self.get_params().items()
                    if k_ != "checkpoint"  # moving the file must not restart
                }
                params["task"] = task
                if streamed:
                    # No raw matrix exists to fingerprint; the sketch
                    # edges are a pure function of the stream, so
                    # thresholds + row/candidate extents pin the same
                    # data-identity contract (the boosting streamed
                    # checkpoint's basis).
                    params["streamed_rows"] = int(n)
                    params["streamed_n_cand"] = np.asarray(
                        binned.n_cand
                    ).tolist()
                    X_basis = np.ascontiguousarray(binned.thresholds)
                else:
                    X_basis = X
                ck = ForestCheckpoint.open(
                    self.checkpoint, params, X_basis, y_enc, sample_weight
                )
                start = min(len(ck.trees), self.n_estimators)
                trees = list(ck.trees[:start])

        batched = not (use_host or self._per_tree_device_builds())
        obs.decision(
            "ensemble_path",
            ("host" if use_host
             else "batched-fused" if batched else "per-tree-device"),
            reason=(
                obs.record.decisions["build_path"]["reason"] if use_host
                else "trees batch into one tree-sharded fused program per "
                     "group" if batched
                else "MPITREE_TPU_ENGINE=levelwise or debug mode: per-tree "
                     "builds keep the levelwise instrumentation"
            ),
            n_estimators=int(self.n_estimators),
        )
        remaining = list(range(start, self.n_estimators))
        if batched:
            if ck is not None and remaining:
                # Checkpoint granularity = the tree-axis width the fused
                # builder will actually pick (same dataset_bytes/HBM-guard
                # inputs): each group is one device program, persisted as
                # it lands, so a preemption costs at most one group.
                from mpitree_tpu.core import fused_builder as _fb

                g, _ = mesh_lib.tree_data_shape(
                    mesh.size, self.n_estimators,
                    dataset_bytes=binned.x_binned.nbytes,
                    hbm_budget=_fb.FOREST_HBM_BUDGET_BYTES,
                )
                # Floor the group width: on a narrow tree axis (e.g. one
                # device, where the fused builder lax.maps the whole batch
                # in one program anyway) per-tree groups would mean one
                # program launch and one checkpoint flush per tree.
                g = max(g, 8)
                groups = [
                    remaining[j:j + g] for j in range(0, len(remaining), g)
                ]
            else:
                groups = [remaining] if remaining else []
            for idxs in groups:
                new = build_group(idxs)
                trees.extend(new)
                if ck is not None:
                    ck.append(new)
                    ck.maybe_compact(
                        getattr(self, "checkpoint_compact_every", None), obs
                    )
        else:
            # Flush the checkpoint per batch of trees, not per tree:
            # appends are O(group) shard writes (resilience.checkpoint),
            # but per-tree flushes would still mean one manifest rewrite
            # and one fsync-sized file per tree for no recovery benefit.
            g = 8
            chunks = (
                [remaining] if ck is None
                else [remaining[j:j + g] for j in range(0, len(remaining), g)]
            )
            for chunk in chunks:
                new = [
                    build_one_host(i) if use_host else build_one_device(i)
                    for i in chunk
                ]
                trees.extend(new)
                if ck is not None:
                    ck.append(new)
                    ck.maybe_compact(
                        getattr(self, "checkpoint_compact_every", None), obs
                    )
        if ck is not None:
            ck.done()
        return trees

    @staticmethod
    def _per_tree_device_builds() -> bool:
        """True when batched tree-sharding must yield to per-tree builds
        (explicit levelwise engine or debug determinism checks)."""
        from mpitree_tpu.config import knobs
        from mpitree_tpu.utils.profiling import debug_checks_enabled

        return (
            knobs.value("MPITREE_TPU_ENGINE") == "levelwise"
            or debug_checks_enabled()
        )

    def _leaf_ids(self, X: np.ndarray):
        """Yield (tree, leaf_ids) — trees descend in vmapped device programs
        over a stacked (tree, node) axis instead of a per-tree Python loop
        (``ops/predict.stacked_leaf_ids``, the ensemble-inference path
        boosting shares). On a multi-device fit the query rows shard over
        the mesh's data axis — the reference's ranks each predicted the
        full set redundantly."""
        from mpitree_tpu.ops.predict import predict_mesh, stacked_leaf_ids

        ids = stacked_leaf_ids(self.trees_, X, mesh=predict_mesh(self))
        for i, t in enumerate(self.trees_):
            yield t, ids[i]

    @property
    def feature_importances_(self):
        """Mean of per-tree normalized importances (sklearn convention)."""
        check_is_fitted(self)
        from mpitree_tpu.utils.importances import feature_importances

        task = ("classification" if hasattr(self, "classes_") else "regression")
        crit = getattr(self, "criterion", "entropy")
        acc = np.zeros(self.n_features_)
        for t in self.trees_:
            acc += feature_importances(
                t, self.n_features_, criterion=crit, task=task
            )
        # Renormalize so stump trees (all-zero vectors) don't break the
        # sum-to-1 convention.
        s = acc.sum()
        return acc / s if s > 0 else acc

    def __sklearn_is_fitted__(self):
        return hasattr(self, "trees_")


class RandomForestClassifier(ClassifierMixin, _BaseForest):
    """Bagged classification forest (soft voting over per-tree class counts).

    ``max_features=None`` (default) is pure bagging — the BASELINE target
    ("bagged random forest"). Set e.g. ``max_features="sqrt"`` for sklearn's
    per-node random subsets (``max_features_mode="node"``), or
    ``max_features_mode="tree"`` for whole-tree subspaces (those trees
    batch into the fused tree-sharded device program).
    """

    def __init__(self, *, n_estimators=10, criterion="entropy", max_depth=None,
                 min_samples_split=2, max_bins=256, binning="auto",
                 bootstrap=True, max_features=None, max_features_mode="node",
                 oob_score=False, class_weight=None,
                 min_weight_fraction_leaf=0.0, min_samples_leaf=1,
                 random_state=None,
                 n_devices=None, backend=None, refine_depth="auto",
                 checkpoint=None, checkpoint_compact_every=None,
                 ccp_alpha=0.0,
                 min_impurity_decrease=0.0, splitter="best",
                 monotonic_cst=None, warm_start=False):
        super().__init__(
            n_estimators=n_estimators, max_depth=max_depth,
            min_samples_split=min_samples_split, max_bins=max_bins,
            binning=binning, bootstrap=bootstrap, max_features=max_features,
            max_features_mode=max_features_mode, oob_score=oob_score,
            min_weight_fraction_leaf=min_weight_fraction_leaf,
            min_samples_leaf=min_samples_leaf,
            random_state=random_state, n_devices=n_devices, backend=backend,
            refine_depth=refine_depth, checkpoint=checkpoint,
            checkpoint_compact_every=checkpoint_compact_every,
            ccp_alpha=ccp_alpha, min_impurity_decrease=min_impurity_decrease,
            splitter=splitter, monotonic_cst=monotonic_cst,
            warm_start=warm_start,
        )
        self.criterion = criterion
        self.class_weight = class_weight

    def fit(self, X=None, y=None, sample_weight=None, *, dataset=None,
            trace_to=None):
        from mpitree_tpu.models._streamed import is_streamed

        if is_streamed(X, dataset):
            res, mesh = self._open_stream(X, dataset, y, trace_to=trace_to)
            y_enc, classes = validate_fit_targets(
                res.y, task="classification"
            )
            F = res.binned.n_features
            self.n_features_ = F
            self.n_features_in_ = F
            self.classes_ = classes
            record_sklearn_attributes(self, None, F, n_classes=len(classes))
            sample_weight = apply_class_weight(
                self.class_weight, y_enc, classes,
                self._stream_weight(res, sample_weight),
            )
            self.trees_ = _TreeList(self._fit_forest(
                None, y_enc, task="classification", criterion=self.criterion,
                n_classes=len(classes), sample_weight=sample_weight,
                stream=(res, mesh),
            ))
            self._mono_p0 = None
            res.close()
            return self._finish_fit()
        names = feature_names_of(X)
        X, y_enc, classes = validate_fit_data(X, y, task="classification")
        self.n_features_ = X.shape[1]
        self.n_features_in_ = X.shape[1]
        self.classes_ = classes
        record_sklearn_attributes(
            self, names, X.shape[1], n_classes=len(classes)
        )
        sample_weight = apply_class_weight(
            self.class_weight, y_enc, classes,
            validate_sample_weight(sample_weight, X.shape[0]),
        )
        self.trees_ = _TreeList(self._fit_forest(
            X, y_enc, task="classification", criterion=self.criterion,
            n_classes=len(classes), sample_weight=sample_weight,
            trace_to=trace_to,
        ))
        self._mono_p0 = None  # predict_proba's clipped-probability cache
        if self.oob_score:
            # Each row is scored only by trees whose bootstrap left it out —
            # an unbiased generalization estimate without a held-out split.
            votes = np.zeros((len(X), len(classes)))
            seen = np.zeros(len(X), bool)
            for (t, ids), oob in zip(self._leaf_ids(X), self._pop_oob_masks()):
                counts = t.count[ids[oob]].astype(np.float64)
                votes[oob] += counts / np.maximum(
                    counts.sum(axis=1, keepdims=True), 1.0
                )
                seen |= oob
            if not seen.any():
                self.oob_score_ = self._warn_no_oob(self._fit_obs)
                self.oob_decision_function_ = np.full(
                    (len(X), len(classes)), np.nan
                )
            else:
                self._warn_partial_oob(seen, self._fit_obs)
                df = votes / np.maximum(
                    votes.sum(axis=1, keepdims=True), 1e-300
                )
                df[~seen] = np.nan  # sklearn marks uncovered rows NaN
                self.oob_decision_function_ = df
                self.oob_score_ = float(
                    (votes[seen].argmax(axis=1) == y_enc[seen]).mean()
                )
        return self._finish_fit()

    def predict_proba(self, X):
        """Mean of per-tree leaf class distributions (normalized — unlike the
        single tree's raw-count reference quirk, which has no ensemble
        analogue). Under ``monotonic_cst`` the per-tree distributions are
        the bound-clipped probabilities (sklearn's forests average their
        trees' clipped stored values), which is what makes the averaged
        ``predict_proba`` monotone."""
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        from mpitree_tpu.utils.monotonic import (
            clipped_class0,
            validate_monotonic_cst,
        )

        mono = validate_monotonic_cst(
            self.monotonic_cst, self.n_features_, task="classification",
            n_classes=len(self.classes_),
        )
        if mono is not None:
            # Clipped p0 is fit-time-constant per tree; cache it so
            # repeated predict calls don't redo the bound propagation.
            cache = getattr(self, "_mono_p0", None)
            if cache is None or len(cache) != len(self.trees_):
                cache = [
                    clipped_class0(t, mono).astype(np.float64)
                    for t in self.trees_
                ]
                self._mono_p0 = cache
        acc = np.zeros((X.shape[0], len(self.classes_)))
        for i, (t, ids) in enumerate(self._leaf_ids(X)):
            if mono is not None:
                p0 = cache[i][ids]
                acc += np.stack([p0, 1.0 - p0], axis=1)
            else:
                counts = t.count[ids].astype(np.float64)
                acc += counts / np.maximum(
                    counts.sum(axis=1, keepdims=True), 1.0
                )
        return acc / len(self.trees_)

    def predict(self, X):
        proba = self.predict_proba(X)
        return self.classes_[proba.argmax(axis=1)]


class RandomForestRegressor(RegressorMixin, _BaseForest):
    """Bagged regression forest (mean of per-tree predictions)."""

    def __init__(self, *, n_estimators=10, max_depth=None,
                 min_samples_split=2, max_bins=256, binning="auto",
                 bootstrap=True, max_features=None, max_features_mode="node",
                 oob_score=False, min_weight_fraction_leaf=0.0,
                 min_samples_leaf=1, random_state=None,
                 n_devices=None, backend=None, refine_depth="auto",
                 checkpoint=None, checkpoint_compact_every=None,
                 ccp_alpha=0.0,
                 min_impurity_decrease=0.0, splitter="best",
                 monotonic_cst=None, warm_start=False):
        super().__init__(
            n_estimators=n_estimators, max_depth=max_depth,
            min_samples_split=min_samples_split, max_bins=max_bins,
            binning=binning, bootstrap=bootstrap, max_features=max_features,
            max_features_mode=max_features_mode, oob_score=oob_score,
            min_weight_fraction_leaf=min_weight_fraction_leaf,
            min_samples_leaf=min_samples_leaf,
            random_state=random_state, n_devices=n_devices, backend=backend,
            refine_depth=refine_depth, checkpoint=checkpoint,
            checkpoint_compact_every=checkpoint_compact_every,
            ccp_alpha=ccp_alpha, min_impurity_decrease=min_impurity_decrease,
            splitter=splitter, monotonic_cst=monotonic_cst,
            warm_start=warm_start,
        )

    def fit(self, X=None, y=None, sample_weight=None, *, dataset=None,
            trace_to=None):
        from mpitree_tpu.models._streamed import is_streamed

        if is_streamed(X, dataset):
            res, mesh = self._open_stream(X, dataset, y, trace_to=trace_to)
            y64, _ = validate_fit_targets(res.y, task="regression")
            F = res.binned.n_features
            self.n_features_ = F
            self.n_features_in_ = F
            record_sklearn_attributes(self, None, F)
            self._y_mean = float(y64.mean()) if len(y64) else 0.0
            sample_weight = self._stream_weight(res, sample_weight)
            self.trees_ = _TreeList(self._fit_forest(
                None, (y64 - self._y_mean).astype(np.float32),
                task="regression", criterion="mse", refit_targets=y64,
                sample_weight=sample_weight, stream=(res, mesh),
            ))
            res.close()
            return self._finish_fit()
        names = feature_names_of(X)
        X, y64, _ = validate_fit_data(X, y, task="regression")
        self.n_features_ = X.shape[1]
        record_sklearn_attributes(self, names, X.shape[1])
        self.n_features_in_ = X.shape[1]
        self._y_mean = float(y64.mean()) if len(y64) else 0.0
        self.trees_ = _TreeList(self._fit_forest(
            X, (y64 - self._y_mean).astype(np.float32), task="regression",
            criterion="mse", refit_targets=y64, sample_weight=sample_weight,
            trace_to=trace_to,
        ))
        if self.oob_score:
            pred = np.zeros(len(X))
            cnt = np.zeros(len(X))
            for (t, ids), oob in zip(self._leaf_ids(X), self._pop_oob_masks()):
                pred[oob] += t.count[ids[oob], 0]
                cnt[oob] += 1
            seen = cnt > 0
            if not seen.any():
                self.oob_score_ = self._warn_no_oob(self._fit_obs)
                self.oob_prediction_ = np.full(len(X), np.nan)
            else:
                self._warn_partial_oob(seen, self._fit_obs)
                self.oob_prediction_ = np.where(seen, pred / np.maximum(cnt, 1), np.nan)
                resid = y64[seen] - self.oob_prediction_[seen]
                tot = y64[seen] - y64[seen].mean()
                self.oob_score_ = float(
                    1.0 - (resid @ resid) / max(tot @ tot, 1e-300)
                )
        return self._finish_fit()

    def predict(self, X):
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        acc = np.zeros(X.shape[0])
        for t, ids in self._leaf_ids(X):
            acc += t.count[ids, 0]
        return acc / len(self.trees_)


class ExtraTreesClassifier(RandomForestClassifier):
    """Extremely-randomized classification forest (sklearn's ExtraTrees).

    Differences from :class:`RandomForestClassifier`, per sklearn's
    grammar: ``splitter="random"`` (one keyed uniform candidate per
    (node, feature) — quantized to this framework's candidate bins),
    ``bootstrap=False`` (whole-sample fits), and per-node
    ``max_features="sqrt"``. Draw keys derive from structural node paths
    (``ops/sampling.py``), so refits and mesh sizes agree exactly.
    """

    def __init__(self, *, n_estimators=10, criterion="entropy",
                 max_depth=None, min_samples_split=2, max_bins=256,
                 binning="auto", bootstrap=False, max_features="sqrt",
                 max_features_mode="node", oob_score=False, class_weight=None,
                 min_weight_fraction_leaf=0.0, min_samples_leaf=1,
                 random_state=None, n_devices=None, backend=None,
                 refine_depth="auto", checkpoint=None,
                 checkpoint_compact_every=None, ccp_alpha=0.0,
                 min_impurity_decrease=0.0, monotonic_cst=None,
                 warm_start=False):
        super().__init__(
            n_estimators=n_estimators, criterion=criterion,
            max_depth=max_depth, min_samples_split=min_samples_split,
            max_bins=max_bins, binning=binning, bootstrap=bootstrap,
            max_features=max_features, max_features_mode=max_features_mode,
            oob_score=oob_score, class_weight=class_weight,
            min_weight_fraction_leaf=min_weight_fraction_leaf,
            min_samples_leaf=min_samples_leaf, random_state=random_state,
            n_devices=n_devices, backend=backend, refine_depth=refine_depth,
            checkpoint=checkpoint,
            checkpoint_compact_every=checkpoint_compact_every,
            ccp_alpha=ccp_alpha,
            min_impurity_decrease=min_impurity_decrease,
            splitter="random", monotonic_cst=monotonic_cst,
            warm_start=warm_start,
        )


class ExtraTreesRegressor(RandomForestRegressor):
    """Extremely-randomized regression forest (sklearn's ExtraTrees)."""

    def __init__(self, *, n_estimators=10, max_depth=None,
                 min_samples_split=2, max_bins=256, binning="auto",
                 bootstrap=False, max_features=1.0, max_features_mode="node",
                 oob_score=False, min_weight_fraction_leaf=0.0,
                 min_samples_leaf=1, random_state=None, n_devices=None,
                 backend=None, refine_depth="auto", checkpoint=None,
                 checkpoint_compact_every=None,
                 ccp_alpha=0.0, min_impurity_decrease=0.0,
                 monotonic_cst=None, warm_start=False):
        super().__init__(
            n_estimators=n_estimators, max_depth=max_depth,
            min_samples_split=min_samples_split, max_bins=max_bins,
            binning=binning, bootstrap=bootstrap, max_features=max_features,
            max_features_mode=max_features_mode, oob_score=oob_score,
            min_weight_fraction_leaf=min_weight_fraction_leaf,
            min_samples_leaf=min_samples_leaf, random_state=random_state,
            n_devices=n_devices, backend=backend, refine_depth=refine_depth,
            checkpoint=checkpoint,
            checkpoint_compact_every=checkpoint_compact_every,
            ccp_alpha=ccp_alpha,
            min_impurity_decrease=min_impurity_decrease,
            splitter="random", monotonic_cst=monotonic_cst,
            warm_start=warm_start,
        )
