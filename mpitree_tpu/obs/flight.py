"""obs.flight — the persistent run registry (flight recorder).

Every fit/serve record the observer finalizes — and every bench section
the harness captures — can append one JSONL line to a durable run store,
stamped with the lineage keys that make records *comparable later*:
git sha, platform, mesh axes, and a config digest (a stable hash of the
workload statics). ``BENCH_r01–r05`` and ``BENCH_TPU.jsonl`` were
written and then read by humans; the flight store is the machine-readable
trajectory ``obs.diff`` and ``tools/benchdiff.py`` query to turn "is this
slower / different?" into an automated, noise-aware verdict.

Store layout: one append-only ``flight.jsonl`` under
``MPITREE_TPU_RUN_DIR`` (the ambient gate — estimators append their
``fit_report_`` automatically whenever it is set; nothing is written
otherwise). Each line is an **envelope**::

    {"schema": 1, "ts": ..., "iso": ..., "kind": "fit"|"serve"|"bench",
     "section": ..., "git": ..., "platform": ..., "mesh_axes": ...,
     "config_digest": ..., "digest": {...}, "metrics": {...},
     "record": {...}}

``digest`` is the compact scalar summary (``obs.record.digest`` for
fits; a section's scalar payload for bench lines) — what verdicts
compare; ``record`` the full BuildRecord dict — what fingerprint
bisection reads. The **lineage** of an envelope is every stored entry
sharing its ``(kind, section, config_digest, platform)`` — the history
dispersion ``obs.diff`` seeds noise thresholds from.

Contracts:

- **stdlib-only, no package imports** — ``tools/tpu_watcher.py`` and
  ``tools/benchdiff.py`` load this module by file path on hosts without
  jax (the ``obs/trace.py`` precedent).
- **telemetry never aborts** — an unwritable store degrades to a warning
  and a ``None`` return; a torn line (SIGKILL mid-append) is skipped on
  read, never poisons the history.
- **bounded under an ambient RUN_DIR** — ``MPITREE_TPU_RUN_MAX_BYTES``
  size-caps the store via a per-lineage tail trim (ISSUE 14; see the
  retention knobs below): every lineage keeps its newest entries, so
  ``obs.diff``/``benchdiff`` baselines survive rotation (histories
  shorter than ``MIN_HISTORY`` degrade to the documented threshold
  floors, never a crash). The append path pays one ``os.stat``.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import time
import warnings
from mpitree_tpu.config import knobs

FLIGHT_SCHEMA = 1
RUN_DIR_ENV = "MPITREE_TPU_RUN_DIR"
STORE_NAME = "flight.jsonl"

# Long-run hygiene (ISSUE 14): under an ambient RUN_DIR the store grows
# one envelope per fit forever. When the file exceeds
# MPITREE_TPU_RUN_MAX_BYTES (0/unset = unbounded), append rotates it
# through a per-lineage tail trim: keep the newest KEEP_PER_LINEAGE
# entries of every (kind, section, config_digest, platform) lineage —
# enough history for obs.diff's noise model (MIN_HISTORY = 3; fewer
# degrades to the documented floors, never a crash) — dropping only the
# old interior of each trajectory. The append path stays cheap: one
# os.stat per append; the full parse happens only on an actual rotate.
RUN_MAX_BYTES_ENV = "MPITREE_TPU_RUN_MAX_BYTES"
RUN_KEEP_ENV = "MPITREE_TPU_RUN_KEEP"
KEEP_PER_LINEAGE = 16

# (kind, section, config_digest, platform): the identity under which two
# entries are comparable — one lineage, one noise model.
LINEAGE_KEYS = ("kind", "section", "config_digest", "platform")

_GIT_SHA: str | None = None
_GIT_PROBED = False


def _env_int(name: str, default: int) -> int:
    raw = knobs.raw(name)
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        warnings.warn(
            f"ignoring malformed {name}={raw!r} (expected an integer)",
            stacklevel=3,
        )
        return default


def enabled() -> bool:
    """Whether the ambient store is configured (``MPITREE_TPU_RUN_DIR``)."""
    return bool(knobs.raw(RUN_DIR_ENV))


def git_sha(cwd: str | None = None) -> str | None:
    """Short HEAD sha, probed once per process (None outside a repo)."""
    global _GIT_SHA, _GIT_PROBED
    if _GIT_PROBED:
        return _GIT_SHA
    _GIT_PROBED = True
    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
        )
        if r.returncode == 0 and r.stdout.strip():
            _GIT_SHA = r.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        _GIT_SHA = None
    return _GIT_SHA


def config_digest(config) -> str:
    """Stable 12-hex digest of a JSON-able config mapping (sorted keys,
    so dict ordering can never split a lineage)."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.blake2b(blob.encode(), digest_size=6).hexdigest()


def config_digest_from_record(record: dict, kind: str = "fit") -> str:
    """Lineage config key derived from a BuildRecord dict: the workload
    statics that make two runs "the same run repeated". Deliberately
    excludes anything data- or wall-clock-dependent (events, phases,
    results), so reruns of one config land in one lineage.

    Fits key on mesh axes + the resolved engine and its resolution
    inputs (rows/features/bins/chunk/depth/task) + the memory plan's
    pricing inputs. SERVE records key on the serving config only
    (compile kind, kernel tier, buckets, dtype) and deliberately EXCLUDE
    model-structure statics (tree/node counts): a retrained model must
    stay in one serving lineage — detecting "the model changed" is the
    fingerprint's job, and splitting the lineage on it would leave every
    fresh model with no baseline to diff against."""
    mem = record.get("memory") or {}
    dec = record.get("decisions") or {}
    if kind == "serve":
        inp = mem.get("inputs") or {}
        return config_digest({
            "kind": (dec.get("serving_compile") or {}).get("value"),
            "kernel": (dec.get("serving_kernel") or {}).get("value"),
            "buckets": inp.get("buckets"),
            "x64": inp.get("x64"),
            "n_out": inp.get("n_out"),
        })
    eng = record.get("engine") or {}
    return config_digest({
        "mesh_axes": (record.get("mesh") or {}).get("axes"),
        "engine": eng.get("value"),
        "inputs": eng.get("inputs"),
        "plan_inputs": mem.get("inputs"),
        "rounds_per_dispatch": (
            dec.get("rounds_per_dispatch") or {}
        ).get("value"),
    })


# Rotation progress guard, keyed by store path: once a trim fails to get
# a store under the cap (too many lineages x keep entries for the
# configured size), stand down instead of re-parsing the whole file on
# EVERY append forever — the one-os.stat contract. Module-level (not
# per handle) because the ambient path (``append_record``,
# bench_tpu's section appends) constructs a FRESH FlightStore per
# append; per-instance state would re-trim and re-warn on every fit.
_ROTATION_STUCK: set = set()


class FlightStore:
    """Append/query handle over one run directory's ``flight.jsonl``."""

    def __init__(self, root: str | None = None):
        root = root or knobs.raw(RUN_DIR_ENV)
        if not root:
            raise ValueError(
                f"no flight run dir: pass root= or set {RUN_DIR_ENV}"
            )
        self.root = str(root)
        self.path = os.path.join(self.root, STORE_NAME)

    # -- append ------------------------------------------------------------
    def append(self, *, kind: str = "fit", record: dict | None = None,
               digest: dict | None = None, metrics: dict | None = None,
               section: str | None = None, config=None,
               platform: str | None = None,
               git: str | None = None) -> dict | None:
        """Append one envelope; returns it, or None when the sink is
        unwritable (warned, never raised — the telemetry contract).

        ``config``: an explicit config mapping (hashed), or None to
        derive the lineage key from ``record``. ``platform`` defaults to
        the record's mesh platform.
        """
        mesh = (record or {}).get("mesh") or {}
        if config is not None:
            cdig = config_digest(config)
        elif record is not None:
            cdig = config_digest_from_record(record, kind=str(kind))
        else:
            cdig = config_digest({"section": section})
        env = {
            "schema": FLIGHT_SCHEMA,
            "ts": time.time(),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "kind": str(kind),
            "section": section,
            "git": git if git is not None else git_sha(),
            "platform": platform or mesh.get("platform"),
            "mesh_axes": mesh.get("axes"),
            "config_digest": cdig,
            "digest": digest or {},
            "metrics": metrics or {},
            "record": record,
        }
        try:
            os.makedirs(self.root, exist_ok=True)
            with open(self.path, "a+b") as f:
                # Heal a torn tail first: a SIGKILL mid-append leaves a
                # partial line with no newline, and appending straight
                # onto it would corrupt THIS entry too — one lost line
                # must stay one lost line.
                f.seek(0, os.SEEK_END)
                if f.tell():
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        f.write(b"\n")
                f.write(
                    (json.dumps(env, sort_keys=True) + "\n").encode()
                )
        except OSError as e:
            warnings.warn(
                f"flight store unwritable ({e}); run not recorded at "
                f"{self.path}",
                stacklevel=2,
            )
            return None
        self._maybe_rotate()
        return env

    # -- retention (ISSUE 14) -----------------------------------------------
    def _maybe_rotate(self) -> None:
        """One os.stat; rotate only past the size cap (see module knobs).
        Telemetry contract holds: any failure degrades to a warning."""
        cap = _env_int(RUN_MAX_BYTES_ENV, 0)
        key = os.path.abspath(self.path)
        if cap <= 0 or key in _ROTATION_STUCK:
            return
        try:
            if os.stat(self.path).st_size <= cap:
                return
        except OSError:
            return
        try:
            self.trim(keep=_env_int(RUN_KEEP_ENV, KEEP_PER_LINEAGE))
            if os.stat(self.path).st_size > cap:
                # The tail trim alone cannot satisfy this cap (many
                # lineages x keep entries exceed it). Warn once and stop
                # rotating this store for the process — re-trimming on
                # every append would turn each telemetry write into a
                # full-file rewrite that drops nothing.
                _ROTATION_STUCK.add(key)
                warnings.warn(
                    f"flight store still {os.stat(self.path).st_size} "
                    f"bytes after a per-lineage tail trim (cap {cap}); "
                    f"raise {RUN_MAX_BYTES_ENV} or lower {RUN_KEEP_ENV} "
                    "— rotation stands down for this process",
                    stacklevel=3,
                )
        except OSError as e:
            warnings.warn(
                f"flight store rotation failed ({e}); {self.path} keeps "
                "growing",
                stacklevel=3,
            )

    def trim(self, keep: int = KEEP_PER_LINEAGE) -> int:
        """Per-lineage tail trim: rewrite the store keeping the newest
        ``keep`` entries of every lineage (file order = append order);
        returns the number of entries dropped.

        Torn/unparseable lines are dropped with the trim (they are
        already invisible to every reader), and the rewrite is
        write-temp + ``os.replace`` so a crash leaves either the old or
        the new store — never a torn one. Appends from a concurrent
        process during the rewrite window can be lost; the store is
        telemetry, and one lost envelope beats an unbounded file.
        """
        keep = max(int(keep), 1)
        entries = self.entries()
        per: dict = {}
        for env in entries:
            key = tuple(env.get(k) for k in LINEAGE_KEYS)
            per.setdefault(key, []).append(env)
        kept = {
            id(env) for rows in per.values() for env in rows[-keep:]
        }
        out = [env for env in entries if id(env) in kept]
        dropped = len(entries) - len(out)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for env in out:
                f.write(json.dumps(env, sort_keys=True) + "\n")
        os.replace(tmp, self.path)
        # An explicit trim re-arms a stood-down rotation (the caller may
        # have raised the keep/cap knobs); _maybe_rotate re-stands-down
        # if the cap is still unsatisfiable.
        _ROTATION_STUCK.discard(os.path.abspath(self.path))
        return dropped

    # -- query -------------------------------------------------------------
    def entries(self, *, kind: str | None = None,
                section: str | None = None,
                config_digest: str | None = None,
                platform: str | None = None,
                limit: int | None = None) -> list:
        """Stored envelopes oldest→newest matching every given filter.
        Torn/foreign lines are skipped (the tolerant-parse contract)."""
        out = []
        try:
            f = open(self.path)
        except OSError:
            return out
        with f:
            for ln in f:
                if not ln.strip():
                    continue
                try:
                    env = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if not isinstance(env, dict):
                    continue
                if kind is not None and env.get("kind") != kind:
                    continue
                if section is not None and env.get("section") != section:
                    continue
                if (config_digest is not None
                        and env.get("config_digest") != config_digest):
                    continue
                if platform is not None and env.get("platform") != platform:
                    continue
                out.append(env)
        return out[-limit:] if limit else out

    def lineage(self, envelope: dict, *, limit: int | None = None) -> list:
        """Every stored entry comparable to ``envelope`` (same kind /
        section / config digest / platform), oldest→newest."""
        return self.entries(
            kind=envelope.get("kind"), section=envelope.get("section"),
            config_digest=envelope.get("config_digest"),
            platform=envelope.get("platform"), limit=limit,
        )

    def sibling_lineage(self, envelope: dict, *,
                        platform: str,
                        limit: int | None = None) -> list:
        """The envelope's lineage AS RUN ON ``platform`` — same kind /
        section / config digest, different backend. The cross-platform
        comparison base (benchdiff ``--cross-platform``): only
        *structural* channels (psum/wire bytes, node counts,
        fingerprints) are comparable across it; wall-clock never is."""
        return self.entries(
            kind=envelope.get("kind"), section=envelope.get("section"),
            config_digest=envelope.get("config_digest"),
            platform=platform, limit=limit,
        )

    def latest(self, **filters) -> dict | None:
        rows = self.entries(**filters, limit=1)
        return rows[-1] if rows else None

    def baseline_for(self, envelope: dict) -> dict | None:
        """The newest lineage entry strictly older than ``envelope`` —
        what a fresh capture diffs against."""
        ts = envelope.get("ts")
        prior = [
            e for e in self.lineage(envelope)
            if ts is None or (e.get("ts") or 0) < ts
        ]
        return prior[-1] if prior else None


def append_record(record: dict, *, kind: str = "fit",
                  digest: dict | None = None,
                  section: str | None = None,
                  metrics: dict | None = None) -> dict | None:
    """Ambient-store append — what ``BuildObserver.report`` calls when
    ``MPITREE_TPU_RUN_DIR`` is set. No-op (None) when it isn't."""
    if not enabled():
        return None
    try:
        store = FlightStore()
    except ValueError:
        return None
    return store.append(
        kind=kind, record=record, digest=digest, section=section,
        metrics=metrics,
    )
