"""obs.diff — record diffing, divergence localization, and the
noise-aware regression sentinel.

The comparison layer the paper's own claim demands ("faster than the
8-rank MPI baseline at sklearn accuracy parity" is a *diff*, not a
number): given two comparable runs — flight-store envelopes
(``obs.flight``), bench section payloads, or raw ``fit_report_`` dicts —
emit per-metric verdicts and one overall verdict:

- ``ok`` — every metric within its threshold;
- ``improved`` — at least one metric better, none worse;
- ``changed`` — a deterministic (structural) metric moved with no
  better/worse direction (node counts, levels) — worth a look, not a
  gate failure;
- ``regression`` — a gated metric got worse past its threshold;
- ``diverged`` — the whole-fit build-state *fingerprint* differs: the
  two runs built different trees. The per-level fingerprint rows are
  then bisected (:func:`localize_divergence`) to the first divergent
  (tree/round, level) and the most upstream divergent channel
  (histogram → winner → allocation), so a broken bit-identity pin
  arrives as "round 3, level 2, hist channel" instead of a red diff.

Noise model — thresholds are **seeded from run history, not magic
constants**: metrics are classed *noisy* (wall clock, throughput,
latency, accuracy — rerunning the same config moves them) or
*structural* (psum/wire/HBM bytes, compile counts, node counts — a
deterministic function of config + code, where ANY change is signal).
Noisy metrics gate at ``max(floor, NOISE_Z × robust CV)`` where the
robust CV is ``1.4826·MAD/median`` over the lineage history
(:func:`threshold_for`); with fewer than :data:`MIN_HISTORY` prior runs
the documented floor applies. Structural metrics compare exactly.

Stdlib-only, no package imports — ``tools/benchdiff.py`` and
``tools/tpu_watcher.py`` load this by file path on jax-less hosts
(the ``obs/trace.py`` / ``obs/flight.py`` contract).
"""

from __future__ import annotations

import statistics

DIFF_SCHEMA = 1

# Mirrors obs/fingerprint.CHANNELS (kept literal here: stdlib-only, and
# the order IS the bisect's upstream-first report order). "refine" (v2)
# rides only refine-tail rows — crown rows omit it, and absent channels
# compare equal below — so a refine divergence reports by name.
CHANNELS = ("hist", "winner", "alloc", "refine")

# Robust z-score a noisy metric must exceed (vs lineage dispersion), and
# the minimum history depth before dispersion supersedes the floor.
NOISE_Z = 3.0
MIN_HISTORY = 3

# Metric classes. ``better``: which direction is an improvement (None =
# directionless structural change → verdict "changed"). ``rel``/``abs``:
# the no-history floor. Matching is exact-name first, then suffix.
METRIC_SPECS: dict = {
    # noisy wall-clock / latency (lower is better; rerun noise is real —
    # the committed BENCH_r01–r05 walls move ~10-20% run to run)
    "wall_s": {"kind": "noisy", "better": "lower", "rel": 0.25},
    "warm_s": {"kind": "noisy", "better": "lower", "rel": 0.25},
    "cold_s": {"kind": "noisy", "better": "lower", "rel": 0.40},
    "fit_s": {"kind": "noisy", "better": "lower", "rel": 0.25},
    "round_s": {"kind": "noisy", "better": "lower", "rel": 0.25},
    "value": {"kind": "noisy", "better": "lower", "rel": 0.25},
    # noisy rates (higher is better)
    "throughput_cells_per_s": {
        "kind": "noisy", "better": "higher", "rel": 0.20,
    },
    "vs_baseline": {"kind": "noisy", "better": "higher", "rel": 0.25},
    # accuracy: absolute floor — 0.005 of accuracy is the parity budget
    # the PARITY.md contract tracks, relative thresholds are meaningless
    # near 1.0
    "test_acc": {"kind": "noisy", "better": "higher", "abs": 0.005},
    "ours_test_acc": {"kind": "noisy", "better": "higher", "abs": 0.005},
    "acc_delta_vs_sklearn": {
        "kind": "noisy", "better": "higher", "abs": 0.005,
    },
    # structural: deterministic per (config, code) — any move is signal.
    # Directional ones gate (more bytes / more compiles = regression);
    # directionless ones report "changed".
    "psum_bytes": {"kind": "structural", "better": "lower"},
    "wire_bytes": {"kind": "structural", "better": "lower"},
    "wire_shard_bytes": {"kind": "structural", "better": "lower"},
    "hbm_peak_bytes": {"kind": "structural", "better": "lower"},
    "host_peak_bytes": {"kind": "structural", "better": "lower"},
    "compile_new": {"kind": "structural", "better": "lower"},
    "request_path_lowerings": {"kind": "structural", "better": "lower"},
    "events": {"kind": "structural", "better": "lower"},
    "n_nodes": {"kind": "structural", "better": None},
    "depth": {"kind": "structural", "better": None},
    "tree_depth": {"kind": "structural", "better": None},
    "tree_n_nodes": {"kind": "structural", "better": None},
    "levels": {"kind": "structural", "better": None},
    "expansions": {"kind": "structural", "better": None},
    "sub_frac": {"kind": "structural", "better": None},
    "feature_shards": {"kind": "structural", "better": None},
    "rounds_per_dispatch": {"kind": "structural", "better": None},
}

# Suffix fallbacks for section-payload scalars the table doesn't name
# (b64_p50_ms, sustained_rows_per_s, speedup_vs_estimator, ...).
_SUFFIX_SPECS = (
    ("_per_s", {"kind": "noisy", "better": "higher", "rel": 0.20}),
    ("_rows_per_s", {"kind": "noisy", "better": "higher", "rel": 0.20}),
    ("_p50_ms", {"kind": "noisy", "better": "lower", "rel": 0.35}),
    ("_p99_ms", {"kind": "noisy", "better": "lower", "rel": 0.50}),
    ("_ms", {"kind": "noisy", "better": "lower", "rel": 0.35}),
    ("_s", {"kind": "noisy", "better": "lower", "rel": 0.25}),
    ("_acc", {"kind": "noisy", "better": "higher", "abs": 0.005}),
    ("_bytes", {"kind": "structural", "better": "lower"}),
    ("_nodes", {"kind": "structural", "better": None}),
)

# Never compared (identity/bookkeeping fields that ride the same dicts).
_SKIP_KEYS = frozenset((
    "engine", "reason", "fingerprint", "record", "phases", "platform",
    "kernel", "ok", "partial", "ts", "git", "rows_cap",
))


def spec_for(metric: str) -> dict | None:
    """The metric's class spec, or None for uncompared keys."""
    if metric in _SKIP_KEYS:
        return None
    if metric in METRIC_SPECS:
        return METRIC_SPECS[metric]
    # First matching suffix wins; "_per_s" sits before "_s" so rates are
    # never misclassified as durations.
    for suffix, spec in _SUFFIX_SPECS:
        if metric.endswith(suffix):
            return spec
    return None


def scalar_metrics(payload: dict, *, prefix: str = "") -> dict:
    """Flatten a section payload / digest into comparable scalars.

    Top-level numeric scalars keep their names; an embedded ``record``
    digest contributes its own fields (digest names are already in the
    table). Booleans and strings are skipped.
    """
    out: dict = {}
    if not isinstance(payload, dict):
        return out
    for k, v in payload.items():
        if isinstance(v, bool) or k in _SKIP_KEYS and k != "record":
            continue
        if k == "record" and isinstance(v, dict):
            for rk, rv in v.items():
                if isinstance(rv, (int, float)) and not isinstance(rv, bool):
                    out.setdefault(rk, rv)
            continue
        if isinstance(v, (int, float)):
            out[prefix + k] = v
    return out


def history_values(history, metric: str) -> list:
    """The metric's numeric trajectory over lineage envelopes/payloads."""
    vals = []
    for h in history or ():
        m = {}
        m.update(scalar_metrics(h.get("digest") or {}))
        m.update(scalar_metrics(h.get("metrics") or {}))
        if not m:
            m = scalar_metrics(h)
        v = m.get(metric)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            vals.append(float(v))
    return vals


def threshold_for(metric: str, spec: dict, history=None) -> dict:
    """``{"rel" | "abs": x, "source": ...}`` — the gate for one metric.

    Structural metrics compare exactly (rel 0 with a 1e-9 float grain).
    Noisy metrics: with >= MIN_HISTORY prior observations the threshold
    is ``max(floor, NOISE_Z * 1.4826 * MAD / |median|)`` — a lineage
    whose wall clock naturally wobbles 15% gets a wider gate than one
    that repeats to 1%; with thin history the documented floor applies.
    """
    if spec["kind"] == "structural":
        return {"rel": 1e-9, "source": "exact"}
    if "abs" in spec:
        return {"abs": float(spec["abs"]), "source": "floor"}
    floor = float(spec.get("rel", 0.25))
    vals = history_values(history, metric)
    if len(vals) >= MIN_HISTORY:
        med = statistics.median(vals)
        if med:
            mad = statistics.median([abs(v - med) for v in vals])
            cv = 1.4826 * mad / abs(med)
            noise = NOISE_Z * cv
            if noise > floor:
                return {
                    "rel": round(noise, 4),
                    "source": f"history dispersion (n={len(vals)})",
                }
    return {"rel": floor, "source": "floor"}


def _metric_row(metric: str, base, cand, spec: dict, history) -> dict:
    thr = threshold_for(metric, spec, history)
    base_f, cand_f = float(base), float(cand)
    delta = cand_f - base_f
    ratio = (cand_f / base_f) if base_f else None
    if "abs" in thr:
        breach = abs(delta) > thr["abs"]
    else:
        breach = base_f != 0 and abs(delta) / abs(base_f) > thr["rel"] or (
            base_f == 0 and cand_f != 0
        )
    verdict = "ok"
    if breach:
        better = spec.get("better")
        if better is None:
            verdict = "changed"
        else:
            worse = delta > 0 if better == "lower" else delta < 0
            verdict = "regression" if worse else "improvement"
    return {
        "metric": metric, "base": base, "cand": cand,
        "delta": round(delta, 6),
        "ratio": None if ratio is None else round(ratio, 4),
        "kind": spec["kind"], "threshold": thr, "verdict": verdict,
    }


def localize_divergence(fp_a: dict, fp_b: dict) -> dict | None:
    """Bisect two records' fingerprint rows to the first divergence.

    Returns ``{"tree", "level", "channel", "channels"}`` — the first
    divergent tree/round index, the first divergent level inside it, the
    most upstream divergent channel (:data:`CHANNELS` order) and every
    divergent channel at that level — or None when the rows match (or
    either side carries none).
    """
    ta = (fp_a or {}).get("trees") or []
    tb = (fp_b or {}).get("trees") or []
    if not ta or not tb:
        return None
    for t, (ra, rb) in enumerate(zip(ta, tb)):
        la = {r["level"]: r for r in ra}
        lb = {r["level"]: r for r in rb}
        for lvl in sorted(set(la) | set(lb)):
            a, b = la.get(lvl), lb.get(lvl)
            if a is None or b is None:
                return {
                    "tree": t, "level": lvl, "channel": "hist",
                    "channels": list(CHANNELS),
                    "note": "level present in only one run",
                }
            bad = [c for c in CHANNELS if a.get(c) != b.get(c)]
            if bad:
                return {
                    "tree": t, "level": lvl, "channel": bad[0],
                    "channels": bad,
                }
    if len(ta) != len(tb):
        return {
            "tree": min(len(ta), len(tb)), "level": 0, "channel": "hist",
            "channels": list(CHANNELS),
            "note": f"tree counts differ ({len(ta)} vs {len(tb)})",
        }
    return None


def diff_metrics(base: dict, cand: dict, *, history=None) -> list:
    """Per-metric verdict rows over the keys both sides carry."""
    rows = []
    for metric in sorted(set(base) & set(cand)):
        spec = spec_for(metric)
        if spec is None:
            continue
        b, c = base[metric], cand[metric]
        if not all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in (b, c)
        ):
            continue
        rows.append(_metric_row(metric, b, c, spec, history))
    return rows


def _envelope_metrics(env: dict) -> dict:
    m = {}
    m.update(scalar_metrics(env.get("digest") or {}))
    m.update(scalar_metrics(env.get("metrics") or {}))
    return m


def diff_envelopes(base: dict, cand: dict, *, history=None) -> dict:
    """Diff two flight envelopes (or two ``{"digest","metrics","record"}``
    shaped dicts); ``history``: older lineage envelopes for thresholds.

    The sentinel verdict: fingerprint divergence dominates (different
    trees make perf deltas unattributable), then regressions, then
    structural changes, then improvements.
    """
    bm, cm = _envelope_metrics(base), _envelope_metrics(cand)
    rows = diff_metrics(bm, cm, history=history)
    fa = (base.get("digest") or {}).get("fingerprint")
    fb = (cand.get("digest") or {}).get("fingerprint")
    divergence = None
    if fa is not None and fb is not None and fa != fb:
        divergence = localize_divergence(
            (base.get("record") or {}).get("fingerprints") or {},
            (cand.get("record") or {}).get("fingerprints") or {},
        ) or {"tree": None, "level": None, "channel": None,
              "note": "whole-fit fingerprints differ; no per-level rows "
                      "stored to bisect"}
    regressions = [r["metric"] for r in rows if r["verdict"] == "regression"]
    changed = [r["metric"] for r in rows if r["verdict"] == "changed"]
    improved = [r["metric"] for r in rows if r["verdict"] == "improvement"]
    if divergence is not None:
        verdict = "diverged"
    elif regressions:
        verdict = "regression"
    elif changed:
        verdict = "changed"
    elif improved:
        verdict = "improved"
    else:
        verdict = "ok"
    return {
        "schema": DIFF_SCHEMA,
        "verdict": verdict,
        "metrics": rows,
        "regressions": regressions,
        "changed": changed,
        "improvements": improved,
        "fingerprint": {
            "base": fa, "cand": fb,
            "match": None if fa is None or fb is None else fa == fb,
            "divergence": divergence,
        },
        "n_history": len(history or ()),
    }


def diff_payloads(base_payload: dict, cand_payload: dict, *,
                  history=None) -> dict:
    """Diff two bench section payloads (``bench_tpu`` line sections):
    scalars + embedded record digests compare; ``history`` is earlier
    payloads of the same section."""
    return diff_envelopes(
        {"metrics": scalar_metrics(base_payload),
         "digest": (base_payload or {}).get("record") or {}},
        {"metrics": scalar_metrics(cand_payload),
         "digest": (cand_payload or {}).get("record") or {}},
        history=[
            {"metrics": scalar_metrics(h),
             "digest": (h or {}).get("record") or {}}
            for h in history or ()
        ],
    )


def exit_code(diff: dict) -> int:
    """Gate semantics: regressions and divergences fail; ok/changed/
    improved pass (changed still prints loudly)."""
    return 1 if diff.get("verdict") in ("regression", "diverged") else 0


def summary_line(diff: dict, *, label: str = "") -> str:
    """One log-friendly verdict line (what the watcher commits)."""
    v = diff.get("verdict")
    parts = [f"{label + ': ' if label else ''}verdict={v}"]
    if diff.get("regressions"):
        worst = [
            r for r in diff["metrics"] if r["verdict"] == "regression"
        ]
        parts.append("regressed " + ", ".join(
            f"{r['metric']} {r['base']}→{r['cand']}" for r in worst[:4]
        ))
    dv = (diff.get("fingerprint") or {}).get("divergence")
    if dv:
        parts.append(
            f"diverged at tree={dv.get('tree')} level={dv.get('level')} "
            f"channel={dv.get('channel')}"
        )
    if diff.get("changed"):
        parts.append("changed " + ", ".join(diff["changed"][:4]))
    if v == "improved":
        parts.append("improved " + ", ".join(diff["improvements"][:4]))
    return " | ".join(parts)


def format_diff(diff: dict, fmt: str = "human") -> str:
    """Render a diff: ``human`` (one row per metric) or ``github``
    (workflow ``::error``/``::warning`` annotations, the graftlint
    idiom — regressions/divergence error, changes warn)."""
    lines = []
    if fmt == "github":
        for r in diff["metrics"]:
            if r["verdict"] == "regression":
                lines.append(
                    f"::error title=benchdiff {r['metric']}::"
                    f"{r['metric']} regressed {r['base']} -> {r['cand']} "
                    f"(threshold {r['threshold']})"
                )
            elif r["verdict"] == "changed":
                lines.append(
                    f"::warning title=benchdiff {r['metric']}::"
                    f"{r['metric']} changed {r['base']} -> {r['cand']}"
                )
        dv = (diff.get("fingerprint") or {}).get("divergence")
        if dv:
            lines.append(
                "::error title=benchdiff divergence::builds diverged at "
                f"tree={dv.get('tree')} level={dv.get('level')} "
                f"channel={dv.get('channel')}"
            )
        lines.append(summary_line(diff))
        return "\n".join(lines)
    for r in diff["metrics"]:
        thr = r["threshold"]
        gate = (
            f"±{thr['abs']}" if "abs" in thr else f"±{thr['rel'] * 100:.1f}%"
        )
        lines.append(
            f"  {r['verdict']:<11} {r['metric']:<28} "
            f"{r['base']} -> {r['cand']}  ({gate}, {thr['source']})"
        )
    fpd = diff.get("fingerprint") or {}
    if fpd.get("match") is True:
        lines.append("  fingerprint  match")
    dv = fpd.get("divergence")
    if dv:
        lines.append(
            f"  DIVERGED at tree={dv.get('tree')} level={dv.get('level')} "
            f"channel={dv.get('channel')} (all: {dv.get('channels')})"
        )
    lines.append(summary_line(diff))
    return "\n".join(lines)
