"""Static-shape accounting: collective payloads and fused per-level rows.

Everything here is host arithmetic on STATIC shapes and the finished
tree's host arrays — it costs nothing on device, which is what lets
collective accounting stay always-on (ISSUE 3 tentpole piece 4). The
levelwise engine accounts live (it owns a host loop anyway); the fused
engine's whole build runs inside one ``lax.while_loop``, so its per-level
rows and psum totals are *reconstructed* after the fact from the depth
histogram of the finished tree — every allocated node was exactly once a
frontier member at its depth, so ``bincount(tree.depth)`` IS the frontier
trajectory, and the tier-routing replay below mirrors
``fused_builder._make_build_body``'s dispatch chain.
"""

from __future__ import annotations

import math

import numpy as np

from mpitree_tpu.obs import fingerprint as fingerprint_mod
from mpitree_tpu.obs import memory as memory_mod
from mpitree_tpu.parallel.collective import (
    counts_psum_bytes,
    gbdt_leaf_psum_bytes,
    select_global_bytes,
    split_psum_bytes,
)


def replay_fingerprints(tree) -> list:
    """Per-level build-state fingerprint rows synthesized from a finished
    tree (ISSUE 13) — the fused engines' twin of the level-wise loop's
    live per-level hashing, the same live/replay split as
    :func:`fused_level_rows` vs the live wire accounting. Both paths hash
    the same bytes from the same host arrays, so live and replayed rows
    are pinned equal (``tests/test_obs_flight.py``)."""
    return fingerprint_mod.tree_fingerprints(tree)


def build_memory_plan(*, mesh=None, mesh_axes=None,
                      **statics) -> memory_mod.MemoryPlan:
    """Assemble the analytical memory ledger for one build — the memory
    twin of :func:`fused_level_rows` (ISSUE 12): the fused engines run
    one compiled program with no per-phase host visibility, so their
    per-phase HBM watermarks are *replayed* analytically from the same
    statics the live level-wise loop prices — one assembly point, so the
    engines cannot drift in what they ledger.

    ``mesh``: a jax Mesh (axis widths are read off it); ``mesh_axes``
    the already-normalized alternative. Everything else forwards to
    :func:`mpitree_tpu.obs.memory.plan_fit`.
    """
    if mesh is not None and mesh_axes is None:
        mesh_axes = {
            str(n): int(mesh.shape[n]) for n in mesh.axis_names
        }
    return memory_mod.plan_fit(mesh_axes=mesh_axes, **statics)


def effective_tiers(tiers: tuple, max_depth: int) -> tuple:
    """Tiers reachable under a depth cap (``max_depth < 0`` = unbounded).

    The ONE copy of the trim ``fused_builder._make_build_body`` applies:
    depth-capped builds bound every interior frontier at
    ``2^(max_depth-1)``, so tiers that can never be the narrowest fit are
    dropped. ``tiers`` must already be normalized (sorted ascending,
    bounded by the chunk width — ``builder.valid_tiers``).
    """
    max_interior = (
        2 ** max(int(max_depth) - 1, 0) if max_depth >= 0 else None
    )
    if max_interior is None or not tiers:
        return tuple(tiers)
    kept, prev = [], 0
    for t in tiers:
        if prev < max_interior:
            kept.append(t)
        prev = t
    return tuple(kept)


def interior_big_reachable(tiers: tuple, max_depth: int) -> bool:
    """Whether the K-slot interior sweep can ever run (fused cond chain)."""
    max_interior = (
        2 ** max(int(max_depth) - 1, 0) if max_depth >= 0 else None
    )
    return not (
        max_interior is not None and tiers and max_interior <= max(tiers)
    )


def fused_level_rows(
    node_depths: np.ndarray,
    *,
    n_slots: int,
    tiers: tuple,
    n_features: int,
    n_bins: int,
    n_channels: int,
    counts_channels: int,
    max_depth: int,
    task: str,
    feature_shards: int = 1,
    data_shards: int = 1,
    n_rows: int | None = None,
    subtraction: bool = False,
    node_samples: np.ndarray | None = None,
    node_left: np.ndarray | None = None,
    node_right: np.ndarray | None = None,
) -> tuple:
    """(level_rows, collectives) replayed from a fused build's finished tree.

    ``node_depths``: the host ``tree.depth`` array. ``tiers`` must be the
    EFFECTIVE tier tuple the compiled program used
    (:func:`effective_tiers` of the valid tiers). ``n_channels`` is the
    histogram payload width (C for classification, 3 moment channels
    otherwise); ``counts_channels`` the terminal counts width.
    ``max_depth < 0`` = unbounded. ``subtraction`` replays the
    sibling-subtraction routing (``fused_builder``'s ``sub_ok`` carry): an
    interior level below the root whose frontier AND parent frontier each
    fit one chunk psums only the compact half-width small-child buffer.
    Returns per-level row dicts (seconds ``None`` — one compiled program
    has no per-level host clock) and a ``{site: {"calls", "bytes"}}``
    dict of logical psum/gather payloads.

    ``node_samples``/``node_left``/``node_right`` (the finished tree's
    per-node weights and child links) make the replay EXACT for realized
    work: every allocated node was once a frontier member at its depth,
    so the per-level frontier weight is ``bincount(depth, weights=n)``,
    and a subtraction level accumulates only each pair's smaller sibling
    — ``min(n[left], n[right])`` binned by child depth. Without them the
    per-row ``rows_scanned``/``small_child_fraction`` stay ``None``
    (depth histogram alone carries no row counts — the pre-ISSUE-8
    contract, still pinned by the golden replay test).
    """
    # On a 2-D (data, feature) mesh the psum'd histogram is each shard's
    # PADDED feature slab — the logical payload divides by the feature-
    # axis width, which is the whole point of the sharding (per-level ICI
    # payload independent of F). Mirrors the levelwise engine's live
    # accounting (builder.build_tree's f_shard).
    fs = max(int(feature_shards), 1)
    f_slab = (n_features + ((-n_features) % fs)) // fs
    depths_a = np.asarray(node_depths, np.int64)
    frontiers = np.bincount(depths_a)
    wlev = minlev = None
    # All-or-nothing: without the child links a subtraction level cannot
    # price its smaller siblings, and a zeros placeholder would claim
    # ZERO realized work — keep the documented None contract instead.
    if (node_samples is not None and node_left is not None
            and node_right is not None):
        n = np.asarray(node_samples, np.float64)
        wlev = np.bincount(depths_a, weights=n, minlength=len(frontiers))
        minlev = np.zeros(len(frontiers) + 1)
        li = np.asarray(node_left)
        ids = np.flatnonzero(li >= 0)
        if len(ids):
            mw = np.minimum(
                n[li[ids]], n[np.asarray(node_right)[ids]]
            )
            minlev = np.bincount(
                depths_a[ids] + 1, weights=mw,
                minlength=len(frontiers) + 1,
            )
    rows: list = []
    coll: dict = {}

    def add(site, calls, nbytes):
        entry = coll.setdefault(site, {"calls": 0, "bytes": 0})
        entry["calls"] += calls
        entry["bytes"] += nbytes

    K = n_slots
    prev_one_chunk = False  # the root has no parent histogram above it
    for d, f in enumerate(frontiers.tolist()):
        if f == 0:
            continue
        splits = (
            int(frontiers[d + 1]) // 2 if d + 1 < len(frontiers) else 0
        )
        terminal = max_depth >= 0 and d == max_depth
        scanned = small_frac = None
        if terminal:
            chunks = math.ceil(f / K)
            nbytes = chunks * counts_psum_bytes(
                n_slots=K, n_channels=counts_channels
            )
            add("counts_psum", chunks, nbytes)
            hist_bytes = 0
            psum_bytes = nbytes
            prev_one_chunk = False
        else:
            S = next((s for s in tiers if f <= s), K)
            chunks = 1 if S < K else math.ceil(f / K)
            sub_here = subtraction and chunks == 1 and prev_one_chunk
            per_chunk = split_psum_bytes(
                n_slots=S // 2 if sub_here else S,
                n_features=f_slab, n_bins=n_bins,
                n_channels=n_channels,
            )
            hist_bytes = chunks * per_chunk
            psum_bytes = chunks * per_chunk
            add("split_hist_psum", chunks, chunks * per_chunk)
            if task == "regression":
                yb = chunks * 2 * S * 4  # pmin/pmax of per-slot f32 y range
                add("y_range_pminmax", chunks, yb)
                psum_bytes += yb
            if feature_shards > 1:
                # select_global's stacked winner gather per chunk, plus
                # the per-level row-routing psum of child ids — per-RING
                # payloads (each feature ring reduces one data-shard's
                # local row block; wire_estimate scales by the concurrent
                # group count), matching the levelwise live accounting.
                gb = chunks * select_global_bytes(n_slots=S)
                add("feature_merge_all_gather", chunks, gb)
                if n_rows is not None:
                    add("route_psum", 1,
                        -(-n_rows // max(int(data_shards), 1)) * 4)
            if wlev is not None:
                fw = float(wlev[d])
                scanned = float(minlev[d]) if sub_here and d > 0 else fw
                small_frac = round(scanned / fw, 6) if fw else None
            prev_one_chunk = chunks == 1
        rows.append({
            "level": d,
            "frontier": int(f),
            "splits": splits,
            "hist_bytes": int(hist_bytes),
            "psum_bytes": int(psum_bytes),
            "rows_scanned": scanned,
            "small_child_fraction": small_frac,
            "seconds": None,
            "new_lowerings": 0,
        })
    return rows, coll


def fused_scan_rows(tree, **kwargs) -> tuple:
    """(rows, coll, counters): :func:`fused_level_rows` with the exact
    realized-work replay wired up from the finished ``TreeArrays``.

    The always-on ``rows_scanned``/``rows_frontier`` counters mirror the
    host-stepped levelwise loop's live accounting (``builder.build_tree``)
    so the ``leafwise_ab`` bench A/B reads the same counter names off
    every engine: scanned = weight actually accumulated into split
    histograms (small siblings only at subtraction levels), frontier =
    what direct accumulation would have scanned. Terminal counts-only
    levels pay no split histogram and count toward neither.
    """
    rows, coll = fused_level_rows(
        tree.depth, node_samples=tree.n_node_samples,
        node_left=tree.left, node_right=tree.right, **kwargs,
    )
    wlev = np.bincount(
        np.asarray(tree.depth, np.int64),
        weights=np.asarray(tree.n_node_samples, np.float64),
    )
    live = [r for r in rows if r["rows_scanned"] is not None]
    counters = {}
    if live:
        counters = {
            "rows_scanned": int(round(sum(
                r["rows_scanned"] for r in live
            ))),
            "rows_frontier": int(round(sum(
                float(wlev[r["level"]]) for r in live
            ))),
        }
    return rows, coll, counters


def leafwise_scan_rows(tree, *, n_features: int, n_bins: int,
                       n_channels: int, task: str, subtraction: bool,
                       gbdt_x64: bool = False,
                       gbdt_leaf_slots: int | None = None) -> tuple:
    """(rows, collectives, counters) replayed from a leaf-wise build.

    Unlike the level-wise replay, the finished tree carries EXACT
    per-expansion work: each interior node was expanded exactly once,
    paying one sibling-pair histogram whose accumulated weight is both
    children (direct) or the smaller child (``subtraction``) — plus the
    root bootstrap, which always scans everything. ``rows_scanned`` /
    ``rows_frontier`` therefore come out exact (the realized-savings
    counters the ``leafwise_ab`` bench A/B compares against the
    level-wise engines' live counters); per-depth aggregate rows stand in
    for the expansion order, which the finished structure cannot replay
    (the host-stepped engine emits true per-expansion rows live instead).
    """
    n = np.asarray(tree.n_node_samples, np.float64)
    interior = tree.left >= 0
    exp_ids = np.flatnonzero(interior)
    nl = n[tree.left[exp_ids]] if len(exp_ids) else np.zeros(0)
    nr = n[tree.right[exp_ids]] if len(exp_ids) else np.zeros(0)
    acc = np.minimum(nl, nr) if subtraction else nl + nr
    rows_scanned = float(n[0]) + float(acc.sum())
    rows_frontier = float(n[0]) + float((nl + nr).sum())
    counters = {
        "rows_scanned": int(round(rows_scanned)),
        "rows_frontier": int(round(rows_frontier)),
        "expansions": int(len(exp_ids)),
    }

    per_pair = split_psum_bytes(
        n_slots=1 if subtraction else 2, n_features=n_features,
        n_bins=n_bins, n_channels=n_channels,
        itemsize=8 if gbdt_x64 else 4,
    )
    calls = len(exp_ids) + 1  # + the root bootstrap pair
    coll = {"split_hist_psum": {"calls": calls, "bytes": calls * per_pair}}
    if task == "regression":
        coll["y_range_pminmax"] = {"calls": calls, "bytes": calls * 2 * 2 * 4}
    if gbdt_leaf_slots is not None:
        # The fused-rounds engine refits leaf values and reduces the
        # training loss in-program once per round tree (G/H over the
        # padded M node slots + two loss scalars).
        coll["gbdt_leaf_psum"] = {
            "calls": 1,
            "bytes": gbdt_leaf_psum_bytes(
                n_slots=gbdt_leaf_slots, itemsize=8 if gbdt_x64 else 4
            ),
        }

    rows = []
    depths = tree.depth[tree.left[exp_ids]] if len(exp_ids) else np.zeros(0)
    for d in sorted(set(np.asarray(depths, np.int64).tolist())):
        sel = depths == d
        scanned = float(acc[sel].sum())
        frontier = float((nl + nr)[sel].sum())
        rows.append({
            "level": int(d),
            "frontier": int(2 * sel.sum()),
            "splits": int(interior[tree.left[exp_ids[sel]]].sum()
                          + interior[tree.right[exp_ids[sel]]].sum()),
            "hist_bytes": int(sel.sum()) * per_pair,
            "psum_bytes": int(sel.sum()) * per_pair,
            "rows_scanned": scanned,
            "small_child_fraction": (
                round(scanned / frontier, 6) if frontier else None
            ),
            "seconds": None,
            "new_lowerings": 0,
        })
    return rows, coll, counters
