"""Static-shape accounting: collective payloads and fused per-level rows.

Everything here is host arithmetic on STATIC shapes and the finished
tree's host arrays — it costs nothing on device, which is what lets
collective accounting stay always-on (ISSUE 3 tentpole piece 4). The
levelwise engine accounts live (it owns a host loop anyway); the fused
engine's whole build runs inside one ``lax.while_loop``, so its per-level
rows and psum totals are *reconstructed* after the fact from the depth
histogram of the finished tree — every allocated node was exactly once a
frontier member at its depth, so ``bincount(tree.depth)`` IS the frontier
trajectory, and the tier-routing replay below mirrors
``fused_builder._make_build_body``'s dispatch chain.
"""

from __future__ import annotations

import math

import numpy as np

from mpitree_tpu.parallel.collective import (
    counts_psum_bytes,
    split_psum_bytes,
)


def effective_tiers(tiers: tuple, max_depth: int) -> tuple:
    """Tiers reachable under a depth cap (``max_depth < 0`` = unbounded).

    The ONE copy of the trim ``fused_builder._make_build_body`` applies:
    depth-capped builds bound every interior frontier at
    ``2^(max_depth-1)``, so tiers that can never be the narrowest fit are
    dropped. ``tiers`` must already be normalized (sorted ascending,
    bounded by the chunk width — ``builder.valid_tiers``).
    """
    max_interior = (
        2 ** max(int(max_depth) - 1, 0) if max_depth >= 0 else None
    )
    if max_interior is None or not tiers:
        return tuple(tiers)
    kept, prev = [], 0
    for t in tiers:
        if prev < max_interior:
            kept.append(t)
        prev = t
    return tuple(kept)


def interior_big_reachable(tiers: tuple, max_depth: int) -> bool:
    """Whether the K-slot interior sweep can ever run (fused cond chain)."""
    max_interior = (
        2 ** max(int(max_depth) - 1, 0) if max_depth >= 0 else None
    )
    return not (
        max_interior is not None and tiers and max_interior <= max(tiers)
    )


def fused_level_rows(
    node_depths: np.ndarray,
    *,
    n_slots: int,
    tiers: tuple,
    n_features: int,
    n_bins: int,
    n_channels: int,
    counts_channels: int,
    max_depth: int,
    task: str,
    feature_shards: int = 1,
    n_rows: int | None = None,
    subtraction: bool = False,
) -> tuple:
    """(level_rows, collectives) replayed from a fused build's finished tree.

    ``node_depths``: the host ``tree.depth`` array. ``tiers`` must be the
    EFFECTIVE tier tuple the compiled program used
    (:func:`effective_tiers` of the valid tiers). ``n_channels`` is the
    histogram payload width (C for classification, 3 moment channels
    otherwise); ``counts_channels`` the terminal counts width.
    ``max_depth < 0`` = unbounded. ``subtraction`` replays the
    sibling-subtraction routing (``fused_builder``'s ``sub_ok`` carry): an
    interior level below the root whose frontier AND parent frontier each
    fit one chunk psums only the compact half-width small-child buffer.
    Returns per-level row dicts (seconds ``None`` — one compiled program
    has no per-level host clock; ``rows_scanned``/``small_child_fraction``
    ``None`` — the depth histogram carries no per-node row counts) and a
    ``{site: {"calls", "bytes"}}`` dict of logical psum/gather payloads.
    """
    frontiers = np.bincount(np.asarray(node_depths, np.int64))
    rows: list = []
    coll: dict = {}

    def add(site, calls, nbytes):
        entry = coll.setdefault(site, {"calls": 0, "bytes": 0})
        entry["calls"] += calls
        entry["bytes"] += nbytes

    K = n_slots
    prev_one_chunk = False  # the root has no parent histogram above it
    for d, f in enumerate(frontiers.tolist()):
        if f == 0:
            continue
        splits = (
            int(frontiers[d + 1]) // 2 if d + 1 < len(frontiers) else 0
        )
        terminal = max_depth >= 0 and d == max_depth
        if terminal:
            chunks = math.ceil(f / K)
            nbytes = chunks * counts_psum_bytes(
                n_slots=K, n_channels=counts_channels
            )
            add("counts_psum", chunks, nbytes)
            hist_bytes = 0
            psum_bytes = nbytes
            prev_one_chunk = False
        else:
            S = next((s for s in tiers if f <= s), K)
            chunks = 1 if S < K else math.ceil(f / K)
            sub_here = subtraction and chunks == 1 and prev_one_chunk
            per_chunk = split_psum_bytes(
                n_slots=S // 2 if sub_here else S,
                n_features=n_features, n_bins=n_bins,
                n_channels=n_channels,
            )
            hist_bytes = chunks * per_chunk
            psum_bytes = chunks * per_chunk
            add("split_hist_psum", chunks, chunks * per_chunk)
            if task == "regression":
                yb = chunks * 2 * S * 4  # pmin/pmax of per-slot f32 y range
                add("y_range_pminmax", chunks, yb)
                psum_bytes += yb
            if feature_shards > 1:
                # select_global's stacked (4, S) f32 all_gather per chunk,
                # plus the per-level row-routing psum of child ids.
                gb = chunks * 4 * S * 4
                add("feature_merge_all_gather", chunks, gb)
                if n_rows is not None:
                    add("route_psum", 1, n_rows * 4)
            prev_one_chunk = chunks == 1
        rows.append({
            "level": d,
            "frontier": int(f),
            "splits": splits,
            "hist_bytes": int(hist_bytes),
            "psum_bytes": int(psum_bytes),
            "rows_scanned": None,
            "small_child_fraction": None,
            "seconds": None,
            "new_lowerings": 0,
        })
    return rows, coll
