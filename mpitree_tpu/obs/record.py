"""BuildRecord: the schema-versioned structured run record every fit emits.

The reference's only observability was a hand-run ``time.time()`` sweep in a
notebook (SURVEY.md §5); our first replacement was a single env-gated
``PhaseTimer`` that recorded *how long* a build took but never *why* it
behaved the way it did. A ``BuildRecord`` is the why: the engine decision
and its reason, the mesh, per-level (or per-phase) rows, compile and
collective accounting, and every structured event (f32-ceiling trips,
fallbacks, determinism-check results) that previously only reached stderr.

Contract:

- **JSON-serializable and schema-versioned.** ``to_dict()`` returns plain
  Python containers (numpy scalars coerced); ``SCHEMA_VERSION`` bumps on
  any field rename/removal so ``BENCH_TPU.jsonl`` consumers can gate.
  The top-level field set is pinned by a golden test
  (``tests/test_obs.py``) — renaming a field is an intentional,
  version-bumped act, never a refactor accident.
- **Cheap when observability is off.** Counters, decisions, events, and
  collective/compile accounting are always on (they are O(1) host dict
  updates computed from static shapes); wall-clock spans and per-level
  rows only exist under ``MPITREE_TPU_PROFILE=1``.
"""

from __future__ import annotations

import dataclasses
import json

# v2: level rows gained ``rows_scanned``/``small_child_fraction`` and the
# digest gained ``sub_frac`` (sibling-subtraction realized savings).
# v3 (ISSUE 8, leaf-wise growth): top-level ``level_stream`` (per-level/
# per-expansion rows past the in-record cap spill to a JSONL file instead
# of dropping — leaf-wise builds emit one row per EXPANSION and blow the
# 512-row cap at max_leaf_nodes=255 within two boosting rounds); digest
# gained ``expansions`` (leaf-wise expansion count) and
# ``rounds_per_dispatch`` (fused multi-round GBDT dispatch width).
# v4 (ISSUE 9, observability v2): top-level ``wire`` — the collective
# ledger's per-site/per-fit/per-shard wire-traffic estimates derived from
# the logical psum payloads and the mesh width (ROADMAP obs follow-up 2);
# digest gained ``wire_bytes``/``wire_shard_bytes``; ``compile`` entries
# gained ``seconds`` (cold-dispatch wall attributed per entry point —
# ROADMAP obs follow-up 1).
# v5 (ISSUE 10, 2-D (data, feature) meshes): ``wire`` attributes traffic
# per MESH AXIS — each site entry carries its ``axis`` and widths come
# from ``record.mesh['axes']`` instead of the flat device count (a psum
# over a 4-wide data axis on a (4, 2) mesh rings over 4 shards, not 8);
# top-level ``wire`` gained ``axes``/``data_bytes``/``feature_bytes``
# and the digest a ``feature_shards`` field.
# v6 (ISSUE 12, obs.memory): top-level ``memory`` — the device/host
# memory ledger (``obs/memory.py``): analytical per-array per-device
# byte rows with per-phase watermarks priced from the partition-rule
# table, plus the ``live`` span-boundary watermark samples when
# ``MPITREE_TPU_MEM_SAMPLE=1``; digest gained
# ``hbm_peak_bytes``/``host_peak_bytes``.
# v7 (ISSUE 13, obs.flight): top-level ``fingerprints`` — cheap u64
# per-level/per-round build-state fingerprints (``obs/fingerprint.py``:
# hist/winner/alloc channels per tree level, live at the level-wise
# host boundaries, replayed from finished trees for the fused
# engines); digest gained the whole-fit ``fingerprint``, the one u64
# ``obs.diff`` bisects from when two runs' digests disagree. Host-loop
# multi-round fits may carry ``memory['aggregate']`` (the whole-fit
# MemoryPlan aggregation that re-arms drift checking, a PR-12
# follow-up).
# v8 (ISSUE 14, resilience v2): digest gains ``level_retries`` /
# ``oom_rescues`` — the sub-build retry and OOM-rescue rung counters
# (typed events ``level_retry``/``oom_rescue``), so the watcher's
# per-section digest line attributes fine-grained recovery without
# parsing the event list. No record field changed shape.
# v9 (ISSUE 18, obs.cost): top-level ``compute`` — the XLA cost-model
# compute ledger (``obs/cost.py``): per-entry flops/bytes captured once
# per fresh compile cache key, optimal-seconds floors from the
# per-platform peak table, achieved utilization joined against the
# measured span walls, per-level floors, and the roofline verdict
# (compute-/HBM-/ICI-bound, the ICI leg from the v4 wire ledger).
# Digest gains ``util_pct``/``roofline``; unpriceable entries are
# honest ``None`` with a typed ``cost_unavailable`` event.
SCHEMA_VERSION = 9

# Which mesh axis each collective site reduces/gathers over — the wire
# ledger's per-axis attribution. Every histogram/counts/y-range reduction
# rides the data axis; the split-winner merge (collective.select_global)
# and the update step's owner-broadcast of child ids are the only
# feature-axis collectives. Unknown sites default to "data".
COLLECTIVE_AXES = {
    "feature_merge_all_gather": "feature",
    "route_psum": "feature",
}

# The golden field set: tests/test_obs.py pins this against to_dict() so a
# rename cannot slip past bench/watcher consumers silently.
TOP_LEVEL_FIELDS = (
    "schema",
    "engine",
    "mesh",
    "decisions",
    "phases",
    "levels",
    "counters",
    "compile",
    "collectives",
    "events",
    "rounds",
    "trees",
    "result",
    "level_stream",
    "wire",
    "memory",
    "fingerprints",
    "compute",
)


def _jsonable(obj):
    """Coerce numpy scalars/containers to plain JSON-serializable Python."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, bool)) or obj is None:
        return obj
    if isinstance(obj, int):
        return obj
    if isinstance(obj, float):
        return obj
    # numpy scalars (and anything else numeric-ish) land here
    if hasattr(obj, "item"):
        return _jsonable(obj.item())
    return str(obj)


@dataclasses.dataclass
class BuildRecord:
    """One fit's structured run record (see module docstring).

    Field semantics:

    - ``engine``: ``{"value", "reason", "inputs"}`` — the resolved build
      engine AND why (``core/builder.py``'s "auto" resolution inputs).
    - ``mesh``: ``{"platform", "n_devices", "axes"}``.
    - ``decisions``: every other recorded routing decision
      (``build_path``, ``refine``, ``early_stop``, ...), same shape as
      ``engine``.
    - ``phases``: PhaseTimer summary (``{name: {seconds, calls}}``) —
      populated only under ``MPITREE_TPU_PROFILE=1``.
    - ``levels``: per-level rows ``{level, frontier, splits, hist_bytes,
      psum_bytes, rows_scanned, small_child_fraction, seconds,
      new_lowerings}`` (levelwise/host: live; fused: reconstructed
      post-hoc from the finished tree's depth histogram, where the two
      row-scan fields are ``None`` — depth counts carry no per-node row
      totals). ``rows_scanned`` is the weight actually accumulated into
      split histograms (under sibling subtraction: the smaller siblings
      only); ``small_child_fraction = rows_scanned / frontier rows``.
      Profile-gated; capped (see BuildObserver).
    - ``counters``: always-on integer counters.
    - ``compile``: per jit entry point ``{"lowerings": lowering events
      seen process-wide (distinct keys, plus re-lowerings of keys the
      factory lru evicted), "new": lowerings triggered during this
      fit}`` — the runtime twin of graftlint GL02.
    - ``collectives``: per psum/gather site ``{"calls", "bytes"}`` — the
      LOGICAL payload computed from static shapes (zero device cost;
      multiply by (shards-1)/shards for wire traffic on an N-wide axis).
    - ``events``: typed events ``{"kind", "message"}`` — the structured
      form of what previously only went to stderr via ``warnings.warn``.
      The resilience ladder (``mpitree_tpu.resilience``) reports through
      here: ``device_retry`` (transient loss re-dispatched on the
      accelerator; paired counter ``device_retries``),
      ``device_failover`` (final rung, host rebuild; counter
      ``device_failovers``), ``checkpoint_resume`` (rounds/groups
      restored), ``nonfinite_grad`` (poisoned gbdt loss channel,
      fail-fast), ``checkpoint_disabled``.
    - ``rounds``: boosting per-round records (train/val loss, subsample
      fraction, early-stop state).
    - ``trees``: ensemble per-member summaries ``{"n_nodes", "depth"}``.
    - ``result``: ``{"n_nodes", "depth"}`` of the fitted tree (aggregates
      for ensembles).
    - ``level_stream``: ``{"path", "rows"}`` when per-level/per-expansion
      rows past the in-record cap were streamed to a JSONL spill file
      (``BuildObserver.stream_levels_to`` / ``MPITREE_TPU_OBS_STREAM_DIR``)
      instead of dropped; ``{}`` otherwise.
    - ``wire``: the collective ledger (:func:`wire_estimate`) — per-site
      and total wire-traffic estimates derived from the LOGICAL psum
      payloads above and the PER-AXIS mesh widths: a ring all-reduce of
      B logical bytes over an n-shard axis moves ``B*(n-1)/n`` per
      shard, ``B*(n-1)`` per concurrent ring across the fabric. Each
      site entry carries the ``axis`` it crosses
      (:data:`COLLECTIVE_AXES`) and the top level breaks fabric bytes
      down as ``data_bytes``/``feature_bytes`` (v5). Zero on a single
      device (no ICI hop exists). Populated by
      ``BuildObserver.report()``.
    - ``memory`` (v6): the device/host memory ledger
      (``obs.memory.MemoryPlan.to_dict()``) — per-array per-device byte
      rows with per-phase watermarks, ``hbm_peak_bytes``/
      ``host_peak_bytes``, the pricing inputs, and (with sampling on) a
      ``live`` section of span-boundary watermarks; ``{}`` when the
      engine recorded no plan. Host-loop multi-round fits add
      ``aggregate`` (v7): the whole-fit plan aggregation drift checking
      compares against.
    - ``fingerprints`` (v7): ``{"version", "trees": [[{level, nodes,
      hist, winner, alloc}, ...], ...], "fit"}`` — per-level u64 state
      fingerprints per built tree/round (``obs/fingerprint.py``) plus
      the whole-fit fold; ``{}`` when no engine committed any (plain
      PhaseTimer callers). ``obs.diff.localize_divergence`` bisects two
      records' trees to the first divergent (tree, level, channel).
    - ``compute`` (v9): the XLA cost-model compute ledger
      (``obs/cost.py``) — ``{"peak", "n_shards", "entries", "levels",
      "optimal_s", "measured_s", "util_pct", "roofline", "bounds_s"}``.
      ``entries`` maps each jit entry point to its captured whole-program
      flops/bytes (once per fresh compile cache key), the per-shard
      division, the optimal-seconds floor from the platform peak table,
      and achieved utilization joined against the measured span wall;
      ``levels`` carries per-level HBM/ICI floors against the per-level
      walls; ``roofline`` names the resource the fit's floor sits on
      (``"compute"``/``"hbm"``/``"ici"``). Everything unpriceable
      (unknown platform, legacy wheel, missing dispatch counts) is
      ``None``; ``{}`` when no entry was captured.
    """

    schema: int = SCHEMA_VERSION
    engine: dict = dataclasses.field(default_factory=dict)
    mesh: dict = dataclasses.field(default_factory=dict)
    decisions: dict = dataclasses.field(default_factory=dict)
    phases: dict = dataclasses.field(default_factory=dict)
    levels: list = dataclasses.field(default_factory=list)
    counters: dict = dataclasses.field(default_factory=dict)
    compile: dict = dataclasses.field(default_factory=dict)
    collectives: dict = dataclasses.field(default_factory=dict)
    events: list = dataclasses.field(default_factory=list)
    rounds: list = dataclasses.field(default_factory=list)
    trees: list = dataclasses.field(default_factory=list)
    result: dict = dataclasses.field(default_factory=dict)
    level_stream: dict = dataclasses.field(default_factory=dict)
    wire: dict = dataclasses.field(default_factory=dict)
    memory: dict = dataclasses.field(default_factory=dict)
    fingerprints: dict = dataclasses.field(default_factory=dict)
    compute: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return _jsonable(dataclasses.asdict(self))

    def to_json(self, **kwargs) -> str:
        kwargs.setdefault("sort_keys", True)
        return json.dumps(self.to_dict(), **kwargs)

    @classmethod
    def from_json(cls, text: str) -> "BuildRecord":
        data = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


def wire_estimate(collectives: dict, axes) -> dict:
    """The collective ledger: wire-traffic estimates per psum/gather site.

    ``collectives`` holds LOGICAL payloads (static-shape bytes per call
    site); on an ``n``-shard axis a ring all-reduce of B logical bytes
    moves ``B*(n-1)/n`` per shard and ``B*(n-1)`` across the fabric —
    the per-shard/per-fit ICI wire estimates the ROADMAP obs follow-up
    asked for. One device means no ICI hop: everything is zero, honestly.

    ``axes``: the mesh's axis widths (``record.mesh['axes']``, e.g.
    ``{"data": 4, "feature": 2}``) — each site's ring width is the width
    of ITS axis (:data:`COLLECTIVE_AXES`), not the flat device count: a
    data-axis psum on a (4, 2) mesh runs df=2 independent 4-shard rings,
    and the recorded logical payload is already per feature group. A
    plain int (legacy callers) means a 1-D data axis of that width. An
    axis the mesh does not carry has width 1 — zero wire. The per-axis
    breakdown (``data_bytes``/``feature_bytes``) sums fabric wire bytes
    by the axis they cross.
    """
    if not isinstance(axes, dict):
        axes = {"data": int(axes or 1)}
    axes = {str(k): int(v) for k, v in axes.items()}
    n = 1
    for v in axes.values():
        n *= max(v, 1)
    sites = {}
    total_logical = 0
    total_wire = 0
    total_shard = 0
    per_axis = {"data": 0, "feature": 0}
    for site, v in sorted(collectives.items()):
        b = int(v.get("bytes", 0))
        axis = COLLECTIVE_AXES.get(site, "data")
        w = max(int(axes.get(axis, 1)), 1)
        # The fabric total counts every concurrent ring: a data-axis
        # reduction on a (dr, df) mesh runs df independent dr-shard rings
        # (one per feature group), each moving the recorded per-group
        # payload; each SHARD still sits in exactly one ring.
        groups = max(n // w, 1)
        wire = b * (w - 1) * groups
        total_logical += b
        total_wire += wire
        total_shard += b * (w - 1) // w
        per_axis[axis] = per_axis.get(axis, 0) + wire
        sites[site] = {
            "bytes": b,
            "axis": axis,
            "wire_bytes": wire,
            "wire_bytes_per_shard": b * (w - 1) // w,
        }
    return {
        "n_shards": n,
        "axes": axes,
        "sites": sites,
        "bytes": total_logical,
        "wire_bytes": total_wire,
        "wire_bytes_per_shard": total_shard,
        "data_bytes": per_axis["data"],
        "feature_bytes": per_axis["feature"],
    }


def digest(report: dict) -> dict:
    """Compact summary of a report dict — what bench section lines embed.

    Small by construction (~10 scalar fields) so a ``BENCH_TPU.jsonl``
    line carrying one per section stays within the driver's tail window
    (the round-4 truncation lesson, ``tests/test_bench_contract.py``).
    The one-line string rendering lives in
    ``bench_tpu.format_record_digest`` — deliberately NOT here, so the
    watcher can format stored digests without importing jax.
    """
    total_psum = sum(
        int(v.get("bytes", 0)) for v in report.get("collectives", {}).values()
    )
    wall = sum(
        float(v.get("seconds", 0.0)) for v in report.get("phases", {}).values()
    )
    # Realized sibling-subtraction savings: the fraction of interior
    # frontier weight that was actually accumulated into histograms
    # (1.0 = direct accumulation everywhere; ~0.5 + 1/levels is the
    # steady-state floor — the root always scans fully). None when the
    # engine recorded no row counters (fused replay, host tiers).
    counters = report.get("counters", {})
    scanned = counters.get("rows_scanned")
    frontier = counters.get("rows_frontier")
    return {
        "engine": report.get("engine", {}).get("value"),
        "reason": (report.get("engine", {}).get("reason") or "")[:120],
        "n_nodes": report.get("result", {}).get("n_nodes"),
        "depth": report.get("result", {}).get("depth"),
        "levels": len(report.get("levels", [])),
        "compile_new": sum(
            int(v.get("new", 0)) for v in report.get("compile", {}).values()
        ),
        "psum_bytes": total_psum,
        "sub_frac": (
            round(scanned / frontier, 4) if scanned is not None and frontier
            else None
        ),
        # Leaf-wise growth (ISSUE 8): interior expansions the best-first
        # frontier actually paid for (None for level-wise builds), and
        # the fused multi-round GBDT dispatch width (None for
        # host-per-round loops and non-boosting fits).
        "expansions": counters.get("expansions"),
        "rounds_per_dispatch": (
            report.get("decisions", {}).get("rounds_per_dispatch") or {}
        ).get("value"),
        "events": len(report.get("events", [])),
        # The collective ledger's per-fit/per-shard ICI wire estimates
        # (v4): zero on one device — a nonzero number here is real fabric
        # traffic, not logical payload (that's psum_bytes).
        "wire_bytes": report.get("wire", {}).get("wire_bytes"),
        "wire_shard_bytes": report.get("wire", {}).get(
            "wire_bytes_per_shard"
        ),
        # Feature-axis width of the build mesh (v5): 1 on every 1-D data
        # mesh — a >1 value says histograms were feature-sharded and
        # psum_bytes is per-slab, not per-F.
        "feature_shards": (
            report.get("mesh", {}).get("axes", {}) or {}
        ).get("feature", 1),
        # The memory ledger's predicted per-device peak HBM and host RAM
        # (v6): None when the engine recorded no plan (plain-PhaseTimer
        # callers, pre-v6 records).
        "hbm_peak_bytes": (report.get("memory") or {}).get(
            "hbm_peak_bytes"
        ),
        "host_peak_bytes": (report.get("memory") or {}).get(
            "host_peak_bytes"
        ),
        # The whole-fit build-state fingerprint (v7): one u64 over every
        # level of every tree (obs/fingerprint.py). Two lineage entries
        # whose fingerprints differ built DIFFERENT trees — obs.diff then
        # bisects the per-level rows to the first divergent
        # (tree, level, channel). None when no engine committed rows.
        "fingerprint": (report.get("fingerprints") or {}).get("fit"),
        # Fine-grained recovery counters (v8, resilience v2): sub-build
        # re-dispatches (level/expansion/dispatch granularity) and
        # on-device OOM rescues. None when the fit needed neither — a
        # nonzero value on a bench line says the capture SURVIVED
        # something, which the noise model should know about.
        "level_retries": counters.get("level_retries"),
        "oom_rescues": counters.get("oom_rescues"),
        # The compute ledger's headline pair (v9, obs/cost.py): achieved
        # utilization of the optimal-seconds floor and the roofline
        # verdict naming which resource that floor sits on. None where
        # the platform/wheel could not be priced — a None here on a TPU
        # capture is itself a signal (cost_unavailable event).
        "util_pct": (report.get("compute") or {}).get("util_pct"),
        "roofline": (report.get("compute") or {}).get("roofline"),
        "wall_s": round(wall, 3),
    }


class ReportMixin:
    """Adds ``dump_report(path)`` to estimators carrying ``fit_report_``."""

    def dump_report(self, path) -> str | None:
        """Write the fitted ``fit_report_`` as JSON to ``path``.

        Round-trip contract: ``json.load(open(path)) == self.fit_report_``
        (pinned in ``tests/test_profiling.py``). Returns ``path``.

        Sink contract (same as checkpoints, the obs level-stream spill,
        and ``trace_to``): the parent directory is created up front, and
        an unwritable path DEGRADES — a warning plus a typed
        ``trace_failed`` event appended to ``fit_report_['events']``,
        returning None — instead of aborting the caller's post-fit flow
        over a telemetry sink.
        """
        import os
        import warnings

        report = getattr(self, "fit_report_", None)
        if report is None:
            raise ValueError(
                "no fit_report_ on this estimator — call fit() first"
            )
        try:
            parent = os.path.dirname(os.path.abspath(str(path)))
            os.makedirs(parent, exist_ok=True)
            with open(path, "w") as f:
                json.dump(report, f, indent=2, sort_keys=True)
        except OSError as e:
            msg = (
                f"dump_report sink unwritable ({e}); report kept in "
                "memory only (fit_report_)"
            )
            warnings.warn(msg, stacklevel=2)
            report.setdefault("events", []).append(
                {"kind": "trace_failed", "message": msg, "path": str(path)}
            )
            return None
        return str(path)
