"""Lock-safe serving metrics: counters, gauges, log-bucketed histograms.

The request-path telemetry registry (ISSUE 9 tentpole, metrics half):
``mpitree_tpu.serving`` threads one :class:`MetricsRegistry` per
:class:`~mpitree_tpu.serving.model.CompiledModel` — request/row counters,
per-bucket latency histograms, stream-stage queue depth — and
``ModelRegistry.metrics_text()`` aggregates every published slot into one
Prometheus text exposition for a scrape endpoint (the asyncio exporter in
``examples/serving_run.py``).

Design constraints, in order:

- **No sample storage.** A serving process observes millions of
  latencies; :class:`Histogram` keeps O(log range) integer bucket counts
  (geometric buckets, ratio ``2**0.25`` ≈ 1.19), so p50/p95/p99 come out
  with bounded ~9% relative error (geometric-midpoint estimate; the
  oracle test pins it against ``numpy.percentile``) at constant memory.
  The one opt-in exception: ``MPITREE_TPU_METRICS_EXEMPLARS=K`` keeps the
  K most recent raw values per bucket (a bounded ring, still O(buckets)
  memory), surfaced as ``# exemplars`` comment lines in the exposition —
  concrete latencies to chase when a tail bucket grows. Off (0) by
  default: no reservoir is allocated and ``observe`` pays one ``is None``
  check.
- **Lock-safe under the registry's concurrent-dispatch contract.** One
  registry lock covers metric creation AND every update — serving
  dispatches run from many threads (``ModelRegistry`` publishes into a
  live asyncio/executor mix) and a dropped increment would silently
  under-report traffic. Updates are O(1) dict ops; the lock is
  uncontended microseconds against millisecond dispatches.
- **Prometheus text exposition** (:func:`MetricsRegistry.metrics_text`):
  counters render as ``name{labels} value``, histograms as cumulative
  ``name_bucket{le="..."}`` series plus ``_sum``/``_count`` — scrapeable
  by anything that speaks the exposition format, with zero dependencies.

Stdlib-only on purpose (no jax, no numpy): metrics observation sits ON
the request path, and the zero-new-compile-keys / zero-device_put pins
in ``tests/test_obs_trace.py`` hold precisely because nothing here can
touch the device.
"""

from __future__ import annotations

import math
import threading

from mpitree_tpu.config import knobs

# Geometric bucket ratio: 2**(1/4) per bucket = 4 buckets per octave.
# Quantile estimates use the geometric midpoint of the winning bucket, so
# the worst-case relative error is sqrt(ratio) - 1 ≈ 9% — tight enough to
# tell a 1 ms p99 from a 10 ms one, at ~150 buckets across ns..hours.
_BUCKET_RATIO = 2.0 ** 0.25
_LOG_RATIO = math.log(_BUCKET_RATIO)


class Counter:
    """Monotonic counter. ``inc`` only; see ``set_total`` for mirrors."""

    def __init__(self, lock):
        self._lock = lock
        self._value = 0.0

    def inc(self, v=1) -> None:
        if v < 0:
            raise ValueError(f"counters only go up; got inc({v!r})")
        with self._lock:
            self._value += v

    def set_total(self, v) -> None:
        """Sync from an upstream monotonic source (the obs record's
        retry/fallback counters, owned by the resilience ladder) — takes
        the max so the mirror can never run a counter backwards."""
        with self._lock:
            self._value = max(self._value, float(v))

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue depth, inflight batches)."""

    def __init__(self, lock):
        self._lock = lock
        self._value = 0.0

    def set(self, v) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, v=1) -> None:
        with self._lock:
            self._value += v

    def dec(self, v=1) -> None:
        with self._lock:
            self._value -= v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Log-bucketed distribution: quantiles without sample storage.

    Bucket ``i`` covers ``(ratio**(i-1), ratio**i]``; non-positive
    observations land in a dedicated zero bucket (quantile 0.0). The
    estimator returns the geometric midpoint of the bucket the target
    rank falls in, clamped to the observed [min, max] — so tiny
    populations degrade gracefully to exact extremes.
    """

    def __init__(self, lock):
        self._lock = lock
        self._buckets: dict = {}  # index -> count; None key = zero bucket
        self.count = 0
        self.sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        # Exemplar reservoir (knob read once at creation): K most recent
        # raw values per bucket, overwritten ring-style by the bucket's
        # own count. None = off, and observe() pays a single None check.
        k = knobs.value("MPITREE_TPU_METRICS_EXEMPLARS")
        self._exemplar_k = max(0, int(k or 0))
        self._exemplars: dict | None = {} if self._exemplar_k else None

    def observe(self, v) -> None:
        v = float(v)
        idx = None if v <= 0.0 else math.ceil(
            math.log(v) / _LOG_RATIO - 1e-9
        )
        with self._lock:
            n = self._buckets[idx] = self._buckets.get(idx, 0) + 1
            self.count += 1
            self.sum += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            if self._exemplars is not None:
                ring = self._exemplars.get(idx)
                if ring is None:
                    ring = self._exemplars[idx] = []
                if len(ring) < self._exemplar_k:
                    ring.append(v)
                else:
                    ring[(n - 1) % self._exemplar_k] = v

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (q in [0, 1]); None with no observations."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q!r}")
        with self._lock:
            if self.count == 0:
                return None
            if q == 0.0:
                return self._min
            if q == 1.0:
                return self._max
            target = q * self.count
            cum = 0.0
            # None (zero bucket) sorts first: it holds the smallest values
            for idx in sorted(
                self._buckets, key=lambda i: -math.inf if i is None else i
            ):
                cum += self._buckets[idx]
                if cum >= target:
                    if idx is None:
                        return max(0.0, self._min)
                    mid = _BUCKET_RATIO ** (idx - 0.5)
                    return min(max(mid, self._min), self._max)
            return self._max

    def snapshot(self) -> dict:
        """(upper_bound -> cumulative count) plus sum/count, for text
        exposition and ``serve_report_``."""
        with self._lock:
            cum = 0
            bounds = {}
            exemplars = {}
            for idx in sorted(
                self._buckets, key=lambda i: -math.inf if i is None else i
            ):
                cum += self._buckets[idx]
                bound = 0.0 if idx is None else _BUCKET_RATIO ** idx
                bounds[bound] = cum
                if self._exemplars is not None and self._exemplars.get(idx):
                    exemplars[bound] = list(self._exemplars[idx])
            snap = {"buckets": bounds, "count": self.count, "sum": self.sum}
            if self._exemplars is not None:
                # Key only present when the knob is on — snapshot shape
                # (and every golden pinning it) is unchanged by default.
                snap["exemplars"] = exemplars
            return snap


def _esc(v) -> str:
    """Prometheus label-value escaping: backslash, quote, newline —
    slot names are caller-controlled, and one raw ``\"`` would make the
    whole scrape endpoint unparseable."""
    return (
        str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _label_str(labels: dict, extra=None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(
        f'{k}="{_esc(v)}"' for k, v in sorted(merged.items())
    )
    return "{" + body + "}"


class MetricsRegistry:
    """Named metric families with label sets; one lock for everything."""

    _TYPES = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}

    def __init__(self):
        self._lock = threading.RLock()
        # name -> (cls, {label_tuple: metric})
        self._families: dict = {}

    def _get(self, cls, name: str, labels: dict):
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = (cls, {})
            if fam[0] is not cls:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{self._TYPES[fam[0]]}, not {self._TYPES[cls]}"
                )
            metric = fam[1].get(key)
            if metric is None:
                metric = fam[1][key] = cls(self._lock)
            return metric

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def render_families(self, extra_labels: dict | None = None) -> dict:
        """{family name: (prometheus type, [sample lines])}, sorted by
        name. The composable half of the exposition: merging several
        registries into ONE scrape (``ModelRegistry.metrics_text``) must
        group samples under a single ``# TYPE`` line per family — the
        Prometheus text parser rejects duplicate TYPE lines, so naive
        per-registry concatenation would fail the whole scrape."""
        with self._lock:
            families = {
                name: (cls, dict(children))
                for name, (cls, children) in self._families.items()
            }
        out: dict = {}
        for name in sorted(families):
            cls, children = families[name]
            lines: list = []
            for key in sorted(children):
                metric = children[key]
                labels = dict(key)
                if cls is Histogram:
                    snap = metric.snapshot()
                    exemplars = snap.get("exemplars") or {}
                    c = 0
                    for bound, c in snap["buckets"].items():
                        le = _label_str(
                            labels, {**(extra_labels or {}),
                                     "le": f"{bound:.9g}"}
                        )
                        lines.append(f"{name}_bucket{le} {c}")
                        if bound in exemplars:
                            # Comment lines (not TYPE/HELP) are ignored
                            # by exposition parsers — the scrape stays
                            # valid with exemplars on.
                            vals = ",".join(
                                f"{v:.9g}" for v in exemplars[bound]
                            )
                            lines.append(
                                f"# exemplars {name}_bucket{le} [{vals}]"
                            )
                    inf = _label_str(
                        labels, {**(extra_labels or {}), "le": "+Inf"}
                    )
                    lines.append(f"{name}_bucket{inf} {snap['count']}")
                    ls = _label_str(labels, extra_labels)
                    lines.append(f"{name}_sum{ls} {snap['sum']:.9g}")
                    lines.append(f"{name}_count{ls} {snap['count']}")
                else:
                    ls = _label_str(labels, extra_labels)
                    v = metric.value
                    val = f"{int(v)}" if float(v).is_integer() else f"{v:.9g}"
                    lines.append(f"{name}{ls} {val}")
            out[name] = (self._TYPES[cls], lines)
        return out

    def metrics_text(self, extra_labels: dict | None = None) -> str:
        """Prometheus text exposition of every family.

        ``extra_labels`` merge into each sample's label set — how
        ``ModelRegistry.metrics_text`` stamps per-slot ``model=...``
        labels onto each published model's private registry.
        """
        return render_text([self.render_families(extra_labels)])

    def snapshot(self) -> dict:
        """Plain-dict view:
        {name: {label_str: value-or-histogram-snapshot}}."""
        with self._lock:
            families = {
                name: (cls, dict(children))
                for name, (cls, children) in self._families.items()
            }
        out: dict = {}
        for name, (cls, children) in families.items():
            fam: dict = {}
            for key, metric in children.items():
                label = _label_str(dict(key)) or ""
                fam[label] = (
                    metric.snapshot() if cls is Histogram else metric.value
                )
            out[name] = fam
        return out


def render_text(family_maps: list) -> str:
    """Merge ``render_families`` maps into one exposition: one ``# TYPE``
    line per family name, all contributors' samples grouped under it.
    Conflicting types for the same name raise — two registries must not
    silently publish a counter and a gauge under one family."""
    merged: dict = {}
    for fams in family_maps:
        for name, (tname, lines) in fams.items():
            prev = merged.get(name)
            if prev is None:
                merged[name] = (tname, list(lines))
            else:
                if prev[0] != tname:
                    raise TypeError(
                        f"metric {name!r} exposed as both {prev[0]} "
                        f"and {tname} across merged registries"
                    )
                prev[1].extend(lines)
    out: list = []
    for name in sorted(merged):
        tname, lines = merged[name]
        out.append(f"# TYPE {name} {tname}")
        out.extend(lines)
    return "\n".join(out) + ("\n" if out else "")


# The process-default registry (module-level convenience for exporters
# that want one scrape surface); serving models keep their own private
# registries so per-model latency never mixes across slots.
DEFAULT = MetricsRegistry()


def metrics_text() -> str:
    """Text exposition of the process-default registry."""
    return DEFAULT.metrics_text()
