"""Chrome-trace-event (Perfetto-loadable) span timelines for fits + serving.

The timeline layer over the PR-3 observer (ISSUE 9 tentpole): every live
``BuildObserver`` span, typed event (resilience retry/failover rungs,
checkpoint notes), compile attribution, and serving dispatch becomes a
Chrome trace event collected by a :class:`TraceSink`; the fused engines —
whose whole build runs inside one ``lax.while_loop``/``lax.scan`` and
therefore has no per-level host clock — get *synthesized post-hoc* spans
replayed from ``obs/accounting``'s exact realized-work rows
(:func:`synthesize_record_tracks` lays the record's level/round rows out
inside the live engine span's window, weighted by their psum payloads).
ICI payloads render as Perfetto counter tracks (logical psum bytes plus
the ring-allreduce wire estimate).

Format: the JSON object form of the Trace Event Format
(``{"traceEvents": [...], "displayTimeUnit": "ms"}``) — loadable in
https://ui.perfetto.dev or ``chrome://tracing``. Tracks are (pid, tid)
pairs named through ``"M"`` (``thread_name``) metadata events; timestamps
are microseconds from sink creation, monotonic per track (the golden
schema test ``tests/test_obs_trace.py`` pins all of this).

This module is deliberately **stdlib-only** (no jax, no numpy, no package
imports): ``tools/tpu_watcher.py`` loads it by file path on the
babysitting host to merge per-section trace files without paying a jax
import inside a capture window.

Gating: nothing here runs unless a sink is configured —
``fit(trace_to=...)`` / ``CompiledModel.trace_to(...)`` for one sink
shared across fits, or ``MPITREE_TPU_TRACE_DIR=<dir>`` ambiently (one
file per observer). The disabled path stays inside the pinned <5%
overhead budget: with no sink the observer's per-span work is one
``is None`` check.
"""

from __future__ import annotations

import json
import os
import threading
import time

# Ambient gate: every BuildObserver created while this is set traces to a
# uniquely named file in the directory (the estimator-internal-observer
# twin of fit(trace_to=...), same contract as MPITREE_TPU_OBS_STREAM_DIR).
TRACE_DIR_ENV = "MPITREE_TPU_TRACE_DIR"

# Phases a valid sink emits (the golden trace schema test whitelists
# these): X = complete span, i = instant, C = counter, M = metadata.
_VALID_PH = ("X", "i", "C", "M")

# The engine-loop phase names: synthesized level/round replay spans are
# laid inside the union of THESE spans' windows, so a replayed "level 3"
# nests under fused_build/split on the timeline instead of overlapping
# the bin/shard preamble.
BUILD_PHASES = frozenset((
    "split", "counts", "update", "fused_build", "leafwise_build",
    "forest_build", "fused_rounds", "host_build", "expand",
))


def _plain(obj):
    """JSON-coerce event args (numpy scalars arrive from record rows)."""
    if isinstance(obj, dict):
        return {str(k): _plain(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_plain(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if hasattr(obj, "item"):  # numpy scalar
        return _plain(obj.item())
    return str(obj)


class TraceSink:
    """Thread-safe Chrome-trace-event collector; one file per sink.

    Multiple observers may share one sink (the ``examples/obs_trace_run``
    fit+serve timeline): each registers its own named tracks via
    :meth:`tid`, and each replaces its *synthesized* replay events
    wholesale through :meth:`set_synth` (keyed by owner), so a repeated
    ``report()`` re-synthesizes instead of duplicating.
    """

    def __init__(self, path=None):
        self.path = None if path is None else str(path)
        self.pid = os.getpid()
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._events: list = []
        self._synth: dict = {}
        self._tids: dict = {}
        self._meta: list = []
        self._meta.append({
            "ph": "M", "pid": self.pid, "tid": 0, "ts": 0,
            "name": "process_name", "args": {"name": "mpitree_tpu"},
        })

    # -- timebase ----------------------------------------------------------
    def ts(self, t: float) -> float:
        """perf_counter seconds -> trace microseconds (sink-relative)."""
        return round((t - self._t0) * 1e6, 3)

    def tid(self, track: str) -> int:
        """The tid for a named track (registers thread_name metadata once)."""
        with self._lock:
            tid = self._tids.get(track)
            if tid is None:
                tid = self._tids[track] = len(self._tids) + 1
                self._meta.append({
                    "ph": "M", "pid": self.pid, "tid": tid, "ts": 0,
                    "name": "thread_name", "args": {"name": track},
                })
            return tid

    # -- event channels ----------------------------------------------------
    def complete(self, track: str, name: str, t_start: float, dur_s: float,
                 *, cat: str = "span", args=None) -> None:
        ev = {
            "ph": "X", "pid": self.pid, "tid": self.tid(track),
            "name": str(name), "cat": cat, "ts": self.ts(t_start),
            "dur": round(max(float(dur_s), 0.0) * 1e6, 3),
        }
        if args:
            ev["args"] = _plain(args)
        with self._lock:
            self._events.append(ev)

    def instant(self, track: str, name: str, t: float | None = None,
                *, cat: str = "event", args=None) -> None:
        t = time.perf_counter() if t is None else t
        ev = {
            "ph": "i", "pid": self.pid, "tid": self.tid(track),
            "name": str(name), "cat": cat, "ts": self.ts(t), "s": "t",
        }
        if args:
            ev["args"] = _plain(args)
        with self._lock:
            self._events.append(ev)

    def counter(self, track: str, name: str, t: float, values: dict) -> None:
        ev = {
            "ph": "C", "pid": self.pid, "tid": self.tid(track),
            "name": str(name), "cat": "counter", "ts": self.ts(t),
            "args": {str(k): float(v) for k, v in values.items()},
        }
        with self._lock:
            self._events.append(ev)

    def set_synth(self, owner: str, events: list) -> None:
        """Replace ``owner``'s synthesized replay events wholesale."""
        with self._lock:
            self._synth[owner] = list(events)

    # -- output ------------------------------------------------------------
    def events(self) -> list:
        """Metadata first, then all events sorted by (tid, ts) — ts stays
        monotonic per track whatever order threads appended in."""
        with self._lock:
            body = list(self._events)
            for lst in self._synth.values():
                body.extend(lst)
            meta = list(self._meta)
        body.sort(key=lambda e: (e.get("tid", 0), e.get("ts", 0.0)))
        return meta + body

    def to_dict(self) -> dict:
        return {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "mpitree_tpu.obs.trace"},
        }

    def write(self, path=None) -> str:
        """Write the trace JSON; makedirs the parent up front.

        Raises ``OSError`` on an unwritable sink — the *observer* owns the
        degrade-to-``trace_failed``-event contract (it has the record to
        put the event in); library callers holding a bare sink get the
        honest error.
        """
        path = self.path if path is None else str(path)
        if path is None:
            raise ValueError("TraceSink has no path; pass write(path=...)")
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
        return path


# ---------------------------------------------------------------------------
# post-hoc synthesis: replay record rows into timeline spans
# ---------------------------------------------------------------------------

def _layout(rows, t0: float, t1: float, weight_key: str):
    """Lay ``rows`` sequentially inside [t0, t1].

    Rows carrying real ``seconds`` (live level-wise loops, boosting
    rounds) keep their true durations; rows without (the fused engines'
    post-hoc replay — one compiled program has no per-level host clock)
    share the remaining window proportionally to ``weight_key`` (their
    psum payload: the replay's best static proxy for realized work).
    Returns [(start, dur, row)] in row order.
    """
    known = sum(float(r["seconds"]) for r in rows if r.get("seconds"))
    blind = [r for r in rows if not r.get("seconds")]
    wsum = sum(float(r.get(weight_key) or 0) + 1.0 for r in blind)
    remaining = max((t1 - t0) - known, 0.0)
    out, cur = [], t0
    for r in rows:
        if r.get("seconds"):
            dur = float(r["seconds"])
        elif wsum > 0:
            dur = remaining * (float(r.get(weight_key) or 0) + 1.0) / wsum
        else:
            dur = 0.0
        dur = max(dur, 1e-6)
        out.append((cur, dur, r))
        cur += dur
    return out


def synthesize_record_tracks(sink: TraceSink, owner: str, track: str,
                             report: dict, window=None) -> int:
    """Replay a finalized record dict into ``<track>:levels`` /
    ``<track>:rounds`` span tracks plus an ``ici`` counter track.

    ``window``: the observer's live span coverage ``[t0, t1]`` in
    perf_counter seconds — replay spans are laid inside it so they nest
    under the engine's real ``fused_build``/``split`` spans. ``owner``
    keys wholesale replacement (repeated ``report()`` calls re-synthesize
    instead of duplicating). Returns the number of events synthesized.
    """
    if window is None:
        t0 = sink._t0
        t1 = t0 + max(
            sum(float(r.get("seconds") or 0)
                for r in report.get("levels", [])),
            1e-3,
        )
    else:
        t0, t1 = window
    events: list = []
    n_shards = int((report.get("mesh") or {}).get("n_devices") or 1)
    cum_logical = 0.0

    levels = report.get("levels") or []
    if levels:
        tid = sink.tid(f"{track}:levels")
        ici_tid = sink.tid("ici")
        for start, dur, r in _layout(levels, t0, t1, "psum_bytes"):
            events.append({
                "ph": "X", "pid": sink.pid, "tid": tid,
                "name": f"level {r.get('level')}", "cat": "replay",
                "ts": sink.ts(start), "dur": round(dur * 1e6, 3),
                "args": _plain(r),
            })
            cum_logical += float(r.get("psum_bytes") or 0)
            events.append({
                "ph": "C", "pid": sink.pid, "tid": ici_tid,
                "name": "ici_psum_bytes", "cat": "counter",
                "ts": sink.ts(start + dur),
                "args": {
                    "logical": cum_logical,
                    "wire": cum_logical * (n_shards - 1),
                },
            })

    rounds = report.get("rounds") or []
    if rounds:
        tid = sink.tid(f"{track}:rounds")
        for start, dur, r in _layout(rounds, t0, t1, "trees"):
            events.append({
                "ph": "X", "pid": sink.pid, "tid": tid,
                "name": f"round {r.get('round')}", "cat": "replay",
                "ts": sink.ts(start), "dur": round(dur * 1e6, 3),
                "args": _plain(r),
            })

    # Utilization counter track (v9, obs/cost.py) next to the ici/mem
    # tracks: the whole-fit achieved utilization at the window edges plus
    # one sample per priced level, laid on the same replay layout as the
    # level spans. Only priced values are emitted (C-event args must be
    # numeric — the golden validate_trace rule); an unpriced record adds
    # no track at all.
    compute = report.get("compute") or {}
    fit_util = compute.get("util_pct")
    level_utils = {
        r.get("level"): r.get("util_pct")
        for r in compute.get("levels") or []
        if isinstance(r.get("util_pct"), (int, float))
    }
    if isinstance(fit_util, (int, float)) or level_utils:
        util_tid = sink.tid("util")
        if isinstance(fit_util, (int, float)):
            events.append({
                "ph": "C", "pid": sink.pid, "tid": util_tid,
                "name": "util_pct", "cat": "counter",
                "ts": sink.ts(t0), "args": {"pct": float(fit_util)},
            })
        t_last = t0
        if level_utils and levels:
            for start, dur, r in _layout(levels, t0, t1, "psum_bytes"):
                u = level_utils.get(r.get("level"))
                if u is None:
                    continue
                events.append({
                    "ph": "C", "pid": sink.pid, "tid": util_tid,
                    "name": "util_pct", "cat": "counter",
                    "ts": sink.ts(start + dur), "args": {"pct": float(u)},
                })
                t_last = max(t_last, start + dur)
        if isinstance(fit_util, (int, float)):
            # The closing sample sits at the window edge — or past it
            # when live level seconds overran the span window (the
            # monotonic-per-track golden rule wins over the edge).
            events.append({
                "ph": "C", "pid": sink.pid, "tid": util_tid,
                "name": "util_pct", "cat": "counter",
                "ts": sink.ts(max(t1, t_last)),
                "args": {"pct": float(fit_util)},
            })

    sink.set_synth(owner, events)
    return len(events)


# ---------------------------------------------------------------------------
# validation + merge (stdlib-only: the watcher and trace-smoke ride these)
# ---------------------------------------------------------------------------

def validate_trace(obj) -> list:
    """Schema problems with a trace dict; ``[]`` means Perfetto-loadable.

    Checks the golden contract ``tests/test_obs_trace.py`` pins: the
    trace-event envelope, required per-event fields, known phases,
    non-negative microsecond timestamps monotonic per (pid, tid) track,
    and a ``thread_name`` metadata event for every track that carries
    events (the pid/tid -> track mapping Perfetto renders by).
    """
    problems = []
    if not isinstance(obj, dict) or not isinstance(
        obj.get("traceEvents"), list
    ):
        return ["top level must be a dict with a traceEvents list"]
    named = set()
    last_ts: dict = {}
    for i, ev in enumerate(obj["traceEvents"]):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        for field in ("ph", "pid", "tid", "name"):
            if field not in ev:
                problems.append(f"event {i}: missing {field!r}")
        ph = ev.get("ph")
        if ph not in _VALID_PH:
            problems.append(f"event {i}: unknown phase {ph!r}")
            continue
        if ph == "M":
            if ev.get("name") == "thread_name":
                named.add((ev.get("pid"), ev.get("tid")))
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        key = (ev.get("pid"), ev.get("tid"))
        if ts < last_ts.get(key, 0.0):
            problems.append(
                f"event {i}: ts {ts} not monotonic on track {key}"
            )
        last_ts[key] = ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: X event bad dur {dur!r}")
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not all(
                isinstance(v, (int, float)) for v in args.values()
            ):
                problems.append(f"event {i}: C event needs numeric args")
    for key in last_ts:
        if key not in named:
            problems.append(f"track {key} has no thread_name metadata")
    return problems


def merge_trace_files(paths: list, out: str) -> str | None:
    """Merge per-observer trace files into ONE Perfetto-loadable file.

    Each source file becomes its own pid (its filename is the
    process_name), so a bench section's many fits render side by side.
    Unreadable/invalid sources are skipped (the watcher merges whatever a
    killed section managed to write). Returns ``out``, or None when no
    source contributed events.
    """
    merged: list = []
    pid = 0
    for p in sorted(paths):
        try:
            with open(p) as f:
                data = json.load(f)
            events = data["traceEvents"]
        except (OSError, ValueError, KeyError, TypeError):
            continue
        if not isinstance(events, list) or not events:
            continue
        pid += 1
        merged.append({
            "ph": "M", "pid": pid, "tid": 0, "ts": 0,
            "name": "process_name",
            "args": {"name": os.path.basename(p)},
        })
        for ev in events:
            if isinstance(ev, dict):
                ev = dict(ev)
                ev["pid"] = pid
                if ev.get("name") == "process_name":
                    continue
                merged.append(ev)
    if not pid:
        return None
    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    with open(out, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
    return out
