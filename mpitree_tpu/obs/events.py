# graftlint: event-registry
"""Typed registry of every structured event kind and decision key.

This module is the ONE place an event kind or decision key is declared
(graftlint GL12 enforces that statically: a literal ``warn_event(obs,
"<kind>", ...)`` / ``obs.event("<kind>", ...)`` / ``obs.decision("<key>",
...)`` whose name is not registered here is a finding). Each entry
carries its severity and the one doc line the README events table is
generated from (``python -m mpitree_tpu.obs --markdown``) — the same
docs-can't-drift contract as the env-knob registry
(``config/knobs.py`` / GL10), applied to the record's ``events`` and
``decisions`` streams: a new event is a registry entry, not a scattered
string plus a hand-edited table row, and a misspelled kind fails lint
instead of shipping as an un-greppable variant.

Severity is the emission contract, not a log level:

- ``warn`` — the site raises a visible Python warning (``warn_event``)
  AND records the typed event; something degraded that the user should
  see once, interactively.
- ``info`` — record-only (``obs.event``): a structured fact for the
  ``fit_report_`` / flight-store consumers, silent on the console.

Deliberately dependency-free (stdlib only), like the knob registry: the
linter and doc tooling read it without importing JAX.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Event:
    """One registered event kind: its severity and doc line."""

    kind: str
    severity: str                 # "warn" | "info"
    doc: str


@dataclasses.dataclass(frozen=True)
class Decision:
    """One registered typed-decision key and what the value records."""

    key: str
    doc: str


EVENTS: tuple = (
    # -- training-path degradations (visible warnings) --------------------
    Event("checkpoint_disabled", "warn",
          "requested boosting/forest checkpointing could not engage"
          " (spec/engine combination) — the fit continues without resume"
          " protection"),
    Event("exact_ties_gap", "warn",
          "the f64 tie-exact cost sweep is memory-gated off for wide"
          " frontier chunks; ties there rank in f32 and may resolve"
          " differently from the host tier"),
    Event("f32_ceiling", "warn",
          "a weight/count channel can exceed 2**24 in float32 —"
          " sibling-subtraction (or the requested accumulation mode) is"
          " disabled to keep sums exact"),
    Event("fused_no_determinism_check", "warn",
          "debug mode requested the on-device determinism check but the"
          " fused engine cannot run it — use engine='levelwise'"),
    Event("oob_empty", "warn",
          "no out-of-bag rows at all (tiny data or unlucky bootstrap) —"
          " `oob_score_` is unavailable"),
    Event("oob_partial", "warn",
          "some rows were in-bag for every tree; the OOB score covers"
          " only the rows with at least one vote"),
    # -- training-path facts (record-only) --------------------------------
    Event("checkpoint_resume", "info",
          "the fit resumed from a checkpoint instead of starting at"
          " round/tree zero"),
    Event("determinism_check_failed", "info",
          "the debug determinism probe saw split decisions diverge"
          " across mesh devices (the fit then raises)"),
    Event("nonfinite_grad", "info",
          "non-finite gradients/hessians at a boosting round — the fit"
          " refuses to continue (the event precedes the raise)"),
    Event("sub_carry_over_budget", "info",
          "keeping a level's chunk histograms for sibling subtraction"
          " would exceed hist_budget_bytes; the next level accumulates"
          " directly"),
    Event("mesh2d_unsupported", "info",
          "the leaf-wise engine fell back to a 1-D data mesh — its pair"
          " program does not shard the feature axis"),
    Event("leafwise_pallas_fallback", "info",
          "the leaf-wise pair histogram dropped from the Pallas kernel"
          " to the XLA path (unsupported shape/platform)"),
    Event("serving_pallas_fallback", "info",
          "the serving tier dropped from the Pallas traversal kernel to"
          " the XLA path (unsupported shape/platform, or forced off)"),
    # -- resilience ladder ------------------------------------------------
    Event("device_retry", "info",
          "a transient device error was re-dispatched after backoff"
          " (the MPITREE_TPU_RETRIES budget)"),
    Event("level_retry", "info",
          "a mid-build blip resumed from the per-level/per-expansion"
          " carry snapshot instead of restarting the tree"),
    Event("device_failover", "info",
          "a device failure rode the resilience ladder onto a fallback"
          " device set or the CPU backend"),
    Event("oom_predicted", "info",
          "the memory preflight predicted an out-of-memory dispatch and"
          " triggered a pre-emptive degrade"),
    Event("oom_rescue", "info",
          "an actual OOM was caught and rescued by degrading the plan"
          " (smaller chunks / host path / engine exit)"),
    Event("oom_postmortem", "info",
          "an OOM's allocation postmortem was attached to the record"
          " naming the binding arrays"),
    # -- observability self-reporting -------------------------------------
    Event("cost_unavailable", "info",
          "the compute ledger could not price optimal-seconds floors"
          " (unknown platform peaks and no override knobs)"),
    Event("mem_estimate_drift", "info",
          "sampled live memory watermarks drifted from the ledger's"
          " estimate beyond MPITREE_TPU_MEM_DRIFT_TOL"),
    Event("level_stream_failed", "info",
          "spilling per-level rows to MPITREE_TPU_OBS_STREAM_DIR failed;"
          " rows stay in memory for this run"),
    Event("trace_failed", "info",
          "writing/finalizing a Chrome trace capture failed — the fit is"
          " unaffected, the trace file is not"),
    Event("trace_unavailable", "info",
          "the ambient MPITREE_TPU_TRACE_DIR capture could not start"
          " (profiler unavailable or already active)"),
)

DECISIONS: tuple = (
    Decision("engine",
             "which build engine ran (fused / levelwise / leafwise /"
             " host) and why the resolver picked it"),
    Decision("build_path",
             "host vs device build for a single-device tree (workload"
             " threshold, explicit backend, or mesh width)"),
    Decision("frontier",
             "frontier policy: best-first leaf-wise pool vs level-wise"
             " breadth sweep"),
    Decision("hist_subtraction",
             "sibling-subtraction histogram carry on/off and the gate"
             " that decided it"),
    Decision("leafwise_mesh",
             "mesh the leaf-wise engine actually ran on (it refuses the"
             " feature axis)"),
    Decision("refine",
             "exact-local-candidate refine depth (quantile-binning"
             " accuracy recovery) or None when off"),
    Decision("refine_tail",
             "refine tail execution: batched native kernel vs"
             " per-subtree host recursion"),
    Decision("ingest",
             "ingest path: streamed chunked sketch+bin vs materialized"
             " host matrix"),
    Decision("ingest_spill",
             "spill-to-disk rung engaged for a one-shot chunk iterator"
             " (store directory and size cap recorded)"),
    Decision("bootstrap",
             "forest bootstrap draw scheme: keyed counter-based"
             " per-chunk masks vs the host RNG multinomial"),
    Decision("ensemble_path",
             "forest build sharding: tree-parallel vs data-parallel (and"
             " the HBM budget verdict)"),
    Decision("rounds_per_dispatch",
             "boosting rounds fused per device dispatch (priced from the"
             " memory planner or forced by knob)"),
    Decision("early_stop",
             "boosting early-stop verdict: the round it triggered at and"
             " the patience evidence"),
    Decision("serving",
             "serving-table plan recorded at fit time (depth-packed flat"
             " node table shape)"),
    Decision("serving_compile",
             "serving tier compiled for a published model (XLA vs Pallas"
             " kernel, bucket widths)"),
    Decision("serving_kernel",
             "per-dispatch serving kernel pick (Pallas traversal vs XLA"
             " gather loop)"),
    Decision("serving_quantize",
             "quantized serving tables on/off and the calibration"
             " tolerance verdict"),
    Decision("registry_publish",
             "a model generation was published to the serving registry"
             " (warm-compile timing rides along)"),
)

EVENT_KINDS: dict = {e.kind: e for e in EVENTS}
DECISION_KEYS: dict = {d.key: d for d in DECISIONS}


def markdown_table() -> str:
    """The README events section, generated from the registry."""
    lines = [
        "| event | severity | meaning |",
        "|---|---|---|",
    ]
    for e in EVENTS:
        lines.append(f"| `{e.kind}` | {e.severity} | {e.doc} |")
    lines.append("")
    lines.append("| decision | records |")
    lines.append("|---|---|")
    for d in DECISIONS:
        lines.append(f"| `{d.key}` | {d.doc} |")
    return "\n".join(lines) + "\n"
