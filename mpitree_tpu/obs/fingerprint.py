"""obs.fingerprint — cheap u64 per-level build-state fingerprints.

The divergence-localization layer under ``obs.diff`` (ISSUE 13): the
repo's core correctness invariant is bit-identity — the same workload
must build the same tree across (8,)/(4,2)/(2,4) meshes, fused/levelwise
engines, and subtraction on/off — but until now that invariant lived
only inside individual tests, and when two runs disagreed nothing could
say *where*. A fingerprint row is three u64 hashes per tree level, one
per state **channel**, ordered by data flow:

- ``hist`` — the reduced-histogram checksum: each level node's total
  accumulated weight (``n_node_samples``), i.e. the 0th moment of the
  globally psum'd histogram. The first channel a corrupted payload or a
  routing bug moves.
- ``winner`` — the packed winning splits: per-node ``(feature,
  threshold)`` (leaves contribute ``(-1, NaN)``). Diverges when the
  gain sweep picks differently off identical histograms (tie seams,
  kernel-exactness opt-outs).
- ``alloc`` — the child-id allocation: per-node ``(left, right)``.
  Diverges when identical winners allocate differently (frontier
  bookkeeping bugs).

Two runs that diverge are bisected by ``obs.diff.localize_divergence``
to the first divergent (tree/round, level) and the first channel in the
order above — "round 3, level 2, hist" instead of "the digests differ".

Cost contract (the acceptance pin): fingerprints are **host-side
arithmetic over arrays the engines already hold** — zero device
collectives, zero transfers. The level-wise/host engines hash each
level's slice of the host tree buffer at their existing host boundary
(the per-level decision fetch); the fused single-program engines
(fused/leaf-wise/forest/fused-rounds), which have no per-level host
boundary, get the identical rows *replayed* from the finished tree
(:func:`tree_fingerprints`) — the same live/replay split as the wire
ledger (``obs/accounting``). Live and replayed rows hash the same bytes
from the same arrays, pinned equal in ``tests/test_obs_flight.py``.

Hashing is BLAKE2b (stdlib, C speed) truncated to 64 bits, rendered as
16 hex chars — compact enough for every level of a depth-20 build to
ride a ``fit_report_``, stable across platforms and processes (no
PYTHONHASHSEED dependence). Only refit-stable fields are hashed:
``value``/``count``/``impurity`` are overwritten post-build by the f64
refit passes (regression/gbdt), so including them would make live and
replayed fingerprints disagree on healthy fits.
"""

from __future__ import annotations

import hashlib

import numpy as np

# Bump on any change to which bytes a channel hashes — stored
# fingerprints are only comparable within one version.
# v2 (ISSUE 20): refine-tail subtrees commit under their own "refine"
# channel instead of reusing hist/winner/alloc, so a streamed-vs-
# in-memory divergence localizes INTO the refine tail by name.
FINGERPRINT_VERSION = 2

# Data-flow order: histogram stats feed the winner sweep, winners feed
# child allocation, and the refine tail re-grows below all three — the
# bisect reports the FIRST divergent channel in this order, which names
# the most upstream divergent state. Crown rows carry the first three
# channels; refine-tail rows carry only "refine" (absent channels
# compare equal in the bisect), so mixed row lists never false-positive.
CHANNELS = ("hist", "winner", "alloc", "refine")


def _h64(*chunks: bytes) -> str:
    """64-bit BLAKE2b over the concatenated chunks, as 16 hex chars."""
    h = hashlib.blake2b(digest_size=8)
    for c in chunks:
        h.update(c)
    return h.hexdigest()


def _canon(a, dtype) -> bytes:
    """Canonical little-endian bytes regardless of the input's dtype."""
    return np.ascontiguousarray(np.asarray(a), dtype=dtype).tobytes()


def level_fingerprint(level: int, n_samples, feature, threshold,
                      left, right) -> dict:
    """One fingerprint row from a level's node slices (id order).

    The arrays are the level's slices of the host tree buffer — what the
    level-wise loop already has at its host boundary, and exactly what
    :func:`tree_fingerprints` re-slices from a finished tree, so the two
    paths can never hash different bytes.
    """
    # -0.0 -> +0.0 before hashing: a column holding both zeros may yield
    # either representative depending on which path selected the edge
    # (the device kernel's sort, the ingest sketch's chunk merge — both
    # documented non-contracts), and the ``x <= t`` predicate cannot
    # tell them apart. Hashing raw bytes would flag predicate-identical
    # trees as divergent. NaN leaf pads are unaffected.
    thr = np.ascontiguousarray(np.asarray(threshold), "<f4")
    thr = thr + np.float32(0.0)
    return {
        "level": int(level),
        "nodes": int(len(np.asarray(feature))),
        "hist": _h64(_canon(n_samples, "<i8")),
        "winner": _h64(_canon(feature, "<i4"), _canon(thr, "<f4")),
        "alloc": _h64(_canon(left, "<i4"), _canon(right, "<i4")),
    }


def tree_fingerprints(tree) -> list:
    """Per-level fingerprint rows replayed from a finished tree.

    ``tree`` is any struct-of-arrays carrying ``depth`` /
    ``n_node_samples`` / ``feature`` / ``threshold`` / ``left`` /
    ``right`` (a ``TreeArrays``). Nodes group by depth in id order —
    the engines allocate level nodes contiguously (level-wise) or
    BFS-renumber (leaf-wise/fused), so id order within a depth is the
    same canonical order the live path hashes.
    """
    depth = np.asarray(tree.depth, np.int64)
    ns = np.asarray(tree.n_node_samples)
    feat = np.asarray(tree.feature)
    thr = np.asarray(tree.threshold)
    left = np.asarray(tree.left)
    right = np.asarray(tree.right)
    rows = []
    for d in range(int(depth.max(initial=0)) + 1):
        ids = np.flatnonzero(depth == d)
        if not len(ids):
            continue
        rows.append(level_fingerprint(
            d, ns[ids], feat[ids], thr[ids], left[ids], right[ids]
        ))
    return rows


def subtree_fingerprints(depth, n_samples, feature, threshold, left,
                         right, ids=None) -> list:
    """Per-level rows for ONE subtree of a larger node buffer (the
    hybrid-refine tail, ISSUE 15 satellite).

    ``ids`` selects the subtree's nodes (None = the whole buffer is the
    subtree, e.g. a standalone per-subtree host build). Node ids are
    REMAPPED to the subtree's local id-rank order before hashing, so the
    two tail engines — the batched multi-root native frontier (subtree
    nodes interleaved in one buffer, buffer-global child ids) and the
    per-subtree host builds (ids local from 0) — commit byte-identical
    rows for identical subtrees; depths are likewise re-based at the
    subtree root. Leaves keep ``-1`` children.

    Rows carry the ``refine`` channel (v2): the per-level hist/winner/
    alloc states fold into ONE hash, so the bisect reports a refine-tail
    divergence as channel ``"refine"`` — "the tails re-grew differently"
    — instead of mislabeling it a histogram bug at some crown level. A
    streamed fit's tail consumes a gathered replay of the chunk stream;
    this channel is what proves the replay fed the same bytes.
    """
    depth = np.asarray(depth, np.int64)
    feature = np.asarray(feature)
    threshold = np.asarray(threshold)
    left = np.asarray(left, np.int64)
    right = np.asarray(right, np.int64)
    ns = np.asarray(n_samples)
    if ids is None:
        ids = np.arange(len(depth), dtype=np.int64)
    else:
        ids = np.asarray(ids, np.int64)
    if not len(ids):
        return []
    # id -> local rank (ids are ascending within a buffer's subtree; the
    # searchsorted remap keeps -1 leaves at -1).
    def remap(child):
        c = child[ids]
        local = np.searchsorted(ids, np.where(c < 0, ids[0], c))
        return np.where(c < 0, -1, local).astype(np.int64)

    l_loc, r_loc = remap(left), remap(right)
    d_loc = depth[ids] - int(depth[ids].min())
    feat_loc = feature[ids]
    thr_loc = threshold[ids]
    ns_loc = ns[ids]
    rows = []
    for d in range(int(d_loc.max(initial=0)) + 1):
        at = np.flatnonzero(d_loc == d)
        if not len(at):
            continue
        r = level_fingerprint(
            d, ns_loc[at], feat_loc[at], thr_loc[at], l_loc[at], r_loc[at]
        )
        rows.append({
            "level": r["level"], "nodes": r["nodes"],
            "refine": _h64(
                f"{r['hist']}:{r['winner']}:{r['alloc']}".encode()
            ),
        })
    return rows


def fold(rows: list, into=None):
    """Fold fingerprint rows into a running whole-fit BLAKE2b state.

    ``into``: an existing hash object (or None to start one). The
    observer folds every committed tree's rows through here and renders
    the final state as the record's whole-fit ``fingerprint`` — one u64
    that changes iff any level of any tree changed.
    """
    h = into if into is not None else hashlib.blake2b(digest_size=8)
    for r in rows:
        if "refine" in r:  # refine-tail row (v2): one channel
            h.update(f"{r['level']}:{r['refine']};".encode())
        else:
            h.update(
                f"{r['level']}:{r['hist']}:{r['winner']}:{r['alloc']};"
                .encode()
            )
    return h


def ensemble_fingerprint(trees) -> str:
    """Whole-model u64 over every member's per-level rows — the serving
    side's "am I serving the same model?" stamp (``serve_report_``)."""
    h = None
    for t in trees:
        h = fold(tree_fingerprints(t), h)
    return (h or fold([])).hexdigest()
