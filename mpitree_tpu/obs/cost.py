"""obs.cost — the XLA cost-model compute ledger (observability v5).

Third sibling of the wire ledger (``record.wire``, ISSUE 9) and the
memory ledger (``record.memory``, ISSUE 12): the repo could price ICI
bytes and HBM bytes but not COMPUTE, so "runs as fast as the hardware
allows" was an aspiration nothing measured. This module closes that gap
with the compiler's own numbers:

- :func:`capture` reads a fresh lowering's ``cost_analysis()`` (flops,
  bytes accessed) — the XLA client-side HLO cost model, no backend
  compile, ~10 ms host work. It runs ONCE per fresh compile cache key
  (the PR-9 ``CompileRegistry`` seam: ``BuildObserver.price_compile``
  fires only when ``compile_note`` returned fresh), so the warm dispatch
  path — including the serving request path — never re-traces and the
  disabled-observability budget is untouched.
- :func:`platform_peaks` maps the live device to a published peak table
  (f32 FLOP/s, HBM GB/s, aggregate ICI GB/s per device).
  ``MPITREE_TPU_PEAK_FLOPS`` / ``MPITREE_TPU_PEAK_HBM_GBPS`` override
  for parts the table does not know. Unknown platforms (XLA-CPU smoke
  runs, new TPU generations) price to honest ``None`` — a typed
  ``cost_unavailable`` event, never a guess and never a crash.
- :func:`compute_section` joins the captured per-dispatch costs against
  the measured span walls the record already carries (live phase
  seconds for the host-stepped engines, the PR-9 replay rows for the
  fused programs) into ``record.compute``: per-entry optimal-seconds
  floors, achieved utilization, per-level floors, and a roofline
  verdict (compute- / HBM- / ICI-bound, the ICI leg priced from the
  existing wire ledger).

Honesty contract: every derived number is a FLOOR joined against a
measured wall — ``util_pct`` can only be computed where both sides
exist (a cost capture, a peak table entry, a dispatch count, a measured
span). Anything unpriceable is ``None``, with the reason recorded.

The capture path imports jax lazily and defensively: a legacy wheel
whose ``Lowered`` has no ``cost_analysis`` degrades to the same typed
``cost_unavailable`` event as an unknown platform.
"""

from __future__ import annotations

from mpitree_tpu.config import knobs

PEAK_FLOPS_ENV = "MPITREE_TPU_PEAK_FLOPS"
PEAK_HBM_ENV = "MPITREE_TPU_PEAK_HBM_GBPS"

# Published per-device peaks, keyed by a lowercase substring of
# ``device.device_kind``. FLOP/s is the f32 vector/matrix peak (the
# histogram and traversal programs run f32 — quoting the bf16 MXU number
# would flatter every utilization figure by ~2x); HBM is the memory
# bandwidth the vendor quotes; ICI is the per-device aggregate across
# links. First match wins; order specific kinds before generic ones.
PEAK_TABLE: tuple = (
    ("tpu v5 lite", dict(flops=98.5e12, hbm_gbps=819.0, ici_gbps=179.2)),
    ("tpu v5e", dict(flops=98.5e12, hbm_gbps=819.0, ici_gbps=179.2)),
    ("tpu v5p", dict(flops=229.5e12, hbm_gbps=2765.0, ici_gbps=537.6)),
    ("tpu v4", dict(flops=137.5e12, hbm_gbps=1228.0, ici_gbps=268.8)),
    ("tpu v6", dict(flops=229.0e12, hbm_gbps=1640.0, ici_gbps=358.4)),
)

# Where each jit entry point's measured wall lives in the record: the
# phase name its dispatches run under (PhaseTimer seconds), and the
# channel its dispatch COUNT can be recovered from without new plumbing
# — "collective:<site>" reads ``record.collectives[site]['calls']``
# (exact chunk counts for the host-stepped split/counts loops),
# "phase" reads the phase's own call count (the fused single-program
# engines run one dispatch per span), "counter:<name>" reads an
# always-on counter. ``None`` means the count is not recoverable and
# utilization stays honestly un-computed for that entry.
ENTRY_JOIN: dict = {
    "split_fn": ("split", "collective:split_hist_psum"),
    "counts_fn": ("counts", "collective:counts_psum"),
    "update_fn": ("update", None),
    "fused_fn": ("fused_build", "phase"),
    "forest_fn": ("forest_build", "phase"),
    "leafwise_fn": ("leafwise_build", "phase"),
    "expand_fn": (None, "counter:expansions"),
    "fused_rounds_fn": ("fused_rounds", "counter:fused_round_dispatches"),
    "serving_traverse": (None, None),
}

# Host-tier work the XLA cost model never sees (the numpy/C++ builders
# and the hybrid refine tail): each entry joins an always-on dispatch
# counter and, where one exists, a measured phase wall. Floors price to
# an honest ``None`` — the point (ISSUE 20 satellite) is that the
# ledger's coverage GAP shows up as counted-but-unpriced entries instead
# of silently missing from ``record.compute``.
HOST_ENTRIES: dict = {
    "host_build": ("host_build", "counter:host_builds"),
    "refine_tail": ("refine", "counter:refine_candidates"),
}


def capture(lower) -> dict | None:
    """Cost-analyze one fresh lowering; None when the wheel cannot.

    ``lower``: a zero-arg callable returning the jitted entry's
    ``Lowered`` stage for the arguments about to dispatch — sites pass
    ``lambda: fn.lower(*args)``. Called right after a fresh
    ``compile_note``, the trace is either not yet cached (this call
    primes the jaxpr cache the real dispatch then reuses) or already
    cached (sub-millisecond re-lower); either way no work is duplicated
    on the device and nothing runs on the warm path.

    Returns ``{"flops", "bytes"}`` (floats, whole-program, pre-division)
    or ``None`` on any failure — legacy wheels without
    ``cost_analysis``, backends whose analysis raises, non-jit entries.
    """
    try:
        lowered = lower()
        analysis = lowered.cost_analysis()
        # Newer wheels return one dict; some return a per-device list.
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if not analysis:
            return None
        flops = analysis.get("flops")
        nbytes = analysis.get("bytes accessed")
        if flops is None and nbytes is None:
            return None
        return {
            "flops": float(flops or 0.0),
            "bytes": float(nbytes or 0.0),
        }
    except Exception:  # noqa: BLE001 — telemetry never aborts a dispatch
        return None


def device_kind() -> str | None:
    """The live backend's device kind string, or None off-jax."""
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001 — uninitialized/absent backend
        return None


def platform_peaks(kind: str | None = None) -> dict:
    """Peak table row for the live (or named) device kind.

    Returns ``{"flops", "hbm_gbps", "ici_gbps", "source"}`` where the
    numeric fields are ``None`` for unknown parts. The env knobs
    override field-wise — a knob set on an unknown platform yields a
    partially-priced row (flops floors without HBM floors, or vice
    versa), each leg honest about what it knows.
    """
    if kind is None:
        kind = device_kind()
    row = {"flops": None, "hbm_gbps": None, "ici_gbps": None}
    source = "unknown"
    if kind:
        low = kind.lower()
        for sub, peaks in PEAK_TABLE:
            if sub in low:
                row.update(peaks)
                source = "table"
                break
    env_flops = knobs.value(PEAK_FLOPS_ENV)
    env_hbm = knobs.value(PEAK_HBM_ENV)
    if env_flops is not None:
        row["flops"] = float(env_flops)
        source = "env"
    if env_hbm is not None:
        row["hbm_gbps"] = float(env_hbm)
        source = "env"
    row["device_kind"] = kind
    row["source"] = source
    return row


def _dispatches(source: str | None, entry_phase, report: dict):
    """Recover an entry's dispatch count from the record (see ENTRY_JOIN)."""
    if source is None:
        return None
    if source == "phase":
        if entry_phase is None:
            return None
        calls = (report.get("phases", {}).get(entry_phase) or {}).get("calls")
        return int(calls) if calls else None
    kind, _, name = source.partition(":")
    if kind == "collective":
        calls = (report.get("collectives", {}).get(name) or {}).get("calls")
        return int(calls) if calls else None
    if kind == "counter":
        n = report.get("counters", {}).get(name)
        return int(n) if n else None
    return None


def _floor_seconds(flops, nbytes, peaks: dict):
    """(t_compute, t_hbm) floors for one dispatch; None legs unpriced."""
    t_c = (
        flops / peaks["flops"]
        if peaks.get("flops") and flops is not None else None
    )
    t_h = (
        nbytes / (peaks["hbm_gbps"] * 1e9)
        if peaks.get("hbm_gbps") and nbytes is not None else None
    )
    return t_c, t_h


def compute_section(report: dict, captures: dict, peaks: dict) -> dict:
    """Assemble ``record.compute`` from raw captures + the live record.

    ``captures``: ``{entry: {"flops", "bytes", "variants"}}`` — the raw
    per-dispatch whole-program costs ``BuildObserver.price_compile``
    collected (latest fresh variant per entry; ``variants`` counts how
    many lowered). ``report``: the record dict built so far (phases /
    collectives / counters / levels / wire / mesh already final).
    Pure host arithmetic; recomputed identically on repeated
    ``report()`` calls.
    """
    n_shards = max(int(report.get("wire", {}).get("n_shards") or 1), 1)
    entries: dict = {}
    opt_total = 0.0
    measured_total = 0.0
    flops_pd_total = 0.0
    bytes_pd_total = 0.0
    joined = False
    for entry, cap in sorted(captures.items()):
        phase, count_src = ENTRY_JOIN.get(entry, (None, None))
        # The partition-rule division: the lowered module is the GLOBAL
        # program, each shard executes 1/n of its row-parallel work —
        # same convention as the wire ledger's per-shard figures.
        flops_pd = cap["flops"] / n_shards
        bytes_pd = cap["bytes"] / n_shards
        t_c, t_h = _floor_seconds(flops_pd, bytes_pd, peaks)
        floors = [t for t in (t_c, t_h) if t is not None]
        optimal = max(floors) if floors else None
        dispatches = _dispatches(count_src, phase, report)
        measured = (
            (report.get("phases", {}).get(phase) or {}).get("seconds")
            if phase is not None else None
        )
        util = None
        if (optimal is not None and dispatches and measured):
            total_floor = optimal * dispatches
            util = round(100.0 * total_floor / measured, 2)
            opt_total += total_floor
            measured_total += measured
            flops_pd_total += flops_pd * dispatches
            bytes_pd_total += bytes_pd * dispatches
            joined = True
        bound = None
        if t_c is not None and t_h is not None:
            bound = "compute" if t_c >= t_h else "hbm"
        entries[entry] = {
            "flops": cap["flops"],
            "bytes": cap["bytes"],
            "flops_per_shard": flops_pd,
            "bytes_per_shard": bytes_pd,
            "variants": cap.get("variants", 1),
            "optimal_s": optimal,
            "dispatches": dispatches,
            "measured_s": measured,
            "util_pct": util,
            "bound": bound,
        }
    # Per-level floors: the live host-stepped rows carry seconds +
    # hist/psum bytes; the fused engines' replay rows carry the bytes
    # with seconds=None — floors are priced either way, utilization only
    # where a wall exists. HBM leg from the level's histogram slab
    # traffic, ICI leg from its psum payload over the data-axis ring.
    axes = report.get("mesh", {}).get("axes") or {}
    dr = max(int(axes.get("data", n_shards) or 1), 1)
    levels = []
    for row in report.get("levels", []):
        hist_b = row.get("hist_bytes") or 0
        psum_b = row.get("psum_bytes") or 0
        t_h = (
            hist_b / (peaks["hbm_gbps"] * 1e9)
            if peaks.get("hbm_gbps") else None
        )
        t_i = (
            psum_b * (dr - 1) / dr / (peaks["ici_gbps"] * 1e9)
            if peaks.get("ici_gbps") and dr > 1 else None
        )
        floors = [t for t in (t_h, t_i) if t is not None]
        floor = max(floors) if floors else None
        sec = row.get("seconds")
        levels.append({
            "level": row.get("level"),
            "floor_s": floor,
            "seconds": sec,
            "util_pct": (
                round(100.0 * floor / sec, 2)
                if floor is not None and sec else None
            ),
        })
    # Roofline verdict: which resource the whole fit's floor sits on.
    # Compute and HBM legs from the joined per-entry totals; the ICI leg
    # from the existing wire ledger's per-shard fabric bytes.
    wire_shard = report.get("wire", {}).get("wire_bytes_per_shard") or 0
    t_compute = (
        flops_pd_total / peaks["flops"]
        if peaks.get("flops") and joined else None
    )
    t_hbm = (
        bytes_pd_total / (peaks["hbm_gbps"] * 1e9)
        if peaks.get("hbm_gbps") and joined else None
    )
    t_ici = (
        wire_shard / (peaks["ici_gbps"] * 1e9)
        if peaks.get("ici_gbps") and joined else None
    )
    roofline = None
    legs = [("compute", t_compute), ("hbm", t_hbm), ("ici", t_ici)]
    priced = [(n, t) for n, t in legs if t is not None]
    if priced:
        roofline = max(priced, key=lambda nt: nt[1])[0]
    return {
        "peak": dict(peaks),
        "n_shards": n_shards,
        "entries": entries,
        "levels": levels,
        "optimal_s": round(opt_total, 6) if joined else None,
        "measured_s": round(measured_total, 6) if joined else None,
        "util_pct": (
            round(100.0 * opt_total / measured_total, 2)
            if joined and measured_total else None
        ),
        "roofline": roofline,
        "bounds_s": {
            "compute": t_compute, "hbm": t_hbm, "ici": t_ici,
        },
    }


def host_entries(report: dict) -> dict:
    """Priced-to-None entries for host-tier dispatches (honesty fix).

    Returns ``{entry: row}`` in the per-entry shape of
    :func:`compute_section`, for every :data:`HOST_ENTRIES` source whose
    dispatch counter fired this fit. Floors, utilization, and bound are
    ``None`` with the reason recorded — the host tier runs numpy/C++
    the XLA cost model cannot capture, and a visible unpriced row beats
    a section that pretends the work did not happen.
    """
    rows: dict = {}
    for entry, (phase, count_src) in sorted(HOST_ENTRIES.items()):
        dispatches = _dispatches(count_src, phase, report)
        if not dispatches:
            continue
        measured = (
            (report.get("phases", {}).get(phase) or {}).get("seconds")
            if phase is not None else None
        )
        rows[entry] = {
            "flops": None,
            "bytes": None,
            "flops_per_shard": None,
            "bytes_per_shard": None,
            "variants": 0,
            "optimal_s": None,
            "dispatches": dispatches,
            "measured_s": measured,
            "util_pct": None,
            "bound": None,
            "unpriced": (
                "host-tier numpy/C++ dispatch: no XLA cost capture"
            ),
        }
    return rows


def host_only_section(rows: dict) -> dict:
    """A ``record.compute`` section for a fit with NO priced captures —
    the whole-fit aggregates are honestly ``None``; only the host-tier
    dispatch counts ride."""
    return {
        "peak": {},
        "n_shards": 1,
        "entries": rows,
        "levels": [],
        "optimal_s": None,
        "measured_s": None,
        "util_pct": None,
        "roofline": None,
        "bounds_s": {"compute": None, "hbm": None, "ici": None},
    }
