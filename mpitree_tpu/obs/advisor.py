"""obs.advisor — evidence-driven ``auto`` policies from the flight store.

The static resolvers (``resolve_hist_subtraction``,
``resolve_rounds_per_dispatch``, ``resolve_mesh_2d``,
``resolve_serving_kernel``) encode platform heuristics measured once and
frozen into code: "subtraction nets ~0.92x on CPU", "K=8 amortizes TPU
dispatch". The flight store (``obs.flight``) has been accumulating the
actual A/B evidence those heuristics were distilled from — every
``bench_tpu`` run appends ``subtraction_ab`` / ``gbdt_fusedK`` /
``mesh2d_ab`` / ``serving`` section envelopes with measured speedups on
THIS machine. This module closes the loop: an ``auto`` resolution may
consult that lineage history and pick the measured winner instead of the
static guess.

Honesty contract (mirrors ``obs.diff``):

- Evidence is consulted only when the margin clears the lineage's own
  noise gate — ``max(floor, NOISE_Z * 1.4826 * MAD / |median|)``, the
  same robust dispersion model ``threshold_for`` uses. A lineage whose
  A/B ratio wobbles across 1.0 yields ``fallback="noise_gate"`` and the
  static policy applies bit-for-bit.
- Fewer than :data:`MIN_HISTORY` matched rows yields
  ``fallback="thin_history"`` — again the static policy, bit-for-bit.
- Evidence NEVER overrides a hard constraint: exactness requirements,
  fused-program blockers, and VMEM fits are checked by the resolvers
  before (or after) the consultation; the advisor only replaces the
  *preference* heuristics.
- Every consultation is recorded as a typed ``advisor_<policy>``
  decision (winner, evidence count, margin, gate, fallback reason) so
  ``fit_report_``/``serve_report_`` explain why a policy flipped.

Gating: ``BuildConfig(policy_evidence="auto"|"off")`` (explicit config
wins) over the ambient ``MPITREE_TPU_POLICY_EVIDENCE`` knob, and the
store itself only exists under ``MPITREE_TPU_RUN_DIR`` — with no store
configured every consultation is a cheap ``None`` (two knob reads, no
I/O) and resolutions are exactly the pre-advisor static ones.

Workload matching: bench envelopes carry their workload shape in
``metrics`` (``n_samples`` / ``n_features`` / ...); a consultation ranks
same-platform rows by log-space distance over the shared shape keys and
reads the nearest :data:`NEAREST_K`. A stored row from a 10x larger
dataset still counts — nearest-first just prefers better-matched
evidence when it exists.

Stdlib-only (the ``obs/diff.py`` contract): no jax import, so the module
prices nothing and can run on watcher hosts.
"""

from __future__ import annotations

import math
import statistics

from mpitree_tpu.config import knobs
from mpitree_tpu.obs import diff as diff_mod
from mpitree_tpu.obs import flight as flight_mod

POLICY_ENV = "MPITREE_TPU_POLICY_EVIDENCE"

MIN_HISTORY = diff_mod.MIN_HISTORY
NOISE_Z = diff_mod.NOISE_Z

# Evidence window: the nearest-by-shape rows a consultation reads. Wide
# enough for the MAD noise model to mean something, narrow enough that
# a store full of foreign workloads cannot outvote the matched ones.
NEAREST_K = 8

# Relative margin floor: even a perfectly quiet lineage must clear ±5%
# before evidence flips a policy — sub-noise "wins" are not wins.
MARGIN_FLOOR = 0.05

# Numeric envelope-metric keys that describe the workload (not the
# result); the nearest-match distance reads whichever of these both
# sides carry.
SHAPE_KEYS = (
    "n_samples", "n_features", "n_bins", "n_classes", "max_iter",
    "max_depth", "n_trees", "fit_rows", "n_devices",
)


def enabled(policy_evidence: str = "auto") -> bool:
    """Whether consultations may run: config gate, env knob, live store."""
    if str(policy_evidence) == "off":
        return False
    if knobs.value(POLICY_ENV) == "off":
        return False
    return flight_mod.enabled()


def _store():
    try:
        return flight_mod.FlightStore()
    except ValueError:  # no RUN_DIR and no explicit root
        return None


def _shape_distance(metrics: dict, shape: dict | None) -> float:
    """Log-space L2 distance over shared shape keys (inf: no overlap).

    Log-space because workloads differ multiplicatively — 1M rows vs
    100k rows should out-distance 64 bins vs 256 bins by the same factor
    regardless of the keys' absolute scales.
    """
    if not shape:
        return math.inf
    d, shared = 0.0, 0
    for k in SHAPE_KEYS:
        a, b = shape.get(k), metrics.get(k)
        if (isinstance(a, (int, float)) and not isinstance(a, bool)
                and isinstance(b, (int, float)) and not isinstance(b, bool)
                and a > 0 and b > 0):
            d += math.log(a / b) ** 2
            shared += 1
    return math.sqrt(d / shared) if shared else math.inf


def nearest_evidence(store, *, section: str, platform: str | None,
                     shape: dict | None, limit: int = NEAREST_K) -> list:
    """Same-platform ``kind="bench"`` envelopes of ``section``, nearest
    workload shape first (recency breaks ties), at most ``limit``."""
    rows = store.entries(kind="bench", section=section, platform=platform)
    scored = [
        (_shape_distance(env.get("metrics") or {}, shape), -i, env)
        for i, env in enumerate(rows)
    ]
    scored.sort(key=lambda t: (t[0], t[1]))
    return [env for _, _, env in scored[:limit]]


def _metric_values(rows: list, metric: str) -> list:
    vals = []
    for env in rows:
        v = (env.get("metrics") or {}).get(metric)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            vals.append(float(v))
    return vals


def _noise_gate(values: list, floor: float = MARGIN_FLOOR) -> tuple:
    """(median, rel_gate): the lineage's own robust dispersion, floored."""
    med = statistics.median(values)
    if not med:
        return med, floor
    mad = statistics.median([abs(v - med) for v in values])
    return med, max(floor, NOISE_Z * 1.4826 * mad / abs(med))


def _advice(policy: str, value, *, section: str, n: int,
            median=None, margin=None, gate=None,
            fallback: str | None = None) -> dict:
    return {
        "policy": policy,
        "value": value,            # winner, or None -> static policy
        "section": section,        # evidence lineage consulted
        "evidence_n": n,           # matched rows that carried the metric
        "median": None if median is None else round(median, 4),
        "margin": None if margin is None else round(margin, 4),
        "gate": None if gate is None else round(gate, 4),
        "fallback": fallback,      # why value is None (None when decided)
    }


def _advise_ratio(store, *, policy: str, section: str, metric: str,
                  platform: str | None, shape: dict | None,
                  hi, lo) -> dict:
    """Generic A/B-ratio consultation: ``metric`` is a B-over-A speedup
    ratio; ``hi`` wins when the matched median clears ``1 + gate``,
    ``lo`` when it clears ``1 - gate``, static policy otherwise."""
    rows = nearest_evidence(
        store, section=section, platform=platform, shape=shape,
    )
    vals = _metric_values(rows, metric)
    if len(vals) < MIN_HISTORY:
        return _advice(
            policy, None, section=section, n=len(vals),
            fallback="thin_history",
        )
    med, gate = _noise_gate(vals)
    margin = abs(med - 1.0)
    if med > 1.0 + gate:
        value = hi
    elif med < 1.0 - gate:
        value = lo
    else:
        return _advice(
            policy, None, section=section, n=len(vals), median=med,
            margin=margin, gate=gate, fallback="noise_gate",
        )
    return _advice(
        policy, value, section=section, n=len(vals), median=med,
        margin=margin, gate=gate,
    )


# -- per-policy consultations ----------------------------------------------

def advise_hist_subtraction(*, platform: str, shape: dict | None = None,
                            policy_evidence: str = "auto",
                            store=None) -> dict | None:
    """"on" / "off" from stored ``subtraction_ab`` A/Bs, or None.

    Evidence metric: ``warm_speedup_on_vs_off`` (off-side warm wall over
    on-side warm wall — >1 means the subtraction won). Rows where auto
    resolved off record ``warm_speedup_off_vs_off`` instead, which is
    correctly invisible here: an off-vs-off "A/B" carries no evidence
    about the trick.
    """
    if not enabled(policy_evidence):
        return None
    store = store if store is not None else _store()
    if store is None:
        return None
    return _advise_ratio(
        store, policy="hist_subtraction", section="subtraction_ab",
        metric="warm_speedup_on_vs_off", platform=platform, shape=shape,
        hi="on", lo="off",
    )


def advise_engine(*, platform: str, shape: dict | None = None,
                  policy_evidence: str = "auto",
                  store=None) -> dict | None:
    """"leafwise" / "levelwise" from stored ``leafwise_ab`` A/Bs, or None.

    Evidence metric: ``warm_speedup_x`` (level-wise warm wall over
    leaf-wise warm wall on the same workload — >1 means the best-first
    frontier won). The caller owns the hard admissibility constraints a
    measured win can never override (leaf budget fits the level-wise
    node bound so trees stay bit-identical, no feature axis, no
    monotonic constraints); the consultation only replaces the "one
    fused program beats per-level dispatch" preference heuristic.
    """
    if not enabled(policy_evidence):
        return None
    store = store if store is not None else _store()
    if store is None:
        return None
    return _advise_ratio(
        store, policy="engine", section="leafwise_ab",
        metric="warm_speedup_x", platform=platform, shape=shape,
        hi="leafwise", lo="levelwise",
    )


def advise_rounds_per_dispatch(*, platform: str, shape: dict | None = None,
                               policy_evidence: str = "auto",
                               store=None) -> dict | None:
    """"fused" / "host" from stored ``gbdt_fusedK`` A/Bs, or None.

    Evidence metric: ``fit_speedup_x`` (host-loop fit wall over fused-K
    fit wall). A "fused" verdict also carries ``K`` — the median of the
    winning rows' recorded K — so the caller dispatches the K the
    evidence was measured at, not a hardcoded default.
    """
    if not enabled(policy_evidence):
        return None
    store = store if store is not None else _store()
    if store is None:
        return None
    adv = _advise_ratio(
        store, policy="rounds_per_dispatch", section="gbdt_fusedK",
        metric="fit_speedup_x", platform=platform, shape=shape,
        hi="fused", lo="host",
    )
    if adv["value"] == "fused":
        rows = nearest_evidence(
            store, section="gbdt_fusedK", platform=platform, shape=shape,
        )
        ks = [int(k) for k in _metric_values(rows, "K") if k >= 1]
        if ks:
            adv["K"] = int(statistics.median(ks))
    return adv


def advise_mesh_2d(*, platform: str, shape: dict | None = None,
                   policy_evidence: str = "auto",
                   store=None) -> dict | None:
    """"2d" / "1d" from stored ``mesh2d_ab`` A/Bs, or None.

    Evidence metric: ``warm_speedup_2d_vs_1d`` (1-D warm wall over 2-D
    warm wall on the same workload and device count).
    """
    if not enabled(policy_evidence):
        return None
    store = store if store is not None else _store()
    if store is None:
        return None
    return _advise_ratio(
        store, policy="mesh_2d", section="mesh2d_ab",
        metric="warm_speedup_2d_vs_1d", platform=platform, shape=shape,
        hi="2d", lo="1d",
    )


def advise_serving_kernel(*, platform: str, shape: dict | None = None,
                          policy_evidence: str = "auto",
                          store=None) -> dict | None:
    """"pallas" / "xla" from stored ``serving`` sections, or None.

    Serving rows are not A/B pairs — each run served one resolved kernel
    (``kernel_pallas`` 0/1) at a measured ``sustained_rows_per_s`` — so
    the consultation groups the matched rows by kernel and compares the
    groups' median throughputs. Both groups need :data:`MIN_HISTORY`
    rows; the margin must clear the noisier group's own gate.
    """
    if not enabled(policy_evidence):
        return None
    store = store if store is not None else _store()
    if store is None:
        return None
    rows = nearest_evidence(
        store, section="serving", platform=platform, shape=shape,
        limit=NEAREST_K * 2,  # two groups share the window
    )
    groups: dict = {0: [], 1: []}
    for env in rows:
        m = env.get("metrics") or {}
        k = m.get("kernel_pallas")
        v = m.get("sustained_rows_per_s")
        if (k in (0, 1) and isinstance(v, (int, float))
                and not isinstance(v, bool)):
            groups[int(k)].append(float(v))
    n = len(groups[0]) + len(groups[1])
    if len(groups[0]) < MIN_HISTORY or len(groups[1]) < MIN_HISTORY:
        return _advice(
            "serving_kernel", None, section="serving", n=n,
            fallback="thin_history",
        )
    med_x, gate_x = _noise_gate(groups[0])
    med_p, gate_p = _noise_gate(groups[1])
    if not med_x:
        return _advice(
            "serving_kernel", None, section="serving", n=n,
            fallback="noise_gate",
        )
    ratio = med_p / med_x
    gate = max(gate_x, gate_p)
    margin = abs(ratio - 1.0)
    if ratio > 1.0 + gate:
        value = "pallas"
    elif ratio < 1.0 - gate:
        value = "xla"
    else:
        return _advice(
            "serving_kernel", None, section="serving", n=n, median=ratio,
            margin=margin, gate=gate, fallback="noise_gate",
        )
    return _advice(
        "serving_kernel", value, section="serving", n=n, median=ratio,
        margin=margin, gate=gate,
    )


def record_advice(obs, advice: dict | None) -> None:
    """One typed ``advisor_<policy>`` decision per consultation (no-op
    when the consultation never ran or there is no observer)."""
    if obs is None or advice is None:
        return
    value = advice["value"] if advice["value"] is not None else "static"
    reason = (
        f"flight-store evidence ({advice['section']}, "
        f"n={advice['evidence_n']}): measured winner"
        if advice["fallback"] is None else
        f"flight-store evidence ({advice['section']}, "
        f"n={advice['evidence_n']}) inconclusive "
        f"({advice['fallback']}); static policy applies"
    )
    obs.decision(
        f"advisor_{advice['policy']}", value, reason=reason,
        evidence_n=advice["evidence_n"], median=advice["median"],
        margin=advice["margin"], gate=advice["gate"],
        fallback=advice["fallback"],
    )
