"""BuildObserver: the span()/counter() API every engine writes into.

A superset of ``utils/profiling.PhaseTimer`` (which it absorbs by
subclassing): the timer's phase spans keep working unchanged — every
``timer.phase(...)``/``timer.span(...)`` site in the engines is also an
observer site — and the observer adds the always-on cheap channels
(counters, decisions, typed events, compile and collective accounting)
plus the profile-gated per-level rows.

Cost model, enforced by ``tests/test_obs.py``'s disabled-path test:

- observability OFF (no ``MPITREE_TPU_PROFILE``): spans are the existing
  no-op ``yield``; level rows are never allocated; counters/events/
  decisions are O(1) dict updates on numbers computed from static shapes
  — within the <5% wall bound on the 2k-row smoke workload;
- observability ON: spans accumulate wall-clock and per-level rows are
  appended (capped — see ``MAX_LEVEL_ROWS``). Rows past the cap stream
  to a JSONL spill file when a sink is configured
  (:meth:`BuildObserver.stream_levels_to` or
  ``MPITREE_TPU_OBS_STREAM_DIR`` — leaf-wise builds emit one row per
  EXPANSION, so a 255-leaf GBDT blows the cap inside two rounds); with
  no sink the honest ``levels_dropped`` counter records the truncation.

Compile accounting is a process-wide cache-key registry — the runtime
twin of graftlint GL02: every jit entry point (``split_fn``,
``counts_fn``, ``update_fn``, ``fused_fn``, ``forest_fn``) notes its
static-configuration key; a key first seen means a fresh lowering (cold
seconds land in whatever span is open), a repeat means the lru-cached
executable. Crossing ``RECOMPILE_WARN_AFTER`` distinct keys for one
entry point warns once — the signature of recompile churn (shape keys
leaking runtime values).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
import warnings
from collections import OrderedDict

from mpitree_tpu.obs import cost as cost_mod
from mpitree_tpu.obs import fingerprint as fingerprint_mod
from mpitree_tpu.obs import flight as flight_mod
from mpitree_tpu.obs import memory as memory_mod
from mpitree_tpu.obs import trace as trace_mod
from mpitree_tpu.obs.record import (
    BuildRecord,
    _jsonable,
    digest as record_digest,
    wire_estimate,
)
from mpitree_tpu.utils.profiling import PhaseTimer, profiling_enabled
from mpitree_tpu.config import knobs

# Per-process spill-file sequence: distinguishes observers sharing a PID
# without relying on id(self) (heap addresses recycle).
_STREAM_SEQ = itertools.count()

# Same idea for trace files and synthesized-track ownership keys: a
# recycled heap address must never let a new fit's replay spans replace a
# live observer's in a shared sink.
_TRACE_SEQ = itertools.count()

# Lowering events per entry point beyond which we warn: the collective
# factories' lru_caches hold 64 entries and the fused builder's 32 — past
# half the cache a workload is compiling more variants than it can keep,
# and every further miss is a silent 20-70s tunnel recompile.
RECOMPILE_WARN_AFTER = 32


class CompileRegistry:
    """Process-wide lowering-event counts per jit entry point.

    Each entry point's key set is an LRU mirroring that factory's
    ``lru_cache`` size: a key seen before but since EVICTED re-traces and
    re-compiles on the device, and the registry reports it as new again —
    without the mirror, cache-cycling workloads would pay full tunnel
    recompiles while ``fit_report_['compile']`` claimed everything warm.
    ``count`` therefore totals lowering *events* (>= distinct keys).
    """

    def __init__(self):
        self._lru: dict = {}  # entry -> OrderedDict of live cache keys
        self._lowerings: dict = {}  # entry -> lowering events
        self._seconds: dict = {}  # entry -> attributed cold-dispatch wall
        # attribute() is called from concurrently-publishing serving
        # threads (the registry's concurrent-dispatch contract, same
        # reason traversal serializes note() under its _NOTE_LOCK); an
        # unlocked read-modify-write would drop addends.
        self._seconds_lock = threading.Lock()
        self._warned: set = set()
        # Compute-ledger cost captures (obs/cost.py, ISSUE 18): one
        # representative {flops, bytes, variants} per entry point,
        # priced at FRESH cache-key registration and reused by every
        # later (warm) fit in the process — the once-per-cache-key
        # contract rides this registry exactly like the lru mirror.
        self._costs: dict = {}

    def note(self, entry: str, key, cache_size: int = 64) -> bool:
        """Record one factory resolution; True when ``key`` lowers fresh
        (first sight OR evicted from the mirrored lru), False when the
        cached executable serves it. ``cache_size`` must match the
        factory's ``lru_cache(maxsize=...)``."""
        lru = self._lru.setdefault(entry, OrderedDict())
        if key in lru:
            lru.move_to_end(key)
            return False
        lru[key] = True
        while len(lru) > cache_size:
            lru.popitem(last=False)
        n = self._lowerings.get(entry, 0) + 1
        self._lowerings[entry] = n
        if n == RECOMPILE_WARN_AFTER and entry not in self._warned:
            self._warned.add(entry)
            warnings.warn(
                f"jit entry point {entry!r} has compiled "
                f"{RECOMPILE_WARN_AFTER} lowerings this process — "
                "recompile churn (a static config key is probably carrying "
                "a runtime-varying value); see fit_report_['compile']",
                stacklevel=4,
            )
        return True

    def count(self, entry: str) -> int:
        return self._lowerings.get(entry, 0)

    def attribute(self, entry: str, seconds: float) -> None:
        """Attribute cold-dispatch wall-clock to ``entry`` (the ROADMAP
        per-entry-point cold-compile follow-up): the wall of the FIRST
        dispatch after a fresh cache-key registration, which is compile
        plus one execution — an honest upper bound on the tunnel-compile
        cost this entry point charged the process."""
        with self._seconds_lock:
            self._seconds[entry] = (
                self._seconds.get(entry, 0.0) + float(seconds)
            )

    def seconds(self, entry: str) -> float:
        """Total cold-dispatch wall attributed to ``entry`` process-wide."""
        with self._seconds_lock:
            return self._seconds.get(entry, 0.0)

    def price(self, entry: str, info: dict) -> None:
        """Store one fresh lowering's cost capture for ``entry`` (the
        latest variant is the representative per-dispatch cost; the
        ``variants`` count stays honest about how many were priced)."""
        with self._seconds_lock:
            cap = self._costs.setdefault(
                entry, {"flops": 0.0, "bytes": 0.0, "variants": 0}
            )
            cap["flops"] = float(info["flops"])
            cap["bytes"] = float(info["bytes"])
            cap["variants"] += 1

    def cost(self, entry: str) -> dict | None:
        """The entry's stored cost capture (a copy), or None."""
        with self._seconds_lock:
            cap = self._costs.get(entry)
            return dict(cap) if cap else None


REGISTRY = CompileRegistry()


def mesh_info(mesh) -> dict:
    """JSON-able mesh description for the record."""
    return {
        "platform": mesh.devices.flat[0].platform,
        "n_devices": int(mesh.size),
        "axes": {str(name): int(mesh.shape[name]) for name in mesh.axis_names},
    }


def warn_event(obs, kind: str, message: str, *, stacklevel: int = 2) -> None:
    """``warnings.warn`` + typed record event — one call per site.

    Every structured-event site in the engines routes through here so the
    stderr warning and the ``fit_report_`` event can never say different
    things. ``stacklevel`` counts from the CALLER (this frame is added).
    ``obs`` may be any PhaseTimer (the base class's ``event`` is a no-op).
    The resilience ladder emits its rung events (``device_retry``,
    ``device_failover``) directly via ``obs.event`` + its own warning —
    the retry loop needs per-attempt data fields; see
    ``resilience/retry.py``.
    """
    warnings.warn(message, stacklevel=stacklevel + 1)
    if obs is not None:
        obs.event(kind, message)


def note_build_path(obs, *, host: bool, backend, n_rows: int,
                    n_features: int) -> None:
    """Record the host-vs-device routing decision (one copy for every
    estimator — ``core/builder.prefer_host_path``'s inputs and verdict)."""
    if backend == "host":
        reason = "backend='host' forces the numpy tier"
    elif host:
        reason = (
            f"auto: {n_rows}x{n_features} = {n_rows * n_features} cells "
            "<= host-path threshold on a single device (per-level device "
            "dispatch would dominate)"
        )
    elif backend is not None:
        reason = f"explicit backend={backend!r} forces the device path"
    else:
        reason = "multi-device mesh or workload above the host-path threshold"
    obs.decision(
        "build_path", "host" if host else "device", reason=reason,
        rows=int(n_rows), features=int(n_features),
    )


def note_refine(obs, *, refine: bool, rd, crown_depth,
                refine_depth_param, constrained: bool = False,
                leafwise: bool = False, streamed: bool = False) -> None:
    """Record the hybrid-refine decision (estimator-level routing)."""
    if streamed:
        reason = (
            "streamed ingest: hybrid tail skipped — single-tree fits "
            "replay the chunk stream to gather refine rows, but "
            "ensembles would replay it once per tree and multi-host "
            "fits only stream their own shard (single-engine full "
            "depth)"
        )
    elif leafwise:
        reason = (
            "max_leaf_nodes: hybrid tail skipped — the best-first frontier "
            "owns the leaf budget end to end (a host tail would re-grow "
            "past it)"
        )
    elif constrained:
        reason = (
            "monotonic_cst: hybrid tail skipped — constraint bounds do not "
            "thread across the graft seam (single-engine full depth)"
        )
    elif not refine:
        reason = (
            "no hybrid tail (refine_depth=None, exact candidates, or "
            "max_depth within the crown)"
        )
    elif refine_depth_param == "auto":
        reason = (
            "auto: quantile binning capped some feature's candidate set — "
            "exact-local-candidate host tail recovers deep-node accuracy"
        )
    else:
        reason = f"explicit refine_depth={refine_depth_param!r}"
    obs.decision(
        "refine", int(rd) if refine and rd is not None else None,
        reason=reason,
        crown_depth=(None if crown_depth is None else int(crown_depth)),
    )


class BuildObserver(PhaseTimer):
    """Structured run-record collector; see module docstring.

    ``timing=None`` reads ``MPITREE_TPU_PROFILE`` (the PhaseTimer gate);
    pass an explicit bool to override. The record is always created —
    counters/decisions/events/accounting are the always-on cheap channel;
    spans and level rows are timing-gated.
    """

    MAX_LEVEL_ROWS = 512
    MAX_EVENTS = 128
    MAX_ROUNDS = 1024

    def __init__(self, timing: bool | None = None):
        super().__init__(
            enabled=profiling_enabled() if timing is None else timing
        )
        self.record = BuildRecord()
        self._level_stream_path: str | None = None
        self._level_stream_file = None
        self._level_stream_failed = False
        # Trace channel (obs/trace.py): spans/events/collectives feed a
        # Chrome-trace sink when one is configured; one `is None` check
        # otherwise (inside the disabled-path <5% budget).
        self._trace: trace_mod.TraceSink | None = None
        self._trace_owned = False
        self._trace_failed = False
        self._trace_seq = next(_TRACE_SEQ)
        self._trace_track = f"fit{self._trace_seq}"
        self._trace_window: list | None = None
        self._trace_windows: dict = {}  # phase name -> [t0, t1]
        tdir = knobs.raw(trace_mod.TRACE_DIR_ENV)
        if tdir:
            self.trace_to(os.path.join(
                tdir, f"trace_{os.getpid()}_{self._trace_seq}.json"
            ))
        # Live memory watermarks (obs/memory.py, ISSUE 12): sampled at
        # span boundaries only, and only when a watch is installed —
        # the disabled path pays one `is None` check per span (inside
        # the pinned <5% budget).
        self._memwatch: memory_mod.MemWatch | None = None
        if knobs.value(memory_mod.MEM_SAMPLE_ENV):
            self.watch_memory()
        # Build-state fingerprints (obs/fingerprint.py, ISSUE 13): the
        # running whole-fit fold plus the per-tree row lists; host-side
        # hashing over arrays the engines already hold — always on.
        self._fp_hash = None
        # Multi-plan fits (the host gbdt round loop records one plan per
        # round): kept for the whole-fit aggregation at report time.
        self._fit_plans: list = []
        # Flight recorder (obs/flight.py): the first report() of a fit
        # appends the finalized record to the MPITREE_TPU_RUN_DIR store.
        # Serving observers relabel their envelopes via ``flight_kind``.
        # An ambient store implies span timing (the trace_to contract):
        # the sentinel's headline metric is wall clock, and an envelope
        # whose digest wall_s is always 0 would be blind to slowdowns.
        self._flight_logged = False
        self.flight_kind = "fit"
        if flight_mod.enabled():
            self.enabled = True
        # Compute ledger (obs/cost.py, ISSUE 18): cost captures live in
        # the process REGISTRY (priced once per FRESH cache key at the
        # dispatch sites via price_compile, reused by warm fits); this
        # set only dedups the per-fit cost_unavailable event.
        self._cost_unavailable: set = set()

    def watch_memory(self, watch=None) -> None:
        """Enable span-boundary live-memory sampling (the ambient form is
        ``MPITREE_TPU_MEM_SAMPLE=1``). Implies timing — watermark samples
        without spans would never fire."""
        self._memwatch = (
            watch if watch is not None else memory_mod.MemWatch()
        )
        self._memwatch.sample()  # baseline: what the process already held
        self.enabled = True

    def memory_plan(self, plan) -> None:
        """Record the analytical memory ledger (a
        :class:`~mpitree_tpu.obs.memory.MemoryPlan` or its dict) under
        ``record.memory`` — the always-on channel every engine writes
        once per fit, before its first dispatch. Multi-round host loops
        write one plan per round; every plan is kept so ``report()`` can
        aggregate them into the whole-fit plan drift checking compares
        against (the PR-12 follow-up)."""
        d = plan if isinstance(plan, dict) else plan.to_dict()
        self._fit_plans.append(d)
        live = self.record.memory.get("live")
        self.record.memory = dict(d)
        if live is not None:
            self.record.memory["live"] = live

    # -- build-state fingerprints (obs/fingerprint.py, ISSUE 13) -----------
    wants_fingerprints = True

    def fingerprint_tree(self, rows) -> None:
        """Commit one built tree's per-level fingerprint rows.

        The level-wise/host engines hash their rows live at the host
        boundary and commit once per finished build; the fused engines
        commit :func:`~mpitree_tpu.obs.fingerprint.tree_fingerprints`
        replays. Every committed tree folds into the running whole-fit
        hash regardless of the row cap, so the record's ``fingerprint``
        covers ensembles of any size.
        """
        rows = list(rows)
        self._fp_hash = fingerprint_mod.fold(rows, self._fp_hash)
        fp = self.record.fingerprints
        if not fp:
            fp["version"] = fingerprint_mod.FINGERPRINT_VERSION
            fp["trees"] = []
        if len(fp["trees"]) >= self.MAX_ROUNDS:
            self.counter("fingerprint_trees_dropped")
            return
        fp["trees"].append(rows)

    def trace_to(self, sink, *, track: str | None = None) -> None:
        """Emit this observer's timeline into ``sink`` (a path, or a
        :class:`~mpitree_tpu.obs.trace.TraceSink` shared across fits —
        what ``fit(trace_to=...)`` plumbs here).

        Tracing implies timing: spans need wall-clock, so ``enabled``
        flips on regardless of ``MPITREE_TPU_PROFILE``. A path sink is
        makedirs'd and probed UP FRONT; an unwritable one degrades to a
        typed ``trace_failed`` event with tracing off (the checkpoint/
        level-stream sink contract — telemetry never aborts a fit).
        """
        if track is not None:
            self._trace_track = str(track)
        if isinstance(sink, trace_mod.TraceSink):
            self._trace, self._trace_owned = sink, False
        else:
            path = str(sink)
            try:
                parent = os.path.dirname(os.path.abspath(path))
                os.makedirs(parent, exist_ok=True)
                with open(path, "a"):
                    pass
            except OSError as e:
                self._trace_failed = True
                self.event(
                    "trace_failed",
                    f"trace sink unwritable ({e}); tracing disabled for "
                    "this fit",
                    path=path,
                )
                return
            self._trace, self._trace_owned = trace_mod.TraceSink(path), True
        self.enabled = True

    def stream_levels_to(self, path) -> None:
        """Spill per-level/per-expansion rows past ``MAX_LEVEL_ROWS`` to
        ``path`` (JSONL, append) instead of dropping them.

        The in-record list keeps the first ``MAX_LEVEL_ROWS`` rows (the
        record stays bench-line sized); everything past the cap lands in
        the spill file and ``record.level_stream`` carries
        ``{"path", "rows"}`` so consumers know where the tail lives.
        ``MPITREE_TPU_OBS_STREAM_DIR=<dir>`` configures the same sink
        ambiently (one uniquely named file per observer, created on first
        spill) for estimators that build their observer internally.
        """
        self._level_stream_path = str(path)

    def _level_sink(self):
        """The open spill file, or None when no sink is configured.

        An unwritable sink (read-only dir, full disk) must never abort a
        fit — the observability channel degrades to ``levels_dropped``
        with a typed event carrying the evidence, same contract as every
        other ambient env knob.
        """
        if self._level_stream_file is not None:
            return self._level_stream_file
        if self._level_stream_failed:
            return None
        path = self._level_stream_path
        try:
            if path is None:
                stream_dir = knobs.raw("MPITREE_TPU_OBS_STREAM_DIR")
                if not stream_dir:
                    return None
                os.makedirs(stream_dir, exist_ok=True)
                # Monotonic per-process counter, NOT id(self): a recycled
                # heap address would append a new fit's rows to a dead
                # observer's spill file.
                path = os.path.join(
                    stream_dir,
                    f"levels_{os.getpid()}_{next(_STREAM_SEQ)}.jsonl",
                )
            self._level_stream_file = open(path, "a")
        except OSError as e:
            self._level_stream_failed = True
            self.event(
                "level_stream_failed",
                f"level-row spill sink unwritable ({e}); rows past the "
                "cap are dropped instead",
                path=path,
            )
            return None
        self._level_stream_path = path
        return self._level_stream_file

    # ``span`` is the obs-native name; ``phase`` stays for PhaseTimer
    # compatibility (both are the same context manager). Overrides the
    # base timer to ALSO emit a Chrome-trace complete event per span
    # instance when a sink is configured — the timer aggregates seconds
    # per phase NAME, the trace keeps every instance on the timeline.
    @contextlib.contextmanager
    def phase(self, name: str):
        tr = self._trace
        mw = self._memwatch
        if not self.enabled and tr is None and mw is None:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if self.enabled:
                self.seconds[name] += dt
                self.calls[name] += 1
            if mw is not None:
                # Span-boundary watermark sample (never inside a device
                # program); rendered as a Perfetto counter track next to
                # the PR-9 ICI tracks when a trace sink is live.
                mw.sample()
                if tr is not None:
                    # Current readings, not the cummax peaks — the track
                    # must show memory being RELEASED (a dropped carry
                    # buffer correlating with a span edge).
                    tr.counter(
                        "mem", "mem_hbm_bytes", time.perf_counter(),
                        {"hbm": mw.hbm_last, "host": mw.host_last},
                    )
            if tr is not None:
                tr.complete(self._trace_track, name, t0, dt)
                w = self._trace_window
                if w is None:
                    self._trace_window = [t0, t0 + dt]
                else:
                    w[0] = min(w[0], t0)
                    w[1] = max(w[1], t0 + dt)
                pw = self._trace_windows.get(name)
                if pw is None:
                    self._trace_windows[name] = [t0, t0 + dt]
                else:
                    pw[0] = min(pw[0], t0)
                    pw[1] = max(pw[1], t0 + dt)

    span = phase

    @contextlib.contextmanager
    def compile_attribution(self, entry: str, fresh: bool = True):
        """Time the dispatch following a FRESH ``compile_note`` and
        attribute its wall to ``entry`` — in the process registry
        (``REGISTRY.seconds``), in ``fit_report_['compile'][entry]
        ['seconds']``, and as a ``compile:{entry}`` trace span. A warm
        key (``fresh=False``) passes through untouched: only cold
        lowerings carry compile cost."""
        if not fresh:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            REGISTRY.attribute(entry, dt)
            rec = self.record.compile.setdefault(
                entry, {"lowerings": 0, "new": 0}
            )
            rec["seconds"] = round(rec.get("seconds", 0.0) + dt, 6)
            if self._trace is not None:
                self._trace.complete(
                    "compile", f"compile:{entry}", t0, dt, cat="compile"
                )

    # -- always-on channels ------------------------------------------------
    def counter(self, name: str, inc=1) -> None:
        c = self.record.counters
        c[name] = c.get(name, 0) + inc

    def event(self, kind: str, message: str, **data) -> None:
        if self._trace is not None:
            # Typed events are the resilience ladder's rung reports
            # (device_retry/device_failover), checkpoint notes, fallback
            # decisions — instants on the timeline, real timestamps.
            self._trace.instant(
                f"{self._trace_track}:events", kind, cat="event",
                args={"message": message, **data},
            )
        ev = self.record.events
        if len(ev) >= self.MAX_EVENTS:
            self.counter("events_dropped")
            return
        row = {"kind": kind, "message": message}
        if data:
            row.update(data)
        ev.append(row)

    def decision(self, key: str, value, reason: str | None = None,
                 **inputs) -> None:
        entry = {"value": value, "reason": reason}
        if inputs:
            entry["inputs"] = inputs
        self.record.decisions[key] = entry
        if key == "engine":
            self.record.engine = entry

    def set_mesh(self, mesh) -> None:
        self.record.mesh = mesh_info(mesh)

    def collective(self, site: str, *, calls: int = 1, nbytes: int = 0) -> None:
        entry = self.record.collectives.setdefault(
            site, {"calls": 0, "bytes": 0}
        )
        entry["calls"] += int(calls)
        entry["bytes"] += int(nbytes)
        if self._trace is not None:
            # Live ICI counter track: cumulative logical payload per site
            # at the moment the engine accounted it (the levelwise loops
            # account live; the fused engines' post-hoc totals land via
            # the synthesized replay counters instead).
            self._trace.counter(
                "ici", f"ici:{site}", time.perf_counter(),
                {"bytes": entry["bytes"]},
            )

    def compile_note(self, entry: str, key, cache_size: int = 64) -> bool:
        new = REGISTRY.note(entry, key, cache_size=cache_size)
        rec = self.record.compile.setdefault(entry, {"lowerings": 0, "new": 0})
        rec["lowerings"] = REGISTRY.count(entry)
        if new:
            rec["new"] += 1
        return new

    def price_compile(self, entry: str, lower) -> None:
        """Capture a FRESH lowering's XLA cost analysis (obs/cost.py).

        ``lower``: zero-arg callable returning the jitted entry's
        ``Lowered`` for the arguments about to dispatch (sites pass
        ``lambda: fn.lower(*args)``). Call ONLY when ``compile_note``
        returned fresh — that is the once-per-cache-key contract: the
        warm path (including every serving request) never re-traces,
        and the ~10 ms host-side analysis rides the cold path that
        already pays the full XLA compile. A wheel or backend that
        cannot price degrades to one typed ``cost_unavailable`` event
        per entry, never a crash.
        """
        info = cost_mod.capture(lower)
        if info is None:
            if entry not in self._cost_unavailable:
                self._cost_unavailable.add(entry)
                self.event(
                    "cost_unavailable",
                    f"XLA cost analysis unavailable for entry {entry!r} "
                    "(legacy wheel without cost_analysis(), or the "
                    "backend's analysis failed); compute-ledger floors "
                    "for this entry stay None",
                    entry=entry,
                )
            return
        REGISTRY.price(entry, info)

    def round(self, **row) -> None:
        r = self.record.rounds
        if len(r) >= self.MAX_ROUNDS:
            self.counter("rounds_dropped")
            return
        r.append(row)

    # -- profile-gated channels --------------------------------------------
    def level(self, **row) -> None:
        if not self.enabled:
            return
        rows = self.record.levels
        if len(rows) >= self.MAX_LEVEL_ROWS:
            sink = self._level_sink()
            if sink is None:
                self.counter("levels_dropped")
                return
            sink.write(json.dumps(_jsonable(row), sort_keys=True) + "\n")
            ls = self.record.level_stream
            ls["path"] = self._level_stream_path
            ls["rows"] = ls.get("rows", 0) + 1
            return
        rows.append(row)

    # -- finalization ------------------------------------------------------
    def report(self, *, tree=None, trees=None) -> dict:
        """Finalize into a plain JSON-able dict (the ``fit_report_`` value).

        ``tree``: a fitted TreeArrays — fills ``result``. ``trees``: an
        ensemble's member list — fills per-member summaries and aggregate
        ``result``. Callable repeatedly (e.g. after post-fit OOB events).
        """
        rec = self.record
        if self._level_stream_file is not None:
            # Close (not just flush) so long-lived processes don't leak
            # one fd per spilling fit; the resolved path stays, so a
            # post-report spill simply reopens in append mode.
            self._level_stream_file.close()
            self._level_stream_file = None
        rec.phases = self.summary() if self.enabled else {}
        if tree is not None:
            rec.result = {
                "n_nodes": int(tree.n_nodes),
                "depth": int(tree.max_depth),
            }
        if trees is not None:
            rec.trees = [
                {"n_nodes": int(t.n_nodes), "depth": int(t.max_depth)}
                for t in trees
            ]
            if rec.trees:
                rec.result = {
                    "n_trees": len(rec.trees),
                    "n_nodes": sum(t["n_nodes"] for t in rec.trees),
                    "depth": max(t["depth"] for t in rec.trees),
                }
        # The collective ledger (v4/v5): wire-traffic estimates derived
        # from the logical payloads and the PER-AXIS mesh widths — free
        # host arithmetic. Axis widths attribute each site's ring to the
        # axis it actually crosses (data psums vs the feature-axis winner
        # merge); records without axes fall back to the flat device count.
        rec.wire = wire_estimate(
            rec.collectives,
            rec.mesh.get("axes") or rec.mesh.get("n_devices"),
        )
        # The compute ledger (v9, obs/cost.py): join this fit's dispatched
        # entry points (everything compile_note saw — warm keys reuse the
        # registry's stored capture, the once-per-cache-key contract)
        # against the measured span walls and the platform peak table.
        # Pure host arithmetic, idempotent across repeated report() calls.
        captures = {}
        for entry in rec.compile:
            cap = REGISTRY.cost(entry)
            if cap:
                captures[entry] = cap
        if captures:
            rec.compute = cost_mod.compute_section(
                {
                    "phases": rec.phases, "collectives": rec.collectives,
                    "counters": rec.counters, "levels": rec.levels,
                    "wire": rec.wire, "mesh": rec.mesh,
                },
                captures,
                cost_mod.platform_peaks(),
            )
        # Host-tier honesty (ISSUE 20 satellite): the numpy/C++ builders
        # and the hybrid refine tail dispatch no XLA programs, so the
        # join above cannot see them — merge priced-to-None entries
        # carrying their dispatch counts, creating the section when the
        # whole fit ran on the host tier. Idempotent like the join.
        host_rows = cost_mod.host_entries(
            {"phases": rec.phases, "counters": rec.counters}
        )
        if host_rows:
            if rec.compute:
                rec.compute["entries"].update(host_rows)
            else:
                rec.compute = cost_mod.host_only_section(host_rows)
        if self._fp_hash is not None:
            # Whole-fit fold over every committed tree (obs/fingerprint):
            # hexdigest() is non-destructive, so repeated report() calls
            # (and later-committed trees) stay correct.
            rec.fingerprints["fit"] = self._fp_hash.hexdigest()
        # Whole-fit plan aggregation (ISSUE 13 satellite, the PR-12
        # follow-up): a host-loop ensemble records one plan per round;
        # the aggregate prices the fit-level peak (max per-round peak
        # plus one extra resident generation of cross-round overlap) so
        # drift checking below can re-arm instead of standing down.
        agg = None
        if len(self._fit_plans) > 1:
            agg = memory_mod.aggregate_plans(self._fit_plans)
            rec.memory["aggregate"] = agg
        if self._memwatch is not None:
            # Final watermark sample + the ledger-vs-live verdict: a
            # delta past the threshold becomes a typed event so drifting
            # pricing formulas surface in fit_report_, not just dashboards.
            self._memwatch.sample()
            live = self._memwatch.summary()
            rec.memory["live"] = live
            # Drift checking compares against the plan that actually
            # covers the sampled window: the one recorded plan for
            # single-build fits and fused multi-round dispatches, the
            # whole-fit AGGREGATE for multi-plan fits (host-loop
            # ensembles) — one per-round plan vs a live watermark
            # spanning every round fired spurious underestimates on
            # healthy fits, so PR 12 stood the check down there; the
            # aggregate re-arms it (ISSUE 13 satellite).
            estimate = (
                agg["hbm_peak_bytes"] if agg is not None
                else rec.memory.get("hbm_peak_bytes")
            )
            drift = memory_mod.drift_check(
                estimate,
                live.get("hbm_peak_delta_bytes"),
                live.get("source", "none"),
            )
            if drift is not None and not any(
                e.get("kind") == "mem_estimate_drift" for e in rec.events
            ):
                self.event(
                    "mem_estimate_drift",
                    "analytical memory ledger and live watermark diverge: "
                    f"estimate {drift['estimate_bytes']} B vs live delta "
                    f"{drift['live_delta_bytes']} B "
                    f"({drift['direction']}, ratio {drift['ratio']}; "
                    f"tolerance {drift['tolerance']}x)",
                    **drift,
                )
        out = rec.to_dict()
        if self._trace is not None:
            # Post-hoc replay: level/round rows (the fused engines' exact
            # realized-work accounting) become spans inside the live
            # ENGINE-span window (split/fused_build/...; the bin/shard
            # preamble did no level work); repeated report() calls
            # replace, never duplicate (owner-keyed).
            build = [
                w for n, w in self._trace_windows.items()
                if n in trace_mod.BUILD_PHASES
            ]
            window = (
                [min(w[0] for w in build), max(w[1] for w in build)]
                if build else self._trace_window
            )
            trace_mod.synthesize_record_tracks(
                self._trace, f"obs{self._trace_seq}", self._trace_track,
                out, window=window,
            )
            if self._trace_owned and not self._trace_failed:
                try:
                    self._trace.write()
                except OSError as e:
                    self._trace_failed = True
                    self.event(
                        "trace_failed",
                        f"trace sink unwritable at report ({e}); trace "
                        "kept in memory only",
                        path=self._trace.path,
                    )
                    out = rec.to_dict()  # carry the event out
        if not self._flight_logged and flight_mod.enabled():
            # Flight recorder (ISSUE 13): the finalized record — stamped
            # with git/platform/mesh/config lineage keys — appends to the
            # MPITREE_TPU_RUN_DIR JSONL store. Once per fit (repeated
            # report() calls refresh `out` but must not duplicate store
            # lines); sink failures degrade inside flight.append (the
            # telemetry-never-aborts contract).
            self._flight_logged = True
            flight_mod.append_record(
                out, kind=self.flight_kind, digest=record_digest(out)
            )
        return out
