"""obs.memory — the device/host memory ledger and preflight capacity planner.

The memory twin of the PR-9 ICI *wire* ledger (ISSUE 12 tentpole): every
build-state array the engines materialize has a NAME in
``parallel/partition.py``'s rule table, its global shape is a pure
function of the workload statics (rows, features, classes, bins,
depth/leaves, dtype policy, mesh axes), and its per-device cost follows
from the spec the table assigns it — so peak HBM is *computable before
dispatch*, exactly the way the wire ledger computes ICI bytes from the
logical psum payloads. Three layers ride the one pricing source:

- **the analytical ledger** (:func:`plan_fit` / :func:`plan_serve`):
  per-array per-device byte rows with per-phase watermarks, recorded
  under ``record.memory`` (schema v6) by every engine;
- **live watermark sampling** (:class:`MemWatch`): span-boundary samples
  of ``device.memory_stats()`` (where the backend provides it — TPU),
  with a live-``jax.Array`` shard-byte fallback for CPU backends, plus
  host RSS; the observer renders them as Perfetto ``mem`` counter tracks
  and logs ledger-vs-live deltas past a threshold as a typed
  ``mem_estimate_drift`` event;
- **the preflight planner** (:func:`preflight` /
  :meth:`MemoryPlan.check`): a config whose predicted peak exceeds the
  per-device budget (``MPITREE_TPU_HBM_BYTES``, or the backend's
  reported ``bytes_limit``) refuses BEFORE any device dispatch with a
  typed ``oom_predicted`` event naming the binding array and the
  smallest workable data-axis widening.

The pricing helpers below are THE one copy of every slab/pool/table
formula: ``core/builder._chunk_size`` (chunk sizing), the
sibling-subtraction carry budget gate, ``mesh.data_feature_shape`` /
``mesh.tree_data_shape`` (mesh shape policy), ``fused_rounds``'s leaf
pool guard, and the serving Pallas tier's ``fits_vmem`` all consume them
— pinned equal to their pre-refactor decisions by
``tests/test_obs_memory.py``.

Import cost: stdlib-only at module level (``math``/``os``/``dataclasses``);
jax and the partition-rule table load lazily, only when a plan is priced
or live memory sampled — so ``parallel/mesh`` can consume the pricing
helpers without an import cycle and the disabled observability path pays
nothing.
"""

from __future__ import annotations

import dataclasses
import math
import os
from mpitree_tpu.config import knobs

# record.memory carries its own sub-schema version (the top-level record
# version is obs.record.SCHEMA_VERSION): bump on any ledger field rename.
MEMORY_SCHEMA = 1

# Env knobs (documented in README "Observability v3 — memory"):
HBM_BUDGET_ENV = "MPITREE_TPU_HBM_BYTES"       # per-device preflight budget
MEM_SAMPLE_ENV = "MPITREE_TPU_MEM_SAMPLE"      # "1" = span-boundary sampling
DRIFT_TOL_ENV = "MPITREE_TPU_MEM_DRIFT_TOL"    # drift-event threshold (x)
# Host-RAM budget for streamed ingestion (ISSUE 15): the chunk size the
# ingest tier streams at is DERIVED from this via ingest_chunk_rows —
# the planner's host_peak_bytes pricing in reverse — never an ad-hoc
# row constant.
HOST_BUDGET_ENV = "MPITREE_TPU_HOST_BYTES"
HOST_INGEST_BUDGET_DEFAULT = 1 << 30

# Ledger-vs-live default drift threshold: the analytical peak prices
# TRANSIENT working sets (the split chunk histogram) that live sampling
# at span boundaries cannot see, so the estimate legitimately sits above
# the sampled resident bytes; a drift event fires only when they diverge
# by more than this FACTOR either way (underestimates are always worth an
# event — see _drift below).
DRIFT_TOL_DEFAULT = 8.0

# The serving Pallas tier's VMEM ceiling (moved here from
# serving/pallas_serve so both the kernel gate and the capacity planner
# read ONE number; pallas_serve re-exports it).
SERVE_VMEM_BUDGET_BYTES = 10 << 20

# Phase names the fit ledger prices. "resident" arrays live for the whole
# build; the others are per-phase working sets layered on top of it —
# matching the observer's span names, so the levelwise engine's live
# spans and the fused engines' single-program builds share one watermark
# vocabulary (the fused twin of the wire ledger's replay).
RESIDENT = "resident"
FIT_PHASES = ("shard", "split", "update", "leafwise", "fused_rounds")


def _round_up(x: int, m: int) -> int:
    return -(-int(x) // int(m)) * int(m)


def c_padded(n_channels: int) -> int:
    """Histogram channel axis padded to the 8-sublane TPU tile."""
    return _round_up(max(int(n_channels), 1), 8)


# ---------------------------------------------------------------------------
# pricing formulas — THE one copy each consumer reads
# ---------------------------------------------------------------------------

def chunk_bytes_per_slot(n_features: int, n_bins: int, n_channels: int,
                         *, itemsize: int = 4) -> int:
    """Live split-phase working set per frontier slot.

    The (K, F, C, B) histogram (C padded to 8 sublanes by TPU tiling)
    plus ~8 (K, F, B) accumulators from the memory-lean gain sweep —
    ``core/builder._chunk_size`` sizes the frontier chunk from exactly
    this number (``itemsize=4``: the chunk-sizing contract predates the
    f64 gbdt path and must not drift with it; the LEDGER prices the f64
    histogram via its real itemsize separately).
    """
    return int(n_features) * int(n_bins) * (
        c_padded(n_channels) * int(itemsize) + 8 * 4
    )


def slab_bytes(n_slots: int, n_features: int, n_channels: int,
               n_bins: int, *, itemsize: int = 4) -> int:
    """One resident (S, F, C, B) histogram slab — the sibling-subtraction
    carry's per-chunk buffer and the ``data_feature_shape`` policy's
    per-shard cost unit."""
    return (int(n_slots) * int(n_features) * int(n_channels)
            * int(n_bins) * int(itemsize))


def pool_hist_bytes(pool_slots: int, n_features: int, n_bins: int) -> int:
    """The fused-rounds leaf pool's (P, F, 3, B) f32 (count, g, h)
    histograms under subtraction — ``resolve_rounds_per_dispatch``'s
    budget guard reads this."""
    return int(pool_slots) * max(int(n_features), 1) * 3 * max(
        int(n_bins), 1
    ) * 4


# Chunk-scaled array -> the BuildConfig/boosting knob that shrinks it —
# what the OOM rescue rung (resilience.recovery.OomRescue, ISSUE 14)
# consults to pick a priced, on-device shrink instead of falling to the
# host tier. Resident arrays (x_binned, row state, node tables) have no
# shrink knob: only a wider data axis or the host rung helps there.
_SHRINK_KNOBS = {
    # The K-slot split working set halves with the frontier chunk.
    "split_hist_chunk": "max_frontier_chunk",
    # The sub-carry slab (kept parent histograms) drops entirely when
    # the subtraction degrades to direct accumulation.
    "parent_hist": "hist_subtraction",
}

# Arrays live only inside the fused multi-round GBDT program: the knob
# is the dispatch width — rounds_per_dispatch=1 routes the fit back to
# the host per-round loop (levelwise engine), whose working set is the
# chunked split sweep instead of the pool + margin carry.
_FUSED_ROUNDS_KNOBS = {
    "pool_hist": "rounds_per_dispatch",
    "pool_nodes": "rounds_per_dispatch",
    "pool_scalars": "rounds_per_dispatch",
    "pair_hist": "rounds_per_dispatch",
    "margin_carry": "rounds_per_dispatch",
    "grad_hess": "rounds_per_dispatch",
}


def shrink_knob(array_name: str, *, engine=None) -> str | None:
    """The knob that shrinks ``array_name``, or None (not chunk-scaled).

    ``engine``: the plan's recorded engine — the fused-rounds pool maps
    to ``rounds_per_dispatch`` only there; a single-tree leaf-wise pool
    has no shrink knob (its capacity IS the requested leaf budget).
    """
    k = _SHRINK_KNOBS.get(array_name)
    if k is not None:
        return k
    if engine == "fused_rounds":
        return _FUSED_ROUNDS_KNOBS.get(array_name)
    if array_name == "pool_hist":
        # A single-tree leaf-wise build's pool-resident histograms are
        # the subtraction carry — direct pair accumulation drops them.
        return "hist_subtraction"
    return None


def host_ingest_budget() -> int:
    """The host-RAM budget streamed chunk sizing derives from
    (``MPITREE_TPU_HOST_BYTES``, default 1 GiB)."""
    env = knobs.raw(HOST_BUDGET_ENV)
    if env:
        try:
            return max(int(env), 1 << 20)
        except ValueError:
            pass
    return HOST_INGEST_BUDGET_DEFAULT


def ingest_row_bytes(features: int) -> int:
    """Peak host bytes ONE streamed row costs while its chunk is live:
    the raw f32 slice plus its binned int32 twin (both exist during the
    bin step), doubled for the transpose/ascontiguousarray staging
    copies the binning pass makes."""
    return 2 * max(int(features), 1) * (4 + 4)


def sketch_budget_bytes(features: int, capacity: int) -> int:
    """A-priori bound on the merged quantile sketches' host cost:
    (f32 value, i64 count) pairs at full capacity per feature, doubled
    for the merge's transient concatenation."""
    return 2 * max(int(features), 1) * max(int(capacity), 1) * (4 + 8)


def ingest_chunk_rows(features: int, *, budget: int | None = None,
                      floor: int = 1024, cap: int = 1 << 22) -> int:
    """Streamed chunk size DERIVED from the host budget (ISSUE 15): the
    widest row count whose per-chunk working set (:func:`ingest_row_bytes`)
    fits the budget, clamped to [floor, cap]. The ONE sizing formula —
    ``ingest.StreamedDataset`` resolves ``chunk_rows=None`` through here
    and :func:`plan_ingest` prices exactly what it returns."""
    b = int(budget) if budget else host_ingest_budget()
    rows = b // ingest_row_bytes(features)
    return int(min(max(rows, int(floor)), int(cap)))


def plan_ingest(*, rows: int, features: int, chunk_rows: int,
                sketch_capacity: int, mesh_axes=None,
                max_bins: int = 256,
                spill_bytes: int | None = None) -> MemoryPlan:
    """Price one streamed ingest pass (the ``plan_fit`` twin for the
    loading path): per-chunk raw/binned staging, the merged sketches,
    and the host-resident per-row state (targets/weights — the one O(N)
    host cost streaming keeps), against the per-device cost of the
    assembled ``x_binned`` (priced per the partition table, plus one
    in-flight chunk piece).

    ``spill_bytes`` (ISSUE 20): bytes the spill rung wrote to disk for a
    one-shot source. Priced as its own ``"disk"``-phase array row — disk
    residency, deliberately OUTSIDE the host-RAM watermarks — and every
    extra stream pass over it (the second binning pass, the hybrid
    tail's raw-row replay, a per-round forest re-read) re-pays only the
    per-chunk staging cost (``replay_pass_bytes`` in ``inputs``), never
    an O(N) host residency: that is the whole out-of-core contract."""
    axes = _axis_widths(mesh_axes)
    rows = int(rows)
    features = int(features)
    K = int(chunk_rows)
    rows_pad = _round_up(rows, axes["data"])
    feat_pad = _round_up(features, axes["feature"])
    arrays = [
        {"name": "chunk_raw", "shape": [K, features], "itemsize": 4,
         "phase": "sketch", "bytes_per_device": 2 * K * features * 4},
        {"name": "chunk_binned", "shape": [K, features], "itemsize": 4,
         "phase": "bin_place", "bytes_per_device": 2 * K * features * 4},
        {"name": "sketch", "shape": [features, int(sketch_capacity)],
         "itemsize": 12, "phase": RESIDENT,
         "bytes_per_device": sketch_budget_bytes(
             features, sketch_capacity)},
        {"name": "y_host", "shape": [rows], "itemsize": 16,
         "phase": RESIDENT, "bytes_per_device": rows * 16},
    ]
    if spill_bytes:
        arrays.append(
            {"name": "spill_store", "shape": [int(spill_bytes)],
             "itemsize": 1, "phase": "disk",
             "bytes_per_device": int(spill_bytes)}
        )
    resident = sum(
        a["bytes_per_device"] for a in arrays if a["phase"] == RESIDENT
    )
    phases = {
        RESIDENT: resident,
        "sketch": resident + 2 * K * features * 4,
        # the bin step holds the raw chunk AND its binned twin
        "bin_place": resident + 4 * K * features * 4,
    }
    peak_phase = max(phases, key=lambda p: phases[p])
    xb_dev = _per_device_bytes(
        "x_binned", (rows_pad, feat_pad), 4, axes
    )
    return MemoryPlan(
        kind="ingest",
        mesh_axes=axes,
        arrays=arrays,
        phases=phases,
        hbm_peak_bytes=int(xb_dev + K * feat_pad * 4),
        peak_phase=peak_phase,
        host_peak_bytes=int(phases[peak_phase]),
        inputs={
            "rows": rows, "features": features, "chunk_rows": K,
            "sketch_capacity": int(sketch_capacity),
            "max_bins": int(max_bins),
            "host_budget_bytes": host_ingest_budget(),
            # what each EXTRA pass over the stream costs the host (the
            # refine replay, a spill re-read): chunk staging only.
            "replay_pass_bytes": 2 * K * features * 4,
            **({"spill_bytes": int(spill_bytes)} if spill_bytes else {}),
        },
    )


def table_bytes(n_slots: int, n_channels: int) -> int:
    """The per-level update/counts tables: one U-wide bool routing mask,
    four U-wide int32 id/bin columns, and the (U, C) f32 counts slab."""
    u = int(n_slots)
    return u * (1 + 4 * 4) + u * max(int(n_channels), 1) * 4


def node_table_bytes(n_nodes: int, value_channels: int,
                     *, value_itemsize: int = 4) -> int:
    """A serving flat node table: five parallel property columns
    (feature/left/right int32, threshold f32, depth int32) plus the
    (M, Kv) leaf-value channel."""
    m = int(n_nodes)
    return m * 5 * 4 + m * max(int(value_channels), 1) * int(value_itemsize)


def pool_capacity(max_leaf_nodes: int, max_depth, n_samples: int) -> int:
    """Open-leaf pool width for best-first growth — the arithmetic twin
    of ``core/leafwise_builder._pool_capacity`` (kept here jax-free so
    the planner can price leaf pools without importing the engine; the
    identity is pinned by ``tests/test_obs_memory.py``)."""
    p = int(max_leaf_nodes)
    if max_depth is not None and int(max_depth) < 31:
        p = min(p, 2 ** max(int(max_depth), 0))
    return max(min(p, max(int(n_samples), 1)), 1)


def feature_shards_for_budget(hist_bytes: int, hist_budget,
                              usable: list) -> int:
    """The 2-D mesh policy's feature-shard engagement threshold: the
    narrowest usable feature divisor whose per-shard slab
    (``hist_bytes / f``) fits ``hist_budget`` — degrading to the widest
    divisor when none fits (never refuse). Extracted verbatim from
    ``mesh.data_feature_shape`` so the shape policy and the capacity
    planner can never disagree about when feature sharding engages."""
    f = 1
    if hist_budget:
        while f < max(usable) and int(hist_bytes) > int(hist_budget) * f:
            f = min(k for k in usable if k > f)
    return f


def tree_shards_for_budget(tree_shards: int, dataset_bytes: int,
                           hbm_budget, divisors: list,
                           n_devices: int) -> int:
    """The forest mesh policy's HBM guard: trade tree-axis width for row
    sharding while the replicated binned matrix would exceed the
    per-device budget (extracted verbatim from
    ``mesh.tree_data_shape``)."""
    t = int(tree_shards)
    if hbm_budget:
        while t > 1 and int(dataset_bytes) > int(hbm_budget) * (
            int(n_devices) // t
        ):
            t = max(k for k in divisors if k < t)
    return t


def serve_kernel_row_tile(n_nodes_max: int, n_features: int, kv: int,
                          n_out: int,
                          budget: int = SERVE_VMEM_BUDGET_BYTES,
                          quantized: bool = False) -> int | None:
    """Largest serving-kernel row tile whose VMEM working set fits
    ``budget`` (the persistent out block + one tree's table/value blocks
    + the one-hot working set), or None — the ONE copy of the arithmetic
    ``serving.pallas_serve.kernel_row_tile``/``fits_vmem`` gate on.

    ``quantized=True`` prices the quantized kernel's residency (ISSUE
    17): bf16 split-byte tables (2 bytes/cell), RAW int8 lattice value
    blocks (1 byte/cell — the affine dequant runs after the kernel),
    and node one-hots in the table dtype (bf16/int8 — exact 0/1 either
    way), while the query/descent working set stays f32. Per padded
    node that is 8*2 + kv*1 resident + rt*2 one-hot vs the f32 tier's
    8*4 + kv*4 + rt*4 — which is why the VMEM tier's node budget
    stretches PAST 2x under quantization.
    """
    mp = _round_up(max(n_nodes_max, 1), 128)
    fp = _round_up(max(n_features, 1), 8)
    cell_t = 2 if quantized else 4   # table: bf16 vs f32
    cell_v = 1 if quantized else 4   # values: int8 lattice vs f32
    cell_o = 2 if quantized else 4   # node one-hot rides the table dtype
    blocks = mp * (8 * cell_t + _round_up(max(kv, 1), 8) * cell_v)
    for rt in (1024, 512, 256, 128, 64, 8):
        work = rt * (mp * cell_o + (2 * fp + 4 + max(n_out, 1)) * 4)
        if blocks + work <= budget:
            return rt
    return None


def serve_fits_vmem(n_nodes_max: int, n_features: int, kv: int,
                    n_out: int, quantized: bool = False) -> bool:
    return serve_kernel_row_tile(
        n_nodes_max, n_features, kv, n_out, quantized=quantized
    ) is not None


# ---------------------------------------------------------------------------
# the plan
# ---------------------------------------------------------------------------

class MemoryPlanError(ValueError):
    """Preflight refusal: the predicted per-device peak exceeds the HBM
    budget. Carries the binding array and the planner's suggestion so the
    caller (and the typed ``oom_predicted`` event) can say exactly what
    to change."""

    def __init__(self, message: str, *, binding_array: str,
                 suggestion: str):
        super().__init__(message)
        self.binding_array = binding_array
        self.suggestion = suggestion


def _axis_widths(mesh_axes) -> dict:
    """Normalize a mesh description into ``{"data": dr, "feature": df}``.

    Accepts an axes dict (``record.mesh['axes']`` shape), a plain int
    (1-D data mesh), a ``(dr, df)`` tuple, or None (single device).
    """
    if mesh_axes is None:
        return {"data": 1, "feature": 1}
    if isinstance(mesh_axes, dict):
        return {
            "data": max(int(mesh_axes.get("data", 1)), 1),
            "feature": max(int(mesh_axes.get("feature", 1)), 1),
        }
    if isinstance(mesh_axes, (tuple, list)):
        dr = int(mesh_axes[0]) if len(mesh_axes) > 0 else 1
        df = int(mesh_axes[1]) if len(mesh_axes) > 1 else 1
        return {"data": max(dr, 1), "feature": max(df, 1)}
    return {"data": max(int(mesh_axes), 1), "feature": 1}


def _spec_axes(name: str, ndim: int) -> tuple:
    """Per-dimension axis names for ``name`` from the partition-rule
    table (lazy import: ``parallel.partition`` pulls jax). Unknown names
    and import failures fall back to replicated — the ledger must price
    in any environment."""
    try:
        from mpitree_tpu.parallel import partition

        spec = partition.match_partition_rules(name, ndim=ndim)
    except Exception:
        return (None,) * ndim
    axes = tuple(spec) + (None,) * (ndim - len(tuple(spec)))
    return axes[:ndim]


def _per_device_bytes(name: str, shape: tuple, itemsize: int,
                      axes: dict) -> int:
    """Bytes per device for a named global array: each dimension the
    rule table shards divides (padded) by its axis width."""
    total = int(itemsize)
    for dim, axis in zip(shape, _spec_axes(name, len(shape))):
        w = axes.get(axis, 1) if axis is not None else 1
        total *= -(-int(dim) // max(int(w), 1))
    return total


@dataclasses.dataclass
class MemoryPlan:
    """The priced ledger: per-array rows, per-phase watermarks, peaks.

    ``arrays``: ``{name, shape, itemsize, phase, bytes_per_device}``
    rows (phase ``"resident"`` = alive for the whole build).
    ``phases``: per-phase per-device watermark = resident + that phase's
    working set. ``hbm_peak_bytes`` = max watermark; ``peak_phase`` its
    phase; ``host_peak_bytes`` the host-RAM side (raw + binned matrix +
    per-row state — the out-of-core chunk-sizing input, ROADMAP item 1).
    """

    kind: str
    mesh_axes: dict
    arrays: list
    phases: dict
    hbm_peak_bytes: int
    peak_phase: str
    host_peak_bytes: int
    inputs: dict

    def to_dict(self) -> dict:
        return {
            "schema": MEMORY_SCHEMA,
            "kind": self.kind,
            "mesh_axes": dict(self.mesh_axes),
            "arrays": [dict(a) for a in self.arrays],
            "phases": dict(self.phases),
            "hbm_peak_bytes": int(self.hbm_peak_bytes),
            "peak_phase": self.peak_phase,
            "host_peak_bytes": int(self.host_peak_bytes),
            "inputs": dict(self.inputs),
        }

    def top(self, k: int = 5) -> list:
        """The k largest per-device arrays — what the OOM postmortem and
        the ``oom_predicted`` refusal name."""
        return sorted(
            self.arrays, key=lambda a: -a["bytes_per_device"]
        )[:k]

    def binding_array(self) -> dict | None:
        """The largest array alive in the peak phase (the one a smaller
        config must shrink first)."""
        live = [
            a for a in self.arrays
            if a["phase"] in (RESIDENT, self.peak_phase)
        ] or self.arrays
        return max(live, key=lambda a: a["bytes_per_device"], default=None)

    def suggestion(self, budget: int) -> str:
        """Smallest workable change: the data-axis widening that brings
        the peak under ``budget`` (row-sharded arrays scale down with
        it), else a chunk/budget knob hint — what the refusal message
        carries."""
        dr = self.mesh_axes.get("data", 1)
        scalable = sum(
            a["bytes_per_device"] for a in self.arrays
            if "data" in _spec_axes(a["name"], len(a["shape"]))
            and a["phase"] in (RESIDENT, self.peak_phase)
        )
        fixed = max(self.hbm_peak_bytes - scalable, 0)
        for widen in (2, 4, 8, 16, 32, 64, 128):
            if fixed + scalable / widen <= budget:
                return (
                    f"widen the data axis to {dr * widen} shards "
                    f"(predicted peak ~{int(fixed + scalable / widen) >> 20}"
                    " MiB/device)"
                )
        return (
            "no data-axis widening (up to 128x) fits; shrink the workload "
            "or lower hist_budget_bytes/max_frontier_chunk so smaller "
            "chunks bound the histogram working set"
        )

    def check(self, budget=None, *, obs=None, what: str = "fit") -> None:
        """Preflight: raise :class:`MemoryPlanError` (after recording a
        typed ``oom_predicted`` event on ``obs``) when the predicted
        per-device peak exceeds ``budget`` (None = no known budget, no
        check — the degrade-never-guess stance on backends that report
        nothing)."""
        if not budget or self.hbm_peak_bytes <= int(budget):
            return
        binding = self.binding_array() or {"name": "?", "bytes_per_device": 0}
        suggestion = self.suggestion(int(budget))
        msg = (
            f"predicted per-device peak {self.hbm_peak_bytes >> 20} MiB "
            f"exceeds the {int(budget) >> 20} MiB HBM budget for this "
            f"{what} (peak phase {self.peak_phase!r}; binding array "
            f"{binding['name']!r} at "
            f"{binding['bytes_per_device'] >> 20} MiB/device); "
            f"{suggestion}. Refusing before dispatch — override with a "
            f"larger {HBM_BUDGET_ENV} if the budget is wrong."
        )
        if obs is not None:
            obs.event(
                "oom_predicted", msg,
                binding_array=binding["name"],
                binding_bytes=int(binding["bytes_per_device"]),
                hbm_peak_bytes=int(self.hbm_peak_bytes),
                budget_bytes=int(budget),
                top=[
                    {"name": a["name"], "bytes": int(a["bytes_per_device"])}
                    for a in self.top(5)
                ],
            )
        raise MemoryPlanError(
            msg, binding_array=binding["name"], suggestion=suggestion,
        )


def _widest_frontier(rows: int, max_depth) -> int:
    w = int(rows)
    if max_depth is not None and int(max_depth) < 31:
        w = min(w, 2 ** int(max_depth))
    return max(w, 1)


def default_chunk_slots(rows: int, f_shard: int, bins: int, channels: int,
                        *, hist_budget_bytes: int = 4 << 30,
                        max_frontier_chunk: int = 4096,
                        max_depth=None) -> int:
    """Mirror of ``core/builder._chunk_size`` on the shared pricing
    formula (identity pinned) — lets :func:`plan_fit` price a build
    before the builder has resolved its own chunk width."""
    per_node = chunk_bytes_per_slot(f_shard, bins, channels)
    cap = max(1, int(hist_budget_bytes) // max(per_node, 1))
    cap = min(cap, int(max_frontier_chunk))
    widest = _widest_frontier(rows, max_depth)
    want = 1 << max(0, math.ceil(math.log2(max(widest, 1))))
    return min(want, 1 << int(math.log2(cap)))


def plan_fit(*, rows: int, features: int, classes: int = 2,
             bins: int = 256, task: str = "classification",
             max_depth=None, max_leaf_nodes=None, mesh_axes=None,
             gbdt_x64: bool = False, subtraction: bool = False,
             chunk_slots: int | None = None,
             table_slots: int | None = None,
             hist_budget_bytes: int = 4 << 30,
             max_frontier_chunk: int = 4096,
             max_table_slots: int = 1 << 17,
             rounds_per_dispatch: int = 1,
             n_out: int = 1,
             engine: str | None = None,
             streamed: bool = False,
             streamed_chunk_rows: int | None = None) -> MemoryPlan:
    """Price one fit's build-state arrays into a :class:`MemoryPlan`.

    Every argument is a workload STATIC (nothing here touches a device):
    the same inputs ``core/builder.build_tree`` resolves before its first
    dispatch, which is what makes this a *preflight* — callable from a
    notebook with nothing but the intended shapes. ``mesh_axes`` follows
    :func:`_axis_widths`'s grammar; ``engine`` is recorded verbatim for
    attribution.
    """
    axes = _axis_widths(mesh_axes)
    dr, df = axes["data"], axes["feature"]
    C = int(classes) if task == "classification" else 3
    rows = int(rows)
    features = int(features)
    bins = int(bins)
    rows_pad = _round_up(rows, dr)
    feat_pad = _round_up(features, df)
    f_shard = feat_pad // df
    hist_itemsize = 8 if gbdt_x64 else 4
    K = (int(chunk_slots) if chunk_slots else default_chunk_slots(
        rows, f_shard, bins, C, hist_budget_bytes=hist_budget_bytes,
        max_frontier_chunk=max_frontier_chunk, max_depth=max_depth,
    ))
    widest = _widest_frontier(rows, max_depth)
    U = (int(table_slots) if table_slots else
         1 << max(0, math.ceil(math.log2(min(widest, int(max_table_slots))))))

    arrays: list = []

    def add(name, shape, itemsize, phase, *, bytes_per_device=None):
        b = (_per_device_bytes(name, shape, itemsize, axes)
             if bytes_per_device is None else int(bytes_per_device))
        arrays.append({
            "name": name, "shape": [int(s) for s in shape],
            "itemsize": int(itemsize), "phase": phase,
            "bytes_per_device": int(b),
        })

    # Resident build state (alive from shard to finalize) — named per the
    # partition table, so per-device division follows the same rules the
    # engines' shard_map in_specs do.
    add("x_binned", (rows_pad, feat_pad), 4, RESIDENT)
    add("y", (rows_pad,), 4, RESIDENT)
    add("weight", (rows_pad,), 4, RESIDENT)
    add("node_id", (rows_pad,), 4, RESIDENT)
    add("cand_mask", (feat_pad, bins), 1, RESIDENT)

    fused_gbdt = task == "gbdt" and int(rounds_per_dispatch) > 1
    if max_leaf_nodes is not None:
        # Best-first growth: the statically-shaped open-leaf pool replaces
        # the level-wise chunk sweep — pool scalars, the 2P-1 node
        # arrays, and (under subtraction) the pool-resident histograms.
        # Inside a fused multi-round GBDT program the pool lives in the
        # SAME compiled dispatch as the margin carry, so its arrays join
        # the fused_rounds phase — pricing them as separate watermarks
        # would let a near-budget config pass preflight and OOM live.
        ph = "fused_rounds" if fused_gbdt else "leafwise"
        Pn = pool_capacity(max_leaf_nodes, max_depth, rows)
        add("pool_scalars", (Pn, 6), 4, ph, bytes_per_device=Pn * 24)
        add("pool_nodes", (2 * Pn - 1, 10 + C), 4, ph,
            bytes_per_device=(2 * Pn - 1) * (10 + C) * 4)
        if subtraction:
            add("pool_hist", (Pn, f_shard, C, bins), hist_itemsize,
                ph,
                bytes_per_device=slab_bytes(
                    Pn, f_shard, C, bins, itemsize=hist_itemsize))
        # One sibling-pair histogram per expansion (the compact
        # small-child buffer under subtraction).
        pair = 1 if subtraction else 2
        add("pair_hist", (pair, f_shard, C, bins), hist_itemsize,
            ph,
            bytes_per_device=slab_bytes(
                pair, f_shard, C, bins, itemsize=hist_itemsize))
    else:
        # Level-synchronous engines: the K-slot split working set plus
        # (under subtraction) the kept-parent carry, budget-gated at the
        # same hist_budget_bytes that sized the live chunk.
        add("split_hist_chunk", (K, f_shard, C, bins), hist_itemsize,
            "split",
            bytes_per_device=K * chunk_bytes_per_slot(
                f_shard, bins, C, itemsize=hist_itemsize))
        if subtraction:
            n_chunks = -(-widest // K)
            carry = min(
                int(hist_budget_bytes),
                n_chunks * slab_bytes(
                    K, f_shard, C, bins, itemsize=hist_itemsize),
            )
            add("parent_hist", (n_chunks, K, f_shard, C, bins),
                hist_itemsize, "split", bytes_per_device=carry)
        add("update_tables", (U,), 4, "update",
            bytes_per_device=table_bytes(U, C))

    if fused_gbdt:
        # Fused multi-round GBDT: the donated f32 margin carry rides the
        # whole dispatch (in + out generation live across the scan
        # boundary), plus the in-program (g, h) recompute. Both are
        # row-sharded like every per-row array — explicit bytes because
        # neither name appears in the partition table (the program
        # derives their placement from the carry's in_specs).
        add("margin_carry", (rows_pad, max(int(n_out), 1)), 4,
            "fused_rounds",
            bytes_per_device=2 * (-(-rows_pad // dr))
            * max(int(n_out), 1) * 4)
        add("grad_hess", (rows_pad, 2), 4, "fused_rounds",
            bytes_per_device=(-(-rows_pad // dr)) * 2 * 4)

    resident = sum(
        a["bytes_per_device"] for a in arrays if a["phase"] == RESIDENT
    )
    phases = {RESIDENT: resident}
    for ph in FIT_PHASES:
        extra = sum(
            a["bytes_per_device"] for a in arrays if a["phase"] == ph
        )
        if extra:
            phases[ph] = resident + extra
    peak_phase = max(phases, key=lambda p: phases[p])
    if streamed:
        # Streamed-ingest pricing mode (ISSUE 15): the raw/binned
        # matrices never exist on host — the host side is per-row state
        # plus one live chunk's staging, which is exactly what
        # ingest_chunk_rows sized against the host budget.
        K_ing = (int(streamed_chunk_rows) if streamed_chunk_rows
                 else ingest_chunk_rows(features))
        host_peak = rows * 16 + K_ing * ingest_row_bytes(features)
    else:
        host_peak = (
            rows * features * 4      # the raw f32 matrix
            + rows * features * 4    # the binned int32 copy
            + rows * 16              # y/weight/node_id/leaf_ids host state
        )
    return MemoryPlan(
        kind="fit",
        mesh_axes=axes,
        arrays=arrays,
        phases=phases,
        hbm_peak_bytes=int(phases[peak_phase]),
        peak_phase=peak_phase,
        host_peak_bytes=int(host_peak),
        inputs={
            "rows": rows, "features": features, "classes": int(classes),
            "bins": bins, "task": task,
            "max_depth": None if max_depth is None else int(max_depth),
            "max_leaf_nodes": (
                None if max_leaf_nodes is None else int(max_leaf_nodes)
            ),
            "chunk_slots": int(K), "table_slots": int(U),
            "gbdt_x64": bool(gbdt_x64), "subtraction": bool(subtraction),
            "rounds_per_dispatch": int(rounds_per_dispatch),
            "engine": engine,
            # Only stamped on streamed fits: absent == in-memory, so
            # every pre-ISSUE-15 record keeps its lineage digest.
            **({"streamed": True} if streamed else {}),
        },
    )


def plan_forest(*, n_trees: int, rows: int, features: int,
                classes: int = 2, bins: int = 256,
                task: str = "classification", max_depth=None,
                tree_shards: int = 1, data_shards: int = 1,
                subtraction: bool = False,
                chunk_slots: int | None = None,
                node_capacity: int | None = None,
                hist_budget_bytes: int = 4 << 30,
                max_frontier_chunk: int = 4096) -> MemoryPlan:
    """Price a tree-sharded forest build (``build_forest_fused``) — the
    PR-12 gap: single-tree, leaf-wise, gbdt and serving all recorded a
    plan, the forest engines did not (ISSUE 13 satellite).

    Per-device division follows ``parallel/partition.py``'s tree-axis
    rules: per-tree operand stacks (``tree_weights`` / ``tree_cand_masks``
    / ``tree_nodes``) shard their leading axis over the ``tree`` axis,
    per-row state and the binned matrix shard over ``data`` (replicated
    when the forest mesh carries no data axis — exactly the engine's
    ``data_sharded`` placement switch). Each device's ``lax.map`` builds
    its tree group SEQUENTIALLY, so the split working set is one tree's —
    not the group's — chunk histogram.
    """
    Dt = max(int(tree_shards), 1)
    Dd = max(int(data_shards), 1)
    axes = {"tree": Dt, "data": Dd}
    C = int(classes) if task == "classification" else 3
    rows_pad = _round_up(int(rows), Dd)
    T_pad = _round_up(int(n_trees), Dt)
    K = (int(chunk_slots) if chunk_slots else default_chunk_slots(
        rows, int(features), int(bins), C,
        hist_budget_bytes=hist_budget_bytes,
        max_frontier_chunk=max_frontier_chunk, max_depth=max_depth,
    ))
    M = (int(node_capacity) if node_capacity else min(
        (2 ** (int(max_depth) + 1) - 1) if max_depth is not None
        and int(max_depth) < 31 else 2 * int(rows) - 1,
        2 * int(rows) - 1,
    ))

    arrays: list = []

    def add(name, shape, itemsize, phase, *, bytes_per_device=None):
        b = (_per_device_bytes(name, shape, itemsize, axes)
             if bytes_per_device is None else int(bytes_per_device))
        arrays.append({
            "name": name, "shape": [int(s) for s in shape],
            "itemsize": int(itemsize), "phase": phase,
            "bytes_per_device": int(b),
        })

    add("x_binned", (rows_pad, int(features)), 4, RESIDENT)
    add("y", (rows_pad,), 4, RESIDENT)
    add("node_id", (rows_pad,), 4, RESIDENT)
    add("tree_weights", (T_pad, rows_pad), 4, RESIDENT)
    add("tree_cand_masks", (T_pad, int(features), int(bins)), 1, RESIDENT)
    # Device-resident node buffers: feature/bin/left/parent int32 columns,
    # the (C or 3)-wide counts slab, and n/value — ~10 + C words per node.
    add("tree_nodes", (T_pad, M, 10 + C), 4, RESIDENT)
    # One tree's split working set at a time (sequential lax.map body).
    add("split_hist_chunk", (K, int(features), C, int(bins)), 4, "split",
        bytes_per_device=K * chunk_bytes_per_slot(
            int(features), int(bins), C))
    if subtraction:
        widest = _widest_frontier(int(rows), max_depth)
        n_chunks = -(-widest // K)
        add("parent_hist", (n_chunks, K, int(features), C, int(bins)), 4,
            "split",
            bytes_per_device=min(
                int(hist_budget_bytes),
                n_chunks * slab_bytes(K, int(features), C, int(bins)),
            ))

    resident = sum(
        a["bytes_per_device"] for a in arrays if a["phase"] == RESIDENT
    )
    phases = {RESIDENT: resident}
    split_extra = sum(
        a["bytes_per_device"] for a in arrays if a["phase"] == "split"
    )
    if split_extra:
        phases["split"] = resident + split_extra
    peak_phase = max(phases, key=lambda p: phases[p])
    host_peak = (
        int(rows) * int(features) * 8      # raw + binned matrix
        + int(n_trees) * int(rows) * 4     # per-tree bootstrap weights
        + int(rows) * 16                   # row state
    )
    return MemoryPlan(
        kind="forest",
        mesh_axes=axes,
        arrays=arrays,
        phases=phases,
        hbm_peak_bytes=int(phases[peak_phase]),
        peak_phase=peak_phase,
        host_peak_bytes=int(host_peak),
        inputs={
            "n_trees": int(n_trees), "rows": int(rows),
            "features": int(features), "classes": int(classes),
            "bins": int(bins), "task": task,
            "max_depth": None if max_depth is None else int(max_depth),
            "tree_shards": Dt, "data_shards": Dd,
            "chunk_slots": int(K), "node_capacity": int(M),
            "subtraction": bool(subtraction),
            "engine": "forest_fused",
        },
    )


def aggregate_plans(plans: list) -> dict:
    """Whole-fit aggregation of a multi-plan fit (ISSUE 13 satellite —
    the PR-12 follow-up): the host boosting loop records one plan per
    round, so drift checking had nothing fit-shaped to compare the
    whole-fit live watermark against and stood down.

    The aggregate's per-phase watermark is the max across rounds; the
    fit-level peak adds ONE extra resident generation on top — the host
    loop places round ``r+1``'s shards before round ``r``'s buffers are
    collected, so at the placement boundary two generations of resident
    build state briefly coexist (more than two would mean a leak, which
    is exactly what the re-armed underestimate check now catches).
    """
    plans = [p if isinstance(p, dict) else p.to_dict() for p in plans]
    peaks = [int(p.get("hbm_peak_bytes") or 0) for p in plans]
    binding = plans[peaks.index(max(peaks))]
    resident = int((binding.get("phases") or {}).get(RESIDENT, 0))
    phases: dict = {}
    for p in plans:
        for ph, v in (p.get("phases") or {}).items():
            phases[ph] = max(int(phases.get(ph, 0)), int(v))
    return {
        "schema": MEMORY_SCHEMA,
        "kind": "fit_aggregate",
        "rounds": len(plans),
        "phases": phases,
        "hbm_peak_bytes": max(peaks) + resident,
        "peak_phase": binding.get("peak_phase"),
        "host_peak_bytes": max(
            int(p.get("host_peak_bytes") or 0) for p in plans
        ),
        "inputs": dict(binding.get("inputs") or {}),
    }


def plan_serve(*, n_trees: int, n_nodes_total: int, n_nodes_max: int,
               n_features: int, value_channels: int, n_out: int,
               buckets=(1, 64, 4096), x64: bool = False,
               kernel: bool = False, quantized: bool = False) -> MemoryPlan:
    """Price a serving model's device residency (the ``plan_fit`` twin
    for the request path): the flat node table + leaf-value channels
    (resident from publish), the largest bucket's query/accumulator
    working set, the optional VMEM-tier stacked tables, and the Pallas
    VMEM verdict itself (:func:`serve_kernel_row_tile`).

    ``quantized=True`` (ISSUE 17) prices the compressed tables: bf16
    thresholds + int16 feature ids shrink the flat table from 5 f32
    columns to the 3 f32 id columns plus two 2-byte ones, leaf values
    ride int8 deltas (+ per-channel f32 scale/base), and the VMEM-tier
    stacked blocks are bf16."""
    val_item = 8 if x64 else 4
    bmax = max(int(b) for b in buckets) if buckets else 1
    kv = max(int(value_channels), 1)
    if quantized:
        # left/right/orig stay int32 (absolute ids outgrow int16);
        # feature int16 + threshold bf16 compress the other two columns.
        table_bytes = int(n_nodes_total) * (3 * 4 + 2 * 2)
        value_bytes = int(n_nodes_total) * kv * 1 + 2 * kv * 4
    else:
        table_bytes = int(n_nodes_total) * 5 * 4
        value_bytes = int(n_nodes_total) * kv * val_item
    arrays = [
        {
            "name": "node_table", "shape": [int(n_nodes_total), 5],
            "itemsize": 2 if quantized else 4, "phase": RESIDENT,
            "bytes_per_device": table_bytes,
        },
        {
            "name": "leaf_values", "shape": [int(n_nodes_total), kv],
            "itemsize": 1 if quantized else val_item, "phase": RESIDENT,
            "bytes_per_device": value_bytes,
        },
        {
            "name": "query_batch", "shape": [bmax, int(n_features)],
            "itemsize": 4, "phase": "dispatch",
            "bytes_per_device": bmax * int(n_features) * 4,
        },
        {
            "name": "accumulator", "shape": [bmax, max(int(n_out), 1)],
            "itemsize": val_item, "phase": "dispatch",
            "bytes_per_device": bmax * max(int(n_out), 1) * val_item,
        },
    ]
    rt = serve_kernel_row_tile(
        n_nodes_max, n_features, kv, n_out, quantized=quantized
    )
    if kernel:
        mp = _round_up(max(int(n_nodes_max), 1), 128)
        kvp = _round_up(kv, 8)
        cell = 2 if quantized else 4
        arrays.append({
            "name": "kernel_tables",
            "shape": [int(n_trees), 8 + kvp, mp], "itemsize": cell,
            "phase": RESIDENT,
            "bytes_per_device": int(n_trees) * (8 + kvp) * mp * cell,
        })
    resident = sum(
        a["bytes_per_device"] for a in arrays if a["phase"] == RESIDENT
    )
    dispatch = resident + sum(
        a["bytes_per_device"] for a in arrays if a["phase"] == "dispatch"
    )
    phases = {RESIDENT: resident, "dispatch": dispatch}
    return MemoryPlan(
        kind="serve",
        mesh_axes={"data": 1, "feature": 1},
        arrays=arrays,
        phases=phases,
        hbm_peak_bytes=int(dispatch),
        peak_phase="dispatch",
        host_peak_bytes=int(n_nodes_total) * (5 * 4 + kv * val_item),
        inputs={
            "n_trees": int(n_trees),
            "n_nodes_total": int(n_nodes_total),
            "n_nodes_max": int(n_nodes_max),
            "n_features": int(n_features),
            "value_channels": kv, "n_out": int(n_out),
            "buckets": [int(b) for b in buckets],
            "x64": bool(x64), "kernel": bool(kernel),
            "quantized": bool(quantized),
            "vmem_row_tile": rt,
            "vmem_fits": rt is not None,
            "vmem_budget_bytes": SERVE_VMEM_BUDGET_BYTES,
        },
    )


# ---------------------------------------------------------------------------
# budgets + preflight
# ---------------------------------------------------------------------------

def device_hbm_budget(device=None) -> int | None:
    """The per-device HBM budget the preflight checks against:
    ``MPITREE_TPU_HBM_BYTES`` wins (the operator knows best); else the
    backend's reported ``bytes_limit`` (TPU runtimes provide it; CPU
    backends report nothing → None → no refusal — the planner never
    guesses a budget)."""
    env = knobs.raw(HBM_BUDGET_ENV)
    if env:
        try:
            return int(env)
        except ValueError:
            return None
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
        stats = dev.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        return None
    return None


def preflight(plan: MemoryPlan, *, obs=None, what: str = "fit",
              device=None) -> None:
    """Refuse an impossible config BEFORE dispatch (the planner's public
    gate): no-op when no budget is known."""
    plan.check(device_hbm_budget(device), obs=obs, what=what)


# ---------------------------------------------------------------------------
# live watermark sampling
# ---------------------------------------------------------------------------

def host_rss_bytes() -> int | None:
    """Host resident-set size, or None where unreadable."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(kb) * 1024
    except Exception:
        return None


def live_hbm_bytes(device=None) -> tuple:
    """(bytes, source) for one device's live allocation.

    Prefers the backend's ``memory_stats()['bytes_in_use']`` (TPU);
    CPU backends fall back to summing the shard bytes of every live
    ``jax.Array`` addressable on that device (``"live_arrays"`` — sees
    resident arrays only, not XLA scratch; the drift tolerance accounts
    for it). (0, "none") when nothing is measurable."""
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
    except Exception:
        return 0, "none"
    try:
        stats = dev.memory_stats()
        if stats and stats.get("bytes_in_use") is not None:
            return int(stats["bytes_in_use"]), "memory_stats"
    except Exception:
        pass
    total = 0
    try:
        import gc

        import jax

        # The fallback counts python-held arrays: collect first, or
        # cycle-retained garbage from earlier levels/fits (dead carry
        # buffers waiting on the gc) inflates "live" several-fold.
        # Opt-in sampling at span boundaries only, so the collect's
        # milliseconds never touch a production path.
        gc.collect()
        for a in jax.live_arrays():
            try:
                for shard in a.addressable_shards:
                    if shard.device == dev:
                        total += int(shard.data.nbytes)
            except Exception:
                continue
    except Exception:
        return 0, "none"
    return total, "live_arrays"


class MemWatch:
    """Span-boundary live-memory watermark tracker.

    One instance per observer (``BuildObserver.watch_memory`` /
    ``MPITREE_TPU_MEM_SAMPLE=1``): the observer calls :meth:`sample` at
    every span close — never inside a device program — and the summary
    lands in ``record.memory['live']``. The baseline is the first
    sample, so ``hbm_peak_delta_bytes`` is what THIS fit added on top of
    whatever the process already held."""

    def __init__(self, device=None):
        self.device = device
        self.source = "none"
        self.samples = 0
        self.hbm_baseline: int | None = None
        self.hbm_peak = 0
        self.host_peak = 0
        # The most recent raw readings — what the Perfetto counter track
        # plots (the peaks above are a cummax and would render a flat
        # high line that can never show memory being released).
        self.hbm_last = 0
        self.host_last = 0

    def sample(self) -> None:
        hbm, source = live_hbm_bytes(self.device)
        if source != "none":
            self.source = source
            self.hbm_last = hbm
            if self.hbm_baseline is None:
                self.hbm_baseline = hbm
            self.hbm_peak = max(self.hbm_peak, hbm)
        rss = host_rss_bytes()
        if rss:
            self.host_last = rss
            self.host_peak = max(self.host_peak, rss)
        self.samples += 1

    def summary(self) -> dict:
        base = self.hbm_baseline or 0
        return {
            "source": self.source,
            "samples": int(self.samples),
            "hbm_baseline_bytes": int(base),
            "hbm_peak_bytes": int(self.hbm_peak),
            "hbm_peak_delta_bytes": int(max(self.hbm_peak - base, 0)),
            "host_peak_bytes": int(self.host_peak),
        }


def drift_tolerance() -> float:
    try:
        return float(knobs.raw(DRIFT_TOL_ENV) or DRIFT_TOL_DEFAULT)
    except ValueError:
        return DRIFT_TOL_DEFAULT


def drift_check(estimate: int | None, live_delta: int | None,
                source: str = "memory_stats") -> dict | None:
    """Ledger-vs-live verdict; a dict of event fields when the delta
    crosses the threshold, else None.

    An UNDERESTIMATE — live measurably above the analytical peak, >25%
    — reports on every source (the ledger's one unforgivable failure
    mode: a preflight that said "fits" while the device filled up). An
    OVERESTIMATE reports only past the tolerance factor AND only on the
    ``memory_stats`` source: the ``live_arrays`` fallback sees resident
    python-held arrays, not XLA scratch, so the analytical peak (which
    prices the transient chunk working set) legitimately sits well above
    it."""
    if not estimate or live_delta is None or live_delta <= 0:
        return None
    tol = drift_tolerance()
    ratio = estimate / live_delta
    over = source == "memory_stats" and ratio > tol
    under = ratio < 0.8
    if not (over or under):
        return None
    return {
        "estimate_bytes": int(estimate),
        "live_delta_bytes": int(live_delta),
        "ratio": round(ratio, 3),
        "tolerance": tol,
        "source": source,
        "direction": "underestimate" if under else "overestimate",
    }
