"""Event-registry doc tooling: ``python -m mpitree_tpu.obs``.

- ``--markdown`` prints the registry as the README's events section.
- ``--check [README]`` extracts the section between the
  ``<!-- event-table:begin -->`` / ``<!-- event-table:end -->`` markers
  and exits 1 when it differs from the generated one — the CI drift gate
  (``make event-check``) that keeps docs and registry one source, the
  knob-table gate's twin.
- ``--write [README]`` rewrites that section in place (the update path a
  contributor runs after registering an event or decision).
"""

from __future__ import annotations

import argparse
import os
import sys

from mpitree_tpu.obs import events

BEGIN = "<!-- event-table:begin -->"
END = "<!-- event-table:end -->"
_DEFAULT_README = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "README.md"
)


def _split_readme(text: str):
    try:
        head, rest = text.split(BEGIN, 1)
        table, tail = rest.split(END, 1)
    except ValueError:
        return None
    return head, table, tail


def main(argv: list | None = None) -> int:
    parser = argparse.ArgumentParser(prog="python -m mpitree_tpu.obs")
    group = parser.add_mutually_exclusive_group(required=True)
    group.add_argument(
        "--markdown", action="store_true",
        help="print the events section generated from the registry",
    )
    group.add_argument(
        "--check", nargs="?", const=_DEFAULT_README, metavar="README",
        help="fail (exit 1) when the README events section drifts from "
        "the registry",
    )
    group.add_argument(
        "--write", nargs="?", const=_DEFAULT_README, metavar="README",
        help="rewrite the README events section from the registry",
    )
    args = parser.parse_args(argv)

    table = events.markdown_table()
    if args.markdown:
        print(table, end="")
        return 0

    path = args.check or args.write
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    parts = _split_readme(text)
    if parts is None:
        print(
            f"event-table markers ({BEGIN} / {END}) not found in {path}",
            file=sys.stderr,
        )
        return 1
    head, current, tail = parts

    if args.write:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(f"{head}{BEGIN}\n{table}{END}{tail}")
        print(f"events section rewritten in {path}", file=sys.stderr)
        return 0

    if current.strip() != table.strip():
        print(
            f"README events section in {path} drifted from the registry "
            "— run `python -m mpitree_tpu.obs --write` to regenerate",
            file=sys.stderr,
        )
        return 1
    print("README events section matches the registry", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
