"""mpitree_tpu.obs — structured build records for every estimator.

The cross-cutting observability layer (ISSUE 3): every engine writes into
a :class:`BuildObserver` (a superset of ``utils/profiling.PhaseTimer``),
every estimator exposes the finalized :class:`BuildRecord` as an
always-on ``fit_report_`` dict plus a ``dump_report(path)`` helper, and
the bench harness embeds the :func:`digest` in each ``BENCH_TPU.jsonl``
section line so on-hardware perf evidence carries its own attribution
(engine decision + reason, per-level rows, compile and collective
accounting, typed events).

Gating: counters, decisions, events, and compile/collective accounting
are always on (O(1) host work from static shapes); wall-clock spans and
per-level rows require ``MPITREE_TPU_PROFILE=1``. Observability v2
(ISSUE 9) layers on top: ``obs.trace`` renders spans/events/replay rows
as Perfetto-loadable Chrome-trace timelines (``fit(trace_to=...)`` /
``MPITREE_TPU_TRACE_DIR``), ``obs.metrics`` carries the serving
latency/throughput registry (log-bucketed histograms, Prometheus text
exposition), fresh compile cache-keys attribute cold-dispatch wall per
entry point, and ``record.wire`` is the ICI wire-traffic ledger.
Observability v3 (ISSUE 12): ``obs.memory`` is the wire ledger's memory
twin — ``record.memory`` carries an analytical per-array device/host
ledger priced from the partition-rule table, ``plan_fit``/``plan_serve``
expose it as a preflight capacity planner (typed ``oom_predicted``
refusal before dispatch), and ``MPITREE_TPU_MEM_SAMPLE=1`` samples live
HBM/host watermarks at span boundaries.
Observability v4 (ISSUE 13): ``obs.fingerprint`` stamps every fit with
cheap u64 per-level build-state fingerprints (hist/winner/alloc channels
— the bit-identity pins, now observable), ``obs.flight`` appends every
finalized record to a persistent run store under ``MPITREE_TPU_RUN_DIR``
(git/platform/mesh/config lineage keys, query API), and ``obs.diff``
compares two runs with noise-aware verdicts seeded from run-history
dispersion, bisecting fingerprint divergences to the first divergent
(tree, level, channel).
Observability v5 (ISSUE 18): ``obs.cost`` is the compute ledger —
``record.compute`` joins each fresh program's XLA ``cost_analysis()``
(flops, bytes accessed, once per compile cache key) against a published
per-platform peak table into optimal-seconds floors, achieved
utilization, and a compute-/HBM-/ICI-bound roofline verdict; and
``obs.advisor`` turns the flight store's recorded A/B history into
evidence-driven ``auto`` policy resolutions (noise-gated, typed
``advisor_<policy>`` decisions, static fallback on thin history).
"""

from mpitree_tpu.obs.advisor import (
    advise_hist_subtraction,
    advise_mesh_2d,
    advise_rounds_per_dispatch,
    advise_serving_kernel,
)
from mpitree_tpu.obs.cost import (
    ENTRY_JOIN,
    PEAK_TABLE,
    compute_section,
    platform_peaks,
)
from mpitree_tpu.obs.diff import (
    diff_envelopes,
    diff_payloads,
    localize_divergence,
)
from mpitree_tpu.obs.fingerprint import (
    FINGERPRINT_VERSION,
    ensemble_fingerprint,
    tree_fingerprints,
)
from mpitree_tpu.obs.flight import RUN_DIR_ENV, FlightStore
from mpitree_tpu.obs.observer import (
    REGISTRY,
    BuildObserver,
    CompileRegistry,
    mesh_info,
    note_build_path,
    note_refine,
    warn_event,
)
from mpitree_tpu.obs.memory import (
    MemoryPlan,
    MemoryPlanError,
    MemWatch,
    plan_fit,
    plan_serve,
)
from mpitree_tpu.obs.metrics import MetricsRegistry, metrics_text
from mpitree_tpu.obs.record import (
    SCHEMA_VERSION,
    TOP_LEVEL_FIELDS,
    BuildRecord,
    ReportMixin,
    digest,
    wire_estimate,
)
from mpitree_tpu.obs.trace import (
    TRACE_DIR_ENV,
    TraceSink,
    merge_trace_files,
    validate_trace,
)

__all__ = [
    "ENTRY_JOIN",
    "FINGERPRINT_VERSION",
    "PEAK_TABLE",
    "RUN_DIR_ENV",
    "SCHEMA_VERSION",
    "TOP_LEVEL_FIELDS",
    "TRACE_DIR_ENV",
    "BuildRecord",
    "FlightStore",
    "BuildObserver",
    "CompileRegistry",
    "MemWatch",
    "MemoryPlan",
    "MemoryPlanError",
    "MetricsRegistry",
    "REGISTRY",
    "ReportMixin",
    "TraceSink",
    "advise_hist_subtraction",
    "advise_mesh_2d",
    "advise_rounds_per_dispatch",
    "advise_serving_kernel",
    "compute_section",
    "diff_envelopes",
    "diff_payloads",
    "digest",
    "ensemble_fingerprint",
    "localize_divergence",
    "merge_trace_files",
    "mesh_info",
    "metrics_text",
    "note_build_path",
    "note_refine",
    "platform_peaks",
    "plan_fit",
    "plan_serve",
    "tree_fingerprints",
    "validate_trace",
    "warn_event",
    "wire_estimate",
]
