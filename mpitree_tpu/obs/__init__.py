"""mpitree_tpu.obs — structured build records for every estimator.

The cross-cutting observability layer (ISSUE 3): every engine writes into
a :class:`BuildObserver` (a superset of ``utils/profiling.PhaseTimer``),
every estimator exposes the finalized :class:`BuildRecord` as an
always-on ``fit_report_`` dict plus a ``dump_report(path)`` helper, and
the bench harness embeds the :func:`digest` in each ``BENCH_TPU.jsonl``
section line so on-hardware perf evidence carries its own attribution
(engine decision + reason, per-level rows, compile and collective
accounting, typed events).

Gating: counters, decisions, events, and compile/collective accounting
are always on (O(1) host work from static shapes); wall-clock spans and
per-level rows require ``MPITREE_TPU_PROFILE=1``.
"""

from mpitree_tpu.obs.observer import (
    REGISTRY,
    BuildObserver,
    CompileRegistry,
    mesh_info,
    note_build_path,
    note_refine,
    warn_event,
)
from mpitree_tpu.obs.record import (
    SCHEMA_VERSION,
    TOP_LEVEL_FIELDS,
    BuildRecord,
    ReportMixin,
    digest,
)

__all__ = [
    "SCHEMA_VERSION",
    "TOP_LEVEL_FIELDS",
    "BuildRecord",
    "BuildObserver",
    "CompileRegistry",
    "REGISTRY",
    "ReportMixin",
    "digest",
    "mesh_info",
    "note_build_path",
    "note_refine",
    "warn_event",
]
