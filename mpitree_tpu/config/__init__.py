"""Package configuration: the typed env-knob registry (``config.knobs``).

``python -m mpitree_tpu.config --markdown`` prints the README knob table;
``--check`` verifies the README section matches the registry (the CI
drift gate).
"""

from mpitree_tpu.config import knobs

__all__ = ["knobs"]
