"""Knob-registry doc tooling: ``python -m mpitree_tpu.config``.

- ``--markdown`` prints the registry as the README's knob table.
- ``--check [README]`` extracts the table between the
  ``<!-- knob-table:begin -->`` / ``<!-- knob-table:end -->`` markers and
  exits 1 when it differs from the generated one — the CI drift gate that
  keeps docs and registry one source.
- ``--write [README]`` rewrites that section in place (the update path a
  contributor runs after adding a knob).
"""

from __future__ import annotations

import argparse
import os
import sys

from mpitree_tpu.config import knobs

BEGIN = "<!-- knob-table:begin -->"
END = "<!-- knob-table:end -->"
_DEFAULT_README = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "README.md"
)


def _split_readme(text: str):
    try:
        head, rest = text.split(BEGIN, 1)
        table, tail = rest.split(END, 1)
    except ValueError:
        return None
    return head, table, tail


def main(argv: list | None = None) -> int:
    parser = argparse.ArgumentParser(prog="python -m mpitree_tpu.config")
    group = parser.add_mutually_exclusive_group(required=True)
    group.add_argument(
        "--markdown", action="store_true",
        help="print the knob table generated from the registry",
    )
    group.add_argument(
        "--check", nargs="?", const=_DEFAULT_README, metavar="README",
        help="fail (exit 1) when the README knob table drifts from the "
        "registry",
    )
    group.add_argument(
        "--write", nargs="?", const=_DEFAULT_README, metavar="README",
        help="rewrite the README knob-table section from the registry",
    )
    args = parser.parse_args(argv)

    table = knobs.markdown_table()
    if args.markdown:
        print(table, end="")
        return 0

    path = args.check or args.write
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    parts = _split_readme(text)
    if parts is None:
        print(
            f"knob-table markers ({BEGIN} / {END}) not found in {path}",
            file=sys.stderr,
        )
        return 1
    head, current, tail = parts

    if args.write:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(f"{head}{BEGIN}\n{table}{END}{tail}")
        print(f"knob table rewritten in {path}", file=sys.stderr)
        return 0

    if current.strip() != table.strip():
        print(
            f"README knob table in {path} drifted from the registry — "
            "run `python -m mpitree_tpu.config --write` to regenerate",
            file=sys.stderr,
        )
        return 1
    print("README knob table matches the registry", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
