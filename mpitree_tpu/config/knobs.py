# graftlint: knob-registry
"""Typed registry of every ``MPITREE_TPU_*`` environment knob.

This module is the ONE place the package reads ``os.environ`` for its own
knobs (graftlint GL10 enforces that statically: a direct
``os.environ.get("MPITREE_TPU_...")`` anywhere else in ``mpitree_tpu/`` is
a finding). Each knob carries its type, default, parse rule, and the one
doc line the README table is generated from
(``python -m mpitree_tpu.config --markdown``) — so the docs can never
drift from the behavior, and a new knob is a registry entry, not a
scattered ``getenv`` plus a hand-edited table row.

Two read paths, both registered:

- :func:`value` — the typed read: unset or empty-string raw values resolve
  to the default; anything else goes through the knob's parse rule. The
  right call for the common bool/str/int/float knobs.
- :func:`raw` — the raw string (or None), for the few sites whose parsing
  is inseparable from site policy (tri-state forces, spec grammars,
  site-specific fallback-with-warning). Those sites keep their exact
  error text and fallback semantics; the registry still types and
  documents the knob.

Deliberately dependency-free (stdlib only): any module in the package —
including the earliest-imported utils — can read knobs without an import
cycle.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable


def _flag(raw: str) -> bool:
    """The package's boolean convention: everything but "0" enables."""
    return raw != "0"


def _one(raw: str) -> bool:
    """Strict opt-in: only the literal "1" enables."""
    return raw == "1"


@dataclasses.dataclass(frozen=True)
class Knob:
    """One registered env knob: its type, default, parse rule, doc line."""

    name: str
    kind: str                     # "bool" | "str" | "int" | "float" | "path"
    default: Any
    doc: str
    parse: Callable[[str], Any] | None = None
    choices: tuple | None = None  # documented domain (informational)

    def read(self) -> Any:
        raw = os.environ.get(self.name)
        if raw is None or raw == "":
            return self.default
        if self.parse is not None:
            return self.parse(raw)
        return raw


KNOBS: tuple = (
    # -- engine / kernel policy -------------------------------------------
    Knob("MPITREE_TPU_ENGINE", "str", "auto",
         "build engine when `BuildConfig(engine='auto')`",
         choices=("auto", "fused", "levelwise")),
    Knob("MPITREE_TPU_HIST_KERNEL", "str", "auto",
         "histogram kernel for `hist_kernel='auto'`",
         choices=("auto", "xla", "pallas")),
    Knob("MPITREE_TPU_WIDE_HIST", "str", "auto",
         "force (`1`) / disable (`0`) the sorted window-packed wide"
         " histogram tier",
         choices=("auto", "0", "1")),
    Knob("MPITREE_TPU_WIDE_KERNEL", "str", "scan",
         "wide-tier kernel: `pallas` forces (fails loudly when"
         " unsatisfiable), `scan` keeps the XLA sweep",
         choices=("scan", "pallas", "auto")),
    Knob("MPITREE_TPU_EXACT_TIES", "str", "auto",
         "`0` disables the f64 tie-exact cost sweep on CPU meshes",
         choices=("auto", "0")),
    Knob("MPITREE_TPU_HIST_SUBTRACTION", "str", "auto",
         "sibling-subtraction histogram carry override",
         choices=("auto", "on", "off")),
    Knob("MPITREE_TPU_GBDT_X64", "str", "auto",
         "`0` disables scoped-f64 gradient accumulation on CPU (perf"
         " escape hatch; ceiling-guard tests ride it)",
         choices=("auto", "0")),
    Knob("MPITREE_TPU_ROUNDS_PER_DISPATCH", "str", "auto",
         "boosting rounds fused per dispatch; an integer K forces, `auto`"
         " prices from the memory planner"),
    Knob("MPITREE_TPU_DEVICE_BIN", "str", None,
         "`1` forces on-device binning (raises on failure), `0` disables"
         " it everywhere; default = real TPUs only",
         choices=("0", "1")),
    Knob("MPITREE_TPU_SERVING_KERNEL", "str", "auto",
         "serving tier: `pallas` forces (degrades gracefully with a typed"
         " event), `xla` disables the kernel",
         choices=("auto", "pallas", "xla")),
    # -- serving: scheduler + quantization --------------------------------
    Knob("MPITREE_TPU_SERVING_QUANTIZE", "str", "off",
         "default table form for `compile_model`/`publish` when the"
         " caller passes no `quantize=`: `int8` serves bf16-threshold /"
         " int16-feature / int8-delta-value tables",
         choices=("off", "int8")),
    Knob("MPITREE_TPU_SERVING_QUANTIZE_TOL", "float", 1e-2,
         "max prediction delta vs the f32 tables on the calibration"
         " batch before quantized compilation REFUSES", parse=float),
    Knob("MPITREE_TPU_SERVING_QOS", "str",
         "interactive:50:256;batch:2000:4096",
         "scheduler QoS classes as `name:deadline_ms:queue_depth;...`"
         " (first class is the default for unlabeled requests)"),
    Knob("MPITREE_TPU_SERVING_SHED_DEPTH", "int", 4096,
         "total in-flight request bound across all scheduler queues;"
         " admissions past it shed with reason `queue_full`", parse=int),
    Knob("MPITREE_TPU_SERVING_MARGIN_MS", "float", 5.0,
         "dispatch-window close margin before the head-of-line deadline"
         " (the EDF batching budget)", parse=float),
    Knob("MPITREE_TPU_SERVING_WAIT_MS", "float", 2.0,
         "max batching window the scheduler holds a non-full bucket open",
         parse=float),
    Knob("MPITREE_TPU_FOREST_HBM_BUDGET", "int", 8 << 30,
         "per-device budget (bytes) for the replicated binned matrix in"
         " tree-sharded forest builds", parse=int),
    # -- observability ----------------------------------------------------
    Knob("MPITREE_TPU_PROFILE", "bool", False,
         "per-phase timing spans + per-level rows (`fit_stats_`)",
         parse=_flag),
    Knob("MPITREE_TPU_DEBUG", "bool", False,
         "on-device determinism assertions + debug checks", parse=_flag),
    Knob("MPITREE_TPU_TRACE_DIR", "path", None,
         "ambient Chrome-trace capture: every observer traces to a unique"
         " file in this directory"),
    Knob("MPITREE_TPU_MEM_SAMPLE", "bool", False,
         "`1` samples live memory watermarks at span boundaries",
         parse=_one),
    Knob("MPITREE_TPU_MEM_DRIFT_TOL", "float", 8.0,
         "ledger-vs-live drift-event threshold (x)", parse=float),
    Knob("MPITREE_TPU_HBM_BYTES", "int", None,
         "per-device HBM preflight budget (wins over the backend's"
         " reported `bytes_limit`)", parse=int),
    Knob("MPITREE_TPU_HOST_BYTES", "int", 1 << 30,
         "host-RAM budget streamed-ingest chunk sizing derives from",
         parse=int),
    Knob("MPITREE_TPU_OBS_STREAM_DIR", "path", None,
         "spill directory for long-run level-row streaming"),
    Knob("MPITREE_TPU_RUN_DIR", "path", None,
         "ambient flight store: every fit/serve record appends an"
         " envelope"),
    Knob("MPITREE_TPU_RUN_MAX_BYTES", "int", 0,
         "flight-store size cap in bytes (0/unset = unbounded)",
         parse=int),
    Knob("MPITREE_TPU_RUN_KEEP", "int", 16,
         "per-lineage record tail length kept when the store rotates",
         parse=int),
    Knob("MPITREE_TPU_PEAK_FLOPS", "float", None,
         "per-device peak f32 FLOP/s the compute ledger prices"
         " optimal-seconds floors from (overrides the obs.cost platform"
         " table; unset + unknown platform = honest `None` floors)",
         parse=float),
    Knob("MPITREE_TPU_PEAK_HBM_GBPS", "float", None,
         "per-device peak HBM bandwidth (GB/s) for the compute ledger's"
         " memory-bound floor (overrides the obs.cost platform table)",
         parse=float),
    Knob("MPITREE_TPU_POLICY_EVIDENCE", "str", "auto",
         "evidence-driven `resolve_*` auto policies (obs.advisor): `auto`"
         " consults the ambient flight store's A/B lineage history when"
         " one exists, `off` keeps every static policy",
         choices=("auto", "off")),
    Knob("MPITREE_TPU_METRICS_EXEMPLARS", "int", 0,
         "per-bucket exemplar reservoir size K for obs.metrics"
         " histograms (surfaced as `metrics_text()` comments; 0 = off,"
         " zero cost)", parse=int),
    # -- resilience -------------------------------------------------------
    Knob("MPITREE_TPU_ELASTIC", "bool", True,
         "`0` turns the whole resilience ladder off — device failures"
         " raise", parse=_flag),
    Knob("MPITREE_TPU_RETRIES", "int", 2,
         "transient re-dispatch budget (also the per-position level-retry"
         " budget)", parse=int),
    Knob("MPITREE_TPU_BACKOFF_S", "float", 0.5,
         "retry backoff base seconds (exponential, deterministic jitter)",
         parse=float),
    Knob("MPITREE_TPU_LEVEL_RETRY", "str", "auto",
         "snapshot the loop carry per level/expansion and resume there on"
         " a blip (`auto` = on)", choices=("auto", "on", "off")),
    Knob("MPITREE_TPU_CHAOS", "str", None,
         "fault-injection plan spec"
         " (`site:at:kind[:arg][:key=value...];...`)"),
    # -- ingest / native / caches -----------------------------------------
    Knob("MPITREE_TPU_SKETCH_CAPACITY", "int", 1 << 20,
         "per-feature unique-value cap before the quantile sketch"
         " compacts", parse=int),
    Knob("MPITREE_TPU_SPILL_DIR", "path", None,
         "spill rung for one-shot chunk iterators: the first ingest pass"
         " tees every chunk here (atomic files, manifest-last commit) so"
         " later passes replay from disk; unset = one-shot sources are"
         " refused"),
    Knob("MPITREE_TPU_SPILL_BYTES", "int", 16 << 30,
         "spill-store size cap in bytes; a stream that would exceed it"
         " raises before the offending chunk is kept", parse=int),
    Knob("MPITREE_TPU_KEYED_BOOTSTRAP", "bool", False,
         "`1` switches in-memory forest bootstrap/feature draws to the"
         " keyed counter-based sampler streamed forests always use —"
         " the fingerprint twin of a streamed forest fit", parse=_one),
    Knob("MPITREE_TPU_NO_NATIVE", "bool", False,
         "disable the C++ host split kernel (numpy fallback)",
         parse=_flag),
    Knob("MPITREE_TPU_NATIVE_CACHE", "path", None,
         "build cache directory for the native kernel"
         " (default: `mpitree_tpu/native/_build`)"),
    Knob("MPITREE_TPU_COMPILE_CACHE", "path", None,
         "persistent XLA executable cache directory (`bench_tpu.py`)"),
)

REGISTRY: dict = {k.name: k for k in KNOBS}


def _lookup(name: str) -> Knob:
    knob = REGISTRY.get(name)
    if knob is None:
        raise KeyError(
            f"unregistered env knob {name!r} — add it to "
            "mpitree_tpu/config/knobs.py (the registry is the single "
            "os.environ read path; GL10 enforces it)"
        )
    return knob


def value(name: str):
    """Typed read: default when unset/empty, else the knob's parse rule."""
    return _lookup(name).read()


def raw(name: str) -> str | None:
    """Raw environ string (or None) for a REGISTERED knob — the escape
    hatch for sites whose parsing is site policy (tri-state forces, spec
    grammars, fallback-with-warning)."""
    return os.environ.get(_lookup(name).name)


def markdown_table() -> str:
    """The README knob table, generated from the registry."""
    lines = [
        "| knob | type | default | effect |",
        "|---|---|---|---|",
    ]
    for k in KNOBS:
        if k.default is None:
            default = "unset"
        elif k.default is True:
            default = "on"
        elif k.default is False:
            default = "off"
        elif k.kind == "int" and isinstance(k.default, int):
            default = f"`{k.default}`"
        else:
            default = f"`{k.default}`"
        doc = k.doc
        if k.choices:
            doc = f"{doc} (one of {', '.join(f'`{c}`' for c in k.choices)})"
        lines.append(f"| `{k.name}` | {k.kind} | {default} | {doc} |")
    return "\n".join(lines) + "\n"
