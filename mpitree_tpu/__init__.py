"""mpitree_tpu: a TPU-native decision-tree framework built on JAX/XLA/Pallas.

A from-scratch rebuild of the capabilities of the ``mpitree`` reference
(scikit-learn-compatible decision trees with a parallel trainer), re-architected
TPU-first:

- split search is a breadth-first, level-synchronous histogram build over a
  struct-of-arrays tree (no Python object recursion, no dynamic shapes),
- rows never move: an on-device ``node_id`` assignment vector replaces the
  reference's recursive row-partition copies
  (reference: ``mpitree/tree/decision_tree.py:150-164``),
- distribution is data-parallel: rows are sharded over a ``jax.sharding.Mesh``
  and per-node class histograms are reduced with ``jax.lax.psum`` over ICI,
  replacing the reference's MPI communicator splitting
  (reference: ``mpitree/tree/decision_tree.py:313-338,456-477``),
- the hot split-evaluation loop (reference:
  ``mpitree/tree/decision_tree.py:53-91``) runs as fused XLA ops, with a
  first-party Pallas (Mosaic) one-hot-matmul histogram kernel
  (``ops/pallas_hist.py``) serving small-frontier levels on TPU — selected
  automatically, controlled by ``BuildConfig.hist_kernel`` /
  ``MPITREE_TPU_HIST_KERNEL``.

Public estimators mirror and extend the reference API
(``mpitree/tree/__init__.py:1-3``):
``DecisionTreeClassifier``, ``ParallelDecisionTreeClassifier`` (TPU-mesh
backed, no ``mpirun``), plus ``DecisionTreeRegressor`` and bagged random
forests.
"""

from mpitree_tpu import _compat  # noqa: F401  (JAX API shims, side effect)
from mpitree_tpu.boosting import (
    GradientBoostingClassifier,
    GradientBoostingRegressor,
)
from mpitree_tpu.ingest import StreamedDataset
from mpitree_tpu.models.classifier import (
    DecisionTreeClassifier,
    ParallelDecisionTreeClassifier,
)
from mpitree_tpu.models.forest import (
    ExtraTreesClassifier,
    ExtraTreesRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
)
from mpitree_tpu.models.regressor import DecisionTreeRegressor
from mpitree_tpu.utils.serialize import load_model, save_model

__version__ = "0.1.0"

__all__ = [
    "DecisionTreeClassifier",
    "ParallelDecisionTreeClassifier",
    "DecisionTreeRegressor",
    "RandomForestClassifier",
    "RandomForestRegressor",
    "ExtraTreesClassifier",
    "ExtraTreesRegressor",
    "GradientBoostingClassifier",
    "GradientBoostingRegressor",
    "StreamedDataset",
    "save_model",
    "load_model",
]
