// Native host split kernel — the C++ tier of the host fast path.
//
// The reference's native substrate is NumPy's C core plus OpenMPI
// (SURVEY.md §2.2); this framework's host tier replaces both with a
// first-party kernel: a level-synchronous split search over all frontier
// nodes that runs in O(rows·features + occupied_bins) per level, using an
// incremental impurity sweep instead of the dense (nodes × features ×
// classes × bins) tensor the vectorized numpy fallback materializes
// (host_builder.py). The win is largest with many classes — e.g. the
// reference's published benchmark workload, where every sample is its own
// class (reference: experiments.ipynb cell 5).
//
// Exposed via ctypes (no pybind11 in this environment): plain C ABI, arrays
// passed as raw pointers with explicit shapes. Compiled on first use by
// native/__init__.py (g++ into a cached .so).
//
// Semantics contract (must match ops/impurity.py and the reference):
//   - candidate b means "x_binned <= b", thresholds ascending per feature;
//   - cost = (n_l*H(l) + n_r*H(r)) / n, H = entropy (bits) or Gini;
//   - per feature: lowest-cost bin wins, ties -> lowest bin;
//   - across features: lowest cost wins, ties -> lowest feature index
//     (reference: mpitree/tree/decision_tree.py:88-91,140);
//   - candidates with an empty side are invalid;
//   - all accumulation in double; cost comparisons in double.
//
// Multi-root frontiers (the hybrid build's batched deep tail,
// core/hybrid_builder.py): every frontier node may descend from a different
// subtree with its own exact local binning, so the valid-candidate count can
// vary per (node, feature). `n_cand_per_slot != 0` switches `n_cand` from
// (n_feat,) shared to (n_slots, n_feat) row-major per-slot.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <thread>
#include <vector>

namespace {

inline double xlogx(double x) { return x > 0.0 ? x * std::log2(x) : 0.0; }

// Integer-count fast path: entropy sweeps spend nearly all their time in
// log2 (4 calls per row move). When every sample weight is integral (the
// common unweighted / bootstrap-count case) all running class counts are
// integers, so n*log2(n) comes from a lazily grown lookup table instead.
// tab[i] = xlogx((double)i) exactly — results are bit-identical to the
// direct computation, so tie-breaking cannot drift between the paths.
// thread_local: ctypes releases the GIL, so concurrent calls from two
// Python threads must not share the growth.
constexpr int64_t kXlogxTabCap = int64_t(1) << 22;  // 33 MB ceiling
thread_local std::vector<double> g_xlogx_tab;

inline const double* xlogx_tab_ensure(int64_t n) {
  if ((int64_t)g_xlogx_tab.size() < n + 1) {
    int64_t old = g_xlogx_tab.size();
    g_xlogx_tab.resize(n + 1);
    for (int64_t i = old; i < (int64_t)g_xlogx_tab.size(); ++i)
      g_xlogx_tab[i] = xlogx((double)i);
  }
  return g_xlogx_tab.data();
}

// Compact feature-major uint16 gather of the LIVE rows in bucket order:
// per-(slot, feature) sweep passes then read a contiguous run of 2-byte
// bins (indexed by bucket position) instead of 216-byte-strided int32
// loads — the row-major layout costs a full cache line per row per
// feature. Gathering only live rows keeps the rebuild proportional to the
// level\'s work (the hybrid refine\'s live set shrinks every level; a
// full-matrix transpose there cost more than it saved). nullptr when bins
// exceed uint16 (exact binning on very-high-cardinality data); callers
// fall back to the strided int32 reads.
// Cap mirrors g_xlogx_tab's: the buffer persists thread_local between
// levels (reallocating 60 MB per level would thrash); past the cap the
// callers simply keep their strided-int32 fallback reads.
constexpr int64_t kXbtCapBytes = int64_t(1) << 27;  // 128 MB ceiling
thread_local std::vector<uint16_t> g_xbt;

// y / w companions to the bin gather: the sweep touches each row's label
// and weight once per FEATURE (54x per level at covtype), so leaving them
// at their original indices costs 54 random reads per row into
// multi-megabyte arrays; in bucket order the per-slot slices are
// L1-resident. Filled once per call, beside the bins.
thread_local std::vector<int32_t> g_y_local;
thread_local std::vector<float> g_yv_local;
thread_local std::vector<double> g_w_local;

inline const int32_t* gather_labels(const int32_t* y,
                                    const std::vector<int64_t>& rows) {
  g_y_local.resize(rows.size());
  for (size_t i = 0; i < rows.size(); ++i) g_y_local[i] = y[rows[i]];
  return g_y_local.data();
}

inline const float* gather_targets(const float* yv,
                                   const std::vector<int64_t>& rows) {
  g_yv_local.resize(rows.size());
  for (size_t i = 0; i < rows.size(); ++i) g_yv_local[i] = yv[rows[i]];
  return g_yv_local.data();
}

inline const double* gather_weights(const double* w,
                                    const std::vector<int64_t>& rows) {
  if (!w) return nullptr;
  g_w_local.resize(rows.size());
  for (size_t i = 0; i < rows.size(); ++i) g_w_local[i] = w[rows[i]];
  return g_w_local.data();
}

inline const uint16_t* gather_bins(const int32_t* xb,
                                   const std::vector<int64_t>& rows_by_slot,
                                   int32_t n_feat, int32_t n_bins) {
  if (n_bins > 65535) return nullptr;
  const int64_t live = (int64_t)rows_by_slot.size();
  if (live * n_feat * 2 > kXbtCapBytes) return nullptr;
  g_xbt.resize((size_t)live * n_feat);
  uint16_t* out = g_xbt.data();
  auto gather_range = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const int32_t* row = xb + rows_by_slot[i] * n_feat;
      for (int32_t f = 0; f < n_feat; ++f)
        out[(size_t)f * live + i] = (uint16_t)row[f];
    }
  };
  // Same thread budget as the sweep (the gather is the sweep's serial
  // prologue — leaving it single-threaded would Amdahl-cap multicore
  // hosts now that the dense sweep itself is cheap). Row ranges write
  // disjoint [lo, hi) runs of every column, so no synchronization.
  int nt = 0;
  if (const char* env = std::getenv("MPITREE_TPU_NATIVE_THREADS")) {
    nt = std::abs(std::atoi(env));
  }
  if (nt <= 0) nt = (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  if (live < (int64_t)1 << 16) nt = 1;  // below this, spawn cost dominates
  if (nt == 1) {
    gather_range(0, live);
    return out;
  }
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t)
    threads.emplace_back(gather_range, live * t / nt, live * (t + 1) / nt);
  for (auto& th : threads) th.join();
  return out;
}

// Strictly-better test with relative tolerance: the incremental sweep's cost
// differs from the reference's dense formula by last-ULP rounding, and exact
// mathematical ties (symmetric splits) must resolve to the lowest
// (feature, bin) as the reference's first-argmin does
// (mpitree/tree/decision_tree.py:88-91,140). 1e-12 relative absorbs ULP
// noise while never confusing genuinely different costs.
inline bool better(double cost, double best) {
  if (std::isinf(best)) return cost < best;
  return cost < best - 1e-12 * (std::abs(best) + 1.0);
}

// Bucket rows by frontier slot (counting sort; parked rows drop out).
// Zero-weight rows (bootstrap out-of-bag) are excluded up front: they
// contribute nothing to counts or impurity, and the device path's
// bin-occupancy ("constant") flag ignores them too.
void bucket_rows(const int32_t* node_id, const double* w, int64_t n_rows,
                 int32_t frontier_lo, int32_t n_slots,
                 std::vector<int64_t>& slot_start,
                 std::vector<int64_t>& rows_by_slot) {
  slot_start.assign(n_slots + 1, 0);
  std::vector<int32_t> slot_of(n_rows);
  for (int64_t r = 0; r < n_rows; ++r) {
    int64_t s = (int64_t)node_id[r] - frontier_lo;
    bool live = s >= 0 && s < n_slots && (!w || w[r] > 0.0);
    slot_of[r] = live ? (int32_t)s : -1;
    if (slot_of[r] >= 0) slot_start[slot_of[r] + 1]++;
  }
  for (int32_t s = 0; s < n_slots; ++s) slot_start[s + 1] += slot_start[s];
  rows_by_slot.resize(slot_start[n_slots]);
  std::vector<int64_t> cur(slot_start.begin(), slot_start.end() - 1);
  for (int64_t r = 0; r < n_rows; ++r)
    if (slot_of[r] >= 0) rows_by_slot[cur[slot_of[r]]++] = r;
}

// Frontier slots are independent, so the per-slot loop parallelizes with no
// synchronization and no effect on results (tie-breaks are within-slot).
// Ranges are row-balanced via the slot_start prefix sums: at the root level
// one slot can hold every row, and an even slot split would leave all but
// one thread idle. MPITREE_TPU_NATIVE_THREADS caps the thread count
// (default: hardware concurrency; 1 disables threading); a NEGATIVE value
// forces |value| threads even below the small-work threshold — a test
// hook, so the cap semantics never cost users the tiny-fit latency path.
template <class Fn>
void run_slot_ranges(const std::vector<int64_t>& slot_start, int32_t n_slots,
                     Fn&& worker) {
  int nt = 0;
  bool force = false;
  if (const char* env = std::getenv("MPITREE_TPU_NATIVE_THREADS")) {
    nt = std::atoi(env);
    if (nt < 0) {
      nt = -nt;
      force = true;
    }
  }
  if (nt <= 0) nt = (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  if (nt > n_slots) nt = n_slots;
  // Tiny levels (the host tier's single-digit-millisecond latency path)
  // must not pay thread spawn/join: their whole sweep costs less than one
  // std::thread startup. Threshold in rows of actual work this call.
  if (!force && slot_start[n_slots] < (int64_t)1 << 15) nt = 1;
  if (nt <= 1) {
    worker(0, n_slots);
    return;
  }
  const int64_t total = slot_start[n_slots];
  std::vector<int32_t> bounds(nt + 1, 0);
  bounds[nt] = n_slots;
  for (int t = 1; t < nt; ++t) {
    const int64_t target = total * t / nt;
    auto it = std::upper_bound(slot_start.begin(),
                               slot_start.begin() + n_slots + 1, target);
    int32_t b = (int32_t)(it - slot_start.begin()) - 1;
    bounds[t] = std::max(b, bounds[t - 1]);
  }
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t)
    if (bounds[t + 1] > bounds[t])
      threads.emplace_back(worker, bounds[t], bounds[t + 1]);
  for (auto& th : threads) th.join();
}

// Produce the ascending occupied-bin order for one (node, feature) pass.
// Dense nodes (occupied bins comparable to the bin range, the exact-binned
// deep-tail case) iterate the range directly; sparse nodes sort the touched
// list — O(min(range, T log T)) instead of always T log T.
inline void order_touched(std::vector<int32_t>& touched, int32_t bt_max) {
  const int64_t T = (int64_t)touched.size();
  if ((int64_t)bt_max + 1 <= 8 * T) {
    // touched densely covers [0, bt_max]: counting iteration
    std::vector<char> seen((size_t)bt_max + 1, 0);
    for (int32_t b : touched) seen[b] = 1;
    touched.clear();
    for (int32_t b = 0; b <= bt_max; ++b)
      if (seen[b]) touched.push_back(b);
  } else {
    std::sort(touched.begin(), touched.end());
  }
}

}  // namespace

extern "C" {

// Per-level split search over one frontier chunk.
//
// Inputs (row-major):
//   xb       : (n_rows, n_feat) int32 bin ids
//   y        : (n_rows,) int32 class ids in [0, n_classes)
//   node_id  : (n_rows,) int32 current assignment; rows outside
//              [frontier_lo, frontier_lo + n_slots) are ignored
//   w        : (n_rows,) double sample weights (may be null -> all 1)
//   n_cand   : valid candidate count per feature — shape (n_feat,) when
//              n_cand_per_slot == 0, else (n_slots, n_feat) row-major
//   mono_cst : (n_feat,) int8 INTERNAL monotonicity signs (nullable):
//              a candidate on a signed feature is valid only when
//              (v_l - v_r)*sign <= 0 and both child class-0 fractions lie
//              in the slot's [mono_lo, mono_hi] (n_slots float32) bounds.
//              Child values are computed as f32(mass) * f32(1/n) —
//              reciprocal-multiply, matching the device engines bit for
//              bit on integer counts (utils/monotonic.py).
// Outputs (caller-allocated):
//   out_feat : (n_slots,) int32 best feature (-1 if no valid candidate)
//   out_bin  : (n_slots,) int32 best bin
//   out_cost : (n_slots,) double best cost (+inf if none)
//   out_counts: (n_slots, n_classes) double class counts
//   out_constant: (n_slots,) uint8 "all features single-bin" flag
//   out_vl/out_vr: (n_slots,) float32 winning candidate's child values
//              (only written when mono_cst is non-null; may be null
//              otherwise)
// criterion: 0 = entropy, 1 = gini.
void best_splits_classification(
    const int32_t* xb, const int32_t* y, const int32_t* node_id,
    const double* w, int64_t n_rows, int32_t n_feat, int32_t n_bins,
    int32_t n_classes, int32_t frontier_lo, int32_t n_slots,
    const int32_t* n_cand, int32_t n_cand_per_slot, int32_t criterion,
    double min_child_w, const int8_t* mono_cst, const float* mono_lo,
    const float* mono_hi, int32_t* out_feat, int32_t* out_bin,
    double* out_cost, double* out_counts, uint8_t* out_constant,
    float* out_vl, float* out_vr) {
  const double inf = std::numeric_limits<double>::infinity();

  std::vector<int64_t> slot_start;
  std::vector<int64_t> rows_by_slot;
  bucket_rows(node_id, w, n_rows, frontier_lo, n_slots, slot_start,
              rows_by_slot);

  // Integral weights -> integer class counts -> xlogx lookup table applies.
  bool int_w = true;
  if (w) {
    for (int64_t r = 0; r < n_rows; ++r)
      if (w[r] != std::floor(w[r])) { int_w = false; break; }
  }

  // Build the lookup table ONCE in the calling thread (its thread_local
  // storage persists across calls, amortizing the fill); workers only read
  // it. Freshly spawned threads would otherwise refill their own empty
  // thread_local copy every level.
  const double* shared_tab = nullptr;
  int64_t tab_size = 0;
  if (criterion == 0 && int_w) {
    double total_live = 0.0;
    for (int64_t i : rows_by_slot) total_live += w ? w[i] : 1.0;
    // Clamp to the memory cap rather than disabling: above the cap only the
    // few giant slots fall back to live log2; the deep tail's many small
    // slots (where the sweep cost concentrates) still hit the table.
    tab_size = std::min((int64_t)total_live + 1, kXlogxTabCap);
    shared_tab = xlogx_tab_ensure(tab_size - 1);
  }

  const uint16_t* xbt = gather_bins(xb, rows_by_slot, n_feat, n_bins);
  const int32_t* yl = gather_labels(y, rows_by_slot);
  const double* wl = gather_weights(w, rows_by_slot);
  const int64_t live = (int64_t)rows_by_slot.size();

  auto worker = [&](int32_t s_begin, int32_t s_end) {
  // Scratch reused across (node, feature) passes — one set per thread.
  std::vector<int32_t> touched_bins;                // occupied bins
  std::vector<double> left_cls(n_classes, 0.0);     // running class counts
  std::vector<double> node_cls(n_classes, 0.0);
  // DENSE slots (rows >> bins — the main build, 256 quantile bins) sweep a
  // per-(bin, class) histogram, zeroed lazily at first touch (occ stamp):
  // the old chain sweep\'s 2 impurity updates per ROW collapse into 2 per
  // (occupied bin, class), a ~rows/bins-fold reduction, and per-class LUT
  // deltas telescope to the identical totals for integer counts. SPARSE
  // slots (the hybrid refine: ~2k-row subtrees with exact local binning,
  // occupied bins ~ rows) keep the per-bin chain walk — there the
  // histogram\'s per-bin class scan would cost n_classes x the row count.
  std::vector<double> hist;  // sized on the first dense slot only
  std::vector<int32_t> occ_stamp(n_bins, -1);
  int32_t stamp = 0;
  std::vector<int64_t> bin_head(n_bins, -1);
  std::vector<int64_t> row_next;
  touched_bins.reserve(n_bins);

  for (int32_t s = s_begin; s < s_end; ++s) {
    const int64_t r0 = slot_start[s], r1 = slot_start[s + 1];
    const int32_t* nc =
        n_cand + (n_cand_per_slot ? (int64_t)s * n_feat : 0);
    out_feat[s] = -1;
    out_bin[s] = 0;
    out_cost[s] = inf;
    out_constant[s] = 1;
    std::fill(node_cls.begin(), node_cls.end(), 0.0);
    for (int64_t i = r0; i < r1; ++i)
      node_cls[yl[i]] += wl ? wl[i] : 1.0;
    double n_tot = 0.0;
    for (int32_t c = 0; c < n_classes; ++c) {
      out_counts[(int64_t)s * n_classes + c] = node_cls[c];
      n_tot += node_cls[c];
    }
    if (r1 == r0) { out_constant[s] = 0; continue; }

    // A slot with no candidate features at all (the hybrid refine zeroes
    // per-slot n_cand for budget-exhausted roots) needs only the counts
    // above — skip the per-feature chain builds and sweeps outright.
    {
      bool any_cand = false;
      for (int32_t f = 0; f < n_feat; ++f)
        if (nc[f] > 0) { any_cand = true; break; }
      if (!any_cand) continue;
    }

    // mode: 0 = entropy via log2, 1 = gini, 2 = entropy via lookup table
    int mode = criterion;
    const double* tab = nullptr;
    if (shared_tab && n_tot < (double)tab_size) {
      tab = shared_tab;
      mode = 2;
    }

    // Dense-path cost is rows + occupied_bins * n_classes per (slot,
    // feature); with many classes (the reference's every-sample-its-own-
    // class benchmark: n_classes == n_rows) that regresses far past the
    // chain walk's 2-updates-per-row, so the class term gates too.
    const bool use_hist =
        (r1 - r0) >= 2 * (int64_t)n_bins
        && (int64_t)n_bins * n_classes <= (r1 - r0);
    if (use_hist && hist.empty())
      hist.resize((size_t)n_bins * n_classes, 0.0);
    if (!use_hist) row_next.resize(r1 - r0);
    for (int32_t f = 0; f < n_feat; ++f) {
      // Accumulate the (bin, class) histogram (dense) or per-bin row
      // chains (sparse) for this (node, feature).
      touched_bins.clear();
      int32_t bt_max = 0;
      ++stamp;
      const uint16_t* col = xbt ? xbt + (size_t)f * live : nullptr;
      if (use_hist) {
        for (int64_t i = r0; i < r1; ++i) {
          const int32_t b = col ? col[i] : xb[rows_by_slot[i] * n_feat + f];
          if (occ_stamp[b] != stamp) {
            occ_stamp[b] = stamp;
            touched_bins.push_back(b);
            if (b > bt_max) bt_max = b;
            double* hb = &hist[(size_t)b * n_classes];
            for (int32_t c = 0; c < n_classes; ++c) hb[c] = 0.0;
          }
          hist[(size_t)b * n_classes + yl[i]] += wl ? wl[i] : 1.0;
        }
      } else {
        for (int64_t i = r0; i < r1; ++i) {
          const int32_t b =
              col ? col[i] : xb[rows_by_slot[i] * n_feat + f];
          if (occ_stamp[b] != stamp) {
            occ_stamp[b] = stamp;
            touched_bins.push_back(b);
            if (b > bt_max) bt_max = b;
            bin_head[b] = -1;
          }
          row_next[i - r0] = bin_head[b];
          bin_head[b] = i;
        }
      }
      if (touched_bins.size() > 1) out_constant[s] = 0;

      if (nc[f] > 0 && touched_bins.size() > 1) {
        // Ascending sweep over occupied bins only.
        order_touched(touched_bins, bt_max);
        double left_n = 0.0;
        double left_sum = 0.0;  // Σ_c xlogx(l_c) (entropy) or Σ_c l_c^2
        // right_c = node_c - left_c; maintain Σ_c f(right_c) incrementally,
        // starting with all mass on the right.
        double right_sum = 0.0;
        std::fill(left_cls.begin(), left_cls.end(), 0.0);
        if (mode == 2) {
          for (int32_t c = 0; c < n_classes; ++c)
            right_sum += tab[(int64_t)node_cls[c]];
        } else if (mode == 0) {
          for (int32_t c = 0; c < n_classes; ++c)
            right_sum += xlogx(node_cls[c]);
        } else {
          for (int32_t c = 0; c < n_classes; ++c)
            right_sum += node_cls[c] * node_cls[c];
        }

        // One shared impurity-delta update for both sweep strategies —
        // the moved mass is a whole bin-class total (dense path; per-row
        // deltas telescope to exactly this) or one row's weight (chains).
        auto apply_mass = [&](int32_t c, double m) {
          const double lc = left_cls[c];
          const double rc = node_cls[c] - lc;
          if (mode == 2) {
            left_sum += tab[(int64_t)(lc + m)] - tab[(int64_t)lc];
            right_sum += tab[(int64_t)(rc - m)] - tab[(int64_t)rc];
          } else if (mode == 0) {
            left_sum += xlogx(lc + m) - xlogx(lc);
            right_sum += xlogx(rc - m) - xlogx(rc);
          } else {
            left_sum += (lc + m) * (lc + m) - lc * lc;
            right_sum += (rc - m) * (rc - m) - rc * rc;
          }
          left_cls[c] = lc + m;
          left_n += m;
        };
        for (size_t ti = 0; ti < touched_bins.size(); ++ti) {
          const int32_t b = touched_bins[ti];
          if (use_hist) {
            const double* hb = &hist[(size_t)b * n_classes];
            for (int32_t c = 0; c < n_classes; ++c)
              if (hb[c] != 0.0) apply_mass(c, hb[c]);
          } else {
            for (int64_t i = bin_head[b]; i >= 0; i = row_next[i - r0])
              apply_mass(yl[i], wl ? wl[i] : 1.0);
          }
          if (b >= nc[f]) break;  // past the last valid candidate
          const double right_n = n_tot - left_n;
          if (left_n <= 0.0 || right_n <= 0.0) continue;
          if (left_n < min_child_w || right_n < min_child_w) continue;
          // Monotonic gate in the device's exact f32 reciprocal-multiply
          // form (ops/impurity._monotonic_ok; utils/monotonic.py).
          float vl_f = 0.0f, vr_f = 0.0f;
          if (mono_cst && mono_cst[f] != 0) {
            vl_f = (float)left_cls[0] *
                   (1.0f / std::max((float)left_n, 1.0f));
            vr_f = (float)(node_cls[0] - left_cls[0]) *
                   (1.0f / std::max((float)right_n, 1.0f));
            const float sgn = (float)mono_cst[f];
            if ((vl_f - vr_f) * sgn > 0.0f) continue;
            if (vl_f < mono_lo[s] || vl_f > mono_hi[s] ||
                vr_f < mono_lo[s] || vr_f > mono_hi[s])
              continue;
          }
          double cost;
          if (mode == 1) {
            const double gl = left_n - left_sum / left_n;
            const double gr = right_n - right_sum / right_n;
            cost = (gl + gr) / n_tot;
          } else {
            // n_l*H_l = n_l*log2(n_l) - Σ_c xlogx(l_c), likewise right.
            const double hl =
                (mode == 2 ? tab[(int64_t)left_n] : xlogx(left_n)) - left_sum;
            const double hr =
                (mode == 2 ? tab[(int64_t)right_n] : xlogx(right_n)) -
                right_sum;
            cost = (hl + hr) / n_tot;
          }
          if (better(cost, out_cost[s])) {
            out_cost[s] = cost;
            out_feat[s] = f;
            out_bin[s] = b;
            if (mono_cst) {
              out_vl[s] = vl_f;
              out_vr[s] = vr_f;
            }
          }
        }
      }
    }
  }
  };  // worker
  run_slot_ranges(slot_start, n_slots, worker);
}

// Regression (squared error) variant: per-node best split from
// (w, w*y, w*y^2) running sums; same tie-break and n_cand contract.
// Outputs: out_counts is (n_slots, 3) = (n, sum_y, sum_y2) with weights.
void best_splits_regression(
    const int32_t* xb, const float* yv, const int32_t* node_id,
    const double* w, int64_t n_rows, int32_t n_feat, int32_t n_bins,
    int32_t frontier_lo, int32_t n_slots, const int32_t* n_cand,
    int32_t n_cand_per_slot, double min_child_w, const int8_t* mono_cst,
    const float* mono_lo, const float* mono_hi, int32_t* out_feat,
    int32_t* out_bin, double* out_cost, double* out_counts,
    uint8_t* out_constant, double* out_ymin, double* out_ymax,
    float* out_vl, float* out_vr) {
  const double inf = std::numeric_limits<double>::infinity();

  std::vector<int64_t> slot_start;
  std::vector<int64_t> rows_by_slot;
  bucket_rows(node_id, w, n_rows, frontier_lo, n_slots, slot_start,
              rows_by_slot);
  const uint16_t* xbt = gather_bins(xb, rows_by_slot, n_feat, n_bins);
  const float* yvl = gather_targets(yv, rows_by_slot);
  const double* wl = gather_weights(w, rows_by_slot);
  const int64_t live = (int64_t)rows_by_slot.size();

  auto worker = [&](int32_t s_begin, int32_t s_end) {
  std::vector<double> bw(n_bins, 0.0), bs(n_bins, 0.0), bq(n_bins, 0.0);
  std::vector<int32_t> touched;
  touched.reserve(n_bins);

  for (int32_t s = s_begin; s < s_end; ++s) {
    const int64_t r0 = slot_start[s], r1 = slot_start[s + 1];
    const int32_t* nc =
        n_cand + (n_cand_per_slot ? (int64_t)s * n_feat : 0);
    out_feat[s] = -1;
    out_bin[s] = 0;
    out_cost[s] = inf;
    out_constant[s] = 1;
    double n_tot = 0.0, s_tot = 0.0, q_tot = 0.0;
    double ymin = inf, ymax = -inf;
    for (int64_t i = r0; i < r1; ++i) {
      const double wr = wl ? wl[i] : 1.0;
      const double yr = (double)yvl[i];
      n_tot += wr;
      s_tot += wr * yr;
      q_tot += wr * yr * yr;
      if (wr > 0) {
        if (yr < ymin) ymin = yr;
        if (yr > ymax) ymax = yr;
      }
    }
    out_counts[(int64_t)s * 3 + 0] = n_tot;
    out_counts[(int64_t)s * 3 + 1] = s_tot;
    out_counts[(int64_t)s * 3 + 2] = q_tot;
    out_ymin[s] = ymin;
    out_ymax[s] = ymax;
    if (r1 == r0) { out_constant[s] = 0; continue; }

    {
      bool any_cand = false;
      for (int32_t f = 0; f < n_feat; ++f)
        if (nc[f] > 0) { any_cand = true; break; }
      if (!any_cand) continue;
    }

    for (int32_t f = 0; f < n_feat; ++f) {
      touched.clear();
      int32_t bt_max = 0;
      const uint16_t* col = xbt ? xbt + (size_t)f * live : nullptr;
      for (int64_t i = r0; i < r1; ++i) {
        const int32_t b = col ? col[i] : xb[rows_by_slot[i] * n_feat + f];
        const double wr = wl ? wl[i] : 1.0;
        const double yr = (double)yvl[i];
        if (bw[b] == 0.0 && bs[b] == 0.0 && bq[b] == 0.0) {
          touched.push_back(b);
          if (b > bt_max) bt_max = b;
        }
        bw[b] += wr;
        bs[b] += wr * yr;
        bq[b] += wr * yr * yr;
      }
      if (touched.size() > 1) out_constant[s] = 0;
      if (nc[f] > 0 && touched.size() > 1) {
        order_touched(touched, bt_max);
        double wl = 0.0, sl = 0.0, ql = 0.0;
        for (int32_t b : touched) {
          wl += bw[b];
          sl += bs[b];
          ql += bq[b];
          if (b >= nc[f]) break;
          const double wr_ = n_tot - wl, sr = s_tot - sl, qr = q_tot - ql;
          if (wl <= 0.0 || wr_ <= 0.0) continue;
          if (wl < min_child_w || wr_ < min_child_w) continue;
          // Monotonic gate — ABI symmetry with the classification kernel.
          // CAVEAT: these child means come from f64 accumulators cast to
          // f32, which is NOT bit-matched to the device engines' f32
          // cumsum arithmetic; host_builder.py therefore routes
          // constrained REGRESSION to its numpy sweep (which mirrors the
          // device op for op) and never passes mono_cst here. A caller
          // wiring this path accepts engine-identity drift on near-tied
          // child means.
          float vl_f = 0.0f, vr_f = 0.0f;
          if (mono_cst && mono_cst[f] != 0) {
            vl_f = (float)sl * (1.0f / std::max((float)wl, 1.0f));
            vr_f = (float)sr * (1.0f / std::max((float)wr_, 1.0f));
            const float sgn = (float)mono_cst[f];
            if ((vl_f - vr_f) * sgn > 0.0f) continue;
            if (vl_f < mono_lo[s] || vl_f > mono_hi[s] ||
                vr_f < mono_lo[s] || vr_f > mono_hi[s])
              continue;
          }
          const double sse_l = ql - sl * sl / wl;
          const double sse_r = qr - sr * sr / wr_;
          const double cost =
              (std::max(sse_l, 0.0) + std::max(sse_r, 0.0)) / n_tot;
          if (better(cost, out_cost[s])) {
            out_cost[s] = cost;
            out_feat[s] = f;
            out_bin[s] = b;
            if (mono_cst) {
              out_vl[s] = vl_f;
              out_vr[s] = vr_f;
            }
          }
        }
      }
      for (int32_t b : touched) { bw[b] = 0.0; bs[b] = 0.0; bq[b] = 0.0; }
    }
  }
  };  // worker
  run_slot_ranges(slot_start, n_slots, worker);
}

}  // extern "C"
