"""Native host kernel: build-on-first-use C++ split search with ctypes.

No pybind11 in this environment, so the kernel exposes a plain C ABI
(``split_kernel.cpp``) and this module compiles it with the system ``g++``
into a cached shared object on first import, then binds it with ctypes.
Everything degrades gracefully: if no compiler is available (or
``MPITREE_TPU_NO_NATIVE=1``), ``lib()`` returns None and callers fall back to
the vectorized numpy implementation in ``core/host_builder.py``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import threading

import numpy as np
from mpitree_tpu.config import knobs

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "split_kernel.cpp")
_LOCK = threading.Lock()
_LIB: list = []  # [] = not tried, [None] = unavailable, [CDLL] = loaded

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _host_tag() -> str:
    """Cache key component tying a -march=native build to compatible hosts.

    The cache dir can be shared across machines (NFS home, baked container
    image); a .so compiled for a newer CPU would SIGILL on an older one, so
    the filename carries the arch plus a hash of the CPU feature flags."""
    import hashlib
    import platform

    tag = platform.machine() or "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    h = hashlib.sha256(line.encode()).hexdigest()[:8]
                    return f"{tag}-{h}"
    except OSError:
        pass
    return tag


def _build() -> str | None:
    """Compile the kernel; returns the .so path or None (numpy fallback)."""
    try:
        cache_dir = (knobs.raw("MPITREE_TPU_NATIVE_CACHE")
                     or os.path.join(_HERE, "_build"))
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, f"split_kernel.{_host_tag()}.so")
        if os.path.exists(so_path) and (
            os.path.getmtime(so_path) >= os.path.getmtime(_SRC)
        ):
            return so_path
        # Unique temp name per process: two first-builds racing must not
        # load each other's half-written .so.
        tmp = f"{so_path}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-march=native",
            "-pthread", _SRC, "-o", tmp,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)  # atomic on the same filesystem
        return so_path
    except Exception:
        return None


def lib():
    """The loaded CDLL, or None when the native path is unavailable."""
    if _LIB:
        return _LIB[0]
    with _LOCK:
        if _LIB:
            return _LIB[0]
        if knobs.value("MPITREE_TPU_NO_NATIVE"):
            _LIB.append(None)
            return None
        so_path = _build()
        if so_path is None:
            _LIB.append(None)
            return None
        try:
            cdll = ctypes.CDLL(so_path)
            cdll.best_splits_classification.argtypes = [
                _i32p, _i32p, _i32p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, _i32p, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_double,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                _i32p, _i32p, _f64p, _f64p, _u8p,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
            cdll.best_splits_classification.restype = None
            cdll.best_splits_regression.argtypes = [
                _i32p, _f32p, _i32p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, _i32p, ctypes.c_int32, ctypes.c_double,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                _i32p, _i32p, _f64p, _f64p, _u8p, _f64p, _f64p,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
            cdll.best_splits_regression.restype = None
            _LIB.append(cdll)
        except Exception:
            _LIB.append(None)
        return _LIB[0]


def _wptr(w: np.ndarray | None):
    if w is None:
        return None
    return w.ctypes.data_as(ctypes.c_void_p)


def _mono_args(mono_cst, mono_lo, mono_hi, n_slots):
    """(cst_ptr, lo_ptr, hi_ptr, out_vl, out_vr, keepalive) for the kernel.

    ``mono_cst=None`` passes nulls (unconstrained — the fast path is
    untouched); otherwise the per-slot f32 bounds windows and the winner
    child-value outputs ride along (utils/monotonic.py semantics).
    """
    if mono_cst is None:
        return None, None, None, None, None, ()
    cst8 = np.ascontiguousarray(mono_cst, np.int8)
    lo32 = np.ascontiguousarray(mono_lo, np.float32)
    hi32 = np.ascontiguousarray(mono_hi, np.float32)
    out_vl = np.zeros(n_slots, np.float32)
    out_vr = np.zeros(n_slots, np.float32)
    return (
        cst8.ctypes.data_as(ctypes.c_void_p),
        lo32.ctypes.data_as(ctypes.c_void_p),
        hi32.ctypes.data_as(ctypes.c_void_p),
        out_vl, out_vr, (cst8, lo32, hi32),
    )


def best_splits_classification(
    xb, y, node_id, w, *, n_bins, n_classes, frontier_lo, n_slots, n_cand,
    criterion, n_cand_per_slot=False, min_child_weight=0.0,
    mono_cst=None, mono_lo=None, mono_hi=None,
):
    """ctypes wrapper; returns dict of per-slot arrays (or None if no lib).

    ``n_cand_per_slot=True`` marks ``n_cand`` as (n_slots, n_feat) — one
    candidate count per frontier node, for multi-root frontiers where every
    node carries its own exact local binning (core/hybrid_builder.py).
    ``mono_cst``/``mono_lo``/``mono_hi`` engage the kernel's monotonic
    gate; the result then carries ``v_left``/``v_right`` winner values.
    """
    cdll = lib()
    if cdll is None:
        return None
    n_rows, n_feat = xb.shape
    out_feat = np.empty(n_slots, np.int32)
    out_bin = np.empty(n_slots, np.int32)
    out_cost = np.empty(n_slots, np.float64)
    out_counts = np.zeros((n_slots, n_classes), np.float64)
    out_constant = np.empty(n_slots, np.uint8)
    w64 = None if w is None else np.ascontiguousarray(w, np.float64)
    n_cand = np.ascontiguousarray(n_cand, np.int32)
    cst_p, lo_p, hi_p, out_vl, out_vr, _keep = _mono_args(
        mono_cst, mono_lo, mono_hi, n_slots
    )
    cdll.best_splits_classification(
        xb, y, node_id, _wptr(w64), n_rows, n_feat, n_bins, n_classes,
        frontier_lo, n_slots, n_cand, 1 if n_cand_per_slot else 0,
        0 if criterion == "entropy" else 1, float(min_child_weight),
        cst_p, lo_p, hi_p,
        out_feat, out_bin, out_cost, out_counts, out_constant,
        _wptr(out_vl), _wptr(out_vr),
    )
    out = {
        "feature": out_feat, "bin": out_bin, "cost": out_cost,
        "counts": out_counts, "constant": out_constant.astype(bool),
    }
    if out_vl is not None:
        out["v_left"] = out_vl
        out["v_right"] = out_vr
    return out


def best_splits_regression(
    xb, yv, node_id, w, *, n_bins, frontier_lo, n_slots, n_cand,
    n_cand_per_slot=False, min_child_weight=0.0,
    mono_cst=None, mono_lo=None, mono_hi=None,
):
    cdll = lib()
    if cdll is None:
        return None
    n_rows, n_feat = xb.shape
    out_feat = np.empty(n_slots, np.int32)
    out_bin = np.empty(n_slots, np.int32)
    out_cost = np.empty(n_slots, np.float64)
    out_counts = np.zeros((n_slots, 3), np.float64)
    out_constant = np.empty(n_slots, np.uint8)
    out_ymin = np.empty(n_slots, np.float64)
    out_ymax = np.empty(n_slots, np.float64)
    w64 = None if w is None else np.ascontiguousarray(w, np.float64)
    n_cand = np.ascontiguousarray(n_cand, np.int32)
    cst_p, lo_p, hi_p, out_vl, out_vr, _keep = _mono_args(
        mono_cst, mono_lo, mono_hi, n_slots
    )
    cdll.best_splits_regression(
        xb, np.ascontiguousarray(yv, np.float32), node_id, _wptr(w64),
        n_rows, n_feat, n_bins, frontier_lo, n_slots, n_cand,
        1 if n_cand_per_slot else 0, float(min_child_weight),
        cst_p, lo_p, hi_p,
        out_feat, out_bin, out_cost, out_counts, out_constant,
        out_ymin, out_ymax,
        _wptr(out_vl), _wptr(out_vr),
    )
    out = {
        "feature": out_feat, "bin": out_bin, "cost": out_cost,
        "counts": out_counts, "constant": out_constant.astype(bool),
        "ymin": out_ymin, "ymax": out_ymax,
    }
    if out_vl is not None:
        out["v_left"] = out_vl
        out["v_right"] = out_vr
    return out
