"""Host-side utilities: input validation and text rendering."""
