"""Model persistence — the checkpoint/resume story the reference lacks.

The reference's fitted model exists only as in-memory linked ``Node`` objects
(reference: ``mpitree/tree/_base.py:22``); nothing saves or loads it
(SURVEY.md §5). Here the struct-of-arrays tree makes persistence trivial: a
fitted estimator round-trips through one ``.npz`` file — flat arrays per tree
plus a JSON header with the constructor params and fit-time attributes.

``save_model(est, path)`` / ``load_model(path)`` cover every estimator in the
package (trees and forests, classification and regression). Loading never
executes code from the file (no pickle): arrays come from ``np.load`` with
``allow_pickle=False`` and the header is JSON.
"""

from __future__ import annotations

import dataclasses
import json
import os
import warnings

import numpy as np

from mpitree_tpu.core.tree_struct import TreeArrays

_TREE_FIELDS = [f.name for f in dataclasses.fields(TreeArrays)]

# Explicit allowlist: load_model instantiates nothing outside this table.
_ESTIMATOR_CLASSES = (
    "DecisionTreeClassifier",
    "ParallelDecisionTreeClassifier",
    "DecisionTreeRegressor",
    "RandomForestClassifier",
    "RandomForestRegressor",
    "ExtraTreesClassifier",
    "ExtraTreesRegressor",
    "GradientBoostingClassifier",
    "GradientBoostingRegressor",
)

# Classes whose fitted trees live in ``trees_`` (ensembles) vs ``tree_``.
_ENSEMBLE_PREFIXES = ("RandomForest", "ExtraTrees", "GradientBoosting")

# Scalar fitted attributes carried through the JSON header (both directions
# iterate this one tuple). n_iter_ / n_trees_per_iteration_ / _y_mean are
# harmlessly absent on estimators that don't define them.
_SCALAR_ATTRS = (
    "n_features_", "n_features_in_", "_y_mean", "n_classes_",
    "n_outputs_", "max_features_", "n_iter_", "n_trees_per_iteration_",
)


def _npz_path(path) -> str:
    """np.savez silently appends .npz; make save/load agree on the name."""
    path = os.fspath(path)
    return path if path.endswith(".npz") else path + ".npz"


def _json_params(params: dict) -> dict:
    """Constructor params with numpy scalars unwrapped; params that cannot be
    represented in JSON (e.g. a ``np.random.Generator`` random_state) are
    dropped with a warning — the loaded estimator falls back to the class
    default for those."""
    out = {}
    for k, v in params.items():
        if isinstance(v, np.generic):
            # genuine host boundary: np.generic params (never device
            # arrays) unwrap to JSON scalars one at a time
            v = v.item()  # graftlint: disable=GL01
        try:
            json.dumps(v)
        except TypeError:
            warnings.warn(
                f"save_model: dropping non-serializable param {k}={v!r}; "
                "the loaded estimator will use the class default",
                stacklevel=3,
            )
            continue
        out[k] = v
    return out


def _tree_arrays(prefix: str, tree: TreeArrays) -> dict:
    return {f"{prefix}{name}": getattr(tree, name) for name in _TREE_FIELDS}


def _read_tree(z, prefix: str) -> TreeArrays:
    # Fields absent from older files (e.g. impurity) fall back to the
    # dataclass default.
    return TreeArrays(**{
        name: z[f"{prefix}{name}"]
        for name in _TREE_FIELDS
        if f"{prefix}{name}" in z.files
    })


def save_model(estimator, path) -> None:
    """Serialize a fitted estimator to ``path`` (.npz, no pickle)."""
    cls = type(estimator)
    if cls.__name__ not in _ESTIMATOR_CLASSES:
        raise ValueError(f"cannot serialize {cls.__name__!r}")
    header = {
        "format": "mpitree_tpu-model",
        "version": 1,
        "class": cls.__name__,
        "params": _json_params(estimator.get_params()),
        "attrs": {},
    }
    arrays: dict = {}

    for attr in _SCALAR_ATTRS:
        if hasattr(estimator, attr):
            header["attrs"][attr] = getattr(estimator, attr)
    if hasattr(estimator, "feature_names_in_"):
        header["attrs"]["feature_names_in_"] = [
            str(c) for c in estimator.feature_names_in_
        ]

    if hasattr(estimator, "classes_"):
        arrays["classes_"] = np.asarray(estimator.classes_)
    if hasattr(estimator, "_baseline_raw"):  # boosting: (K,) f64 raw offsets
        arrays["_baseline_raw"] = np.asarray(estimator._baseline_raw)

    if hasattr(estimator, "trees_"):  # forest
        header["n_trees"] = len(estimator.trees_)
        for i, t in enumerate(estimator.trees_):
            arrays.update(_tree_arrays(f"tree{i}/", t))
    elif hasattr(estimator, "tree_"):
        header["n_trees"] = 1
        arrays.update(_tree_arrays("tree0/", estimator.tree_))
    else:
        raise ValueError("estimator is not fitted (no tree_/trees_)")

    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8
    )
    np.savez(_npz_path(path), **arrays)


def load_model(path):
    """Reconstruct the fitted estimator saved by :func:`save_model`."""
    import mpitree_tpu

    with np.load(_npz_path(path), allow_pickle=False) as z:
        if "__header__" not in z.files:
            raise ValueError(f"{path!r} is not an mpitree_tpu model file")
        header = json.loads(bytes(z["__header__"]).decode())
        if header.get("format") != "mpitree_tpu-model":
            raise ValueError(f"{path!r} is not an mpitree_tpu model file")
        if header["class"] not in _ESTIMATOR_CLASSES:
            raise ValueError(f"unknown estimator class {header['class']!r}")
        cls = getattr(mpitree_tpu, header["class"])
        est = cls(**header["params"])
        for attr in _SCALAR_ATTRS:
            if attr in header["attrs"]:
                setattr(est, attr, header["attrs"][attr])
        if "feature_names_in_" in header["attrs"]:
            est.feature_names_in_ = np.asarray(
                header["attrs"]["feature_names_in_"], dtype=object
            )
        if "classes_" in z.files:
            est.classes_ = z["classes_"]
        if "_baseline_raw" in z.files:
            est._baseline_raw = z["_baseline_raw"]
        trees = [_read_tree(z, f"tree{i}/") for i in range(header["n_trees"])]
    if header["class"].startswith(_ENSEMBLE_PREFIXES):
        # _TreeList (not a plain list) so the weak-ref stacked-predict cache
        # works on loaded ensembles exactly as on freshly fitted ones.
        from mpitree_tpu.models.forest import _TreeList

        est.trees_ = _TreeList(trees)
    else:
        est.tree_ = trees[0]
    return est
