"""Text rendering of a fitted tree — byte-parity with the reference.

Reproduces ``export_text`` from the reference
(``mpitree/tree/decision_tree.py:250-307``) including its quirks:

- glyphs ``┌──``/``├──``/``└──`` (``mpitree/tree/_base.py:16-20``);
- edge labels ``[<= t]`` / ``[> t]`` carry the *parent's* threshold, formatted
  to ``precision`` decimals (``decision_tree.py:270-276``); the root line has
  no edge label;
- child print order comes from ``sorted(node.children)`` driven by the
  side-effecting ``Node.__lt__`` (``_base.py:63-75``). Net behavior (verified
  against the notebook's stored renderings): if the *right* child is interior
  it prints first with ``├──`` and the left child follows with ``└──``;
  otherwise the children print (left ``├──``, right ``└──``);
- descendants of a node rendered with ``└──`` are indented with three spaces,
  all others with ``"│  "`` (``decision_tree.py:300-303``).
"""

from __future__ import annotations

import numpy as np

from mpitree_tpu.core.tree_struct import TreeArrays

_GLYPH_ROOT = "┌──"
_GLYPH_INTERIOR = "├──"
_GLYPH_LEAF = "└──"


def export_tree_text(
    tree: TreeArrays,
    *,
    feature_names=None,
    class_names=None,
    precision: int = 2,
    task: str = "classification",
) -> str:
    """Render ``tree`` exactly as the reference's ``export_text`` would."""
    lines: list[str] = []

    def label(i: int) -> str:
        if tree.feature[i] < 0:  # leaf
            if task == "regression":
                return f"value: {float(tree.value[i]):.{precision}f}"
            v = int(tree.value[i])
            return class_names[v] if class_names is not None else f"class: {v}"
        f = int(tree.feature[i])
        return feature_names[f] if feature_names is not None else f"feature_{f}"

    # Explicit stack (preorder): recursion depth would equal tree depth, and
    # the reference's own cell-5 workload (y = arange(n)) grows unbounded
    # chains past Python's frame limit.
    stack = [(0, _GLYPH_ROOT, "")] if tree.n_nodes else []
    while stack:
        i, glyph, prefix = stack.pop()
        text = f"{glyph} {label(i)}"
        p = int(tree.parent[i])
        if p >= 0:
            sign = "<=" if int(tree.left[p]) == i else ">"
            text += f" [{sign} {float(tree.threshold[p]):.{precision}f}]"
        lines.append(prefix + text)

        if tree.feature[i] < 0:
            continue
        l, r = int(tree.left[i]), int(tree.right[i])
        # Reference child ordering via Node.__lt__ side effects (_base.py:63-75):
        # an interior right child wins the first slot; otherwise (left, right).
        if tree.feature[r] >= 0:
            order = [(r, _GLYPH_INTERIOR), (l, _GLYPH_LEAF)]
        else:
            order = [(l, _GLYPH_INTERIOR), (r, _GLYPH_LEAF)]
        child_prefix = prefix + ("   " if glyph == _GLYPH_LEAF else "│  ")
        for c, g in reversed(order):
            stack.append((c, g, child_prefix))
    return "\n".join(lines)


def check_feature_names(names, n_features: int):
    if names is not None and len(names) < n_features:
        raise ValueError(
            f"feature_names has {len(names)} entries; need >= {n_features}"
        )
    return np.asarray(names) if names is not None else None
