"""Text rendering of a fitted tree — byte-parity with the reference.

Reproduces ``export_text`` from the reference
(``mpitree/tree/decision_tree.py:250-307``) including its quirks:

- glyphs ``┌──``/``├──``/``└──`` (``mpitree/tree/_base.py:16-20``);
- edge labels ``[<= t]`` / ``[> t]`` carry the *parent's* threshold, formatted
  to ``precision`` decimals (``decision_tree.py:270-276``); the root line has
  no edge label;
- child print order comes from ``sorted(node.children)`` driven by the
  side-effecting ``Node.__lt__`` (``_base.py:63-75``). Net behavior (verified
  against the notebook's stored renderings): if the *right* child is interior
  it prints first with ``├──`` and the left child follows with ``└──``;
  otherwise the children print (left ``├──``, right ``└──``);
- descendants of a node rendered with ``└──`` are indented with three spaces,
  all others with ``"│  "`` (``decision_tree.py:300-303``).
"""

from __future__ import annotations

import numpy as np

from mpitree_tpu.core.tree_struct import TreeArrays

_GLYPH_ROOT = "┌──"
_GLYPH_INTERIOR = "├──"
_GLYPH_LEAF = "└──"


def export_tree_text(
    tree: TreeArrays,
    *,
    feature_names=None,
    class_names=None,
    precision: int = 2,
    task: str = "classification",
) -> str:
    """Render ``tree`` exactly as the reference's ``export_text`` would."""
    lines: list[str] = []

    def label(i: int) -> str:
        if tree.feature[i] < 0:  # leaf
            if task == "regression":
                return f"value: {float(tree.value[i]):.{precision}f}"
            v = int(tree.value[i])
            return class_names[v] if class_names is not None else f"class: {v}"
        f = int(tree.feature[i])
        return feature_names[f] if feature_names is not None else f"feature_{f}"

    # Explicit stack (preorder): recursion depth would equal tree depth, and
    # the reference's own cell-5 workload (y = arange(n)) grows unbounded
    # chains past Python's frame limit.
    stack = [(0, _GLYPH_ROOT, "")] if tree.n_nodes else []
    while stack:
        i, glyph, prefix = stack.pop()
        text = f"{glyph} {label(i)}"
        p = int(tree.parent[i])
        if p >= 0:
            sign = "<=" if int(tree.left[p]) == i else ">"
            text += f" [{sign} {float(tree.threshold[p]):.{precision}f}]"
        lines.append(prefix + text)

        if tree.feature[i] < 0:
            continue
        l, r = int(tree.left[i]), int(tree.right[i])
        # Reference child ordering via Node.__lt__ side effects (_base.py:63-75):
        # an interior right child wins the first slot; otherwise (left, right).
        if tree.feature[r] >= 0:
            order = [(r, _GLYPH_INTERIOR), (l, _GLYPH_LEAF)]
        else:
            order = [(l, _GLYPH_INTERIOR), (r, _GLYPH_LEAF)]
        child_prefix = prefix + ("   " if glyph == _GLYPH_LEAF else "│  ")
        for c, g in reversed(order):
            stack.append((c, g, child_prefix))
    return "\n".join(lines)


def check_feature_names(names, n_features: int):
    if names is not None and len(names) < n_features:
        raise ValueError(
            f"feature_names has {len(names)} entries; need >= {n_features}"
        )
    return np.asarray(names) if names is not None else None


def export_tree_dot(
    tree: TreeArrays, *, feature_names=None, class_names=None,
    precision: int = 2, task: str = "classification",
    n_features: int | None = None,
) -> str:
    """Graphviz ``digraph`` source for a fitted tree (sklearn's
    ``export_graphviz`` idiom, adapted to this framework's node stats).

    Interior nodes show the split (``f <= t``), weighted sample count, and
    impurity; leaves show the class (or mean) and counts. Edge labels mark
    the True/False branches like sklearn's rendering.
    """
    width = (
        n_features if n_features is not None
        else int(tree.feature.max(initial=-1)) + 1
    )
    names = check_feature_names(feature_names, width)

    def esc(s) -> str:
        # DOT label strings: backslash first, then the quote delimiter.
        return str(s).replace("\\", "\\\\").replace('"', '\\"')

    def fname(f: int) -> str:
        return esc(names[f]) if names is not None else f"x[{f}]"

    lines = [
        "digraph Tree {",
        'node [shape=box, style="rounded", fontname="helvetica"];',
        'edge [fontname="helvetica"];',
    ]
    for i in range(tree.n_nodes):
        imp = float(tree.impurity[i])
        if tree.feature[i] >= 0:
            head = (
                f"{fname(int(tree.feature[i]))} <= "
                f"{float(tree.threshold[i]):.{precision}f}"
            )
        elif task == "classification":
            c = int(tree.value[i])
            head = (
                f"class = {esc(class_names[c])}" if class_names is not None
                else f"class = {c}"
            )
        else:
            head = f"value = {float(tree.count[i, 0]):.{precision}f}"
        if task == "classification":
            counts = ", ".join(
                str(int(v)) if float(v).is_integer() else f"{float(v):.4f}"
                for v in np.asarray(tree.count[i], dtype=float)
            )
            body = f"impurity = {imp:.{precision}f}\\ncounts = [{counts}]"
        else:
            body = (
                f"impurity = {imp:.{precision}f}\\n"
                f"n = {int(tree.n_node_samples[i])}"
            )
        lines.append(f'{i} [label="{head}\\n{body}"];')
        l_, r_ = int(tree.left[i]), int(tree.right[i])
        if l_ >= 0:
            extra = (
                ' [labeldistance=2.5, labelangle=45, headlabel="True"]'
                if i == 0 else ""
            )
            lines.append(f"{i} -> {l_}{extra};")
            extra = (
                ' [labeldistance=2.5, labelangle=-45, headlabel="False"]'
                if i == 0 else ""
            )
            lines.append(f"{i} -> {r_}{extra};")
    lines.append("}")
    return "\n".join(lines)


def tree_decision_path(tree: TreeArrays, X_binned_ids: np.ndarray):
    """CSR indicator of the nodes each sample traverses (sklearn's
    ``decision_path``), from per-sample LEAF ids: the parent chain is
    reconstructed host-side (parents always have smaller ids).
    """
    from scipy import sparse

    n = len(X_binned_ids)
    depth = tree.depth
    lens = depth[X_binned_ids] + 1
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=indptr[1:])
    indices = np.empty(int(indptr[-1]), np.int64)
    cur = np.asarray(X_binned_ids, np.int64).copy()
    # walk leaf -> root, filling each sample's segment from the back
    pos = indptr[1:].copy() - 1
    alive = np.ones(n, bool)
    while alive.any():
        indices[pos[alive]] = cur[alive]
        pos[alive] -= 1
        parents = tree.parent[cur[alive]]
        up = parents >= 0
        nxt = cur[alive]
        nxt[up] = parents[up]
        cur[alive] = nxt
        alive[alive] = up  # refine the mask to rows still below the root
    data = np.ones(len(indices), np.int8)
    return sparse.csr_matrix(
        (data, indices, indptr), shape=(n, tree.n_nodes)
    )
