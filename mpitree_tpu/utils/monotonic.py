"""sklearn ``monotonic_cst`` support: validation, bounds, value clipping.

The reference has no monotonicity constraints; this implements sklearn's
(>= 1.4) semantics, pinned from sklearn/tree/_classes.py (validation and
the class-0 sign flip), _criterion.pyx (``_check_monotonicity``,
``middle_value``, ``clip_node_value``) and _tree.pyx (bound propagation):

- a candidate split on a constrained feature is valid only when
  ``(v_left - v_right) * cst <= 0`` and both child values lie inside the
  node's propagated ``[lower, upper]`` bounds;
- children of a constrained split are bounded by
  ``mid = (v_left + v_right) / 2``;
- node values are clipped into their bounds for prediction.

"Value" is sklearn's internal convention: mean target for regression, and
the *class-0* fraction for binary classification — the estimator flips the
user-facing signs (which constrain the positive class) so the internal
arithmetic matches regression. All value arithmetic is float32
reciprocal-multiply (``f32(mass) * f32(1/n)``) on every engine, so
integer-weight fits stay engine-identical.

Bounds are a pure function of the finished tree (each split's child values
are its children's own aggregates), so clipping recomputes them here
instead of threading build-time state out of every engine.
"""

from __future__ import annotations

import numpy as np


def validate_monotonic_cst(monotonic_cst, n_features: int, *, task: str,
                           n_classes: int | None = None):
    """User array -> INTERNAL (F,) int8 signs, or None when unconstrained.

    Mirrors sklearn's validation (sklearn/tree/_classes.py): shape must be
    (n_features,), values in {-1, 0, 1}; classification must be binary and
    flips the signs (user signs constrain the positive class, internal
    arithmetic tracks the class-0 fraction).
    """
    if monotonic_cst is None:
        return None
    cst = np.asarray(monotonic_cst)
    if cst.ndim != 1 or cst.shape[0] != n_features:
        raise ValueError(
            f"monotonic_cst has shape {cst.shape} but the input data "
            f"X has {n_features} features."
        )
    if not np.isin(cst, (-1, 0, 1)).all():
        raise ValueError(
            "monotonic_cst must be None or an array-like of -1, 0 or 1, "
            f"but got {np.unique(cst)}"
        )
    cst = cst.astype(np.int8)
    if not cst.any():
        return None
    if task == "classification":
        if n_classes is not None and n_classes > 2:
            raise ValueError(
                "Monotonicity constraints are not supported with multiclass "
                "classification"
            )
        cst = -cst
    return cst


class BoundsStore:
    """Growable per-node ``[lower, upper]`` value bounds — the ONE host-side
    bound-propagation implementation (sklearn/_tree.pyx rule). The level
    loops (``core/builder.py``, ``core/host_builder.py``) both thread
    bounds through this store so the engine-identity contract cannot be
    broken by divergent copies; the fused engine runs the jnp twin of
    ``assign_children`` inside its while_loop body (same twin pattern as
    ``ops/sampling.py``).
    """

    def __init__(self) -> None:
        self.lo = np.full(256, -np.inf, np.float32)
        self.hi = np.full(256, np.inf, np.float32)

    def ensure(self, n: int) -> None:
        if n <= len(self.lo):
            return
        g_lo = np.full(max(n, 2 * len(self.lo)), -np.inf, np.float32)
        g_hi = np.full(len(g_lo), np.inf, np.float32)
        g_lo[: len(self.lo)] = self.lo
        g_hi[: len(self.hi)] = self.hi
        self.lo, self.hi = g_lo, g_hi

    def window(self, lo: int, take: int, size: int):
        """(size,) padded f32 lo/hi operands for frontier [lo, lo+take)."""
        lo_t = np.full(size, -np.inf, np.float32)
        hi_t = np.full(size, np.inf, np.float32)
        lo_t[:take] = self.lo[lo:lo + take]
        hi_t[:take] = self.hi[lo:lo + take]
        return lo_t, hi_t

    def assign_children(self, parent_ids, lefts, rights, v_left, v_right,
                        sign, n_total: int) -> None:
        """sklearn's bound propagation: a split on a constrained feature
        pins ``mid = (v_left + v_right)/2`` between the children; sign-0
        splits inherit the parent bounds."""
        self.ensure(n_total)
        mid = (v_left.astype(np.float32) + v_right.astype(np.float32)) \
            * np.float32(0.5)
        plo = self.lo[parent_ids].copy()
        phi = self.hi[parent_ids].copy()
        self.lo[lefts] = np.where(sign == -1, mid, plo)
        self.hi[lefts] = np.where(sign == 1, mid, phi)
        self.lo[rights] = np.where(sign == 1, mid, plo)
        self.hi[rights] = np.where(sign == -1, mid, phi)


def _node_values_f32(tree, task: str) -> np.ndarray:
    """Per-node internal value: class-0 fraction or mean target (f32).

    The reciprocal-multiply form matches the build engines bit for bit on
    integer-weight classification (counts and totals are exact in f32).
    """
    if task == "classification":
        c0 = tree.count[:, 0].astype(np.float32)
        n = tree.count.sum(axis=1).astype(np.float32)
        return c0 * (np.float32(1.0) / np.maximum(n, np.float32(1.0)))
    return tree.count[:, 0].astype(np.float32)


def tree_bounds(tree, cst: np.ndarray, task: str):
    """Recompute every node's ``[lower, upper]`` value bounds (f32).

    Vectorized by depth level (parents precede children in id order, as
    with ``ops/sampling.py:keys_for_tree``).
    """
    n = tree.n_nodes
    store = BoundsStore()
    store.ensure(n)
    if n == 0:
        return store.lo[:0], store.hi[:0]
    v = _node_values_f32(tree, task)
    for d in range(int(tree.depth.max(initial=0)) + 1):
        parents = np.flatnonzero((tree.depth == d) & (tree.left >= 0))
        if not len(parents):
            continue
        left = tree.left[parents]
        right = tree.right[parents]
        store.assign_children(
            parents, left, right, v[left], v[right],
            cst[tree.feature[parents]], n,
        )
    return store.lo[:n], store.hi[:n]


def clipped_class0(tree, cst: np.ndarray) -> np.ndarray:
    """Per-node bound-clipped class-0 fraction (binary classification).

    Forest ``predict_proba`` under constraints averages these — sklearn's
    forests average the clipped probabilities its trees store, and the
    averaged-raw-count alternative loses the monotone guarantee.
    """
    lo, hi = tree_bounds(tree, cst, "classification")
    return np.clip(_node_values_f32(tree, "classification"), lo, hi)


def clip_tree_values(tree, cst: np.ndarray, task: str) -> None:
    """sklearn's ``clip_node_value`` applied to the finished tree (in place).

    Classification: the clipped class-0 fraction decides the predicted
    label (label 0 iff clipped p0 >= 0.5 — argmax of the clipped
    probability pair with sklearn's lowest-index tie). Raw ``count`` stays
    untouched: this framework's ``predict_proba`` returns raw counts by
    reference contract, so the monotonicity guarantee applies to
    ``predict`` (documented divergence from sklearn, whose stored
    probabilities are clipped). Regression clips ``value``/``count``.
    """
    lo, hi = tree_bounds(tree, cst, task)
    if task == "classification":
        p0 = np.clip(_node_values_f32(tree, task), lo, hi)
        tree.value = np.where(p0 >= 0.5, 0, 1).astype(np.int32)
    else:
        v = np.clip(tree.count[:, 0], lo.astype(np.float64),
                    hi.astype(np.float64))
        tree.count[:, 0] = v
        tree.value = v.astype(np.float32)
