"""Minimal cost-complexity pruning (sklearn's ``ccp_alpha``), engine-free.

One host-side implementation serves every build engine: pruning operates on
the finished struct-of-arrays tree (``TreeArrays``), whose per-node f64
impurities, interior counts/means, and row counts all engines already
populate — so a pruned device tree equals the pruned host tree by
construction.

Semantics follow sklearn's weakest-link algorithm: with node risk
``R(t) = (w_t / w_root) * impurity(t)`` and subtree risk ``R(T_t)`` (sum of
leaf risks below ``t``), the effective alpha of an interior node is
``(R(t) - R(T_t)) / (|leaves(T_t)| - 1)``; nodes are collapsed weakest
first while their effective alpha is ``<= ccp_alpha``. Node weight ``w_t``
is the weighted class mass for classification and the training row count
for regression (per-node sample weights are not persisted — identical when
fits are unweighted, documented divergence otherwise).
"""

from __future__ import annotations

import heapq

import numpy as np

from mpitree_tpu.core.tree_struct import TreeArrays


def _node_weights(tree: TreeArrays, task: str) -> np.ndarray:
    if task == "classification":
        return tree.count.sum(axis=1).astype(np.float64)
    return tree.n_node_samples.astype(np.float64)


def _subtree_stats(tree: TreeArrays, r: np.ndarray):
    """(r_subtree, n_leaves) per node, one reverse pass (children ids are
    always larger than their parent's — every engine's allocation order)."""
    n = tree.n_nodes
    leaf = tree.feature < 0
    r_sub = np.where(leaf, r, 0.0)
    leaves = np.where(leaf, 1, 0).astype(np.int64)
    for i in range(n - 1, 0, -1):
        p = tree.parent[i]
        if p >= 0:
            r_sub[p] += r_sub[i]
            leaves[p] += leaves[i]
    return r_sub, leaves


def _descendants(tree: TreeArrays, t: int) -> list:
    out, stack = [], [t]
    while stack:
        i = stack.pop()
        l_, r_ = int(tree.left[i]), int(tree.right[i])
        for c in (l_, r_):
            if c >= 0:
                out.append(c)
                stack.append(c)
    return out


def ccp_prune(tree: TreeArrays, ccp_alpha: float, *, task: str) -> TreeArrays:
    """Return the minimal cost-complexity pruning of ``tree`` at
    ``ccp_alpha`` (the tree itself when ``ccp_alpha <= 0`` or it is a
    single leaf)."""
    if ccp_alpha < 0:
        raise ValueError(f"ccp_alpha must be >= 0, got {ccp_alpha!r}")
    if ccp_alpha == 0 or tree.n_nodes <= 1:
        return tree
    return _prune_impl(tree, ccp_alpha, task)


def _prune_impl(tree: TreeArrays, ccp_alpha: float, task: str,
                path_out: list | None = None) -> TreeArrays:
    """Weakest-link pruning at ``ccp_alpha`` WITHOUT the public zero
    short-circuit: collapses every node whose effective alpha is
    ``<= ccp_alpha``, including exactly zero — ``pruning_path`` relies on
    that to make progress when a split has zero impurity gain.

    ``path_out``: when given, every collapse appends
    ``(effective_alpha, total_leaf_risk_after)`` — the heap already pops
    collapses in ascending effective alpha, so one sweep with
    ``ccp_alpha=inf`` yields the whole pruning path.
    """
    n = tree.n_nodes
    w = _node_weights(tree, task)
    r = (w / max(w[0], 1e-300)) * np.asarray(tree.impurity, np.float64)
    r_sub, leaves = _subtree_stats(tree, r)

    interior = np.nonzero(tree.feature >= 0)[0]
    removed = np.zeros(n, bool)   # node no longer exists (inside a cut)
    collapsed = np.zeros(n, bool)  # interior node turned leaf

    def alpha_eff(t: int) -> float:
        return (r[t] - r_sub[t]) / max(leaves[t] - 1, 1)

    # Lazy heap: stale entries (outdated alpha, removed/collapsed nodes)
    # are dropped at pop time by re-checking the current value.
    heap = [(alpha_eff(t), int(t)) for t in interior]
    heapq.heapify(heap)
    while heap:
        a, t = heapq.heappop(heap)
        if removed[t] or collapsed[t] or tree.feature[t] < 0:
            continue
        cur = alpha_eff(t)
        if a != cur:  # stale — ancestors' stats moved since this push
            heapq.heappush(heap, (cur, t))
            continue
        if a > ccp_alpha:
            break
        collapsed[t] = True
        for d in _descendants(tree, t):
            removed[d] = True
        d_r, d_leaves = r[t] - r_sub[t], 1 - leaves[t]
        r_sub[t] = r[t]
        leaves[t] = 1
        p = int(tree.parent[t])
        while p >= 0:
            r_sub[p] += d_r
            leaves[p] += d_leaves
            if not (removed[p] or collapsed[p]):
                heapq.heappush(heap, (alpha_eff(p), p))
            p = int(tree.parent[p])
        if path_out is not None:
            path_out.append((max(a, 0.0), float(r_sub[0])))

    if not collapsed.any():
        return tree

    # Compact: drop removed nodes, keep original order (preserves the
    # children-after-parent invariant every consumer relies on).
    keep = ~removed
    new_id = np.cumsum(keep) - 1
    feature = tree.feature[keep].copy()
    left = tree.left[keep].copy()
    right = tree.right[keep].copy()
    threshold = tree.threshold[keep].copy()
    is_cut = collapsed[keep]
    feature[is_cut] = -1
    left[is_cut] = -1
    right[is_cut] = -1
    threshold[is_cut] = np.nan
    remap = np.where(
        (left >= 0), new_id[np.clip(left, 0, None)], -1
    ).astype(np.int32)
    left = remap
    right = np.where(
        (right >= 0), new_id[np.clip(right, 0, None)], -1
    ).astype(np.int32)
    parent = tree.parent[keep]
    parent = np.where(
        parent >= 0, new_id[np.clip(parent, 0, None)], -1
    ).astype(np.int32)

    return TreeArrays(
        feature=feature.astype(np.int32),
        threshold=threshold,
        left=left,
        right=right,
        parent=parent,
        depth=tree.depth[keep].copy(),
        value=tree.value[keep].copy(),
        count=tree.count[keep].copy(),
        n_node_samples=tree.n_node_samples[keep].copy(),
        impurity=tree.impurity[keep].copy(),
    )


def pruning_path(tree: TreeArrays, *, task: str):
    """(ccp_alphas, impurities) — sklearn's ``cost_complexity_pruning_path``
    analogue: the sequence of effective alphas at which the tree collapses,
    and the total leaf impurity after each collapse.

    ONE weakest-link sweep (``_prune_impl`` at ``inf`` with ``path_out``)
    produces the whole path — collapses with equal effective alpha merge
    into one step, keeping the last (fully collapsed) impurity.
    """
    w = _node_weights(tree, task)
    r = (w / max(w[0], 1e-300)) * np.asarray(tree.impurity, np.float64)
    rs, _ = _subtree_stats(tree, r)
    alphas, impurities = [0.0], [float(rs[0])]
    steps: list = []
    _prune_impl(tree, np.inf, task, path_out=steps)
    for a, imp in steps:
        if alphas and abs(a - alphas[-1]) <= 1e-300:
            impurities[-1] = imp  # simultaneous collapse at equal alpha
        else:
            alphas.append(a)
            impurities.append(imp)
    return np.asarray(alphas), np.asarray(impurities)


def pruning_path_for(estimator, X, y, sample_weight=None):
    """Shared body of the estimators\' ``cost_complexity_pruning_path``:
    fit an unpruned clone, return sklearn\'s Bunch of path alphas and
    impurities."""
    from sklearn.base import clone
    from sklearn.utils import Bunch

    est = clone(estimator)
    est.ccp_alpha = 0.0
    est.fit(X, y, sample_weight=sample_weight)
    alphas, impurities = pruning_path(est.tree_, task=estimator._task)
    return Bunch(ccp_alphas=alphas, impurities=impurities)
