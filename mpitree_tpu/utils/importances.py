"""Impurity-based feature importances from the struct-of-arrays tree.

The reference exposes no importances; sklearn users expect
``feature_importances_`` (mean decrease in impurity). Computed host-side from
the stored per-node class counts / values: for every interior node,

    importance[feature] += n/N * impurity(node)
                           - n_l/N * impurity(left) - n_r/N * impurity(right)

normalized to sum to 1 (sklearn's convention). Classification impurity uses
the tree's training criterion; regression uses variance, which is not
recoverable from stored node means alone — regression trees therefore use
weighted split counts (``kind="split"``) unless per-node SSE is available.
"""

from __future__ import annotations

import numpy as np

from mpitree_tpu.core.tree_struct import TreeArrays


def _class_impurity(counts: np.ndarray, criterion: str) -> np.ndarray:
    """(M, C) counts -> (M,) impurity per node."""
    n = counts.sum(axis=1, keepdims=True).astype(np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        p = counts / np.maximum(n, 1.0)
        if criterion == "gini":
            return 1.0 - (p * p).sum(axis=1)
        t = np.where(counts > 0, p * np.log2(np.maximum(p, 1e-300)), 0.0)
        return -t.sum(axis=1)


def feature_importances(
    tree: TreeArrays, n_features: int, *, criterion: str = "entropy",
    task: str = "classification",
) -> np.ndarray:
    """Normalized mean-decrease-in-impurity importances, shape (n_features,)."""
    imp = np.zeros(n_features, np.float64)
    interior = np.flatnonzero(tree.feature >= 0)
    if len(interior) == 0:
        return imp
    n = tree.n_node_samples.astype(np.float64)
    total = max(n[0], 1.0)

    if task == "classification":
        node_imp = _class_impurity(tree.count.astype(np.float64), criterion)
        left, right = tree.left[interior], tree.right[interior]
        decrease = (
            n[interior] * node_imp[interior]
            - n[left] * node_imp[left]
            - n[right] * node_imp[right]
        ) / total
    else:
        # Node variance is not stored for regression; weight each split by
        # the fraction of samples it touches (split-count importance).
        decrease = n[interior] / total

    np.add.at(imp, tree.feature[interior], np.maximum(decrease, 0.0))
    s = imp.sum()
    return imp / s if s > 0 else imp
