"""Impurity-based feature importances from the struct-of-arrays tree.

The reference exposes no importances; sklearn users expect
``feature_importances_`` (mean decrease in impurity). Computed host-side: for
every interior node,

    importance[feature] += n/N * impurity(node)
                           - n_l/N * impurity(left) - n_r/N * impurity(right)

normalized to sum to 1 (sklearn's convention). Classification impurity is
recomputed exactly from the stored per-node class counts under the training
criterion; regression uses the per-node variance stored in
``TreeArrays.impurity`` (an exact f64 pass over the final row assignments —
see ``builder.refit_regression_values``).
"""

from __future__ import annotations

import numpy as np

from mpitree_tpu.core.tree_struct import TreeArrays


def class_node_impurity(counts: np.ndarray, criterion: str) -> np.ndarray:
    """(M, C) class counts -> (M,) entropy/gini impurity per node, f64."""
    counts = counts.astype(np.float64)
    n = counts.sum(axis=1, keepdims=True)
    with np.errstate(divide="ignore", invalid="ignore"):
        p = counts / np.maximum(n, 1.0)
        if criterion == "gini":
            return np.where(n[:, 0] > 0, 1.0 - (p * p).sum(axis=1), 0.0)
        t = np.where(counts > 0, p * np.log2(np.maximum(p, 1e-300)), 0.0)
        return -t.sum(axis=1)


def moment_node_impurity(moments: np.ndarray) -> np.ndarray:
    """(M, 3) ``(w, w*y, w*y^2)`` moments -> (M,) variance per node, f64.

    Only a float32-accuracy fallback for builds without a refit pass; the
    exact values come from ``builder.refit_regression_values``.
    """
    m = moments.astype(np.float64)
    w = np.maximum(m[:, 0], 1e-300)
    mean = m[:, 1] / w
    return np.maximum(m[:, 2] / w - mean * mean, 0.0)


def feature_importances(
    tree: TreeArrays, n_features: int, *, criterion: str = "entropy",
    task: str = "classification",
) -> np.ndarray:
    """Normalized mean-decrease-in-impurity importances, shape (n_features,)."""
    imp = np.zeros(n_features, np.float64)
    interior = np.flatnonzero(tree.feature >= 0)
    if len(interior) == 0:
        return imp
    n = tree.n_node_samples.astype(np.float64)
    total = max(n[0], 1.0)

    if task == "classification":
        node_imp = class_node_impurity(tree.count, criterion)
    else:
        node_imp = tree.impurity
        if not node_imp.any():
            # Trees saved before the impurity field existed load with zeros;
            # returning an all-zero vector would silently read as "no
            # signal". Fall back to the pre-field behavior.
            import warnings

            warnings.warn(
                "regression tree has no stored per-node impurity (saved by "
                "an older version?); falling back to split-count "
                "importances — refit to get exact MDI",
                stacklevel=2,
            )
            decrease = n[interior] / total
            np.add.at(imp, tree.feature[interior], decrease)
            s = imp.sum()
            return imp / s if s > 0 else imp
    left, right = tree.left[interior], tree.right[interior]
    decrease = (
        n[interior] * node_imp[interior]
        - n[left] * node_imp[left]
        - n[right] * node_imp[right]
    ) / total

    np.add.at(imp, tree.feature[interior], np.maximum(decrease, 0.0))
    s = imp.sum()
    return imp / s if s > 0 else imp
