"""Tracing/profiling + the debug determinism check the reference lacks.

SURVEY.md §5: the reference's only observability is a hand-run ``time.time()``
sweep in a notebook, and nothing verifies its replicated-determinism
correctness invariant. Here:

- :class:`PhaseTimer` collects per-phase wall-clock (bin / shard / split /
  counts / update) for a build; estimators expose it as ``fit_stats_`` when
  ``MPITREE_TPU_PROFILE=1``. Library callers can pass their own timer to
  ``build_tree(..., timer=...)``.
- :func:`trace` wraps ``jax.profiler.trace`` for device-level traces viewable
  in TensorBoard/Perfetto.
- :func:`assert_replicated` is the race-detection analogue: in debug mode the
  builder asserts that the split decision every device computed is identical
  (``psum`` of a per-device fingerprint must equal ``n_devices * fingerprint``)
  — the XLA restatement of the reference's every-rank-agrees contract
  (reference: ``mpitree/tree/decision_tree.py:408-419``).
"""

from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict

import jax
import jax.numpy as jnp
from jax import lax


def profiling_enabled() -> bool:
    return os.environ.get("MPITREE_TPU_PROFILE", "") not in ("", "0")


def debug_checks_enabled() -> bool:
    return os.environ.get("MPITREE_TPU_DEBUG", "") not in ("", "0")


class PhaseTimer:
    """Accumulates wall-clock seconds and call counts per named phase."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.seconds: dict = defaultdict(float)
        self.calls: dict = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] += time.perf_counter() - t0
            self.calls[name] += 1

    def summary(self) -> dict:
        return {
            name: {"seconds": round(self.seconds[name], 4), "calls": self.calls[name]}
            for name in sorted(self.seconds)
        }

    def __repr__(self):
        total = sum(self.seconds.values())
        rows = [
            f"  {name:<12} {self.seconds[name]:8.3f}s  x{self.calls[name]}"
            for name in sorted(self.seconds, key=self.seconds.get, reverse=True)
        ]
        body = "\n".join(rows)
        return f"PhaseTimer(total={total:.3f}s\n{body}\n)"


@contextlib.contextmanager
def trace(log_dir: str):
    """Device-level profiler trace (TensorBoard/Perfetto), or no-op if the
    profiler is unavailable on the current platform. Exceptions raised by the
    traced block propagate unchanged."""
    ctx = jax.profiler.trace(log_dir)
    try:
        ctx.__enter__()
        entered = True
    except Exception:
        entered = False
    try:
        yield
    finally:
        if entered:
            ctx.__exit__(None, None, None)


def replication_fingerprint(*arrays) -> jax.Array:
    """Order-sensitive fingerprint of per-device integer-valued arrays (call
    inside shard_map). Returns a small integer as f32 (< 2**16) so that
    ``psum`` over any mesh size and reduction order is *exact* — a float-sum
    fingerprint would trip the check on benign reduction rounding."""
    acc = jnp.uint32(0)
    for a in arrays:
        ai = a.astype(jnp.int32).ravel().astype(jnp.uint32)
        weights = (jnp.arange(ai.shape[0], dtype=jnp.uint32) % 8191) + 1
        acc = acc + jnp.sum(ai * weights)  # wraps mod 2**32, deterministic
    return (acc % jnp.uint32(1 << 16)).astype(jnp.float32)


def assert_replicated(fingerprint: jax.Array, axis: str) -> jax.Array:
    """Inside shard_map: returns |psum(fp) - n*fp|, which must be 0 when the
    value is truly replicated. The caller checks the hostside result."""
    n = lax.psum(jnp.float32(1), axis)
    return jnp.abs(lax.psum(fingerprint, axis) - n * fingerprint)
