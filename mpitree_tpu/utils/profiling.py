"""Tracing/profiling + the debug determinism check the reference lacks.

SURVEY.md §5: the reference's only observability is a hand-run ``time.time()``
sweep in a notebook, and nothing verifies its replicated-determinism
correctness invariant. Here:

- :class:`PhaseTimer` collects per-phase wall-clock (bin / shard / split /
  counts / update) for a build; estimators expose it as ``fit_stats_`` when
  ``MPITREE_TPU_PROFILE=1``. Library callers can pass their own timer to
  ``build_tree(..., timer=...)``.
- :func:`trace` wraps ``jax.profiler.trace`` for device-level traces viewable
  in TensorBoard/Perfetto.
- :func:`assert_replicated` is the race-detection analogue: in debug mode the
  builder asserts that the split decision every device computed is identical
  (``psum`` of a per-device fingerprint must equal ``n_devices * fingerprint``)
  — the XLA restatement of the reference's every-rank-agrees contract
  (reference: ``mpitree/tree/decision_tree.py:408-419``).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax
import jax.numpy as jnp
from jax import lax

from mpitree_tpu.config import knobs


def profiling_enabled() -> bool:
    return knobs.value("MPITREE_TPU_PROFILE")


def debug_checks_enabled() -> bool:
    return knobs.value("MPITREE_TPU_DEBUG")


class PhaseTimer:
    """Accumulates wall-clock seconds and call counts per named phase.

    Also the base of the observability API: the no-op hooks below are the
    structured-record channels ``mpitree_tpu.obs.BuildObserver`` overrides
    (counters, decisions, typed events, per-level rows, collective and
    compile accounting). The engines call them unconditionally, so a
    library caller passing a plain ``PhaseTimer`` to ``build_tree(...,
    timer=...)`` keeps working and pays nothing for the record.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.seconds: dict = defaultdict(float)
        self.calls: dict = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] += time.perf_counter() - t0
            self.calls[name] += 1

    # obs-native alias: ``with timer.span("bin"):`` == ``timer.phase``.
    span = phase

    # -- observability hooks (no-ops; see mpitree_tpu.obs.BuildObserver) ---
    def counter(self, name: str, inc=1) -> None:
        pass

    def event(self, kind: str, message: str, **data) -> None:
        pass

    def decision(self, key: str, value, reason: str | None = None,
                 **inputs) -> None:
        pass

    def set_mesh(self, mesh) -> None:
        pass

    def level(self, **row) -> None:
        pass

    def collective(self, site: str, *, calls: int = 1,
                   nbytes: int = 0) -> None:
        pass

    def compile_note(self, entry: str, key, cache_size: int = 64) -> bool:
        return False

    def memory_plan(self, plan) -> None:
        """No-op twin of BuildObserver.memory_plan (the obs.memory
        device/host ledger); plain timers pay nothing."""

    # Engines compute per-level state fingerprints (obs/fingerprint.py)
    # only when the timer wants them; a plain PhaseTimer doesn't, so
    # library callers pay neither the hashing nor the row storage.
    wants_fingerprints = False

    def fingerprint_tree(self, rows) -> None:
        """No-op twin of BuildObserver.fingerprint_tree."""

    @contextlib.contextmanager
    def compile_attribution(self, entry: str, fresh: bool = True):
        """No-op twin of BuildObserver.compile_attribution (cold-dispatch
        wall attribution per jit entry point); plain timers pay nothing."""
        yield

    def round(self, **row) -> None:
        pass

    def summary(self) -> dict:
        return {
            name: {"seconds": round(self.seconds[name], 4), "calls": self.calls[name]}
            for name in sorted(self.seconds)
        }

    def __repr__(self):
        total = sum(self.seconds.values())
        rows = [
            f"  {name:<12} {self.seconds[name]:8.3f}s  x{self.calls[name]}"
            for name in sorted(self.seconds, key=self.seconds.get, reverse=True)
        ]
        body = "\n".join(rows)
        return f"PhaseTimer(total={total:.3f}s\n{body}\n)"


@contextlib.contextmanager
def trace(log_dir: str, on_event=None):
    """Device-level profiler trace (TensorBoard/Perfetto), or no-op if the
    profiler is unavailable on the current platform. Exceptions raised by the
    traced block propagate unchanged.

    ``jax.profiler.trace.__enter__`` can raise AFTER partially starting the
    backend profiler (e.g. the log-dir write fails once the collector is
    live); a swallowed error would then leave the profiler running and every
    later ``trace`` failing with "profiler already active". On entry failure
    we stop any half-started trace and report a structured
    ``trace_unavailable`` event through ``on_event(kind, message)`` (e.g.
    ``BuildObserver.event``) instead of silence.
    """
    ctx = jax.profiler.trace(log_dir)
    entered = False
    try:
        ctx.__enter__()
        entered = True
    except Exception as e:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass  # nothing was started — the usual unavailable-platform case
        if on_event is not None:
            on_event("trace_unavailable", f"{type(e).__name__}: {e}")
    try:
        yield
    finally:
        if entered:
            ctx.__exit__(None, None, None)


def replication_fingerprint(*arrays) -> jax.Array:
    """Order-sensitive fingerprint of per-device integer-valued arrays (call
    inside shard_map). Returns a small integer as f32 (< 2**16) so that
    ``psum`` over any mesh size and reduction order is *exact* — a float-sum
    fingerprint would trip the check on benign reduction rounding."""
    acc = jnp.uint32(0)
    for a in arrays:
        ai = a.astype(jnp.int32).ravel().astype(jnp.uint32)
        weights = (jnp.arange(ai.shape[0], dtype=jnp.uint32) % 8191) + 1
        acc = acc + jnp.sum(ai * weights)  # wraps mod 2**32, deterministic
    return (acc % jnp.uint32(1 << 16)).astype(jnp.float32)


# Two scalar psums per probe — priced by collective.replication_check_bytes
# and recorded by the builder's determinism check.
# graftlint: wire=replication_check
def assert_replicated(fingerprint: jax.Array, axis) -> jax.Array:
    """Inside shard_map: returns |psum(fp) - n*fp|, which must be 0 when the
    value is truly replicated. The caller checks the hostside result.
    ``axis`` may be one mesh axis name or a tuple of them (the 2-D
    (data, feature) mesh checks replication across both)."""
    n = lax.psum(jnp.float32(1), axis)
    return jnp.abs(lax.psum(fingerprint, axis) - n * fingerprint)
