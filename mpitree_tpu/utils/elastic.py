"""Failure detection and elastic recovery — SURVEY.md §5's missing subsystem.

The reference has no failure story at all: a rank dying inside
``comm.allgather`` deadlocks or aborts the whole job (reference:
``mpitree/tree/decision_tree.py:456``; SURVEY §5 "Failure detection").
The TPU-native analogue of a lost rank is a lost/hung accelerator client —
on this project's tunneled transport an everyday event, observed as
``XlaRuntimeError`` (UNAVAILABLE / DEADLINE_EXCEEDED / INTERNAL) or a
PJRT wire error surfacing as ``RuntimeError``.

Two mechanisms, both estimator-integrated:

- **Device failover** (:func:`device_failover`): every estimator wraps its
  device-engine build; a *device* failure (never a user error — those
  re-raise untouched) logs a warning and rebuilds on the host tier, which
  consumes the same binned matrix and produces the identical tree (the
  engine-identity contract, ``tests/test_engine_identity.py``). The job
  completes where the reference's would abort. Opt out with
  ``MPITREE_TPU_ELASTIC=0`` (then device failures raise).

- **Forest checkpointing** (:class:`ForestCheckpoint`): with
  ``RandomForestClassifier(checkpoint=path)`` the build runs in tree-axis
  sized groups, each group persisted (pickle-free ``.npz``) as it
  completes. A crashed or preempted fit re-run with the same params and
  data resumes after the last finished group — a fingerprint of params,
  data, and RNG state guards against silently resuming onto different
  inputs. Per-tree RNG draws happen up front either way, so a resumed
  forest is bit-identical to an uninterrupted one (pinned in
  ``tests/test_elastic.py``).
"""

from __future__ import annotations

import hashlib
import json
import os
import warnings

import numpy as np

# Status markers that identify an accelerator/transport loss inside an
# exception message. Deliberately conservative: program bugs
# (INVALID_ARGUMENT shape errors, ENOSPC, arbitrary RuntimeErrors) must
# re-raise, or a device-engine regression would silently pass CI on the
# 10-100x slower host tier.
# Matching is CASE-SENSITIVE on purpose: the uppercase entries are gRPC
# status codes exactly as PJRT prints them — lowercasing would make
# ordinary prose ("Resource temporarily unavailable", "launch aborted")
# classify as transport loss.
_TRANSPORT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "DATA_LOSS",
    "ABORTED",
    "CANCELLED",
    "Connection",
    "connection",
    "socket",
    "PJRT",
    "pjrt",
)


def elastic_enabled() -> bool:
    return os.environ.get("MPITREE_TPU_ELASTIC", "1") != "0"


def is_device_failure(exc: BaseException) -> bool:
    """True when ``exc`` looks like an accelerator/runtime loss.

    ``XlaRuntimeError`` (jaxlib) / jax's ``JaxRuntimeError`` qualify only
    when they carry a transport status (UNAVAILABLE, DEADLINE_EXCEEDED,
    ...; INTERNAL also qualifies there — runtime/compiler crashes surface
    so) — an INVALID_ARGUMENT program bug re-raises. A plain
    ``RuntimeError``/``OSError`` qualifies only on an explicit transport
    marker (ENOSPC's "No space left on device" does not). ValueError &
    friends — user errors — never do.
    """
    name = type(exc).__name__
    msg = str(exc)
    if name in ("XlaRuntimeError", "JaxRuntimeError"):
        return any(m in msg for m in _TRANSPORT_MARKERS + ("INTERNAL",))
    if isinstance(exc, ConnectionError):
        return True  # ConnectionReset/Refused/Aborted ARE transport losses
    if isinstance(exc, (RuntimeError, OSError)):
        return any(m in msg for m in _TRANSPORT_MARKERS)
    return False


def device_failover(device_fn, host_fn, *, what: str):
    """Run ``device_fn``; on a *device* failure fall back to ``host_fn``.

    The TPU-native answer to the reference's abort-the-job failure mode:
    the host tier consumes the same binned inputs and produces the
    identical tree, so losing the accelerator mid-fit costs wall-clock,
    not the job. User errors re-raise untouched; with elasticity disabled
    (``MPITREE_TPU_ELASTIC=0``) device failures re-raise too.
    """
    try:
        return device_fn()
    except Exception as e:  # noqa: BLE001 — classified, not swallowed
        if not (elastic_enabled() and is_device_failure(e)):
            raise
        warnings.warn(
            f"device failure during {what} ({type(e).__name__}: "
            f"{str(e)[:200]}); rebuilding on the host tier",
            stacklevel=2,
        )
        return host_fn()


# --------------------------------------------------------------------------
# Forest checkpoint/resume
# --------------------------------------------------------------------------

_CKPT_VERSION = 1


def _fingerprint(params: dict, X: np.ndarray, y: np.ndarray,
                 sample_weight) -> str:
    """Stable digest of everything that determines the fitted forest.

    Hashes the constructor params (JSON), the data's shape/dtype and
    content, targets, and weights — resuming onto different inputs would
    silently mix two forests, so a mismatch restarts from scratch instead.
    """
    h = hashlib.sha256()
    h.update(json.dumps(params, sort_keys=True, default=str).encode())
    for a in (X, y):
        a = np.ascontiguousarray(a)
        h.update(str((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    if sample_weight is not None:
        h.update(np.ascontiguousarray(sample_weight).tobytes())
    return h.hexdigest()


class ForestCheckpoint:
    """Pickle-free incremental persistence for a forest build.

    One ``.npz`` file holding the fingerprint, the completed-tree count,
    and each finished tree's arrays (post-refine, i.e. final). Append is
    atomic-by-rename so a crash mid-write leaves the previous state.
    """

    def __init__(self, path: str, fingerprint: str):
        self.path = os.fspath(path)
        self.fingerprint = fingerprint
        self.trees: list = []

    @classmethod
    def open(cls, path, params: dict, X, y, sample_weight) -> ForestCheckpoint:
        """Load a resumable checkpoint, or a fresh one on any mismatch."""
        fp = _fingerprint(params, X, y, sample_weight)
        ck = cls(path, fp)
        if not os.path.exists(ck.path):
            return ck
        try:
            from mpitree_tpu.utils.serialize import _read_tree

            with np.load(ck.path, allow_pickle=False) as z:
                head = json.loads(str(z["header"]))
                if (head.get("version") != _CKPT_VERSION
                        or head.get("fingerprint") != fp):
                    raise ValueError("fingerprint mismatch")
                ck.trees = [
                    _read_tree(z, f"tree{i}_")
                    for i in range(int(head["n_trees"]))
                ]
        except Exception as e:  # noqa: BLE001 — a bad checkpoint restarts
            warnings.warn(
                f"forest checkpoint at {ck.path} not resumable "
                f"({type(e).__name__}: {e}); starting fresh",
                stacklevel=3,
            )
            ck.trees = []
        return ck

    def append(self, new_trees: list) -> None:
        """Persist ``new_trees`` as completed (write-temp + rename).

        Each append rewrites the whole file (the price of one atomic
        ``.npz``), so callers append at GROUP granularity — the forest
        flushes per device-program batch, never per tree — keeping total
        write cost O(groups x forest size), and recovery granularity = one
        group.
        """
        from mpitree_tpu.utils.serialize import _tree_arrays

        self.trees.extend(new_trees)
        payload: dict = {
            "header": json.dumps({
                "version": _CKPT_VERSION,
                "fingerprint": self.fingerprint,
                "n_trees": len(self.trees),
            })
        }
        for i, t in enumerate(self.trees):
            payload.update(_tree_arrays(f"tree{i}_", t))
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, self.path)

    def done(self) -> None:
        """Remove the file once the full fit has succeeded."""
        try:
            os.unlink(self.path)
        except OSError:
            pass
