"""Back-compat shim — this subsystem is now ``mpitree_tpu.resilience``.

PR 6 promoted the single-module failure story here (device-failure
classification, host failover, forest checkpointing) into a full
subsystem with a retry/backoff ladder, sharded checkpoints that also
cover boosting rounds, and a deterministic chaos layer. Import from
``mpitree_tpu.resilience`` going forward; this module re-exports the
historical names so existing callers and serialized references keep
working.
"""

from mpitree_tpu.resilience.checkpoint import (  # noqa: F401
    BoostCheckpoint,
    BuildCheckpoint,
    ForestCheckpoint,
    _fingerprint,
)
from mpitree_tpu.resilience.config import (  # noqa: F401
    ResilienceConfig,
    elastic_enabled,
)
from mpitree_tpu.resilience.failure import (  # noqa: F401
    _TRANSPORT_MARKERS,
    is_device_failure,
    is_transient_failure,
)
from mpitree_tpu.resilience.retry import (  # noqa: F401
    device_failover,
    retry_device,
)

__all__ = [
    "BoostCheckpoint",
    "BuildCheckpoint",
    "ForestCheckpoint",
    "ResilienceConfig",
    "device_failover",
    "elastic_enabled",
    "is_device_failure",
    "is_transient_failure",
    "retry_device",
]
