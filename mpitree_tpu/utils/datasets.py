"""Benchmark datasets.

The north-star workload is covtype (581012 x 54; 10 quantitative + 44 binary
one-hot soil/wilderness columns; 7 imbalanced classes — the BASELINE.json
target). The benchmark environment has no network, so ``covtype_like``
generates a deterministic stand-in with the same shape and the same
*structure*: continuous features with heterogeneous scales, one-hot binary
blocks derived from latent categories, and labels produced by a noisy
axis-aligned decision structure (so depth-20 trees are meaningfully better
than shallow ones, as on real covtype). ``load_covtype`` prefers the real
dataset when a cached copy exists.
"""

from __future__ import annotations

import numpy as np


def covtype_like(n_samples: int = 581012, seed: int = 0):
    """Deterministic covtype-shaped classification problem (n x 54, 7 classes)."""
    rng = np.random.default_rng(seed)
    n = n_samples

    # 10 quantitative columns with covtype-ish heterogeneous scales.
    elev = rng.normal(2800, 400, n)
    aspect = rng.uniform(0, 360, n)
    slope = rng.gamma(2.0, 7.0, n)
    h_hydro = rng.gamma(1.5, 180.0, n)
    v_hydro = rng.normal(45, 60, n)
    h_road = rng.gamma(1.8, 1300.0, n)
    hill_9 = np.clip(rng.normal(212, 27, n), 0, 254)
    hill_noon = np.clip(rng.normal(223, 20, n), 0, 254)
    hill_3 = np.clip(rng.normal(143, 38, n), 0, 254)
    h_fire = rng.gamma(1.7, 1100.0, n)
    quant = np.column_stack(
        [elev, aspect, slope, h_hydro, v_hydro, h_road, hill_9, hill_noon,
         hill_3, h_fire]
    )

    # 4 wilderness-area + 40 soil-type one-hot columns from latent categories
    # correlated with elevation (as in the real data).
    wild_logits = rng.normal(size=(n, 4)) + np.column_stack(
        [elev / 400.0, -elev / 800.0, np.zeros(n), np.zeros(n)]
    )
    wild = np.eye(4, dtype=np.float64)[wild_logits.argmax(1)]
    soil_latent = (elev - 1800) / 250.0 + rng.normal(0, 2.0, n)
    soil_idx = np.clip(soil_latent.astype(int) % 40, 0, 39)
    soil = np.zeros((n, 40))
    soil[np.arange(n), soil_idx] = 1.0

    X = np.column_stack([quant, wild, soil]).astype(np.float32)

    # Labels: noisy axis-aligned rules on several features (tree-learnable,
    # imbalanced like covtype's 7 cover types).
    score = np.zeros(n)
    score += 2.0 * (elev > 3000)
    score += 1.0 * (elev > 3250)
    score -= 1.5 * (elev < 2400)
    score += 1.0 * (h_hydro < 120)
    score -= 1.0 * (slope > 22)
    score += 0.8 * (hill_noon > 230)
    score += 0.6 * wild[:, 0] - 0.7 * wild[:, 3]
    score += 0.4 * ((soil_idx >= 20) & (soil_idx < 30))
    score += rng.normal(0, 0.55, n)
    edges = np.quantile(score, [0.365, 0.852, 0.913, 0.918, 0.934, 0.966])
    y = np.searchsorted(edges, score).astype(np.int64)
    return X, y


def california_like(n_samples: int = 20640, seed: int = 0):
    """Deterministic stand-in for California housing (n x 8, f64 target).

    Mirrors the real dataset's structure (BASELINE config "DecisionTreeRegressor
    (MSE split criterion) on California housing"): 8 quantitative features
    with heterogeneous scales and a smooth nonlinear median-house-value
    target with noise, so deep regression trees meaningfully outperform
    shallow ones.
    """
    rng = np.random.default_rng(seed)
    n = n_samples
    med_inc = rng.gamma(2.5, 1.55, n)                 # median income
    house_age = rng.uniform(1, 52, n)
    ave_rooms = np.clip(rng.normal(5.4, 2.3, n), 1, None)
    ave_bedrms = np.clip(ave_rooms / 5 + rng.normal(0, 0.2, n), 0.3, None)
    population = rng.gamma(1.8, 790.0, n)
    ave_occup = np.clip(rng.normal(3.0, 1.6, n), 0.7, None)
    latitude = rng.uniform(32.5, 42.0, n)
    longitude = rng.uniform(-124.3, -114.3, n)
    X = np.column_stack(
        [med_inc, house_age, ave_rooms, ave_bedrms, population, ave_occup,
         latitude, longitude]
    ).astype(np.float32)
    coast = np.hypot(latitude - 34.0, longitude + 118.2)  # LA-ish anchor
    y = (
        0.45 * med_inc
        + 0.7 * np.exp(-coast / 3.0)
        + 0.004 * house_age
        + 0.08 * np.log1p(ave_rooms)
        - 0.12 * np.log1p(ave_occup)
        + rng.normal(0, 0.35, n)
    )
    return X, np.clip(y, 0.15, 5.0).astype(np.float64)


def load_california(n_samples: int | None = None, seed: int = 0):
    """Real California housing when cached; california_like otherwise.

    Returns (X, y, name).
    """
    try:
        from sklearn.datasets import fetch_california_housing

        d = fetch_california_housing(download_if_missing=False)
        X = d.data.astype(np.float32)
        y = d.target.astype(np.float64)
        name = "california_housing"
    except Exception:
        X, y = california_like(20640 if n_samples is None else n_samples, seed)
        name = "california_like"
    if n_samples is not None and len(X) > n_samples:
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(X))[:n_samples]
        X, y = X[idx], y[idx]
    return X, y, name


def load_covtype(n_samples: int | None = None, seed: int = 0):
    """Real covtype when a cached copy exists; covtype_like otherwise.

    Returns (X, y, name) with y relabelled to 0..6.
    """
    try:
        from sklearn.datasets import fetch_covtype

        d = fetch_covtype(download_if_missing=False)
        X = d.data.astype(np.float32)
        y = (d.target - 1).astype(np.int64)
        name = "covtype"
    except Exception:
        X, y = covtype_like(581012 if n_samples is None else n_samples, seed)
        name = "covtype_like"
    if n_samples is not None and len(X) > n_samples:
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(X))[:n_samples]
        X, y = X[idx], y[idx]
    return X, y, name
