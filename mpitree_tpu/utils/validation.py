"""Input validation — the sklearn contract without sklearn on the hot path.

The reference validates via ``check_X_y(..., dtype=object)`` and keeps X as an
object array compared with Python-level ``<=``
(reference: ``mpitree/tree/decision_tree.py:184,205,246``). A TPU build needs
numeric arrays, so we validate shape/finiteness with sklearn's checkers (host
side, once per call) and cast to float32. The one behavioral divergence —
object-dtype string features, which happen to "work" lexicographically in the
reference — is rejected with a clear error.

Labels: the reference requires contiguous non-negative integer labels
(``np.bincount(y).argmax()`` leaf rule, ``decision_tree.py:125``; anything else
crashes in ``predict_proba``'s ragged stacking). We accept arbitrary discrete
labels by encoding against ``classes_`` — for ``0..C-1`` integer labels this is
bit-identical to the reference.
"""

from __future__ import annotations

import numpy as np
from sklearn.utils.multiclass import check_classification_targets
from sklearn.utils.validation import check_array, check_X_y


def feature_names_of(X):
    """sklearn's ``feature_names_in_`` source: DataFrame column names
    (object dtype, sklearn's storage), or None for plain arrays. Mixed
    string/non-string columns raise, as sklearn's validation does."""
    cols = getattr(X, "columns", None)
    if cols is None:
        return None
    names = np.asarray(cols, dtype=object)
    str_mask = [isinstance(c, str) for c in names]
    if all(str_mask):
        return names
    if any(str_mask):
        raise TypeError(
            "Feature names are only supported if all input features have "
            "string names, but your input has mixed types."
        )
    return None


def validate_fit_data(X, y, *, task: str = "classification"):
    """Returns (X float32 (N,F), y_encoded, classes_ or None)."""
    X, y = check_X_y(X, y, dtype="numeric", y_numeric=(task == "regression"))
    X = np.ascontiguousarray(X, dtype=np.float32)
    y_enc, classes = validate_fit_targets(y, task=task)
    return X, y_enc, classes


def validate_fit_targets(y, *, task: str = "classification"):
    """(y_encoded, classes_ or None) — the target half of
    :func:`validate_fit_data`, factored out for fits whose X never
    materializes whole (streamed ingestion accumulates y chunk by chunk
    and validates it here once)."""
    y = np.asarray(y)
    if y.ndim != 1:
        raise ValueError(f"y must be 1-D, got shape {y.shape}")
    if task == "classification":
        check_classification_targets(y)
        classes, y_enc = np.unique(y, return_inverse=True)
        return y_enc.astype(np.int32), classes
    # Regression targets stay float64 on the host: the estimator centers in
    # f64 (shift invariance) and casts to f32 only for the device moment
    # histograms; leaf values are refit exactly in f64 afterwards.
    y64 = np.ascontiguousarray(y, dtype=np.float64)
    if not np.isfinite(y64).all():
        raise ValueError("regression targets must be finite")
    return y64, None


def record_sklearn_attributes(est, names, n_features, *,
                              n_classes=None) -> None:
    """The sklearn fitted-attribute surface every estimator exposes.

    ``feature_names_in_`` (DataFrame fits only, deleted otherwise — the
    sklearn convention), ``n_outputs_`` (always 1 here), ``n_classes_``
    (classifiers), and ``max_features_`` (the estimator's ``max_features``
    grammar resolved to a count).
    """
    if names is not None:
        est.feature_names_in_ = names
    elif hasattr(est, "feature_names_in_"):
        del est.feature_names_in_
    est.n_outputs_ = 1
    if n_classes is not None:
        est.n_classes_ = n_classes
    from mpitree_tpu.ops.sampling import n_subspace_features

    est.max_features_ = n_subspace_features(est.max_features, n_features)


def validate_sample_weight(sample_weight, n_samples: int):
    if sample_weight is None:
        return None
    w = np.asarray(sample_weight, dtype=np.float32)
    if w.shape != (n_samples,):
        raise ValueError(
            f"sample_weight has shape {w.shape}, expected ({n_samples},)"
        )
    if (w < 0).any() or not np.isfinite(w).all():
        raise ValueError("sample_weight must be finite and non-negative")
    if n_samples and not (w > 0).any():
        raise ValueError("sample_weight is all zero: nothing to fit")
    return w


def resolve_min_samples_leaf(min_samples_leaf, n_samples: int) -> int:
    """sklearn's ``min_samples_leaf`` grammar -> a row count (int >= 1).

    Fractional values in (0, 1) mean ``ceil(fraction * n_samples)`` rows;
    integers pass through; anything else raises. The ONE copy of the
    grammar — the weight-floor composition (:func:`min_child_weight`) and
    the boosting estimators' row-count gate both resolve through it.
    """
    import numbers

    if isinstance(min_samples_leaf, numbers.Real) and not isinstance(
        min_samples_leaf, numbers.Integral
    ):
        # sklearn's fractional form: ceil(fraction * n_samples) rows
        if not 0.0 < min_samples_leaf < 1.0:
            raise ValueError(
                f"float min_samples_leaf must be in (0, 1), "
                f"got {min_samples_leaf!r}"
            )
        return int(np.ceil(min_samples_leaf * n_samples))
    msl = int(min_samples_leaf)
    if msl != min_samples_leaf or msl < 1:
        raise ValueError(
            f"int min_samples_leaf must be a positive integer, "
            f"got {min_samples_leaf!r}"
        )
    return msl


def min_child_weight(min_weight_fraction_leaf, sample_weight, n_samples,
                     min_samples_leaf=1):
    """sklearn's leaf floors -> one absolute per-child weight floor.

    ``min_weight_fraction_leaf`` is a fraction of the TOTAL fit weight
    (sklearn semantics); ``min_samples_leaf`` is a sample count. Both bound
    the same weighted child total here, so the effective floor is their
    max. Caveat (documented): with fractional sample weights the count
    floor reads weighted counts, whereas sklearn counts raw rows — for
    unweighted fits and integer bootstrap multiplicities (where sklearn
    materializes duplicated rows) the two coincide exactly.
    """
    frac = float(min_weight_fraction_leaf)
    if not 0.0 <= frac <= 0.5:
        raise ValueError(
            f"min_weight_fraction_leaf must be in [0, 0.5], got {frac!r}"
        )
    msl = resolve_min_samples_leaf(min_samples_leaf, n_samples)
    floor = 0.0 if msl == 1 else float(msl)
    if frac > 0.0:
        total = float(n_samples) if sample_weight is None else float(
            np.sum(sample_weight)
        )
        floor = max(floor, frac * total)
    return floor


def min_decrease_scaled(min_impurity_decrease, sample_weight, n_samples):
    """sklearn's ``min_impurity_decrease`` -> the pre-scaled engine gate.

    The engines compare ``n_t * (imp_t - cost_t)`` (global weighted
    decrease x total weight) against this value, so scaling by the total
    fit weight here makes the rule exact everywhere, including inside
    hybrid-refine subtree rebuilds.
    """
    d = float(min_impurity_decrease)
    if d < 0.0:
        raise ValueError(
            f"min_impurity_decrease must be >= 0, got {min_impurity_decrease!r}"
        )
    if d == 0.0:
        return 0.0
    total = (
        float(n_samples) if sample_weight is None
        else float(np.sum(sample_weight))
    )
    return d * total


def apply_class_weight(class_weight, y_enc, classes, sample_weight):
    """Compose sklearn-style ``class_weight`` into per-sample weights.

    Delegates to ``sklearn.utils.class_weight.compute_sample_weight`` (the
    exact routine sklearn's own trees use — "balanced" formula, dict over
    ORIGINAL labels with missing labels defaulting to 1, sklearn's
    validation errors). Returns float32 weights, or ``sample_weight``
    unchanged when ``class_weight`` is None.
    """
    if class_weight is None:
        return sample_weight
    from sklearn.utils.class_weight import compute_sample_weight

    try:
        cw = compute_sample_weight(
            class_weight, np.asarray(classes)[y_enc]
        ).astype(np.float32)
    except (ValueError, TypeError) as e:
        # normalize sklearn's InvalidParameterError variants to ValueError
        raise ValueError(f"invalid class_weight: {e}") from e
    return cw if sample_weight is None else cw * sample_weight


def validate_predict_data(X, estimator):
    """Width + feature-name consistency checks, sklearn's wording.

    Takes the fitted estimator so every predict-time entrypoint gets the
    same checks from one call — ``n_features_``, the class name for
    messages, and ``feature_names_in_`` all come off it. Name handling
    follows sklearn: both sides named and different -> ValueError; named
    on one side only -> UserWarning; mixed-type columns -> TypeError
    (raised by :func:`feature_names_of`, same as the fit path).
    """
    import warnings

    n_features = estimator.n_features_
    name = type(estimator).__name__
    fitted_names = getattr(estimator, "feature_names_in_", None)
    pred_names = feature_names_of(X)
    if fitted_names is not None and pred_names is not None:
        if list(pred_names) != list(fitted_names):
            raise ValueError(
                "The feature names should match those that were passed "
                "during fit.\n"
                f"Feature names seen at fit time: {list(fitted_names)}\n"
                f"Feature names seen now: {list(pred_names)}"
            )
    elif fitted_names is not None and pred_names is None:
        # stacklevel 2 points at the estimator method uniformly (direct
        # predict and forest predict->predict_proba differ in user-frame
        # depth, so no constant reaches the user's line in both).
        warnings.warn(
            f"X does not have valid feature names, but {name} was fitted "
            "with feature names",
            stacklevel=2,
        )
    elif fitted_names is None and pred_names is not None:
        warnings.warn(
            f"X has feature names, but {name} was fitted without feature "
            "names",
            stacklevel=2,
        )
    X = check_array(X, dtype="numeric")
    if X.shape[1] != n_features:
        # sklearn's canonical inconsistent-width message (its estimator
        # conformance checks match this wording).
        raise ValueError(
            f"X has {X.shape[1]} features, but {name} is expecting "
            f"{n_features} features as input."
        )
    return np.ascontiguousarray(X, dtype=np.float32)


def validate_refine_depth(refine_depth):
    """Normalize the hybrid-build crossover depth: None or an exact int >= 0.

    A non-integral value would make the crown's ``depth == max_depth``
    terminal test never fire (unbounded growth) and then match zero
    refinement candidates — reject it outright. The string ``"auto"``
    passes through; :func:`resolve_refine` grounds it per dataset.
    """
    if refine_depth is None:
        return None
    if isinstance(refine_depth, str):
        if refine_depth == "auto":
            return "auto"
        raise ValueError(
            f"refine_depth must be None, 'auto', or a non-negative "
            f"integer, got {refine_depth!r}"
        )
    rd = int(refine_depth)
    if rd != refine_depth or rd < 0:
        raise ValueError(
            f"refine_depth must be None, 'auto', or a non-negative "
            f"integer, got {refine_depth!r}"
        )
    return rd


# Crown leaves of roughly this many rows are where the hybrid crossover
# pays: small enough that exact local candidates are cheap on the host,
# large enough that the device still amortizes the levels above.
_AUTO_REFINE_LEAF_ROWS = 2048


def resolve_refine(max_depth, refine_depth, *, n_rows=None, quantized=True):
    """Shared hybrid-build crossover decision for every estimator.

    Returns ``(rd, refine, crown_max_depth)``: the resolved crossover
    depth, whether the hybrid tail runs at all (it needs room below the
    crown), and the depth cap the crown build should use. One source of
    truth so the classifier and regressor cannot diverge on it.

    ``refine_depth="auto"`` engages the hybrid only when quantile binning
    actually capped some feature's candidates (``quantized`` — otherwise the
    exact global candidates already match the reference's semantics and a
    refine pass would rebuild identical subtrees), and picks the crown depth
    whose average frontier leaf holds ~2k rows. It also requires the C++
    tail kernel: without it the pure-numpy fallback re-bins and rebuilds one
    candidate subtree at a time (~n_rows/2048 of them), a large default-fit
    regression on hosts with no compiler. An explicit integer
    ``refine_depth`` still opts in to the numpy fallback.
    """
    rd = validate_refine_depth(refine_depth)
    if rd == "auto":
        if not quantized or not n_rows:
            rd = None
        else:
            from mpitree_tpu import native

            if native.lib() is None:
                rd = None
            else:
                rd = max(
                    1, round(np.log2(max(n_rows, 2) / _AUTO_REFINE_LEAF_ROWS))
                )
    refine = rd is not None and (max_depth is None or max_depth > rd)
    return rd, refine, (rd if refine else max_depth)


def validate_max_leaf_nodes(est):
    """Resolve an estimator's ``max_leaf_nodes`` into an int budget or None.

    sklearn's grammar (None or an int > 1), plus this framework's routing
    constraint: the best-first frontier lives in the device engines only
    (``core/leafwise_builder.py``), so ``backend="host"`` cannot honor it
    — refusing loudly beats silently growing a level-wise tree.
    """
    mln = getattr(est, "max_leaf_nodes", None)
    if mln is None:
        return None
    mln = int(mln)
    if mln < 2:
        raise ValueError(
            f"max_leaf_nodes {mln} must be either None or larger than 1"
        )
    if getattr(est, "backend", None) == "host":
        raise ValueError(
            "max_leaf_nodes requires a device engine (the numpy host tier "
            "grows level-wise only); drop backend='host'"
        )
    nd = getattr(est, "n_devices", None)
    if isinstance(nd, (tuple, list)) and len(nd) == 2 and int(nd[1]) > 1:
        # Mirror of the engine-level refusal (leafwise_builder's typed
        # mesh2d_unsupported event): fail at param validation, before any
        # sharding work, when the mesh request itself names feature
        # shards the best-first frontier cannot honor.
        raise ValueError(
            "max_leaf_nodes supports 1-D data meshes only "
            f"(mesh2d_unsupported: n_devices={tuple(nd)!r} requests "
            f"{int(nd[1])} feature shards, and the best-first frontier "
            "has no feature-axis select_global twin)"
        )
    return mln
