"""Fused device builder: the whole tree build as ONE compiled program.

The level-synchronous builder in ``builder.py`` round-trips to the host every
level (decisions out, update tables in) — ~2-4 dispatches per level, which on
a remote-attached TPU puts tens of tunnel round trips on the critical path of
a depth-20 build. This module is the design SURVEY.md §7 calls for outright:
*"keep the whole build in one compiled loop (lax.while_loop over levels)"* —
tree arrays live on device at fixed capacity, levels advance in a
``lax.while_loop`` whose body runs the chunked histogram + psum + replicated
split selection + child allocation + row rerouting entirely on device, and
the host receives the finished struct-of-arrays once.

Mapping to the reference (for parity auditing):
- stopping rules (purity / all-rows-identical / max_depth equality /
  min_samples_split) — reference ``mpitree/tree/decision_tree.py:118-123``,
  evaluated here from histogram statistics on device;
- first-min tie-breaks over (feature, bin) — reference ``:88-91,140`` via
  ``ops/impurity.py``;
- the MPI choreography (``:446-477``) is again replaced by ``lax.psum`` over
  the mesh, now inside the loop body.

Static configuration per compile: per-shard row count, F, B, C, chunk width
K, node capacity M, max_depth. The node capacity is exact:
``min(2^(max_depth+1)-1, 2*N-1)`` — a tree from N rows can never allocate
more (every split has two non-empty sides).
"""

from __future__ import annotations

import math
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mpitree_tpu.core.builder import (
    _chunk_size,
    exact_ties_fits,
    integer_weights,
    warn_exact_ties_gap,
    refit_regression_values,
    resolve_exact_ties,
    resolve_hist_kernel,
    resolve_hist_subtraction,
    resolve_wide_hist,
    resolve_wide_pallas,
    valid_tiers as builder_valid_tiers,
)
from mpitree_tpu.core.tree_struct import TreeArrays
from mpitree_tpu.obs import accounting as obs_acct
from mpitree_tpu.obs import memory as memory_lib
from mpitree_tpu.obs import warn_event
from mpitree_tpu.ops import histogram as hist_ops
from mpitree_tpu.ops import impurity as imp_ops
from mpitree_tpu.ops import pallas_hist
from mpitree_tpu.ops import wide_hist
from mpitree_tpu.ops import sampling as sampling_ops
from mpitree_tpu.parallel import mesh as mesh_lib
from mpitree_tpu.parallel import partition
from mpitree_tpu.parallel.collective import (
    node_counts_local,
    regression_y_range,
    select_global,
)
from mpitree_tpu.parallel.mesh import DATA_AXIS
from mpitree_tpu.utils import importances as imp_utils
from mpitree_tpu.utils.profiling import PhaseTimer
from mpitree_tpu.config import knobs


# Per-device budget for the replicated binned matrix in the tree-sharded
# forest build (a v5e chip carries 16 GB HBM; half is left for histograms,
# candidate masks, and XLA scratch). When the matrix would exceed it, the
# forest mesh trades tree-axis width for a data axis — rows shard and
# histograms psum inside each tree group (mesh_lib.tree_data_shape).
FOREST_HBM_BUDGET_BYTES = int(
    knobs.value("MPITREE_TPU_FOREST_HBM_BUDGET")
)


def _node_capacity(n_samples: int, max_depth) -> int:
    """Upper bound on allocatable nodes, rounded up to a power of two.

    The true bound is ``min(2^(max_depth+1)-1, 2N-1)`` (every split needs a
    positive-weight row on both sides); rounding up means nearby sample
    counts (CV folds, subsamples) share one compiled executable — capacity is
    only a buffer size, the result is trimmed to ``n_nodes``.
    """
    cap = 2 * max(n_samples, 1) - 1
    if max_depth is not None and max_depth < 31:
        cap = min(cap, 2 ** (max_depth + 1) - 1)
    return 1 << max(0, math.ceil(math.log2(max(cap, 1))))


def _sampler_statics(feature_sampler, n_features: int):
    """(sample_k, random_split, root_key operand) for a NodeFeatureSampler.

    ``sample_k=None`` disables per-node masks (k >= F subsets everything);
    the root key is a uint32 scalar operand so subtree rebuilds (hybrid
    refine roots carry ``root_key_value``) reuse the compiled executable.
    """
    if feature_sampler is None or not feature_sampler.active:
        return None, False, np.uint32(0)
    k = feature_sampler.k
    return (
        k if k < n_features else None,
        bool(feature_sampler.random_split),
        np.uint32(feature_sampler.root_key()),
    )


def _make_build_body(*, n_slots: int, n_bins: int, n_classes: int,
                     task: str, criterion: str, max_nodes: int,
                     max_depth: int, min_samples_split: int,
                     tiers: tuple = (), use_pallas: bool = False,
                     use_wide: bool = False, wide_bf16: bool = False,
                     wide_pallas: bool = False,
                     exact_ties: bool = False,
                     psum_axis: str | None = DATA_AXIS,
                     feature_axis: str | None = None,
                     sample_k: int | None = None,
                     random_split: bool = False,
                     monotonic: bool = False,
                     subtraction: bool = False):
    """Pure per-device build fn (xb, y, nid0, w, cand_mask) -> tree arrays.

    ``max_depth < 0`` means unbounded. ``psum_axis`` names the mesh axis that
    row shards reduce over (None = rows are device-local, e.g. the
    tree-parallel forest build where data is replicated per device).
    ``feature_axis`` names the tensor-parallel mesh axis sharding the
    histogram's feature dimension (None = features device-complete): each
    shard evaluates its own feature block, the winners reduce via a tiny
    all_gather + first-min (contiguous blocks keep the lowest-global-feature
    tie-break), and the split owner broadcasts row routing bits with a psum.

    ``tiers`` adds frontier-width branches (a ``lax.cond`` chain in the
    level body): a level whose frontier fits tier S computes an S-slot
    histogram + gain sweep instead of the full K-slot one — otherwise the
    first ~log2(K) levels of every build pay the K=4096-slot sweep for a
    handful of live nodes. ``use_pallas`` swaps tier histograms (where the
    out block fits VMEM) for the Mosaic one-hot-matmul kernel
    (``ops/pallas_hist.py``) — bit-identical for integer-valued class
    counts, explicit-opt-in-only for non-integer payloads (the exactness
    policy in ``builder.resolve_hist_kernel``).

    ``sample_k`` enables sklearn's per-NODE random feature subsets inside the
    fused program: a uint32 path-key array rides the while_loop state, each
    level slices its frontier's keys and derives (slot, F) feature masks
    with the jnp twin of the host tier's PCG arithmetic
    (``ops/sampling.py:node_masks_jnp``), and splitting nodes hash child
    keys into their slots — the same keys every other engine computes, so
    the engine-identity contract holds. ``random_split`` likewise derives
    per-(node, feature) candidate draws (ExtraTrees, splitter="random").
    The build fn then takes a trailing ``root_key`` uint32 operand.

    ``monotonic`` threads per-node value bounds (f32 lo/hi arrays) through
    the while_loop state and rejects constraint-violating candidates in
    split selection (sklearn ``monotonic_cst``; ``ops/impurity.py``). The
    build fn takes a further trailing ``mono_cst`` (F,) int32 operand of
    INTERNAL signs; children of a constrained split receive mid-value
    bounds through the same allocation scatter as the parent links.

    ``subtraction`` compiles the sibling-subtraction frontier
    (``ops/histogram.sibling_accumulate_slots`` / ``sibling_reconstruct``)
    into the loop: the previous level's globally-reduced histogram stays
    resident in a (K, F, C, B) while-state buffer alongside a per-node
    smaller-sibling mask and the slot -> parent-slot map (``parent_a``
    minus the carried previous frontier_lo), and every interior level
    whose frontier (and parent frontier) fit one chunk accumulates only
    the smaller children — into a compact half-width buffer, halving both
    the scatter work and the histogram psum payload — then reconstructs
    the larger siblings as ``parent - small`` after the reduction. Levels
    that overflow one chunk (or follow one that did) fall back to direct
    accumulation via a ``lax.cond`` on the carried ``sub_ok`` flag.
    Callers gate this on the exactness policy
    (``builder.resolve_hist_subtraction``).
    """
    # K slots of slack past the true capacity: the last chunk's
    # dynamic_update_slice window [chunk_lo, chunk_lo+K) may extend past the
    # final frontier, and an unpadded buffer would make DUS clamp the start
    # index and silently overwrite earlier nodes.
    K, C = n_slots, n_classes
    M = max_nodes + n_slots
    tiers = builder_valid_tiers(tiers, K)
    # Depth-capped builds bound every INTERIOR frontier at 2^(max_depth-1)
    # (the terminal level runs the counts-only branch regardless): tiers
    # that can never be the narrowest fit, and — when the widest interior
    # frontier fits a tier — the K-slot interior sweep itself, are
    # unreachable cond branches. Compiling them anyway costs tens of
    # seconds through the remote-compile tunnel (the K-slot histogram +
    # gain sweep is the largest executable in the program); crown programs
    # (the hybrid's device half) drop them here. The trim lives in
    # obs/accounting.py — the post-hoc collective accounting must replay
    # the identical tier routing, so there is exactly one copy.
    tiers = obs_acct.effective_tiers(tiers, max_depth)
    interior_big_reachable = obs_acct.interior_big_reachable(
        tiers, max_depth
    )
    hist_vma = tuple(a for a in (psum_axis, feature_axis) if a is not None)
    sampling = sample_k is not None or random_split
    if sampling and feature_axis is not None:
        raise ValueError(
            "per-node feature sampling is not supported on a "
            "(data, feature) mesh"
        )
    if monotonic and feature_axis is not None:
        raise ValueError(
            "monotonic_cst is not supported on a (data, feature) mesh"
        )

    # Histogram all-reduce helper — same priced site as the levelwise
    # split step (collective.split_psum_bytes).
    # graftlint: wire=split_hist_psum
    def psum(x):
        return lax.psum(x, psum_axis) if psum_axis is not None else x

    # graftlint: device-fn (jit-wrapped indirectly: this factory's return
    # value reaches jax.shard_map in _make_fused_fn / _make_forest_fn)
    def build(xb, y, nid0, w, cand_mask, mcw, mid, root_key, mono_cst):
        # mid: sklearn's min_impurity_decrease pre-scaled by the total fit
        # weight (BuildConfig.min_decrease_scaled), a runtime operand so
        # distinct thresholds share one executable. root_key: the tree's
        # path-key seed (unused scalar when sampling is off). mono_cst:
        # (F,) int32 internal monotonicity signs (unused when monotonic is
        # off — riding as an operand keeps distinct constraint vectors on
        # one compiled executable).
        R, F = xb.shape  # F = per-shard feature count on a feature mesh
        # C == n_classes for classification, 3 (moment channels) for
        # regression — the VMEM check covers both payload widths.
        pallas_tiers = frozenset(
            s for s in tiers
            if use_pallas and pallas_hist.fits_vmem(F, s, C, n_bins)
        )
        # The sorted window-packed matmul tier (ops/wide_hist.py) serves
        # widths the Pallas VMEM budget cannot reach: the deep-level slot
        # widths where the XLA scatter otherwise runs on the scalar unit.
        # slot_width: the candidate tier width under test, NOT the build's
        # n_slots (the _width suffix also tells graftlint's dataflow this
        # predicate is static — see astutil.looks_shape_static)
        def wide_ok(slot_width):
            return (use_wide and slot_width >= wide_hist.MIN_SLOTS
                    and slot_width % wide_hist.WINDOW == 0)

        if use_pallas or use_wide:  # unused widths are DCE'd
            payload = (  # loop-invariant
                pallas_hist.class_payload(y, w, C)
                if task == "classification"
                else pallas_hist.moment_payload(y, w)
            )

        def node_subsets(chunk_lo, n_stat_slots, key_a):
            """Per-node feature masks + candidate draws for a frontier window."""
            if not sampling:
                return None, None
            kw = lax.dynamic_slice(key_a, (chunk_lo,), (n_stat_slots,))
            nmask = (
                sampling_ops.node_masks_jnp(kw, sample_k, F)
                if sample_k is not None else None
            )
            draws = (
                sampling_ops.node_draws_jnp(kw, F) if random_split else None
            )
            return nmask, draws

        def raw_hist(slot_rel, n_acc_slots, pallas_ok=False):
            """One frontier histogram accumulation at ``n_acc_slots`` slots.

            ``slot_rel`` is the per-row slot (or the sibling-subtraction
            remap, already compacted and masked to -1); kernel routing is
            width-generic so the subtraction path reuses every tier at its
            halved accumulate width. ``n_acc_slots``/``pallas_ok`` are
            STATIC (python ints/bools at trace time — the n_/default
            conventions graftlint's dataflow reads)."""
            n_chan = C if task == "classification" else 3
            if pallas_ok:
                return pallas_hist.histogram_small(
                    xb, payload, slot_rel, n_slots=n_acc_slots,
                    n_bins=n_bins, n_channels=n_chan, vma=hist_vma,
                )
            if wide_ok(n_acc_slots):
                wide_fn = (wide_hist.histogram_wide_pallas if wide_pallas
                           else wide_hist.histogram_wide)
                return wide_fn(
                    xb, payload, slot_rel, n_slots=n_acc_slots,
                    n_bins=n_bins, n_channels=n_chan,
                    window=wide_hist.WINDOW,
                    bf16_ok=wide_bf16 if task == "classification" else False,
                    vma=hist_vma,
                )
            if task == "classification":
                return hist_ops.class_histogram(
                    xb, y, slot_rel, jnp.int32(0), n_slots=n_acc_slots,
                    n_bins=n_bins, n_classes=C, sample_weight=w,
                )
            return hist_ops.moment_histogram(
                xb, y, slot_rel, jnp.int32(0), n_slots=n_acc_slots,
                n_bins=n_bins, sample_weight=w,
            )

        def chunk_stats(chunk_lo, nid, n_stat_slots, pallas_ok=False,
                        key_a=None, bounds=None, sub=None):
            """Histogram + split search for nodes [chunk_lo, chunk_lo+S_or_K).

            ``sub`` (subtraction builds only): ``(sub_now, phist, small_a,
            parent_a, pflo)`` — the traced use-subtraction flag for this
            level plus the carried parent histogram and per-node
            smaller-sibling/parent bookkeeping. Returns ``(dec, pure, h)``
            with ``h`` the globally-reduced frontier histogram (what the
            next level subtracts against)."""
            nmask, draws = node_subsets(chunk_lo, n_stat_slots, key_a)
            mono = {}
            if monotonic:
                lo_a, hi_a = bounds
                mono = {
                    "mono_cst": mono_cst,
                    "mono_lo": lax.dynamic_slice(
                        lo_a, (chunk_lo,), (n_stat_slots,)
                    ),
                    "mono_hi": lax.dynamic_slice(
                        hi_a, (chunk_lo,), (n_stat_slots,)
                    ),
                }
            slot = nid - chunk_lo
            if sub is not None:
                sub_now, phist, small_a, parent_p, pflo = sub
                sm = lax.dynamic_slice(small_a, (chunk_lo,), (n_stat_slots,))
                half = max(n_stat_slots // 2, 1)
                pallas_half = (
                    pallas_ok
                    and pallas_hist.fits_vmem(
                        F, half, C if task == "classification" else 3, n_bins
                    )
                )

                def sub_branch(_):
                    acc = hist_ops.sibling_accumulate_slots(
                        nid, chunk_lo, sm, n_slots=n_stat_slots
                    )
                    hs = psum(raw_hist(acc, half, pallas_half))
                    ps = (
                        lax.dynamic_slice(
                            parent_p, (chunk_lo,), (n_stat_slots,)
                        )
                        - pflo
                    )
                    return hist_ops.sibling_reconstruct(hs, phist, ps, sm)

                def direct_branch(_):
                    return psum(raw_hist(slot, n_stat_slots, pallas_ok))

                h = lax.cond(sub_now, sub_branch, direct_branch, None)
            else:
                h = psum(raw_hist(slot, n_stat_slots, pallas_ok))
            if task == "classification":
                dec = select_global(imp_ops.best_split_classification(
                    h, cand_mask, criterion=criterion,
                    min_child_weight=mcw, node_mask=nmask,
                    forced_draw=draws,
                    exact_ties=exact_ties and exact_ties_fits(
                        n_stat_slots, F, n_bins
                    ),
                    **mono,
                ), feature_axis, F)
                pure = (dec.counts > 0).sum(axis=1) <= 1
            else:
                dec = select_global(imp_ops.best_split_regression(
                    h, cand_mask, min_child_weight=mcw, node_mask=nmask,
                    forced_draw=draws, **mono,
                ), feature_axis, F)
                ymin, ymax = regression_y_range(
                    y, nid, w, chunk_lo, n_slots=n_stat_slots, axis=psum_axis
                )
                pure = ~(ymax > ymin)
            return dec, pure, h

        def chunk_counts(chunk_lo, nid):
            """Terminal level: per-node counts only (O(R) instead of O(R*F))."""
            return node_counts_local(
                y, nid, w, chunk_lo, n_slots=K, n_classes=C, task=task,
                axis=psum_axis,
            )

        def level_body(state):
            (feat_a, bin_a, counts_a, n_a, left_a, parent_a, nid, flo, fsz,
             depth, key_a) = state[:11]
            idx = 11
            bounds = None
            if monotonic:
                bounds = (state[idx], state[idx + 1])
                idx += 2
            if subtraction:
                small_a, phist0, pflo, sub_ok = state[idx:idx + 4]
            terminal = jnp.logical_and(max_depth >= 0, depth == max_depth)
            n_chunks = (fsz + K - 1) // K

            def decide(dec, pure):
                n = (dec.counts.sum(axis=1) if task == "classification"
                     else dec.counts[:, 0])
                stop = (
                    pure | dec.constant | (n < min_samples_split)
                    | jnp.isinf(dec.cost)
                    # min_impurity_decrease on the best split; gated on
                    # mid > 0 so the default never trips on float noise
                    | ((mid > 0)
                       & (n * (dec.impurity - dec.cost) < mid))
                )
                feat_k = jnp.where(stop, -1, dec.feature).astype(jnp.int32)
                out = (feat_k, dec.bin.astype(jnp.int32), dec.counts, n)
                if monotonic:
                    # sklearn's middle_value of the winning candidate —
                    # the child-bound pin below.
                    out = out + ((dec.v_left + dec.v_right) * 0.5,)
                if subtraction:
                    # Winner's left weight — the smaller-child pick during
                    # child allocation below.
                    out = out + (dec.n_left,)
                return out

            # bufs layout: (feat, bin, counts, n)[, mid][, nl][, phist] —
            # pieces cover everything but phist, which branches update in
            # place (it is level-global, not per-chunk-slot data).
            def write_bufs(bufs, pieces, at):
                out = []
                for buf, piece in zip(bufs, pieces):
                    ix = (at, 0) if buf.ndim == 2 else (at,)
                    out.append(lax.dynamic_update_slice(buf, piece, ix))
                return tuple(out) + tuple(bufs[len(pieces):])

            n_pieces = 4 + int(monotonic) + int(subtraction)
            sub_args_big = (
                (jnp.logical_and(sub_ok, n_chunks == 1), phist0, small_a,
                 parent_a, pflo)
                if subtraction else None
            )

            def chunk_body(c, bufs):
                chunk_lo = flo + c * K

                def interior(_):
                    dec, pure, h = chunk_stats(
                        chunk_lo, nid, K, key_a=key_a, bounds=bounds,
                        sub=sub_args_big,
                    )
                    # Offset 0, not chunk_lo - flo: multi-chunk levels
                    # cannot serve as subtraction parents (sub_ok drops
                    # below), so later chunks overwriting slot 0 is dead
                    # data, while the single-chunk case lands exactly.
                    return decide(dec, pure), (h if subtraction else None)

                def term(_):
                    cc = chunk_counts(chunk_lo, nid)
                    n = cc.sum(axis=1) if task == "classification" else cc[:, 0]
                    out = (jnp.full(K, -1, jnp.int32),
                           jnp.zeros(K, jnp.int32), cc, n)
                    if monotonic:
                        out = out + (jnp.zeros(K, jnp.float32),)
                    if subtraction:
                        out = out + (jnp.zeros(K, jnp.float32),)
                    return out, (bufs[n_pieces] if subtraction else None)

                if not interior_big_reachable:
                    # Every interior frontier fits a tier branch, so the
                    # big path only ever runs terminal counts — don't
                    # compile the K-slot sweep at all (crown programs).
                    pieces, h = term(None)
                else:
                    pieces, h = lax.cond(terminal, term, interior, None)
                bufs = write_bufs(bufs, pieces, chunk_lo)
                if subtraction:
                    bufs = bufs[:n_pieces] + (h,)
                return bufs

            def big_level(bufs):
                return lax.fori_loop(0, n_chunks, chunk_body, bufs)

            def tier_level(s):
                def branch(bufs):
                    dec, pure, h = chunk_stats(
                        flo, nid, s, pallas_ok=s in pallas_tiers,
                        key_a=key_a, bounds=bounds,
                        sub=(
                            (sub_ok, phist0, small_a, parent_a, pflo)
                            if subtraction else None
                        ),
                    )
                    pieces = decide(dec, pure)
                    bufs = write_bufs(bufs, pieces, flo)
                    if subtraction:
                        bufs = bufs[:n_pieces] + (
                            lax.dynamic_update_slice(
                                bufs[n_pieces], h, (0, 0, 0, 0)
                            ),
                        )
                    return bufs

                return branch

            # Tier chain, smallest first: a level routes to the narrowest
            # sweep its frontier fits; terminal levels always take the big
            # path (its per-chunk counts-only branch).
            dispatch = big_level
            for s in reversed(tiers):
                def dispatch(bufs, s=s, nxt=dispatch):
                    return lax.cond(
                        jnp.logical_and(fsz <= s, ~terminal),
                        tier_level(s), nxt, bufs,
                    )

            bufs = (feat_a, bin_a, counts_a, n_a)
            if monotonic:
                bufs = bufs + (jnp.zeros(M, jnp.float32),)  # winner mids
            if subtraction:
                bufs = bufs + (jnp.zeros(M, jnp.float32),)  # winner n_left
                bufs = bufs + (phist0,)
            bufs = dispatch(bufs)
            feat_a, bin_a, counts_a, n_a = bufs[:4]
            mid_a = bufs[4] if monotonic else None
            nl_a = bufs[4 + int(monotonic)] if subtraction else None
            phist_new = bufs[n_pieces] if subtraction else None

            # Child allocation, frontier-windowed: the previous full-M
            # formulation scattered 2*(M+2) elements per level (M is the
            # ~2^21 node CAPACITY at covtype scale — ~84M scalar-unit
            # scatter updates over a depth-20 build, the same cost class
            # as the histogram scatter the wide tier removed). Walking the
            # frontier in the existing K-chunks makes every step K-sized:
            # updates are proportional to the LIVE frontier, and node ids
            # still inherit frontier order (rank offsets carry across
            # chunks), so slot arithmetic keeps working next level.
            # parent_a / key_a / bounds are carried PADDED to (M+2,) in the
            # while state: non-split lanes dump their scatter at index M,
            # and padding the buffers once at state init beats re-building
            # M+2 copies every level.
            parent_p = parent_a
            key_p = key_a if sampling else None
            small_p = small_a if subtraction else None
            if monotonic:
                lo_a, hi_a = bounds
                lo_p, hi_p = lo_a, hi_a
            else:
                lo_p = hi_p = None

            def alloc_chunk(c, carry):
                left_a, parent_p, key_p, lo_p, hi_p, small_p, child_base = carry
                chunk_lo = flo + c * K
                gidx = chunk_lo + jnp.arange(K, dtype=jnp.int32)
                loc_feat = lax.dynamic_slice(feat_a, (chunk_lo,), (K,))
                split = (gidx < flo + fsz) & (loc_feat >= 0)
                rank = jnp.cumsum(split.astype(jnp.int32))
                lids = child_base + 2 * (rank - 1)
                old_left = lax.dynamic_slice(left_a, (chunk_lo,), (K,))
                left_a = lax.dynamic_update_slice(
                    left_a, jnp.where(split, lids, old_left), (chunk_lo,)
                )
                # Non-split lanes dump at index M (sliced off) — every
                # real child position is written by exactly one lane.
                scat = jnp.where(split, lids, M)
                parent_p = parent_p.at[scat].set(
                    jnp.where(split, gidx, -1)
                )
                parent_p = parent_p.at[scat + 1].set(
                    jnp.where(split, gidx, -1)
                )
                if sampling:
                    # Children inherit path-hashed keys through the same
                    # scatter pattern (ops/sampling.py arithmetic).
                    lk, rk = sampling_ops.child_keys_jnp(
                        lax.dynamic_slice(key_a, (chunk_lo,), (K,))
                    )
                    key_p = key_p.at[scat].set(
                        jnp.where(split, lk, jnp.uint32(0))
                    )
                    key_p = key_p.at[scat + 1].set(
                        jnp.where(split, rk, jnp.uint32(0))
                    )
                if subtraction:
                    # Smaller-sibling pick from the winner's left weight
                    # (ties go left — same rule as the levelwise host
                    # tier, so both engines accumulate the same children).
                    loc_nl = lax.dynamic_slice(nl_a, (chunk_lo,), (K,))
                    loc_n = lax.dynamic_slice(n_a, (chunk_lo,), (K,))
                    left_small = loc_nl * 2.0 <= loc_n
                    small_p = small_p.at[scat].set(
                        jnp.where(split, left_small, True)
                    )
                    small_p = small_p.at[scat + 1].set(
                        jnp.where(split, ~left_small, True)
                    )
                if monotonic:
                    # sklearn bound propagation: a split on a constrained
                    # feature pins mid between the children.
                    loc_mid = lax.dynamic_slice(mid_a, (chunk_lo,), (K,))
                    loc_lo = lax.dynamic_slice(lo_a, (chunk_lo,), (K,))
                    loc_hi = lax.dynamic_slice(hi_a, (chunk_lo,), (K,))
                    cstf = mono_cst[jnp.clip(loc_feat, 0, None)]
                    llo = jnp.where(cstf == -1, loc_mid, loc_lo)
                    lhi = jnp.where(cstf == 1, loc_mid, loc_hi)
                    rlo = jnp.where(cstf == 1, loc_mid, loc_lo)
                    rhi = jnp.where(cstf == -1, loc_mid, loc_hi)
                    lo_p = lo_p.at[scat].set(jnp.where(split, llo, 0.0))
                    lo_p = lo_p.at[scat + 1].set(jnp.where(split, rlo, 0.0))
                    hi_p = hi_p.at[scat].set(jnp.where(split, lhi, 0.0))
                    hi_p = hi_p.at[scat + 1].set(jnp.where(split, rhi, 0.0))
                child_base = child_base + 2 * rank[-1]
                return (left_a, parent_p, key_p, lo_p, hi_p, small_p,
                        child_base)

            carry = (left_a, parent_p, key_p, lo_p, hi_p, small_p, flo + fsz)
            carry = lax.fori_loop(0, n_chunks, alloc_chunk, carry)
            left_a, parent_a, key_p, lo_p, hi_p, small_p, child_end = carry
            n_split = (child_end - (flo + fsz)) // 2
            if sampling:
                key_a = key_p
            if monotonic:
                bounds = (lo_p, hi_p)

            # Reroute rows of splitting nodes (on-device mask partition —
            # the reference's recursive X[region] copies, decision_tree.py:150-164).
            node = jnp.clip(nid, 0, M - 1)
            f = feat_a[node]
            active = (nid >= flo) & (nid < flo + fsz) & (f >= 0)
            # Only the feature shard owning each node's split feature can
            # read that column; it computes the child id and a psum over
            # the feature axis delivers it to every shard (each active
            # row has exactly one owner, others contribute zero) —
            # hist_ops.slab_local_features, the shared slab plumbing.
            local, owner = hist_ops.slab_local_features(f, feature_axis, F)
            xf = jnp.take_along_axis(xb, local[:, None], axis=1)[:, 0]
            go_left = xf <= bin_a[node]
            child = jnp.where(go_left, left_a[node], left_a[node] + 1)
            if feature_axis is None:
                nid = jnp.where(active, child, nid)
            else:
                child_all = lax.psum(  # graftlint: wire=route_psum
                    jnp.where(active & owner, child, 0), feature_axis
                )
                nid = jnp.where(active, child_all, nid)

            out = (feat_a, bin_a, counts_a, n_a, left_a, parent_a, nid,
                   flo + fsz, 2 * n_split, depth + 1, key_a)
            if monotonic:
                out = out + bounds
            if subtraction:
                # Next level may subtract iff this level's reduced
                # histogram is whole in the carry: one interior chunk.
                out = out + (
                    small_p, phist_new, flo,
                    jnp.logical_and(n_chunks == 1, ~terminal),
                )
            return out

        def level_cond(state):
            return state[8] > 0

        # parent / keys / bounds carry 2 pad lanes (index M is the
        # allocation's dump slot for non-split lanes) — see alloc_chunk.
        state0 = (
            jnp.full(M, -1, jnp.int32),            # feature
            jnp.zeros(M, jnp.int32),               # bin
            jnp.zeros((M, C if task == "classification" else 3), jnp.float32),
            jnp.zeros(M, jnp.float32),             # n per node
            jnp.full(M, -1, jnp.int32),            # left
            jnp.full(M + 2, -1, jnp.int32),        # parent (padded)
            nid0,
            jnp.int32(0),                          # frontier_lo
            jnp.int32(1),                          # frontier_size
            jnp.int32(0),                          # depth
            jnp.zeros(M + 2, jnp.uint32).at[0].set(
                root_key.astype(jnp.uint32)
            ),
        )
        if monotonic:
            state0 = state0 + (
                jnp.full(M + 2, -jnp.inf, jnp.float32),  # node lower bounds
                jnp.full(M + 2, jnp.inf, jnp.float32),   # node upper bounds
            )
        if subtraction:
            n_chan = C if task == "classification" else 3
            state0 = state0 + (
                # smaller-sibling per node (padded; True = pads read the
                # zero pair in sibling_reconstruct)
                jnp.ones(M + 2, bool),
                # resident parent histogram, slot-indexed from the parent
                # level's frontier_lo — one chunk's worth
                jnp.zeros((K, F, n_chan, n_bins), jnp.float32),
                jnp.int32(0),         # parent level's frontier_lo
                jnp.array(False),     # sub_ok: no parent above the root
            )
        out = lax.while_loop(level_cond, level_body, state0)
        feat_a, bin_a, counts_a, n_a, left_a, parent_a, nid, flo = out[:8]
        return feat_a, bin_a, counts_a, n_a, left_a, parent_a[:M], nid, flo

    return build


@lru_cache(maxsize=32)
def _make_fused_fn(mesh, *, n_slots: int, n_bins: int, n_classes: int,
                   task: str, criterion: str, max_nodes: int, max_depth: int,
                   min_samples_split: int, tiers: tuple = (),
                   use_pallas: bool = False, use_wide: bool = False,
                   wide_bf16: bool = False, wide_pallas: bool = False,
                   exact_ties: bool = False,
                   sample_k: int | None = None,
                   random_split: bool = False, monotonic: bool = False,
                   subtraction: bool = False):
    """Data-parallel single-tree build: rows sharded, histograms psum'd.

    Jitted (xb, y, nid0, w, cand_mask, mcw, mid, root_key, mono_cst) ->
    (tree arrays..., nid, n_nodes); tree outputs replicated, the final row
    assignment sharded (for the regression refit pass). On a 2-D
    ``(data, feature)`` mesh the histogram's feature dimension shards over
    the second axis (tensor parallelism).
    """
    feature_axis = (
        mesh_lib.FEATURE_AXIS
        if mesh_lib.feature_shards(mesh) > 1 else None
    )
    build = _make_build_body(
        n_slots=n_slots, n_bins=n_bins, n_classes=n_classes, task=task,
        criterion=criterion, max_nodes=max_nodes, max_depth=max_depth,
        min_samples_split=min_samples_split, tiers=tiers,
        use_pallas=use_pallas, use_wide=use_wide, wide_bf16=wide_bf16,
        wide_pallas=wide_pallas, exact_ties=exact_ties,
        psum_axis=DATA_AXIS,
        feature_axis=feature_axis, sample_k=sample_k,
        random_split=random_split, monotonic=monotonic,
        subtraction=subtraction,
    )
    sharded = jax.shard_map(
        build,
        mesh=mesh,
        # Operand AND result specs from the ONE partition-rule table
        # (parallel/partition.py) — trimmed to 1-D meshes automatically.
        in_specs=partition.in_specs_for(
            mesh, ("x_binned", "y", "node_id", "weight", "cand_mask",
                   ("mcw", 0), ("mid", 0), ("root_key", 0),
                   "mono_cst"),
        ),
        out_specs=partition.out_specs_for(
            mesh, ("feat", "bin", "counts", "n_vec", "left_id",
                   "parent_id", "node_id", ("n_nodes", 0)),
        ),
        check_vma=feature_axis is None,  # replicated/varying mixes in the 2-D cond
    )
    # Donate the row-assignment input (arg 2, nid0): it is freshly sharded
    # per build (shard_build_inputs) and the program returns nid with the
    # identical shape/sharding, so XLA reuses the buffer instead of
    # double-buffering an N-row vector across the fused while_loop (GL05).
    # xb/y/w are NOT donatable: the forest path reuses them across groups.
    # GL08 (donation-after-use) audits the caller: build_tree_fused never
    # touches nid_d after the call — everything downstream reads the
    # returned nid_out.
    return jax.jit(sharded, donate_argnums=(2,))


@lru_cache(maxsize=32)
def _make_forest_fn(mesh, *, n_slots: int, n_bins: int, n_classes: int,
                    task: str, criterion: str, max_nodes: int,
                    max_depth: int, min_samples_split: int,
                    tiers: tuple = (), use_pallas: bool = False,
                    use_wide: bool = False, wide_bf16: bool = False,
                    wide_pallas: bool = False,
                    exact_ties: bool = False,
                    data_sharded: bool = False,
                    sample_k: int | None = None,
                    random_split: bool = False,
                    monotonic: bool = False,
                    subtraction: bool = False):
    """Tree-parallel forest build: trees sharded over the mesh (ensemble
    parallelism — BASELINE configs[4], "N trees sharded across TPU chips").

    Jitted (xb, y, nid0, ws, cand_masks) with ``ws: (T, N)`` bootstrap
    weights and ``cand_masks: (T, F, B)`` per-tree candidate masks ->
    per-tree stacked tree arrays. Each device runs its tree batch
    sequentially (``lax.map``); devices run their batches concurrently —
    the whole forest is ONE device program.

    ``data_sharded=False``: 1-D tree mesh, data replicated per device.
    ``data_sharded=True``: 2-D ``(tree, data)`` mesh — rows shard over the
    data axis inside each tree group and histograms psum over it (the same
    collective path as the single-tree build), so forests scale past
    one device's HBM per tree and surplus devices stop idling when
    ``n_trees < n_devices``.

    ``subtraction`` compiles the sibling-subtraction frontier into the
    per-tree body: the build body allocates its resident parent histogram
    inside ``build``, so under ``lax.map`` each in-flight tree carries
    its own copy on the loop state for free — one extra chunk-sized
    buffer per tree in flight, exactly the ROADMAP follow-up's cost
    estimate. Callers gate on ``builder.resolve_hist_subtraction`` (the
    forest's per-tree bootstrap totals drive the f32-ceiling guard).
    """
    build = _make_build_body(
        n_slots=n_slots, n_bins=n_bins, n_classes=n_classes, task=task,
        criterion=criterion, max_nodes=max_nodes, max_depth=max_depth,
        min_samples_split=min_samples_split, tiers=tiers,
        use_pallas=use_pallas, use_wide=use_wide, wide_bf16=wide_bf16,
        wide_pallas=wide_pallas, exact_ties=exact_ties,
        psum_axis=DATA_AXIS if data_sharded else None,
        sample_k=sample_k, random_split=random_split, monotonic=monotonic,
        subtraction=subtraction,
    )

    def per_device(xb, y, nid0, ws, cand_masks, mcw, mid, root_keys,
                   mono_cst):
        # mcw/mid: (T_local,) per-tree leaf floors and decrease gates —
        # sklearn recomputes both min_weight_fraction_leaf and the
        # min_impurity_decrease scaling from each tree's composed bootstrap
        # weight total, so both ride the tree axis with the weights (and
        # the host failover path, which uses tree_cfg per tree, stays
        # bit-identical to this program). root_keys: (T_local,) per-tree
        # path-key seeds (per-node feature subsets / random splits).
        # mono_cst: (F,) shared constraint signs (sklearn forests apply one
        # monotonic_cst to every tree).
        return lax.map(
            lambda wcm: build(xb, y, nid0, wcm[0], wcm[1], wcm[2], wcm[3],
                              wcm[4], mono_cst),
            (ws, cand_masks, mcw, mid, root_keys),
        )

    # One branch-free table derivation serves BOTH forest meshes: on the
    # 1-D tree-only mesh (data replicated per device) every ``data`` axis
    # entry trims to None, on the 2-D (tree, data) mesh it stays — the
    # literal per-branch tuples this replaced were exactly those two
    # trims of the same rules. Tree outputs replicate across each tree
    # group after the psum'd decisions; the per-tree row assignment
    # (``tree_node_id``) keeps its rows sharded for the refit pass.
    in_specs = partition.in_specs_for(
        mesh, ("x_binned", "y", "node_id", "tree_weights",
               "tree_cand_masks", "tree_mcw", "tree_mid",
               "tree_root_keys", "mono_cst"),
    )
    out_specs = partition.out_specs_for(
        mesh, ("tree_feat", "tree_bin", "tree_counts", "tree_n_vec",
               "tree_left", "tree_parent", "tree_node_id",
               "tree_n_nodes"),
    )
    sharded = jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        # vma tracking only flags replicated-vs-varying mixes in lax.cond
        # branches that are semantically fine here (same stance as the
        # single-tree fused fn on a feature mesh).
        check_vma=False,
    )
    # No usable donation here: every output is tree-stacked (T, ...) while
    # the inputs are per-row/per-tree shapes XLA cannot alias onto them,
    # and xb/y/nid0 replicate across the whole lax.map tree batch — an
    # unusable donation would only emit compile-time warnings (the ceiling
    # tests run warnings-as-errors). Re-audited under GL08: build_forest_
    # fused also re-reads none of the inputs post-call, so donation is
    # neither usable nor (if it were) unsafe — the opt-out stands.
    return jax.jit(sharded)  # graftlint: disable=GL05


# graftlint: host-fn — host shell around the fused device program:
# materializes the finished tree arrays after ONE device_get
def build_tree_fused(
    binned,
    y: np.ndarray,
    *,
    config,
    mesh,
    n_classes: int | None = None,
    sample_weight: np.ndarray | None = None,
    refit_targets: np.ndarray | None = None,
    timer: PhaseTimer | None = None,
    return_leaf_ids: bool = False,
    feature_sampler=None,
    mono_cst: np.ndarray | None = None,
) -> TreeArrays:
    """Same contract as ``builder.build_tree``, one device program per build.

    ``feature_sampler`` (:class:`ops.sampling.NodeFeatureSampler`): per-node
    feature subsets and/or splitter="random" draws, evaluated entirely
    inside the compiled while_loop (the jnp path-key arithmetic) — the same
    trees every host/levelwise engine builds from the same sampler.
    ``mono_cst``: (F,) INTERNAL monotonicity signs (see
    ``builder.build_tree``); bounds thread through the while_loop state.
    """
    cfg = config
    task = cfg.task
    timer = timer if timer is not None else PhaseTimer(enabled=False)
    # Dataclass extents: a streamed matrix is pre-padded on device and
    # n_samples/n_features report the real dataset (builder.py twin).
    N, F = binned.n_samples, binned.n_features
    B = binned.n_bins
    C = n_classes if task == "classification" else 3

    sample_k, random_split, root_key = _sampler_statics(feature_sampler, F)
    monotonic = mono_cst is not None and bool(np.any(np.asarray(mono_cst)))
    cst_op = (
        np.ascontiguousarray(mono_cst, np.int32) if monotonic
        else np.zeros(F, np.int32)
    )

    # Chunk width binds per DEVICE: on a (data, feature) mesh each shard
    # holds only its padded feature slab, so a budget-bound chunk can be
    # df times wider than the feature-complete formula allows (the same
    # slab sizing as the levelwise engine).
    df = mesh_lib.feature_shards(mesh)
    K = _chunk_size(N, (F + ((-F) % df)) // df, B, C, cfg)
    M = _node_capacity(N, cfg.max_depth)
    int_ok = integer_weights(sample_weight)
    use_pallas = resolve_hist_kernel(
        cfg, mesh.devices.flat[0].platform, task, integer_ok=int_ok,
    )
    use_wide, wide_bf16 = resolve_wide_hist(
        cfg, mesh.devices.flat[0].platform, task, integer_ok=int_ok,
        sample_weight=sample_weight,
    )
    exact_ties = resolve_exact_ties(mesh.devices.flat[0].platform)
    if exact_ties and not exact_ties_fits(K, F, B):
        warn_exact_ties_gap(K, F, B, obs=timer)
    wide_pallas = resolve_wide_pallas(
        mesh.devices.flat[0].platform, use_wide=use_wide,
        n_channels=C, n_bins=B,
    )
    total_w_all = (
        float(N) if sample_weight is None else float(np.sum(sample_weight))
    )
    use_sub = resolve_hist_subtraction(
        cfg, mesh.devices.flat[0].platform, task, integer_ok=int_ok,
        total_weight=total_w_all, obs=timer,
        shape={"n_samples": int(N), "n_features": int(F),
               "n_bins": int(B)},
    )

    timer.set_mesh(mesh)
    timer.decision(
        "hist_subtraction", "on" if use_sub else "off",
        reason=(
            "sibling-subtraction frontier compiled into the fused loop: "
            "single-chunk interior levels accumulate the smaller child "
            "only and derive the larger from the resident parent histogram"
            if use_sub else
            "direct accumulation (resolve_hist_subtraction: config/env "
            "off, non-exact channels or non-accelerator platform under "
            "'auto', or the 2**24 f32 ceiling)"
        ),
    )
    md = -1 if cfg.max_depth is None else int(cfg.max_depth)
    fn_kw = dict(
        n_slots=K, n_bins=B, n_classes=C, task=task,
        criterion=cfg.criterion, max_nodes=M,
        max_depth=md,
        min_samples_split=int(cfg.min_samples_split),
        tiers=tuple(cfg.frontier_tiers),
        use_pallas=use_pallas, use_wide=use_wide, wide_bf16=wide_bf16,
        wide_pallas=wide_pallas, exact_ties=exact_ties,
        sample_k=sample_k, random_split=random_split,
        monotonic=monotonic,
        subtraction=use_sub,
    )
    fn = _make_fused_fn(mesh, **fn_kw)
    fused_fresh = timer.compile_note(
        "fused_fn", (mesh,) + tuple(sorted(fn_kw.items())), cache_size=32
    )

    with timer.phase("shard"):
        xb_d, y_d, w_d, nid_d, cand_d = mesh_lib.shard_build_inputs(
            mesh, binned, y, sample_weight
        )
    with timer.phase("fused_build"):
        with timer.compile_attribution("fused_fn", fused_fresh):
            if fused_fresh:
                # Compute ledger: price the fresh whole-tree program once
                # per cache key (trace-cache work the call below reuses).
                timer.price_compile("fused_fn", lambda: fn.lower(
                    xb_d, y_d, nid_d, w_d, cand_d,
                    np.float32(cfg.min_child_weight),
                    np.float32(cfg.min_decrease_scaled),
                    root_key, cst_op,
                ))
            out = fn(xb_d, y_d, nid_d, w_d, cand_d,
                     np.float32(cfg.min_child_weight),
                     np.float32(cfg.min_decrease_scaled),
                     root_key, cst_op)
        feat, bins, counts, nvec, left, parent, nid_out, n_nodes = out
        # Tree outputs are replicated (addressable from any process); the
        # row-sharded nid_out is only fetched when the refit needs it —
        # and via a cross-process gather when row shards span hosts.
        feat, bins, counts, nvec, left, parent, n_nodes = jax.device_get(
            (feat, bins, counts, nvec, left, parent, n_nodes)
        )

    with timer.phase("host_finalize"):
        tree = _finalize_tree(
            binned, task, cfg.criterion, int(n_nodes), feat, bins, counts,
            nvec, left, parent, integer_counts=integer_weights(sample_weight),
        )

    # Post-hoc per-level rows + collective accounting: replayed from the
    # finished tree's depth histogram on host (static shapes — zero device
    # cost; see obs/accounting.py). Level rows are profile-gated inside
    # timer.level; collective byte totals are always-on.
    timer.counter("fused_builds")
    eff_tiers = obs_acct.effective_tiers(
        builder_valid_tiers(tuple(cfg.frontier_tiers), K), md
    )
    rows, coll, counters = obs_acct.fused_scan_rows(
        tree, n_slots=K, tiers=eff_tiers, n_features=F, n_bins=B,
        n_channels=C, counts_channels=C, max_depth=md, task=task,
        feature_shards=mesh_lib.feature_shards(mesh),
        data_shards=mesh_lib.data_shards(mesh), n_rows=N,
        subtraction=use_sub,
    )
    for name, v in counters.items():
        timer.counter(name, v)
    for site, v in coll.items():
        timer.collective(site, calls=v["calls"], nbytes=v["bytes"])
    for r in rows:
        timer.level(**r)
    if timer.wants_fingerprints:
        # Build-state fingerprints (ISSUE 13): the one-program build has
        # no per-level host boundary, so the rows are replayed from the
        # finished tree — pinned equal to the level-wise loop's live rows.
        timer.fingerprint_tree(obs_acct.replay_fingerprints(tree))

    from mpitree_tpu.core.builder import fetch_row_nodes

    nid_host = None
    if task == "regression" and refit_targets is not None:
        nid_host = fetch_row_nodes(nid_out, N)
        w64 = (np.ones(N) if sample_weight is None
               else sample_weight).astype(np.float64)
        refit_regression_values(tree, nid_host, w64, refit_targets)

    if return_leaf_ids:
        if nid_host is None:
            nid_host = fetch_row_nodes(nid_out, N)
        return tree, nid_host
    return tree


# graftlint: host-fn — post-device_get numpy finalization
def _finalize_tree(binned, task, criterion, n_nodes, feat, bins, counts,
                   nvec, left, parent, *, integer_counts: bool) -> TreeArrays:
    """Device build buffers (full capacity) -> host TreeArrays (trimmed)."""
    feat = feat[:n_nodes]
    bins = bins[:n_nodes]
    counts = counts[:n_nodes]
    nvec = nvec[:n_nodes]
    left = left[:n_nodes]
    parent = parent[:n_nodes]

    right = np.where(left >= 0, left + 1, -1).astype(np.int32)
    threshold = np.full(n_nodes, np.nan, np.float32)
    interior = feat >= 0
    threshold[interior] = binned.thresholds[feat[interior], bins[interior]]
    depth = np.zeros(n_nodes, np.int32)
    has_parent = parent >= 0
    # Parents precede children in id order; k sweeps settle depth <= k,
    # so this converges in tree-depth iterations.
    while True:
        nd = np.where(
            has_parent, depth[np.maximum(parent, 0)] + 1, 0
        ).astype(np.int32)
        if np.array_equal(nd, depth):
            break
        depth = nd

    if task == "classification":
        count_out = counts.astype(np.int64 if integer_counts else np.float64)
        value = counts.argmax(axis=1).astype(np.int32)
        impurity = imp_utils.class_node_impurity(counts, criterion)
    else:
        mean = counts[:, 1] / np.maximum(counts[:, 0], 1.0)
        value = mean.astype(np.float32)
        count_out = mean[:, None].astype(np.float64)
        # f32-accuracy variance; overwritten exactly by the refit pass.
        impurity = imp_utils.moment_node_impurity(counts)

    return TreeArrays(
        feature=feat.astype(np.int32),
        threshold=threshold,
        left=left.astype(np.int32),
        right=right,
        parent=parent.astype(np.int32),
        depth=depth,
        value=value,
        count=count_out,
        n_node_samples=nvec.astype(np.int64),
        impurity=impurity,
    )


# graftlint: host-fn — host shell; per-tree np.asarray pulls happen
# after the single forest-program device_get (deliberate boundary)
def build_forest_fused(
    binned,
    y: np.ndarray,
    *,
    config,
    mesh,
    weights: np.ndarray,
    cand_masks: np.ndarray,
    n_classes: int | None = None,
    refit_targets: np.ndarray | None = None,
    integer_counts: bool = True,
    timer: PhaseTimer | None = None,
    return_leaf_ids: bool = False,
    min_child_weights: np.ndarray | None = None,
    min_decrease_scaleds: np.ndarray | None = None,
    root_keys: np.ndarray | None = None,
    sample_k: int | None = None,
    random_split: bool = False,
    mono_cst: np.ndarray | None = None,
) -> list:
    """Build T trees as ONE device program, trees sharded over the mesh.

    ``weights``: (T, N) per-tree sample weights (bootstrap multiplicities
    composed with any user weights); ``cand_masks``: (T, F, B) per-tree
    candidate masks (random subspaces). ``root_keys``: (T,) uint32 per-tree
    path-key seeds with ``sample_k``/``random_split`` — sklearn's per-NODE
    ``max_features`` subsets and ExtraTrees random splits, evaluated inside
    the one compiled forest program (``ops/sampling.py`` jnp twins).
    The mesh is 2-D ``(tree, data)``
    (``mesh_lib.tree_data_shape``): the tree axis carries ensemble
    parallelism (the reference's subtree task-parallelism reborn; BASELINE
    configs[4]) and the data axis — engaged when trees are fewer than
    devices, or when the binned matrix would blow the per-device HBM budget
    — row-shards each tree group's build with psum'd histograms, the same
    collective path as the single-tree engine.

    Trees are bit-identical to sequential single-device builds with the same
    weights/masks: the per-device build body is the same program.
    """
    from mpitree_tpu.ops.binning import StreamedBinnedData

    cfg = config
    task = cfg.task
    timer = timer if timer is not None else PhaseTimer(enabled=False)
    # A streamed matrix arrives PRE-padded and pre-placed by the ingest
    # tier: real extents come from the dataclass, the program width from
    # the buffer (ingest feature padding stays inert — its candidate-mask
    # columns are force-zeroed below, so no split ever lands there).
    streamed = isinstance(binned, StreamedBinnedData)
    T, N = weights.shape
    F = binned.n_features if streamed else binned.x_binned.shape[1]
    Fb = binned.x_binned.shape[1]
    B = binned.n_bins
    C = n_classes if task == "classification" else 3

    K = _chunk_size(N, Fb, B, C, cfg)
    M = _node_capacity(N, cfg.max_depth)
    Dt, Dd = mesh_lib.tree_data_shape(
        mesh.size, T, dataset_bytes=binned.x_binned.nbytes,
        hbm_budget=FOREST_HBM_BUDGET_BYTES,
    )
    T_pad = ((T + Dt - 1) // Dt) * Dt
    data_sharded = Dd > 1
    tmesh = (
        mesh_lib.as_tree_data_mesh(mesh, (Dt, Dd))
        if data_sharded else mesh_lib.as_tree_mesh(mesh)
    )
    use_pallas = resolve_hist_kernel(
        cfg, mesh.devices.flat[0].platform, task, integer_ok=integer_counts
    )
    use_wide, wide_bf16 = resolve_wide_hist(
        cfg, mesh.devices.flat[0].platform, task, integer_ok=integer_counts,
        sample_weight=weights,
    )
    exact_ties = resolve_exact_ties(mesh.devices.flat[0].platform)
    if exact_ties and not exact_ties_fits(K, F, B):
        warn_exact_ties_gap(K, F, B, obs=timer)
    wide_pallas = resolve_wide_pallas(
        mesh.devices.flat[0].platform, use_wide=use_wide,
        n_channels=C, n_bins=B,
    )
    # Sibling subtraction in the forest program (ROADMAP carried
    # follow-up): the per-tree build body owns its resident parent
    # histogram, so it rides the lax.map carry with no extra plumbing;
    # the f32-ceiling guard bounds on the largest per-tree bootstrap
    # total (the per-channel maximum any tree's parent can reach).
    tree_totals_max = float(weights.sum(axis=1).max(initial=0.0))
    use_sub = resolve_hist_subtraction(
        cfg, mesh.devices.flat[0].platform, task, integer_ok=integer_counts,
        total_weight=tree_totals_max, obs=timer,
        shape={"n_samples": int(N), "n_features": int(F),
               "n_bins": int(B)},
    )
    timer.decision(
        "hist_subtraction", "on" if use_sub else "off",
        reason=(
            "sibling-subtraction frontier compiled into the per-tree "
            "lax.map body (parent histogram rides each tree's loop carry)"
            if use_sub else
            "direct accumulation (resolve_hist_subtraction: config/env "
            "off, non-exact channels or non-accelerator platform under "
            "'auto', or the 2**24 f32 ceiling)"
        ),
    )

    if task == "classification" and tree_totals_max >= 2**24:
        warn_event(
            timer, "f32_ceiling",
            "device class counts accumulate in float32: beyond 2**24 "
            "per-tree total weight the raw-count contract can lose integer "
            "exactness",
            stacklevel=2,
        )

    timer.set_mesh(tmesh)
    # Memory ledger + OOM preflight (ISSUE 13 satellite, the PR-12 gap):
    # the forest program records a plan like every other engine, priced
    # per the partition table's tree-axis rules, and refuses a predicted
    # over-budget build BEFORE the one big dispatch.
    fplan = memory_lib.plan_forest(
        n_trees=T, rows=int(N), features=int(F),
        classes=int(n_classes or 2), bins=int(B), task=task,
        max_depth=cfg.max_depth, tree_shards=Dt, data_shards=Dd,
        subtraction=use_sub, chunk_slots=K, node_capacity=M,
        hist_budget_bytes=cfg.hist_budget_bytes,
    )
    timer.memory_plan(fplan.to_dict())
    memory_lib.preflight(fplan, obs=timer, what="forest build")
    md = -1 if cfg.max_depth is None else int(cfg.max_depth)
    fn_kw = dict(
        n_slots=K, n_bins=B, n_classes=C, task=task,
        criterion=cfg.criterion, max_nodes=M,
        max_depth=md,
        min_samples_split=int(cfg.min_samples_split),
        tiers=tuple(cfg.frontier_tiers),
        use_pallas=use_pallas, use_wide=use_wide, wide_bf16=wide_bf16,
        wide_pallas=wide_pallas, exact_ties=exact_ties,
        data_sharded=data_sharded,
        sample_k=sample_k, random_split=random_split,
        monotonic=mono_cst is not None and bool(np.any(np.asarray(mono_cst))),
        subtraction=use_sub,
    )
    fn = _make_forest_fn(tmesh, **fn_kw)
    forest_fresh = timer.compile_note(
        "forest_fn", (tmesh,) + tuple(sorted(fn_kw.items())), cache_size=32
    )

    ws = weights.astype(np.float32)
    cm = np.asarray(cand_masks)
    if Fb != F:
        # Ingest feature padding: zero candidate columns keep the padded
        # features inert inside the program.
        cm = np.concatenate(
            [cm, np.zeros((cm.shape[0], Fb - F, cm.shape[2]), bool)],
            axis=1,
        )
    # Per-tree leaf floors (sklearn recomputes min_weight_fraction_leaf per
    # bootstrap); a shared scalar floor broadcasts when none are given.
    mcw = (
        np.full(T, np.float32(cfg.min_child_weight))
        if min_child_weights is None
        else np.asarray(min_child_weights, np.float32)
    )
    mid = (
        np.full(T, np.float32(cfg.min_decrease_scaled))
        if min_decrease_scaleds is None
        else np.asarray(min_decrease_scaleds, np.float32)
    )
    rks = (
        np.zeros(T, np.uint32) if root_keys is None
        else np.asarray(root_keys, np.uint32)
    )
    if T_pad != T:  # pad with repeats; surplus trees are dropped after build
        ws = np.concatenate([ws, np.broadcast_to(ws[-1:], (T_pad - T, N))])
        cm = np.concatenate(
            [cm, np.broadcast_to(cm[-1:], (T_pad - T, Fb, cm.shape[2]))]
        )
        mcw = np.concatenate([mcw, np.broadcast_to(mcw[-1:], (T_pad - T,))])
        mid = np.concatenate([mid, np.broadcast_to(mid[-1:], (T_pad - T,))])
        rks = np.concatenate([rks, np.broadcast_to(rks[-1:], (T_pad - T,))])

    with timer.phase("shard"):
        if streamed:
            # The matrix is already device-resident, padded for the
            # ingest mesh's data axis (pad rows at the global END). That
            # padding carries over: pad rows ride as node_id=-1 /
            # weight-0 rows exactly like pad_row_arrays', contributing
            # +0.0f to every histogram — bit-inert whatever the width
            # mismatch between the ingest data axis and this forest
            # mesh's Dd. Only the row-axis divisibility must be
            # re-established when Dd does not divide the buffer rows.
            xb_h = binned.x_binned
            R = int(xb_h.shape[0])
            extra = (-R) % Dd
            if extra:
                xb_h = jnp.concatenate(
                    [xb_h, jnp.zeros((extra, Fb), xb_h.dtype)]
                )
                R += extra
            pad = R - N
            y_np = np.asarray(y)
            y_h = np.concatenate([y_np, np.zeros(pad, y_np.dtype)])
            ws = np.concatenate(
                [ws, np.zeros((ws.shape[0], pad), np.float32)], axis=1
            )
            nid_h = np.concatenate(
                [np.zeros(N, np.int32), np.full(pad, -1, np.int32)]
            )
        else:
            xb_h, y_h, ws, nid_h = mesh_lib.pad_row_arrays(
                binned.x_binned, np.asarray(y), ws, np.zeros(N, np.int32),
                Dd,
            )
        cst_op = (
            np.zeros(Fb, np.int32) if mono_cst is None
            else np.ascontiguousarray(mono_cst, np.int32)
        )
        if mono_cst is not None and len(cst_op) != Fb:
            cst_op = np.concatenate(
                [cst_op, np.zeros(Fb - len(cst_op), np.int32)]
            )
        # Placement from the rule table (partition.shard_build_state) —
        # the same names _make_forest_fn's in_specs consult, trimmed the
        # same way on both forest meshes, replacing the per-branch
        # device_put spec tuples this block used to hand-write.
        placed = partition.shard_build_state(tmesh, {
            "x_binned": xb_h, "y": y_h, "node_id": nid_h,
            "tree_weights": ws, "tree_cand_masks": cm,
            "tree_mcw": mcw, "tree_mid": mid, "tree_root_keys": rks,
            "mono_cst": cst_op,
        })

    with timer.phase("forest_build"):
        with timer.compile_attribution("forest_fn", forest_fresh):
            if forest_fresh:
                timer.price_compile("forest_fn", lambda: fn.lower(
                    placed["x_binned"], placed["y"], placed["node_id"],
                    placed["tree_weights"], placed["tree_cand_masks"],
                    placed["tree_mcw"], placed["tree_mid"],
                    placed["tree_root_keys"], placed["mono_cst"],
                ))
            out = fn(placed["x_binned"], placed["y"], placed["node_id"],
                     placed["tree_weights"], placed["tree_cand_masks"],
                     placed["tree_mcw"], placed["tree_mid"],
                     placed["tree_root_keys"], placed["mono_cst"])
        feat, bins, counts, nvec, left, parent, nid_out, n_nodes = (
            jax.device_get(out)
        )

    trees = []
    with timer.phase("host_finalize"):
        for t in range(T):
            tree = _finalize_tree(
                binned, task, cfg.criterion, int(n_nodes[t]), feat[t],
                bins[t], counts[t], nvec[t], left[t], parent[t],
                integer_counts=integer_counts,
            )
            if task == "regression" and refit_targets is not None:
                refit_regression_values(
                    tree, np.asarray(nid_out[t])[:N],
                    weights[t].astype(np.float64), refit_targets,
                )
            trees.append(tree)
    timer.counter("forest_fused_builds")
    timer.counter("trees_built", T)
    # Realized-work counters replay per tree (always-on; the subtraction
    # carry on the per-tree lax.map loop shows up as scanned < frontier).
    # Collective rows only when row shards actually psum: non-data-sharded
    # forests run with psum_axis=None (data replicated per device).
    eff_tiers = obs_acct.effective_tiers(
        builder_valid_tiers(tuple(cfg.frontier_tiers), K), md
    )
    for tree in trees:
        _, coll, counters = obs_acct.fused_scan_rows(
            tree, n_slots=K, tiers=eff_tiers, n_features=F,
            n_bins=B, n_channels=C, counts_channels=C, max_depth=md,
            task=task, subtraction=use_sub,
        )
        for name, v in counters.items():
            timer.counter(name, v)
        if data_sharded:
            for site, v in coll.items():
                timer.collective(site, calls=v["calls"], nbytes=v["bytes"])
        if timer.wants_fingerprints:
            # One fingerprint row list per ensemble member, in member
            # order — the forest twin of the boosting per-round commits.
            timer.fingerprint_tree(obs_acct.replay_fingerprints(tree))
    if return_leaf_ids:
        return trees, np.asarray(nid_out)[:T, :N]
    return trees
