"""Core build machinery: struct-of-arrays tree and the level-synchronous builder."""
