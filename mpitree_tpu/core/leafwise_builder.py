"""Leaf-wise (best-first) tree growth — the ``max_leaf_nodes`` frontier.

The level-synchronous engines spend one full O(N*F) histogram pass per
LEVEL: every frontier slot gets a histogram whether its best split is
worth anything or not, and on covtype-like data most depth-20 slots carry
near-zero gain. This module grows the tree in the LightGBM order instead
(Ke et al. 2017, "best-first"/"lossguide"): a fixed-capacity,
statically-shaped priority pool holds every open leaf with its best
candidate split and gain; each step expands ONLY the highest-gain leaf,
paying one sibling-pair histogram — under the PR-5 subtraction carry the
accumulated side is just the SMALLER child (the larger is
``parent - small`` against the leaf's pool-resident histogram), so each
split costs one half-pair histogram + psum. Growth stops at
``max_leaf_nodes`` leaves or when no open leaf clears the gain gates.

Two engines, one arithmetic (``parallel/collective.pair_split_stats`` is
the shared pair kernel, ``ops/impurity.leaf_gain``/``best_leaf_slot``
the shared priority):

- **fused** (default): the whole best-first loop is ONE compiled
  ``lax.while_loop`` program — pool gains, node arrays, and (under
  subtraction) the per-leaf resident histograms all ride the loop carry;
  best-leaf selection is a ``lax.top_k`` over the padded pool with a
  lowest-node-id tie-break — no host sync anywhere in the loop
  (GL01-clean). This body is also what the fused multi-round GBDT
  program (``boosting/fused_rounds``) scans over.
- **levelwise** (the host-stepped counterpart): one
  ``collective.make_expand_fn`` dispatch per expansion with the pool
  bookkeeping on host — per-expansion obs rows, chaos seams, and the
  engine-identity cross-check against the fused program.

Node ids are assigned in EXPANSION order on device, then renumbered to
the canonical breadth-first order every level-synchronous engine uses
(:func:`bfs_new_ids`) — so with ``max_leaf_nodes`` at the level-wise
node budget (``2^max_depth``) the finished tree is bit-identical to the
level-wise engines wherever the stopping rules are (they are node-local
and order-independent), which is what the equivalence pins hold.
"""

from __future__ import annotations

import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mpitree_tpu.core.builder import (
    integer_weights,
    resolve_exact_ties,
    resolve_gbdt_x64,
    resolve_hist_subtraction,
)
from mpitree_tpu.core.fused_builder import _finalize_tree
from mpitree_tpu.obs import accounting as obs_acct
from mpitree_tpu.ops import impurity as imp_ops
from mpitree_tpu.parallel import collective, mesh as mesh_lib, partition
from mpitree_tpu.parallel.mesh import DATA_AXIS
from mpitree_tpu.resilience import chaos, recovery as recovery_lib
from mpitree_tpu.utils.profiling import PhaseTimer
from mpitree_tpu.config import knobs


def _pool_capacity(max_leaf_nodes: int, max_depth, n_samples: int) -> int:
    """Open-leaf pool width: the static shape every buffer sizes from.

    A depth-``d`` tree can hold at most ``2^d`` leaves and ``N`` rows at
    most ``N`` non-empty ones, so the pool (and the ``2P - 1`` node
    capacity) shrinks to whatever is actually reachable — the compiled
    program's buffers are proportional to the LEAF budget, not the node
    capacity of a depth-bounded level-wise build.
    """
    p = int(max_leaf_nodes)
    if max_depth is not None and max_depth < 31:
        p = min(p, 2 ** max(int(max_depth), 0))
    return max(min(p, max(n_samples, 1)), 1)


def _stop_and_gain_jnp(dec, pure, child_depth, *, task, max_depth,
                       min_samples_split, mid, msg):
    """Stopping rules + expansion priority for a decision pair (device).

    The identical rule set the level-synchronous engines apply (purity /
    constancy / ``min_samples_split`` / no-valid-candidate /
    ``min_impurity_decrease`` / gbdt ``min_split_gain`` / depth cap),
    evaluated in the same f32 arithmetic; a stopped child enters the pool
    with ``-inf`` gain and can never be expanded.
    """
    n = (dec.counts.sum(axis=1) if task == "classification"
         else dec.counts[:, 0])
    stop = (
        pure | dec.constant | (n < min_samples_split)
        | jnp.isinf(dec.cost)
        | ((mid > 0) & (n * (dec.impurity - dec.cost) < mid))
    )
    if task == "gbdt":
        stop = stop | ((msg > 0) & (dec.impurity - dec.cost < msg))
    if max_depth >= 0:
        stop = stop | (child_depth == max_depth)
    gain = imp_ops.leaf_gain(n, dec.impurity, dec.cost, task=task)
    gain = jnp.where(stop | jnp.isnan(gain), -jnp.inf, gain)
    return n, stop, gain


def _stop_and_gain_np(dec, child_depth, *, task, cfg):
    """Host twin of :func:`_stop_and_gain_jnp` for the stepped engine.

    Operates on an :func:`collective.unpack_decision` dict (all-f32
    fields) with the same one-multiply-one-subtract f32 arithmetic, so
    both engines rank every pair identically.
    """
    counts = dec["counts"]
    if task == "classification":
        n = counts.sum(axis=1, dtype=np.float32)
        pure = (counts > 0).sum(axis=1) <= 1
    elif task == "gbdt":
        n = counts[:, 0]
        pure = np.zeros(2, bool)
    else:
        n = counts[:, 0]
        pure = dec["y_range"] <= 0.0
    imp, cost = dec["impurity"], dec["cost"]
    with np.errstate(invalid="ignore"):
        stop = (
            pure | dec["constant"] | (n < cfg.min_samples_split)
            | np.isinf(cost)
        )
        if cfg.min_decrease_scaled > 0.0:
            stop |= (
                n * (imp - cost) < np.float32(cfg.min_decrease_scaled)
            )
        if task == "gbdt" and cfg.min_split_gain > 0.0:
            stop |= (imp - cost) < np.float32(cfg.min_split_gain)
        if cfg.max_depth is not None and child_depth == cfg.max_depth:
            stop = np.ones(2, bool)
        gain = imp_ops.leaf_gain(n, imp, cost, task=task)
        gain = np.where(stop | np.isnan(gain), -np.inf, gain)
    return n, stop, gain.astype(np.float32)


def _make_leafwise_body(*, n_bins: int, n_classes: int, task: str,
                        criterion: str, max_leaves: int, max_depth: int,
                        min_samples_split: int,
                        psum_axis: str | None = DATA_AXIS,
                        exact_ties: bool = False, gbdt_x64: bool = False,
                        subtraction: bool = False):
    """Pure per-device best-first build: (xb, y, nid0, w, cand_mask,
    mcw, mid, lam, msl, msg) -> (feat, bin, counts, n, left, parent,
    depth, nid, n_nodes).

    ``max_depth < 0`` = unbounded. Node capacity is exactly
    ``2 * max_leaves - 1`` (every expansion adds two nodes and one leaf).
    The per-expansion histograms are two-slot scatters (one compact slot
    under ``subtraction``), so no Pallas/wide kernel tiers apply — the
    scalar-unit scatter is already minimal at pair width. ``lam``/
    ``msl``/``msg`` are the gbdt Newton scalars (reg_lambda,
    min_samples_leaf, min_split_gain; dead operands otherwise).
    """
    Pn = int(max_leaves)
    M = 2 * Pn - 1
    C = n_classes if task == "classification" else 3
    f64_pool = subtraction and task == "gbdt" and gbdt_x64

    # graftlint: device-fn (jit-wrapped indirectly: this factory's return
    # value reaches jax.shard_map in _make_leafwise_fn and the fused
    # multi-round GBDT program)
    def build(xb, y, nid0, w, cand_mask, mcw, mid, lam, msl, msg):
        R, F = xb.shape

        def pair(nid, base_id, is_small, phist_row):
            return collective.pair_split_stats(
                xb, y, nid, w, cand_mask, base_id, is_small, phist_row,
                mcw, lam, msl, task=task, criterion=criterion,
                n_bins=n_bins, n_classes=C, exact_ties=exact_ties,
                gbdt_x64=gbdt_x64, subtraction=subtraction,
                psum_axis=psum_axis,
            )

        # Pool + tree buffers. The f64 pool histogram (gbdt scoped-x64
        # path) is created as f32 zeros CONVERTED inside the scope — a
        # direct f64 zeros canonicalizes to f32 at lowering time on
        # pre-shard_map wheels (the ops/histogram._channel_histogram
        # lesson); every later read/write of it is scoped the same way.
        if subtraction:
            if f64_pool:
                with jax.enable_x64(True):
                    # Slice INSIDE the scope too: an outside-scope op on
                    # an f64 array canonicalizes its aval to f32 while the
                    # runtime value stays f64 — a lowering-time verifier
                    # mismatch on legacy wheels.
                    pool_hist = jnp.zeros(
                        (Pn, F, C, n_bins), jnp.float32
                    ).astype(jnp.float64)
                    root_phist = pool_hist[:1]
            else:
                pool_hist = jnp.zeros((Pn, F, C, n_bins), jnp.float32)
                root_phist = pool_hist[:1]
        else:
            pool_hist = root_phist = None

        # Root bootstrap rides the pair kernel: every row still carries
        # node 0, so slot 0 IS the root (slot 1 empty under direct
        # accumulation; garbage-but-unread against the zero parent under
        # subtraction, where "small" slot 0 accumulates everything).
        root_small = jnp.array([True, False])
        dec0, pure0, keep0 = pair(nid0, jnp.int32(0), root_small,
                                  root_phist)
        n0, _, gain0 = _stop_and_gain_jnp(
            dec0, pure0, jnp.int32(0), task=task, max_depth=max_depth,
            min_samples_split=min_samples_split, mid=mid, msg=msg,
        )

        feat_a = jnp.full(M, -1, jnp.int32)
        bin_a = jnp.zeros(M, jnp.int32)
        counts_a = jnp.zeros((M, C), jnp.float32).at[0].set(
            dec0.counts[0].astype(jnp.float32)
        )
        n_a = jnp.zeros(M, jnp.float32).at[0].set(n0[0])
        left_a = jnp.full(M, -1, jnp.int32)
        parent_a = jnp.full(M, -1, jnp.int32)
        depth_a = jnp.zeros(M, jnp.int32)

        pool_gain = jnp.full(Pn, -jnp.inf, jnp.float32).at[0].set(gain0[0])
        pool_node = jnp.zeros(Pn, jnp.int32)
        pool_feat = jnp.zeros(Pn, jnp.int32).at[0].set(dec0.feature[0])
        pool_bin = jnp.zeros(Pn, jnp.int32).at[0].set(dec0.bin[0])
        pool_nl = jnp.zeros(Pn, jnp.float32).at[0].set(dec0.n_left[0])
        if subtraction:
            if f64_pool:
                with jax.enable_x64(True):
                    pool_hist = pool_hist.at[0].set(keep0[0])
            else:
                pool_hist = pool_hist.at[0].set(keep0[0])

        def cond(state):
            pool_gain, n_leaves = state[8], state[14]
            return jnp.logical_and(
                n_leaves < Pn, jnp.max(pool_gain) > -jnp.inf
            )

        def body(state):
            (feat_a, bin_a, counts_a, n_a, left_a, parent_a, depth_a, nid,
             pool_gain, pool_node, pool_feat, pool_bin, pool_nl,
             n_nodes, n_leaves) = state[:15]
            pool_hist = state[15] if subtraction else None

            # Best open leaf: lax.top_k over the padded pool, gain ties
            # broken toward the lowest node id (ops/impurity).
            p = imp_ops.best_leaf_slot(pool_gain, pool_node)
            enode = pool_node[p]
            f = pool_feat[p]
            b = pool_bin[p]
            l_id = n_nodes

            feat_a = feat_a.at[enode].set(f)
            bin_a = bin_a.at[enode].set(b)
            left_a = left_a.at[enode].set(l_id)
            parent_a = parent_a.at[l_id].set(enode)
            parent_a = parent_a.at[l_id + 1].set(enode)
            child_depth = depth_a[enode] + 1
            depth_a = depth_a.at[l_id].set(child_depth)
            depth_a = depth_a.at[l_id + 1].set(child_depth)

            # Reroute the expanded leaf's rows (everyone else is parked).
            xf = jnp.take_along_axis(
                xb, jnp.broadcast_to(jnp.maximum(f, 0), (R,))[:, None],
                axis=1,
            )[:, 0]
            child = jnp.where(xf <= b, l_id, l_id + 1)
            nid = jnp.where(nid == enode, child, nid)

            # Smaller-sibling pick from the recorded winner's left weight
            # (ties go left — the same rule as the level-wise carry).
            small_left = pool_nl[p] * 2.0 <= n_a[enode]
            is_small = jnp.stack([small_left, ~small_left])
            if subtraction:
                # All-i32 start indices: inside the scoped-x64 branch the
                # literal zeros would otherwise promote to i64 and clash
                # with the i32 pool slot.
                z = jnp.int32(0)
                if f64_pool:
                    with jax.enable_x64(True):
                        phist_row = lax.dynamic_slice(
                            pool_hist, (p, z, z, z), (1, F, C, n_bins)
                        )
                else:
                    phist_row = lax.dynamic_slice(
                        pool_hist, (p, z, z, z), (1, F, C, n_bins)
                    )
            else:
                phist_row = None
            dec, pure, keep = pair(nid, l_id, is_small, phist_row)
            n2, _, gain2 = _stop_and_gain_jnp(
                dec, pure, child_depth, task=task, max_depth=max_depth,
                min_samples_split=min_samples_split, mid=mid, msg=msg,
            )

            counts_a = lax.dynamic_update_slice(
                counts_a, dec.counts.astype(jnp.float32), (l_id, 0)
            )
            n_a = lax.dynamic_update_slice(
                n_a, n2.astype(jnp.float32), (l_id,)
            )

            # Left child reuses the parent's pool slot, right child takes
            # the next fresh one — slot count == n_leaves by induction.
            q = n_leaves
            pool_gain = pool_gain.at[p].set(gain2[0]).at[q].set(gain2[1])
            pool_node = pool_node.at[p].set(l_id).at[q].set(l_id + 1)
            pool_feat = (
                pool_feat.at[p].set(dec.feature[0]).at[q].set(dec.feature[1])
            )
            pool_bin = pool_bin.at[p].set(dec.bin[0]).at[q].set(dec.bin[1])
            pool_nl = pool_nl.at[p].set(dec.n_left[0]).at[q].set(
                dec.n_left[1]
            )
            out = (feat_a, bin_a, counts_a, n_a, left_a, parent_a, depth_a,
                   nid, pool_gain, pool_node, pool_feat, pool_bin, pool_nl,
                   n_nodes + 2, n_leaves + 1)
            if subtraction:
                if f64_pool:
                    with jax.enable_x64(True):
                        pool_hist = pool_hist.at[p].set(keep[0])
                        pool_hist = pool_hist.at[q].set(keep[1])
                else:
                    pool_hist = pool_hist.at[p].set(keep[0])
                    pool_hist = pool_hist.at[q].set(keep[1])
                out = out + (pool_hist,)
            return out

        state0 = (feat_a, bin_a, counts_a, n_a, left_a, parent_a, depth_a,
                  nid0, pool_gain, pool_node, pool_feat, pool_bin, pool_nl,
                  jnp.int32(1), jnp.int32(1))
        if subtraction:
            state0 = state0 + (pool_hist,)
        out = lax.while_loop(cond, body, state0)
        (feat_a, bin_a, counts_a, n_a, left_a, parent_a, depth_a,
         nid) = out[:8]
        return (feat_a, bin_a, counts_a, n_a, left_a, parent_a, depth_a,
                nid, out[13])

    return build


@lru_cache(maxsize=32)
def _make_leafwise_fn(mesh, *, n_bins: int, n_classes: int, task: str,
                      criterion: str, max_leaves: int, max_depth: int,
                      min_samples_split: int, exact_ties: bool = False,
                      gbdt_x64: bool = False, subtraction: bool = False):
    """Data-parallel fused leaf-wise build: rows sharded, pair histograms
    psum'd, the whole best-first loop one compiled program."""
    build = _make_leafwise_body(
        n_bins=n_bins, n_classes=n_classes, task=task, criterion=criterion,
        max_leaves=max_leaves, max_depth=max_depth,
        min_samples_split=min_samples_split, psum_axis=DATA_AXIS,
        exact_ties=exact_ties, gbdt_x64=gbdt_x64, subtraction=subtraction,
    )
    sharded = jax.shard_map(
        build,
        mesh=mesh,
        in_specs=partition.in_specs_for(mesh, (
            "x_binned", "y", "node_id", "weight", "cand_mask",
            ("mcw", 0), ("mid", 0), ("lam", 0), ("msl", 0), ("msg", 0),
        )),
        out_specs=partition.out_specs_for(mesh, (
            "feat", "bin", "counts", "n_vec", "left_id", "parent_id",
            "depth", "node_id", ("n_nodes", 0),
        )),
    )
    # nid0 donated (GL05): freshly sharded per build, and the program
    # returns the advanced assignment with identical shape/sharding —
    # callers (GL08) never touch nid_d after the call.
    return jax.jit(sharded, donate_argnums=(2,))


def bfs_new_ids(left: np.ndarray) -> np.ndarray:
    """Expansion-ordered node ids -> canonical breadth-first ids.

    The level-synchronous engines allocate children level by level in
    parent-id order (left before right); replaying that walk over the
    finished structure gives each node the id a level-wise build would
    have assigned — the identity-pin permutation. ``left`` must hold
    expansion-order ids (children allocated pairwise, right = left + 1);
    returns ``new_id[old_id]``.
    """
    n = len(left)
    perm = np.zeros(n, np.int64)
    frontier = np.array([0], np.int64)
    k = 1
    while len(frontier):
        parents = frontier[left[frontier] >= 0]
        if not len(parents):
            break
        kids = np.empty(2 * len(parents), np.int64)
        kids[0::2] = left[parents]
        kids[1::2] = left[parents] + 1
        perm[kids] = k + np.arange(len(kids))
        k += len(kids)
        frontier = kids
    return perm


def _finalize_leafwise(binned, task, criterion, n_nodes, feat, bins, counts,
                       nvec, left, parent, *, integer_counts: bool):
    """Trim, BFS-renumber, and finalize device buffers into a TreeArrays.

    Returns ``(tree, perm)`` with ``perm`` the old->new id map (callers
    remap row->leaf assignments through it).
    """
    feat = np.asarray(feat[:n_nodes])
    bins = np.asarray(bins[:n_nodes])
    counts = np.asarray(counts[:n_nodes])
    nvec = np.asarray(nvec[:n_nodes])
    left = np.asarray(left[:n_nodes])
    parent = np.asarray(parent[:n_nodes])
    perm = bfs_new_ids(left)

    def scatter(a):
        out = np.empty_like(a)
        out[perm] = a
        return out

    left_v = np.where(left >= 0, perm[np.maximum(left, 0)], -1)
    parent_v = np.where(parent >= 0, perm[np.maximum(parent, 0)], -1)
    tree = _finalize_tree(
        binned, task, criterion, int(n_nodes), scatter(feat), scatter(bins),
        scatter(counts), scatter(nvec), scatter(left_v).astype(np.int32),
        scatter(parent_v).astype(np.int32), integer_counts=integer_counts,
    )
    return tree, perm


# graftlint: host-fn — the leaf-wise router/finalizer: engine resolution,
# device_get of finished buffers, and numpy renumbering are its job
def build_tree_leafwise(
    binned,
    y: np.ndarray,
    *,
    config,
    mesh,
    n_classes: int | None = None,
    sample_weight: np.ndarray | None = None,
    refit_targets: np.ndarray | None = None,
    timer: PhaseTimer | None = None,
    return_leaf_ids: bool = False,
    feature_sampler=None,
    mono_cst: np.ndarray | None = None,
    snapshot_slot=None,
):
    """Grow one tree best-first; same contract as ``builder.build_tree``.

    ``snapshot_slot``: the sub-build retry handle (ISSUE 14) — the
    host-stepped engine snapshots its carry at EXPANSION granularity, so
    a transient failure at expansion e re-dispatches expansions >= e
    only. The fused engine (one compiled program, no host boundary)
    ignores it.

    Routed by ``build_tree`` whenever ``BuildConfig.max_leaf_nodes`` is
    set. Engine resolution mirrors the level-wise one: "fused" (default —
    the whole loop is one program) or "levelwise" (the host-stepped
    expansion loop with per-expansion obs rows and chaos seams);
    ``MPITREE_TPU_ENGINE`` steers the default. Per-node feature sampling,
    monotonic constraints, and (data, feature) meshes are not supported
    with a leaf-wise frontier yet (ROADMAP carries the follow-ups).
    """
    cfg = config
    task = cfg.task
    timer = timer if timer is not None else PhaseTimer(enabled=False)
    timer.set_mesh(mesh)
    if feature_sampler is not None and feature_sampler.active:
        raise ValueError(
            "max_leaf_nodes does not support per-node feature sampling "
            "(max_features / splitter='random') yet"
        )
    if mono_cst is not None and bool(np.any(np.asarray(mono_cst) != 0)):
        raise ValueError(
            "max_leaf_nodes does not support monotonic_cst yet"
        )
    if mesh_lib.feature_shards(mesh) > 1:
        # The best-first frontier has no feature-axis winner merge yet:
        # its pair kernel sweeps feature-complete histograms, so running
        # it on a (data, feature) mesh would silently evaluate only one
        # shard's slab — refuse LOUDLY, with the typed event + recorded
        # decision so fit_report_ postmortems see why (the expansion-step
        # select_global twin is the ROADMAP follow-up).
        timer.decision(
            "leafwise_mesh", "refused",
            reason=(
                "(data, feature) mesh: the leaf-wise pair kernel has no "
                "feature-axis select_global twin yet — use a 1-D data "
                "mesh or drop max_leaf_nodes"
            ),
            feature_shards=int(mesh_lib.feature_shards(mesh)),
        )
        timer.event(
            "mesh2d_unsupported",
            "max_leaf_nodes supports 1-D data meshes only (no feature-"
            "axis winner merge in the expansion loop)",
        )
        raise ValueError(
            "max_leaf_nodes supports 1-D data meshes only "
            "(mesh2d_unsupported: the best-first frontier has no "
            "feature-axis select_global twin)"
        )
    if cfg.hist_kernel == "pallas":
        raise ValueError(
            "hist_kernel='pallas' cannot apply to a leaf-wise frontier: "
            "per-expansion histograms are two-slot scatters with no "
            "Mosaic tier"
        )
    if (cfg.hist_kernel == "auto"
            and knobs.value("MPITREE_TPU_HIST_KERNEL") == "pallas"):
        # The env var is an ambient preference for level-wise fits and
        # must not crash a fit it cannot apply to (only the explicit
        # BuildConfig raises) — same graceful identity opt-out as the
        # serving tier's forced-but-unsatisfiable kernel.
        timer.event(
            "leafwise_pallas_fallback",
            "MPITREE_TPU_HIST_KERNEL=pallas ignored for the leaf-wise "
            "frontier: per-expansion histograms are two-slot scatters "
            "with no Mosaic tier (scatter path used)",
        )

    engine = cfg.engine
    engine_reason = None
    if engine != "auto":
        engine_reason = f"explicit BuildConfig(engine={engine!r})"
    else:
        env_engine = knobs.value("MPITREE_TPU_ENGINE")
        if env_engine != "auto":
            engine = env_engine
            engine_reason = f"MPITREE_TPU_ENGINE={env_engine}"
    if engine not in ("auto", "fused", "levelwise"):
        raise ValueError(f"unknown build engine {engine!r}")
    if engine == "auto":
        engine = "fused"
        engine_reason = (
            "auto: the best-first loop runs one expansion per step — "
            "per-expansion host dispatch would put O(max_leaf_nodes) "
            "round trips on the critical path, so the fused single-"
            "program loop is the default"
        )

    platform = mesh.devices.flat[0].platform
    # Dataclass extents: a streamed matrix is pre-padded on device and
    # n_samples/n_features report the real dataset (builder.py twin).
    N, F = binned.n_samples, binned.n_features
    B = binned.n_bins
    C = n_classes if task == "classification" else 3
    int_ok = integer_weights(sample_weight)
    exact_ties = resolve_exact_ties(platform)
    gbdt_x64 = task == "gbdt" and resolve_gbdt_x64(platform)
    total_w = (
        float(N) if sample_weight is None else float(np.sum(sample_weight))
    )
    use_sub = resolve_hist_subtraction(
        cfg, platform, task, integer_ok=int_ok, gbdt_x64=gbdt_x64,
        total_weight=total_w, obs=timer,
        shape={"n_samples": int(N), "n_features": int(F),
               "n_bins": int(B)},
    )
    Pn = _pool_capacity(cfg.max_leaf_nodes, cfg.max_depth, N)
    M = 2 * Pn - 1
    md = -1 if cfg.max_depth is None else int(cfg.max_depth)

    timer.decision(
        "engine", engine, reason=engine_reason,
        rows=int(N), features=int(F), bins=int(B), task=task,
    )
    timer.decision(
        "frontier", "leafwise",
        reason=(
            f"max_leaf_nodes={cfg.max_leaf_nodes}: best-first priority "
            f"pool of {Pn} open leaves; each expansion pays one "
            "sibling-pair histogram"
            + (" (smaller child only, larger = parent - small)"
               if use_sub else "")
        ),
        max_leaf_nodes=int(cfg.max_leaf_nodes), pool=int(Pn),
    )
    timer.decision(
        "hist_subtraction", "on" if use_sub else "off",
        reason=(
            "per-expansion sibling subtraction against the pool-resident "
            "parent histogram" if use_sub else
            "direct pair accumulation (resolve_hist_subtraction: "
            "config/env off, non-exact channels or non-accelerator "
            "platform under 'auto', or the 2**24 f32 ceiling)"
        ),
    )

    mcw = np.float32(cfg.min_child_weight)
    mid = np.float32(cfg.min_decrease_scaled)
    lam = np.float32(cfg.reg_lambda)
    msl = np.float32(cfg.min_leaf_rows)
    msg = np.float32(cfg.min_split_gain)

    if engine == "fused":
        fn_kw = dict(
            n_bins=B, n_classes=C, task=task, criterion=cfg.criterion,
            max_leaves=Pn, max_depth=md,
            min_samples_split=int(cfg.min_samples_split),
            exact_ties=exact_ties, gbdt_x64=gbdt_x64, subtraction=use_sub,
        )
        fn = _make_leafwise_fn(mesh, **fn_kw)
        lw_fresh = timer.compile_note(
            "leafwise_fn", (mesh,) + tuple(sorted(fn_kw.items())),
            cache_size=32,
        )
        with timer.phase("shard"):
            xb_d, y_d, w_d, nid_d, cand_d = mesh_lib.shard_build_inputs(
                mesh, binned, y, sample_weight
            )
        with timer.phase("leafwise_build"):
            chaos.step("leafwise_build")
            with timer.compile_attribution("leafwise_fn", lw_fresh):
                if lw_fresh:
                    timer.price_compile("leafwise_fn", lambda: fn.lower(
                        xb_d, y_d, nid_d, w_d, cand_d, mcw, mid, lam, msl,
                        msg,
                    ))
                out = fn(
                    xb_d, y_d, nid_d, w_d, cand_d, mcw, mid, lam, msl, msg
                )
            feat, bins, counts, nvec, left, parent, _depth, nid_out, nn = out
            feat, bins, counts, nvec, left, parent, nn = jax.device_get(
                (feat, bins, counts, nvec, left, parent, nn)
            )
        n_nodes = int(nn)
        timer.counter("leafwise_fused_builds")
    else:
        feat, bins, counts, nvec, left, parent, n_nodes, nid_out = (
            _build_leafwise_stepped(
                binned, y, cfg=cfg, mesh=mesh, n_classes=C, task=task,
                pool=Pn, max_nodes=M, sample_weight=sample_weight,
                exact_ties=exact_ties, gbdt_x64=gbdt_x64, use_sub=use_sub,
                mcw=mcw, mid=mid, lam=lam, msl=msl, msg=msg, timer=timer,
                snapshot_slot=snapshot_slot,
            )
        )
        timer.counter("leafwise_stepped_builds")

    with timer.phase("host_finalize"):
        tree, perm = _finalize_leafwise(
            binned, task, cfg.criterion, n_nodes, feat, bins, counts, nvec,
            left, parent, integer_counts=int_ok,
        )

    # Realized-work accounting (always-on counters; per-depth rows for the
    # fused engine, whose expansion order the finished tree cannot replay
    # — the stepped loop already emitted live per-expansion rows).
    rows, coll, counters = obs_acct.leafwise_scan_rows(
        tree, n_features=F, n_bins=B, n_channels=C, task=task,
        subtraction=use_sub, gbdt_x64=gbdt_x64,
    )
    for name, v in counters.items():
        timer.counter(name, v)
    for site, v in coll.items():
        timer.collective(site, calls=v["calls"], nbytes=v["bytes"])
    if engine == "fused":
        for r in rows:
            timer.level(**r)
    if timer.wants_fingerprints:
        # Build-state fingerprints (ISSUE 13), replayed from the
        # BFS-renumbered tree — at the level-wise node budget these rows
        # are bit-identical to the level-wise engines' (the pin, now
        # observable).
        timer.fingerprint_tree(obs_acct.replay_fingerprints(tree))

    from mpitree_tpu.core.builder import fetch_row_nodes

    nid_host = None
    if task == "regression" and refit_targets is not None:
        from mpitree_tpu.core.builder import refit_regression_values

        nid_host = perm[fetch_row_nodes(nid_out, N)]
        w64 = (np.ones(N) if sample_weight is None
               else sample_weight).astype(np.float64)
        refit_regression_values(tree, nid_host, w64, refit_targets)

    if return_leaf_ids:
        if nid_host is None:
            nid_host = perm[fetch_row_nodes(nid_out, N)]
        return tree, nid_host
    return tree


# graftlint: host-fn — the stepped engine's host loop: per-expansion
# device_get of packed pair decisions is its deliberate job
def _build_leafwise_stepped(binned, y, *, cfg, mesh, n_classes, task, pool,
                            max_nodes, sample_weight, exact_ties, gbdt_x64,
                            use_sub, mcw, mid, lam, msl, msg, timer,
                            snapshot_slot=None):
    """Host-orchestrated best-first loop: one expand dispatch per step.

    Returns raw expansion-ordered buffers (the shared finalizer
    renumbers). Pool bookkeeping lives on host; under subtraction each
    open leaf's reduced pair histogram stays DEVICE-resident (a slice of
    the expansion output that created it) and is fed back as the parent
    operand when the leaf is expanded.

    With ``snapshot_slot`` active (resolve_level_retry), the loop carry
    is snapshotted at every per-expansion host boundary — reference
    grabs only: the pre-dispatch in-place writes (feat/left/parent/depth
    of the expanding pair) are deterministic re-writes of the restored
    carry, and pool mutations happen only after the expansion's
    device_get succeeded — so a transient blip resumes at the failed
    expansion instead of restarting the build.
    """
    B = binned.n_bins
    F = binned.n_features
    expand_kw = dict(
        n_bins=B, n_classes=n_classes, task=task, criterion=cfg.criterion,
        exact_ties=exact_ties, gbdt_x64=gbdt_x64, subtraction=use_sub,
    )
    expand = collective.make_expand_fn(mesh, **expand_kw)
    expand_fresh = timer.compile_note(
        "expand_fn", (mesh,) + tuple(sorted(expand_kw.items()))
    )
    lr_on = (
        snapshot_slot is not None
        and recovery_lib.resolve_level_retry(cfg.level_retry)
    )
    resume_state = snapshot_slot.take("expansion") if lr_on else None
    if resume_state is None:
        with timer.phase("shard"):
            xb_d, y_d, w_d, nid_d, cand_d = mesh_lib.shard_build_inputs(
                mesh, binned, y, sample_weight
            )

        M = max_nodes
        feat = np.full(M, -1, np.int32)
        bins = np.zeros(M, np.int32)
        counts = np.zeros((M, n_classes), np.float32)
        nvec = np.zeros(M, np.float32)
        left = np.full(M, -1, np.int32)
        parent = np.full(M, -1, np.int32)
        depth = np.zeros(M, np.int32)

        pool_gain = np.full(pool, -np.inf, np.float32)
        pool_node = np.zeros(pool, np.int32)
        pool_feat = np.zeros(pool, np.int32)
        pool_bin = np.zeros(pool, np.int32)
        pool_nl = np.zeros(pool, np.float32)
        # Per-slot (pair_hist device array, 0|1) refs — subtraction only.
        pool_hist: list = [None] * pool
    else:
        xb_d, y_d, w_d, cand_d = resume_state["inputs"]
        nid_d = resume_state["nid"]
        (feat, bins, counts, nvec, left, parent, depth) = (
            resume_state["bufs"]
        )
        (pool_gain, pool_node, pool_feat, pool_bin, pool_nl,
         pool_hist) = resume_state["pool"]
        n_nodes, n_leaves = resume_state["n"]

    if use_sub and gbdt_x64:
        # f32 zeros converted INSIDE the scope — a direct f64 zeros
        # canonicalizes to f32 on legacy wheels (_channel_histogram).
        with jax.enable_x64(True):
            zeros_ph = jnp.zeros(
                (1, F, n_classes, B), jnp.float32
            ).astype(jnp.float64)
    elif use_sub:
        zeros_ph = jnp.zeros((1, F, n_classes, B), jnp.float32)

    def dispatch(e_node, f, b, l_id, small_left, phist):
        sub_ops = (phist,) if use_sub else ()
        return expand(
            xb_d, y_d, nid_d, w_d, cand_d, np.int32(e_node), np.int32(f),
            np.int32(b), np.int32(l_id), bool(small_left), mcw, lam, msl,
            *sub_ops,
        )

    if resume_state is None:
        # Root bootstrap: sentinel -2 reroutes nothing (live rows are
        # >= 0, padding is -1), left_id 0 puts the whole dataset in pair
        # slot 0.
        with timer.compile_attribution("expand_fn", expand_fresh):
            if expand_fresh:
                timer.price_compile("expand_fn", lambda: expand.lower(
                    xb_d, y_d, nid_d, w_d, cand_d, np.int32(-2),
                    np.int32(0), np.int32(0), np.int32(0), True, mcw, lam,
                    msl, *((zeros_ph,) if use_sub else ()),
                ))
            res = dispatch(
                -2, 0, 0, 0, True, zeros_ph if use_sub else None
            )
        nid_d = res[0]
        dec = collective.unpack_decision(
            np.asarray(jax.device_get(res[1]))
        )
        n0, _, gain0 = _stop_and_gain_np(dec, 0, task=task, cfg=cfg)
        counts[0] = dec["counts"][0]
        nvec[0] = n0[0]
        pool_gain[0] = gain0[0]
        pool_feat[0] = dec["feature"][0]
        pool_bin[0] = dec["bin"][0]
        pool_nl[0] = dec["n_left"][0]
        if use_sub:
            pool_hist[0] = (res[2], 0)

        n_nodes, n_leaves = 1, 1
    while n_leaves < pool and pool_gain.max() > -np.inf:
        if lr_on:
            snapshot_slot.save("expansion", n_leaves, dict(
                inputs=(xb_d, y_d, w_d, cand_d), nid=nid_d,
                bufs=(feat, bins, counts, nvec, left, parent, depth),
                pool=(pool_gain, pool_node, pool_feat, pool_bin,
                      pool_nl, pool_hist),
                n=(n_nodes, n_leaves),
            ))
        timer.counter("expansion_dispatches")
        # Chaos seam (resilience.chaos): deterministic kill/blip at an
        # exact expansion (``level`` reports the 1-based expansion
        # ordinal for Fault(at_level=...) arms); free (one global read)
        # with no plan installed.
        chaos.step("expansion", level=n_leaves)
        t_exp = time.perf_counter() if timer.enabled else 0.0
        p = imp_ops.best_leaf_slot_np(pool_gain, pool_node)
        enode = int(pool_node[p])
        f, b = int(pool_feat[p]), int(pool_bin[p])
        l_id = n_nodes
        feat[enode] = f
        bins[enode] = b
        left[enode] = l_id
        parent[l_id] = parent[l_id + 1] = enode
        d_child = int(depth[enode]) + 1
        depth[l_id] = depth[l_id + 1] = d_child
        small_left = bool(pool_nl[p] * np.float32(2.0) <= nvec[enode])
        phist = None
        if use_sub:
            keep, idx = pool_hist[p]
            if gbdt_x64:
                # Scoped slice: an outside-scope op on the f64 pair
                # histogram would round the operand aval to f32.
                with jax.enable_x64(True):
                    phist = keep[idx:idx + 1]
            else:
                phist = keep[idx:idx + 1]
        res = dispatch(enode, f, b, l_id, small_left, phist)
        nid_d = res[0]
        dec = collective.unpack_decision(
            np.asarray(jax.device_get(res[1]))
        )
        n2, stop2, gain2 = _stop_and_gain_np(
            dec, d_child, task=task, cfg=cfg
        )
        counts[l_id:l_id + 2] = dec["counts"]
        nvec[l_id:l_id + 2] = n2
        q = n_leaves
        pool_gain[p], pool_gain[q] = gain2[0], gain2[1]
        pool_node[p], pool_node[q] = l_id, l_id + 1
        pool_feat[p], pool_feat[q] = dec["feature"][0], dec["feature"][1]
        pool_bin[p], pool_bin[q] = dec["bin"][0], dec["bin"][1]
        pool_nl[p], pool_nl[q] = dec["n_left"][0], dec["n_left"][1]
        if use_sub:
            pool_hist[p] = (res[2], 0)
            pool_hist[q] = (res[2], 1)
        small_n = float(n2[0] if small_left else n2[1])
        timer.level(
            level=d_child, frontier=2, splits=int((~stop2).sum()),
            hist_bytes=collective.split_psum_bytes(
                n_slots=1 if use_sub else 2, n_features=F, n_bins=B,
                n_channels=n_classes, itemsize=8 if gbdt_x64 else 4,
            ),
            psum_bytes=None,
            rows_scanned=small_n if use_sub else float(n2.sum()),
            small_child_fraction=None,
            seconds=(
                round(time.perf_counter() - t_exp, 6)
                if timer.enabled else None
            ),
            new_lowerings=0,
        )
        n_nodes += 2
        n_leaves += 1

    if lr_on:
        # Loop complete: drop the snapshot (it holds device buffers) so
        # a later failure restarts clean instead of resuming into a
        # finished build.
        snapshot_slot.clear()
    return feat, bins, counts, nvec, left, parent, n_nodes, nid_d
