"""Breadth-first, level-synchronous tree construction.

This is the TPU-first re-architecture of the reference's recursive
depth-first builder (reference: ``mpitree/tree/decision_tree.py:93-166`` and
its MPI variant ``:364-479``): instead of recursing per node with partition
copies and communicator splits, each *level* of the tree is grown with a
constant number of fused device programs:

1. for every frontier chunk, one SPMD step computes the sharded
   (node, feature, bin) histogram, psums it over ICI, and evaluates the best
   split per node (``parallel/collective.py``);
2. the host applies the reference's stopping rules to the O(frontier) decision
   vectors and appends node records (struct-of-arrays, contiguous ids per
   level — which is what makes slot arithmetic work);
3. one more SPMD step advances the on-device ``node_id`` row assignments.

Useful parallelism is no longer capped at ``min(size, 2^depth)`` subtree tasks
(reference ``:446-466``): the whole frontier is one batch dimension, and every
level's split search is data-parallel over all rows on all devices.

Frontier chunking bounds histogram HBM: chunks of ``K`` nodes cost
``K*F*B*C*4`` bytes; ``K`` is chosen from a memory budget and rounded to a
power of two so the same compiled executable serves every level.
"""

from __future__ import annotations

import dataclasses
import math
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from mpitree_tpu.config import knobs
from mpitree_tpu.core.tree_struct import TreeArrays
from mpitree_tpu.obs import accounting as obs_acct, warn_event
from mpitree_tpu.obs import fingerprint as fingerprint_lib
from mpitree_tpu.obs import memory as memory_lib
from mpitree_tpu.ops.binning import BinnedData, StreamedBinnedData
from mpitree_tpu.parallel import collective, mesh as mesh_lib
from mpitree_tpu.resilience import chaos, recovery as recovery_lib
from mpitree_tpu.utils import importances as imp_utils
from mpitree_tpu.utils.profiling import PhaseTimer, debug_checks_enabled


@dataclasses.dataclass(frozen=True)
class BuildConfig:
    # "classification" | "regression" | "gbdt" (one Newton boosting round:
    # y carries per-row gradients, sample_weight per-row hessians).
    task: str = "classification"
    criterion: str = "entropy"  # entropy | gini (classification), mse (regression)
    max_depth: int | None = None
    # Leaf-wise (best-first) growth budget: when set, the tree grows by
    # repeatedly expanding the highest-gain open leaf (LightGBM's
    # ``num_leaves`` / sklearn's best-first ``max_leaf_nodes``) instead of
    # level-synchronously, stopping at this many leaves —
    # ``core/leafwise_builder.py``; ``None`` = level-wise growth. With the
    # budget at the level-wise node bound (``2^max_depth``) the finished
    # tree is bit-identical to the level-wise engines (stopping rules are
    # node-local and order-independent; node ids are BFS-renumbered).
    max_leaf_nodes: int | None = None
    min_samples_split: int = 2
    # gbdt only: L2 leaf regularization (XGBoost's lambda), the minimum
    # Newton gain a split must clear, and the minimum subsampled row count
    # per child (min_child_weight below is the per-child HESSIAN floor for
    # gbdt — the hessian is the weight of the second-order fit).
    reg_lambda: float = 0.0
    min_split_gain: float = 0.0
    min_leaf_rows: float = 0.0
    # Absolute weight floor for each side of a split (the estimator computes
    # it as min_weight_fraction_leaf * total fit weight, sklearn semantics);
    # 0.0 = unconstrained.
    min_child_weight: float = 0.0
    # sklearn's min_impurity_decrease, pre-scaled by the TOTAL fit weight
    # (decrease_global = (n_t / W) * (imp_t - cost_t) >= threshold becomes
    # n_t * (imp_t - cost_t) >= threshold * W = this field). Pre-scaling
    # makes the rule exact inside hybrid-refine subtree rebuilds, whose
    # local n_t are already global weights. 0.0 = unconstrained.
    min_decrease_scaled: float = 0.0
    hist_budget_bytes: int = 4 << 30  # HBM budget for one histogram chunk
    max_frontier_chunk: int = 4096
    max_table_slots: int = 1 << 17  # width of per-level update/counts tables
    # Relative tolerance for declaring a regression node pure. Kept below the
    # f32 moment-cancellation noise floor on purpose: a node whose true
    # variance is zero but whose computed variance is noise keeps splitting
    # and terminates via the singleton/constant rules instead, which preserves
    # exact memorization; classification purity is exact from counts.
    var_rel_tol: float = 1e-9
    # Runtime determinism check: assert on-device that every mesh device
    # selected the identical split (SURVEY.md §5). Also forced on by
    # MPITREE_TPU_DEBUG=1.
    debug: bool = False
    # Device build engine: "fused" = whole build in one compiled
    # lax.while_loop program (fused_builder.py — no per-level host round
    # trips); "levelwise" = host-orchestrated level loop (keeps per-phase
    # timers and the on-device determinism check). "auto" picks fused
    # (measured faster at every scale on tunneled transport — see
    # build_tree's engine resolution) unless debug needs the levelwise
    # instrumentation. MPITREE_TPU_ENGINE overrides.
    engine: str = "auto"
    # Histogram kernel for frontier-tier levels in BOTH device engines:
    # "auto" = the Mosaic one-hot-matmul kernel (ops/pallas_hist.py) where
    # it is bit-identical to the scatter (TPU + classification + integer
    # weights), the segment_sum scatter otherwise; "xla" = the scatter
    # everywhere; "pallas" = the Mosaic kernel for ALL payloads (raises off
    # TPU) — an explicit opt-out of kernel-exactness for regression moments
    # and fractional weights (see resolve_hist_kernel).
    # MPITREE_TPU_HIST_KERNEL overrides "auto".
    hist_kernel: str = "auto"
    # Sibling-subtraction histogram frontier (LightGBM's halved-histogram
    # trick) in BOTH device engines: at each level the globally-reduced
    # parent histograms stay resident on device (one buffer per frontier
    # chunk, kept while the total fits ``hist_budget_bytes`` — so the
    # carry at most doubles peak histogram HBM; over budget the next
    # level falls back to direct accumulation with a typed
    # ``sub_carry_over_budget`` event), only the SMALLER child of each
    # sibling pair accumulates
    # rows — into a compact half-width buffer, so the per-level histogram
    # psum payload also halves — and the larger child is reconstructed as
    # ``parent - small_sibling`` after the reduction (exact under the
    # linearity of the allreduce; ops/histogram.sibling_reconstruct).
    # "auto" enables it only where the subtraction is exact (the tree
    # stays toggle-invariant: classification with integer weights —
    # integer f32 counts < 2**24 subtract exactly) AND the platform wins
    # from it (TPU: masked accumulation cannot skip rows under static
    # shapes, so the payoff is the halved psum payload + halved MXU-tier
    # FLOPs; on XLA-CPU the scatter dominates and the remap/reconstruct
    # overhead nets ~0.92x — same policy shape as resolve_wide_hist).
    # "on" forces it anywhere: exact for integer-weight classification
    # and the scoped-f64 gbdt path (resolve_gbdt_x64, CPU meshes); for
    # non-integer f32 channels it is the same explicit identity opt-out
    # as hist_kernel="pallas" (reconstruction differs from direct
    # accumulation by ulps). The 2**24 f32-ceiling guard overrides even
    # "on": cancellation must never silently corrupt a large-child
    # histogram. MPITREE_TPU_HIST_SUBTRACTION overrides "auto" (see
    # resolve_hist_subtraction).
    hist_subtraction: str = "auto"
    # Sub-build retry granularity (resilience v2, ISSUE 14): "auto"/"on"
    # lets the host-stepped engines snapshot their loop carry at each
    # level/expansion boundary (row->node state, frontier ids, resident
    # parent histograms + slot maps, fingerprint fold), so a transient
    # device failure re-dispatches FROM THE LAST COMPLETED boundary
    # instead of restarting the fit (retry ladder rung 1,
    # resilience/retry.py). Snapshots are reference captures — no copies
    # beyond the fingerprint row list — and recovery is pinned
    # bit-identical to an uninterrupted fit via the PR-13 fingerprint
    # channels. "off" disables capture (every transient failure restarts
    # the whole dispatch, the PR-6 behavior). The fused single-program
    # engines have no host boundary and simply never snapshot.
    # MPITREE_TPU_LEVEL_RETRY overrides "auto" (resolve_level_retry).
    level_retry: str = "auto"
    # Frontier-width tiers served by dedicated branches (lax.cond chain in
    # the fused loop): a level whose frontier fits tier S computes an S-slot
    # histogram + gain sweep instead of the full K-slot one. Shallow levels
    # otherwise pay the K=4096-slot sweep for a handful of live nodes. The
    # smallest eligible tier also hosts the Pallas kernel (VMEM permitting).
    # 128 serves frontiers of 65..128 nodes — a depth-7 level's worst case:
    # the feature-gridded Pallas layout reaches S=128 for classification
    # payloads, so a refine_depth=8 crown's last level rides the MXU
    # instead of the 512-slot scatter. 512 stays the scatter tier that
    # bounds the gain-sweep width below the K=4096 chunk.
    frontier_tiers: tuple = (8, 64, 128, 512)
    # Evidence-driven auto policies (obs/advisor.py, ISSUE 18): "auto"
    # lets an auto-mode resolver consult the flight store's recorded A/B
    # history and pick the measured winner (noise-gated; static policy on
    # thin or inconclusive history); "off" pins every resolution to the
    # static heuristics. Ambient twin: MPITREE_TPU_POLICY_EVIDENCE.
    policy_evidence: str = "auto"


# Below this many matrix cells, per-level device dispatch latency dominates
# the arithmetic and the numpy fast path (host_builder.py) wins outright.
HOST_PATH_MAX_CELLS = 1 << 19


def prefer_host_path(n_samples: int, n_features: int, n_devices, backend) -> bool:
    """Route small single-device fits to the vectorized host builder.

    ``backend="host"`` forces it; any explicit device backend ("tpu", "cpu")
    or a multi-device mesh forces the device path.
    """
    if backend == "host":
        return True
    if backend is not None:
        return False
    if n_devices not in (None, 1):
        return False
    return n_samples * max(n_features, 1) <= HOST_PATH_MAX_CELLS


def _chunk_size(n_samples: int, n_feat: int, n_bins: int, n_chan: int,
                cfg: BuildConfig) -> int:
    """Frontier-chunk slot count, fixed for the whole build.

    One size covers every non-tier level, so a build compiles one K-slot
    (split, update) executable pair plus at most the Pallas-eligible tier
    sizes it actually hits — TPU compiles cost tens of seconds through the
    remote tunnel, so tier counts are kept deliberately small. Bounded by
    the histogram HBM budget, the widest possible frontier (2^max_depth, or
    n_samples when unbounded), and a hard cap.
    """
    # Live peak per slot: the (K,F,C,B) histogram (C padded to 8 sublanes by
    # TPU tiling) plus ~8 (K,F,B) f32 accumulators (impurity.py's memory-lean
    # gain formulation keeps per-class cumsums transient). The formula
    # lives in obs.memory (ISSUE 12: ONE pricing source — the capacity
    # planner and this chunk sizing can never disagree).
    per_node = memory_lib.chunk_bytes_per_slot(n_feat, n_bins, n_chan)
    cap = max(1, cfg.hist_budget_bytes // max(per_node, 1))
    cap = min(cap, cfg.max_frontier_chunk)
    widest = _widest_frontier(n_samples, cfg)
    want = 1 << max(0, math.ceil(math.log2(max(widest, 1))))
    return min(want, 1 << int(math.log2(cap)))


def _widest_frontier(n_samples: int, cfg: BuildConfig) -> int:
    widest = n_samples
    if cfg.max_depth is not None and cfg.max_depth < 31:
        widest = min(widest, 2 ** cfg.max_depth)
    return max(widest, 1)


def _table_slots(n_samples: int, cfg: BuildConfig) -> int:
    """Per-level table width for node-assignment updates and terminal counts.

    Tables are O(slots) ints — cheap — so one wide table lets a whole level's
    update run as a single full-row pass instead of one pass per histogram
    chunk. Capped so pathological frontiers chunk rather than explode."""
    widest = min(_widest_frontier(n_samples, cfg), cfg.max_table_slots)
    return 1 << max(0, math.ceil(math.log2(widest)))


def valid_tiers(tiers, n_slots: int) -> tuple:
    """Normalize frontier tiers: positive, at most the chunk width, sorted.

    ``s == n_slots`` stays eligible: on small builds the chunk width K can
    equal the smallest tier, and dropping it would silently disable an
    explicitly requested Pallas kernel."""
    return tuple(sorted(s for s in set(tiers) if 0 < s <= n_slots))


def resolve_hist_kernel(cfg: BuildConfig, platform: str, task: str, *,
                        integer_ok: bool) -> bool:
    """Shared hist_kernel resolution for every device build path.

    Exactness policy: under ``"auto"`` the Pallas kernel is used only where
    it is bit-identical to the XLA scatter — classification with
    integer-valued sample weights (integer f32 counts below 2**24 sum
    exactly in any order). Regression moments and fractional weights are
    non-integer f32, where the MXU matmul's reduction order differs from
    the scatter's, so those run Pallas only on an explicit
    ``hist_kernel="pallas"`` opt-out of the one-tree-regardless-of-kernel
    identity contract: split *selection* may differ in FP ties; regression
    leaf values are still exact (the f64 host refit,
    :func:`refit_regression_values`), while classification leaf counts
    under fractional weights come straight from the device f32 histogram
    and can carry reduction-order noise. Returns whether to use the
    Pallas kernel; raises on an invalid or unsatisfiable request.
    """
    from mpitree_tpu.ops import pallas_hist

    hist_kernel = cfg.hist_kernel
    if hist_kernel == "auto":
        hist_kernel = knobs.value("MPITREE_TPU_HIST_KERNEL")
    if hist_kernel not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown hist_kernel {hist_kernel!r}")
    if hist_kernel == "xla":
        return False
    exact = task == "classification" and integer_ok
    if hist_kernel == "pallas":
        if not pallas_hist.pallas_available(platform):
            raise ValueError(
                "hist_kernel='pallas' needs a TPU backend "
                f"(platform={platform!r})"
            )
        return True
    return pallas_hist.pallas_available(platform) and exact


def resolve_wide_hist(cfg: BuildConfig, platform: str, task: str, *,
                      integer_ok: bool, sample_weight=None) -> tuple:
    """(use_wide, bf16_ok) for the sorted window-packed deep-level tier.

    Same exactness policy as :func:`resolve_hist_kernel`: under "auto" the
    wide matmul histogram (``ops/wide_hist.py``) replaces the scatter only
    where it is bit-identical to it — classification with integer weights —
    and only on a real TPU: the tier exists to dodge the TPU scalar-unit
    scatter; on XLA-CPU the scatter is fast and the dense one-hot
    contraction loses (measured 0.2x at the covtype chunk shape). It
    additionally runs the matmul inputs in bfloat16 (2x MXU rate) when
    every payload value is an integer <= 256 (exactly representable in
    bf16's 8-bit mantissa) — unit and bootstrap weights always qualify.
    ``MPITREE_TPU_WIDE_HIST``: "0" disables everywhere, "1" forces it on
    any platform for ALL payloads (for non-integer ones that is the same
    explicit identity opt-out as hist_kernel="pallas": f32 accumulation
    whose summation order differs from the scatter's) — the CPU identity
    tests and the multichip dryrun ride the force flag.
    """
    flag = knobs.value("MPITREE_TPU_WIDE_HIST")
    if flag == "0":
        return False, False
    exact = task == "classification" and integer_ok
    if flag != "1" and not (exact and platform in ("tpu", "axon")):
        return False, False
    bf16 = bool(
        exact
        and (sample_weight is None
             or float(np.max(sample_weight, initial=0.0)) <= 256.0)
    )
    return True, bf16


def resolve_wide_pallas(platform: str, *, use_wide: bool,
                        n_channels: int, n_bins: int) -> bool:
    """Whether the wide tier uses the Mosaic grouped-matmul executor
    (``wide_hist.histogram_wide_pallas``) instead of the XLA scan — the
    ONE routing point for both engines.

    Both executors are bit-identical (same pack, same contraction); they
    differ in accumulation traffic — the Mosaic kernel keeps each window
    block in VMEM across its tile run, the scan pays a read-modify-write
    per tile. Default stays the scan until the hist_tput capture proves
    the kernel on hardware; ``MPITREE_TPU_WIDE_KERNEL=pallas|scan``
    overrides. A forced ``pallas`` fails LOUDLY when the backend or the
    VMEM fit (``wide_hist.pallas_fits``) can't satisfy it — a silent
    downgrade would attribute scan timings to the kernel.
    """
    from mpitree_tpu.ops import wide_hist

    flag = knobs.value("MPITREE_TPU_WIDE_KERNEL")
    if flag == "pallas":
        if not use_wide:
            raise ValueError(
                "MPITREE_TPU_WIDE_KERNEL=pallas: the wide tier is not "
                "active for this build (resolve_wide_hist policy — e.g. "
                "regression or fractional weights without "
                "MPITREE_TPU_WIDE_HIST=1); enable the tier or drop the "
                "kernel force"
            )
        if not wide_hist.wide_pallas_available(platform):
            raise ValueError(
                "MPITREE_TPU_WIDE_KERNEL=pallas needs a TPU backend "
                f"(platform={platform!r})"
            )
        if not wide_hist.pallas_fits(n_channels, n_bins):
            raise ValueError(
                "MPITREE_TPU_WIDE_KERNEL=pallas: working set exceeds "
                f"VMEM at C={n_channels} B={n_bins} "
                "(wide_hist.pallas_fits)"
            )
        return True
    if flag not in ("scan", "auto"):
        raise ValueError(f"unknown MPITREE_TPU_WIDE_KERNEL {flag!r}")
    return False


def resolve_exact_ties(platform: str) -> bool:
    """Whether device classification sweeps rank costs in f64 (seam closure).

    The known device/host seam: split costs are mathematically tied (or
    1e-12-close) at small deep nodes, the host's f64 resolves them one way
    and the device's f32 noise the other (first seen at a 13-row depth-9
    node). On CPU backends the device engines now run the cost sweep in
    scoped-x64 f64 mirroring the host formulation (`ops/impurity.py:
    _cost_sweep_f64`): cost gaps the host's f64 resolves now resolve
    identically on-device — full-depth identity holds on the r4 seam
    workload to depth 20 — for every chunk width within
    ``exact_ties_fits``'s memory bound (wider chunks keep the f32 sweep
    and ``warn_exact_ties_gap`` says so at build time). NOT closed: exact
    rational-coincidence ties, where XLA CPU's fused codegen (excess
    precision / reassociation, see _cost_sweep_f64) computes ulps apart
    what numpy computes equal — those picks can still flip, bounded by
    test_exact_tie_residual_is_bounded. TPUs have no
    f64 unit, so accelerator builds keep the f32 sweep — there the
    production hybrid masks the seam (crowns stop while nodes are large;
    the exact host tail owns deep small nodes). MPITREE_TPU_EXACT_TIES=0
    opts out (perf escape hatch for CPU-mesh experiments).
    """
    if knobs.value("MPITREE_TPU_EXACT_TIES") == "0":
        return False
    from mpitree_tpu import _compat

    if _compat.LEGACY_JAX:
        # Pre-shard_map wheels mislower the sweep's scoped-f64 weak
        # constants (see _compat.LEGACY_JAX); ties rank in f32 there.
        return False
    return platform == "cpu"


def exact_ties_fits(n_slots: int, n_features: int,
                    n_bins: int) -> bool:
    """Bound the f64 sweep's working set (~8 live (K,F,B) f64 buffers —
    the per-class accumulation keeps the C axis transient). Chunk widths
    past the bound keep the f32 sweep; ``warn_exact_ties_gap`` makes that
    visible at build time."""
    return n_slots * n_features * n_bins * 64 <= (2 << 30)


def warn_exact_ties_gap(K: int, n_features: int,
                        n_bins: int, obs=None) -> None:
    """One visible warning when the f64 tie sweep is memory-gated off for
    the K-slot chunks: the device/host identity contract then only covers
    frontiers up to the widest tier that still fits — deep wide-chunk
    ties rank in f32 (the pre-closure behavior). ``obs``: an optional
    PhaseTimer/BuildObserver that also receives the typed event."""
    warn_event(
        obs, "exact_ties_gap",
        f"exact-ties f64 cost sweep disabled for {K}-slot frontier chunks "
        f"(working set ~{K * n_features * n_bins * 64 >> 20} MB exceeds "
        "the 2 GB bound); ties on frontiers wider than the largest "
        "fitting tier rank in f32 and may resolve differently from the "
        "host tier's f64",
        stacklevel=3,
    )


def resolve_hist_subtraction(cfg: BuildConfig, platform: str, task: str, *,
                             integer_ok: bool, gbdt_x64: bool = False,
                             total_weight: float | None = None,
                             obs=None, shape: dict | None = None) -> bool:
    """Shared sibling-subtraction resolution for both device engines.

    Follows the engine-resolution idiom: the env var
    ``MPITREE_TPU_HIST_SUBTRACTION`` steers the default ("auto") only; an
    explicit ``BuildConfig(hist_subtraction=...)`` choice wins.

    Where the win lives: masked accumulation cannot skip rows under XLA's
    static shapes, so the scatter tier does N*F updates regardless — the
    subtraction's gains are the HALVED per-level histogram ``psum``
    payload over ICI and the halved MXU-tier FLOPs (``pallas_hist``'s
    one-hot contraction scales with the slot count). On XLA-CPU meshes
    psum is shared-memory and the scatter dominates, so the remap +
    reconstruct overhead nets a measured ~0.92x — the same evidence shape
    that gates the wide tier (:func:`resolve_wide_hist`) — hence "auto"
    engages on accelerator platforms only; "on" forces any platform (the
    CPU engine-identity tests ride it).

    Exactness policy mirrors :func:`resolve_hist_kernel`: the subtraction
    runs under "auto" only where ``parent - small`` is bit-identical to
    direct accumulation of the large child — classification with
    integer-valued weights (integer f32 sums below 2**24 are exact in any
    order, so the difference is too). The gbdt scoped-f64 path
    (``resolve_gbdt_x64``; f64 carries 29 extra mantissa bits over the
    f32 (g, h) inputs, so the reconstruction rounds to the same f32
    histogram direct accumulation does) is exact too but CPU-only, so it
    runs subtraction on explicit "on". Regression moments and fractional
    weights are non-exact everywhere: "on" for them is the documented
    one-tree identity opt-out.

    The f32-ceiling guard overrides even "on": when a parent channel
    total can reach 2**24 in f32, the sums themselves lose integer
    exactness and subtraction could silently cancel into a corrupt
    large-child histogram — warn (typed ``f32_ceiling`` event) and fall
    back to direct accumulation. The guard is moot on the f64 gbdt path
    (53-bit mantissa). ``total_weight``: the max per-channel total the
    caller can bound (total fit weight / hessian total); ``None`` skips
    the guard (caller guarantees f64).
    """
    flag = cfg.hist_subtraction
    if flag == "auto":
        flag = knobs.value("MPITREE_TPU_HIST_SUBTRACTION")
    if flag not in ("auto", "on", "off"):
        raise ValueError(f"unknown hist_subtraction {flag!r}")
    if flag == "off":
        return False
    exact = (
        (task == "classification" and integer_ok)
        or (task == "gbdt" and gbdt_x64)
    )
    if flag == "auto":
        # Evidence consultation (obs/advisor.py, ISSUE 18): stored
        # subtraction_ab history on this platform may replace the static
        # platform preference — a measured loser turns it off even on
        # accelerators, a measured winner engages it where exactness
        # holds. Exactness and the f32-ceiling guard below are hard
        # constraints the evidence never overrides.
        from mpitree_tpu.obs import advisor

        adv = advisor.advise_hist_subtraction(
            platform=platform, shape=shape,
            policy_evidence=cfg.policy_evidence,
        )
        advisor.record_advice(obs, adv)
        verdict = adv["value"] if adv is not None else None
        if verdict == "off":
            return False
        if not (exact and (verdict == "on"
                           or platform in ("tpu", "axon"))):
            return False
    f64_path = task == "gbdt" and gbdt_x64
    if (not f64_path and total_weight is not None
            and total_weight >= 2**24):
        warn_event(
            obs, "f32_ceiling",
            "sibling-subtraction histograms disabled: a parent channel "
            "total can exceed 2**24 in float32, where sums lose integer "
            "exactness and parent-minus-sibling cancellation could "
            "silently corrupt a large-child histogram; accumulating "
            "every child directly instead",
            stacklevel=3,
        )
        return False
    return True


def resolve_gbdt_x64(platform: str) -> bool:
    """Whether gbdt (g, h) histograms accumulate in f64 (mesh invariance).

    Gradients and hessians are non-integer f32, so their scatter sums are
    reduction-order-dependent — a row shard split across D devices psums D
    partials that differ in last-ulp from the single-device sum, and an
    ulp-level cost difference can flip a first-min split pick. On CPU
    meshes the histogram accumulates in a scoped-x64 f64 and rounds the
    psum'd result to f32: f64 carries 29 extra mantissa bits over the f32
    inputs, so every partition order rounds to the same f32 histogram and
    boosted ensembles are bit-identical across mesh sizes (the same closure
    story as ``resolve_exact_ties``). TPUs have no f64 unit and keep the
    f32 scatter — there the build_tree ceiling guard below is the warning
    surface. ``MPITREE_TPU_GBDT_X64=0`` opts out (perf escape hatch; the
    ceiling-guard tests also ride it to exercise the f32 path on CPU).
    """
    if knobs.value("MPITREE_TPU_GBDT_X64") == "0":
        return False
    return platform == "cpu"


def ledger_and_preflight(*, binned, mesh, cfg: BuildConfig, task: str,
                         n_classes, sample_weight, platform: str,
                         gbdt_x64: bool, timer, engine: str,
                         chunk_slots: int | None = None,
                         rounds_per_dispatch: int = 1,
                         n_out: int = 1) -> dict:
    """Record the analytical memory ledger and refuse a config whose
    predicted per-device peak exceeds the HBM budget — BEFORE the first
    device dispatch (ISSUE 12).

    The subtraction resolve here is the QUIET twin of the engines' own
    later resolution (same pure function, warnings suppressed) — it only
    prices the carry; the engine's resolution still owns the recorded
    decision and any f32-ceiling event. Returns the plan dict (also
    recorded through ``timer.memory_plan``). Raises
    :class:`~mpitree_tpu.obs.memory.MemoryPlanError` on a predicted OOM
    (typed ``oom_predicted`` event attached first).
    """
    # Real extents off the dataclass (a streamed matrix is pre-padded on
    # device; its host pricing must not claim the full-matrix bytes).
    N, F = binned.n_samples, binned.n_features
    streamed = isinstance(binned, StreamedBinnedData)
    total_w = (
        float(N) if sample_weight is None else float(np.sum(sample_weight))
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        sub = resolve_hist_subtraction(
            cfg, platform, task,
            integer_ok=integer_weights(sample_weight),
            gbdt_x64=gbdt_x64, total_weight=total_w, obs=None,
            shape={"n_samples": int(N), "n_features": int(F),
                   "n_bins": int(binned.n_bins)},
        )
    plan = obs_acct.build_memory_plan(
        mesh=mesh, rows=int(N), features=int(F),
        classes=int(n_classes or 2), bins=int(binned.n_bins), task=task,
        max_depth=cfg.max_depth, max_leaf_nodes=cfg.max_leaf_nodes,
        gbdt_x64=gbdt_x64, subtraction=sub, chunk_slots=chunk_slots,
        hist_budget_bytes=cfg.hist_budget_bytes,
        max_frontier_chunk=cfg.max_frontier_chunk,
        max_table_slots=cfg.max_table_slots,
        rounds_per_dispatch=rounds_per_dispatch, n_out=n_out,
        engine=engine, streamed=streamed,
        streamed_chunk_rows=(
            getattr(binned, "chunk_rows", 0) or None if streamed else None
        ),
    )
    d = plan.to_dict()
    timer.memory_plan(d)
    memory_lib.preflight(plan, obs=timer, what=f"{engine} build")
    return d


def integer_weights(sample_weight) -> bool:
    """True when raw class counts can stay integral (the reference's
    predict_proba contract) — i.e. no fractional sample weights."""
    return sample_weight is None or np.array_equal(
        sample_weight, np.round(sample_weight)
    )


def refit_regression_values(tree: TreeArrays, nid_host: np.ndarray,
                            w64: np.ndarray, refit_targets: np.ndarray) -> None:
    """Exact f64 node-value/impurity refit from final row assignments (in place).

    The on-device f32 moment histograms drive split *selection*; leaf and
    interior means — and per-node variances for ``feature_importances_`` —
    come from this exact host pass so neither carries cancellation noise.
    Children always have larger ids than their parent, so one descending pass
    rolls leaf sums up the whole tree."""
    s = np.bincount(nid_host, weights=refit_targets * w64,
                    minlength=tree.n_nodes)
    s2 = np.bincount(nid_host, weights=refit_targets * refit_targets * w64,
                     minlength=tree.n_nodes)
    ww = np.bincount(nid_host, weights=w64, minlength=tree.n_nodes)
    for i in range(tree.n_nodes - 1, 0, -1):
        p = tree.parent[i]
        if p < 0:
            continue  # multi-root buffer (batched refine): roots end rollup
        s[p] += s[i]
        s2[p] += s2[i]
        ww[p] += ww[i]
    mean = s / np.maximum(ww, 1e-300)
    tree.value = mean.astype(np.float32)
    tree.count = mean[:, None].copy()
    tree.impurity = np.maximum(s2 / np.maximum(ww, 1e-300) - mean * mean, 0.0)


class _TreeBuffer:
    """Growable struct-of-arrays node store (host side)."""

    def __init__(self, n_value_cols: int, value_dtype, count_dtype):
        self.cap = 256
        self.n = 0
        self.feature = np.full(self.cap, -1, np.int32)
        self.threshold = np.full(self.cap, np.nan, np.float32)
        self.left = np.full(self.cap, -1, np.int32)
        self.right = np.full(self.cap, -1, np.int32)
        self.parent = np.full(self.cap, -1, np.int32)
        self.depth = np.zeros(self.cap, np.int32)
        self.value = np.zeros(self.cap, value_dtype)
        self.count = np.zeros((self.cap, n_value_cols), count_dtype)
        self.n_node_samples = np.zeros(self.cap, np.int64)
        self.impurity = np.zeros(self.cap, np.float64)

    # Grown regions must match __init__'s fills: nodes allocated there and
    # left as leaves keep the pad value — threshold's leaf contract is NaN
    # (TreeArrays docstring), and a 0 fill leaked 0.0 leaf thresholds on
    # every tree past 256 nodes (caught by the depth-boundary identity
    # test; the depth-5 fuzz trees never grew).
    _GROW_FILL = {"feature": -1, "threshold": np.nan, "left": -1,
                  "right": -1, "parent": -1}

    def ensure(self, n: int) -> None:
        if n <= self.cap:
            return
        new_cap = max(n, self.cap * 2)
        for name in ("feature", "threshold", "left", "right", "parent",
                     "depth", "value", "count", "n_node_samples", "impurity"):
            old = getattr(self, name)
            shape = (new_cap,) + old.shape[1:]
            fill = self._GROW_FILL.get(name, 0)
            new = np.full(shape, fill, old.dtype)
            new[: self.cap] = old
            setattr(self, name, new)
        self.cap = new_cap

    def alloc_children(self, parents: np.ndarray, depth: int) -> tuple[np.ndarray, np.ndarray]:
        """Append 2*len(parents) nodes (left/right interleaved); returns their ids."""
        m = len(parents)
        base = self.n
        self.ensure(base + 2 * m)
        lefts = base + 2 * np.arange(m, dtype=np.int32)
        rights = lefts + 1
        self.parent[lefts] = parents
        self.parent[rights] = parents
        self.depth[base: base + 2 * m] = depth
        self.n = base + 2 * m
        return lefts, rights

    def finalize(self) -> TreeArrays:
        s = slice(0, self.n)
        return TreeArrays(
            feature=self.feature[s].copy(),
            threshold=self.threshold[s].copy(),
            left=self.left[s].copy(),
            right=self.right[s].copy(),
            parent=self.parent[s].copy(),
            depth=self.depth[s].copy(),
            value=self.value[s].copy(),
            count=self.count[s].copy(),
            n_node_samples=self.n_node_samples[s].copy(),
            impurity=self.impurity[s].copy(),
        )


def fetch_row_nodes(nid_d, N: int) -> np.ndarray:
    """Final on-device row->node assignments as a host array (first N rows).

    Multi-host aware: when row shards span processes a plain ``asarray`` on
    the global array is not addressable, so gather across hosts first.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(nid_d, tiled=True)
        )[:N]
    return np.asarray(nid_d)[:N]


# graftlint: host-fn — the levelwise host driver: device_get of packed
# decisions and per-level Python orchestration are its deliberate job
def build_tree(
    binned: BinnedData,
    y: np.ndarray,
    *,
    config: BuildConfig,
    mesh,
    n_classes: int | None = None,
    sample_weight: np.ndarray | None = None,
    refit_targets: np.ndarray | None = None,
    timer: PhaseTimer | None = None,
    return_leaf_ids: bool = False,
    feature_sampler=None,
    mono_cst: np.ndarray | None = None,
    snapshot_slot=None,
) -> TreeArrays:
    """Grow one tree level-synchronously; returns host struct-of-arrays.

    ``snapshot_slot`` (:class:`~mpitree_tpu.resilience.recovery.
    SnapshotSlot`, optional): the sub-build retry handle shared with the
    retry ladder (ISSUE 14). When ``level_retry`` resolves on, the
    level loop saves its carry there at every per-level host boundary;
    a re-invocation with a pending snapshot fast-forwards from the last
    completed level instead of restarting (sharding included). The
    fused engine ignores it (no host boundary to snapshot).

    ``mono_cst`` ((F,) int8, optional): INTERNAL monotonicity signs
    (sklearn's convention — the estimator flips user signs for
    classification; ``utils/monotonic.py``). Candidates violating the
    ordering or the node's propagated value bounds are rejected in split
    selection; children of a constrained split receive mid-value bounds.

    ``feature_sampler`` (:class:`ops.sampling.NodeFeatureSampler`, optional):
    per-node random feature subsets, sklearn ``max_features`` semantics.
    Both engines run it — the levelwise loop threads node keys host-side,
    the fused program evaluates the identical PCG arithmetic in-jit
    (``ops/sampling.py`` jnp twins) — so trees are engine-invariant.
    Incompatible with a (data, feature) mesh.

    ``refit_targets`` (regression only): f64 target vector used to recompute
    every node's value exactly from the final row assignments — the on-device
    f32 moment histograms drive split *selection*, but leaf/interior means come
    from an exact host-side f64 pass, so predictions carry no cancellation
    noise.

    ``timer``: optional :class:`PhaseTimer` that accumulates per-phase
    wall-clock (shard / split / counts / update).

    ``return_leaf_ids``: also return the final row->leaf assignment
    (``(tree, leaf_ids)``). The build maintains it on device anyway, so this
    is free — callers (the hybrid refine) must not pay a second full-matrix
    descent, which would re-upload X over a possibly tunneled transport.
    """
    cfg = config
    timer = timer if timer is not None else PhaseTimer(enabled=False)
    if cfg.max_leaf_nodes is not None:
        if int(cfg.max_leaf_nodes) < 2:
            raise ValueError(
                f"max_leaf_nodes must be >= 2 or None, got "
                f"{cfg.max_leaf_nodes!r}"
            )
        from mpitree_tpu.core.leafwise_builder import build_tree_leafwise

        ledger_and_preflight(
            binned=binned, mesh=mesh, cfg=cfg, task=cfg.task,
            n_classes=n_classes, sample_weight=sample_weight,
            platform=mesh.devices.flat[0].platform,
            gbdt_x64=(
                cfg.task == "gbdt"
                and resolve_gbdt_x64(mesh.devices.flat[0].platform)
            ),
            timer=timer, engine="leafwise",
        )
        return build_tree_leafwise(
            binned, y, config=cfg, mesh=mesh, n_classes=n_classes,
            sample_weight=sample_weight, refit_targets=refit_targets,
            timer=timer, return_leaf_ids=return_leaf_ids,
            feature_sampler=feature_sampler, mono_cst=mono_cst,
            snapshot_slot=snapshot_slot,
        )
    debug = cfg.debug or debug_checks_enabled()
    timer.set_mesh(mesh)

    platform = mesh.devices.flat[0].platform
    if cfg.task == "classification":
        total_w = (
            float(binned.n_samples) if sample_weight is None
            else float(np.sum(sample_weight))
        )
        if total_w >= 2**24:
            warn_event(
                timer, "f32_ceiling",
                "device class counts accumulate in float32: beyond 2**24 "
                "total weight the raw-count predict_proba contract can lose "
                "integer exactness (split selection is unaffected at the "
                "node sizes where it matters)",
                stacklevel=2,
            )
    gbdt64 = cfg.task == "gbdt" and resolve_gbdt_x64(platform)

    # The env var only steers the default ("auto"); an explicit
    # BuildConfig(engine=...) choice always wins. ``engine_reason`` is the
    # attribution fit_report_ carries — every resolution branch states why.
    engine = cfg.engine
    engine_reason = None
    if engine != "auto":
        engine_reason = f"explicit BuildConfig(engine={engine!r})"
    else:
        env_engine = knobs.value("MPITREE_TPU_ENGINE")
        if env_engine != "auto":
            engine = env_engine
            engine_reason = f"MPITREE_TPU_ENGINE={env_engine}"
    if engine not in ("auto", "fused", "levelwise"):
        raise ValueError(f"unknown build engine {engine!r}")
    if cfg.task == "gbdt":
        # Newton rounds run the levelwise engine only: the boosting outer
        # loop is host-sequential anyway (each round's gradients depend on
        # the previous round's tree), so a fused whole-build program would
        # buy nothing per tree while duplicating the Newton sweep in the
        # while_loop body. 2-D (data, feature) meshes ride the same
        # engine: the per-round split program feature-shards its (g, h)
        # slabs and merges winners through collective.select_global.
        if cfg.engine == "fused":
            raise ValueError(
                "the fused engine does not implement task='gbdt'; use "
                "engine='auto' or 'levelwise'"
            )
        engine = "levelwise"
        engine_reason = (
            "task='gbdt': Newton rounds run the levelwise engine only "
            "(the boosting outer loop is host-sequential per round)"
        )
    mono = mono_cst is not None and bool(np.any(np.asarray(mono_cst) != 0))
    if not mono:
        mono_cst = None
    if mono and mesh_lib.feature_shards(mesh) > 1:
        raise ValueError(
            "monotonic_cst is not supported on a (data, feature) mesh"
        )
    sampling = feature_sampler is not None and feature_sampler.active
    if sampling and mesh_lib.feature_shards(mesh) > 1:
        # Neither engine evaluates per-node masks across feature shards
        # (the subset straddles blocks; the first-min merge would need
        # mask-aware rerouting). Both 1-D engines support sampling: the
        # levelwise loop threads keys host-side, the fused program runs
        # the jnp twin of the same arithmetic in its while_loop body.
        raise ValueError(
            "per-node feature sampling is not supported on a "
            "(data, feature) mesh"
        )
    task = cfg.task
    # Dataclass extents, not array shape: a streamed matrix is pre-padded
    # on device, and the row-state arithmetic below (weights, leaf-id
    # fetches) must see the REAL row count (padding shards identically:
    # ceil(N / dr) == rows_pad / dr).
    N, F = binned.n_samples, binned.n_features
    B = binned.n_bins
    C = n_classes if task == "classification" else 3
    # 2-D (data, feature) mesh: each device holds only its PADDED
    # feature slab, so both the chunk sizing (the histogram HBM budget
    # binds per device) and the psum-payload accounting work in slab
    # width — the per-level ICI payload becomes independent of the
    # global feature count, and a budget-bound chunk can be df times
    # wider than the feature-complete formula would allow. The winner
    # merge's cross-axis gather is accounted separately
    # (select_global_bytes).
    df = mesh_lib.feature_shards(mesh)
    f_shard = (F + ((-F) % df)) // df
    K = _chunk_size(N, f_shard, B, C, cfg)
    if engine == "auto" and not debug:
        # Evidence-driven engine choice (ISSUE 20 satellite, the PR-18
        # advisor widened): stored leafwise_ab A/Bs may route the build
        # through the best-first frontier INSTEAD of the static fused
        # pick — with the leaf budget pinned at the level-wise node
        # bound (2^max_depth) the finished tree is bit-identical, so
        # only wall-clock is at stake. Hard constraints the evidence
        # cannot override: a finite depth small enough for that budget,
        # no feature axis, no monotonic constraints, no per-node
        # sampling (the keyed-draw threading differs per engine).
        adv = None
        budget = (
            2 ** int(cfg.max_depth)
            if cfg.max_depth is not None and 1 <= int(cfg.max_depth) <= 12
            else None
        )
        if (task != "gbdt" and budget is not None and df == 1
                and mono_cst is None and not sampling):
            from mpitree_tpu.obs import advisor

            adv = advisor.advise_engine(
                platform=platform,
                shape={
                    "n_samples": int(N), "n_features": int(F),
                    "n_bins": int(B), "max_depth": int(cfg.max_depth),
                },
                policy_evidence=cfg.policy_evidence,
            )
            advisor.record_advice(timer, adv)
        if adv is not None and adv["value"] == "leafwise":
            # The best-first engine records its own engine/frontier
            # decisions; the advisor_engine decision above carries the
            # evidence that routed here.
            ledger_and_preflight(
                binned=binned, mesh=mesh, cfg=cfg, task=task,
                n_classes=n_classes, sample_weight=sample_weight,
                platform=platform, gbdt_x64=gbdt64, timer=timer,
                engine="leafwise",
            )
            from mpitree_tpu.core.leafwise_builder import (
                build_tree_leafwise,
            )

            return build_tree_leafwise(
                binned, y,
                config=dataclasses.replace(cfg, max_leaf_nodes=budget),
                mesh=mesh, n_classes=n_classes,
                sample_weight=sample_weight, refit_targets=refit_targets,
                timer=timer, return_leaf_ids=return_leaf_ids,
                feature_sampler=feature_sampler, mono_cst=mono_cst,
                snapshot_slot=snapshot_slot,
            )
        # One compiled program beats per-level dispatch on the committed
        # evidence (BENCH_TPU.jsonl r4 line 1): the fused engine built the
        # full depth-20 covtype tree in 17.5s warm (0.88s/level including
        # its deep scatter levels) while the levelwise crown paid ~1.84s of
        # tunnel dispatch PER LEVEL (split phase 12.9s over 7 levels) —
        # projecting ~38s full-depth. Round 2 had measured the opposite
        # (levelwise 18.0s vs fused 23.1s), but that predates the packed
        # per-level transfer and the MXU middle tiers, and the crossover is
        # transport-latency-dependent. MPITREE_TPU_ENGINE=levelwise (or
        # BuildConfig(engine="levelwise")) remains the escape hatch for
        # direct-attached parts where dispatch is ~free; the
        # engine_levelwise capture section re-derives the crossover when
        # the tunnel allows.
        engine = "fused"
        engine_reason = (
            "auto: one compiled program beats per-level dispatch on "
            "tunneled transport (BENCH_TPU.jsonl r4: fused 17.5s warm vs "
            "~38s projected levelwise at covtype depth 20)"
        )
    elif engine == "auto":
        engine_reason = (
            "auto + debug: the on-device determinism check runs only in "
            "the levelwise engine"
        )
    timer.decision(
        "engine", "fused" if engine == "fused" else "levelwise",
        reason=engine_reason,
        rows=int(N), features=int(F), bins=int(B), chunk_slots=int(K),
        max_depth=cfg.max_depth, task=task, debug=bool(debug),
    )
    # Memory ledger + OOM preflight (ISSUE 12): recorded for BOTH device
    # engines before their first dispatch — the fused engine gets its
    # per-phase watermarks replayed analytically (obs/accounting), the
    # levelwise engine prices the identical statics.
    ledger_and_preflight(
        binned=binned, mesh=mesh, cfg=cfg, task=task,
        n_classes=n_classes, sample_weight=sample_weight,
        platform=platform, gbdt_x64=gbdt64, timer=timer,
        engine=engine, chunk_slots=K,
    )
    if engine == "fused":
        if debug:
            warn_event(
                timer, "fused_no_determinism_check",
                "the fused engine does not run the on-device determinism "
                "check; use engine='levelwise' (or engine='auto') with "
                "debug mode",
                stacklevel=2,
            )
        from mpitree_tpu.core.fused_builder import build_tree_fused

        return build_tree_fused(
            binned, y, config=cfg, mesh=mesh, n_classes=n_classes,
            sample_weight=sample_weight, refit_targets=refit_targets,
            timer=timer, return_leaf_ids=return_leaf_ids,
            feature_sampler=feature_sampler, mono_cst=mono_cst,
        )
    # Sub-build retry (resilience v2, ISSUE 14): when a snapshot slot is
    # shared with the retry ladder and level_retry resolves on, the loop
    # below saves its carry at every per-level host boundary, and a
    # re-invocation with a pending snapshot restores it here — skipping
    # the re-shard and fast-forwarding to the last completed level.
    lr_on = (
        snapshot_slot is not None
        and recovery_lib.resolve_level_retry(cfg.level_retry)
    )
    resume_state = snapshot_slot.take("level") if lr_on else None

    if resume_state is not None:
        xb_d, y_d, w_d, cand_mask_d = resume_state["inputs"]
        nid_d = resume_state["nid"]
        # The buffer is shared with the snapshot by reference; rolling
        # tree.n back un-allocates the failed level's children — its row
        # ranges are rewritten verbatim when the level re-runs (every
        # per-level write is a deterministic function of the restored
        # carry, which is what the fingerprint-equality pins hold).
        tree = resume_state["tree"]
        tree.n = resume_state["tree_n"]
        keys = resume_state["keys"]
    else:
        with timer.phase("shard"):
            xb_d, y_d, w_d, nid_d, cand_mask_d = mesh_lib.shard_build_inputs(
                mesh, binned, y, sample_weight
            )

        tree = _TreeBuffer(
            n_value_cols=(C if task == "classification" else 1),
            value_dtype=np.int32 if task == "classification" else np.float32,
            # Raw class counts stay int64 (the reference's predict_proba
            # contract) unless fractional sample weights make them
            # non-integral.
            count_dtype=(
                np.int64
                if (task == "classification"
                    and integer_weights(sample_weight))
                else np.float64
            ),
        )
        tree.ensure(1)
        tree.n = 1  # root

        # Path-derived per-node keys (ops/sampling.py): the root hashes
        # the tree seed, children hash the parent — engine-invariant.
        keys = feature_sampler.key_store() if sampling else None

    # Per-node monotonic value bounds (utils/monotonic.py BoundsStore —
    # the one host-side propagation implementation), grown with the tree.
    if mono:
        from mpitree_tpu.utils.monotonic import BoundsStore

        mono_cst32 = np.ascontiguousarray(mono_cst, np.int32)
        bounds = (
            resume_state["bounds"] if resume_state is not None
            else BoundsStore()
        )

    U = _table_slots(N, cfg)
    int_ok = integer_weights(sample_weight)
    use_pallas = resolve_hist_kernel(
        cfg, platform, task, integer_ok=int_ok,
    )
    use_wide, wide_bf16 = resolve_wide_hist(
        cfg, platform, task, integer_ok=int_ok,
        sample_weight=sample_weight,
    )
    # Forced Pallas/wide kernels are the documented exactness opt-out
    # (resolve_hist_kernel): they accumulate in f32, so the f64 gbdt
    # closure stands down rather than silently fighting them.
    gbdt64 = gbdt64 and not (use_pallas or use_wide)
    if cfg.task == "gbdt" and not gbdt64:
        # Same f32 ceiling as class counts, restated for the (g, h)
        # channels: once the total hessian weight approaches 2**24 the f32
        # histogram sums lose ulps to accumulation order, so split picks
        # (and the min_child_weight gate) can drift run-to-run. Decided
        # HERE, after the forced-kernel downgrade above, so a CPU mesh
        # running the f32 wide/Pallas path still warns; only the live f64
        # accumulation path (resolve_gbdt_x64, scatter kernel) is exempt.
        total_h = (
            float(N) if sample_weight is None
            else float(np.sum(sample_weight))
        )
        if total_h >= 2**24:
            warn_event(
                timer, "f32_ceiling",
                "gradient/hessian histograms accumulate in float32 on this "
                "backend: beyond 2**24 total hessian weight the (g, h) "
                "sums lose precision to accumulation order, and Newton "
                "split selection can drift; shard rows wider or rescale "
                "sample_weight",
                stacklevel=2,
            )
    exact_ok = resolve_exact_ties(platform)
    if exact_ok and not exact_ties_fits(K, F, B):
        warn_exact_ties_gap(K, F, B, obs=timer)
    # Levelwise keeps only Pallas-eligible tiers: that is where the measured
    # win lives (the MXU kernel beat the scatter 3.3x at S=8), while XLA
    # tiers saved <3% warm and cost an extra ~20-40s tunnel compile each.
    from mpitree_tpu.ops import pallas_hist, wide_hist

    wide_pallas = resolve_wide_pallas(
        platform, use_wide=use_wide,
        n_channels=C, n_bins=B,
    )

    total_w_all = (
        float(N) if sample_weight is None else float(np.sum(sample_weight))
    )
    use_sub = resolve_hist_subtraction(
        cfg, platform, task, integer_ok=int_ok, gbdt_x64=gbdt64,
        total_weight=total_w_all, obs=timer,
        shape={"n_samples": int(N), "n_features": int(F),
               "n_bins": int(B)},
    )
    timer.decision(
        "hist_subtraction", "on" if use_sub else "off",
        reason=(
            "sibling-subtraction frontier: accumulate the smaller child, "
            "derive the larger as parent - small after the psum"
            if use_sub else
            "direct accumulation (resolve_hist_subtraction: config/env "
            "off, non-exact channels or non-accelerator platform under "
            "'auto', or the 2**24 f32 ceiling)"
        ),
    )

    tiers = (
        tuple(
            s for s in valid_tiers(cfg.frontier_tiers, K)
            if pallas_hist.fits_vmem(F, s, C, B)
        )
        if use_pallas else ()
    )

    def split_fn_for(frontier: int, *, sub: bool = False,
                     keep: bool = False):
        """Narrowest tier the frontier fits (Pallas), else the K-slot sweep
        (wide-width sweeps ride the sorted window-packed matmul tier).
        Returns ``(S, fn, new_lowering)`` — the compile-accounting flag is
        True when this static configuration had not been traced before
        (the cache-key registry, ``obs.CompileRegistry``). ``sub``/``keep``
        route the sibling-subtraction variant; kernel eligibility is
        evaluated at the ACCUMULATE width (S // 2 under subtraction — only
        the compact small-child buffer is scattered/matmul'd)."""
        S = next((s for s in tiers if frontier <= s), K)
        acc = S // 2 if sub else S
        kw = dict(
            n_slots=S, n_bins=B, n_classes=C, task=task,
            criterion=cfg.criterion, debug=debug,
            use_pallas=S in tiers and pallas_hist.fits_vmem(F, acc, C, B),
            exact_ties=exact_ok and exact_ties_fits(S, F, B),
            wide_pallas=wide_pallas,
            use_wide=(use_wide and S not in tiers
                      and acc >= wide_hist.MIN_SLOTS
                      and acc % wide_hist.WINDOW == 0),
            wide_bf16=wide_bf16,
            node_mask=sampling,
            random_split=sampling and feature_sampler.random_split,
            monotonic=mono,
            gbdt_x64=gbdt64,
            subtraction=sub, keep_hist=keep,
        )
        fn = collective.make_split_fn(mesh, **kw)
        new = timer.compile_note(
            "split_fn", (mesh,) + tuple(sorted(kw.items()))
        )
        return S, fn, new

    mcw32 = np.float32(cfg.min_child_weight)

    def split_args(lo, take, S_lvl):
        """Positional tail of a split_fn call for the chunk at ``lo``."""
        args = (np.int32(lo), mcw32)
        if task == "gbdt":
            args = args + (
                np.float32(cfg.reg_lambda), np.float32(cfg.min_leaf_rows),
            )
        if sampling:
            nmask = np.ones((S_lvl, F), bool)
            nmask[:take] = keys.masks(lo, lo + take)
            args = args + (nmask,)
            if feature_sampler.random_split:
                draws = np.zeros((S_lvl, F), np.uint32)
                draws[:take] = keys.draws(lo, lo + take)
                args = args + (draws,)
        if mono:
            args = args + (mono_cst32, *bounds.window(lo, take, S_lvl))
        return args

    update_fn = collective.make_update_fn(mesh, n_slots=U)
    update_fresh = timer.compile_note("update_fn", (mesh, U))
    counts_fn = collective.make_counts_fn(
        mesh, n_slots=U, n_classes=C, task=task
    )
    counts_fresh = timer.compile_note("counts_fn", (mesh, U, C, task))

    frontier_lo, frontier_size, depth = 0, 1, 0
    # Per-level build-state fingerprints (obs/fingerprint.py, ISSUE 13):
    # hashed LIVE at this loop's existing host boundary — the level's
    # decisions and child allocations are already host-resident — and
    # committed as one tree at the end. Zero device collectives; the
    # fused engines replay identical rows from the finished tree.
    fp_rows: list = [] if timer.wants_fingerprints else None
    # Sibling-subtraction carry: the previous level's globally-reduced
    # chunk histograms (device-resident) plus the host-side child ->
    # (parent slot, smaller sibling) maps derived from its decisions.
    # Multi-chunk levels keep ONE buffer PER CHUNK (the ISSUE-8
    # follow-up; previously multi-chunk levels broke the carry) as long
    # as the total kept bytes fit ``cfg.hist_budget_bytes`` — the same
    # budget that sized the live chunk, so the carry at most doubles
    # peak histogram HBM. None whenever the previous level cannot serve
    # as a subtraction parent (over budget, terminal, or subtraction
    # off).
    sub_parent = None
    carry_budget_warned = False
    hist_itemsize = 8 if gbdt64 else 4

    if resume_state is not None:
        frontier_lo, frontier_size, depth = resume_state["frontier"]
        if fp_rows is not None and resume_state["fp_rows"] is not None:
            # The committed prefix of per-level fingerprint rows: levels
            # < depth hashed exactly once; the failed level re-hashes
            # when it re-runs.
            fp_rows = list(resume_state["fp_rows"])
        sub_parent = resume_state["sub_parent"]
        carry_budget_warned = resume_state["carry_warned"]

    def _sub_ops_for_chunk(sp, base, take, S_lvl):
        """Subtraction operands for the child chunk at frontier offset
        ``base``: ``(parent_hist, slot_map, is_small)``.

        Single-chunk parents pass their resident buffer straight through
        (zero-copy, the PR-5 shape). Multi-chunk parents gather this
        chunk's pair parents into one COMPACT buffer — row ``p`` serves
        child slots ``2p``/``2p + 1``, so the slot map becomes the
        static ``j // 2`` ramp — with one device ``take`` per touched
        parent chunk (grouped, then un-permuted; ``mode="clip"`` because
        fill-mode gathers mislower inside scoped x64 on legacy wheels).
        Pads map to parent row 0 as small siblings: they accumulate
        nothing and nothing reads them back.
        """
        pslot = np.zeros(S_lvl, np.int32)
        ismall = np.ones(S_lvl, bool)
        ismall[:take] = sp["is_small"][base:base + take]
        hists = sp["hists"]
        if len(hists) == 1:
            pslot[:take] = sp["parent_slot"][base:base + take]
            return hists[0], pslot, ismall
        S_par = sp["S_par"]
        pair = np.zeros(max(S_lvl // 2, 1), np.int64)
        pair[:take // 2] = sp["parent_slot"][base:base + take:2]
        cid = pair // S_par
        order = np.argsort(cid, kind="stable")
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))

        def gather():
            parts = [
                jnp.take(
                    hists[int(c)],
                    jnp.asarray(
                        (pair[order][cid[order] == c] % S_par).astype(
                            np.int32
                        )
                    ),
                    axis=0, mode="clip",
                )
                for c in np.unique(cid)
            ]
            buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            return jnp.take(
                buf, jnp.asarray(inv.astype(np.int32)), axis=0, mode="clip"
            )

        if gbdt64:
            with jax.enable_x64(True):
                buf = gather()
        else:
            buf = gather()
        pslot[:take] = np.repeat(
            np.arange(take // 2, dtype=np.int32), 2
        )
        return buf, pslot, ismall

    while frontier_size > 0:
        if lr_on:
            # Capture the loop carry at the per-level host boundary —
            # reference grabs only (nid_d updates are functional, the
            # tree buffer rolls back via tree.n, in-place level writes
            # are deterministic re-writes); the one copy is the
            # fingerprint row list. A failure anywhere below resumes
            # HERE via the retry ladder's level_retry rung.
            snapshot_slot.save("level", depth, dict(
                inputs=(xb_d, y_d, w_d, cand_mask_d), nid=nid_d,
                tree=tree, tree_n=tree.n, keys=keys,
                bounds=(bounds if mono else None),
                fp_rows=(None if fp_rows is None else list(fp_rows)),
                sub_parent=sub_parent, carry_warned=carry_budget_warned,
                frontier=(frontier_lo, frontier_size, depth),
            ))
        # Per-level dispatch counter: what the recovery-identity tests
        # pin — a fit resumed at level k re-runs levels >= k only, so
        # this counts (levels + levels re-dispatched), not 2x levels.
        timer.counter("level_dispatches")
        # Chaos seam (resilience.chaos): lets tests kill/blip the build at
        # an exact level (Fault(at_level=depth) arms match the reported
        # level); free (one global read) with no plan installed.
        chaos.step("level", level=depth)
        terminal = cfg.max_depth is not None and depth == cfg.max_depth
        t_level = time.perf_counter() if timer.enabled else 0.0
        lvl_new = 0
        lvl_hist_b = 0
        lvl_psum_b = 0
        sub_now = keep_now = False
        ismall_lvl = None
        kept_hist = None

        # Phase A: per-node statistics. Terminal levels (every node becomes a
        # leaf) need only counts — an O(N) scatter over wide U-slot tables —
        # while interior levels run the full O(N*F) histogram + split search
        # in K-node chunks. All chunks are dispatched asynchronously before
        # any device_get: per-array round trips dominate on high-latency
        # device transports.
        if terminal:
            with timer.phase("counts"):
                with timer.compile_attribution("counts_fn", counts_fresh):
                    if counts_fresh:
                        timer.price_compile("counts_fn", lambda: (
                            counts_fn.lower(
                                y_d, nid_d, w_d, np.int32(frontier_lo)
                            )
                        ))
                    futures = [
                        (min(U, frontier_lo + frontier_size - lo),
                         counts_fn(y_d, nid_d, w_d, np.int32(lo)))
                        for lo in range(
                            frontier_lo, frontier_lo + frontier_size, U
                        )
                    ]
                counts_fresh = False
                counts_all = np.concatenate(
                    [jax.device_get(h)[:take] for take, h in futures]
                )
            lvl_psum_b = len(futures) * collective.counts_psum_bytes(
                n_slots=U, n_channels=C
            )
            timer.collective(
                "counts_psum", calls=len(futures), nbytes=lvl_psum_b
            )
            dec = {"counts": counts_all}
        else:
            # Subtraction runs whenever the previous level's reduced
            # chunk histograms stayed resident; keeping THIS level's is
            # budget-gated (multi-chunk levels keep one buffer per chunk
            # — see the carry comment above the loop). Width-1 chunks
            # (a floor hist_budget_bytes / max_frontier_chunk=1 drives
            # _chunk_size to K=1) cannot hold a sibling PAIR, so both
            # legs fall back to direct accumulation there.
            S_pred = next((s for s in tiers if frontier_size <= s), K)
            sub_now = use_sub and sub_parent is not None and S_pred >= 2
            n_chunks_pred = -(-frontier_size // S_pred)
            # Per-device resident cost: the kept buffers stay feature-
            # sharded slabs on a 2-D mesh (slab formula: obs.memory, the
            # one pricing source).
            keep_bytes = n_chunks_pred * memory_lib.slab_bytes(
                S_pred, f_shard, C, B, itemsize=hist_itemsize
            )
            over_budget = keep_bytes > cfg.hist_budget_bytes
            keep_now = use_sub and S_pred >= 2 and not over_budget
            if use_sub and over_budget and not carry_budget_warned:
                carry_budget_warned = True
                timer.event(
                    "sub_carry_over_budget",
                    f"depth={depth}: keeping {n_chunks_pred} chunk "
                    f"histograms ({keep_bytes >> 20} MiB) exceeds "
                    "hist_budget_bytes; next level accumulates directly",
                )
            with timer.phase("split"):
                S_lvl, split_fn, new_fn = split_fn_for(
                    frontier_size, sub=sub_now, keep=keep_now
                )
                lvl_new = int(new_fn)
                hi = frontier_lo + frontier_size
                chunks = [
                    (lo, min(S_lvl, hi - lo))
                    for lo in range(frontier_lo, hi, S_lvl)
                ]
                if sub_now:
                    ismall_lvl = sub_parent["is_small"]
                n_extra = int(keep_now) + int(debug)
                with timer.compile_attribution("split_fn", bool(new_fn)):
                    if new_fn:
                        # Compute ledger (obs/cost.py): price the fresh
                        # variant's XLA cost once per cache key — the
                        # lowering is trace-cache work the dispatch
                        # below reuses, nothing runs twice.
                        lo0, take0 = chunks[0]
                        timer.price_compile("split_fn", lambda: (
                            split_fn.lower(
                                xb_d, y_d, nid_d, w_d, cand_mask_d,
                                *split_args(lo0, take0, S_lvl),
                                *(_sub_ops_for_chunk(
                                    sub_parent, lo0 - frontier_lo, take0,
                                    S_lvl,
                                ) if sub_now else ()),
                            )
                        ))
                    futures = [
                        (take,
                         split_fn(xb_d, y_d, nid_d, w_d, cand_mask_d,
                                  *split_args(lo, take, S_lvl),
                                  *(_sub_ops_for_chunk(
                                      sub_parent, lo - frontier_lo, take,
                                      S_lvl,
                                  ) if sub_now else ())))
                        for lo, take in chunks
                    ]
                if keep_now:  # outputs: (packed[, hist][, repl_err])
                    kept_hist = [r[1] for _take, r in futures]
                if debug:  # repl_err is always the last output
                    errs = [float(jax.device_get(r[-1])) for _, r in futures]
                    if any(e != 0.0 for e in errs):
                        timer.event(
                            "determinism_check_failed",
                            f"split decisions diverged at depth={depth}",
                        )
                        raise RuntimeError(
                            "determinism check failed: split decisions diverged "
                            f"across mesh devices (level depth={depth}, "
                            f"errs={errs})"
                        )
                    timer.counter("determinism_checks_passed", len(errs))
                    # The probe's two scalar psums per chunk are real
                    # fabric traffic — priced so a debug run's wire
                    # ledger stays honest.
                    timer.collective(
                        "replication_check", calls=len(errs),
                        nbytes=len(errs)
                        * collective.replication_check_bytes(),
                    )
                # One packed buffer per chunk = one host transfer, not one
                # per decision field (8x fewer round trips on the tunnel).
                decs = [
                    collective.unpack_decision(
                        jax.device_get(r[0] if n_extra else r)[:take]
                    )
                    for take, r in futures
                ]
            dec = {k: np.concatenate([c[k] for c in decs]) for k in decs[0]}
            per_chunk = collective.split_psum_bytes(
                # Subtraction psums only the compact small-child buffer —
                # half the slots, half the ICI payload per level. On a
                # 2-D mesh the psum'd array is each shard's feature slab:
                # payload independent of the global feature count.
                n_slots=S_lvl // 2 if sub_now else S_lvl,
                n_features=f_shard, n_bins=B, n_channels=C,
                itemsize=8 if gbdt64 else 4,
            )
            lvl_hist_b = len(chunks) * per_chunk
            lvl_psum_b = lvl_hist_b
            timer.collective(
                "split_hist_psum", calls=len(chunks), nbytes=lvl_hist_b
            )
            if df > 1:
                # select_global's stacked (4, K) winner gather — the one
                # cross-(feature)-axis collective per chunk.
                gb = len(chunks) * collective.select_global_bytes(
                    n_slots=S_lvl
                )
                lvl_psum_b += gb
                timer.collective(
                    "feature_merge_all_gather", calls=len(chunks),
                    nbytes=gb,
                )
            if task == "regression":
                yb = len(chunks) * 2 * S_lvl * 4
                lvl_psum_b += yb
                timer.collective(
                    "y_range_pminmax", calls=len(chunks), nbytes=yb
                )

        # Phase B: stopping rules + node records (host, vectorized).
        ids = frontier_lo + np.arange(frontier_size)
        if task == "classification":
            counts = dec["counts"]  # (S, C) integer-valued f32
            n = counts.sum(axis=1)
            pure = (counts > 0).sum(axis=1) <= 1
            value = counts.argmax(axis=1).astype(np.int32)
        elif task == "gbdt":
            m = dec["counts"]  # (S, 3) = (count, G, H)
            n = m[:, 0]
            # Raw Newton leaf value; the boosting loop overwrites it with
            # the exact f64 host refit and applies shrinkage itself.
            value = (
                -m[:, 1] / np.maximum(m[:, 2] + cfg.reg_lambda, 1e-12)
            ).astype(np.float32)
        else:
            m = dec["counts"]  # (S, 3) moments
            n = m[:, 0]
            mean = m[:, 1] / np.maximum(m[:, 0], 1.0)
            value = mean.astype(np.float32)
        if terminal:
            stop = np.ones(frontier_size, bool)
        else:
            if task == "gbdt":
                # No purity concept for gradients: a node with zero best
                # gain stops through the min_split_gain gate below (or the
                # constant/inf-cost rules).
                pure = np.zeros(frontier_size, bool)
            elif task != "classification":
                pure = dec["y_range"] <= 0.0
            stop = (
                pure | dec["constant"] | (n < cfg.min_samples_split)
                | np.isinf(dec["cost"])
            )
            if cfg.min_decrease_scaled > 0.0:
                # sklearn's min_impurity_decrease on the BEST split only
                with np.errstate(invalid="ignore"):
                    stop |= (
                        n * (dec["impurity"] - dec["cost"])
                        < cfg.min_decrease_scaled
                    )
            if task == "gbdt" and cfg.min_split_gain > 0.0:
                # impurity - cost IS the Newton gain (best_split_newton's
                # sign convention); unlike min_decrease_scaled it is a raw
                # per-split threshold, not weight-scaled.
                with np.errstate(invalid="ignore"):
                    stop |= (
                        dec["impurity"] - dec["cost"] < cfg.min_split_gain
                    )

        tree.feature[ids] = (
            np.full(frontier_size, -1, np.int32) if terminal
            else np.where(stop, -1, dec["feature"]).astype(np.int32)
        )
        tree.value[ids] = value
        tree.n_node_samples[ids] = n.astype(np.int64)
        if task == "classification":
            tree.count[ids] = counts.astype(tree.count.dtype)
            tree.impurity[ids] = imp_utils.class_node_impurity(
                counts, cfg.criterion
            )
        elif task == "gbdt":
            tree.count[ids, 0] = value
            # f32-accuracy Newton structure score 1/2 G^2/(H+lambda);
            # value, count AND impurity are all overwritten exactly by the
            # boosting loop's f64 host refit (_newton_refit) — same
            # contract as the regression refit pass.
            m = dec["counts"]
            tree.impurity[ids] = (
                0.5 * m[:, 1] * m[:, 1]
                / np.maximum(m[:, 2] + cfg.reg_lambda, 1e-12)
            )
        else:
            tree.count[ids, 0] = value
            # f32-accuracy variance; overwritten exactly by the refit pass.
            tree.impurity[ids] = imp_utils.moment_node_impurity(dec["counts"])

        split_ids = ids[~stop]
        if len(split_ids):
            feat = dec["feature"][~stop].astype(np.int32)
            bins = dec["bin"][~stop].astype(np.int32)
            tree.threshold[split_ids] = binned.thresholds[feat, bins]
            lefts, rights = tree.alloc_children(split_ids.astype(np.int32), depth + 1)
            tree.left[split_ids] = lefts
            tree.right[split_ids] = rights
            if sampling:
                keys.assign_children(split_ids, lefts, rights, tree.n)
            if mono:
                bounds.assign_children(
                    split_ids, lefts, rights,
                    dec["v_left"][~stop], dec["v_right"][~stop],
                    mono_cst32[feat], tree.n,
                )

            # Phase C: advance on-device row assignments — one full-row pass
            # per U-slot table (normally one per level). Host tables ride the
            # jit dispatch (a single transfer) rather than explicit device_puts.
            is_split_full = ~stop
            lr = np.zeros(frontier_size, np.int32)
            rr = np.zeros(frontier_size, np.int32)
            lr[np.flatnonzero(is_split_full)] = lefts
            rr[np.flatnonzero(is_split_full)] = rights
            upd_calls = 0
            with timer.phase("update"):
                for lo in range(frontier_lo, frontier_lo + frontier_size, U):
                    take = min(U, frontier_lo + frontier_size - lo)
                    sl = slice(lo - frontier_lo, lo - frontier_lo + take)
                    if not is_split_full[sl].any():
                        continue
                    is_split = np.zeros(U, bool)
                    feat_t = np.zeros(U, np.int32)
                    bin_t = np.zeros(U, np.int32)
                    left_t = np.zeros(U, np.int32)
                    right_t = np.zeros(U, np.int32)
                    is_split[:take] = is_split_full[sl]
                    feat_t[:take] = np.where(is_split_full[sl], dec["feature"][sl], 0)
                    bin_t[:take] = np.where(is_split_full[sl], dec["bin"][sl], 0)
                    left_t[:take] = lr[sl]
                    right_t[:take] = rr[sl]
                    with timer.compile_attribution("update_fn", update_fresh):
                        if update_fresh:
                            timer.price_compile("update_fn", lambda: (
                                update_fn.lower(
                                    nid_d, xb_d, np.int32(lo), is_split,
                                    feat_t, bin_t, left_t, right_t,
                                )
                            ))
                        nid_d = update_fn(
                            nid_d, xb_d, np.int32(lo),
                            is_split, feat_t, bin_t, left_t, right_t,
                        )
                    update_fresh = False
                    upd_calls += 1
            if lr_on and upd_calls:
                # The update dispatch is the level's only async tail: a
                # deferred failure would otherwise surface at the NEXT
                # level's device_get and the resume would re-consume a
                # poisoned row-assignment. Blocking here attributes the
                # failure to the level that issued it — and costs only
                # the update/next-split overlap, which the data
                # dependency (next split consumes nid_d) mostly forbids
                # anyway.
                jax.block_until_ready(nid_d)
            if df > 1 and upd_calls:
                # Owner-broadcast of child ids across feature shards: the
                # update step's psum over the feature axis reduces each
                # data-shard's LOCAL row block — the ledger records the
                # per-ring payload (wire_estimate multiplies by the
                # concurrent data-group count), so divide by dr.
                nloc = -(-N // mesh_lib.data_shards(mesh))
                timer.collective(
                    "route_psum", calls=upd_calls,
                    nbytes=upd_calls * nloc * 4,
                )

        # Realized-savings accounting (always-on counters + level-row
        # fields): rows_scanned is the weight actually accumulated into
        # split histograms this level — under subtraction only the smaller
        # siblings; rows_frontier what direct accumulation would scan.
        rows_scanned = rows_frontier = small_frac = None
        if not terminal:
            rows_frontier = float(np.sum(n))
            rows_scanned = (
                float(np.sum(n[ismall_lvl[:frontier_size]]))
                if sub_now else rows_frontier
            )
            small_frac = (
                round(rows_scanned / rows_frontier, 6)
                if rows_frontier else None
            )
            timer.counter("rows_scanned", int(round(rows_scanned)))
            timer.counter("rows_frontier", int(round(rows_frontier)))

        # Carry this level's reduced histogram + child maps so the next
        # level can accumulate small siblings only. Children are allocated
        # left/right interleaved starting at the next frontier_lo, so
        # child 2r/2r+1 pair exactly (ops/histogram slot pairing).
        if keep_now and not terminal and len(split_ids):
            nl = dec["n_left"][~stop]
            left_small = nl * 2.0 <= n[~stop]  # ties go left
            ism = np.empty(2 * len(split_ids), bool)
            ism[0::2] = left_small
            ism[1::2] = ~left_small
            sub_parent = {
                "hists": kept_hist,
                "S_par": S_lvl,
                "is_small": ism,
                "parent_slot": np.repeat(
                    split_ids.astype(np.int32) - frontier_lo, 2
                ),
            }
        else:
            sub_parent = None

        timer.level(
            level=depth, frontier=frontier_size, splits=len(split_ids),
            hist_bytes=lvl_hist_b, psum_bytes=lvl_psum_b,
            rows_scanned=rows_scanned, small_child_fraction=small_frac,
            seconds=(
                round(time.perf_counter() - t_level, 6)
                if timer.enabled else None
            ),
            new_lowerings=lvl_new,
        )
        if fp_rows is not None:
            # The level's nodes are fully decided here (stats, winners,
            # child ids) — hash the same tree-buffer slices the replay
            # path re-slices from the finished tree.
            fp_rows.append(fingerprint_lib.level_fingerprint(
                depth, tree.n_node_samples[ids], tree.feature[ids],
                tree.threshold[ids], tree.left[ids], tree.right[ids],
            ))
        frontier_lo = frontier_lo + frontier_size
        frontier_size = 2 * len(split_ids)
        depth += 1

    if lr_on:
        # Build complete: drop the snapshot (it holds device buffers) so
        # any later failure restarts clean rather than resuming into a
        # finalized build.
        snapshot_slot.clear()
    out = tree.finalize()
    if fp_rows is not None:
        timer.fingerprint_tree(fp_rows)

    nid_host = None
    if task == "regression" and refit_targets is not None:
        w64 = (np.ones(N) if sample_weight is None
               else sample_weight).astype(np.float64)
        nid_host = fetch_row_nodes(nid_d, N)
        refit_regression_values(out, nid_host, w64, refit_targets)

    if return_leaf_ids:
        if nid_host is None:
            nid_host = fetch_row_nodes(nid_d, N)
        return out, nid_host
    return out
