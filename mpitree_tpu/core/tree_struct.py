"""Struct-of-arrays decision tree + optional linked-``Node`` view.

The reference stores a fitted tree as a graph of Python ``Node`` dataclasses
(reference: ``mpitree/tree/_base.py:22-101``) — unserializable-by-design and
interpreter-bound at predict time. Here the tree is six flat arrays with
JIT-static shapes: trivially saved/loaded (``.npz``), replicated to devices
once, and traversed by a vectorized gather-descent (``ops/predict.py``).

``Node``/``to_nodes()`` provide a reference-compatible object view for users
who walked ``clf.tree_`` directly (``value`` overloading per
``_base.py:50``: feature index on interior nodes, class label on leaves).
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np


@dataclasses.dataclass
class TreeArrays:
    """A fitted tree as parallel arrays indexed by node id (root = 0).

    Attributes
    ----------
    feature : (n_nodes,) int32
        Split feature per interior node; ``-1`` marks a leaf.
    threshold : (n_nodes,) float32
        Split value (``x <= threshold`` goes left); ``nan`` on leaves.
    left, right : (n_nodes,) int32
        Child ids; ``-1`` on leaves.
    parent : (n_nodes,) int32
        Parent id; ``-1`` on the root.
    depth : (n_nodes,) int32
        Edges from the root.
    value : (n_nodes,) — int32 class index (classification) or float32 mean
        (regression); defined for interior nodes too (majority/mean), matching
        the reference's interior ``count`` bookkeeping (``decision_tree.py:146``).
    count : classification (n_nodes, n_classes) int64 raw class counts
        (the reference's ``Node.count``, ``_base.py:53``); regression
        ``(n_nodes, 1)`` float64 node means.
    n_node_samples : (n_nodes,) int64
        Training rows routed through each node.
    impurity : (n_nodes,) float64
        Per-node impurity under the training criterion (entropy/gini for
        classification, variance for regression) — feeds exact
        mean-decrease-in-impurity ``feature_importances_``. Regression
        values come from an exact f64 host pass (``refit_regression_values``);
        files saved before this field existed load with zeros.
    """

    feature: np.ndarray
    threshold: np.ndarray
    left: np.ndarray
    right: np.ndarray
    parent: np.ndarray
    depth: np.ndarray
    value: np.ndarray
    count: np.ndarray
    n_node_samples: np.ndarray
    impurity: np.ndarray = None

    def __post_init__(self):
        if self.impurity is None:
            self.impurity = np.zeros(self.feature.shape[0], np.float64)

    @property
    def n_nodes(self) -> int:
        return int(self.feature.shape[0])

    @property
    def max_depth(self) -> int:
        return int(self.depth.max(initial=0))

    @property
    def n_leaves(self) -> int:
        return int((self.feature < 0).sum())

    def is_leaf(self, i: int) -> bool:
        return self.feature[i] < 0

    def save(self, path) -> None:
        np.savez(path, **dataclasses.asdict(self))

    @classmethod
    def load(cls, path) -> TreeArrays:
        with np.load(path) as z:
            return cls(**{k: z[k] for k in z.files})

    def to_nodes(self) -> Node:
        """Materialize the reference-style linked-node view (root returned)."""
        # One host materialization up front: the arrays may be
        # device-resident after a fused build, and per-node ``.item()``
        # indexing costs one D2H round trip per node (graftlint GL01).
        # ``.tolist()`` unwraps every leaf payload to Python scalars in one
        # transfer, preserving the old per-element ``.item()`` semantics.
        feature = np.asarray(self.feature)
        threshold = np.asarray(self.threshold)
        depth = np.asarray(self.depth)
        count = np.asarray(self.count)
        left = np.asarray(self.left)
        right = np.asarray(self.right)
        value = np.asarray(self.value).tolist()
        nodes = [
            Node(
                value=(int(feature[i]) if feature[i] >= 0 else value[i]),
                threshold=(float(threshold[i]) if feature[i] >= 0 else None),
                depth=int(depth[i]),
                count=count[i],
            )
            for i in range(self.n_nodes)
        ]
        for i, node in enumerate(nodes):
            if feature[i] >= 0:
                node.left = nodes[left[i]]
                node.right = nodes[right[i]]
                node.left.parent = node
                node.right.parent = node
        return nodes[0] if nodes else Node(value=0)


class BranchType(enum.Enum):
    """Rendering glyph per node (reference ``mpitree/tree/_base.py:16-19``)."""

    ROOT = "┌──"
    INTERIOR_LIKE = "├──"
    LEAF_LIKE = "└──"


@dataclasses.dataclass
class Node:
    """Reference-compatible linked tree node (view over :class:`TreeArrays`).

    Mirrors the full attribute surface of the reference ``Node``
    (``mpitree/tree/_base.py:50-75``): overloaded ``value``, optional
    ``threshold``, ``depth``, class-count vector ``count``,
    parent/left/right links, the ``_btype`` rendering state, and the
    side-effecting ``__lt__`` the reference's renderer relies on (sorting
    a node pair stamps each side's ``_btype`` and orders interior nodes
    after leaves). Code written against reference nodes — including
    ``sorted(node.children)`` idioms — behaves identically on this view.
    """

    value: object
    threshold: float | None = None
    depth: int = 0
    count: object = None
    parent: Node | None = dataclasses.field(default=None, repr=False)
    left: Node | None = dataclasses.field(default=None, repr=False)
    right: Node | None = dataclasses.field(default=None, repr=False)
    _btype: BranchType = dataclasses.field(
        default=BranchType.ROOT, repr=False
    )

    def __lt__(self, other: Node) -> bool:
        # Reference semantics verbatim (_base.py:63-75): comparing stamps
        # both sides' branch glyphs as a side effect, and returns whether
        # SELF is interior — so interior nodes compare less-than and sort
        # first (the reference's quirk, kept for parity).
        if self.is_leaf:
            other._btype = BranchType.INTERIOR_LIKE
            self._btype = BranchType.LEAF_LIKE
        else:
            self._btype = BranchType.INTERIOR_LIKE
            other._btype = BranchType.LEAF_LIKE
        return not self.is_leaf

    @property
    def is_leaf(self) -> bool:
        return self.left is None and self.right is None

    @property
    def children(self) -> list:
        return [] if self.is_leaf else [self.left, self.right]
