"""Vectorized host (numpy) builder for small inputs — latency fast path.

The device builder (``builder.py``) amortizes beautifully at covtype scale but
pays fixed per-level dispatch (and per-shape compile) costs that dwarf the
arithmetic below a few thousand rows — exactly the regime of the reference's
published benchmark sweep (reference: ``experiments.ipynb`` cell 5,
``n_samples = arange(1, 250, 10)``, where reference fits take milliseconds).
This module grows the *same* level-synchronous histogram tree with plain
numpy: same binning, same stopping rules, same first-min/first-max tie-break
semantics (reference: ``mpitree/tree/decision_tree.py:88-91,140``), same
struct-of-arrays result — so estimators can route small fits here (or callers
can force it with ``backend="host"``) and get an identical tree shape
contract, with single-digit-millisecond latency.

Float caveat: gains here are computed in float64 (like the reference's numpy
path) while the device path uses float32. On exact ties the argmin can in
principle differ between the two paths by floating-point noise; the test
suite pins identity on the standard fixtures. A second seam of the same kind:
the native C++ sweep (split_kernel.cpp) accepts a new minimum only when it
beats the incumbent by >1e-12 relative (guarding against non-associative
incremental updates), while this numpy fallback uses strict first-argmin —
two genuinely distinct costs closer than 1e-12 relative could resolve
differently depending on whether g++ was available. The cross-engine fuzz
tests (tests/test_engine_identity.py) pin this seam across many seeds.
"""

from __future__ import annotations

import time

import numpy as np

from mpitree_tpu.core.tree_struct import TreeArrays
from mpitree_tpu.utils.importances import (
    class_node_impurity,
    moment_node_impurity,
)
from mpitree_tpu.utils.profiling import PhaseTimer


def _child_impurity_class(hist, criterion: str):
    """(S,F,C,B) class histogram -> (S,F,B) weighted child cost, f64.

    Mirrors ``ops/impurity.py:best_split_classification`` (device) and the
    reference's weighted-entropy cost (``decision_tree.py:79-86``).
    """
    l = hist.cumsum(axis=3)  # noqa: E741 - left counts per class
    n_l = l.sum(axis=2)
    n_t = n_l[:, :, -1:]
    n_r = n_t - n_l
    r = l[:, :, :, -1:] - l

    def h(counts, n):
        with np.errstate(divide="ignore", invalid="ignore"):
            p = counts / np.maximum(n, 1.0)[:, :, None, :]
            if criterion == "entropy":
                t = np.where(counts > 0, p * np.log2(np.maximum(p, 1e-300)), 0.0)
                return -t.sum(axis=2)
            return np.where(n > 0, 1.0 - (p * p).sum(axis=2), 0.0)

    cost = (n_l * h(l, n_l) + n_r * h(r, n_r)) / np.maximum(n_t, 1.0)
    return cost, n_l, n_r


def _child_cost_mse(hist):
    """(S,F,3,B) moment histogram -> (S,F,B) weighted child variance.

    Computed in float32 end to end, mirroring the device kernel
    (``ops/impurity.py:best_split_regression``) op for op, so host and device
    builds select identical splits even where f32 moment cancellation makes
    near-tied costs ambiguous.
    """
    h32 = hist.astype(np.float32)
    w_l = h32[:, :, 0, :].cumsum(axis=2, dtype=np.float32)
    s_l = h32[:, :, 1, :].cumsum(axis=2, dtype=np.float32)
    q_l = h32[:, :, 2, :].cumsum(axis=2, dtype=np.float32)
    w_t, s_t, q_t = w_l[:, :, -1:], s_l[:, :, -1:], q_l[:, :, -1:]
    w_r, s_r, q_r = w_t - w_l, s_t - s_l, q_t - q_l

    def sse(w, s, q):
        return np.maximum(q - s * s / np.maximum(w, np.float32(1.0)), np.float32(0.0))

    cost = (sse(w_l, s_l, q_l) + sse(w_r, s_r, q_r)) / np.maximum(w_t, np.float32(1.0))
    return cost, w_l, w_r


def _native_splits(xb, y, nid, sample_weight, binned, cfg, *, frontier_lo,
                   n_slots, n_classes, task, node_mask=None, mono=None):
    """Call the C++ sweep (native/__init__.py); None -> use numpy fallback.

    ``node_mask`` (n_slots, F) bool routes per-node feature sampling through
    the kernel's per-slot candidate counts (masked features keep bin chains
    for the occupancy stop but can never win). ``mono``: a
    ``(cst_int32, BoundsStore)`` pair engaging the kernel's monotonic gate
    for this frontier window; the result then carries winner
    ``v_left``/``v_right`` for the child-bound propagation.
    """
    from mpitree_tpu import native

    if node_mask is None:
        n_cand, per_slot = binned.n_cand, False
    else:
        n_cand = np.where(node_mask, binned.n_cand[None, :], 0)
        per_slot = True
    mono_kw = {}
    if mono is not None:
        cst32, bounds = mono
        bounds.ensure(frontier_lo + n_slots)
        lo_w, hi_w = bounds.window(frontier_lo, n_slots, n_slots)
        mono_kw = dict(
            mono_cst=cst32.astype(np.int8), mono_lo=lo_w, mono_hi=hi_w
        )
    if task == "classification":
        return native.best_splits_classification(
            xb, y, nid, sample_weight, n_bins=binned.n_bins,
            n_classes=n_classes, frontier_lo=frontier_lo, n_slots=n_slots,
            n_cand=n_cand, n_cand_per_slot=per_slot, criterion=cfg.criterion,
            min_child_weight=cfg.min_child_weight, **mono_kw,
        )
    return native.best_splits_regression(
        xb, np.asarray(y, np.float32), nid, sample_weight,
        n_bins=binned.n_bins, frontier_lo=frontier_lo, n_slots=n_slots,
        n_cand=n_cand, n_cand_per_slot=per_slot,
        min_child_weight=cfg.min_child_weight, **mono_kw,
    )


def _native_level_decisions(nat, *, task, cfg):
    """Node stats + stopping decision from one native sweep's outputs.

    Single source of the stop-rule formula for every consumer of the C++
    kernel (the host builder and the batched hybrid refine) — the two tail
    engines must not be able to diverge on purity/constancy/min-samples
    semantics.
    """
    if task == "classification":
        counts = nat["counts"]
        n = counts.sum(axis=1)
        pure = (counts > 0).sum(axis=1) <= 1
        value = counts.argmax(axis=1).astype(np.int32)
        node_imp = class_node_impurity(counts, cfg.criterion)
    else:
        counts = None
        n = nat["counts"][:, 0]
        value = (nat["counts"][:, 1] / np.maximum(n, 1.0)).astype(np.float32)
        pure = ~(nat["ymax"] > nat["ymin"])
        node_imp = moment_node_impurity(nat["counts"])
    feat_best = nat["feature"]
    stop = (
        pure | nat["constant"] | (n < cfg.min_samples_split)
        | np.isinf(nat["cost"]) | (feat_best < 0)
    )
    if cfg.min_decrease_scaled > 0.0:
        # sklearn's min_impurity_decrease on the best split only
        with np.errstate(invalid="ignore"):
            stop |= n * (node_imp - nat["cost"]) < cfg.min_decrease_scaled
    return counts, n, value, node_imp, feat_best, nat["bin"], stop


def _leaf_stats(slot, live, y, w_dense, S, C, *, task, criterion):
    """Terminal-level node stats (counts/value/impurity) by plain bincounts."""
    if task == "classification":
        flat = (slot[live] * C + y[live]).astype(np.intp)
        counts = np.bincount(
            flat, weights=w_dense[live], minlength=S * C
        ).reshape(S, C)
        n = counts.sum(axis=1)
        value = counts.argmax(axis=1).astype(np.int32)
        node_imp = class_node_impurity(counts, criterion)
    else:
        flat = slot[live].astype(np.intp)
        wv = w_dense[live]
        counts = None
        n = np.bincount(flat, weights=wv, minlength=S)
        s1 = np.bincount(flat, weights=wv * y[live], minlength=S)
        s2 = np.bincount(
            flat, weights=wv * np.square(y[live], dtype=np.float64),
            minlength=S,
        )
        value = (s1 / np.maximum(n, 1.0)).astype(np.float32)
        node_imp = moment_node_impurity(np.stack([n, s1, s2], axis=1))
    return counts, n, value, node_imp


def _record_level(tree, ids, S, terminal, stop, feat_best, value, n, counts,
                  task, node_imp):
    tree.feature[ids] = (
        np.full(S, -1, np.int32) if terminal
        else np.where(stop, -1, feat_best).astype(np.int32)
    )
    tree.value[ids] = value
    tree.n_node_samples[ids] = n.astype(np.int64)
    tree.impurity[ids] = node_imp
    if task == "classification":
        tree.count[ids] = counts.astype(tree.count.dtype)
    else:
        tree.count[ids, 0] = value


def _split_and_advance(tree, binned, xb, nid, ids, stop, feat_best, bin_best,
                       slot, live, S, frontier_lo, depth, thr_values=None):
    """Create children for splitting nodes and reroute their rows.

    ``thr_values`` (len == number of splitting nodes) overrides the shared
    ``binned.thresholds`` lookup — used by the multi-root batched refine
    (hybrid_builder.py) where every root carries its own local thresholds.
    """
    split_ids = ids[~stop]
    if len(split_ids):
        f_sel = feat_best[~stop].astype(np.int32)
        b_sel = bin_best[~stop].astype(np.int32)
        tree.threshold[split_ids] = (
            binned.thresholds[f_sel, b_sel] if thr_values is None
            else thr_values
        )
        lefts, rights = tree.alloc_children(split_ids.astype(np.int32),
                                            depth + 1)
        tree.left[split_ids] = lefts
        tree.right[split_ids] = rights

        split_mask = np.zeros(S, bool)
        split_mask[~stop] = True
        feat_t = np.zeros(S, np.int32)
        bin_t = np.zeros(S, np.int32)
        left_t = np.zeros(S, np.int32)
        right_t = np.zeros(S, np.int32)
        feat_t[~stop] = f_sel
        bin_t[~stop] = b_sel
        left_t[~stop] = lefts
        right_t[~stop] = rights
        N = len(nid)
        s_cl = np.clip(slot, 0, S - 1)
        active = live & split_mask[s_cl]
        xf = xb[np.arange(N), feat_t[s_cl]]
        go_left = xf <= bin_t[s_cl]
        nid = np.where(
            active, np.where(go_left, left_t[s_cl], right_t[s_cl]), nid
        ).astype(np.int32)
    return nid, frontier_lo + S, 2 * len(split_ids), depth + 1


def build_tree_host(
    binned,
    y: np.ndarray,
    *,
    config,
    n_classes: int | None = None,
    sample_weight: np.ndarray | None = None,
    refit_targets: np.ndarray | None = None,
    return_leaf_ids: bool = False,
    feature_sampler=None,
    mono_cst: np.ndarray | None = None,
    timer: PhaseTimer | None = None,
) -> TreeArrays:
    """Grow one tree on the host; same contract as ``builder.build_tree``.

    ``timer``: optional PhaseTimer/BuildObserver — per-level record rows
    (level, frontier, splits, histogram bytes, wall seconds) under
    ``MPITREE_TPU_PROFILE=1``, always-on counters otherwise
    (``mpitree_tpu.obs``). No collectives: this tier is single-host numpy.

    ``feature_sampler``: per-node random feature subsets (ops/sampling.py) —
    identical node keys and masks to the device levelwise build.
    ``mono_cst``: (F,) INTERNAL monotonicity signs (utils/monotonic.py).
    Integer-weight classification runs the C++ kernel's constraint gate
    (integer counts make its f32 child values bit-identical to the numpy
    and device engines); fractional-weight classification and all
    regression stay on the numpy sweep, whose f32 arithmetic mirrors the
    device op for op where the kernel's f64 accumulation order cannot.
    """
    from mpitree_tpu.core.builder import _TreeBuffer  # shared node store

    cfg = config
    task = cfg.task
    timer = timer if timer is not None else PhaseTimer(enabled=False)
    timer.counter("host_builds")
    xb = binned.x_binned
    N, F = xb.shape
    B = binned.n_bins
    C = n_classes if task == "classification" else 3
    # Memory ledger (obs.memory, ISSUE 12): the host tier carries no
    # device arrays — its record prices the HOST side (raw + binned
    # matrix + row state), which is what out-of-core chunk sizing
    # (ROADMAP item 1) budgets against.
    from mpitree_tpu.obs import accounting as obs_acct

    timer.memory_plan(obs_acct.build_memory_plan(
        mesh_axes=1, rows=int(N), features=int(F),
        classes=int(n_classes or 2), bins=int(B), task=task,
        max_depth=cfg.max_depth, max_leaf_nodes=cfg.max_leaf_nodes,
        hist_budget_bytes=cfg.hist_budget_bytes,
        max_frontier_chunk=cfg.max_frontier_chunk,
        max_table_slots=cfg.max_table_slots, engine="host",
    ))
    cand = binned.candidate_mask()  # (F, B)
    w = np.ones(N) if sample_weight is None else sample_weight.astype(np.float64)
    if task == "regression":
        # f32 targets/payloads mirror the device moment path; split selection
        # then agrees with the device build bit for bit (see _child_cost_mse).
        y_f = y.astype(np.float32)
        w32 = w.astype(np.float32)

    fractional_w = sample_weight is not None and not np.array_equal(
        sample_weight, np.round(sample_weight)
    )
    tree = _TreeBuffer(
        n_value_cols=(C if task == "classification" else 1),
        value_dtype=np.int32 if task == "classification" else np.float32,
        count_dtype=(
            np.float64 if (task != "classification" or fractional_w) else np.int64
        ),
    )
    tree.ensure(1)
    tree.n = 1

    sampling = feature_sampler is not None and feature_sampler.active
    rand_split = sampling and feature_sampler.random_split
    keys = feature_sampler.key_store() if sampling else None

    mono = mono_cst is not None and bool(np.any(np.asarray(mono_cst) != 0))
    if mono:
        from mpitree_tpu.utils.monotonic import BoundsStore

        cst32 = np.ascontiguousarray(mono_cst, np.int32)
        bounds = BoundsStore()

    nid = np.zeros(N, np.int32)
    rows_feat = np.broadcast_to(np.arange(F, dtype=np.intp)[None, :], (N, F))
    frontier_lo, frontier_size, depth = 0, 1, 0

    def thread_keys(ids, stop):
        """Hand child nodes their path-derived sampling keys."""
        split_ids = ids[~stop]
        if not sampling or not len(split_ids):
            return
        keys.assign_children(
            split_ids, tree.left[split_ids], tree.right[split_ids], tree.n
        )

    def note_level(d, S, splits, hist_nbytes, t0):
        timer.level(
            level=d, frontier=int(S), splits=int(splits),
            hist_bytes=int(hist_nbytes), psum_bytes=0,
            seconds=(
                round(time.perf_counter() - t0, 6)
                if timer.enabled else None
            ),
            new_lowerings=0,
        )

    while frontier_size > 0:
        S = frontier_size
        t_level = time.perf_counter() if timer.enabled else 0.0
        terminal = cfg.max_depth is not None and depth == cfg.max_depth
        slot = nid - frontier_lo  # all rows are in the frontier or parked (<0)
        live = slot >= 0

        # Terminal levels (the widest frontier) never split — skip the
        # per-node mask hashing outright.
        nmask = (
            keys.masks(frontier_lo, frontier_lo + S)
            if sampling and not terminal else None
        )
        # Fast path: the native C++ sweep computes node stats and best splits
        # in O(rows + occupied bins) per node (native/split_kernel.cpp); the
        # numpy blocks below are the portable fallback.
        # splitter="random" stays on the numpy sweep: the C++ kernel has
        # no drawn-bin mode (the draw replaces its incremental argmin).
        # Monotonic INTEGER-WEIGHT classification runs the kernel's
        # constraint gate (integer counts keep its f32 child values
        # bit-identical to the device engines); fractional weights (e.g.
        # class_weight="balanced") and all regression stay on the numpy
        # sweep, whose f32 cumsums mirror the device arithmetic op for op
        # — the kernel's f64 accumulation order cannot, and the gate is a
        # hard binary (no tie tolerance absorbs a 1-ULP value flip).
        mono_native = mono and task == "classification" and not fractional_w
        skip_native = terminal or rand_split or (mono and not mono_native)
        nat = None if skip_native else _native_splits(
            xb, y, nid, sample_weight, binned, cfg,
            frontier_lo=frontier_lo, n_slots=S, n_classes=C, task=task,
            node_mask=nmask,
            mono=(cst32, bounds) if mono_native else None,
        )
        if nat is not None:
            counts, n, value, node_imp, feat_best, bin_best, stop = (
                _native_level_decisions(nat, task=task, cfg=cfg)
            )
            ids = frontier_lo + np.arange(S)
            _record_level(
                tree, ids, S, False, stop, feat_best, value, n, counts,
                task, node_imp,
            )
            nid, frontier_lo, frontier_size, depth = _split_and_advance(
                tree, binned, xb, nid, ids, stop, feat_best, bin_best,
                slot, live, S, frontier_lo, depth,
            )
            thread_keys(ids, stop)
            if mono_native and (~stop).any():
                sel = np.flatnonzero(~stop)
                split_ids = ids[~stop]
                bounds.assign_children(
                    split_ids, tree.left[split_ids], tree.right[split_ids],
                    nat["v_left"][sel], nat["v_right"][sel],
                    cst32[feat_best[sel]], tree.n,
                )
            note_level(depth - 1, S, (~stop).sum(), 0, t_level)
            continue

        # Per-node statistics (and, unless terminal, full split histograms).
        if task == "classification":
            flat = (slot[live] * C + y[live]).astype(np.intp)
            counts = np.bincount(flat, weights=w[live], minlength=S * C)
            counts = counts.reshape(S, C)
            n = counts.sum(axis=1)
            pure = (counts > 0).sum(axis=1) <= 1
            value = counts.argmax(axis=1).astype(np.int32)
            node_imp = class_node_impurity(counts, cfg.criterion)
        else:
            flat = slot[live].astype(np.intp)
            wv = w[live]
            n = np.bincount(flat, weights=wv, minlength=S)
            s1 = np.bincount(flat, weights=wv * y_f[live], minlength=S)
            s2 = np.bincount(flat, weights=wv * np.square(y_f[live], dtype=np.float64), minlength=S)
            mean = s1 / np.maximum(n, 1.0)
            value = mean.astype(np.float32)
            node_imp = moment_node_impurity(np.stack([n, s1, s2], axis=1))
            live_w = live & (w > 0)
            ymin = np.full(S, np.inf)
            ymax = np.full(S, -np.inf)
            np.minimum.at(ymin, slot[live_w].astype(np.intp), y_f[live_w])
            np.maximum.at(ymax, slot[live_w].astype(np.intp), y_f[live_w])
            pure = ~(ymax > ymin)

        ids = frontier_lo + np.arange(S)
        lvl_hist = 0
        if terminal:
            stop = np.ones(S, bool)
            feat_best = bin_best = None
        else:
            ch = C if task == "classification" else 3
            hist = np.zeros((S, F, ch, B))
            li = np.flatnonzero(live)
            sl = slot[li][:, None]
            xbl = xb[li]
            if task == "classification":
                idx = ((sl * F + rows_feat[: len(li)]) * C + y[li][:, None]) * B + xbl
                np.add.at(
                    hist.reshape(-1), idx.astype(np.intp).ravel(),
                    np.broadcast_to(w[li][:, None], xbl.shape).ravel(),
                )
                cost, n_l, n_r = _child_impurity_class(hist, cfg.criterion)
            else:
                hist = hist.astype(np.float32)
                base = (sl * F + rows_feat[: len(li)]) * 3 * B + xbl
                for ci, payload in enumerate(
                    (w32[li], w32[li] * y_f[li], w32[li] * y_f[li] * y_f[li])
                ):
                    np.add.at(
                        hist.reshape(-1),
                        (base + ci * B).astype(np.intp).ravel(),
                        np.broadcast_to(payload[:, None], xbl.shape).ravel(),
                    )
                cost, n_l, n_r = _child_cost_mse(hist)
            lvl_hist = hist.nbytes

            valid = cand[None, :, :] & (n_l > 0) & (n_r > 0)
            if cfg.min_child_weight > 0.0:
                valid = valid & (
                    (n_l >= cfg.min_child_weight)
                    & (n_r >= cfg.min_child_weight)
                )
            if nmask is not None:
                valid = valid & nmask[:, :, None]
            if mono:
                # sklearn's monotonic gate in the device's exact f32
                # reciprocal-multiply form (ops/impurity._monotonic_ok).
                f1 = np.float32(1.0)
                if task == "classification":
                    m_l = hist[:, :, 0, :].cumsum(axis=2)
                else:
                    m_l = hist[:, :, 1, :].cumsum(axis=2, dtype=np.float32)
                nl32 = n_l.astype(np.float32)
                nr32 = n_r.astype(np.float32)
                vl_all = m_l.astype(np.float32) * (
                    f1 / np.maximum(nl32, f1)
                )
                vr_all = (m_l[:, :, -1:] - m_l).astype(np.float32) * (
                    f1 / np.maximum(nr32, f1)
                )
                bounds.ensure(frontier_lo + S)
                lo_w, hi_w = bounds.window(frontier_lo, S, S)
                b_lo = lo_w[:, None, None]
                b_hi = hi_w[:, None, None]
                sgn = cst32[None, :, None].astype(np.float32)
                ok = (
                    ((vl_all - vr_all) * sgn <= 0)
                    & (vl_all >= b_lo) & (vl_all <= b_hi)
                    & (vr_all >= b_lo) & (vr_all <= b_hi)
                )
                valid = valid & ((sgn == 0) | ok)
            cost = np.where(valid, cost, np.inf)
            if rand_split:
                # splitter="random": one uniform pick among the VALID bins
                # per (node, feature) — same keyed draw as the device
                # engine (ops/impurity._drawn_bins), so trees agree.
                draws = keys.draws(frontier_lo, frontier_lo + S)
                cnt = valid.sum(axis=2)
                j = (draws % np.maximum(cnt, 1).astype(np.uint32))
                csum = np.cumsum(valid, axis=2)
                bin_f = (csum > j[:, :, None].astype(np.int64)).argmax(axis=2)
            else:
                bin_f = cost.argmin(axis=2)  # first-min = lowest threshold
            cost_f = np.take_along_axis(cost, bin_f[:, :, None], axis=2)[:, :, 0]
            feat_best = cost_f.argmin(axis=1).astype(np.int32)  # lowest feature
            bin_best = np.take_along_axis(
                bin_f, feat_best[:, None].astype(np.intp), axis=1
            )[:, 0].astype(np.int32)
            best_cost = np.take_along_axis(
                cost_f, feat_best[:, None].astype(np.intp), axis=1
            )[:, 0]
            occupied = (hist.sum(axis=2) > 0).sum(axis=2)  # (S, F)
            constant = (occupied <= 1).all(axis=1)
            stop = (
                pure | constant | (n < cfg.min_samples_split)
                | np.isinf(best_cost)
            )
            if cfg.min_decrease_scaled > 0.0:
                with np.errstate(invalid="ignore"):
                    stop |= (
                        n * (node_imp - best_cost) < cfg.min_decrease_scaled
                    )

        if terminal:
            feat_best = np.full(S, -1, np.int32)
            bin_best = np.zeros(S, np.int32)
        _record_level(
            tree, ids, S, terminal, stop, feat_best, value, n,
            counts if task == "classification" else None, task, node_imp,
        )
        nid, frontier_lo, frontier_size, depth = _split_and_advance(
            tree, binned, xb, nid, ids, stop, feat_best, bin_best,
            slot, live, S, frontier_lo, depth,
        )
        thread_keys(ids, stop)
        note_level(depth - 1, S, (~stop).sum(), lvl_hist, t_level)
        if mono and not terminal and (~stop).any():
            # Children of a constrained split are pinned by the winning
            # candidate's mid value (utils/monotonic.py BoundsStore).
            split_ids = ids[~stop]
            sel = np.flatnonzero(~stop)
            bounds.assign_children(
                split_ids, tree.left[split_ids], tree.right[split_ids],
                vl_all[sel, feat_best[sel], bin_best[sel]],
                vr_all[sel, feat_best[sel], bin_best[sel]],
                cst32[feat_best[sel]], tree.n,
            )

    out = tree.finalize()
    if timer.wants_fingerprints:
        # Build-state fingerprints (ISSUE 13): the whole build is host
        # work, so the finished buffer IS the host boundary — one shared
        # replay hashes the same per-level bytes the device level-wise
        # loop hashes live (engine identity makes them equal wherever the
        # trees are).
        timer.fingerprint_tree(obs_acct.replay_fingerprints(out))

    if task == "regression" and refit_targets is not None:
        from mpitree_tpu.core.builder import refit_regression_values

        w64 = (np.ones(N) if sample_weight is None else sample_weight).astype(
            np.float64
        )
        refit_regression_values(out, nid, w64, refit_targets)

    if return_leaf_ids:
        return out, nid
    return out
