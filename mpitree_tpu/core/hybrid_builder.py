"""Hybrid device+host build: TPU crown, C++ deep tail.

Quantile-binned device builds lose accuracy in the deep tail: a node at
depth ~10 spans a narrow slice of each feature, and only a handful of the
256 *global* quantile edges fall inside it — candidate starvation (measured:
-0.016 accuracy vs sklearn at covtype scale, where exact candidates close it
to -0.006). The device is also least efficient exactly there: thousands of
small nodes, scatter-bound histograms.

The hybrid splits the build at the latency/throughput crossover:

1. the device engines grow the tree to ``refine_depth`` — wide,
   data-parallel frontiers where psum'd histograms and the MXU kernel
   dominate;
2. every still-splittable leaf at that depth becomes the root of a host
   subtree built by the native C++ sweep (``host_builder.py``) on its own
   rows with **exact local candidates** — every unique value of the rows
   actually in the node, the reference's own semantics
   (``mpitree/tree/decision_tree.py:73``), infeasible device-side at scale
   but trivial on a few hundred rows;
3. subtrees graft back into the struct-of-arrays tree (id remap + concat);
   parent-before-child id order is preserved, so every downstream consumer
   (predict, export, refit, MDI) works unchanged.
"""

from __future__ import annotations

import numpy as np

from mpitree_tpu.core.tree_struct import TreeArrays


def _concat_trees(top: TreeArrays, subtrees: list, attach_at: list) -> TreeArrays:
    """Graft ``subtrees[i]`` in place of leaf node ``attach_at[i]`` of ``top``.

    The grafted root reuses the top leaf's node id (its arrays overwrite the
    leaf's entries); descendants append after all existing nodes, offset in
    discovery order. Children always carry larger ids than their parents
    afterwards — the invariant the refit/rollup passes rely on.
    """
    n_total = top.n_nodes
    offsets = []
    for st in subtrees:
        # subtree node 0 maps onto the attach point; nodes 1.. append
        offsets.append(n_total - 1)
        n_total += st.n_nodes - 1

    def alloc(arr, fill):
        shape = (n_total,) + arr.shape[1:]
        out = np.full(shape, fill, arr.dtype) if arr.ndim == 1 else np.zeros(
            shape, arr.dtype
        )
        out[: top.n_nodes] = arr
        return out

    feature = alloc(top.feature, -1)
    threshold = alloc(top.threshold, np.nan)
    left = alloc(top.left, -1)
    right = alloc(top.right, -1)
    parent = alloc(top.parent, -1)
    depth = alloc(top.depth, 0)
    value = alloc(top.value, 0)
    count = alloc(top.count, 0)
    n_node_samples = alloc(top.n_node_samples, 0)
    impurity = alloc(top.impurity, 0)

    for st, at, off in zip(subtrees, attach_at, offsets):
        dst = np.concatenate(
            [[at], off + 1 + np.arange(st.n_nodes - 1, dtype=np.int64)]
        )
        kids = np.where(st.left >= 0, dst[st.left], -1)
        rkids = np.where(st.right >= 0, dst[st.right], -1)
        pars = np.where(st.parent >= 0, dst[st.parent], parent[at])
        feature[dst] = st.feature
        threshold[dst] = st.threshold
        left[dst] = kids
        right[dst] = rkids
        # the grafted root keeps the top tree's parent link
        parent[dst[1:]] = pars[1:]
        depth[dst] = st.depth + depth[at]
        value[dst] = st.value.astype(value.dtype)
        count[dst] = st.count.astype(count.dtype)
        n_node_samples[dst] = st.n_node_samples
        impurity[dst] = st.impurity

    return TreeArrays(
        feature=feature, threshold=threshold, left=left, right=right,
        parent=parent, depth=depth, value=value, count=count,
        n_node_samples=n_node_samples, impurity=impurity,
    )


def refine_deep_subtrees(
    tree: TreeArrays,
    X: np.ndarray,
    y_enc: np.ndarray,
    leaf_ids: np.ndarray,
    *,
    config,
    refine_depth: int,
    n_classes: int | None = None,
    sample_weight: np.ndarray | None = None,
    refit_targets: np.ndarray | None = None,
) -> TreeArrays:
    """Host-finish every still-splittable leaf at ``refine_depth``.

    ``tree`` is the device-built crown (grown with
    ``max_depth=refine_depth``); ``leaf_ids`` the training rows' leaf
    assignment in it. Leaves shallower than ``refine_depth`` stopped for a
    real reason (purity / min_samples_split / constancy) and stay leaves.
    """
    import dataclasses

    from mpitree_tpu.core.host_builder import build_tree_host
    from mpitree_tpu.ops.binning import bin_dataset

    cfg = config
    remaining = (
        None if cfg.max_depth is None else int(cfg.max_depth) - refine_depth
    )
    if remaining is not None and remaining <= 0:
        return tree

    candidates = np.flatnonzero(
        (tree.feature < 0)
        & (tree.depth == refine_depth)
        & (tree.n_node_samples >= cfg.min_samples_split)
        # pure leaves (exact 0.0 impurity in every engine) can't split —
        # skip their exact re-binning outright
        & (tree.impurity > 0)
    )
    if len(candidates) == 0:
        return tree

    sub_cfg = dataclasses.replace(
        cfg, max_depth=remaining, engine="auto", frontier_tiers=(),
    )
    order = np.argsort(leaf_ids, kind="stable")
    sorted_leaves = leaf_ids[order]
    starts = np.searchsorted(sorted_leaves, candidates, side="left")
    ends = np.searchsorted(sorted_leaves, candidates, side="right")

    subtrees, attach = [], []
    for leaf, s, e in zip(candidates, starts, ends):
        rows = order[s:e]
        if len(rows) == 0:
            continue
        # No raw-count gate here: min_samples_split is a WEIGHTED rule and
        # the subtree build applies it itself (n_nodes <= 1 means it stopped).
        sw = None if sample_weight is None else sample_weight[rows]
        rt = None if refit_targets is None else refit_targets[rows]
        # exact LOCAL candidates: every unique value among this node's rows
        binned = bin_dataset(X[rows], binning="exact")
        st = build_tree_host(
            binned, y_enc[rows], config=sub_cfg, n_classes=n_classes,
            sample_weight=sw, refit_targets=rt,
        )
        if st.n_nodes <= 1:
            continue  # immediately stopped: keep the original leaf
        subtrees.append(st)
        attach.append(int(leaf))

    if not subtrees:
        return tree
    return _concat_trees(tree, subtrees, attach)
