"""Hybrid device+host build: TPU crown, C++ deep tail.

Quantile-binned device builds lose accuracy in the deep tail: a node at
depth ~10 spans a narrow slice of each feature, and only a handful of the
256 *global* quantile edges fall inside it — candidate starvation (measured:
-0.016 accuracy vs sklearn at covtype scale, where exact candidates close it
to ~-0.004; see BENCH_r02.json). The device is also least efficient exactly
there: thousands of small nodes, scatter-bound histograms.

The hybrid splits the build at the latency/throughput crossover:

1. the device engines grow the tree to ``refine_depth`` — wide,
   data-parallel frontiers where psum'd histograms and the MXU kernel
   dominate;
2. every still-splittable leaf at depth <= ``refine_depth`` (impure, enough
   samples — including leaves the device stopped as "constant under the
   global bins" shallower than the crown frontier) becomes the root of a
   host subtree built by the native C++ sweep (``host_builder.py``) on its
   own rows with **exact local candidates** — every unique value of the rows
   actually in the node, the reference's own semantics
   (``mpitree/tree/decision_tree.py:73``), infeasible device-side at scale
   but trivial on a few hundred rows;
3. subtrees graft back into the struct-of-arrays tree (id remap + concat);
   parent-before-child id order is preserved, so every downstream consumer
   (predict, export, refit, MDI) works unchanged.

Two tail engines share this module:

- **batched** (default when the native C++ kernel is available): ALL
  subtrees grow together in one multi-root level-synchronous frontier —
  one native sweep call per level instead of one per (subtree, level).
  Per-root exact local bins make the candidate count vary per
  (node, feature), which the kernel supports via per-slot ``n_cand``
  (split_kernel.cpp). Identical trees to the per-subtree engine: each
  frontier slot's result depends only on its own rows.
- **per-subtree** (portable fallback, no g++): the original loop calling
  ``build_tree_host`` once per candidate leaf.
"""

from __future__ import annotations

import numpy as np

from mpitree_tpu.core.tree_struct import TreeArrays


class _GatheredRows:
    """A gathered raw-row block masquerading as the training matrix.

    The tail engines only ever *fancy row-index* ``X`` with training-row
    arrays (``X[rows_all]`` / ``X[rows]``), so a streamed fit — whose raw
    matrix never materializes — satisfies them with one chunk-stream
    replay: the sorted union of every candidate's rows gathers into a
    dense block (``ingest.stream.StreamRowProvider``), and ``__getitem__``
    maps global row ids onto it. Candidate row sets are disjoint, so the
    block is exactly the tail's working set — host residency stays
    O(refine rows), not O(N).
    """

    def __init__(self, rows: np.ndarray, block: np.ndarray):
        self._rows = rows          # sorted global row ids
        self._block = block        # (len(rows), F) f32

    def __getitem__(self, idx):
        return self._block[np.searchsorted(self._rows, idx)]


def _alloc_extended(top: TreeArrays, n_total: int) -> TreeArrays:
    """Copy ``top`` into freshly allocated arrays of ``n_total`` nodes.

    Shared by both graft engines so a future ``TreeArrays`` field cannot be
    wired into one and silently dropped from the other.
    """

    def alloc(arr, fill):
        shape = (n_total,) + arr.shape[1:]
        out = np.full(shape, fill, arr.dtype) if arr.ndim == 1 else np.zeros(
            shape, arr.dtype
        )
        out[: top.n_nodes] = arr
        return out

    return TreeArrays(
        feature=alloc(top.feature, -1),
        threshold=alloc(top.threshold, np.nan),
        left=alloc(top.left, -1),
        right=alloc(top.right, -1),
        parent=alloc(top.parent, -1),
        depth=alloc(top.depth, 0),
        value=alloc(top.value, 0),
        count=alloc(top.count, 0),
        n_node_samples=alloc(top.n_node_samples, 0),
        impurity=alloc(top.impurity, 0),
    )


def _concat_trees(top: TreeArrays, subtrees: list, attach_at: list) -> TreeArrays:
    """Graft ``subtrees[i]`` in place of leaf node ``attach_at[i]`` of ``top``.

    The grafted root reuses the top leaf's node id (its arrays overwrite the
    leaf's entries); descendants append after all existing nodes, offset in
    discovery order. Children always carry larger ids than their parents
    afterwards — the invariant the refit/rollup passes rely on.
    """
    n_total = top.n_nodes
    offsets = []
    for st in subtrees:
        # subtree node 0 maps onto the attach point; nodes 1.. append
        offsets.append(n_total - 1)
        n_total += st.n_nodes - 1

    ext = _alloc_extended(top, n_total)
    feature, threshold, left, right = (
        ext.feature, ext.threshold, ext.left, ext.right
    )
    parent, depth, value, count = ext.parent, ext.depth, ext.value, ext.count
    n_node_samples, impurity = ext.n_node_samples, ext.impurity

    for st, at, off in zip(subtrees, attach_at, offsets):
        dst = np.concatenate(
            [[at], off + 1 + np.arange(st.n_nodes - 1, dtype=np.int64)]
        )
        kids = np.where(st.left >= 0, dst[st.left], -1)
        rkids = np.where(st.right >= 0, dst[st.right], -1)
        pars = np.where(st.parent >= 0, dst[st.parent], parent[at])
        feature[dst] = st.feature
        threshold[dst] = st.threshold
        left[dst] = kids
        right[dst] = rkids
        # the grafted root keeps the top tree's parent link
        parent[dst[1:]] = pars[1:]
        depth[dst] = st.depth + depth[at]
        value[dst] = st.value.astype(value.dtype)
        count[dst] = st.count.astype(count.dtype)
        n_node_samples[dst] = st.n_node_samples
        impurity[dst] = st.impurity

    return ext


def _bin_per_root(Xr: np.ndarray, starts: np.ndarray, ends: np.ndarray):
    """Exact local binning per (root, feature) over the gathered row block.

    ``np.unique(col, return_inverse=True)`` yields both the bin ids (the
    rank of each value among the root's uniques) and the local threshold
    list ``unique[:-1]`` — the reference's candidate set restricted to the
    node's own rows (``mpitree/tree/decision_tree.py:73``). Returns the
    binned matrix, per-(root, feature) candidate counts, and the ragged
    threshold store (flat array + offsets).
    """
    R, F = len(starts), Xr.shape[1]
    xb = np.empty(Xr.shape, np.int32)
    ncand = np.zeros((R, F), np.int32)
    off = np.zeros((R, F), np.int64)
    chunks = []
    pos = 0
    for i in range(R):
        sl = slice(starts[i], ends[i])
        for f in range(F):
            uniq, inv = np.unique(Xr[sl, f], return_inverse=True)
            xb[sl, f] = inv
            ncand[i, f] = len(uniq) - 1
            off[i, f] = pos
            pos += len(uniq) - 1
            if len(uniq) > 1:
                chunks.append(uniq[:-1])
    thr_flat = (
        np.concatenate(chunks).astype(np.float32) if chunks
        else np.empty(0, np.float32)
    )
    return xb, ncand, off, thr_flat


def _refine_batched(
    top: TreeArrays, X, y_enc, candidates, rows_per, *, cfg_sub,
    max_depth_total, root_depth, n_classes, sample_weight, refit_targets,
    feature_mask=None, feature_sampler=None, root_keys=None, obs=None,
) -> TreeArrays:
    """Grow every deep subtree together in one multi-root host frontier.

    ``root_depth[i]`` is candidate ``i``'s depth in the crown — candidates
    need not share a depth (a leaf the crown stopped as "constant" under
    global bins at depth 3 refines alongside the depth-8 frontier), so each
    root gets its own remaining-depth budget
    ``max_depth_total - root_depth[i]``.
    """
    from mpitree_tpu import native
    from mpitree_tpu.core.builder import (
        _TreeBuffer,
        refit_regression_values,
    )
    from mpitree_tpu.core.host_builder import (
        _leaf_stats,
        _native_level_decisions,
        _record_level,
        _split_and_advance,
    )

    task = cfg_sub.task
    R = len(candidates)
    sizes = np.array([len(r) for r in rows_per], np.int64)
    rows_all = np.concatenate(rows_per)
    starts = np.zeros(R, np.int64)
    np.cumsum(sizes[:-1], out=starts[1:])
    ends = starts + sizes
    sub_of = np.repeat(np.arange(R, dtype=np.int32), sizes)

    Xr = np.ascontiguousarray(X[rows_all], np.float32)
    xb, ncand, off, thr_flat = _bin_per_root(Xr, starts, ends)
    del Xr
    # Scratch sizing must cover every bin id present in xb — including
    # masked features', whose chains the kernel still builds — so compute
    # it BEFORE the subspace mask zeroes candidate counts.
    n_bins = int(ncand.max(initial=0)) + 1
    if feature_mask is not None:
        # Random-subspace trees must not discover masked features in the tail.
        ncand[:, ~np.asarray(feature_mask, bool)] = 0

    Nr = len(rows_all)
    if task == "classification":
        y_r = np.ascontiguousarray(y_enc[rows_all], np.int32)
        C = n_classes
    else:
        y_r = np.ascontiguousarray(y_enc[rows_all], np.float32)
        C = 3
    w = None if sample_weight is None else np.ascontiguousarray(
        sample_weight[rows_all], np.float64
    )
    w_dense = np.ones(Nr) if w is None else w

    from mpitree_tpu.core.builder import integer_weights

    buf = _TreeBuffer(
        n_value_cols=(C if task == "classification" else 1),
        value_dtype=np.int32 if task == "classification" else np.float32,
        # Same dtype rule as the crown builders (builder.py): the graft's
        # count.astype(...) must never truncate.
        count_dtype=(
            np.int64 if (task == "classification" and integer_weights(w))
            else np.float64
        ),
    )
    buf.ensure(R)
    buf.n = R
    root_of = np.arange(R, dtype=np.int32)
    sampling = feature_sampler is not None and feature_sampler.active
    keys = feature_sampler.key_store(root_keys) if sampling else None
    root_depth = np.asarray(root_depth, np.int32)
    # Per-root budget of additional levels below its crown leaf.
    rem = (
        None if max_depth_total is None
        else (int(max_depth_total) - root_depth)
    )
    nid = sub_of.copy()
    frontier_lo, frontier_size, depth = 0, R, 0

    while frontier_size > 0:
        S = frontier_size
        terminal = rem is not None and depth == int(rem.max())
        slot = nid - frontier_lo
        live = slot >= 0
        ids = frontier_lo + np.arange(S)
        slot_roots = root_of[frontier_lo:frontier_lo + S]

        if terminal:
            # Every surviving root is depth-exhausted: leaf stats only.
            counts, n, value, node_imp = _leaf_stats(
                slot, live, y_r, w_dense, S, C, task=task,
                criterion=cfg_sub.criterion,
            )
            _record_level(
                buf, ids, S, True, np.ones(S, bool), None, value, n, counts,
                task, node_imp,
            )
            break

        ncand_slot = np.ascontiguousarray(ncand[slot_roots])
        if sampling:
            # Per-node feature subsets: masked features cannot win.
            ncand_slot = np.where(
                keys.masks(frontier_lo, frontier_lo + S), ncand_slot, 0,
            )
        if rem is not None:
            # Budget-exhausted roots' nodes become leaves this level no
            # matter what the sweep would say — zero their candidate counts
            # so the kernel takes its counts-only fast path for them.
            exhausted = rem[slot_roots] <= depth
            ncand_slot[exhausted] = 0
        if task == "classification":
            nat = native.best_splits_classification(
                xb, y_r, nid, w, n_bins=n_bins, n_classes=C,
                frontier_lo=frontier_lo, n_slots=S, n_cand=ncand_slot,
                n_cand_per_slot=True, criterion=cfg_sub.criterion,
                min_child_weight=cfg_sub.min_child_weight,
            )
        else:
            nat = native.best_splits_regression(
                xb, y_r, nid, w, n_bins=n_bins, frontier_lo=frontier_lo,
                n_slots=S, n_cand=ncand_slot, n_cand_per_slot=True,
                min_child_weight=cfg_sub.min_child_weight,
            )
        counts, n, value, node_imp, feat_best, bin_best, stop = (
            _native_level_decisions(nat, task=task, cfg=cfg_sub)
        )
        if rem is not None:
            # Roots shallower in the crown carry a larger budget; force-stop
            # the ones whose budget this level exhausts.
            stop = stop | (rem[slot_roots] <= depth)
        _record_level(
            buf, ids, S, False, stop, feat_best, value, n, counts, task,
            node_imp,
        )
        thr_values = thr_flat[
            off[slot_roots[~stop], feat_best[~stop]] + bin_best[~stop]
        ]
        n_split = int((~stop).sum())
        nid, frontier_lo, frontier_size, depth = _split_and_advance(
            buf, None, xb, nid, ids, stop, feat_best, bin_best,
            slot, live, S, frontier_lo, depth, thr_values=thr_values,
        )
        if n_split:
            root_of = np.concatenate(
                [root_of, np.repeat(slot_roots[~stop], 2)]
            )
            if sampling:
                split_ids = ids[~stop]
                keys.assign_children(
                    split_ids, buf.left[split_ids], buf.right[split_ids],
                    buf.n,
                )

    bt = buf.finalize()
    if task == "regression" and refit_targets is not None:
        refit_regression_values(
            bt, nid, w_dense, np.asarray(refit_targets)[rows_all]
        )
    # Per-subtree fingerprint commits (PR-13 follow-up): slice the
    # multi-root buffer by root and commit each subtree's rows with ids
    # remapped to local rank order — byte-identical to what the
    # per-subtree host path commits for the same subtree, so refine
    # divergences localize regardless of which tail engine ran.
    # Single-node roots are skipped to mirror that path's "immediately
    # stopped: keep the original leaf".
    if obs is not None and getattr(obs, "wants_fingerprints", False):
        from mpitree_tpu.obs import fingerprint as fp_mod

        for r in range(R):
            ids = np.flatnonzero(root_of == r)
            if len(ids) <= 1:
                continue
            obs.fingerprint_tree(fp_mod.subtree_fingerprints(
                bt.depth, bt.n_node_samples, bt.feature, bt.threshold,
                bt.left, bt.right, ids=ids,
            ))
    return _graft_batched(top, bt, candidates, root_depth[root_of])


def _graft_batched(
    top: TreeArrays, bt: TreeArrays, attach, depth_offset: np.ndarray
) -> TreeArrays:
    """Vectorized remap of the batched tail tree into the crown.

    Batched node ``i < R`` (a root) reuses attach leaf ``attach[i]``'s id;
    nodes ``i >= R`` append after the crown in batched order — children keep
    larger ids than parents, preserving the rollup invariant.
    ``depth_offset[i]`` is batched node ``i``'s root's depth in the crown.
    """
    R = len(attach)
    extra = bt.n_nodes - R
    dst = np.empty(bt.n_nodes, np.int64)
    dst[:R] = np.asarray(attach, np.int64)
    dst[R:] = top.n_nodes + np.arange(extra, dtype=np.int64)

    ext = _alloc_extended(top, top.n_nodes + extra)

    def remap(child):
        return np.where(child >= 0, dst[np.clip(child, 0, None)], -1)

    # A root whose candidate subtree immediately stopped (no children) keeps
    # the crown leaf byte-for-byte — matching the per-subtree fallback path,
    # which skips such candidates entirely (the host rebuild's f64 stats
    # could otherwise nudge the leaf's low-order value/count/impurity).
    keep = np.ones(bt.n_nodes, bool)
    keep[:R] = np.asarray(bt.left[:R]) >= 0
    src, d = np.arange(bt.n_nodes)[keep], dst[keep]

    ext.feature[d] = bt.feature[src]
    ext.threshold[d] = bt.threshold[src]
    ext.left[d] = remap(bt.left)[src]
    ext.right[d] = remap(bt.right)[src]
    # grafted roots keep the crown's parent link; descendants remap
    ext.parent[dst[R:]] = dst[np.clip(bt.parent[R:], 0, None)]
    ext.depth[d] = (bt.depth + depth_offset)[src]
    ext.value[d] = bt.value[src].astype(ext.value.dtype)
    ext.count[d] = bt.count[src].astype(ext.count.dtype)
    ext.n_node_samples[d] = bt.n_node_samples[src]
    ext.impurity[d] = bt.impurity[src]

    return ext


def apply_refine(
    tree, leaf_ids, X, y_build, *, cfg, max_depth, rd, timer,
    n_classes=None, sample_weight=None, refit_targets=None,
    feature_mask=None, feature_sampler=None,
):
    """Estimator-side entry: run the hybrid tail under the refine timer.

    Shared by the classifier, regressor, and forests so the crossover wiring
    (depth override, phase accounting, argument plumbing) lives in one
    place. ``feature_mask`` restricts tail splits to a feature subset (a
    forest tree's random subspace).
    """
    import dataclasses

    with timer.phase("refine"):
        out = refine_deep_subtrees(
            tree, X, y_build, leaf_ids,
            config=dataclasses.replace(cfg, max_depth=max_depth),
            refine_depth=rd, n_classes=n_classes,
            sample_weight=sample_weight, refit_targets=refit_targets,
            feature_mask=feature_mask, feature_sampler=feature_sampler,
            obs=timer,
        )
    timer.counter("refine_nodes_added", int(out.n_nodes - tree.n_nodes))
    return out


# graftlint: host-fn — hybrid orchestration: crown/frontier handoff is
# an intentional host boundary (np.asarray of fetched row assignments)
def refine_deep_subtrees(
    tree: TreeArrays,
    X: np.ndarray,
    y_enc: np.ndarray,
    leaf_ids: np.ndarray,
    *,
    config,
    refine_depth: int,
    n_classes: int | None = None,
    sample_weight: np.ndarray | None = None,
    refit_targets: np.ndarray | None = None,
    feature_mask: np.ndarray | None = None,
    feature_sampler=None,
    obs=None,
) -> TreeArrays:
    """Host-finish every still-splittable leaf of the crown.

    ``obs``: optional PhaseTimer/BuildObserver (``mpitree_tpu.obs``) —
    receives the tail-engine decision and candidate counters.

    ``tree`` is the device-built crown (grown with
    ``max_depth=refine_depth``); ``leaf_ids`` the training rows' leaf
    assignment in it. Candidates are selected by *outcome*, not by depth
    alone: any leaf at depth <= ``refine_depth`` with impurity > 0 and
    enough samples may be a victim of global-quantile candidate starvation
    (e.g. the device's "constant" stop means *constant under the global
    bins*, which exact local candidates can still split). Leaves that truly
    cannot split (pure, or identical raw rows) refine into a single root
    and graft back unchanged.
    """
    import dataclasses

    from mpitree_tpu.core.host_builder import build_tree_host
    from mpitree_tpu.ops.binning import bin_dataset

    cfg = config
    if cfg.max_depth is not None and int(cfg.max_depth) <= refine_depth:
        return tree

    candidates = np.flatnonzero(
        (tree.feature < 0)
        & (tree.depth <= refine_depth)
        & (tree.n_node_samples >= cfg.min_samples_split)
        # pure leaves (exact 0.0 impurity in every engine) can't split —
        # skip their exact re-binning outright
        & (tree.impurity > 0)
    )
    if len(candidates) == 0:
        return tree

    order = np.argsort(leaf_ids, kind="stable")
    sorted_leaves = leaf_ids[order]
    starts = np.searchsorted(sorted_leaves, candidates, side="left")
    ends = np.searchsorted(sorted_leaves, candidates, side="right")

    from mpitree_tpu import native

    keep = ends > starts
    if not keep.any():
        return tree
    candidates, starts, ends = candidates[keep], starts[keep], ends[keep]
    if obs is not None:
        obs.counter("refine_candidates", len(candidates))

    if hasattr(X, "gather"):
        # Streamed fit: the raw matrix never materialized. Replay the
        # chunk stream ONCE for the sorted union of every candidate's
        # rows; both tail engines below then index the gathered block
        # transparently. Candidate row sets are disjoint, so the union
        # is duplicate-free and np.searchsorted is exact.
        needed = np.sort(
            np.concatenate([order[s:e] for s, e in zip(starts, ends)])
        )
        X = _GatheredRows(needed, X.gather(needed))

    sampling = feature_sampler is not None and feature_sampler.active
    batched = native.lib() is not None and not (
        feature_sampler is not None and feature_sampler.random_split
    )
    if obs is not None:
        obs.decision(
            "refine_tail",
            "batched-native" if batched else "per-subtree",
            reason=(
                "C++ kernel available: all subtrees grow in one multi-root "
                "frontier" if batched else
                "no native kernel (or splitter='random'): per-subtree "
                "host builds"
            ),
            refine_depth=int(refine_depth),
        )
    root_keys = (
        feature_sampler.keys_for_tree(tree)[candidates] if sampling else None
    )

    if batched:
        rows_per = [order[s:e] for s, e in zip(starts, ends)]
        return _refine_batched(
            tree, X, y_enc, candidates, rows_per,
            cfg_sub=dataclasses.replace(
                cfg, engine="auto", frontier_tiers=(),
            ),
            max_depth_total=cfg.max_depth,
            root_depth=tree.depth[candidates],
            n_classes=n_classes, sample_weight=sample_weight,
            refit_targets=refit_targets, feature_mask=feature_mask,
            feature_sampler=feature_sampler, root_keys=root_keys,
            obs=obs,
        )

    subtrees, attach = [], []
    for idx, (leaf, s, e) in enumerate(zip(candidates, starts, ends)):
        rows = order[s:e]
        # No raw-count gate here: min_samples_split is a WEIGHTED rule and
        # the subtree build applies it itself (n_nodes <= 1 means it stopped).
        sw = None if sample_weight is None else sample_weight[rows]
        rt = None if refit_targets is None else refit_targets[rows]
        remaining = (
            None if cfg.max_depth is None
            else int(cfg.max_depth) - int(tree.depth[leaf])
        )
        sub_cfg = dataclasses.replace(
            cfg, max_depth=remaining, engine="auto", frontier_tiers=(),
        )
        # exact LOCAL candidates: every unique value among this node's rows
        binned = bin_dataset(X[rows], binning="exact")
        if feature_mask is not None:
            n_cand = np.where(feature_mask, binned.n_cand, 0).astype(np.int32)
            binned = dataclasses.replace(binned, n_cand=n_cand)
        sub_sampler = (
            dataclasses.replace(
                feature_sampler, root_key_value=int(root_keys[idx])
            ) if sampling else None
        )
        st = build_tree_host(
            binned, y_enc[rows], config=sub_cfg, n_classes=n_classes,
            sample_weight=sw, refit_targets=rt, feature_sampler=sub_sampler,
        )
        if st.n_nodes <= 1:
            continue  # immediately stopped: keep the original leaf
        subtrees.append(st)
        attach.append(int(leaf))

    if not subtrees:
        return tree
    # Per-subtree fingerprint commits (PR-13 follow-up): each refined
    # subtree folds into the whole-fit hash as its own tree, so a refine
    # divergence localizes to (subtree index, level, channel) exactly
    # like a crown build — the batched tail commits identical rows.
    if obs is not None and getattr(obs, "wants_fingerprints", False):
        from mpitree_tpu.obs import fingerprint as fp_mod

        for st in subtrees:
            obs.fingerprint_tree(fp_mod.subtree_fingerprints(
                st.depth, st.n_node_samples, st.feature, st.threshold,
                st.left, st.right,
            ))
    return _concat_trees(tree, subtrees, attach)
