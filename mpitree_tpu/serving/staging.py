"""Donated double-buffered input staging for streaming inference.

The streaming request loop's overlap story: while batch *k* computes on
device, batch *k+1*'s host→device transfer should already be in flight.
JAX's async dispatch gives the overlap for free ONCE two batches are in
flight simultaneously — what this stage adds is the bounded pipeline that
keeps exactly ``depth`` results outstanding (backpressure blocks on the
oldest, so an unbounded burst cannot queue device work without limit) and
the donation discipline around it.

Donation contract (the GL05/GL08 caller side, annotated here because the
traversal's ``donate_argnums`` makes every staged buffer single-use):
each submitted batch is staged as a FRESH host array handed to exactly
one ``raw_async`` dispatch, which donates the transferred device buffer
into the traversal's loop state. The stage never re-reads a submitted
buffer — results come back as the traversal's OUTPUT arrays — and callers
get their numpy results copied out at drain time, so no donated storage
ever escapes.
"""

from __future__ import annotations

from collections import deque


class StreamStage:
    """Bounded async pipeline over a :class:`~.model.CompiledModel`.

    >>> stage = StreamStage(model, depth=2)
    >>> for batch in batches:
    ...     for ticket, out in stage.submit(batch):
    ...         handle(ticket, out)
    >>> for ticket, out in stage.drain():
    ...     handle(ticket, out)
    """

    def __init__(self, model, *, depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.model = model
        self.depth = int(depth)
        self._inflight: deque = deque()
        self._next_ticket = 0
        # Queue-depth telemetry (obs/metrics.py): streaming callers skip
        # the blocking per-request latency clock, so the pipeline's
        # outstanding-batch gauge is their scrape-side signal.
        self._m_depth = model.metrics.gauge("mpitree_serving_inflight")
        self._m_staged = model.metrics.counter(
            "mpitree_serving_staged_batches_total"
        )

    def _materialize(self, entry) -> tuple:
        ticket, out, n = entry
        return ticket, self.model.finalize(out, n)

    def submit(self, X) -> list:
        """Stage + dispatch one batch; returns any results whose slots
        this submission displaced (ready-or-forced, oldest first)."""
        done = []
        while len(self._inflight) >= self.depth:
            done.append(self._materialize(self._inflight.popleft()))
        out, n = self.model.raw_async(X)
        self._inflight.append((self._next_ticket, out, n))
        self._next_ticket += 1
        self._m_staged.inc()
        self._m_depth.set(len(self._inflight))
        return done

    def drain(self) -> list:
        """Block on everything still in flight (oldest first)."""
        done = []
        while self._inflight:
            done.append(self._materialize(self._inflight.popleft()))
        self._m_depth.set(0)
        return done
