"""CompiledModel — a fitted estimator flattened for the request path.

``compile_model(estimator)`` turns any fitted mpitree_tpu estimator
(single trees, forests/ExtraTrees, GradientBoosting*) into a serving
handle whose predict surface is ONE jitted traversal dispatch per
(model, batch-bucket):

- the depth-packed node table and every leaf-value channel are device-
  resident from compile time (``serving.tables``) — the request path
  transfers nothing but the query batch;
- leaf-value application is fused into the traversal
  (``serving.traversal``): margins, probabilities, and values come back
  as one (N, K) device result, with the estimators' host-side float64
  sequential aggregation reproduced bit-for-bit on CPU backends (the
  parity contract ``tests/test_serving.py`` pins);
- batches ride shape BUCKETS (default 1/64/4096): a request pads to the
  smallest covering bucket, oversize batches chunk at the largest — so a
  warmed model never compiles on the request path, whatever sizes
  arrive;
- dispatches run through the resilience retry rung
  (``resilience.retry_device``) with a dedicated ``serving_dispatch``
  chaos seam, and every compile note / fallback / retry lands in the
  model's own ``serve_report_`` (the ``fit_report_`` analogue for the
  serving side).

The optional Mosaic tier (``serving.pallas_serve``) engages by the
``resolve_serving_kernel`` policy — VMEM-resident tables on real TPUs,
graceful XLA fallback (typed event) everywhere else.
"""

from __future__ import annotations

import threading
import time

import numpy as np

import jax

from mpitree_tpu.config import knobs
from mpitree_tpu.obs import BuildObserver
from mpitree_tpu.obs import fingerprint as fingerprint_lib
from mpitree_tpu.obs import memory as memory_lib
from mpitree_tpu.obs.metrics import MetricsRegistry
from mpitree_tpu.resilience import chaos, retry_device
from mpitree_tpu.serving import pallas_serve, traversal
from mpitree_tpu.serving import quantize as quantize_lib
from mpitree_tpu.serving.tables import table_notes, tables_for

DEFAULT_BUCKETS = (1, 64, 4096)


def _pad_rows(X: np.ndarray, b: int) -> np.ndarray:
    """Zero-pad ``X`` up to ``b`` rows (identity at the exact bucket)."""
    k = X.shape[0]
    if k == b:
        return X
    return np.concatenate([X, np.zeros((b - k, X.shape[1]), np.float32)])


def _channel(trees, per_tree, table, dtype) -> np.ndarray:
    """Concatenate a per-tree leaf channel and depth-pack it."""
    flat = np.concatenate(
        [np.asarray(per_tree(t)).reshape(t.n_nodes, -1) for t in trees],
        axis=0,
    )
    return np.ascontiguousarray(flat[table.scatter_order()], dtype=dtype)


class CompiledModel:
    """One published model: flat table + fused traversal + buckets."""

    def __init__(self, trees, *, kind, n_features, n_out, values_fn,
                 classes=None, loss=None, scale=1.0, baseline=None,
                 buckets=DEFAULT_BUCKETS, value_dtype=None,
                 channel_salt="", quantize=None, quantize_tol=None,
                 calibration=None):
        self._state_lock = threading.Lock()
        self._obs = BuildObserver()
        # Request-path telemetry (obs/metrics.py): per-bucket latency
        # histograms + request/row counters, private per model so slot
        # swaps never mix distributions. Pure host dict work — the
        # zero-compile/zero-transfer request-path pins hold with metrics
        # on (tests/test_obs_trace.py).
        self.metrics = MetricsRegistry()
        self.trees = list(trees)
        self.kind = kind
        self.n_features = int(n_features)
        self.n_out = int(n_out)
        self.classes = classes
        self._loss = loss
        self._values_fn = values_fn
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self._lat = {
            b: self.metrics.histogram(
                "mpitree_serving_request_seconds", bucket=str(b)
            )
            for b in self.buckets
        }
        # Oversize batches chunk at the largest bucket: their end-to-end
        # wall is a chunk-LOOP total, which must not masquerade as
        # single-dispatch latency in the largest bucket's p99.
        self._lat_over = self.metrics.histogram(
            "mpitree_serving_request_seconds", bucket="oversize"
        )
        self._m_requests = self.metrics.counter(
            "mpitree_serving_requests_total"
        )
        self._m_rows = self.metrics.counter("mpitree_serving_rows_total")
        # Rows that actually went through raw()'s latency clock — the
        # honest numerator for sustained rows/s (warmup and streaming
        # raw_async rows are counted in serving_rows but never timed).
        self._m_lat_rows = self.metrics.counter(
            "mpitree_serving_latency_rows_total"
        )
        platform = jax.devices()[0].platform
        # CPU backends aggregate in f64 under a scoped enable_x64 — the
        # bit-identical twin of the estimators' host accumulation.
        # Accelerators have no f64 unit: channels ride f32 there (the
        # documented serving divergence; ids and argmaxes still agree).
        # Integer channels (single-tree label/count gathers) involve no
        # float aggregation at all — bit-exact on every platform.
        self._int_channel = (
            value_dtype is not None and np.dtype(value_dtype).kind in "iu"
        )
        # Quantized node tables (ISSUE 17): explicit argument wins, the
        # knob is the fleet default. Integer channels (single-tree
        # label/count gathers) are already exact AND minimal — an int8
        # affine would only add error, so they pass through unquantized
        # with the decision recorded.
        qmode = quantize_lib.resolve_quantize(
            knobs.value("MPITREE_TPU_SERVING_QUANTIZE")
            if quantize is None else quantize
        )
        if qmode is not None and self._int_channel:
            self._obs.decision(
                "serving_quantize", "skip",
                reason="integer leaf channel is exact and minimal "
                       "already; serving it unquantized",
            )
            qmode = None
        self.quantize = qmode
        self.exact = qmode is None and (
            self._int_channel or (platform == "cpu" and value_dtype is None)
        )
        dtype = (np.float32 if qmode is not None
                 else value_dtype if value_dtype is not None
                 else (np.float64 if platform == "cpu" else np.float32))
        self._x64 = np.dtype(dtype) == np.float64

        # Key the table cache on the CALLER's container (the estimator's
        # ``trees_`` anchor), so the fused path and the estimators'
        # leaf-id path share one weak-ref cache entry.
        [self.table] = tables_for(trees, group_bytes=None)
        # The salt carries any estimator hyperparameter BAKED INTO the
        # channel contents (the gbdt learning rate): the table cache
        # outlives this CompiledModel via the trees_ anchor, so without
        # it a recompile after a hyperparameter edit would silently
        # reuse the stale channel.
        self._quant = None
        if qmode is not None:
            # Quantized tier: the f32/f64 value channel is never device-
            # put (pinning it would defeat the compression); the int8
            # state carries its own compressed columns. build_state
            # REFUSES (typed QuantizationError) past the exactness
            # tolerance — a badly quantizing model must fail at compile,
            # not drift under traffic.
            flat = _channel(self.trees, values_fn, self.table, np.float64)
            prepared = quantize_lib.prepare_channel(kind, flat)
            tol = float(
                quantize_tol if quantize_tol is not None
                else knobs.value("MPITREE_TPU_SERVING_QUANTIZE_TOL")
            )
            self._quant = quantize_lib.build_state(
                self.table, prepared, kind=kind, scale=scale,
                n_steps=self.table.n_steps, tol=tol,
                calibration=calibration, n_features=self.n_features,
            )
            self._values = None
            kv = int(prepared.shape[1])
            rep = self._quant.report
            self._obs.decision(
                "serving_quantize", qmode,
                reason=(
                    "bf16 thresholds / int16 feature ids / int8-delta "
                    f"values; max calibration prediction delta "
                    f"{rep['max_abs_delta']:.2e} <= tol {tol:.2e}"
                ),
                **rep,
            )
        else:
            self._values = self.table.dev_values(
                f"serve:{kind}{channel_salt}", lambda tb: _channel(
                    self.trees, values_fn, tb, dtype
                ), dtype=dtype,
            )
            kv = int(self._values.shape[1])
        if self._x64:
            with jax.enable_x64(True):
                self._scale = jax.device_put(np.float64(scale))
        else:
            self._scale = jax.device_put(np.asarray(scale, np.float32))
        # The staged accumulator template (traverse_accumulate donates the
        # per-request copy): the boosting baseline row, or zeros.
        self._acc_row = (
            np.zeros(max(self.n_out, 1), dtype)
            if baseline is None
            else np.asarray(baseline, dtype).reshape(-1)
        )
        self._scale_host = float(scale)
        self._baseline_host = (
            np.zeros(max(self.n_out, 1), np.float32) if baseline is None
            else np.asarray(baseline, np.float32).reshape(-1)
        )
        precision = ("int-exact gather" if self._int_channel
                     else "f64-exact" if self.exact else "f32")
        self._obs.decision(
            "serving_compile", kind,
            reason=f"{precision} fused traversal, buckets {self.buckets}",
            exact=bool(self.exact), n_out=self.n_out,
            **table_notes(self.trees),
        )
        self._use_kernel = kind in (
            "forest_proba", "forest_mean", "margin", "forest_values"
        ) and pallas_serve.resolve_serving_kernel(
            platform,
            n_nodes_max=max(t.n_nodes for t in self.trees),
            n_features=self.n_features, kv=kv, n_out=self.n_out,
            quantized=qmode is not None, obs=self._obs,
        )
        self._kernel_state = None
        self._obs.decision(
            "serving_kernel", "pallas" if self._use_kernel else "xla",
            reason=(
                "VMEM-resident Mosaic traversal (table fits the budget)"
                if self._use_kernel else
                "XLA gather traversal (policy: resolve_serving_kernel)"
            ),
        )
        # Serving memory ledger (obs.memory, ISSUE 12): the published
        # model's device residency — flat table + value channels + the
        # largest bucket's working set (+ the stacked VMEM-tier tables
        # when the kernel engaged) — recorded so serve_report_ carries
        # capacity the same way fit_report_ does.
        self._obs.memory_plan(memory_lib.plan_serve(
            n_trees=len(self.trees),
            n_nodes_total=sum(t.n_nodes for t in self.trees),
            n_nodes_max=max(t.n_nodes for t in self.trees),
            n_features=self.n_features, value_channels=kv,
            n_out=self.n_out, buckets=self.buckets, x64=self._x64,
            kernel=self._use_kernel, quantized=qmode is not None,
        ))
        # Per-request deadline tracking (carried ROADMAP obs follow-up):
        # schedulers report misses here so metrics_text() exposes them
        # under the model label next to the latency histograms.
        self._m_deadline = self.metrics.counter(
            "mpitree_serving_deadline_misses_total"
        )
        # Model build-state fingerprint (ISSUE 13): the whole-ensemble
        # u64 over every member's per-level rows — serve_report_'s "am I
        # serving the same model the baseline served?" stamp. A serving
        # lineage whose latency moved AND whose fingerprint moved is a
        # model change, not a serving regression; obs.diff reads it from
        # the digest like the fit side's.
        self._obs.record.fingerprints = {
            "version": fingerprint_lib.FINGERPRINT_VERSION,
            "trees": [],
            "fit": fingerprint_lib.ensemble_fingerprint(self.trees),
        }
        # Flight-store envelopes from this observer are serve records,
        # not fits (obs/flight lineage keys separate the two).
        self._obs.flight_kind = "serve"

    def note_deadline_miss(self, n: int = 1) -> None:
        """Count requests answered past their deadline (the EDF
        micro-batcher's SLO signal — ``examples/serving_run.py``)."""
        self._m_deadline.inc(n)

    # -- dispatch ----------------------------------------------------------
    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _dispatch(self, Xp: np.ndarray):
        """One bucket-shaped traversal dispatch through the retry rung."""

        def dev():
            # Chaos seam: a serving dispatch blip (tunnel flap, device
            # loss) rides the same transient-retry ladder as fit.
            chaos.step("serving_dispatch")
            if self._use_kernel:
                return self._dispatch_kernel(Xp)
            acc0 = None
            if self.kind in traversal.ACC_KINDS:
                # Freshly staged per ATTEMPT (the traversal donates it);
                # for margins this is exactly the estimators' host-side
                # baseline tile.
                acc0 = np.broadcast_to(
                    self._acc_row[None, :],
                    (Xp.shape[0], self._acc_row.shape[0]),
                ).copy()
            if self._quant is not None:
                return quantize_lib.dispatch(
                    Xp, self._quant, kind=self.kind,
                    n_steps=self.table.n_steps, acc0=acc0,
                    scale=self._scale, obs=self._obs,
                )
            return traversal.dispatch(
                Xp, self.table.dev_arrays()[:5], self._values,
                kind=self.kind, n_steps=self.table.n_steps,
                acc0=acc0, scale=self._scale, x64=self._x64,
                obs=self._obs,
            )

        with self._state_lock:
            self._obs.counter("serving_dispatches")
        # Retry-rung obs writes (device_retry events/counters) stay
        # unlocked: they are failure-path-only and best-effort under
        # concurrency; the load-bearing audits (compile registry, request
        # counters) are all locked.
        with self._obs.span("serving_dispatch"):
            return retry_device(
                dev, what="serving traversal dispatch", obs=self._obs
            )

    def _dispatch_kernel(self, Xp: np.ndarray):
        """The Mosaic tier: VMEM-resident stacked tables, f32 aggregate,
        per-kind post-scale as two eager element-wise ops over device-
        cached constants — nothing but the query batch transfers."""
        quantized = self._quant is not None
        with self._state_lock:
            # Locked lazy init: the registry's contract is concurrent
            # dispatch, and a racing double-build would transiently pin
            # two device copies of the kernel tables.
            if self._kernel_state is None:
                if quantized:
                    # bf16 split-byte tables + RAW int8 lattice value
                    # blocks; the kernel accumulates integer q-sums and
                    # the affine dequant lands HERE, once, after the
                    # kernel (linear across the ensemble sum: column k
                    # collects T_k trees, so true_k = T_k*base_k +
                    # scale_k*raw_k). Exactly the int8-affine values the
                    # XLA quantized tier serves — the exactness report
                    # covers both. forest_proba rows are pre-normalized
                    # at build -> plain "sum".
                    tbl, _ = pallas_serve.build_kernel_tables_quantized(
                        self.trees
                    )
                    agg = {"forest_proba": "sum", "forest_mean": "sum",
                           "margin": "percls",
                           "forest_values": "sum"}[self.kind]
                    kv = (self.n_out
                          if self.kind in ("forest_proba", "forest_values")
                          else 1)
                    per = self._quant.q_rows_per_tree(
                        self.trees, self.table
                    )
                    vals = pallas_serve.build_kernel_values(
                        self.trees, lambda t: per[id(t)], kv,
                        dtype=np.int8,
                    )
                    vs = np.asarray(self._quant.vscale, np.float32)
                    vb = np.asarray(self._quant.vbase, np.float32)
                    T = len(self.trees)
                    if agg == "percls":
                        # Round-major margin layout: each class column
                        # collects exactly T/n_out trees' channel 0.
                        qscale = np.full(self.n_out, vs[0], np.float32)
                        qbase = np.full(
                            self.n_out,
                            (T // self.n_out) * vb[0], np.float32,
                        )
                    else:
                        qscale = vs[:kv].astype(np.float32)
                        qbase = (T * vb[:kv]).astype(np.float32)
                    qaff = (jax.device_put(qscale), jax.device_put(qbase))
                else:
                    tbl, _ = pallas_serve.build_kernel_tables(self.trees)
                    agg = {"forest_proba": "norm", "forest_mean": "sum",
                           "margin": "percls",
                           "forest_values": "sum"}[self.kind]
                    kv = (self.n_out
                          if self.kind in ("forest_proba", "forest_values")
                          else 1)
                    vals = pallas_serve.build_kernel_values(
                        self.trees, self._values_fn, kv
                    )
                    qaff = None
                rt = pallas_serve.kernel_row_tile(
                    max(t.n_nodes for t in self.trees), self.n_features,
                    kv, self.n_out, quantized=quantized,
                )
                self._kernel_state = (
                    jax.device_put(tbl), jax.device_put(vals), agg, kv, rt,
                    jax.device_put(np.float32(self._scale_host)),
                    jax.device_put(self._baseline_host), qaff,
                )
            # Unpack under the same lock: a concurrent swap_ensemble may
            # replace the tuple wholesale, and reading it outside the
            # critical section could observe a half-published rebuild.
            tbl, vals, agg, kv, rt, dscale, dbase, qaff = self._kernel_state
        out = pallas_serve.traverse_batch_pallas(
            Xp, tbl, vals, n_steps=self.table.n_steps, agg=agg,
            n_out=self.n_out, kv=kv, row_tile=rt, quantized=quantized,
        )
        if qaff is not None:
            out = out * qaff[0][None, :] + qaff[1][None, :]
        if agg == "percls":
            return out * dscale + dbase[None, :]
        return out / dscale

    def raw_async(self, X) -> tuple:
        """Dispatch without blocking: (device result, true row count).

        The streaming stage rides this — JAX's async dispatch overlaps
        this batch's H2D + compute with the caller staging the next one.
        """
        X = np.ascontiguousarray(np.asarray(X, np.float32))
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"expected (n, {self.n_features}) query batch, got "
                f"{X.shape}"
            )
        n = X.shape[0]
        with self._state_lock:
            # The observer's dict counters are read-modify-write; the
            # registry serves concurrent requests, and dropped increments
            # would silently under-report serve_report_ traffic.
            self._obs.counter("serving_requests")
            self._obs.counter("serving_rows", n)
        self._m_requests.inc()
        self._m_rows.inc(n)
        b = self._bucket(n)
        if n <= b:
            return self._dispatch(_pad_rows(X, b)), n
        # Oversize batch: chunk at the largest bucket (every chunk is a
        # warm shape; the tail pads). Device-side chunks concatenate on
        # host at materialization.
        outs = []
        for lo in range(0, n, b):
            outs.append(
                (self._dispatch(_pad_rows(X[lo:lo + b], b)), min(b, n - lo))
            )
        return outs, n

    def finalize(self, out, n: int) -> np.ndarray:
        """Materialize a ``raw_async`` result into the estimator-shaped
        host array (blocks; trims bucket padding; forest means travel on
        device as an (N, 1) accumulator column). The ONE copy of the
        chunk-concat + shape logic — ``raw`` and the streaming stage both
        ride it."""
        if isinstance(out, list):
            host = np.concatenate(
                [np.asarray(o)[:k] for o, k in out], axis=0
            )
        else:
            host = np.asarray(out)[:n]
        return host[:, 0] if self.kind == "forest_mean" else host

    def raw(self, X) -> np.ndarray:
        """The fused traversal result as a host array (margins for
        boosting, probabilities for classification forests, values for
        regressors, raw counts for single classification trees).

        Blocking end-to-end request latency lands in the per-bucket
        metrics histograms here (pad + dispatch + materialize — what a
        caller actually waits). Streaming callers ride ``raw_async``
        without a per-request clock; the stage's queue-depth gauge is
        their telemetry."""
        t0 = time.perf_counter()
        out, n = self.raw_async(X)
        host = self.finalize(out, n)
        dt = time.perf_counter() - t0
        b = self._bucket(n)
        (self._lat[b] if n <= b else self._lat_over).observe(dt)
        self._m_lat_rows.inc(n)
        return host

    def warmup(self, buckets=None) -> None:
        """Pre-compile every bucket shape OFF the request path (what the
        registry runs before a slot swap, so swapping a freshly trained
        model never compiles under traffic). Deliberately skips ``raw``'s
        latency clock: a warmup dispatch is one cold XLA compile, and
        folding 100-1000x-of-steady-state walls into the histograms
        would poison every early p99 the scrape side reports."""
        for b in buckets or self.buckets:
            self.finalize(*self.raw_async(
                np.zeros((int(b), self.n_features), np.float32)
            ))

    # -- estimator-equivalent surface -------------------------------------
    def predict(self, X):
        out = self.raw(X)
        if self.kind == "gather_counts":
            return self.classes[out.argmax(axis=1)]
        if self.kind == "gather_value":
            if self.classes is not None:  # monotonic classifier labels
                return self.classes[out.astype(np.int64)]
            return out
        if self.kind in ("forest_proba", "forest_values"):
            return self.classes[out.argmax(axis=1)]
        if self.kind == "forest_mean":
            return out
        # margin
        if self.classes is None:
            return out[:, 0]
        return self.classes[
            self._loss.proba(out.astype(np.float64)).argmax(axis=1)
        ]

    def predict_proba(self, X):
        out = self.raw(X)
        if self.kind == "gather_counts":
            # The reference quirk, preserved: RAW leaf counts.
            return out.astype(np.int64)
        if self.kind in ("forest_proba", "forest_values"):
            return out
        if self.kind == "margin" and self.classes is not None:
            return self._loss.proba(out.astype(np.float64))
        raise AttributeError(
            f"predict_proba undefined for serving kind {self.kind!r}"
        )

    def decision_function(self, X):
        if self.kind != "margin" or self.classes is None:
            raise AttributeError(
                "decision_function is a boosting-classifier surface"
            )
        raw = self.raw(X)
        return raw[:, 0] if raw.shape[1] == 1 else raw

    def trace_to(self, sink, *, track: str = "serving") -> None:
        """Route this model's dispatch spans/events into a Chrome-trace
        sink (a path or a :class:`~mpitree_tpu.obs.trace.TraceSink`
        shared with training fits — one fit+serve timeline)."""
        self._obs.trace_to(sink, track=track)

    def _sync_metrics(self) -> None:
        """Mirror the obs record's failure-path counters into the metrics
        registry (the retry rung writes through the observer; Prometheus
        scrapes should see the same numbers). set_total is max-based, so
        the mirror can never run a counter backwards."""
        with self._state_lock:
            c = dict(self._obs.record.counters)
            fallbacks = sum(
                1 for e in self._obs.record.events
                if e.get("kind") == "serving_pallas_fallback"
            )
        self.metrics.counter("mpitree_serving_retries_total").set_total(
            c.get("device_retries", 0)
        )
        self.metrics.counter("mpitree_serving_fallbacks_total").set_total(
            fallbacks
        )

    def latency_summary(self) -> dict:
        """Per-bucket p50/p95/p99 (log-bucketed histogram estimates) plus
        the sustained throughput over observed request wall.

        Buckets are the padded dispatch shapes; ``oversize`` collects
        chunk-looped requests past the largest bucket (their wall is a
        loop total, not a single-dispatch latency). ``rows`` counts ALL
        rows the model served (incl. warmup/streaming); the sustained
        rate divides only the latency-clocked rows by the clocked wall —
        mixing in untimed rows would inflate it by orders of magnitude
        on any freshly warmed model."""
        out: dict = {"buckets": {}}
        total_s, total_n = 0.0, 0
        hists = [(str(b), self._lat[b]) for b in self.buckets]
        hists.append(("oversize", self._lat_over))
        for label, h in hists:
            if h.count == 0:
                continue
            out["buckets"][label] = {
                "count": h.count,
                "p50_ms": round(h.quantile(0.5) * 1e3, 4),
                "p95_ms": round(h.quantile(0.95) * 1e3, 4),
                "p99_ms": round(h.quantile(0.99) * 1e3, 4),
                "mean_ms": round(h.sum / h.count * 1e3, 4),
            }
            total_s += h.sum
            total_n += h.count
        with self._state_lock:
            rows = int(self._obs.record.counters.get("serving_rows", 0))
        clocked = int(self._m_lat_rows.value)
        out["requests"] = total_n
        out["rows"] = rows
        out["rows_latency_clocked"] = clocked
        out["rows_per_s_sustained"] = (
            round(clocked / total_s, 1) if total_s > 0 else None
        )
        return out

    def metrics_text(self, extra_labels: dict | None = None) -> str:
        """Prometheus text exposition of this model's registry."""
        self._sync_metrics()
        return self.metrics.metrics_text(extra_labels)

    def metrics_families(self, extra_labels: dict | None = None) -> dict:
        """Synced ``render_families`` map — what ``ModelRegistry``
        merges into its single-TYPE-line-per-family exposition."""
        self._sync_metrics()
        return self.metrics.render_families(extra_labels)

    @property
    def serve_report_(self) -> dict:
        """Structured serving record (the ``fit_report_`` analogue):
        compile notes per bucket (with cold-dispatch ``seconds``
        attribution), kernel policy decision, retry/fallback events,
        request/row counters, and the per-bucket ``latency`` quantile
        block from the log-bucketed histograms."""
        self._sync_metrics()
        rep = self._obs.report()
        rep["latency"] = self.latency_summary()
        # The quantization decision + exactness report (ISSUE 17): what
        # mode the tables serve in, and how far the calibration batch's
        # predictions sit from the f32 tables.
        rep["quantization"] = (
            dict(self._quant.report) if self._quant is not None
            else {"mode": "off"}
        )
        return rep


def compile_model(estimator, *, buckets=DEFAULT_BUCKETS, quantize=None,
                  quantize_tol=None, calibration=None) -> CompiledModel:
    """Flatten a FITTED estimator into a :class:`CompiledModel`.

    ``quantize`` ("int8", or None to follow the
    ``MPITREE_TPU_SERVING_QUANTIZE`` knob) serves compressed node tables
    with an exactness report, refusing past ``quantize_tol`` (knob
    ``MPITREE_TPU_SERVING_QUANTIZE_TOL``) on the ``calibration`` batch
    (synthesized around the table's thresholds when omitted)."""
    from mpitree_tpu.boosting.gradient_boosting import (
        _BaseGradientBoosting,
    )
    from mpitree_tpu.models.classifier import DecisionTreeClassifier
    from mpitree_tpu.models.forest import _BaseForest
    from mpitree_tpu.models.regressor import DecisionTreeRegressor

    q_kw = dict(quantize=quantize, quantize_tol=quantize_tol,
                calibration=calibration)
    if isinstance(estimator, _BaseGradientBoosting):
        classes = getattr(estimator, "classes_", None)
        K = int(estimator.n_trees_per_iteration_)
        lr = float(estimator.learning_rate)
        return CompiledModel(
            estimator.trees_, kind="margin",
            n_features=estimator.n_features_in_, n_out=K,
            # Leaf values pre-scaled by the learning rate in host f64 —
            # see traversal._margin's FMA note.
            values_fn=lambda t, lr=lr: lr * np.asarray(
                t.count[:, 0], np.float64
            ),
            channel_salt=f":lr={lr!r}",
            classes=classes,
            loss=estimator._loss() if classes is not None else None,
            baseline=np.asarray(estimator._baseline_raw, np.float64),
            buckets=buckets, **q_kw,
        )
    if isinstance(estimator, _BaseForest):
        T = len(estimator.trees_)
        mono = getattr(estimator, "monotonic_cst", None)
        if hasattr(estimator, "classes_"):
            C = len(estimator.classes_)
            if mono is not None:
                # Constrained forests average their trees' bound-CLIPPED
                # class-0 fractions (forest.predict_proba's mono path), a
                # per-NODE quantity — so the rows are final at build time
                # and ride the pure-add forest_values kind; the raw-count
                # forest_proba channel would re-derive the UNCLIPPED
                # distribution. Salted: the clip depends on the cst,
                # which isn't part of the trees_ cache anchor.
                from mpitree_tpu.utils.monotonic import (
                    clipped_class0,
                    validate_monotonic_cst,
                )
                cst = validate_monotonic_cst(
                    mono, estimator.n_features_, task="classification",
                    n_classes=C,
                )

                def _mono_rows(t, cst=cst):
                    p0 = clipped_class0(t, cst).astype(np.float64)
                    return np.stack([p0, 1.0 - p0], axis=1)

                return CompiledModel(
                    estimator.trees_, kind="forest_values",
                    n_features=estimator.n_features_, n_out=C,
                    values_fn=_mono_rows,
                    channel_salt=f":cst={np.asarray(cst).tolist()!r}",
                    classes=estimator.classes_, scale=float(T),
                    buckets=buckets, **q_kw,
                )
            return CompiledModel(
                estimator.trees_, kind="forest_proba",
                n_features=estimator.n_features_, n_out=C,
                values_fn=lambda t: np.asarray(t.count, np.float64),
                classes=estimator.classes_, scale=float(T),
                buckets=buckets, **q_kw,
            )
        # Regressor: monotonic clipping is baked into count[:, 0] IN
        # PLACE at fit time (clip_tree_values), so the constrained and
        # unconstrained forests serve the same mean channel.
        return CompiledModel(
            estimator.trees_, kind="forest_mean",
            n_features=estimator.n_features_, n_out=1,
            values_fn=lambda t: np.asarray(t.count[:, 0], np.float64),
            scale=float(T), buckets=buckets, **q_kw,
        )
    if isinstance(estimator, DecisionTreeClassifier):
        tree = estimator.tree_
        if getattr(estimator, "monotonic_cst", None) is not None:
            # Constrained classifiers predict from the bound-clipped leaf
            # LABELS (classifier.predict's documented divergence) — an
            # int32 label channel, plain gather, no f64 needed.
            return CompiledModel(
                [tree], kind="gather_value",
                n_features=estimator.n_features_, n_out=1,
                values_fn=lambda t: np.asarray(t.value, np.int32),
                classes=estimator.classes_, buckets=buckets,
                value_dtype=np.int32, **q_kw,
            )
        counts = np.asarray(tree.count)
        if counts.max(initial=0) >= 2**31:
            raise OverflowError(
                "leaf counts exceed int32 on the serving table"
            )
        return CompiledModel(
            [tree], kind="gather_counts",
            n_features=estimator.n_features_,
            n_out=len(estimator.classes_),
            values_fn=lambda t: np.asarray(t.count, np.int32),
            classes=estimator.classes_, buckets=buckets,
            value_dtype=np.int32, **q_kw,
        )
    if isinstance(estimator, DecisionTreeRegressor):
        return CompiledModel(
            [estimator.tree_], kind="gather_value",
            n_features=estimator.n_features_, n_out=1,
            values_fn=lambda t: np.asarray(t.count[:, 0], np.float64),
            buckets=buckets, **q_kw,
        )
    raise TypeError(
        f"compile_model: unsupported estimator {type(estimator).__name__}"
    )
