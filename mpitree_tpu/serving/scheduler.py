"""EDF continuous-batching scheduler with admission control (ISSUE 17).

The PR-7 serving tier answers one bucket-shaped batch at a time; the only
batching logic lived in an example script's ``MicroBatcher``. This module
promotes it into the subsystem the north star needs: a scheduler that
owns a deadline heap of in-flight requests, coalesces them into the
compiled models' EXISTING bucket shapes, and — the part an example can't
carry — refuses work it cannot serve instead of letting a burst melt
every SLO at once.

**EDF with a bounded window.** Requests are earliest-deadline-first per
model; the dispatch window generalizes the example's
``DISPATCH_MARGIN_MS`` rule: hold a non-full batch open at most
``wait_ms``, but always close it ``margin_ms`` before the head-of-line
deadline. Coalesced batches ride ``CompiledModel.raw`` unchanged — the
model pads to its warm bucket shapes, so the scheduler adds ZERO new
compile keys and ZERO ``device_put`` on the request path (the PR-7 pins,
re-pinned with the scheduler on in ``tests/test_serving_sched.py``).

**QoS classes.** Each request names a class (``interactive``/``batch`` by
default — the ``MPITREE_TPU_SERVING_QOS`` grammar
``name:deadline_ms:queue_depth;...``): the class carries the default
deadline and a per-(model, class) queue bound. Isolation is structural,
not cooperative: EDF orders tight interactive deadlines ahead of any
batch backlog, and a flooded class sheds against ITS OWN depth bound
before it can starve another class's admissions.

**Admission control.** ``submit`` REFUSES (typed
:class:`RejectedRequest`, ``reason`` in :data:`REJECT_REASONS`) rather
than queueing work it cannot serve: past the global ``shed_depth`` or the
class's queue bound (``queue_full``), or when the deadline is already
infeasible — inside the close margin, or sooner than the model's
observed EWMA service time (``deadline_infeasible``). Shedding is loud
and cheap at the door, never silent at the heap.

**Observability + chaos.** Queue depths, shed counts by reason, deadline
misses, and per-class latency all land in ``obs.metrics``
(``metrics_text()`` merges them with the registry's per-model families
under one ``# TYPE`` line each). The worker's dispatch wraps the
``sched_dispatch`` chaos seam: a ``kind="unavailable"`` blip requeues the
batch once (then fails its futures), and a ``kind="hang"`` stalls the
worker so the backlog grows and admissions shed — the deterministic
overload burst the tests pin.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import threading
import time
from concurrent.futures import Future

import numpy as np

from concurrent.futures import InvalidStateError

from mpitree_tpu.config import knobs
from mpitree_tpu.obs.metrics import MetricsRegistry, render_text
from mpitree_tpu.resilience import chaos

REJECT_REASONS = (
    "queue_full", "deadline_infeasible", "unknown_model",
    "unknown_class", "shutdown",
)

# EWMA weight for the per-model service-time estimate the feasibility
# gate reads (newest dispatch counts ~1/4 — reactive, but one slow cold
# outlier can't condemn every later admission).
_EWMA_ALPHA = 0.25


def _resolve(future: Future, value, *, is_error: bool = False) -> bool:
    """Resolve a request future, tolerating the close/requeue races
    where two paths reach the same future (close() failing the backlog
    while a racing dispatch serves it): first resolution wins, the
    second is a no-op."""
    try:
        if not future.set_running_or_notify_cancel():
            return False
        if is_error:
            future.set_exception(value)
        else:
            future.set_result(value)
        return True
    except InvalidStateError:
        return False


class RejectedRequest(RuntimeError):
    """Typed admission refusal; ``reason`` is one of REJECT_REASONS."""

    def __init__(self, message: str, *, reason: str):
        super().__init__(message)
        assert reason in REJECT_REASONS
        self.reason = reason


@dataclasses.dataclass(frozen=True)
class QoSClass:
    """One scheduling class: its default deadline + per-(model, class)
    admission bound."""

    name: str
    deadline_ms: float
    queue_depth: int


def parse_qos(spec: str) -> tuple[QoSClass, ...]:
    """``name:deadline_ms:queue_depth;...`` -> classes (first = default).

    The grammar is the ``MPITREE_TPU_SERVING_QOS`` knob's; parse errors
    are loud — a typo'd QoS spec silently admitting everything at one
    depth is exactly the overload it exists to prevent."""
    classes = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        try:
            name, deadline_ms, depth = part.split(":")
            cls = QoSClass(name.strip(), float(deadline_ms), int(depth))
        except ValueError:
            raise ValueError(
                f"bad QoS class {part!r} (grammar: "
                "`name:deadline_ms:queue_depth;...`)"
            ) from None
        if cls.deadline_ms <= 0 or cls.queue_depth <= 0:
            raise ValueError(
                f"QoS class {cls.name!r} needs positive deadline_ms and "
                f"queue_depth (got {part!r})"
            )
        classes.append(cls)
    if not classes:
        raise ValueError("empty QoS spec")
    return tuple(classes)


@dataclasses.dataclass
class _Request:
    """One queued row. Orderable by (deadline, seq) via the heap tuple —
    this body just carries the payload."""

    row: np.ndarray
    qos: str
    deadline: float     # absolute perf_counter() seconds
    arrival: float
    future: Future
    retried: bool = False


class Scheduler:
    """EDF continuous-batching front of a :class:`ModelRegistry`."""

    def __init__(self, registry, *, qos=None, shed_depth=None,
                 margin_ms=None, wait_ms=None):
        self.registry = registry
        spec = qos if qos is not None else knobs.value(
            "MPITREE_TPU_SERVING_QOS"
        )
        self.qos = (spec if isinstance(spec, tuple) else parse_qos(spec))
        self._qos_by_name = {c.name: c for c in self.qos}
        self.default_qos = self.qos[0].name
        self.shed_depth = int(
            shed_depth if shed_depth is not None
            else knobs.value("MPITREE_TPU_SERVING_SHED_DEPTH")
        )
        self.margin_s = float(
            margin_ms if margin_ms is not None
            else knobs.value("MPITREE_TPU_SERVING_MARGIN_MS")
        ) / 1e3
        self.wait_s = float(
            wait_ms if wait_ms is not None
            else knobs.value("MPITREE_TPU_SERVING_WAIT_MS")
        ) / 1e3
        self.metrics = MetricsRegistry()
        self._lock = threading.Condition()
        # Per-model EDF heaps of (deadline, seq, _Request); seq breaks
        # deadline ties FIFO and keeps the heap total-ordered without
        # comparing request bodies.
        self._heaps: dict[str, list] = {}
        self._depth: dict[tuple[str, str], int] = {}
        self._total = 0
        self._seq = itertools.count()
        # Per-model EWMA of observed per-dispatch service seconds — the
        # feasibility gate's estimate (None until the first dispatch:
        # admission never guesses before it has evidence).
        self._service_s: dict[str, float] = {}
        self._closed = False
        self._m_shed = {
            r: self.metrics.counter("mpitree_sched_shed_total", reason=r)
            for r in REJECT_REASONS
        }
        self._m_miss = self.metrics.counter(
            "mpitree_sched_deadline_misses_total"
        )
        self._m_dispatch = self.metrics.counter(
            "mpitree_sched_dispatches_total"
        )
        self._m_requeue = self.metrics.counter(
            "mpitree_sched_requeues_total"
        )
        self._m_lat = {
            c.name: self.metrics.histogram(
                "mpitree_sched_class_latency_seconds", qos=c.name
            )
            for c in self.qos
        }
        self._worker = threading.Thread(
            target=self._run, name="mpitree-sched", daemon=True
        )
        self._worker.start()

    # -- admission ---------------------------------------------------------
    def _shed(self, reason: str, message: str):
        self._m_shed[reason].inc()
        return RejectedRequest(message, reason=reason)

    def submit(self, model: str, row, *, qos: str | None = None,
               deadline_ms: float | None = None) -> Future:
        """Admit one request row, or raise a typed
        :class:`RejectedRequest`. The future resolves to the model's
        ``raw`` output row for this request."""
        qos = qos if qos is not None else self.default_qos
        cls = self._qos_by_name.get(qos)
        if cls is None:
            raise self._shed(
                "unknown_class",
                f"unknown QoS class {qos!r} (have "
                f"{sorted(self._qos_by_name)})",
            )
        try:
            compiled = self.registry.get(model)
        except KeyError as e:
            raise self._shed("unknown_model", str(e)) from None
        row = np.ascontiguousarray(np.asarray(row, np.float32)).reshape(-1)
        if row.shape[0] != compiled.n_features:
            raise ValueError(
                f"expected {compiled.n_features} features, got "
                f"{row.shape[0]}"
            )
        now = time.perf_counter()
        budget_s = (deadline_ms if deadline_ms is not None
                    else cls.deadline_ms) / 1e3
        deadline = now + budget_s
        with self._lock:
            if self._closed:
                raise self._shed("shutdown", "scheduler is closed")
            if self._total >= self.shed_depth:
                raise self._shed(
                    "queue_full",
                    f"scheduler at shed_depth {self.shed_depth} "
                    f"in-flight requests",
                )
            depth_key = (model, qos)
            if self._depth.get(depth_key, 0) >= cls.queue_depth:
                raise self._shed(
                    "queue_full",
                    f"class {qos!r} at queue_depth {cls.queue_depth} "
                    f"for model {model!r}",
                )
            # Feasibility: refuse a deadline the window margin already
            # eats, or — when work is already queued ahead — one sooner
            # than the model's observed service time. No estimate yet ->
            # admit (never guess). The depth>0 condition is what lets
            # the estimate RECOVER: one slow burst (a hang, a cold
            # executable) inflates the EWMA, and if it also gated an
            # idle scheduler nothing would ever dispatch to pull it back
            # down — an accepted request on an idle queue dispatches
            # immediately, so the worst case is one recorded deadline
            # miss, not a permanent lockout.
            est = self._service_s.get(model)
            if budget_s <= self.margin_s or (
                est is not None and budget_s < est and self._total > 0
            ):
                raise self._shed(
                    "deadline_infeasible",
                    f"deadline {budget_s * 1e3:.1f}ms is inside the "
                    f"{self.margin_s * 1e3:.1f}ms close margin"
                    if budget_s <= self.margin_s else
                    f"deadline {budget_s * 1e3:.1f}ms < observed "
                    f"service time {est * 1e3:.1f}ms for {model!r}",
                )
            req = _Request(row=row, qos=qos, deadline=deadline,
                           arrival=now, future=Future())
            heapq.heappush(
                self._heaps.setdefault(model, []),
                (deadline, next(self._seq), req),
            )
            self._depth[depth_key] = self._depth.get(depth_key, 0) + 1
            self._total += 1
            self._gauge_depth(model, qos)
            self._lock.notify_all()
        return req.future

    def _gauge_depth(self, model: str, qos: str) -> None:
        self.metrics.gauge(
            "mpitree_sched_queue_depth", model=model, qos=qos
        ).set(self._depth.get((model, qos), 0))

    # -- the worker --------------------------------------------------------
    def _head(self):
        """(model, head_deadline) of the earliest head-of-line request
        across models, or (None, None). Caller holds the lock."""
        best, best_dl = None, None
        for model, heap in self._heaps.items():
            if heap and (best_dl is None or heap[0][0] < best_dl):
                best, best_dl = model, heap[0][0]
        return best, best_dl

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._closed and self._head()[0] is None:
                    self._lock.wait()
                if self._closed and self._head()[0] is None:
                    return
                model, head_dl = self._head()
                heap = self._heaps[model]
                head = heap[0][2]
                cap = self.registry.get(model).buckets[-1]
                # The window rule, generalized from the example: hold a
                # non-full batch open at most wait_s past the head's
                # arrival, but ALWAYS close margin_s before its deadline.
                window_end = min(
                    head.arrival + self.wait_s, head_dl - self.margin_s
                )
                changed = False
                while (not self._closed and len(heap) < cap
                       and time.perf_counter() < window_end):
                    self._lock.wait(
                        max(window_end - time.perf_counter(), 0.0)
                    )
                    # A tighter deadline may have arrived at the head of
                    # any heap; restart selection (and the window rule)
                    # rather than serving a stale pick.
                    if self._head() != (model, head_dl):
                        changed = True
                        break
                if changed:
                    continue
                batch = [
                    heapq.heappop(heap)[2]
                    for _ in range(min(len(heap), cap))
                ]
                if not batch:
                    continue
                for r in batch:
                    self._depth[(model, r.qos)] -= 1
                self._total -= len(batch)
                for q in {r.qos for r in batch}:
                    self._gauge_depth(model, q)
            self._dispatch(model, batch)

    def _dispatch(self, model: str, batch: list) -> None:
        """Serve one coalesced batch; resolve/requeue/fail its futures.

        Runs OUTSIDE the lock — admissions and other submissions proceed
        while the model dispatches (the registry's concurrency
        contract)."""
        compiled = self.registry.get(model)
        t0 = time.perf_counter()
        try:
            # Chaos seam: a blip here (tunnel flap under traffic) is a
            # requeue-once; a hang stalls this worker so the backlog
            # grows and admissions shed — the deterministic overload
            # burst. Note the model's own serving_dispatch seam +
            # retry rung still guard the inner dispatch.
            chaos.step("sched_dispatch")
            out = compiled.raw(np.stack([r.row for r in batch]))
        except chaos.ChaosKilled:
            raise
        except Exception as e:
            fresh = [r for r in batch if not r.retried]
            stale = [r for r in batch if r.retried]
            for r in stale:
                _resolve(r.future, e, is_error=True)
            if fresh:
                self._m_requeue.inc(len(fresh))
                with self._lock:
                    for r in fresh:
                        r.retried = True
                        heapq.heappush(
                            self._heaps.setdefault(model, []),
                            (r.deadline, next(self._seq), r),
                        )
                        key = (model, r.qos)
                        self._depth[key] = self._depth.get(key, 0) + 1
                        self._total += 1
                    self._lock.notify_all()
            return
        done = time.perf_counter()
        self._m_dispatch.inc()
        # EWMA service estimate for the feasibility gate. The read-
        # modify-write must hold the lock: the admission path reads
        # _service_s concurrently, and two racing dispatch threads would
        # otherwise drop one sample's worth of smoothing.
        with self._lock:
            prev = self._service_s.get(model)
            self._service_s[model] = (
                done - t0 if prev is None
                else (1 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * (done - t0)
            )
        misses = 0
        for i, r in enumerate(batch):
            if not _resolve(r.future, out[i]):
                continue
            self._m_lat[r.qos].observe(done - r.arrival)
            if done > r.deadline:
                misses += 1
        if misses:
            self._m_miss.inc(misses)
            compiled.note_deadline_miss(misses)

    # -- lifecycle / observability ----------------------------------------
    def queue_depth(self, model: str | None = None) -> int:
        with self._lock:
            if model is None:
                return self._total
            return sum(len(h) for m, h in self._heaps.items()
                       if m == model)

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every queued request resolved (True) or timeout."""
        end = time.perf_counter() + timeout
        while time.perf_counter() < end:
            with self._lock:
                if self._total == 0:
                    return True
            time.sleep(0.002)
        return False

    def close(self, *, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admitting; optionally drain the backlog first. Queued
        requests after a drainless close fail with reason ``shutdown``."""
        if drain:
            self.drain(timeout)
        with self._lock:
            self._closed = True
            pending = [
                r for heap in self._heaps.values() for _, _, r in heap
            ]
            self._heaps.clear()
            self._depth = {k: 0 for k in self._depth}
            self._total = 0
            self._lock.notify_all()
        for r in pending:
            _resolve(
                r.future, self._shed("shutdown", "scheduler closed"),
                is_error=True,
            )
        self._worker.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self) -> dict:
        """Host-side snapshot for reports/benches (no scrape needed)."""
        with self._lock:
            depth = {f"{m}/{q}": d for (m, q), d in self._depth.items()
                     if d}
        return {
            "queued": self.queue_depth(),
            "queue_depth": depth,
            "dispatches": int(self._m_dispatch.value),
            "requeues": int(self._m_requeue.value),
            "deadline_misses": int(self._m_miss.value),
            "shed": {r: int(c.value) for r, c in self._m_shed.items()
                     if c.value},
            "class_latency_ms": {
                name: {
                    "count": h.count,
                    "p50": round((h.quantile(0.5) or 0) * 1e3, 3),
                    "p99": round((h.quantile(0.99) or 0) * 1e3, 3),
                }
                for name, h in self._m_lat.items() if h.count
            },
        }

    def metrics_text(self) -> str:
        """One Prometheus exposition: scheduler families merged with the
        registry's per-model families under single ``# TYPE`` lines."""
        return render_text(
            [self.metrics.render_families()]
            + self.registry.metrics_families()
        )
