"""ModelRegistry — named model slots with a warm compile pool.

The production swap story: a trainer finishes a new model while the old
one serves traffic. Publishing compiles + bucket-warms the NEW model
ENTIRELY off the request path (``CompiledModel.warmup`` runs every bucket
shape), then flips the slot pointer under a lock — so the first request
after a swap hits a warm executable, never a 20-70 s XLA tunnel compile.
The process compile registry (``obs.REGISTRY``, entry
``serving_traverse``) is the audit trail: the swap-under-load test pins
ZERO new cache-key entries on the request path after a publish.

Thread-safety: slot reads/writes hold a lock; the dispatch itself is
outside it (concurrent requests serve concurrently — JAX executables are
thread-safe to call).
"""

from __future__ import annotations

import threading
import time

from mpitree_tpu.obs.metrics import MetricsRegistry
from mpitree_tpu.serving.model import DEFAULT_BUCKETS, CompiledModel


class ModelRegistry:
    """Named slots of :class:`CompiledModel`; see module docstring."""

    def __init__(self, *, buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        self._slots: dict[str, CompiledModel] = {}
        self._meta: dict[str, dict] = {}
        self._lock = threading.Lock()
        # Registry-level metrics (obs/metrics.py): publish counts + warm
        # seconds; metrics_text() merges every slot model's private
        # registry under a model=<name> label for one scrape surface.
        self.metrics = MetricsRegistry()

    def publish(self, name: str, model, *, warm: bool = True,
                quantize=None, quantize_tol=None,
                calibration=None) -> CompiledModel:
        """Compile (if needed) + warm ``model``, then swap it live.

        ``model``: a fitted estimator or an already-compiled
        :class:`CompiledModel`. Everything expensive happens BEFORE the
        pointer flip; requests racing the publish keep hitting the old
        slot until the new one is warm. ``quantize``/``quantize_tol``/
        ``calibration`` pass through to ``compile_model`` — a
        quantization REFUSAL (exactness past tolerance) therefore fails
        the publish before the slot flips, leaving the old model
        serving.
        """
        if not isinstance(model, CompiledModel):
            from mpitree_tpu.serving.model import compile_model

            model = compile_model(
                model, buckets=self.buckets, quantize=quantize,
                quantize_tol=quantize_tol, calibration=calibration,
            )
        t0 = time.perf_counter()
        if warm:
            model.warmup()
        warm_s = time.perf_counter() - t0
        self.metrics.counter(
            "mpitree_registry_publish_total", model=name
        ).inc()
        self.metrics.histogram(
            "mpitree_registry_warm_seconds", model=name
        ).observe(warm_s)
        with self._lock:
            generation = self._meta.get(name, {}).get("generation", 0) + 1
            self._slots[name] = model
            self._meta[name] = {
                "generation": generation,
                "warm_s": round(warm_s, 3),
                "buckets": model.buckets,
                "kind": model.kind,
            }
        model._obs.decision(
            "registry_publish", name,
            reason=f"generation {generation}, warmed in {warm_s:.3f}s",
            warm=bool(warm),
        )
        return model

    def get(self, name: str) -> CompiledModel:
        with self._lock:
            try:
                return self._slots[name]
            except KeyError:
                raise KeyError(
                    f"no model published under {name!r}; "
                    f"published: {sorted(self._slots)}"
                ) from None

    def drop(self, name: str) -> None:
        with self._lock:
            self._slots.pop(name, None)
            self._meta.pop(name, None)

    def models(self) -> dict:
        """Snapshot of slot metadata (generation, warm time, buckets)."""
        with self._lock:
            return {k: dict(v) for k, v in self._meta.items()}

    def metrics_families(self) -> list:
        """The registry's family maps: its own publish/warm metrics plus
        every published model's request-path registry stamped with a
        ``model=<slot>`` label. The building blocks ``metrics_text``
        renders — exposed so the scheduler can merge ITS families into
        the same exposition (one ``# TYPE`` line per name)."""
        with self._lock:
            slots = dict(self._slots)
        maps = [self.metrics.render_families()]
        for name in sorted(slots):
            maps.append(slots[name].metrics_families({"model": name}))
        return maps

    def metrics_text(self) -> str:
        """One Prometheus exposition for the whole registry (the scrape
        surface ``examples/serving_run.py``'s asyncio exporter serves).
        Families merge under ONE ``# TYPE`` line per name — the
        Prometheus parser rejects duplicates, so two published slots
        must share each family header (``obs.metrics.render_text``)."""
        from mpitree_tpu.obs.metrics import render_text

        return render_text(self.metrics_families())

    # Request-path conveniences — one slot read, then the model's own
    # bucketed single-dispatch path.
    def predict(self, name: str, X):
        return self.get(name).predict(X)

    def predict_proba(self, name: str, X):
        return self.get(name).predict_proba(X)

    def raw(self, name: str, X):
        return self.get(name).raw(X)
