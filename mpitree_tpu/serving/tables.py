"""Depth-packed structure-of-arrays node tables — the serving-side tree form.

Training produces per-tree :class:`~mpitree_tpu.core.tree_struct.TreeArrays`
keyed by within-tree node ids; the old ensemble descent stacked them into a
padded ``(T, M)`` grid (``M`` = the LARGEST member's node count) and vmapped
a per-tree gather loop over it, re-uploading every tree slice on every
predict call. A :class:`NodeTable` is the serving-native flattening:

- **one flat id space** — every node of every tree in the group lives at an
  absolute index into five parallel arrays (feature, threshold, left, right,
  orig), children addressed absolutely, so the whole ensemble traverses as
  ONE gather program with no tree axis in the table (mixed-size ensembles
  carry zero padding);
- **packed contiguously per depth level** — nodes are ordered by
  ``(depth, tree, node)`` with ``level_off`` recording the slab bounds, so
  the ids live at traversal step ``d`` all fall in one dense slab instead of
  scattering across a sparse ``(T, M)`` grid;
- **true-depth steps** — ``n_steps`` is the deepest MEMBER's depth (the
  number of level slabs minus one), not the estimator's ``max_depth``
  budget: a ``max_depth=20`` ensemble whose trees all stopped at depth 6
  descends 6 steps, not 20;
- **cached device residency** — host arrays build once per ensemble object
  (weak-ref anchored, like every predict cache) and ``dev_arrays()`` /
  ``dev_values()`` pin the device copies in the same cache entry, so the
  request path transfers nothing but the query batch.

Leaf-value channels (``values``) attach lazily — only the fused serving
path (``serving.model``) needs them; the estimators' leaf-id path descends
on the five structural arrays alone.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from mpitree_tpu.ops.predict import WeakIdCache

# Device-memory ceiling for one table's five structural int32/f32 arrays
# plus headroom for lazily-attached value channels — the same role as the
# old stacked path's STACKED_GROUP_BYTES, now counted on the flat (padding
# free) layout, so a given budget admits strictly more trees.
TABLE_GROUP_BYTES = 256 << 20
_BYTES_PER_NODE = 24  # 5 x int32/f32 structural columns + value headroom


@dataclasses.dataclass
class NodeTable:
    """One depth-packed flat node table (a whole ensemble, or one group).

    Attributes
    ----------
    feature : (M,) int32 — split feature per node, ``-1`` marks leaves.
    threshold : (M,) float32 — split value; ``nan`` on leaves.
    left, right : (M,) int32 — ABSOLUTE child ids into this table
        (``-1`` on leaves; never followed — the traversal holds leaves).
    orig : (M,) int32 — the node's id within its source tree (what maps
        absolute traversal results back to per-tree leaf ids).
    root : (T,) int32 — absolute root id per member tree.
    level_off : (D+2,) int64 — slab offsets: level ``d`` occupies
        ``[level_off[d], level_off[d+1])``.
    n_steps : int — true ensemble depth (deepest member; >= 1).
    """

    feature: np.ndarray
    threshold: np.ndarray
    left: np.ndarray
    right: np.ndarray
    orig: np.ndarray
    root: np.ndarray
    level_off: np.ndarray
    n_steps: int

    def __post_init__(self):
        self._dev = None
        self._values: dict = {}
        self._dev_values: dict = {}

    @property
    def n_nodes(self) -> int:
        return int(self.feature.shape[0])

    @property
    def n_trees(self) -> int:
        return int(self.root.shape[0])

    def dev_arrays(self, *, cache: bool = True) -> tuple:
        """The five traversal arrays + root + orig on device.

        ``cache=True`` pins the copies on the table (uploading becomes a
        first-touch cost, never a request-path one) — right for tables
        within the ``group_bytes`` budget and for published serving
        models, whose whole point is persistent residency.
        ``cache=False`` uploads transiently (the buffers free when the
        caller drops them) — how the estimator predict path keeps a
        multi-table ensemble's PEAK device residency bounded by one
        group instead of the whole forest."""
        if self._dev is not None:
            return self._dev
        dev = tuple(
            jax.device_put(a)
            for a in (self.feature, self.threshold, self.left,
                      self.right, self.root, self.orig)
        )
        if cache:
            self._dev = dev
        return dev

    def values(self, channel: str, build) -> np.ndarray:
        """Host value channel ``channel``, built once via ``build(self)``."""
        v = self._values.get(channel)
        if v is None:
            v = self._values[channel] = build(self)
        return v

    def dev_values(self, channel: str, build, *, dtype) -> jax.Array:
        """Device copy of a value channel at ``dtype``, cached.

        f64 channels transfer inside a scoped ``enable_x64`` — outside it
        this wheel canonicalizes the upload to f32 (the gbdt-path lesson,
        ``ops/histogram.py``).
        """
        key = (channel, np.dtype(dtype).str)
        d = self._dev_values.get(key)
        if d is None:
            host = np.asarray(self.values(channel, build), dtype=dtype)
            if host.dtype == np.float64:
                with jax.enable_x64(True):
                    d = jax.device_put(host)
            else:
                d = jax.device_put(host)
            self._dev_values[key] = d
        return d

    def scatter_order(self) -> np.ndarray:
        """(M,) permutation mapping absolute table position -> index into
        the per-tree concatenation (``concat(arrays)[scatter_order()]``
        depth-packs a per-node channel)."""
        return self._order


def _flatten(trees, lo: int, hi: int) -> NodeTable:
    """Depth-pack ``trees[lo:hi]`` into one :class:`NodeTable`."""
    group = trees[lo:hi]
    sizes = np.array([t.n_nodes for t in group], np.int64)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    total = int(offs[-1])
    all_depth = np.concatenate(
        [np.asarray(t.depth, np.int64) for t in group]
    )
    all_tree = np.repeat(np.arange(len(group), dtype=np.int64), sizes)
    all_node = np.concatenate([np.arange(s, dtype=np.int64) for s in sizes])
    # (depth, tree, node) ascending: each depth level is one contiguous
    # slab, trees in member order inside it.
    order = np.lexsort((all_node, all_tree, all_depth))
    pos = np.empty(total, np.int64)
    pos[order] = np.arange(total)

    feat = np.concatenate([np.asarray(t.feature, np.int32) for t in group])
    thr = np.concatenate([np.asarray(t.threshold, np.float32) for t in group])
    left = np.concatenate([np.asarray(t.left, np.int64) for t in group])
    right = np.concatenate([np.asarray(t.right, np.int64) for t in group])
    # Child ids are within-tree; lift to flat-concat ids, then through the
    # depth-pack permutation to absolute table ids. Leaves stay -1 (their
    # ``pos[-1]`` lookup is a valid-but-masked numpy wraparound read).
    tree_off = offs[all_tree]
    left_abs = np.where(left >= 0, pos[left + tree_off], -1)
    right_abs = np.where(right >= 0, pos[right + tree_off], -1)

    depth_sorted = all_depth[order]
    n_levels = int(depth_sorted[-1]) + 1 if total else 1
    level_off = np.searchsorted(
        depth_sorted, np.arange(n_levels + 1), side="left"
    )
    table = NodeTable(
        feature=feat[order],
        threshold=thr[order],
        left=left_abs[order].astype(np.int32),
        right=right_abs[order].astype(np.int32),
        orig=all_node[order].astype(np.int32),
        root=pos[offs[:-1]].astype(np.int32),
        level_off=level_off.astype(np.int64),
        n_steps=max(n_levels - 1, 1),
    )
    table._order = order
    return table


_tables_cache = WeakIdCache()


def tables_for(trees, *, group_bytes: int | None = TABLE_GROUP_BYTES) -> list:
    """Depth-packed tables for ``trees``, cached on the ensemble object.

    ``group_bytes`` caps one table's structural footprint; ``None`` means
    one table regardless of size (the fused serving path, whose ensemble
    accumulation is a single program over one table). The cache entry is
    keyed by the trees CONTAINER (the estimators' ``_TreeList``/``tree_``
    anchor) and holds host arrays — plus, for within-budget single-table
    ensembles, their cached device copies — so repeat predict calls
    upload nothing (the PR-6-era per-call ``jax.device_put(a[sl])``
    re-upload is gone). Oversize ensembles split into multiple tables
    whose device copies stay TRANSIENT on the estimator path (peak
    residency = one group, the old bound; see ``dev_arrays``).
    """

    n = len(trees)
    if group_bytes is None:
        bounds = [0, n]
    else:
        per_group = []
        cur = 0
        budget = max(int(group_bytes), 1)
        acc = 0
        for i, t in enumerate(trees):
            b = t.n_nodes * _BYTES_PER_NODE
            if i > cur and acc + b > budget:
                per_group.append(i)
                cur = i
                acc = 0
            acc += b
        bounds = [0, *per_group, n]

    by_bytes = _tables_cache.get_or_build(trees, dict)
    # A byte budget the whole ensemble fits inside yields the same single
    # table as group_bytes=None — normalize the key so the estimator
    # predict path and a published CompiledModel share ONE table (and one
    # device copy) instead of flattening twice.
    key = "one" if len(bounds) == 2 else int(group_bytes)
    tables = by_bytes.get(key)
    if tables is None:
        tables = by_bytes[key] = [
            _flatten(trees, bounds[i], bounds[i + 1])
            for i in range(len(bounds) - 1)
        ]
    return tables


def table_notes(trees) -> dict:
    """Cheap (host-only) serving notes for a fitted ensemble — what
    ``fit_report_`` records without building device tables: total nodes,
    true descent depth vs the padded stacked grid, and the flat table's
    size advantage over the old ``(T, max_nodes)`` layout."""
    sizes = [int(t.n_nodes) for t in trees]
    n_steps = max(max((int(t.max_depth) for t in trees), default=0), 1)
    total = sum(sizes)
    stacked_cells = len(sizes) * max(sizes, default=0)
    return {
        "n_trees": len(sizes),
        "n_nodes": total,
        "n_steps": n_steps,
        "flat_fill": round(total / stacked_cells, 4) if stacked_cells else 1.0,
    }


def note_serving(obs, trees) -> None:
    """Record the serving-table plan on a fit's ``BuildObserver`` — the
    ``fit_report_`` side of the serving story (the compile-side notes land
    in the process compile registry under ``serving_traverse`` when the
    model is actually published; ``serving.model.CompiledModel`` carries
    those in its own ``serve_report_``)."""
    notes = table_notes(trees)
    obs.decision(
        "serving", "flat-table",
        reason=(
            f"depth-packed node table: {notes['n_nodes']} nodes, "
            f"{notes['n_steps']} descent steps (true ensemble depth), "
            f"{notes['flat_fill']:.0%} of the padded stacked grid"
        ),
        **notes,
    )
