"""Quantized node tables — compressed serving state (ISSUE 17).

The serving tier's per-row cost is dominated by the device residency the
flat table pins: five f32/int32 structural columns plus f64/f32 leaf-value
channels. For the ensembles production actually serves, most of that
precision is head-room: thresholds route identically at bf16 for almost
every query row, feature ids fit int16, and leaf values — once expressed
as per-channel affine deltas — fit int8. This module is the ONE copy of
the compression scheme both serving tiers ride:

- **thresholds** ride bf16 (upcast-exact f32 compare: every bf16 value is
  an exact f32, so the descent stays a deterministic ``x <= thr``);
- **feature ids** ride int16 (refused past 32767 features);
- **leaf values** ride int8 deltas with per-channel affine dequant
  ``v = base + q * scale`` (scale spans the channel's [min, max] over 254
  steps). Channels are PREPARED per serving kind first
  (:func:`prepare_channel`): forest count rows normalize at build time so
  the int8 grid spans [0, 1] probabilities instead of raw counts — the
  accumulation then becomes a plain sum, numerically identical in shape
  to the margin/mean kinds;
- children (and roots) stay int32: absolute flat-table ids outgrow int16
  on exactly the large ensembles quantization exists for.

Quantization is lossy BY CONTRACT, so every compiled quantized model
carries an exactness report (:func:`exactness_report`): the max absolute
prediction delta vs the f32 tables on a calibration batch (caller-provided
or synthesized around the table's own thresholds, where routing flips
live). A delta past the tolerance REFUSES compilation with a typed
``quantize_refused`` event and :class:`QuantizationError` — a model that
quantizes badly must fail at publish time, never drift silently under
traffic.

The dispatch path mirrors ``serving.traversal``: one jitted program per
(model, bucket), compile-noted under the SAME ``serving_traverse`` entry
(distinct key element ``"int8"``) so the registry's zero-new-compile-keys
audit covers quantized models unchanged. The f64 CPU exactness contract
does NOT extend here — quantized models are ``exact=False`` everywhere,
with the report quantifying the divergence instead of hiding it.
"""

from __future__ import annotations

import contextlib
import dataclasses
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from mpitree_tpu.obs import REGISTRY
from mpitree_tpu.serving.traversal import _NOTE_LOCK

# int8 delta grid: 254 steps across the channel span, symmetric around 0
# (the -128 code is unused so dequant never needs an asymmetric clamp).
_Q_STEPS = 254.0
_Q_LO = -127

# The one quantized-mode spelling ``compile_model(quantize=)`` accepts
# (beyond the off-values None/False/"off"/"0"/"none").
QUANTIZE_MODES = ("int8",)


class QuantizationError(ValueError):
    """Exactness refusal: the quantized tables' max prediction delta on
    the calibration batch exceeded the tolerance. Carries the full
    report so the publish site (and the typed ``quantize_refused``
    event) can say exactly how far off it was."""

    def __init__(self, message: str, *, report: dict):
        super().__init__(message)
        self.report = report


def resolve_quantize(mode) -> str | None:
    """Normalize a ``quantize=`` argument / knob value to ``"int8"`` or
    None. Unknown spellings are loud — a typo'd mode silently serving
    f32 would defeat the capacity planning built on it."""
    if mode in (None, False, "", "off", "0", "none"):
        return None
    if mode in QUANTIZE_MODES or mode is True:
        return "int8"
    raise ValueError(
        f"unknown serving quantize mode {mode!r} (expected one of "
        f"{QUANTIZE_MODES} or an off-value)"
    )


def prepare_channel(kind: str, flat: np.ndarray) -> np.ndarray:
    """Per-kind host f64 value transform applied BEFORE quantization.

    ``forest_proba`` rows normalize here (the f32 tier normalizes inside
    the per-tree loop): probabilities span [0, 1], so the int8 grid
    resolves ~0.004 per channel instead of being wasted on raw-count
    dynamic range — and the quantized accumulation for every kind
    becomes the same plain row sum."""
    flat = np.asarray(flat, np.float64).reshape(flat.shape[0], -1)
    if kind == "forest_proba":
        return flat / np.maximum(flat.sum(axis=1, keepdims=True), 1.0)
    return flat


def affine_int8(prepared: np.ndarray):
    """(M, K) prepared f64 channel -> (q int8, scale f32, base f32).

    Per-channel affine: ``q = round((v - lo)/scale) + _Q_LO``,
    ``dequant = base + q*scale`` with ``base = lo - _Q_LO*scale``.
    Constant channels get scale 0 and dequant exactly to their value."""
    lo = prepared.min(axis=0)
    hi = prepared.max(axis=0)
    span = hi - lo
    scale = np.where(span > 0, span / _Q_STEPS, 1.0)
    q = np.clip(
        np.rint((prepared - lo[None, :]) / scale[None, :]) + _Q_LO,
        -127, 127,
    ).astype(np.int8)
    scale = np.where(span > 0, scale, 0.0).astype(np.float32)
    base = (lo - _Q_LO * scale).astype(np.float32)
    # Constant channels: scale 0 makes dequant = base = the value.
    base = np.where(span > 0, base, lo).astype(np.float32)
    return q, scale, base


def dequantize(q: np.ndarray, scale: np.ndarray,
               base: np.ndarray) -> np.ndarray:
    """Host f32 dequant — the numpy twin of the in-program dequant (same
    ops, same order) the exactness report and the kernel-tier value
    blocks read."""
    return (base[None, :]
            + q.astype(np.float32) * scale[None, :]).astype(np.float32)


def quantize_thresholds(threshold: np.ndarray) -> np.ndarray:
    """f32 thresholds -> bf16, rounded toward -inf (leaf NaNs
    neutralized like the kernel tables — they never route).

    FLOOR rounding is load-bearing, not a style choice. The descent
    compares ``x <= thr``; a rounded threshold ``t_q != thr`` misroutes
    exactly the x in the open-closed gap between them. Rounding DOWN
    puts that gap at ``(t_q, thr]`` with ``t_q`` the largest bf16 value
    <= thr — an interval that by construction contains NO bf16 lattice
    point. Hence the theorem the synthesized calibration (and the
    routing property test) rides: every query whose features are bf16
    values routes IDENTICALLY to the f32 tables; only sub-bf16-ulp query
    detail can reroute, which a full-precision calibration batch
    honestly measures. Round-to-nearest would instead put the lattice
    point ``t_q`` itself inside the gap — reroutes on essentially every
    real model."""
    t = np.nan_to_num(np.asarray(threshold, np.float32), nan=0.0)
    q = t.astype(jnp.bfloat16)
    qf = q.astype(np.float32)
    bits = q.view(np.uint16).copy()
    over = qf > t  # rounded up: step down one bf16 ulp
    bits[over & (qf > 0)] -= 1
    bits[over & (qf < 0)] += 1
    # q == +/-0 but t < 0: next below zero is the smallest-magnitude
    # negative bf16.
    bits[over & (qf == 0)] = np.uint16(0x8001)
    return bits.view(jnp.bfloat16)


@dataclasses.dataclass
class QuantizedState:
    """Device-resident quantized model state (built once at compile)."""

    feature: jax.Array    # (M,) int16
    threshold: jax.Array  # (M,) bf16
    left: jax.Array       # (M,) int32 (shared with the f32 table)
    right: jax.Array      # (M,) int32
    root: jax.Array       # (T,) int32
    qvals: jax.Array      # (M, K) int8
    vscale: jax.Array     # (K,) f32
    vbase: jax.Array      # (K,) f32
    report: dict          # the exactness report recorded in serve_report_
    rows_host: np.ndarray  # (M, K) f32 dequantized, flat-table order
    q_host: np.ndarray     # (M, K) int8 raw lattice, flat-table order

    def _per_tree(self, flat: np.ndarray, trees, table) -> dict:
        """Invert the flat table's depth-pack scatter: ``id(tree) ->
        (n_nodes, K)`` rows in per-tree node order."""
        order = table.scatter_order()
        concat = np.empty_like(flat)
        concat[order] = flat
        offs = np.cumsum([0] + [t.n_nodes for t in trees])
        return {
            id(t): concat[offs[i]:offs[i + 1]]
            for i, t in enumerate(trees)
        }

    def rows_per_tree(self, trees, table) -> dict:
        """Dequantized f32 value rows per tree (host oracle / debugging
        view of what the tiers serve)."""
        return self._per_tree(self.rows_host, trees, table)

    def q_rows_per_tree(self, trees, table) -> dict:
        """RAW int8 lattice rows per tree — what the Pallas tier's value
        blocks store. The kernel accumulates the integer lattice and the
        dispatch applies the affine once at the end (the affine is
        linear across the ensemble sum), so the kernel serves exactly
        the int8-affine values the XLA quantized tier serves and the
        exactness report covers both."""
        return self._per_tree(self.q_host, trees, table)


def build_state(table, prepared: np.ndarray, *, kind: str, scale,
                n_steps: int, tol: float, calibration=None,
                n_features: int | None = None) -> QuantizedState:
    """Quantize one flat table + prepared channel; refuse past ``tol``.

    ``table``: a ``serving.tables.NodeTable`` (its cached int32
    left/right/root device copies are SHARED — quantization must not
    double-pin them). Raises :class:`QuantizationError` when the
    calibration delta exceeds ``tol``."""
    if n_features is None:
        n_features = int(table.feature.max(initial=0)) + 1
    if n_features > np.iinfo(np.int16).max:
        raise QuantizationError(
            f"int16 feature ids cannot address {n_features} features",
            report={"ok": False, "reason": "n_features"},
        )
    q, vscale, vbase = affine_int8(prepared)
    thr_q = quantize_thresholds(table.threshold)
    rep = exactness_report(
        table, prepared, (q, vscale, vbase), kind=kind,
        scale=scale, n_steps=n_steps, tol=tol,
        calibration=calibration, n_features=n_features,
    )
    if not rep["ok"]:
        raise QuantizationError(
            f"quantized tables diverge past tolerance: max prediction "
            f"delta {rep['max_abs_delta']:.3e} > {tol:.3e} on "
            f"{rep['rows']} calibration rows",
            report=rep,
        )
    _f, _t, left_d, right_d, root_d, _o = table.dev_arrays()
    return QuantizedState(
        feature=jax.device_put(table.feature.astype(np.int16)),
        threshold=jax.device_put(thr_q),
        left=left_d, right=right_d, root=root_d,
        qvals=jax.device_put(q),
        vscale=jax.device_put(vscale),
        vbase=jax.device_put(vbase),
        report=rep,
        rows_host=dequantize(q, vscale, vbase),
        q_host=q,
    )


# ---------------------------------------------------------------------------
# host reference (numpy) — the exactness oracle
# ---------------------------------------------------------------------------

def _host_descend(X, feature, threshold, left, right, root,
                  n_steps: int) -> np.ndarray:
    """(N, T) absolute leaf ids — the numpy twin of the unrolled descent
    (rows parked on leaves hold their id; children never read at -1)."""
    node = np.broadcast_to(
        root[None, :].astype(np.int64), (len(X), len(root))
    ).copy()
    for _ in range(n_steps):
        f = feature[node]
        thr = threshold[node]
        xf = np.take_along_axis(X, np.maximum(f, 0).astype(np.int64), axis=1)
        nxt = np.where(xf <= thr, left[node], right[node])
        node = np.where(f < 0, node, nxt)
    return node


def _host_apply(kind: str, node: np.ndarray, rows: np.ndarray,
                scale: float, n_out: int) -> np.ndarray:
    """Apply a prepared f32 channel at leaf ids, per serving kind —
    BASELINE-FREE for margins (the baseline is identical on both sides
    of the delta and cancels)."""
    N, T = node.shape
    if kind == "margin":
        K = int(n_out)
        acc = np.zeros((N, K), np.float32)
        for r in range(T // K):
            ids = node[:, r * K:(r + 1) * K]
            acc = acc + rows[ids, 0]
        return acc
    if kind == "gather_value":
        return rows[node[:, 0], 0:1]
    acc = np.zeros((N, rows.shape[1]), np.float32)
    for t in range(T):
        acc = acc + rows[node[:, t]]
    if kind == "forest_mean":
        acc = acc[:, 0:1]
    return acc / np.float32(scale)


def synthesize_calibration(table, n_features: int, rows: int = 256,
                           seed: int = 0) -> np.ndarray:
    """A deterministic calibration batch when the caller has no data:
    per-feature uniform draws spanning (and 10% past) that feature's own
    threshold range, SNAPPED to the bf16 lattice. On-lattice rows route
    identically through the floor-rounded thresholds (see
    :func:`quantize_thresholds`), so the default report isolates VALUE
    quantization error — the quantity the tolerance gate is calibrated
    for. Sub-ulp routing sensitivity is a property of the caller's real
    query distribution; measuring it honestly needs the caller's own
    full-precision ``calibration`` batch. Features the table never
    splits on get [0, 1] (they route nothing)."""
    rng = np.random.default_rng(seed)
    lo = np.zeros(n_features, np.float64)
    hi = np.ones(n_features, np.float64)
    inner = table.feature >= 0
    for f in range(n_features):
        thrs = table.threshold[inner & (table.feature == f)]
        if thrs.size:
            t_lo, t_hi = float(thrs.min()), float(thrs.max())
            pad = 0.1 * max(t_hi - t_lo, 1.0)
            lo[f], hi[f] = t_lo - pad, t_hi + pad
    X = rng.uniform(lo, hi, size=(rows, n_features)).astype(np.float32)
    return X.astype(jnp.bfloat16).astype(np.float32)


def exactness_report(table, prepared: np.ndarray, quant, *, kind: str,
                     scale, n_steps: int, tol: float, calibration=None,
                     n_features: int | None = None) -> dict:
    """Max prediction delta of the quantized tables vs the f32 tables on
    a calibration batch (numpy on both sides — same descent, same value
    application, so the delta isolates QUANTIZATION, not tier noise)."""
    q, vscale, vbase = quant
    if n_features is None:
        n_features = int(table.feature.max(initial=0)) + 1
    X = (np.ascontiguousarray(np.asarray(calibration, np.float32))
         if calibration is not None
         else synthesize_calibration(table, n_features))
    rows_ref = np.asarray(prepared, np.float32)
    rows_q = dequantize(q, np.asarray(vscale), np.asarray(vbase))
    thr_ref = np.nan_to_num(
        np.asarray(table.threshold, np.float32), nan=0.0
    )
    thr_q = np.asarray(
        quantize_thresholds(table.threshold), np.float32
    )
    n_out = rows_ref.shape[1]
    ids_ref = _host_descend(
        X, table.feature, thr_ref, table.left, table.right, table.root,
        n_steps,
    )
    ids_q = _host_descend(
        X, table.feature, thr_q, table.left, table.right, table.root,
        n_steps,
    )
    ref = _host_apply(kind, ids_ref, rows_ref, float(scale), n_out)
    got = _host_apply(kind, ids_q, rows_q, float(scale), n_out)
    max_abs = float(np.max(np.abs(ref - got))) if len(X) else 0.0
    denom = float(np.max(np.abs(ref))) if len(X) else 0.0
    return {
        "mode": "int8",
        "max_abs_delta": max_abs,
        "max_rel_delta": round(max_abs / denom, 6) if denom > 0 else 0.0,
        "rows": int(len(X)),
        "rerouted_rows": int((ids_ref != ids_q).any(axis=1).sum()),
        "tolerance": float(tol),
        "ok": bool(max_abs <= tol),
    }


# ---------------------------------------------------------------------------
# the jitted quantized traversal (the XLA tier's compressed twin)
# ---------------------------------------------------------------------------

def _descend_q(X, feature, threshold, left, right, root, n_steps: int):
    """The unrolled descent over compressed columns: int16 feature ids
    and bf16 thresholds upcast in-program (both upcasts exact), children
    int32 as ever. Same clip-mode gathers, same leaf-hold rule as
    ``traversal._descend``."""
    node = jnp.broadcast_to(
        root[None, :], (X.shape[0], root.shape[0])
    ).astype(jnp.int32)
    for _ in range(n_steps):
        f = jnp.take(feature, node, mode="clip").astype(jnp.int32)
        thr = jnp.take(threshold, node, mode="clip").astype(jnp.float32)
        xf = jnp.take_along_axis(X, jnp.maximum(f, 0), axis=1)
        nxt = jnp.where(
            xf <= thr,
            jnp.take(left, node, mode="clip"),
            jnp.take(right, node, mode="clip"),
        )
        node = jnp.where(f < 0, node, nxt)
    return node


def _dequant_rows(qvals, ids, vscale, vbase):
    """Gather int8 rows at ``ids`` then dequant the GATHERED slice (the
    full-table dequant would materialize the f32 table this module
    exists to avoid pinning)."""
    g = jnp.take(qvals, ids, axis=0, mode="clip").astype(jnp.float32)
    return vbase[None, :] + g * vscale[None, :]


@partial(
    jax.jit,
    static_argnames=("kind", "n_steps"),
    donate_argnums=(6,),
)
def q_traverse_accumulate(X, feature, threshold, left, right, root, acc0,
                          qvals, vscale, vbase, scale, *, kind: str,
                          n_steps: int):
    """Descent + dequantized sequential ensemble reduction into the
    donated ``acc0`` (same caller contract as
    ``traversal.traverse_accumulate``: acc0 is staged fresh per
    dispatch). Channels arrive PREPARED (forest count rows normalized at
    build), so every kind reduces to a plain dequantized row sum."""
    node = _descend_q(X, feature, threshold, left, right, root, n_steps)
    if kind == "margin":
        N, K = acc0.shape
        rounds = node.shape[1] // K

        def mbody(r, raw):
            ids = lax.dynamic_slice(node, (0, r * K), (N, K))
            g = jnp.take(qvals[:, 0], ids, mode="clip").astype(jnp.float32)
            return raw + vbase[0] + g * vscale[0]

        return lax.fori_loop(0, rounds, mbody, acc0)
    if kind == "forest_mean":
        def vbody(t, acc):
            ids = jnp.take(node, t, axis=1, mode="clip")
            g = jnp.take(qvals[:, 0], ids, mode="clip").astype(jnp.float32)
            return acc + (vbase[0] + g * vscale[0])[:, None]

        return lax.fori_loop(0, node.shape[1], vbody, acc0) / scale
    if kind not in ("forest_proba", "forest_values"):
        raise ValueError(f"unknown quantized accumulate kind {kind!r}")

    def body(t, acc):
        ids = jnp.take(node, t, axis=1, mode="clip")
        return acc + _dequant_rows(qvals, ids, vscale, vbase)

    return lax.fori_loop(0, node.shape[1], body, acc0) / scale


@partial(jax.jit, static_argnames=("n_steps",))
def q_traverse_gather(X, feature, threshold, left, right, root, qvals,
                      vscale, vbase, *, n_steps: int):
    """Single-tree float channel: descend, gather int8, dequant."""
    node = _descend_q(X, feature, threshold, left, right, root, n_steps)
    g = jnp.take(qvals[:, 0], node[:, 0], mode="clip").astype(jnp.float32)
    return vbase[0] + g * vscale[0]


def dispatch(Xp, state: QuantizedState, *, kind: str, n_steps: int,
             acc0=None, scale=None, obs=None):
    """One quantized request-path dispatch — the compile-note/attribution
    twin of ``traversal.dispatch``, keyed under the SAME
    ``serving_traverse`` entry (distinct ``"int8"`` element) so the
    zero-new-compile-keys audit spans both table forms."""
    key = (
        kind, n_steps, "int8", Xp.shape,
        state.qvals.shape, state.root.shape,
        None if acc0 is None else acc0.shape,
    )
    with _NOTE_LOCK:
        if obs is not None:
            fresh = obs.compile_note("serving_traverse", key, cache_size=64)
        else:
            fresh = REGISTRY.note("serving_traverse", key, cache_size=64)

    def run():
        if kind == "gather_value":
            return q_traverse_gather(
                Xp, state.feature, state.threshold, state.left,
                state.right, state.root, state.qvals, state.vscale,
                state.vbase, n_steps=n_steps,
            )
        return q_traverse_accumulate(
            Xp, state.feature, state.threshold, state.left, state.right,
            state.root, acc0, state.qvals, state.vscale, state.vbase,
            scale, kind=kind, n_steps=n_steps,
        )

    attr = (
        obs.compile_attribution("serving_traverse", fresh)
        if obs is not None else contextlib.nullcontext()
    )
    with attr:
        out = run()
        if fresh and obs is not None:
            # Compute ledger (obs/cost.py): price the fresh int8 bucket
            # once; the warm request path never reaches this branch.
            if kind == "gather_value":
                obs.price_compile(
                    "serving_traverse",
                    lambda: q_traverse_gather.lower(
                        Xp, state.feature, state.threshold, state.left,
                        state.right, state.root, state.qvals,
                        state.vscale, state.vbase, n_steps=n_steps,
                    ),
                )
            else:
                obs.price_compile(
                    "serving_traverse",
                    lambda: q_traverse_accumulate.lower(
                        Xp, state.feature, state.threshold, state.left,
                        state.right, state.root, acc0, state.qvals,
                        state.vscale, state.vbase, scale, kind=kind,
                        n_steps=n_steps,
                    ),
                )
        return out
