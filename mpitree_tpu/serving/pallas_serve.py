"""Pallas (Mosaic) serving kernel — VMEM-resident ensemble traversal.

The XLA traversal (``serving.traversal``) re-reads the node table from HBM
at every descent step of every batch; for the small/medium tables that
production serving actually pins (a few thousand nodes), the whole table
fits in VMEM. This kernel keeps one tree's table block resident across a
batch tile's full descent and accumulates the ensemble reduction into a
persistent output block — the table crosses HBM→VMEM once per (tile,
tree), not once per step.

TPU Mosaic has no vectorized dynamic gather, so — like the histogram
kernel (``ops/pallas_hist.py``) — the per-step node lookup is reformulated
as a dense one-hot contraction on the MXU::

    props[r, c] = sum_m  onehot(node[r]) [r, m] * table[m, c]

with the per-row feature-value pick ``x[r, feature[r]]`` as a one-hot
row-reduction on the VPU. The kernel uses the per-tree STACKED layout
(``(T, Mp)`` blocks, roots at 0) rather than the flat table: each grid
step owns one tree's block, whose ids are tree-relative — exactly the
shape Mosaic's block slicing wants.

Grid: ``(row_tiles, T)`` — trees innermost, so the (Rt, K) output block
persists in VMEM while the ensemble accumulates (the same
constant-index-map idiom as ``pallas_hist``). Aggregation is f32 (the
accelerator serving dtype); the CPU f64 exactness contract stays with the
XLA tier. Selection lives in :func:`resolve_serving_kernel` — same policy
shape as ``resolve_wide_hist``/``resolve_hist_subtraction``: the env var
steers "auto", a forced ``pallas`` falls back GRACEFULLY (typed
``serving_pallas_fallback`` obs event) when the backend or the VMEM fit
can't satisfy it — serving must degrade, never die, on a policy mismatch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from mpitree_tpu.obs import memory as memory_lib
from mpitree_tpu.ops.pallas_hist import _round_up, pallas_available
from mpitree_tpu.config import knobs
from mpitree_tpu.serving import quantize as quantize_lib


def _traverse_kernel(x_ref, tbl_ref, val_ref, out_ref, *, n_steps,
                     agg, n_out, kv, quantized=False):
    """One grid step: descend one row tile through one tree, accumulate.

    x_ref   : (Rt, Fp) f32 — query rows (features padded to Fp).
    tbl_ref : (1, 8, Mp) f32 — this tree's (feature, threshold, left,
              right, pad...) rows, node axis on lanes; pad nodes carry
              feature = -1 (leaves). Quantized tier: bf16 with the
              SPLIT-BYTE id layout (``build_kernel_tables_quantized``) —
              bf16's 8-bit mantissa can't hold ids past 256 exactly, so
              each id rides as an exact (lo, hi) byte pair recombined
              ``hi*256 + lo`` after the contraction (both bytes and the
              recombined id are integers < 2^24, exact in f32).
    val_ref : (1, Kvp, Mp) f32 — this tree's leaf-value channels. The
              quantized tier stores the RAW int8 lattice instead: the
              leaf selection contracts int8 x int8 into an exact int32,
              the f32 out block accumulates integer q-sums, and the
              caller applies the affine dequant ONCE after the kernel
              (it is linear across the ensemble sum) — a 4x smaller
              resident value block with zero added error.
    out_ref : (Rt, Kop) f32 — ensemble accumulation (persists over T).
    """
    Rt, Fp = x_ref.shape
    Mp = tbl_ref.shape[2]
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    tbl = tbl_ref[0]  # (8, Mp)
    x = x_ref[...]
    m_iota = jax.lax.broadcasted_iota(jnp.int32, (Rt, Mp), 1)
    f_iota = jax.lax.broadcasted_iota(jnp.int32, (Rt, Fp), 1)
    node = jnp.zeros((Rt,), jnp.int32)  # stacked layout: every root is 0
    for _ in range(n_steps):
        onehot = (node[:, None] == m_iota).astype(tbl.dtype)
        # HIGHEST precision on both contractions: the MXU's default
        # truncates the f32 table operand to bf16, which corrupts child
        # ids above 256 and rounds thresholds — silent misrouting on
        # exactly the real-TPU tier this kernel exists for. Cheap: the
        # one-hot operand is exact 0/1 either way. (The quantized tier's
        # operands are bf16 BY CONSTRUCTION — every stored value is a
        # byte or a bf16 threshold, so the selection is still exact.)
        props = jax.lax.dot_general(
            onehot, tbl,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (Rt, 8): feature, threshold, left, right, pad
        if quantized:
            f = (props[:, 4] * 256.0 + props[:, 0]).astype(jnp.int32)
            thr = props[:, 1]
            left = props[:, 5] * 256.0 + props[:, 2]
            right = props[:, 6] * 256.0 + props[:, 3]
        else:
            f = props[:, 0].astype(jnp.int32)
            thr, left, right = props[:, 1], props[:, 2], props[:, 3]
        xf = jnp.sum(
            jnp.where(f[:, None] == f_iota, x, 0.0), axis=1
        )
        nxt = jnp.where(xf <= thr, left, right)
        node = jnp.where(f < 0, node, nxt.astype(jnp.int32))
    if quantized:
        # int8 one-hot x int8 lattice -> int32: exact by construction
        # (one nonzero per row, |q| <= 127), no precision knob needed.
        vals = jax.lax.dot_general(
            (node[:, None] == m_iota).astype(jnp.int8), val_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)  # (Rt, Kvp) raw q
    else:
        onehot = (node[:, None] == m_iota).astype(val_ref.dtype)
        vals = jax.lax.dot_general(
            onehot, val_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (Rt, Kvp)
    if agg == "norm":
        # Per-tree normalized count rows (forest predict_proba): the pad
        # channels are zero, so the kv-wide row sum is the true one.
        rowsum = jnp.sum(vals[:, :kv], axis=1, keepdims=True)
        out_ref[...] += vals / jnp.maximum(rowsum, 1.0)
    elif agg == "percls":
        # Boosting: tree t contributes its single value channel to margin
        # column t mod K (trees are laid out round-major, class-minor).
        col = jax.lax.rem(t, n_out)
        k_iota = jax.lax.broadcasted_iota(jnp.int32, (Rt, out_ref.shape[1]), 1)
        out_ref[...] += vals[:, 0][:, None] * (k_iota == col).astype(
            jnp.float32
        )
    else:  # "sum"
        out_ref[...] += vals


@functools.partial(
    jax.jit,
    static_argnames=("n_steps", "agg", "n_out", "kv", "row_tile",
                     "interpret", "quantized"),
)
def traverse_batch_pallas(X, tables, values, *, n_steps: int, agg: str,
                          n_out: int, kv: int, row_tile: int = 256,
                          interpret: bool = False, quantized: bool = False):
    """(N, F) rows + stacked per-tree tables -> (N, n_out) f32 aggregate.

    ``tables``: (T, 8, Mp) f32 (property axis sublane-padded, nodes on
    lanes); ``values``: (T, Kvp, Mp) f32 — both built by
    :func:`build_kernel_tables`. ``quantized=True`` serves the bf16
    split-byte tables (:func:`build_kernel_tables_quantized`) + RAW
    int8 lattice value blocks; the returned aggregate is then the
    integer q-sum and the CALLER owns the affine dequant (one
    elementwise op — linear across the ensemble sum). Tables halve,
    values quarter, one-hots ride bf16/int8 — the VMEM tier stretches
    past 2x the ensemble. ``interpret=True`` runs the Pallas
    interpreter (the CPU parity tests); on hardware the caller gates on
    :func:`fits_vmem`.
    """
    N, F = X.shape
    T, _, Mp = tables.shape
    Kop = values.shape[1] if agg != "percls" else n_out
    Np = _round_up(max(N, 1), row_tile)
    Fp = _round_up(max(F, 1), 8)
    Xp = jnp.pad(X.astype(jnp.float32), ((0, Np - N), (0, Fp - F)))
    out = pl.pallas_call(
        functools.partial(
            _traverse_kernel, n_steps=n_steps, agg=agg, n_out=n_out, kv=kv,
            quantized=quantized,
        ),
        # Trees innermost (TPU grids iterate the last axis fastest): each
        # row tile's out block accumulates across the full ensemble before
        # the grid advances to the next tile.
        grid=(Np // row_tile, T),
        in_specs=[
            pl.BlockSpec((row_tile, Fp), lambda i, t: (i, 0)),
            pl.BlockSpec((1, 8, Mp), lambda i, t: (t, 0, 0)),
            pl.BlockSpec((1, values.shape[1], Mp), lambda i, t: (t, 0, 0)),
        ],
        out_specs=pl.BlockSpec((row_tile, Kop), lambda i, t: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Np, Kop), jnp.float32),
        interpret=interpret,
    )(Xp, tables, values)
    return out[:N, :n_out]


def build_kernel_tables(trees) -> tuple:
    """Stacked per-tree kernel layout: ((T, 8, Mp) f32, Mp).

    Node ids are tree-relative (roots at 0) and live on the LANE axis
    (``Mp`` rounds to the 128-lane boundary the one-hot contraction
    wants); the property axis pads to the 8-sublane tile. Pad nodes carry
    feature = -1 so descent holds on them like any leaf.
    """
    T = len(trees)
    Mp = _round_up(max(t.n_nodes for t in trees), 128)
    tbl = np.zeros((T, 8, Mp), np.float32)
    tbl[:, 0, :] = -1.0
    for i, t in enumerate(trees):
        m = t.n_nodes
        tbl[i, 0, :m] = np.asarray(t.feature, np.float32)
        # Leaf thresholds are NaN in TreeArrays; the one-hot CONTRACTION
        # would propagate them (0 * nan = nan) into every row's props, so
        # leaves store a neutral 0.0 — they never route anyway.
        tbl[i, 1, :m] = np.nan_to_num(
            np.asarray(t.threshold, np.float32), nan=0.0
        )
        tbl[i, 2, :m] = np.maximum(np.asarray(t.left, np.float32), 0.0)
        tbl[i, 3, :m] = np.maximum(np.asarray(t.right, np.float32), 0.0)
    return tbl, Mp


def build_kernel_values(trees, channel_fn, kv: int,
                        dtype=np.float32) -> np.ndarray:
    """(T, Kvp, Mp) leaf-value channels (channels padded to the
    8-sublane tile, node axis on lanes). The quantized tier passes
    ``dtype=jnp.bfloat16`` — value blocks halve alongside the tables."""
    T = len(trees)
    Mp = _round_up(max(t.n_nodes for t in trees), 128)
    kvp = _round_up(max(kv, 1), 8)
    vals = np.zeros((T, kvp, Mp), dtype)
    for i, t in enumerate(trees):
        ch = np.asarray(channel_fn(t), np.float32).reshape(t.n_nodes, -1)
        vals[i, : ch.shape[1], : t.n_nodes] = ch.T.astype(dtype)
    return vals


# Split-byte id ceiling: (lo, hi) byte pairs recombine to hi*256 + lo,
# so tree-relative node ids must fit two bytes.
QUANTIZED_KERNEL_MAX_NODES = 65536


def build_kernel_tables_quantized(trees) -> tuple:
    """Stacked bf16 kernel layout with split-byte ids: ((T, 8, Mp), Mp).

    bf16 holds every integer in [0, 256] exactly but nothing certain past
    it, so feature/left/right ids each ride as an exact byte pair::

        row 0: feature lo   row 4: feature hi
        row 2: left lo      row 5: left hi
        row 3: right lo     row 6: right hi
        row 1: threshold (bf16 — the SAME rounding the XLA quantized
               tier compares against, so the tiers route identically)

    Leaves/pad keep the ``feature = -1`` hold marker as (lo=-1, hi=0).
    Requires ``n_nodes < QUANTIZED_KERNEL_MAX_NODES`` — the resolver
    refuses larger tables back to the XLA tier.
    """
    T = len(trees)
    n_max = max(t.n_nodes for t in trees)
    if n_max >= QUANTIZED_KERNEL_MAX_NODES:
        raise ValueError(
            f"split-byte kernel ids cap at {QUANTIZED_KERNEL_MAX_NODES} "
            f"nodes per tree (got {n_max})"
        )
    Mp = _round_up(n_max, 128)
    tbl = np.zeros((T, 8, Mp), jnp.bfloat16)
    tbl[:, 0, :] = -1.0
    for i, t in enumerate(trees):
        m = t.n_nodes
        f = np.asarray(t.feature, np.int32)
        lo = np.where(f < 0, -1, f % 256)
        tbl[i, 0, :m] = lo.astype(np.float32)
        tbl[i, 4, :m] = np.maximum(f // 256, 0).astype(np.float32)
        tbl[i, 1, :m] = quantize_lib.quantize_thresholds(t.threshold)
        for prop, (lo_row, hi_row) in (("left", (2, 5)), ("right", (3, 6))):
            c = np.maximum(np.asarray(getattr(t, prop), np.int32), 0)
            tbl[i, lo_row, :m] = (c % 256).astype(np.float32)
            tbl[i, hi_row, :m] = (c // 256).astype(np.float32)
    return tbl, Mp


# Conservative VMEM ceiling (same stance as pallas_hist): the persistent
# out block + one tree's table/value blocks + the one-hot working set.
# The arithmetic lives in obs.memory (ISSUE 12: the serving capacity
# planner and this kernel gate read ONE pricing source — pinned equal to
# the pre-refactor loop); this module keeps thin delegates so kernel
# callers and the policy below stay import-stable.
_VMEM_BUDGET_BYTES = memory_lib.SERVE_VMEM_BUDGET_BYTES


def kernel_row_tile(n_nodes_max: int, n_features: int, kv: int,
                    n_out: int, quantized: bool = False) -> int | None:
    """Largest row tile whose working set fits the VMEM budget, or None."""
    return memory_lib.serve_kernel_row_tile(
        n_nodes_max, n_features, kv, n_out, budget=_VMEM_BUDGET_BYTES,
        quantized=quantized,
    )


def fits_vmem(n_nodes_max: int, n_features: int, kv: int,
              n_out: int, quantized: bool = False) -> bool:
    return kernel_row_tile(
        n_nodes_max, n_features, kv, n_out, quantized
    ) is not None


def resolve_serving_kernel(platform: str, *, n_nodes_max: int,
                           n_features: int, kv: int, n_out: int,
                           quantized: bool = False, obs=None) -> bool:
    """Whether the fused serving path runs the Mosaic kernel.

    Policy shape mirrors ``resolve_wide_hist``: ``MPITREE_TPU_SERVING_
    KERNEL`` is "auto" (kernel on real TPUs whose table fits VMEM — there
    the XLA tier is f32 too, so the tiers differ only in where the table
    lives), "xla" (off everywhere), or "pallas" (forced). Unlike the wide
    kernel's loud force-failure, an unsatisfiable force here degrades
    GRACEFULLY to the XLA tier with a typed ``serving_pallas_fallback``
    event: a serving stack must answer the request, not die, when a model
    outgrows VMEM or fails over to a f64-capable host.
    """
    flag = knobs.value("MPITREE_TPU_SERVING_KERNEL")
    if flag == "xla":
        return False
    if flag not in ("auto", "pallas"):
        raise ValueError(f"unknown MPITREE_TPU_SERVING_KERNEL {flag!r}")
    ok = pallas_available(platform)
    if flag == "auto":
        # Evidence consultation (obs/advisor.py, ISSUE 18): stored
        # serving sections on this platform — grouped by the kernel each
        # run resolved — may override the tier preference. The VMEM fit
        # and node-id cap below stay hard constraints: a "pallas"
        # verdict still needs the table to fit; an "xla" verdict turns
        # the kernel off outright.
        from mpitree_tpu.obs import advisor

        adv = advisor.advise_serving_kernel(
            platform=platform,
            shape={"n_features": int(n_features)},
        )
        advisor.record_advice(obs, adv)
        if adv is not None and adv["value"] == "xla":
            return False
    # The quantized tier's split-byte ids cap a tree at 65536 nodes; a
    # bigger table refuses back to XLA like a VMEM overflow would.
    ids_ok = (not quantized
              or n_nodes_max < QUANTIZED_KERNEL_MAX_NODES)
    fits = ids_ok and fits_vmem(
        n_nodes_max, n_features, kv, n_out, quantized
    )
    if flag == "pallas" and not (ok and fits):
        why = ("needs a TPU backend" if not ok
               else "split-byte ids cap at 65536 nodes/tree"
               if not ids_ok
               else "table working set exceeds the VMEM budget")
        if obs is not None:
            obs.event(
                "serving_pallas_fallback",
                f"MPITREE_TPU_SERVING_KERNEL=pallas: {why} "
                f"(platform={platform!r}, nodes={n_nodes_max}); serving "
                "the XLA traversal tier instead",
            )
        return False
    return ok and fits
