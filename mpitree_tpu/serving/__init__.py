"""mpitree_tpu.serving — compiled batched inference (ISSUE 7, ROADMAP 1).

Everything before this subsystem optimized ``fit``; a system serving
millions of users lives or dies on ``predict``. The serving stack:

- **tables** — fitted trees/ensembles flatten into depth-packed
  structure-of-arrays node tables (one flat id space, level slabs,
  true-depth step counts) with cached device residency;
- **traversal** — ONE jitted gather program per (model, batch-bucket):
  descent unrolled to the table's true depth, leaf-value application
  fused in, ensemble aggregation bit-identical to the estimators' host
  float64 semantics on CPU backends;
- **pallas_serve** — optional Mosaic tier keeping small/medium tables
  VMEM-resident (``MPITREE_TPU_SERVING_KERNEL``, graceful typed-event
  fallback);
- **model** — :func:`compile_model` / :class:`CompiledModel`: the
  estimator-equivalent predict surface plus ``serve_report_``;
- **registry** — named slots with bucket-warmed publish, so swapping a
  freshly trained model never compiles on the request path;
- **scheduler** — EDF continuous batching with admission control and
  QoS classes in front of the registry (ISSUE 17): deadline-heaped
  requests coalesce into the warm bucket shapes, overload sheds with
  typed reject reasons instead of melting every SLO;
- **quantize** — compressed node tables (bf16 thresholds / int16
  feature ids / int8-delta leaf values) behind
  ``compile_model(quantize=)``, with a per-model exactness report that
  REFUSES past tolerance — the Pallas VMEM tier stretches ~2x;
- **staging** — donated double-buffered input staging for streaming.

The estimators' own ensemble predicts ride the same tables:
``ops/predict.stacked_leaf_ids`` descends the cached flat table in one
dispatch and leaves the exact host-side value application untouched.
"""

from mpitree_tpu.serving.model import (
    DEFAULT_BUCKETS,
    CompiledModel,
    compile_model,
)
from mpitree_tpu.serving.pallas_serve import resolve_serving_kernel
from mpitree_tpu.serving.quantize import QuantizationError
from mpitree_tpu.serving.registry import ModelRegistry
from mpitree_tpu.serving.scheduler import (
    QoSClass,
    RejectedRequest,
    Scheduler,
    parse_qos,
)
from mpitree_tpu.serving.staging import StreamStage
from mpitree_tpu.serving.tables import NodeTable, note_serving, tables_for

__all__ = [
    "DEFAULT_BUCKETS",
    "CompiledModel",
    "ModelRegistry",
    "NodeTable",
    "QoSClass",
    "QuantizationError",
    "RejectedRequest",
    "Scheduler",
    "StreamStage",
    "compile_model",
    "note_serving",
    "parse_qos",
    "resolve_serving_kernel",
    "tables_for",
]
