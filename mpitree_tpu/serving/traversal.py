"""Single-dispatch compiled traversal over depth-packed node tables.

The request-path contract (ISSUE 7 / ROADMAP item 1): ONE jitted call per
(model, batch-bucket) — no per-tree Python loop, no per-call device upload
of tree slices, leaf-value application fused into the same program. Three
entry points:

- :func:`flat_leaf_ids` — descent only, returning per-tree RELATIVE leaf
  ids. The estimators' ensemble predict path
  (``ops/predict.stacked_leaf_ids``) rides this so every existing
  host-side value application stays bit-identical while the descent
  becomes a single gather program over the cached flat table.
- :func:`traverse_gather` — descent + a fused leaf-value gather (single
  trees: raw counts, regression means, monotonic labels).
- :func:`traverse_accumulate` — descent + the fused ensemble reduction
  (forest probabilities/means, boosting margins), sequentially
  accumulated into a DONATED carry: the caller stages the (N, K)
  accumulator init host-side (zeros, or the tiled boosting baseline —
  literally what the estimators build host-side) and hands it over;
  the ``lax.fori_loop`` carry aliases that buffer in place, which is
  exactly the donation GL05 asks fused-state programs for. Caller
  contract (GL08): the staged init is single-use — every dispatch
  stages a fresh one (``CompiledModel._dispatch`` and the retry rung
  both rebuild it per attempt). The table/value arrays are deliberately
  NOT donated: they are the cached device-resident model state reused
  by every request — donating them would be the garbage-read bug GL08
  exists to catch.

Descent is an UNROLLED gather sequence: ``n_steps`` is the table's true
ensemble depth (static, small), so the loop is Python-level — each step
is four clip-mode gathers plus a compare, and the step count is the
table's, not the estimator's ``max_depth`` budget.

Exactness: the estimators aggregate leaf values HOST-SIDE in float64 with
a strict sequential per-tree order (``forest.predict_proba``'s ``acc +=``
loop, boosting's ``raw[:, k] += lr * vals``). The fused path reproduces
that bit-for-bit on CPU backends: value channels ride in f64 under a
scoped ``jax.enable_x64`` and the ensemble reduction runs in member
order — same IEEE ops, same order. The legacy-wheel scoped-x64 hazards
are all routed around the way the gbdt engine does (``ops/histogram.py``):
f64 constants enter as f32 exactly converted (:func:`_fconst`), gathers
run clip-mode, and f64 operands are device-put inside the scope.
Accelerator backends have no f64 unit; there the same programs run with
f32 channels (``exact=False`` in the model's ``serve_report_`` — the
documented serving-tier divergence).
"""

from __future__ import annotations

import contextlib
import threading
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from mpitree_tpu.obs import REGISTRY

# Guards the compile-registry bookkeeping below: the process-wide
# REGISTRY's LRU mirror and the per-model obs compile records are plain
# dict read-modify-writes, and the registry's contract is concurrent
# dispatch (possibly across models). The jit CALL itself stays outside
# any lock — executables are thread-safe and must serve concurrently.
_NOTE_LOCK = threading.Lock()


def _fconst(v: float, dtype) -> jax.Array:
    """A scalar constant that lowers under scoped x64 on legacy wheels.

    f64 literals canonicalize to f32 at lowering time there (the
    ``_channel_histogram`` lesson), so constants enter as exact-in-f32
    values converted on device. Callers only pass such values (0, 1,
    small integers)."""
    return jnp.float32(v).astype(dtype)


def _descend(X, feature, threshold, left, right, root, n_steps: int):
    """(N, T) absolute leaf ids — the unrolled lockstep gather descent.

    Rows parked on a leaf (``feature < 0``) keep their node id, so
    ``n_steps`` iterations (the table's true depth) land every row on its
    leaf. All gathers are clip-mode: leaf children are ``-1`` and never
    followed, and clip is the gather mode that lowers everywhere this
    wheel runs (fill-mode gathers mislower under scoped x64).
    """
    node = jnp.broadcast_to(
        root[None, :], (X.shape[0], root.shape[0])
    ).astype(jnp.int32)
    for _ in range(n_steps):
        f = jnp.take(feature, node, mode="clip")
        thr = jnp.take(threshold, node, mode="clip")
        xf = jnp.take_along_axis(X, jnp.maximum(f, 0), axis=1)
        nxt = jnp.where(
            xf <= thr,
            jnp.take(left, node, mode="clip"),
            jnp.take(right, node, mode="clip"),
        )
        node = jnp.where(f < 0, node, nxt)
    return node


@partial(jax.jit, static_argnames=("n_steps",))
def flat_leaf_ids(X, feature, threshold, left, right, root, orig, *,
                  n_steps: int):
    """(N, T) per-tree RELATIVE leaf ids for a query batch.

    One dispatch for the whole table: the absolute descent result maps
    back through ``orig`` so callers (the estimators' host-side value
    application) see exactly the ids the old stacked path produced.
    """
    node = _descend(X, feature, threshold, left, right, root, n_steps)
    return jnp.take(orig, node, mode="clip")


# Aggregation kinds (static trace branch, one lowering per kind):
#   gather_counts — single classification tree: (N, C) raw leaf counts
#                   (the reference's predict_proba quirk), int32 gather.
#   gather_value  — single tree, one value channel: (N,) gather
#                   (f64 regressor means; monotonic classifier labels
#                   ride the same shape with an int32 channel).
#   forest_proba  — per-tree normalized count rows, sequentially
#                   accumulated then divided by T (RandomForestClassifier
#                   .predict_proba's loop, verbatim in f64).
#   forest_mean   — per-tree value column, sequentially accumulated then
#                   divided by T (RandomForestRegressor.predict).
#   margin        — boosting: staged baseline tile + lr * per-round
#                   (N, K) value blocks, in round order (``_staged_raw``'s
#                   accumulation, verbatim in f64).
#   forest_values — per-tree PRE-NORMALIZED value rows, sequentially
#                   accumulated then divided by T. Monotonic-constrained
#                   forest classifiers ride this: the estimator gathers
#                   each tree's clipped class-0 fraction (a per-NODE
#                   quantity — ``clipped_class0``), so the row is final
#                   at build time and the reduction is a pure add; the
#                   forest_proba in-program normalization would re-derive
#                   a DIFFERENT (unclipped) distribution from raw counts.
GATHER_KINDS = ("gather_counts", "gather_value")
ACC_KINDS = ("forest_proba", "forest_mean", "margin", "forest_values")


@partial(jax.jit, static_argnames=("kind", "n_steps"))
def traverse_gather(X, feature, threshold, left, right, root, values, *,
                    kind: str, n_steps: int):
    """Descent + single-tree leaf-value gather; see module docstring."""
    node = _descend(X, feature, threshold, left, right, root, n_steps)
    if kind == "gather_counts":
        return jnp.take(values, node[:, 0], axis=0, mode="clip")
    if kind == "gather_value":
        return jnp.take(values[:, 0], node[:, 0], mode="clip")
    raise ValueError(f"unknown serving gather kind {kind!r}")


def _forest_proba(node, values, acc0, scale):
    one = _fconst(1.0, values.dtype)

    def body(t, acc):
        ids = jnp.take(node, t, axis=1, mode="clip")
        cnt = jnp.take(values, ids, axis=0, mode="clip")
        return acc + cnt / jnp.maximum(
            jnp.sum(cnt, axis=1, keepdims=True), one
        )

    return lax.fori_loop(0, node.shape[1], body, acc0) / scale


def _forest_mean(node, values, acc0, scale):
    def body(t, acc):
        ids = jnp.take(node, t, axis=1, mode="clip")
        return acc + jnp.take(values[:, 0], ids, mode="clip")[:, None]

    return lax.fori_loop(0, node.shape[1], body, acc0) / scale


def _margin(node, values, acc0, scale):
    # ``values`` arrives PRE-SCALED by the learning rate (a host f64
    # multiply at compile time — the same numpy op the estimator applies
    # per gather), so each round is a pure add: a device ``raw + lr *
    # vals`` would contract to an FMA and drift one ulp off the host's
    # separate mul-then-add. ``scale`` is unused here by design.
    del scale
    N, K = acc0.shape
    rounds = node.shape[1] // K

    def body(r, raw):
        ids = lax.dynamic_slice(node, (0, r * K), (N, K))
        return raw + jnp.take(values[:, 0], ids, mode="clip")

    return lax.fori_loop(0, rounds, body, acc0)


def _forest_values(node, values, acc0, scale):
    def body(t, acc):
        ids = jnp.take(node, t, axis=1, mode="clip")
        return acc + jnp.take(values, ids, axis=0, mode="clip")

    return lax.fori_loop(0, node.shape[1], body, acc0) / scale


_ACC_FNS = {
    "forest_proba": _forest_proba,
    "forest_mean": _forest_mean,
    "margin": _margin,
    "forest_values": _forest_values,
}


# acc0 is donated: the fori carry aliases the staged accumulator buffer
# in place (see module docstring for the caller contract — acc0 is a
# fresh host-staged array per dispatch, dead to the caller afterwards).
@partial(
    jax.jit,
    static_argnames=("kind", "n_steps"),
    donate_argnums=(6,),
)
def traverse_accumulate(X, feature, threshold, left, right, root, acc0,
                        values, scale, *, kind: str, n_steps: int):
    """Descent + fused sequential ensemble reduction into ``acc0``."""
    node = _descend(X, feature, threshold, left, right, root, n_steps)
    try:
        fn = _ACC_FNS[kind]
    except KeyError:
        raise ValueError(
            f"unknown serving accumulate kind {kind!r}"
        ) from None
    return fn(node, values, acc0, scale)


def dispatch(X, table_args, values, *, kind: str, n_steps: int,
             acc0=None, scale=None, x64: bool, obs=None):
    """One request-path dispatch: compile-note the cache key, then run.

    ``x64=True`` (CPU exactness mode) enters the scoped ``enable_x64``
    for the call — the same trace context the program compiled under, so
    the cached executable serves it (a context mismatch would silently
    retrace). The key mirrors everything static about the lowering; the
    process-wide compile registry (obs.REGISTRY — the GL02 runtime twin)
    is what the swap-under-load test pins at zero new entries on the
    request path.
    """
    key = (
        kind, n_steps, x64, X.shape,
        values.shape, str(values.dtype),
        # root's (T,) aval: two tables can share total node count M but
        # differ in tree count — jit would retrace while an M-only key
        # claimed a cache hit, silently defeating the zero-compile audit.
        table_args[4].shape,
        None if acc0 is None else acc0.shape,
    )
    with _NOTE_LOCK:
        # ONE registry note per dispatch: obs.compile_note already feeds
        # the process REGISTRY, so calling both would mark the key warm
        # before the record could count it new (and double-count the
        # lowering event).
        if obs is not None:
            fresh = obs.compile_note("serving_traverse", key, cache_size=64)
        else:
            fresh = REGISTRY.note("serving_traverse", key, cache_size=64)

    def run():
        if kind in GATHER_KINDS:
            return traverse_gather(
                X, *table_args, values, kind=kind, n_steps=n_steps
            )
        return traverse_accumulate(
            X, *table_args, acc0, values, scale, kind=kind, n_steps=n_steps
        )

    # Cold-compile attribution (ISSUE 9): a fresh cache key's dispatch
    # wall lands on the 'serving_traverse' entry point — in practice the
    # registry warms every bucket OFF the request path, so request-time
    # attribution staying zero IS the swap-under-load story.
    attr = (
        obs.compile_attribution("serving_traverse", fresh)
        if obs is not None else contextlib.nullcontext()
    )
    def price():
        # Compute ledger (obs/cost.py): price the fresh bucket once, off
        # the warm request path (zero new compile keys there). Called
        # inside the same enable_x64 context ``run`` dispatches under so
        # the lowering hits the cached trace instead of forking a twin.
        if kind in GATHER_KINDS:
            obs.price_compile(
                "serving_traverse",
                lambda: traverse_gather.lower(
                    X, *table_args, values, kind=kind, n_steps=n_steps
                ),
            )
        else:
            obs.price_compile(
                "serving_traverse",
                lambda: traverse_accumulate.lower(
                    X, *table_args, acc0, values, scale, kind=kind,
                    n_steps=n_steps,
                ),
            )

    with attr:
        if x64:
            with jax.enable_x64(True):
                out = run()
                if fresh and obs is not None:
                    price()
                return out
        out = run()
        if fresh and obs is not None:
            price()
        return out
