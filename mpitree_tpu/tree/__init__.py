"""Drop-in import surface matching the reference package layout.

The reference exposes its estimators as
``from mpitree.tree import DecisionTreeClassifier, ParallelDecisionTreeClassifier``
(reference: ``mpitree/tree/__init__.py:1-3``). This module mirrors that path so
reference users can switch with a one-line import change, and additionally
exports the estimators the reference lacks (regressor, forests).
"""

from mpitree_tpu.core.tree_struct import BranchType, Node, TreeArrays
from mpitree_tpu.models.classifier import (
    DecisionTreeClassifier,
    ParallelDecisionTreeClassifier,
)
from mpitree_tpu.models.forest import RandomForestClassifier, RandomForestRegressor
from mpitree_tpu.models.regressor import DecisionTreeRegressor

__all__ = [
    "DecisionTreeClassifier",
    "ParallelDecisionTreeClassifier",
    "DecisionTreeRegressor",
    "RandomForestClassifier",
    "RandomForestRegressor",
    "BranchType",
    "Node",
    "TreeArrays",
]
