"""Histogram gradient-boosted trees (sklearn ``HistGradientBoosting*`` API).

Each round fits one tree (per class, for multiclass softmax) to the
current Newton residuals:

1. gradients/hessians come from ``losses.py`` (host f64, O(N) per round);
2. the tree grows through the SAME level-synchronous device engine every
   estimator uses — ``core/builder.build_tree(task="gbdt")`` drives the
   psum'd (count, g, h) histograms (``ops/histogram.grad_hess_histogram``)
   and the Newton-gain sweep (``ops/impurity.best_split_newton``), so data
   sharding, frontier chunking, and the f32/f64 accumulation policy are
   inherited, not duplicated;
3. leaf values are refit on host in exact f64 from the final row
   assignments (the same stance as the regressor's ``refit_regression_
   values``) — mesh-invariant, no cancellation noise — and shrunk by
   ``learning_rate`` at prediction time.

Rows never re-bin: ``X`` is binned once for the whole ensemble. Stochastic
rounds (``subsample < 1``) draw keyed Bernoulli row masks
(``ops/sampling.row_subsample_mask``) — a pure function of
(seed, round, row), so resumed fits and every mesh size agree. Excluded
rows carry ``h == 0`` and fall out of every histogram channel, but their
``node_id`` still advances, which is what makes the training-set margin
update free (no re-descent).

Resilience (``mpitree_tpu.resilience``): each round's device build runs
through the retry rung (transient transport blips re-dispatch on the
accelerator, ``retry_device``); with ``checkpoint=path`` completed rounds
persist at ``checkpoint_every`` granularity (trees plus the f64 raw-margin
matrix and early-stopping state, sharded atomic-rename ``.npz`` — see
``resilience.checkpoint``), and a killed fit re-run with the same params
and data resumes to a **bit-identical** ensemble — the keyed masks above
are exactly what makes that true. Per-round (g, h) totals are guarded for
NaN/Inf (typed ``nonfinite_grad`` event + fail-fast) so a poisoned loss
channel can never silently fit garbage rounds.
"""

from __future__ import annotations

import numbers
import time

import numpy as np
from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
from sklearn.utils.validation import check_is_fitted

from mpitree_tpu.boosting.losses import loss_for
from mpitree_tpu.core.builder import BuildConfig, build_tree
from mpitree_tpu.models.forest import _TreeList
from mpitree_tpu.obs import BuildObserver, ReportMixin, warn_event
from mpitree_tpu.ops.binning import BinnedData, bin_dataset
from mpitree_tpu.ops.predict import predict_mesh, stacked_leaf_ids
from mpitree_tpu.ops.sampling import (
    feature_subsample_mask,
    row_subsample_mask,
    seed_from,
)
from mpitree_tpu.parallel import mesh as mesh_lib
from mpitree_tpu.resilience import (
    BoostCheckpoint,
    OomRescue,
    SnapshotSlot,
    chaos,
    retry_device,
)
from mpitree_tpu.serving.tables import note_serving
from mpitree_tpu.utils.validation import (
    feature_names_of,
    resolve_min_samples_leaf,
    validate_fit_data,
    validate_fit_targets,
    validate_max_leaf_nodes,
    validate_predict_data,
    validate_sample_weight,
)


def _newton_refit(tree, leaf_ids: np.ndarray, g64: np.ndarray,
                  h64: np.ndarray, reg_lambda: float) -> np.ndarray:
    """Exact f64 Newton refit from final row assignments (in place).

    One descending rollup (children always have larger ids than their
    parent — the level-synchronous allocation order) turns per-leaf (G, H)
    sums into per-node sums; every node then gets its Newton value
    ``-G/(H + lambda)`` (returned, and stored f64 in ``count[:, 0]`` — the
    predict surface) and its structure score ``1/2 G^2/(H + lambda)`` as
    ``impurity``. The same stance as ``refit_regression_values``: the
    build's device f32 statistics drive split *selection* only; every
    persisted per-node number comes from this host pass, so the whole
    serialized tree — impurity at depth-capped leaves included — is
    mesh-invariant.
    """
    G = np.bincount(leaf_ids, weights=g64, minlength=tree.n_nodes)
    H = np.bincount(leaf_ids, weights=h64, minlength=tree.n_nodes)
    for i in range(tree.n_nodes - 1, 0, -1):
        p = tree.parent[i]
        if p < 0:
            continue
        G[p] += G[i]
        H[p] += H[i]
    denom = np.maximum(H + reg_lambda, 1e-12)
    vals = -G / denom
    tree.value = vals.astype(np.float32)
    tree.count[:, 0] = vals
    tree.impurity = 0.5 * G * G / denom
    return vals


def _host_leaf_ids(tree, X: np.ndarray) -> np.ndarray:
    """Vectorized numpy descent (validation rows during fit).

    Early stopping scores a small held-out slice once per round; each
    round's tree has a different node count, so the jitted device descent
    would recompile every round. The numpy gather loop is O(n_val * depth)
    and compiles nothing.
    """
    node = np.zeros(X.shape[0], np.int32)
    for _ in range(max(tree.max_depth, 1)):
        f = tree.feature[node]
        leaf = f < 0
        xf = X[np.arange(X.shape[0]), np.maximum(f, 0)]
        nxt = np.where(
            xf <= tree.threshold[node], tree.left[node], tree.right[node]
        )
        node = np.where(leaf, node, nxt).astype(np.int32)
    return node


def _column_slice(binned, kept):
    """Per-round feature-subset BinnedData (``colsample_bytree``).

    Slicing the binned matrix — rather than only masking candidates in
    the gain sweep — shrinks the O(N*F) histogram hot path itself: every
    engine sees a k-feature problem, the same hot path the
    sibling-subtraction frontier halves row-wise. Tree feature ids are
    remapped back through ``kept`` after each build; k is constant across
    rounds (``feature_subsample_mask`` draws exactly k), so all rounds
    share one compiled executable set.
    """
    return BinnedData(
        x_binned=np.ascontiguousarray(binned.x_binned[:, kept]),
        thresholds=binned.thresholds[kept],
        n_cand=binned.n_cand[kept],
        n_bins=binned.n_bins,
        quantized=binned.quantized,
    )


class _BaseGradientBoosting(ReportMixin, BaseEstimator):
    """Shared fit/predict machinery; subclasses bind the task and loss."""

    def __init__(self, *, loss, learning_rate=0.1, max_iter=100, max_depth=6,
                 max_leaf_nodes=None, rounds_per_dispatch="auto",
                 max_bins=256, binning="auto", subsample=1.0,
                 colsample_bytree=1.0,
                 min_samples_split=2, min_samples_leaf=20,
                 min_child_weight=1e-3, reg_lambda=0.0, min_split_gain=0.0,
                 early_stopping=False, validation_fraction=0.1,
                 n_iter_no_change=10, tol=1e-7, random_state=None,
                 n_devices=None, backend=None, verbose=0,
                 checkpoint=None, checkpoint_every=10,
                 checkpoint_compact_every=None):
        self.loss = loss
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.max_depth = max_depth
        # Leaf-wise growth budget (LightGBM's num_leaves): rounds grow
        # best-first through core/leafwise_builder when set; None keeps
        # the depth-wise level-synchronous engine.
        self.max_leaf_nodes = max_leaf_nodes
        # K boosting rounds per compiled device dispatch (boosting/
        # fused_rounds.py): "auto" = 8 on accelerators when eligible,
        # host-per-round otherwise; an explicit K forces (and raises on
        # ineligible configs).
        self.rounds_per_dispatch = rounds_per_dispatch
        self.max_bins = max_bins
        self.binning = binning
        self.subsample = subsample
        self.colsample_bytree = colsample_bytree
        self.min_samples_split = min_samples_split
        self.min_samples_leaf = min_samples_leaf
        self.min_child_weight = min_child_weight
        self.reg_lambda = reg_lambda
        self.min_split_gain = min_split_gain
        self.early_stopping = early_stopping
        self.validation_fraction = validation_fraction
        self.n_iter_no_change = n_iter_no_change
        self.tol = tol
        self.random_state = random_state
        self.n_devices = n_devices
        self.backend = backend
        self.verbose = verbose
        # Optional path for round-granular checkpoint/resume of the
        # boosting build (resilience.checkpoint.BoostCheckpoint): every
        # `checkpoint_every` completed rounds persist trees + resume state;
        # a killed fit re-run with the same params/data resumes
        # bit-identically.
        self.checkpoint = checkpoint
        self.checkpoint_every = checkpoint_every
        # Long-run hygiene (ISSUE 14): once the checkpoint accumulates
        # this many shard files, merge them into one
        # (BuildCheckpoint.compact — manifest-committed, crash-safe).
        # None disables compaction; very long builds otherwise pay one
        # file open per shard at every resume.
        self.checkpoint_compact_every = checkpoint_compact_every

    # -- fit ---------------------------------------------------------------
    def _validate_params_(self):
        if not self.learning_rate > 0:
            raise ValueError(
                f"learning_rate must be > 0, got {self.learning_rate!r}"
            )
        if int(self.max_iter) < 1:
            raise ValueError(f"max_iter must be >= 1, got {self.max_iter!r}")
        for name in ("reg_lambda", "min_split_gain", "min_child_weight"):
            if float(getattr(self, name)) < 0:
                raise ValueError(
                    f"{name} must be >= 0, got {getattr(self, name)!r}"
                )
        if not 0.0 < float(self.subsample) <= 1.0:
            raise ValueError(
                f"subsample must be in (0, 1], got {self.subsample!r}"
            )
        if not 0.0 < float(self.colsample_bytree) <= 1.0:
            raise ValueError(
                "colsample_bytree must be in (0, 1], got "
                f"{self.colsample_bytree!r}"
            )
        if int(self.checkpoint_every) < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {self.checkpoint_every!r}"
            )
        cce = self.checkpoint_compact_every
        if cce is not None and int(cce) < 2:
            raise ValueError(
                "checkpoint_compact_every must be >= 2 shards or None, "
                f"got {cce!r}"
            )
        # Shared grammar + the backend="host" refusal (boosting rounds
        # run the device engines only, same as the tree estimators).
        validate_max_leaf_nodes(self)
        rpd = self.rounds_per_dispatch
        if rpd not in (None, "auto"):
            # Strict grammar like every other param here: integral values
            # only (a float would silently truncate through int()).
            if (not isinstance(rpd, numbers.Integral)
                    or isinstance(rpd, bool) or int(rpd) < 1):
                raise ValueError(
                    "rounds_per_dispatch must be an integer >= 1 or "
                    f"'auto', got {rpd!r}"
                )

    def _streamed_refusals_(self, X, y, dataset):
        """Typed refusals for ``fit(dataset=...)`` combinations the
        streamed round loop cannot honor."""
        if dataset is not None and X is not None:
            raise ValueError(
                "pass the StreamedDataset as X or dataset=, not both"
            )
        if y is not None:
            raise ValueError(
                "a StreamedDataset carries its own targets; fit(dataset) "
                "takes no separate y — rebuild the dataset with the labels "
                "you want"
            )
        if self.early_stopping:
            raise ValueError(
                "early_stopping scores a held-out raw-feature slice by "
                "host descent every round; a streamed fit never "
                "materializes raw rows — disable early_stopping or fit "
                "in memory"
            )
        if float(self.colsample_bytree) < 1.0:
            raise ValueError(
                "colsample_bytree < 1 re-slices the binned matrix on "
                "host every round; the streamed matrix lives sharded on "
                "device — use subsample (keyed row masks stay streamed) "
                "or fit in memory"
            )

    def _fit(self, X, y, sample_weight, *, task, dataset=None,
             trace_to=None):
        self._validate_params_()
        from mpitree_tpu.models._streamed import is_streamed

        streamed = is_streamed(X, dataset)
        # Structured run record (mpitree_tpu.obs): per-round rows always
        # on (losses are already computed); phases/levels profile-gated.
        obs = BuildObserver()
        if trace_to is not None:
            # Chrome-trace timeline (obs/trace.py): a path, or a shared
            # TraceSink when one file should cover several fits + serving.
            obs.trace_to(trace_to)
        res = None
        if streamed:
            from mpitree_tpu.ingest import ingest_dataset

            self._streamed_refusals_(
                None if dataset is None else X, y, dataset
            )
            ds = dataset if dataset is not None else X
            # Placement needs the mesh BEFORE binning (chunks land on
            # their slots) — the reverse of the in-memory order below.
            mesh = mesh_lib.resolve_mesh(
                backend=self.backend, n_devices=self.n_devices
            )
            obs.set_mesh(mesh)
            with obs.span("bin"):
                res = ingest_dataset(
                    ds, mesh=mesh, max_bins=self.max_bins,
                    binning=self.binning, obs=obs,
                )
            binned = res.binned
            y_t, classes = validate_fit_targets(res.y, task=task)
            if sample_weight is not None and res.sample_weight is not None:
                raise ValueError(
                    "sample weights arrived both per-chunk and as a fit "
                    "argument; pick one"
                )
            sw = validate_sample_weight(
                res.sample_weight if sample_weight is None
                else sample_weight, binned.n_samples,
            )
            self.ingest_stats_ = res.stats
            if hasattr(self, "feature_names_in_"):
                del self.feature_names_in_
            self.n_features_ = binned.n_features
            self.n_features_in_ = binned.n_features
        else:
            names = feature_names_of(X)
            X, y_t, classes = validate_fit_data(X, y, task=task)
            sw = validate_sample_weight(sample_weight, X.shape[0])
            if names is not None:
                self.feature_names_in_ = names
            elif hasattr(self, "feature_names_in_"):
                del self.feature_names_in_
            self.n_features_ = X.shape[1]
            self.n_features_in_ = X.shape[1]
        self.n_outputs_ = 1
        if task == "classification":
            if len(classes) < 2:
                raise ValueError(
                    "gradient boosting needs at least 2 classes; got "
                    f"{len(classes)}"
                )
            self.classes_ = classes
            self.n_classes_ = len(classes)
        loss = loss_for(self.loss, task, len(classes) if classes is not None
                        else None)
        K = loss.K
        self.n_trees_per_iteration_ = K
        seed = seed_from(self.random_state)

        if streamed:
            # early_stopping was refused above: every row trains.
            X_tr = X_val = y_val = sw_val = None
            y_tr, sw_tr = y_t, sw
            n_tr = binned.n_samples
        else:
            # Held-out rows for early stopping come off the top of a keyed
            # permutation BEFORE binning: the validation slice must not
            # leak into the bin edges any more than into the trees.
            if self.early_stopping:
                if not 0.0 < float(self.validation_fraction) < 1.0:
                    raise ValueError(
                        "validation_fraction must be in (0, 1), got "
                        f"{self.validation_fraction!r}"
                    )
                perm = np.random.default_rng(seed).permutation(X.shape[0])
                n_val = max(
                    1, int(round(self.validation_fraction * X.shape[0]))
                )
                if n_val >= X.shape[0]:
                    raise ValueError(
                        "validation_fraction leaves no training rows"
                    )
                val_idx, tr_idx = perm[:n_val], perm[n_val:]
                X_tr, X_val = X[tr_idx], X[val_idx]
                y_tr, y_val = y_t[tr_idx], y_t[val_idx]
                sw_tr = sw[tr_idx] if sw is not None else None
                sw_val = sw[val_idx] if sw is not None else None
            else:
                X_tr, y_tr, sw_tr = X, y_t, sw
                X_val = y_val = sw_val = None

            n_tr = X_tr.shape[0]
            with obs.span("bin"):
                binned = bin_dataset(
                    X_tr, max_bins=self.max_bins, binning=self.binning
                )
            mesh = mesh_lib.resolve_mesh(
                backend=self.backend, n_devices=self.n_devices
            )
            obs.set_mesh(mesh)
        cfg = BuildConfig(
            task="gbdt",
            max_depth=self.max_depth,
            max_leaf_nodes=(
                None if self.max_leaf_nodes is None
                else int(self.max_leaf_nodes)
            ),
            min_samples_split=int(self.min_samples_split),
            min_child_weight=float(self.min_child_weight),
            reg_lambda=float(self.reg_lambda),
            min_split_gain=float(self.min_split_gain),
            min_leaf_rows=float(
                resolve_min_samples_leaf(self.min_samples_leaf, n_tr)
            ),
        )

        # Round-granular checkpoint (resilience.checkpoint): fingerprinted
        # over the FULL validated inputs (pre val-split — both runs split
        # identically from the seed) and every non-checkpoint param.
        # A stateful Generator/RandomState random_state draws fresh
        # entropy per fit, so the resumed run's keyed masks would differ
        # and resume would silently mix two ensembles — refuse and warn
        # (None and int are both reproducible: seed_from(None) == 0).
        ck = None
        if getattr(self, "checkpoint", None):
            if isinstance(self.random_state,
                          (np.random.Generator, np.random.RandomState)):
                warn_event(
                    obs, "checkpoint_disabled",
                    "boosting checkpointing requires a reproducible "
                    "random_state (None or a fixed integer) so a resumed "
                    "fit replays the same subsample/validation draws; "
                    "checkpoint disabled",
                    stacklevel=3,
                )
            else:
                ck_params = {
                    k_: v for k_, v in self.get_params().items()
                    if k_ not in ("checkpoint", "checkpoint_every")
                }
                ck_params["task"] = task
                if streamed:
                    # No raw matrix ever exists to hash: the sketch-derived
                    # bin table (same stream -> same edges, bit-identical)
                    # plus the real row count stand in for X; y/weights
                    # hash as usual. A resumed streamed fit re-ingests and
                    # must land on the identical table or resume refuses.
                    ck_params["streamed_rows"] = int(binned.n_samples)
                    ck_params["streamed_n_cand"] = (
                        np.asarray(binned.n_cand).tolist()
                    )
                    ck = BoostCheckpoint.open(
                        self.checkpoint, ck_params,
                        np.ascontiguousarray(binned.thresholds), y_t, sw,
                    )
                else:
                    ck = BoostCheckpoint.open(
                        self.checkpoint, ck_params, X, y_t, sw
                    )

        baseline = loss.init_raw(y_tr, sw_tr)  # (K,) f64
        self._baseline_raw = np.asarray(baseline, np.float64)
        raw_tr = np.tile(baseline, (n_tr, 1))
        raw_val = (
            np.tile(baseline, (len(X_val), 1)) if X_val is not None else None
        )
        lr = float(self.learning_rate)
        trees: list = []
        train_scores = [-loss.loss(raw_tr, y_tr, sw_tr)]
        val_scores = (
            [-loss.loss(raw_val, y_val, sw_val)] if X_val is not None else None
        )
        best_val = -np.inf if val_scores is None else val_scores[0]
        stale = 0
        n_iter = 0
        stopped_early = False
        start_round = 0
        if ck is not None and ck.trees:
            # Resume: restore the completed rounds' trees plus the exact
            # f64 raw margins and score/early-stopping state they left
            # behind. Everything after start_round re-derives from the
            # keyed (seed, round, row) masks, so the resumed ensemble is
            # bit-identical to an uninterrupted fit (pinned in
            # tests/test_resilience.py).
            st = ck.state or {}
            n_rounds, rem = divmod(len(ck.trees), K)
            rt = st.get("raw_tr")
            ts = st.get("train_scores")
            resumable = (
                rem == 0
                and rt is not None and rt.shape == raw_tr.shape
                and ts is not None and len(ts) == n_rounds + 1
                and (X_val is None) == ("raw_val" not in st)
                and (X_val is None or (
                    st["raw_val"].shape == raw_val.shape
                    and all(k in st for k in
                            ("val_scores", "best_val", "stale"))
                ))
            )
            if not resumable:
                warn_event(
                    obs, "checkpoint_disabled",
                    f"boosting checkpoint at {self.checkpoint} carries "
                    "inconsistent round state (crash inside a flush "
                    "window, or tampering); starting fresh",
                    stacklevel=3,
                )
                ck = BoostCheckpoint(self.checkpoint, ck.fingerprint)
            else:
                trees = list(ck.trees)
                raw_tr[:] = rt
                train_scores = [float(v) for v in ts]
                if X_val is not None:
                    raw_val[:] = st["raw_val"]
                    val_scores = [float(v) for v in st["val_scores"]]
                    best_val = float(st["best_val"])
                    stale = int(st["stale"])
                    # A preemption can land between the flush at the
                    # early-stop round and the checkpoint removal; the
                    # restored staleness must re-derive the verdict or a
                    # resumed fit would train past the stop.
                    stopped_early = stale >= int(self.n_iter_no_change)
                start_round = n_iter = n_rounds
                obs.event(
                    "checkpoint_resume",
                    f"resumed {n_rounds} completed boosting rounds "
                    f"({len(trees)} trees) from {self.checkpoint}",
                    rounds=n_rounds,
                )
        # Fused multi-round path (boosting/fused_rounds.py): K rounds per
        # compiled dispatch. Resolution follows the engine idiom — "auto"
        # engages on accelerators for eligible configs, an explicit K
        # forces (or raises); K == 1 keeps the host-per-round loop below.
        from mpitree_tpu.boosting import fused_rounds as fused_rounds_mod

        k_dispatch, rpd_reason = fused_rounds_mod.resolve_rounds_per_dispatch(
            self.rounds_per_dispatch,
            platform=mesh.devices.flat[0].platform,
            loss_kind=getattr(loss, "kind", None), loss_K=K,
            early_stopping=bool(self.early_stopping),
            colsample=float(self.colsample_bytree),
            max_depth=self.max_depth, max_leaf_nodes=self.max_leaf_nodes,
            # Real extents, not buffer shapes: a streamed matrix is
            # pre-padded to the mesh axes and would mis-price the pool.
            n_samples=binned.n_samples,
            n_features=binned.n_features, n_bins=binned.n_bins,
            hist_budget_bytes=cfg.hist_budget_bytes,
            feature_shards=mesh_lib.feature_shards(mesh),
            policy_evidence=cfg.policy_evidence, obs=obs,
        )
        obs.decision(
            "rounds_per_dispatch", int(k_dispatch), reason=rpd_reason
        )
        # Resilience v2 (ISSUE 14): one snapshot slot + OOM rescue per
        # fit — the slot resumes a blipped round build from its failed
        # level (host loop) and marks dispatch-boundary resume points
        # (fused loop); the rescue's shrink ladder spans rounds, so a
        # plan that OOM'd once stays shrunk for the rest of the fit.
        slot = SnapshotSlot()
        rescue = OomRescue(obs=obs, snapshot_slot=slot)
        if k_dispatch > 1:
            if not stopped_early and start_round < int(self.max_iter):
                try:
                    n_iter = fused_rounds_mod.run_fused_rounds(
                        binned=binned, y_tr=y_tr, sw_tr=sw_tr,
                        raw_tr=raw_tr,
                        trees=trees, train_scores=train_scores,
                        start_round=start_round,
                        max_iter=int(self.max_iter),
                        cfg=cfg, mesh=mesh, obs=obs, seed=seed, ck=ck,
                        lr=lr, loss_kind=loss.kind,
                        rounds_per_dispatch=int(k_dispatch),
                        subsample=float(self.subsample),
                        checkpoint_every=int(self.checkpoint_every),
                        checkpoint_compact_every=self.checkpoint_compact_every,
                        verbose=bool(self.verbose),
                        slot=slot, rescue=rescue,
                    )
                except FloatingPointError:
                    # The raise aborts _fit before the normal report
                    # assignment; attach the record now so the typed
                    # nonfinite_grad event survives for postmortem
                    # (the host loop's guard does the same).
                    self.fit_report_ = obs.report(trees=trees)
                    raise
            # An OOM rescue inside the fused loop degrades
            # rounds_per_dispatch to 1 and returns early: the fused
            # pool + donated margin carry don't scale with the dispatch
            # width, so the real shrink is finishing the remaining
            # rounds here on the host per-round loop (bit-identical
            # rounds, chunked split working set, per-round plans).
            host_rounds = range(int(n_iter), int(self.max_iter))
        else:
            host_rounds = range(start_round, int(self.max_iter))
        for r in host_rounds:
            if stopped_early:
                break  # resumed at (or past) the early-stop round
            # Chaos seam: deterministic kill/blip/hang at an exact round
            # (resilience.chaos) — how the resume-equivalence tests die.
            chaos.step("round")
            t_round = time.perf_counter() if obs.enabled else 0.0
            mask = row_subsample_mask(seed, r, n_tr, float(self.subsample))
            colsample = float(self.colsample_bytree)
            if colsample < 1.0:
                kept = np.flatnonzero(feature_subsample_mask(
                    seed, r, binned.n_features, colsample
                )).astype(np.int32)
                binned_r = _column_slice(binned, kept)
            else:
                kept = None
                binned_r = binned
            g, h = loss.grad_hess(raw_tr, y_tr)  # (N, K) f64 each
            if sw_tr is not None:
                g = g * sw_tr[:, None]
                h = h * sw_tr[:, None]
            if float(self.subsample) < 1.0:
                g = g * mask[:, None]
                h = h * mask[:, None]
            # Non-finite guard on the loss channel: one poisoned row (an
            # overflowed sigmoid/softmax margin, a NaN target that slipped
            # validation, a chaos injection) poisons the psum'd histogram
            # totals and every split after it. Checking the per-round
            # TOTALS is O(N) host work the loss already paid; fail fast
            # with a typed event instead of silently fitting garbage
            # rounds. chaos.corrupt is the injection seam the tier-1
            # chaos test drives.
            g, h = chaos.corrupt("grad_hess", g, h)
            g_total, h_total = float(np.sum(g)), float(np.sum(h))
            if not (np.isfinite(g_total) and np.isfinite(h_total)):
                msg = (
                    f"non-finite gradient/hessian totals at boosting round "
                    f"{r} (G_total={g_total}, H_total={h_total}): the raw "
                    "predictions have overflowed or the inputs carry "
                    "non-finite values; lower learning_rate, rescale "
                    "targets/sample_weight, or enable early_stopping — "
                    "refusing to fit garbage rounds"
                )
                obs.event("nonfinite_grad", msg)
                # The raise aborts _fit before the normal report
                # assignment; attach the record now so the typed event
                # survives for postmortem (dump_report, log scrapers).
                self.fit_report_ = obs.report(trees=trees)
                raise FloatingPointError(msg)
            for k in range(K):
                g32 = np.ascontiguousarray(g[:, k], np.float32)
                h32 = np.ascontiguousarray(h[:, k], np.float32)

                # Retry rung only (resilience.retry): boosting has no host
                # twin of the round build — below retries, the recovery
                # rung is the round checkpoint. Resilience v2: the shared
                # snapshot slot resumes a transient blip from the failed
                # LEVEL of this round's build, and the OOM rescue
                # re-dispatches shrinkable RESOURCE_EXHAUSTED on-device
                # (rescue.apply reads the accumulated shrinks at every
                # (re-)dispatch, so the shrunk plan is re-preflighted).
                def _round_dev(binned_r=binned_r, g32=g32, h32=h32):
                    return build_tree(
                        binned_r, g32, config=rescue.apply(cfg), mesh=mesh,
                        sample_weight=h32, return_leaf_ids=True, timer=obs,
                        snapshot_slot=slot,
                    )

                tree, leaf_ids = retry_device(
                    _round_dev,
                    what=f"gbdt round {r} tree build", obs=obs,
                    resume=slot, rescue=rescue,
                )
                if kept is not None:
                    # Back to full-matrix feature ids (the predict surface
                    # and importances read the original columns).
                    interior = tree.feature >= 0
                    tree.feature[interior] = kept[tree.feature[interior]]
                vals = _newton_refit(
                    tree, leaf_ids, g[:, k], h[:, k], float(self.reg_lambda)
                )
                raw_tr[:, k] += lr * vals[leaf_ids]
                if X_val is not None:
                    raw_val[:, k] += lr * vals[_host_leaf_ids(tree, X_val)]
                trees.append(tree)
            n_iter = r + 1
            train_scores.append(-loss.loss(raw_tr, y_tr, sw_tr))
            if self.verbose and (r % 10 == 0 or r + 1 == int(self.max_iter)):
                print(
                    f"[gbdt] round {r + 1}/{self.max_iter} "
                    f"train_loss={-train_scores[-1]:.6f}"
                )
            if val_scores is not None:
                val_scores.append(-loss.loss(raw_val, y_val, sw_val))
                if val_scores[-1] > best_val + float(self.tol):
                    best_val = val_scores[-1]
                    stale = 0
                else:
                    stale += 1
                    stopped_early = stale >= int(self.n_iter_no_change)
            obs.round(
                round=r,
                trees=K,
                subsample=float(self.subsample),
                colsample=colsample,
                train_loss=float(-train_scores[-1]),
                val_loss=(
                    float(-val_scores[-1]) if val_scores is not None else None
                ),
                stale=(int(stale) if val_scores is not None else None),
                early_stop=stopped_early,
                seconds=(
                    round(time.perf_counter() - t_round, 6)
                    if obs.enabled else None
                ),
            )
            if ck is not None and (r + 1) % int(self.checkpoint_every) == 0:
                # Round-group flush: this group's K*checkpoint_every trees
                # as one O(group) shard, plus the full resume state (exact
                # f64 margins + score history + early-stopping counters).
                state = {
                    "raw_tr": raw_tr,
                    "train_scores": np.asarray(train_scores, np.float64),
                }
                if val_scores is not None:
                    state["raw_val"] = raw_val
                    state["val_scores"] = np.asarray(val_scores, np.float64)
                    state["best_val"] = np.float64(best_val)
                    state["stale"] = np.int64(stale)
                with obs.span("checkpoint_flush"):
                    ck.append(trees[len(ck.trees):], state)
                    # Long-run hygiene: merge accumulated shard files
                    # (manifest-committed — a crash mid-compaction
                    # recovers to the pre-compaction state).
                    ck.maybe_compact(self.checkpoint_compact_every, obs)
            if stopped_early:
                break
        if ck is not None:
            ck.done()
        obs.decision(
            "early_stop", stopped_early,
            reason=(
                f"held-out loss stale for {stale} rounds "
                f"(n_iter_no_change={self.n_iter_no_change})"
                if stopped_early else
                "ran the full max_iter budget" if val_scores is not None
                else "early_stopping disabled"
            ),
            n_iter=int(n_iter),
        )
        self.trees_ = _TreeList(trees)
        self.n_iter_ = n_iter
        self.train_score_ = np.asarray(train_scores)
        self.validation_score_ = (
            np.asarray(val_scores) if val_scores is not None else None
        )
        self._loss_obj = loss
        self.fit_stats_ = obs.summary() if obs.enabled else None
        # Serving-table notes (mpitree_tpu.serving): the flat-table plan
        # the compiled inference path will serve this ensemble from.
        note_serving(obs, self.trees_)
        # Always-on structured run record (mpitree_tpu.obs): per-round
        # rows, engine decision, compile/collective accounting.
        self.fit_report_ = obs.report(trees=self.trees_)
        if res is not None:
            res.close()  # release the spill store, if the ingest made one
        return self

    # -- predict -----------------------------------------------------------
    def _loss(self):
        loss = getattr(self, "_loss_obj", None)
        if loss is None:  # loaded models skip fit; rebuild from params.
            # NOT cached on self: predict paths must leave the estimator's
            # __dict__ untouched (the sklearn conformance contract the
            # WeakIdCache docstring records), and construction is trivial.
            task = (
                "classification" if hasattr(self, "classes_") else "regression"
            )
            loss = loss_for(
                self.loss, task, getattr(self, "n_classes_", None)
            )
        return loss

    def _staged_raw(self, X):
        """Yield the (N, K) raw margin matrix after each boosting round.

        One stacked descent computes every tree's leaf ids up front (the
        shared ensemble-inference path); staging is then pure numpy
        accumulation.
        """
        check_is_fitted(self)
        X = validate_predict_data(X, self)
        K = self.n_trees_per_iteration_
        ids = stacked_leaf_ids(self.trees_, X, mesh=predict_mesh(self))
        raw = np.tile(self._baseline_raw, (X.shape[0], 1))
        lr = float(self.learning_rate)
        for r in range(len(self.trees_) // K):
            for k in range(K):
                t = self.trees_[r * K + k]
                raw[:, k] += lr * t.count[ids[r * K + k], 0]
            yield raw

    def _raw_predict(self, X):
        raw = None
        for raw in self._staged_raw(X):
            pass
        return raw

    def __sklearn_is_fitted__(self):
        return hasattr(self, "trees_")


class GradientBoostingRegressor(RegressorMixin, _BaseGradientBoosting):
    """Histogram gradient-boosted regression trees (squared error).

    sklearn ``HistGradientBoostingRegressor``-style API on the TPU-native
    level-synchronous engine; growth is depth-wise (``max_depth``, default
    6) rather than sklearn's leaf-wise ``max_leaf_nodes`` — the frontier
    IS the batch dimension here.
    """

    def __init__(self, *, loss="squared_error", learning_rate=0.1,
                 max_iter=100, max_depth=6, max_leaf_nodes=None,
                 rounds_per_dispatch="auto", max_bins=256, binning="auto",
                 subsample=1.0, colsample_bytree=1.0,
                 min_samples_split=2, min_samples_leaf=20,
                 min_child_weight=1e-3, reg_lambda=0.0, min_split_gain=0.0,
                 early_stopping=False, validation_fraction=0.1,
                 n_iter_no_change=10, tol=1e-7, random_state=None,
                 n_devices=None, backend=None, verbose=0,
                 checkpoint=None, checkpoint_every=10,
                 checkpoint_compact_every=None):
        super().__init__(
            loss=loss, learning_rate=learning_rate, max_iter=max_iter,
            max_depth=max_depth, max_leaf_nodes=max_leaf_nodes,
            rounds_per_dispatch=rounds_per_dispatch,
            max_bins=max_bins, binning=binning,
            subsample=subsample, colsample_bytree=colsample_bytree,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_child_weight=min_child_weight, reg_lambda=reg_lambda,
            min_split_gain=min_split_gain, early_stopping=early_stopping,
            validation_fraction=validation_fraction,
            n_iter_no_change=n_iter_no_change, tol=tol,
            random_state=random_state, n_devices=n_devices, backend=backend,
            verbose=verbose, checkpoint=checkpoint,
            checkpoint_every=checkpoint_every,
            checkpoint_compact_every=checkpoint_compact_every,
        )

    def fit(self, X=None, y=None, sample_weight=None, *, dataset=None,
            trace_to=None):
        return self._fit(
            X, y, sample_weight, task="regression", dataset=dataset,
            trace_to=trace_to,
        )

    def predict(self, X):
        return self._raw_predict(X)[:, 0]

    def staged_predict(self, X):
        """Prediction after each boosting round (sklearn's staged API)."""
        for raw in self._staged_raw(X):
            yield raw[:, 0].copy()


class GradientBoostingClassifier(ClassifierMixin, _BaseGradientBoosting):
    """Histogram gradient-boosted classification trees (log loss).

    Binary targets train one tree per round on the logistic gradient;
    ``C > 2`` classes train one tree per class per round on the softmax
    diagonal Newton residuals. See :class:`GradientBoostingRegressor` for
    the engine notes.
    """

    def __init__(self, *, loss="log_loss", learning_rate=0.1, max_iter=100,
                 max_depth=6, max_leaf_nodes=None,
                 rounds_per_dispatch="auto",
                 max_bins=256, binning="auto", subsample=1.0,
                 colsample_bytree=1.0,
                 min_samples_split=2, min_samples_leaf=20,
                 min_child_weight=1e-3, reg_lambda=0.0, min_split_gain=0.0,
                 early_stopping=False, validation_fraction=0.1,
                 n_iter_no_change=10, tol=1e-7, random_state=None,
                 n_devices=None, backend=None, verbose=0,
                 checkpoint=None, checkpoint_every=10,
                 checkpoint_compact_every=None):
        super().__init__(
            loss=loss, learning_rate=learning_rate, max_iter=max_iter,
            max_depth=max_depth, max_leaf_nodes=max_leaf_nodes,
            rounds_per_dispatch=rounds_per_dispatch,
            max_bins=max_bins, binning=binning,
            subsample=subsample, colsample_bytree=colsample_bytree,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            min_child_weight=min_child_weight, reg_lambda=reg_lambda,
            min_split_gain=min_split_gain, early_stopping=early_stopping,
            validation_fraction=validation_fraction,
            n_iter_no_change=n_iter_no_change, tol=tol,
            random_state=random_state, n_devices=n_devices, backend=backend,
            verbose=verbose, checkpoint=checkpoint,
            checkpoint_every=checkpoint_every,
            checkpoint_compact_every=checkpoint_compact_every,
        )

    def fit(self, X=None, y=None, sample_weight=None, *, dataset=None,
            trace_to=None):
        return self._fit(
            X, y, sample_weight, task="classification", dataset=dataset,
            trace_to=trace_to,
        )

    def decision_function(self, X):
        raw = self._raw_predict(X)
        return raw[:, 0] if raw.shape[1] == 1 else raw

    def predict_proba(self, X):
        return self._loss().proba(self._raw_predict(X))

    def predict(self, X):
        return self.classes_[self.predict_proba(X).argmax(axis=1)]

    def staged_predict_proba(self, X):
        loss = self._loss()
        for raw in self._staged_raw(X):
            yield loss.proba(raw)

    def staged_predict(self, X):
        for proba in self.staged_predict_proba(X):
            yield self.classes_[proba.argmax(axis=1)]
