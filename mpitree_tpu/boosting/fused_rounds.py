"""Fused multi-round GBDT device program (``rounds_per_dispatch=K``).

The host boosting loop pays one full dispatch round trip — and, cold,
one compile-cache probe — PER ROUND: gradients out, tree build dispatch,
decisions back, margins updated, repeat. For shallow-tree GBDT that
per-round traffic dominates the arithmetic the same way the PR-7 serving
capture showed request-path compiles dominating inference. This module
runs **K full boosting rounds inside one compiled dispatch**: a
``lax.scan`` whose body recomputes (g, h) from the carried f32 margins,
grows one leaf-wise tree (``core/leafwise_builder._make_leafwise_body``
— the best-first pool rides entirely in-program), refits leaf values
from f64-scoped (G, H) sums rounded to f32, and applies the
learning-rate-shrunk update to the donated margin carry. Per-ensemble
dispatch count drops to ``ceil(max_iter / K)`` and the compile-cache
sees ONE key per (K, shape) bucket.

Determinism contract (CPU meshes): the (g, h) recompute is elementwise
per row (mesh-layout-free); histograms accumulate scoped-f64 and round
to f32 after the psum (``resolve_gbdt_x64``, the PR-2 closure); leaf
(G, H) sums accumulate scoped-f64 and ROUND TO f32 before the division,
so every mesh size computes identical leaf values — fused-round
ensembles are bit-identical across mesh sizes. They are NOT bit-identical
to ``rounds_per_dispatch=1`` fits: the host loop carries f64 margins and
f64 leaf refits, the fused program carries f32 margins (documented
divergence, the price of the in-program carry). Keyed row subsampling
(``ops/sampling.row_subsample_mask_jnp``) is a pure function of
(seed, round, global row), so checkpoint-resumed fused fits replay the
identical draws — resume stays bit-identical.

Eligibility (``resolve_rounds_per_dispatch``): one tree per round
(binary logistic / squared error), no early stopping (held-out scoring
is per-round host work), no ``colsample_bytree`` (per-round column
slices change the compiled shape), and a static leaf budget
(``max_depth`` and/or ``max_leaf_nodes``). ``"auto"`` engages K=8 on
accelerator platforms only — on XLA-CPU dispatch is cheap and the
per-expansion leaf-wise scan costs more than it saves;
``MPITREE_TPU_ROUNDS_PER_DISPATCH`` steers the default, an explicit
``rounds_per_dispatch=K`` forces any platform (the CPU determinism tests
ride it) and raises on ineligible configurations rather than silently
degrading.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mpitree_tpu.core import leafwise_builder as leafwise
from mpitree_tpu.obs import accounting as obs_acct
from mpitree_tpu.obs import memory as obs_memory
from mpitree_tpu.core.builder import (
    fetch_row_nodes,
    resolve_gbdt_x64,
    resolve_hist_subtraction,
)
from mpitree_tpu.ops import sampling as sampling_ops
from mpitree_tpu.parallel import mesh as mesh_lib, partition
from mpitree_tpu.parallel.mesh import DATA_AXIS
from mpitree_tpu.resilience import (
    chaos,
    elastic_enabled,
    is_oom_failure,
    retry_device,
)
from mpitree_tpu.config import knobs

DEFAULT_ROUNDS_PER_DISPATCH = 8

# Leaf-pool ceiling for the fused program: each open leaf is one
# SEQUENTIAL expansion step inside the scanned round body, so a pool
# this wide already runs thousands of per-expansion psums per round —
# past it the level-wise host loop's chunked dispatches win regardless
# of round-trip savings (and under subtraction the pool-resident
# histograms scale with the pool too).
FUSED_POOL_CEILING = 4096


def resolve_rounds_per_dispatch(param, *, platform: str, loss_kind,
                                loss_K: int, early_stopping: bool,
                                colsample: float, max_depth,
                                max_leaf_nodes, n_samples=None,
                                n_features=None, n_bins=None,
                                hist_budget_bytes=None,
                                feature_shards: int = 1,
                                policy_evidence: str = "auto",
                                obs=None) -> tuple:
    """Resolve the estimator's ``rounds_per_dispatch`` into (K, reason).

    Follows the engine-resolution idiom: the env var steers the "auto"
    default only; an explicit integer wins — and raises when the
    configuration cannot honor it (silent degradation would attribute
    host-loop timings to the fused program).

    ``n_samples``/``n_features``/``n_bins``/``hist_budget_bytes`` (all
    optional) size the in-program leaf pool: a ``max_depth``-only config
    implies a ``2^max_depth`` pool, and past :data:`FUSED_POOL_CEILING`
    open leaves — or a pool-resident histogram estimate over the
    histogram HBM budget — the fused program would be pathologically
    large, so the guard blocks it like any other ineligibility.
    """
    blockers = []
    if n_samples is not None:
        pn = leafwise._pool_capacity(
            max_leaf_nodes if max_leaf_nodes is not None else 1 << 30,
            max_depth, int(n_samples),
        )
        # (count, g, h) f32 pool histograms under subtraction — the
        # widest buffer the scanned build carries (formula: obs.memory,
        # the one pricing source the capacity planner also reads).
        pool_bytes = obs_memory.pool_hist_bytes(
            pn, int(n_features or 1), int(n_bins or 256)
        )
        budget = (
            int(hist_budget_bytes) if hist_budget_bytes else 4 << 30
        )
        if pn > FUSED_POOL_CEILING or pool_bytes > budget:
            blockers.append(
                f"leaf pool of {pn} open leaves exceeds the fused-program "
                f"budget (> {FUSED_POOL_CEILING} sequential expansions "
                f"per round, or ~{pool_bytes >> 20} MiB pool histograms "
                "vs hist_budget_bytes) — set max_leaf_nodes to bound it"
            )
    if loss_K > 1 or loss_kind is None:
        blockers.append(
            "the loss has no in-device twin (multiclass softmax fits one "
            "tree per class per round)"
        )
    if early_stopping:
        blockers.append(
            "early_stopping scores the held-out slice per round on host"
        )
    if float(colsample) < 1.0:
        blockers.append(
            "colsample_bytree < 1 re-slices the binned matrix per round "
            "(one compiled shape per round set)"
        )
    if max_depth is None and max_leaf_nodes is None:
        blockers.append(
            "unbounded trees: the in-program leaf pool needs a static "
            "budget (set max_depth or max_leaf_nodes)"
        )
    if int(feature_shards) > 1:
        # The in-program leaf-wise build sweeps feature-complete pair
        # histograms — no select_global twin in the expansion loop, so a
        # (data, feature) mesh would silently reshard the slabs back to
        # feature-complete and waste the feature axis (same refusal as
        # max_leaf_nodes, resolved here instead of mis-attributed).
        blockers.append(
            "(data, feature) mesh: the fused-rounds leaf pool has no "
            "feature-axis winner merge (mesh2d_unsupported) — use a 1-D "
            "data mesh or rounds_per_dispatch=1"
        )
    flag = "auto" if param in (None, "auto") else param
    from_env = False
    env_note = ""
    if flag == "auto":
        env = knobs.value("MPITREE_TPU_ROUNDS_PER_DISPATCH")
        if env != "auto":
            try:
                ek = int(env)
            except ValueError:
                ek = -1
            if ek >= 1:
                flag, from_env = ek, True
            else:
                # An ambient env setting must never crash fits — an
                # invalid value falls back to auto, with the reason
                # string carrying the evidence for triage.
                env_note = (
                    f"MPITREE_TPU_ROUNDS_PER_DISPATCH={env!r} invalid "
                    "(ignored; use an integer >= 1 or 'auto'); "
                )
    if flag == "auto":
        if blockers:
            return 1, env_note + "auto: " + "; ".join(blockers)
        # Evidence consultation (obs/advisor.py, ISSUE 18): stored
        # gbdt_fusedK A/Bs on this platform may replace the static
        # platform preference — AFTER the blockers, which are hard
        # eligibility constraints no measurement overrides.
        from mpitree_tpu.obs import advisor

        adv = advisor.advise_rounds_per_dispatch(
            platform=platform, policy_evidence=policy_evidence,
            shape={
                k: int(v) for k, v in (
                    ("n_samples", n_samples), ("n_features", n_features),
                    ("n_bins", n_bins),
                ) if v is not None
            },
        )
        advisor.record_advice(obs, adv)
        if adv is not None and adv["value"] == "host":
            return 1, env_note + (
                "evidence: the host per-round loop measured faster on "
                f"this platform (gbdt_fusedK history, n="
                f"{adv['evidence_n']}, median speedup {adv['median']}x)"
            )
        if adv is not None and adv["value"] == "fused":
            k_ev = int(adv.get("K") or DEFAULT_ROUNDS_PER_DISPATCH)
            return k_ev, env_note + (
                f"evidence: K={k_ev} fused rounds measured "
                f"{adv['median']}x faster than the host loop "
                f"(gbdt_fusedK history, n={adv['evidence_n']})"
            )
        if platform not in ("tpu", "axon"):
            return 1, env_note + (
                "auto: host-per-round on XLA-CPU — dispatch is cheap "
                "there and the leaf-wise in-program build scans more "
                "(accelerators amortize K rounds per dispatch instead)"
            )
        return DEFAULT_ROUNDS_PER_DISPATCH, env_note + (
            f"auto: accelerator platform — {DEFAULT_ROUNDS_PER_DISPATCH} "
            "rounds per dispatch amortize round-trip and compile-cache "
            "traffic"
        )
    k = int(flag)
    if k < 1:
        raise ValueError(
            f"rounds_per_dispatch must be >= 1 or 'auto', got {param!r}"
        )
    if k > 1 and blockers:
        if from_env:
            # The env var steers the DEFAULT only — an ambient setting
            # must not crash fits it cannot apply to (the estimator
            # param is the consent surface for that).
            return 1, (
                f"MPITREE_TPU_ROUNDS_PER_DISPATCH={k} overridden "
                "(env steers the auto default only): " + "; ".join(blockers)
            )
        raise ValueError(
            f"rounds_per_dispatch={k} cannot apply: " + "; ".join(blockers)
        )
    if from_env:
        return k, f"explicit MPITREE_TPU_ROUNDS_PER_DISPATCH={k}"
    return k, f"explicit rounds_per_dispatch={k}"


def _grad_hess_jnp(loss_kind: str, raw, y):
    """In-scan (g, h) twins of ``boosting/losses.py`` (f32 elementwise)."""
    if loss_kind == "squared_error":
        g = raw - y
        return g, jnp.ones_like(g)
    # logistic — the host's tanh form, stable at both tails
    p = 0.5 * (1.0 + jnp.tanh(0.5 * raw))
    return p - y, p * (1.0 - p)


def _loss_rows_jnp(loss_kind: str, raw, y):
    """Per-row loss twins (the in-dispatch train-score channel)."""
    if loss_kind == "squared_error":
        return 0.5 * (raw - y) ** 2
    return jnp.logaddexp(0.0, raw) - y * raw


@lru_cache(maxsize=16)
def _make_rounds_fn(mesh, *, loss_kind: str, n_rounds: int, n_bins: int,
                    max_leaves: int, max_depth: int, min_samples_split: int,
                    gbdt_x64: bool, subtraction: bool, subsample_on: bool):
    """One jitted program running ``n_rounds`` boosting rounds.

    (xb, y, raw0, sw, cand_mask, mcw, mid, lam, msl, msg, lr, r0, seed,
    sub_thresh) -> (raw_out, feat, bin, counts, n, left, parent, n_nodes,
    G, H, loss_sum, loss_weight) with every tree output stacked
    (n_rounds, ...). ``r0`` is a RUNTIME round offset so every dispatch
    of the same width — including checkpoint-resumed ones — shares one
    executable.
    """
    M = 2 * max_leaves - 1
    build = leafwise._make_leafwise_body(
        n_bins=n_bins, n_classes=3, task="gbdt", criterion="mse",
        max_leaves=max_leaves, max_depth=max_depth,
        min_samples_split=min_samples_split, psum_axis=DATA_AXIS,
        exact_ties=False, gbdt_x64=gbdt_x64, subtraction=subtraction,
    )

    # graftlint: device-fn (jit-wrapped through jax.shard_map below)
    def program(xb, y, raw0, sw, cand_mask, mcw, mid, lam, msl, msg, lr,
                r0, seed, sub_thresh):
        R = y.shape[0]
        j = lax.axis_index(DATA_AXIS).astype(jnp.uint32)
        gidx = j * jnp.uint32(R) + jnp.arange(R, dtype=jnp.uint32)

        # The round's leaf refit (G/H) and training-loss reductions —
        # priced together as collective.gbdt_leaf_psum_bytes. The
        # histogram psums live in the leafwise body, not here.
        # graftlint: wire=gbdt_leaf_psum
        def round_step(raw, r):
            g, h = _grad_hess_jnp(loss_kind, raw, y)
            g = g * sw
            h = h * sw
            if subsample_on:
                m = sampling_ops.row_subsample_mask_jnp(
                    seed, r, gidx, sub_thresh
                ).astype(jnp.float32)
                g = g * m
                h = h * m
            nid0 = jnp.zeros(R, jnp.int32)
            out = build(xb, g, nid0, h, cand_mask, mcw, mid, lam, msl, msg)
            feat_a, bin_a, counts_a, n_a, left_a, parent_a = out[:6]
            nid_f, n_nodes = out[7], out[8]
            # Leaf (G, H): scoped-f64 accumulation ROUNDED to f32 before
            # the division — any row partition rounds to the same f32
            # sums (29 spare mantissa bits over the f32 terms), so leaf
            # values — and therefore margins, and therefore every later
            # round — are identical at every mesh size.
            if gbdt_x64:
                with jax.enable_x64(True):
                    zero = jnp.zeros(M, jnp.float32).astype(jnp.float64)
                    G = lax.psum(
                        zero.at[nid_f].add(g.astype(jnp.float64)),
                        DATA_AXIS,
                    ).astype(jnp.float32)
                    H = lax.psum(
                        zero.at[nid_f].add(h.astype(jnp.float64)),
                        DATA_AXIS,
                    ).astype(jnp.float32)
            else:
                G = lax.psum(
                    jax.ops.segment_sum(g, nid_f, num_segments=M), DATA_AXIS
                )
                H = lax.psum(
                    jax.ops.segment_sum(h, nid_f, num_segments=M), DATA_AXIS
                )
            # The host refit mirror (run_fused_rounds) reproduces this
            # f32 arithmetic bit for bit into tree.count[:, 0].
            vals = -G / jnp.maximum(H + lam, 1e-12)
            raw_new = raw + lr * jnp.take(vals, nid_f, mode="clip")
            ls = lax.psum(
                jnp.sum(sw * _loss_rows_jnp(loss_kind, raw_new, y)),
                DATA_AXIS,
            )
            lw = lax.psum(jnp.sum(sw), DATA_AXIS)
            return raw_new, (feat_a, bin_a, counts_a, n_a, left_a,
                             parent_a, n_nodes, G, H, ls, lw)

        raw_out, stacks = lax.scan(
            round_step, raw0, r0 + jnp.arange(n_rounds, dtype=jnp.int32)
        )
        return (raw_out,) + stacks

    sharded = jax.shard_map(
        program,
        mesh=mesh,
        # Specs from the ONE partition-rule table (parallel/partition.py):
        # row-state operands shard their rows, the margin carry rides the
        # ``raw_margin`` rule in and out, round-stacked result tables and
        # the per-leaf (G, H) / loss accumulators replicate.
        in_specs=partition.in_specs_for(
            mesh, ("x_binned", "y", "raw_margin", "sample_weight",
                   "cand_mask", ("mcw", 0), ("mid", 0), ("lam", 0),
                   ("msl", 0), ("msg", 0), ("lr", 0), ("r0", 0),
                   ("seed", 0), ("sub_thresh", 0)),
        ),
        out_specs=partition.out_specs_for(
            mesh, ("raw_margin", "feat", "bin", "counts", "n_vec",
                   "left_id", "parent_id", "n_nodes", "grad_tot",
                   "hess_tot", "loss_sum", "loss_weight"),
        ),
    )
    # The margin carry is donated (GL05: jit-of-lax-scan): each dispatch
    # device_puts a FRESH raw shard from the host mirror (GL08-safe — a
    # retried dispatch can never re-read a consumed buffer).
    return jax.jit(sharded, donate_argnums=(2,))


def _finalize_round_tree(binned, feat, bins, counts, nvec, left, parent,
                         n_nodes, G32, H32, reg_lambda: float):
    """One scanned round's buffers -> a host TreeArrays with f64 refit.

    The Newton rollup mirrors ``gradient_boosting._newton_refit`` but
    starts from the DEVICE's psum'd-and-rounded per-leaf (G, H) — leaf
    values reproduce the in-program f32 division bit for bit, so the
    predict surface replays the training-time margins exactly (in f64
    accumulation; interior values/impurities come from the f64 rollup).
    """
    tree, perm = leafwise._finalize_leafwise(
        binned, "gbdt", "mse", n_nodes, feat, bins, counts, nvec, left,
        parent, integer_counts=False,
    )
    G = np.zeros(tree.n_nodes)
    H = np.zeros(tree.n_nodes)
    G[perm] = np.asarray(G32[:n_nodes], np.float64)
    H[perm] = np.asarray(H32[:n_nodes], np.float64)
    for i in range(tree.n_nodes - 1, 0, -1):
        p = tree.parent[i]
        if p < 0:
            continue
        G[p] += G[i]
        H[p] += H[i]
    denom = np.maximum(H + reg_lambda, 1e-12)
    vals = -G / denom
    leaves = tree.left < 0
    # Leaf arithmetic replayed in f32 — the device computed
    # -G32 / max(H32 + lam, 1e-12) in f32 and updated margins with it.
    lam32 = np.float32(reg_lambda)
    vals32 = -G[leaves].astype(np.float32) / np.maximum(
        H[leaves].astype(np.float32) + lam32, np.float32(1e-12)
    )
    vals[leaves] = vals32.astype(np.float64)
    tree.value = vals.astype(np.float32)
    tree.count[:, 0] = vals
    tree.impurity = 0.5 * G * G / denom
    return tree


# graftlint: host-fn — the dispatch-granular boosting driver: host
# mirrors of margins/scores and per-dispatch device_get are its job
def run_fused_rounds(*, binned, y_tr, sw_tr, raw_tr, trees, train_scores,
                     start_round: int, max_iter: int, cfg, mesh, obs,
                     seed: int, ck, lr: float, loss_kind: str,
                     rounds_per_dispatch: int, subsample: float,
                     checkpoint_every: int,
                     checkpoint_compact_every=None,
                     verbose: bool = False,
                     slot=None, rescue=None) -> int:
    """Drive the boosting fit in K-round fused dispatches.

    Mutates ``trees``/``train_scores``/``raw_tr`` in place (the same
    state the host loop owns) and returns the completed round count.
    Checkpoints flush at DISPATCH boundaries: whenever a dispatch crosses
    a ``checkpoint_every`` multiple, the completed rounds' trees plus the
    exact margin mirror persist — a killed fit re-run with the same
    params resumes bit-identically (the keyed subsample masks and the
    runtime ``r0`` operand make resumed dispatches replay exactly).

    Resilience v2 (ISSUE 14): ``slot`` marks each dispatch boundary as a
    resume point — the loop carries the completed rounds' margin mirror
    on host, so retrying the failed dispatch IS sub-build retry at
    dispatch granularity (typed ``level_retry`` events with
    granularity="dispatch"). ``rescue``: an OOM whose ledger postmortem
    names the fused pool/margin arrays degrades ``rounds_per_dispatch``
    to 1 and RETURNS EARLY — none of those arrays scale with the
    dispatch width, so the real shrink is routing the remaining rounds
    back through gradient_boosting's host per-round loop (bit-identical
    rounds, chunked working set, per-round re-priced plans).
    ``checkpoint_compact_every``: merge checkpoint shards past this
    count at each flush (long-run hygiene).
    """
    # Real extents from the dataclass, not the buffer: a streamed matrix
    # arrives pre-padded to the mesh axes (StreamedBinnedData), and the
    # margin mirror / leaf fetch / pool pricing below must all see the
    # true row count.
    N = binned.n_samples
    F = binned.n_features
    B = binned.n_bins
    platform = mesh.devices.flat[0].platform
    gbdt_x64 = resolve_gbdt_x64(platform)
    # Ceiling guard bound: per-round f32 hessian totals never exceed
    # sum(sw) (squared error h == sw, logistic h <= sw/4), so the
    # weight total is a static upper bound for EVERY scanned round —
    # past 2**24 the parent-minus-small reconstruction could cancel
    # into a corrupt large-child histogram, and the guard falls back
    # to direct accumulation exactly like the level-wise twin (the
    # scoped-f64 CPU path is exempt inside resolve_hist_subtraction).
    total_w = float(np.sum(sw_tr)) if sw_tr is not None else float(N)
    use_sub = resolve_hist_subtraction(
        cfg, platform, "gbdt", integer_ok=False, gbdt_x64=gbdt_x64,
        total_weight=total_w, obs=obs,
        shape={"n_samples": int(N),
               "n_features": int(F),
               "n_bins": int(binned.n_bins)},
    )
    Pn = leafwise._pool_capacity(
        cfg.max_leaf_nodes if cfg.max_leaf_nodes is not None else 1 << 30,
        cfg.max_depth, N,
    )
    md = -1 if cfg.max_depth is None else int(cfg.max_depth)
    subsample_on = float(subsample) < 1.0

    # Memory ledger + OOM preflight (obs.memory, ISSUE 12): the fused
    # multi-round program never routes through build_tree, so it records
    # its own analytical plan — pool histograms, the donated margin
    # carry, the (g, h) recompute — BEFORE the first device placement.
    plan = obs_acct.build_memory_plan(
        mesh=mesh, rows=int(N), features=int(F),
        classes=2, bins=int(B), task="gbdt", max_depth=cfg.max_depth,
        max_leaf_nodes=int(Pn), gbdt_x64=gbdt_x64, subtraction=use_sub,
        hist_budget_bytes=cfg.hist_budget_bytes,
        max_frontier_chunk=cfg.max_frontier_chunk,
        max_table_slots=cfg.max_table_slots,
        rounds_per_dispatch=int(rounds_per_dispatch),
        engine="fused_rounds",
    )
    obs.memory_plan(plan.to_dict())
    obs_memory.preflight(plan, obs=obs, what="fused-rounds dispatch")

    with obs.span("shard"):
        yf = np.ascontiguousarray(y_tr, np.float32)
        xb_d, y_d, w_d, _nid_d, cand_d = mesh_lib.shard_build_inputs(
            mesh, binned, yf, sw_tr
        )
    pad = mesh_lib.pad_rows(N, mesh_lib.data_shards(mesh))

    mcw = np.float32(cfg.min_child_weight)
    mid = np.float32(cfg.min_decrease_scaled)
    lam = np.float32(cfg.reg_lambda)
    msl = np.float32(cfg.min_leaf_rows)
    msg = np.float32(cfg.min_split_gain)
    lr32 = np.float32(lr)
    sub_thresh = (
        sampling_ops.subsample_threshold_u32(float(subsample))
        if subsample_on else np.uint32(0)
    )

    # The fused path never routes through build_tree, so the record's
    # engine attribution (what the digest leads with) is claimed here.
    obs.decision(
        "engine", "fused_rounds",
        reason=(
            f"rounds_per_dispatch={rounds_per_dispatch}: K full boosting "
            "rounds (grad/hess, leaf-wise build, leaf refit, margin "
            "update) per compiled lax.scan dispatch"
        ),
        rounds_per_dispatch=int(rounds_per_dispatch), pool=int(Pn),
    )

    raw32 = np.ascontiguousarray(raw_tr[:, 0], np.float32)
    r = start_round
    while r < max_iter:
        if rescue is not None and rescue.rounds_per_dispatch:
            # An OOM rescue named the fused pool/margin arrays as
            # binding. None of them scale with the dispatch width —
            # re-dispatching a k=1 FUSED program would allocate the
            # same pool + donated margin carry + in-program (g, h) and
            # OOM identically — so the degrade EXITS to the host
            # per-round loop (gradient_boosting picks up the remaining
            # rounds; its per-round levelwise builds carry the chunked
            # split working set instead, record their own re-priced
            # plans, and are pinned bit-identical to fused rounds).
            break
        k = min(int(rounds_per_dispatch), max_iter - r)
        fn_kw = dict(
            loss_kind=loss_kind, n_rounds=k, n_bins=B, max_leaves=Pn,
            max_depth=md, min_samples_split=int(cfg.min_samples_split),
            gbdt_x64=gbdt_x64, subtraction=use_sub,
            subsample_on=subsample_on,
        )
        fn = _make_rounds_fn(mesh, **fn_kw)
        rounds_fresh = obs.compile_note(
            "fused_rounds_fn", (mesh,) + tuple(sorted(fn_kw.items())),
            cache_size=16,
        )

        def dispatch():
            # Chaos seam INSIDE the retried closure: a planned blip here
            # exercises the retry rung exactly like a transport loss at
            # the dispatch boundary (resilience.chaos).
            chaos.step("fused_rounds")
            # grad_hess corrupt seam, fused twin: (g, h) are recomputed
            # in-program from the margins, so poisoning the margin
            # mirror is how a corrupt loss channel enters here — the
            # NaN rides into every psum'd total and the post-dispatch
            # guard below fails fast exactly like the host loop's.
            raw_c = chaos.corrupt("grad_hess", raw32)
            raw_p = (
                np.concatenate([raw_c, np.zeros(pad, np.float32)])
                if pad else raw_c
            )
            raw_d = mesh_lib.shard_rows(mesh, raw_p)
            if rounds_fresh:
                obs.price_compile("fused_rounds_fn", lambda: fn.lower(
                    xb_d, y_d, raw_d, w_d, cand_d, mcw, mid, lam, msl,
                    msg, lr32, np.int32(r), np.uint32(seed), sub_thresh,
                ))
            return fn(xb_d, y_d, raw_d, w_d, cand_d, mcw, mid, lam, msl,
                      msg, lr32, np.int32(r), np.uint32(seed), sub_thresh)

        if slot is not None:
            # Dispatch-boundary resume point (ISSUE 14): the host margin
            # mirror already carries rounds < r, so retrying THIS
            # dispatch is sub-build retry at dispatch granularity — the
            # ladder's level_retry rung re-invokes the closure and only
            # rounds r..r+k-1 re-run.
            slot.save("dispatch", r, {})
        with obs.span("fused_rounds"):
            with obs.compile_attribution("fused_rounds_fn", rounds_fresh):
                try:
                    out = retry_device(
                        dispatch,
                        what=f"gbdt fused rounds {r}..{r + k - 1}",
                        obs=obs, resume=slot,
                    )
                except Exception as e:  # noqa: BLE001 — OOM-rescue seam
                    # The rescue cannot re-call the SAME closure (the
                    # shrink changes the program), so it is handled
                    # here: re-enter the loop, whose rescue check above
                    # exits to the host per-round loop.
                    if (rescue is None
                            or not (elastic_enabled()
                                    and is_oom_failure(e))
                            or not rescue.attempt(
                                e, what=f"gbdt fused rounds "
                                f"{r}..{r + k - 1}")):
                        raise
                    continue
            raw32 = np.ascontiguousarray(fetch_row_nodes(out[0], N))
            (feat_s, bin_s, counts_s, n_s, left_s, parent_s, nn_s, G_s,
             H_s, ls_s, lw_s) = jax.device_get(out[1:])
        for i in range(k):
            # Non-finite guard, fused twin of the host loop's: a poisoned
            # loss channel (overflowed f32 margin carry, NaN targets, a
            # chaos injection) poisons the psum'd (G, H)/loss totals and
            # every scanned round after it. The totals are already on
            # host — checking them is O(pool) — so fail fast with the
            # same typed event instead of silently appending garbage
            # trees; rounds before the poisoned one stay finalized.
            gt, ht = float(np.sum(G_s[i])), float(np.sum(H_s[i]))
            if not (np.isfinite(gt) and np.isfinite(ht)
                    and np.isfinite(float(ls_s[i]))):
                err = (
                    f"non-finite gradient/hessian totals at boosting "
                    f"round {r + i} (G_total={gt}, H_total={ht}, in a "
                    f"fused rounds_per_dispatch={rounds_per_dispatch} "
                    "dispatch): the f32 margin carry has overflowed or "
                    "the inputs carry non-finite values; lower "
                    "learning_rate, rescale targets/sample_weight, or "
                    "set rounds_per_dispatch=1 for the f64-margin host "
                    "loop — refusing to fit garbage rounds"
                )
                obs.event("nonfinite_grad", err)
                raise FloatingPointError(err)
            tree = _finalize_round_tree(
                binned, feat_s[i], bin_s[i], counts_s[i], n_s[i],
                left_s[i], parent_s[i], int(nn_s[i]), G_s[i], H_s[i],
                float(cfg.reg_lambda),
            )
            trees.append(tree)
            # Realized-work replay per finished round tree — the
            # in-program build emits no live counters, but the structure
            # replays its expansion work exactly (same accounting as the
            # single-tree fused leaf-wise engine), so the record's
            # rows_scanned / psum payload / expansions stay comparable
            # with the host per-round loop's live numbers.
            rows_i, coll_i, counters_i = obs_acct.leafwise_scan_rows(
                tree, n_features=F, n_bins=B,
                n_channels=3, task="gbdt", subtraction=use_sub,
                gbdt_x64=gbdt_x64, gbdt_leaf_slots=2 * Pn - 1,
            )
            for name, v in counters_i.items():
                obs.counter(name, v)
            for site, v in coll_i.items():
                obs.collective(site, calls=v["calls"], nbytes=v["bytes"])
            for row in rows_i:
                obs.level(**row)
            if obs.wants_fingerprints:
                # Per-ROUND fingerprint rows replayed from the finished
                # round tree (ISSUE 13) — commit order matches the host
                # loop's per-round build_tree commits, so obs.diff's
                # bisect names the same round index on either engine.
                obs.fingerprint_tree(obs_acct.replay_fingerprints(tree))
            mean_loss = float(ls_s[i]) / max(float(lw_s[i]), 1e-300)
            train_scores.append(-mean_loss)
            obs.round(
                round=r + i, trees=1, subsample=float(subsample),
                colsample=1.0, train_loss=mean_loss, val_loss=None,
                stale=None, early_stop=False, seconds=None,
                rounds_per_dispatch=int(rounds_per_dispatch),
            )
        obs.counter("fused_round_dispatches")
        obs.counter("rounds_fused", k)
        new_r = r + k
        if verbose:
            # The host loop prints every 10th round; one dispatch IS the
            # progress granularity here (per-round losses landed above),
            # so print per dispatch — a hung dispatch stays tellable
            # from normal progress.
            print(
                f"[gbdt] rounds {r + 1}..{new_r}/{max_iter} (fused "
                f"dispatch) train_loss={-train_scores[-1]:.6f}"
            )
        if ck is not None and (
            new_r // int(checkpoint_every) > r // int(checkpoint_every)
        ):
            raw_tr[:, 0] = raw32
            state = {
                "raw_tr": raw_tr,
                "train_scores": np.asarray(train_scores, np.float64),
            }
            with obs.span("checkpoint_flush"):
                ck.append(trees[len(ck.trees):], state)
                ck.maybe_compact(checkpoint_compact_every, obs)
        r = new_r
    if slot is not None:
        slot.clear()
    if r > start_round:
        # The f32 device carry is authoritative only for rounds that
        # actually dispatched; with zero committed dispatches (an OOM
        # rescue exiting before round one) writing raw32 back would
        # round the exact f64 margins through f32 for nothing and break
        # the host-loop continuation's bit-identity.
        raw_tr[:, 0] = raw32
    return r
