"""Histogram gradient-boosted trees on the level-synchronous engine.

The sequential, gradient-driven outer loop (XGBoost / LightGBM lineage)
layered on the proven per-tree machinery: one binned matrix for the whole
ensemble (``ops/binning.py``), per-node (count, g, h) histograms through
the same psum'd scatter path every tree build uses
(``ops/histogram.grad_hess_histogram`` + ``parallel/collective.py``), and
Newton-gain split selection (``ops/impurity.best_split_newton``) driven by
the levelwise builder (``core/builder.build_tree`` with ``task="gbdt"``).
"""

from mpitree_tpu.boosting.gradient_boosting import (
    GradientBoostingClassifier,
    GradientBoostingRegressor,
)

__all__ = ["GradientBoostingClassifier", "GradientBoostingRegressor"]
