"""Boosting losses: baseline raw scores, per-row (g, h), and eval metrics.

All host-side f64 numpy — gradients are O(N) elementwise work recomputed
once per round, dwarfed by the tree build; keeping them in f64 makes the
exact Newton leaf refit (``gradient_boosting._newton_leaf_values``) and the
early-stopping loss curves carry no f32 noise. The device sees only the
f32 casts that feed the (count, g, h) histograms.

Conventions: ``raw`` is the (N, K) margin matrix (K = trees per round);
``g``/``h`` are the first/second derivatives of the per-row loss w.r.t. the
raw score, so the Newton leaf value is ``-G/(H + lambda)`` and every loss
here is MINIMIZED. Multinomial softmax uses the diagonal hessian
``p(1-p)`` (sklearn's HistGradientBoosting choice; LightGBM's extra factor
2 is an equivalent reparametrization of the learning rate).
"""

from __future__ import annotations

import numpy as np


def _sigmoid(x: np.ndarray) -> np.ndarray:
    # tanh form: stable at both tails without piecewise masking
    return 0.5 * (1.0 + np.tanh(0.5 * x))


def _weighted_mean(v: np.ndarray, w: np.ndarray | None) -> float:
    if w is None:
        return float(np.mean(v))
    return float(np.sum(v * w) / max(np.sum(w), 1e-300))


class SquaredError:
    """1/2 (y - raw)^2 — h == 1, so Newton boosting == gradient boosting."""

    K = 1
    # In-device twin id: the fused multi-round program (boosting/
    # fused_rounds.py) recomputes (g, h) from f32 margins inside its
    # lax.scan body, keyed by this kind string — a loss without one can
    # only run the host-per-round path (rounds_per_dispatch=1).
    kind = "squared_error"

    def init_raw(self, y: np.ndarray, w: np.ndarray | None) -> np.ndarray:
        return np.array([_weighted_mean(y, w)])

    def grad_hess(self, raw: np.ndarray, y: np.ndarray):
        g = raw[:, 0] - y
        return g[:, None], np.ones_like(g)[:, None]

    def loss(self, raw: np.ndarray, y: np.ndarray,
             w: np.ndarray | None) -> float:
        return _weighted_mean(0.5 * (raw[:, 0] - y) ** 2, w)


class BinaryLogistic:
    """Binomial deviance on {0, 1} labels; one tree per round."""

    K = 1
    kind = "logistic"  # fused-round twin id (see SquaredError.kind)

    def init_raw(self, y: np.ndarray, w: np.ndarray | None) -> np.ndarray:
        p = np.clip(_weighted_mean(y.astype(np.float64), w), 1e-12, 1 - 1e-12)
        return np.array([np.log(p / (1.0 - p))])

    def grad_hess(self, raw: np.ndarray, y: np.ndarray):
        p = _sigmoid(raw[:, 0])
        return (p - y)[:, None], (p * (1.0 - p))[:, None]

    def loss(self, raw: np.ndarray, y: np.ndarray,
             w: np.ndarray | None) -> float:
        m = raw[:, 0]
        return _weighted_mean(np.logaddexp(0.0, m) - y * m, w)

    def proba(self, raw: np.ndarray) -> np.ndarray:
        p1 = _sigmoid(raw[:, 0])
        return np.stack([1.0 - p1, p1], axis=1)


class MultinomialLogistic:
    """Softmax cross-entropy; one tree per class per round."""

    kind = None  # no fused-round twin: one tree per CLASS per round

    def __init__(self, n_classes: int):
        self.K = n_classes

    def init_raw(self, y: np.ndarray, w: np.ndarray | None) -> np.ndarray:
        prior = np.zeros(self.K)
        for k in range(self.K):
            prior[k] = _weighted_mean((y == k).astype(np.float64), w)
        return np.log(np.clip(prior, 1e-12, None))

    def _softmax(self, raw: np.ndarray) -> np.ndarray:
        z = raw - raw.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)

    def grad_hess(self, raw: np.ndarray, y: np.ndarray):
        p = self._softmax(raw)
        g = p.copy()
        g[np.arange(len(y)), y] -= 1.0
        return g, p * (1.0 - p)

    def loss(self, raw: np.ndarray, y: np.ndarray,
             w: np.ndarray | None) -> float:
        z = raw - raw.max(axis=1, keepdims=True)
        lse = np.log(np.exp(z).sum(axis=1))
        return _weighted_mean(lse - z[np.arange(len(y)), y], w)

    def proba(self, raw: np.ndarray) -> np.ndarray:
        return self._softmax(raw)


def loss_for(name: str, task: str, n_classes: int | None):
    """Resolve the estimator's ``loss`` parameter to a loss object."""
    if task == "regression":
        if name in ("squared_error", "mse"):
            return SquaredError()
        raise ValueError(f"unknown regression loss: {name!r}")
    if name != "log_loss":
        raise ValueError(f"unknown classification loss: {name!r}")
    if n_classes == 2:
        return BinaryLogistic()
    return MultinomialLogistic(n_classes)
