"""ResilienceConfig: the knobs of the retry/backoff/failover ladder.

Precedence: an explicit ``ResilienceConfig`` passed by a caller wins;
otherwise :meth:`ResilienceConfig.from_env` reads the env once per
failover site:

- ``MPITREE_TPU_RETRIES`` — max in-place device retries for *transient*
  failures before the next rung (default 2; 0 disables the retry rung).
- ``MPITREE_TPU_BACKOFF_S`` — base backoff in seconds (default 0.5;
  attempt ``a`` sleeps ``base * 2**a`` plus deterministic jitter, capped).
- ``MPITREE_TPU_ELASTIC`` — ``0`` switches the whole ladder off: device
  failures raise immediately (the CI stance — a device regression must
  never silently pass on the host tier).

Malformed env values warn and fall back to the default rather than
failing a fit over a typo.
"""

from __future__ import annotations

import dataclasses
import hashlib
import warnings
from mpitree_tpu.config import knobs


def elastic_enabled() -> bool:
    return knobs.value("MPITREE_TPU_ELASTIC")


def _env_number(name: str, cast, default):
    raw = knobs.raw(name)
    if raw is None or raw == "":
        return default
    try:
        v = cast(raw)
        if v < 0:
            raise ValueError(v)
        return v
    except (TypeError, ValueError):
        warnings.warn(
            f"ignoring malformed {name}={raw!r} (expected a non-negative "
            f"{cast.__name__}); using the default {default!r}",
            stacklevel=3,
        )
        return default


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Bounded retry-with-exponential-backoff parameters.

    ``jitter_key`` seeds the *deterministic* jitter (a hash, never
    ``random``): two ranks retrying the same blip spread out, yet a rerun
    of the same config reproduces the same schedule — the same stance as
    the keyed subsample masks.
    """

    max_retries: int = 2
    backoff_base_s: float = 0.5
    backoff_cap_s: float = 8.0
    jitter_key: int = 0

    @classmethod
    def from_env(cls) -> "ResilienceConfig":
        return cls(
            max_retries=_env_number("MPITREE_TPU_RETRIES", int, 2),
            backoff_base_s=_env_number("MPITREE_TPU_BACKOFF_S", float, 0.5),
        )


def backoff_delay(cfg: ResilienceConfig, attempt: int, salt: str = "") -> float:
    """Seconds to sleep before retry ``attempt`` (0-based): exponential
    base with up to +25% deterministic jitter from (jitter_key, salt,
    attempt)."""
    base = min(cfg.backoff_base_s * (2.0 ** attempt), cfg.backoff_cap_s)
    h = hashlib.sha256(
        f"{cfg.jitter_key}:{salt}:{attempt}".encode()
    ).digest()
    frac = int.from_bytes(h[:4], "big") / 2.0**32
    return base * (1.0 + 0.25 * frac)
