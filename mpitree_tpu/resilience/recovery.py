"""Sub-build recovery state: level snapshots and the OOM rescue ladder.

Resilience v2 (ISSUE 14) refines the PR-6 ladder's granularity. PR 6
retried the *dispatch* — which for the levelwise engine is the whole
build, so a transient blip at level 17 of a depth-20 fit re-dispatched
twenty levels to recover one. The two objects here are the shared state
between an engine and the retry ladder that make recovery *targeted*:

- :class:`SnapshotSlot` — a mutable handle the engine fills with a
  :class:`LevelSnapshot` of its loop carry at each host boundary (the
  levelwise per-level boundary, the stepped best-first per-expansion
  boundary, the fused-GBDT dispatch boundary). On a transient failure,
  ``retry.py``'s sub-build rung re-invokes the build closure, the engine
  finds the snapshot and fast-forwards *from the last completed level*
  instead of restarting. Snapshots are reference captures (the engines'
  in-place mutations are deterministic re-writes, and functional device
  updates leave the captured arrays valid), so saving one costs a dict
  and a few scalars — nothing is copied except the fingerprint row list.
- :class:`OomRescue` — the rung between "retry on device" and "fall to
  host" for RESOURCE_EXHAUSTED: when the obs.memory postmortem names a
  chunk-scaled array, shrink the knob it scales with (halve
  ``max_frontier_chunk``; degrade ``hist_subtraction``→direct;
  ``rounds_per_dispatch``→1 — whichever the ledger prices as binding)
  and re-dispatch ON DEVICE, bounded at :data:`MAX_SHRINKS` shrinks.
  Every rung is a typed ``oom_rescue`` event naming the knob and the
  old/new bytes; the re-dispatch re-runs the engine's own
  ``ledger_and_preflight`` so the shrunk plan is re-priced (and
  re-refused if still over budget) before any device work commits.

``BuildConfig(level_retry="auto"|"on"|"off")`` /
``MPITREE_TPU_LEVEL_RETRY`` gate the snapshot capture
(:func:`resolve_level_retry`); the OOM rescue rides the existing
``MPITREE_TPU_ELASTIC`` gate — both are recovery behavior, not new
arithmetic, so neither changes a single fitted tree (the fingerprint
pins in ``tests/test_resilience_v2.py`` hold recovered == uninterrupted
bit-identical).
"""

from __future__ import annotations

import dataclasses
from mpitree_tpu.config import knobs

# OOM rescue ladder bound: three shrinks ~ one chunk halved 8x or every
# knob class tried once — past that the plan is not the problem and the
# host rung (which needs no HBM at all) is the honest answer.
MAX_SHRINKS = 3

LEVEL_RETRY_ENV = "MPITREE_TPU_LEVEL_RETRY"


def resolve_level_retry(flag: str) -> bool:
    """Shared ``level_retry`` resolution (the engine-resolution idiom:
    ``MPITREE_TPU_LEVEL_RETRY`` steers the default "auto" only; an
    explicit ``BuildConfig(level_retry=...)`` wins).

    "auto" resolves ON: snapshot capture is reference-grabbing at a host
    boundary the loop already crosses, and the only added device work is
    one ``block_until_ready`` on the row-assignment array per level (so
    an async update failure is attributed to the level that issued it,
    not discovered one level late). Engines with no host boundary (the
    fused single-program builds) simply never save a snapshot.
    """
    v = flag
    if v == "auto":
        v = knobs.value(LEVEL_RETRY_ENV)
    if v not in ("auto", "on", "off"):
        raise ValueError(f"unknown level_retry {v!r}")
    return v != "off"


@dataclasses.dataclass
class LevelSnapshot:
    """One resumable engine boundary.

    ``kind`` names the granularity ("level" | "expansion" | "dispatch"),
    ``position`` the last completed index (= the next one to run), and
    ``state`` the engine-owned resume payload — opaque to the ladder,
    which only reads kind/position for the typed event.
    """

    kind: str
    position: int
    state: dict


class SnapshotSlot:
    """The mutable handle shared between a build closure and the ladder.

    The engine ``save()``s at every boundary and ``clear()``s on
    success; the retry ladder's sub-build rung checks ``snapshot`` and
    accounts retries through :meth:`note_retry`. The retry budget is
    *per position*: progress (a snapshot at a later position than the
    last retry's) resets the count, so a long fit survives independent
    blips at many levels, while a dead device exhausts the budget at one
    position and falls to the next rung — with the slot cleared, so the
    full-build rungs restart clean instead of resuming into the same
    failure.
    """

    def __init__(self):
        self.snapshot: LevelSnapshot | None = None
        self.retries = 0          # consecutive retries at one position
        self.total_retries = 0    # whole-fit (the fit_report_ counter)
        self._retry_key: tuple | None = None

    def save(self, kind: str, position: int, state: dict) -> None:
        self.snapshot = LevelSnapshot(kind, int(position), state)

    def take(self, kind: str) -> dict | None:
        """The resume payload when a snapshot of ``kind`` is pending
        (None otherwise) — what an engine checks on (re-)entry."""
        s = self.snapshot
        return s.state if s is not None and s.kind == kind else None

    def clear(self) -> None:
        self.snapshot = None
        # A cleared slot means a completed build or a ladder that gave
        # up and restarted clean — either way the next build (e.g. the
        # next boosting round sharing this per-fit slot) deserves a
        # fresh per-position budget.
        self._retry_key = None
        self.retries = 0

    def note_retry(self, budget: int) -> bool:
        """Account one sub-build retry attempt; False = budget for this
        position is spent (and the slot is cleared — see class doc)."""
        s = self.snapshot
        key = None if s is None else (s.kind, s.position)
        if key != self._retry_key:
            self._retry_key = key
            self.retries = 0
        if self.retries >= budget:
            self.clear()
            return False
        self.retries += 1
        self.total_retries += 1
        return True


class OomRescue:
    """The bounded shrink ladder between "retry on device" and "host".

    Built per fit by the estimator and consulted by ``retry.py`` when
    ``is_oom_failure`` fires: :meth:`attempt` reads the memory ledger the
    failed build recorded (``obs.record.memory``), maps the binding
    chunk-scaled array to its knob (``obs.memory.shrink_knob``), applies
    the shrink to :attr:`overrides`, and emits the typed ``oom_rescue``
    event. The build closure applies :meth:`apply` to its BuildConfig on
    every (re-)dispatch, so the engine's own ``ledger_and_preflight``
    re-prices — and re-preflights — the shrunk plan before committing.

    ``snapshot_slot``: cleared on every rescue — a level snapshot holds
    device buffers shaped by the *old* plan (and is itself part of what
    exhausted the allocator), so a rescued build restarts from scratch
    under the shrunk config.
    """

    def __init__(self, obs=None, snapshot_slot: SnapshotSlot | None = None,
                 max_shrinks: int = MAX_SHRINKS):
        self.obs = obs
        self.slot = snapshot_slot
        self.max_shrinks = int(max_shrinks)
        self.shrinks = 0
        self.overrides: dict = {}

    # -- build-closure side -------------------------------------------------
    def apply(self, cfg):
        """``cfg`` with the accumulated shrinks applied (BuildConfig
        fields only — ``rounds_per_dispatch`` is read separately by the
        fused boosting loop, which owns that knob)."""
        kw = {
            k: v for k, v in self.overrides.items()
            if k in ("max_frontier_chunk", "hist_subtraction")
        }
        return dataclasses.replace(cfg, **kw) if kw else cfg

    @property
    def rounds_per_dispatch(self) -> int | None:
        return self.overrides.get("rounds_per_dispatch")

    # -- ladder side --------------------------------------------------------
    def attempt(self, exc: BaseException, *, what: str) -> bool:
        """Propose and record one shrink; True = re-dispatch on device.

        False when the ladder is spent, the ledger recorded no plan, or
        no chunk-scaled array is binding (a resident-array OOM — only a
        wider mesh or the host rung helps there).
        """
        from mpitree_tpu.obs import memory as memory_lib

        if self.shrinks >= self.max_shrinks:
            return False
        rec = getattr(self.obs, "record", None)
        mem = getattr(rec, "memory", None) or {}
        arrays = mem.get("arrays") or []
        if not arrays:
            return False
        # The postmortem's view: the top per-device arrays, largest
        # first; rescue only when one of them is shrinkable (the ISSUE-12
        # postmortem "names a chunk-scaled array").
        top = sorted(
            arrays, key=lambda a: -int(a.get("bytes_per_device", 0))
        )[:5]
        engine = (mem.get("inputs") or {}).get("engine")
        pick = None
        for a in top:
            knob = memory_lib.shrink_knob(str(a.get("name")), engine=engine)
            if knob is None:
                continue
            old_bytes = int(a.get("bytes_per_device", 0))
            if knob == "max_frontier_chunk":
                cur = self.overrides.get(
                    "max_frontier_chunk",
                    (mem.get("inputs") or {}).get("chunk_slots"),
                )
                cur = int(cur) if cur else 0
                if cur <= 1:
                    continue  # nothing left to halve — try the next array
                pick = (knob, a, old_bytes, max(cur // 2, 1),
                        old_bytes // 2)
            elif knob == "hist_subtraction":
                if self.overrides.get("hist_subtraction") == "off":
                    continue  # carry already dropped
                pick = (knob, a, old_bytes, "off", 0)
            else:  # rounds_per_dispatch -> 1
                if self.overrides.get("rounds_per_dispatch") == 1:
                    continue
                pick = (knob, a, old_bytes, 1, None)
            break
        if pick is None:
            return False
        knob, arr, old_bytes, new_value, new_bytes = pick
        self.overrides[knob] = new_value
        self.shrinks += 1
        if self.slot is not None:
            self.slot.clear()
        if self.obs is not None:
            self.obs.counter("oom_rescues")
            self.obs.event(
                "oom_rescue",
                f"device OOM during {what} ({type(exc).__name__}: "
                f"{str(exc)[:160]}); the memory ledger prices "
                f"{arr.get('name')!r} as the binding chunk-scaled array — "
                f"shrinking {knob} to {new_value!r} and re-dispatching "
                f"on-device (rung {self.shrinks}/{self.max_shrinks}; "
                "preflight re-prices the shrunk plan before the next "
                "dispatch commits)",
                knob=knob,
                new_value=new_value,
                binding_array=arr.get("name"),
                old_bytes=old_bytes,
                new_bytes=new_bytes,
                shrink=self.shrinks,
                hbm_peak_bytes=mem.get("hbm_peak_bytes"),
            )
        return True
