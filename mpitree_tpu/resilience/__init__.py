"""mpitree_tpu.resilience — the failure-handling subsystem.

The reference's failure story is "a rank dying inside ``comm.allgather``
aborts the job" (SURVEY §5). This package is the TPU-native answer, a
standard training-stack resilience ladder:

1. **retry in place** — transient transport blips re-dispatch on the
   accelerator with bounded exponential backoff (``retry``);
2. **checkpoint at natural barriers** — forest tree groups and boosting
   round groups persist as they complete and resume bit-identically
   (``checkpoint``);
3. **degrade last** — only terminal device failures (or an exhausted
   retry budget) rebuild on the host tier, which produces the identical
   tree (``retry.device_failover``'s final rung);

plus the deterministic fault-injection layer (``chaos``) that proves
every rung in CI without hardware. ``mpitree_tpu.utils.elastic`` (the
pre-PR-6 home) re-exports this API for backward compatibility.

Resilience v2 (ISSUE 14) refines rung 1's granularity: engines with a
host boundary snapshot their loop carry (``recovery.SnapshotSlot``) so a
transient blip re-dispatches from the last completed level/expansion/
dispatch instead of restarting the build, and a RESOURCE_EXHAUSTED
whose memory-ledger postmortem names a chunk-scaled array is rescued
ON DEVICE by a bounded, priced shrink ladder (``recovery.OomRescue``)
before the host rung.

Env surface: ``MPITREE_TPU_RETRIES``, ``MPITREE_TPU_BACKOFF_S``,
``MPITREE_TPU_ELASTIC``, ``MPITREE_TPU_LEVEL_RETRY``,
``MPITREE_TPU_CHAOS`` — see ``config``, ``recovery`` and ``chaos``.
"""

from mpitree_tpu.resilience import chaos
from mpitree_tpu.resilience.checkpoint import (
    BoostCheckpoint,
    BuildCheckpoint,
    ForestCheckpoint,
)
from mpitree_tpu.resilience.config import (
    ResilienceConfig,
    backoff_delay,
    elastic_enabled,
)
from mpitree_tpu.resilience.failure import (
    is_device_failure,
    is_oom_failure,
    is_transient_failure,
)
from mpitree_tpu.resilience.recovery import (
    OomRescue,
    SnapshotSlot,
    resolve_level_retry,
)
from mpitree_tpu.resilience.retry import device_failover, retry_device

__all__ = [
    "BoostCheckpoint",
    "BuildCheckpoint",
    "ForestCheckpoint",
    "OomRescue",
    "ResilienceConfig",
    "SnapshotSlot",
    "backoff_delay",
    "chaos",
    "device_failover",
    "elastic_enabled",
    "is_device_failure",
    "is_oom_failure",
    "is_transient_failure",
    "resolve_level_retry",
    "retry_device",
]
