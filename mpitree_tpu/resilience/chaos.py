"""Deterministic fault injection — the chaos layer that proves the ladder.

None of the recovery paths (retry rung, host failover, checkpoint resume)
should only ever run for real when a tunnel actually dies at 3am. This
module lets tests — and the tier-1 chaos job — inject the exact failure
shapes PJRT produces, at exact points, on CPU, deterministically:

- ``Fault("dispatch", at=3, kind="unavailable")`` — the 3rd device
  dispatch raises ``UNAVAILABLE`` (a chaos-built exception whose type
  *name* is ``XlaRuntimeError``, so the failure classifier treats it
  exactly like jaxlib's).
- ``Fault("grad_hess", at=2, kind="nan")`` — poison the round-2 (g, h)
  payload with NaN (exercises the non-finite guard).
- ``Fault("round", at=5, kind="kill")`` — simulate a preemption at
  boosting round 5 (``ChaosKilled`` derives from ``BaseException`` so no
  recovery layer can swallow it — like a real SIGKILL).
- ``Fault("level", at=4, kind="hang", arg=0.05)`` — stall a level
  dispatch (watchdog/timeout paths).

Sites are host-side seams, zero-cost when no plan is installed (one
module-global ``is None`` check): ``dispatch`` (the retry ladder, one
step per device attempt), ``split_dispatch``/``counts_dispatch``/
``update_dispatch`` (the levelwise collective programs,
``parallel/collective.py``), ``level`` (each level of the levelwise
loop), ``round`` (each boosting round), ``grad_hess`` (the per-round
gradient payload, via :func:`corrupt`), ``serving_dispatch`` (the
compiled-inference request path, ``serving/traversal.py``), and
``sched_dispatch`` (the continuous-batching scheduler's coalesced
dispatch, ``serving/scheduler.py`` — an ``unavailable`` blip exercises
the requeue-once rung; a ``hang`` stalls the worker so the backlog
grows and admissions shed: the deterministic overload burst). The fused
single-program engines (ISSUE 8) add: ``leafwise_build`` (immediately
before the one-dispatch best-first build,
``core/leafwise_builder.py``), ``expansion`` (each step of the
host-stepped best-first loop), ``expand_dispatch`` (its per-expansion
collective program), and ``fused_rounds`` (inside the retried closure
of each K-round fused GBDT dispatch, ``boosting/fused_rounds.py`` —
a blip here exercises the retry rung exactly like a transport loss at
the dispatch boundary).

Install programmatically (:func:`install` / :func:`active`) or via
``MPITREE_TPU_CHAOS="site:at:kind[:arg];..."`` (e.g.
``dispatch:1:unavailable;round:3:hang:0.5``) — the env form is how the
CI chaos job and the bench harness inject without touching code. All
counting is per-plan and 1-based; a plan is exhausted, never random.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from mpitree_tpu.config import knobs


class ChaosXlaError(Exception):
    """Chaos-injected accelerator failure.

    The type NAME is rebound to ``XlaRuntimeError`` below so
    ``resilience.failure``'s name-based classification (which cannot
    import jaxlib's private exception type) treats injected faults
    exactly like real ones. Tests that need to catch it still have the
    ``chaos.ChaosXlaError`` module attribute.
    """


ChaosXlaError.__name__ = "XlaRuntimeError"


class ChaosKilled(BaseException):
    """Simulated preemption/SIGKILL. Derives from BaseException on
    purpose: no recovery rung may swallow it — the process is 'dead', and
    only the on-disk checkpoint survives."""


_STATUS = {
    "unavailable": "UNAVAILABLE",
    "deadline": "DEADLINE_EXCEEDED",
    "aborted": "ABORTED",
    "cancelled": "CANCELLED",
    "internal": "INTERNAL",
    "data_loss": "DATA_LOSS",
    # ISSUE 12: allocator exhaustion, terminal by classification — the
    # seam the OOM-postmortem and straight-to-host-rung tests inject
    # (message mirrors a real PJRT allocator failure).
    "oom": "RESOURCE_EXHAUSTED",
}

_KINDS = tuple(_STATUS) + ("nan", "hang", "kill", "skew")


@dataclasses.dataclass(frozen=True)
class Fault:
    """One planned fault: fire at the ``at``-th (1-based) step of ``site``.

    ``arg``: seconds for ``kind='hang'``; skew factor for ``kind='skew'``;
    ignored otherwise.

    Resilience-v2 arms (ISSUE 14 — the ``level_kill_at``/``oom_until``
    seams, expressible from the env grammar too):

    - ``at_level``: match only steps whose site reported this level/
      expansion index (``chaos.step("level", level=depth)``); ``at`` then
      counts *matching* steps — so ``Fault("level", 1, "unavailable",
      at_level=4)`` fires the FIRST time level 4 runs and stays quiet
      when the sub-build retry re-dispatches it. Sites that report no
      level never match an ``at_level`` fault.
    - ``clears_after``: the fault fires on ``clears_after`` consecutive
      matching steps starting at ``at``, then clears — an OOM that stops
      reproducing once the engine has shrunk its plan ``n`` times
      (``oom_until=n``). ``None`` keeps the fire-exactly-once semantics.
    """

    site: str
    at: int
    kind: str
    arg: float | None = None
    at_level: int | None = None
    clears_after: int | None = None

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown chaos fault kind {self.kind!r}; one of {_KINDS}"
            )
        if self.at < 1:
            raise ValueError(f"fault 'at' is 1-based, got {self.at}")
        if self.clears_after is not None and self.clears_after < 1:
            raise ValueError(
                f"fault 'clears_after' must be >= 1, got {self.clears_after}"
            )


class ChaosPlan:
    """A set of faults plus the per-site step counters that sequence them.

    Counters live on the plan (not the module) so installing a fresh plan
    restarts the clock — what makes kill-at-round-k tests deterministic.
    ``fired`` records ``(site, step, kind)`` for every fault that actually
    triggered, so a test can assert the injection happened.
    """

    def __init__(self, faults):
        self.faults = [
            f if isinstance(f, Fault) else Fault(*f) for f in faults
        ]
        self.counts: dict[str, int] = {}
        # Per-fault matching-step counters: for plain faults every site
        # step matches (hits == counts[site]); ``at_level`` faults count
        # only the steps whose reported level matched, so a sub-build
        # retry re-running earlier levels cannot desynchronize the clock.
        self.hits: dict[int, int] = {}
        self.fired: list[tuple[str, int, str]] = []

    def step(self, site: str, level: int | None = None) -> Fault | None:
        n = self.counts.get(site, 0) + 1
        self.counts[site] = n
        hit = None
        # Every matching fault's clock advances on every step (no early
        # return): two faults planned at steps 1 and 2 of one site must
        # fire on consecutive steps, not drift apart.
        for i, f in enumerate(self.faults):
            if f.site != site:
                continue
            if f.at_level is not None and level != f.at_level:
                continue
            h = self.hits.get(i, 0) + 1
            self.hits[i] = h
            if hit is None and (
                h == f.at if f.clears_after is None
                else f.at <= h < f.at + f.clears_after
            ):
                hit = f
        if hit is not None:
            self.fired.append((site, n, hit.kind))
        return hit


_PLAN: ChaosPlan | None = None
# Env plans are parsed once per distinct spec string and keep their step
# counters for the life of the process (matching "the 3rd dispatch" of a
# whole run, which is what a CI chaos job injects against).
_ENV_SPEC: str | None = None
_ENV_PLAN: ChaosPlan | None = None


def parse_plan(spec: str) -> ChaosPlan:
    """Parse ``"site:at:kind[:arg][:key=value...];..."`` into a
    :class:`ChaosPlan`.

    Trailing fields are either ONE positional float ``arg`` or named
    ``key=value`` pairs (``at_level``, ``clears_after``, ``arg``) — so
    the v2 seams stay env-expressible:
    ``level:1:unavailable:at_level=4`` (the ``level_kill_at`` seam) and
    ``level:1:oom:clears_after=2`` (the ``oom_until`` seam).
    """
    faults = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) < 3:
            raise ValueError(
                f"malformed chaos fault {part!r}; expected "
                "site:at:kind[:arg][:key=value...]"
            )
        site, at, kind = bits[0], int(bits[1]), bits[2]
        arg = None
        named: dict = {}
        for bit in bits[3:]:
            if "=" in bit:
                key, _, val = bit.partition("=")
                if key == "arg":
                    named["arg"] = float(val)
                elif key in ("at_level", "clears_after"):
                    named[key] = int(val)
                else:
                    raise ValueError(
                        f"unknown chaos fault option {key!r} in {part!r}; "
                        "one of arg/at_level/clears_after"
                    )
            elif arg is None and not named:
                arg = float(bit)
            else:
                raise ValueError(
                    f"malformed chaos fault {part!r}: positional arg must "
                    "come before (and at most once among) key=value options"
                )
        if arg is not None:
            named["arg"] = arg
        faults.append(Fault(site, at, kind, **named))
    return ChaosPlan(faults)


def install(plan) -> ChaosPlan:
    """Install a plan (a ChaosPlan, an iterable of Faults, or a spec
    string); returns the live plan object (for ``.fired`` assertions)."""
    global _PLAN
    if isinstance(plan, str):
        plan = parse_plan(plan)
    elif not isinstance(plan, ChaosPlan):
        plan = ChaosPlan(plan)
    _PLAN = plan
    return plan


def clear() -> None:
    """Remove any programmatic plan and forget the cached env plan."""
    global _PLAN, _ENV_SPEC, _ENV_PLAN
    _PLAN = None
    _ENV_SPEC = None
    _ENV_PLAN = None


@contextlib.contextmanager
def active(*faults):
    """``with chaos.active(Fault(...), ...):`` — install for a block."""
    plan = install(faults)
    try:
        yield plan
    finally:
        clear()


def _current() -> ChaosPlan | None:
    if _PLAN is not None:
        return _PLAN
    spec = knobs.raw("MPITREE_TPU_CHAOS")
    if not spec:
        return None
    global _ENV_SPEC, _ENV_PLAN
    if spec != _ENV_SPEC:
        _ENV_SPEC = spec
        _ENV_PLAN = parse_plan(spec)
    return _ENV_PLAN


def _fire(f: Fault, site: str, n: int) -> None:
    if f.kind == "oom":
        raise ChaosXlaError(
            f"RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            f"chaos-injected fault at {site}#{n}"
        )
    if f.kind in _STATUS:
        raise ChaosXlaError(
            f"{_STATUS[f.kind]}: chaos-injected fault at {site}#{n}"
        )
    if f.kind == "kill":
        raise ChaosKilled(f"chaos-injected preemption at {site}#{n}")
    if f.kind == "hang":
        time.sleep(float(f.arg or 0.0))
    # kind == "nan" is corrupt()-only: a raise site stepping past one is
    # a plan mistake, not a crash — ignore it here.


def step(site: str, level: int | None = None) -> None:
    """Advance ``site``'s step counter; fire a matching fault if planned.

    The hook every raise/hang seam calls. ``level``: the site's current
    level/expansion index, matched by ``Fault(at_level=...)`` — the
    level-wise loop reports its depth, the stepped best-first loop its
    expansion ordinal. No plan installed: one global read, zero
    allocation — always-on seams cost nothing in production.
    """
    plan = _current()
    if plan is None:
        return
    f = plan.step(site, level)
    if f is not None:
        _fire(f, site, plan.counts[site])


def corrupt(site: str, *arrays):
    """Advance ``site``; on a planned ``nan`` fault, return copies of
    ``arrays`` with NaN poisoned into the first element of each — the
    payload-corruption seam (raise/hang kinds also honor their semantics
    here, so one site can plan either shape).
    """
    plan = _current()
    if plan is None:
        return arrays if len(arrays) != 1 else arrays[0]
    f = plan.step(site)
    if f is not None:
        if f.kind == "nan":
            poisoned = []
            for a in arrays:
                a = a.copy()
                a.reshape(-1)[0] = float("nan")
                poisoned.append(a)
            arrays = tuple(poisoned)
        elif f.kind == "skew":
            # Finite corruption (ISSUE 13): scale the first half of each
            # payload by ``arg`` (default 2.0). Unlike ``nan`` — which the
            # non-finite guards fail-fast on — a skewed payload builds a
            # VALID but DIFFERENT tree, which is exactly what the
            # fingerprint-divergence sentinel must localize to its first
            # divergent level and channel (obs.diff).
            factor = float(f.arg if f.arg is not None else 2.0)
            skewed = []
            for a in arrays:
                a = a.copy()
                flat = a.reshape(-1)
                flat[: max(len(flat) // 2, 1)] *= factor
                skewed.append(a)
            arrays = tuple(skewed)
        else:
            _fire(f, site, plan.counts[site])
    return arrays if len(arrays) != 1 else arrays[0]
