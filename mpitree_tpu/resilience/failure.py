"""Failure classification: which exceptions mean the accelerator is gone.

The reference has no failure taxonomy at all — a rank dying inside
``comm.allgather`` aborts the job (``mpitree/tree/decision_tree.py:456``).
Our TPU-native analogue of a lost rank is a lost/hung accelerator client:
``XlaRuntimeError`` (UNAVAILABLE / DEADLINE_EXCEEDED / INTERNAL) or a PJRT
wire error surfacing as ``RuntimeError``. Two orthogonal questions, two
predicates:

- :func:`is_device_failure` — is this an accelerator/runtime loss at all
  (vs a program bug or user error, which must re-raise untouched)?
- :func:`is_transient_failure` — is it the kind of loss a bounded retry
  can heal (a tunnel blip), vs a terminal one (compiler crash, data loss)
  where re-running the same program on the same runtime buys nothing?

Both walk the exception chain (``__cause__``/``__context__``, bounded
depth): library layers routinely wrap transport errors as
``raise RuntimeError(...) from XlaRuntimeError(UNAVAILABLE)``, and
matching only the outermost link used to re-raise exactly the failures
this subsystem exists to recover. The walk refuses to look past an
unambiguous user-error link (``ValueError`` & friends): a bug raised
*while handling* a device failure is still a bug the caller must see.
"""

from __future__ import annotations

# Status markers that identify an accelerator/transport loss inside an
# exception message. Deliberately conservative: program bugs
# (INVALID_ARGUMENT shape errors, ENOSPC, arbitrary RuntimeErrors) must
# re-raise, or a device-engine regression would silently pass CI on the
# 10-100x slower host tier.
# Matching is CASE-SENSITIVE on purpose: the uppercase entries are gRPC
# status codes exactly as PJRT prints them — lowercasing would make
# ordinary prose ("Resource temporarily unavailable", "launch aborted")
# classify as transport loss.
_TRANSPORT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "DATA_LOSS",
    "ABORTED",
    "CANCELLED",
    "RESOURCE_EXHAUSTED",
    "Connection",
    "connection",
    "socket",
    "PJRT",
    "pjrt",
)

# Terminal statuses: still device failures (the host tier rescues the
# fit) but re-dispatching the same program at the same runtime state
# would fail the same way, so the retry rung skips straight past them.
# Checked with PRIORITY over the transient markers — a real
# "INTERNAL: PJRT_LoadedExecutable_Execute failed" carries both kinds of
# token, and burning the retry budget on it would just delay the rescue.
# RESOURCE_EXHAUSTED (ISSUE 12): an OOM is deterministic for a given
# program + live state — re-dispatching the identical program burns the
# whole retry/backoff ladder to fail identically, so it goes straight to
# the host rung (with the memory ledger's postmortem, resilience/retry).
_TERMINAL_MARKERS = ("INTERNAL", "DATA_LOSS", "RESOURCE_EXHAUSTED")

# OOM-shaped markers (the postmortem trigger): the gRPC status plus the
# prose PJRT puts in allocator failures.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")

# The retryable subset: statuses a healthy-again transport serves on the
# next attempt.
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "Connection",
    "connection",
    "socket",
    "PJRT",
    "pjrt",
)

# Definite user-error/program-bug types: never classified, and the chain
# walk stops rather than looking past them (see module docstring).
_USER_ERROR_TYPES = (
    ValueError,
    TypeError,
    KeyError,
    IndexError,
    AttributeError,
    AssertionError,
    NotImplementedError,
)

# Chained-exception walk bound: real wrap chains are 2-3 deep; anything
# deeper is pathological and O(1) inspection matters on the hot except
# path.
_MAX_CHAIN_DEPTH = 8


def _chain(exc: BaseException):
    """Yield ``exc`` then its causes/contexts, bounded and cycle-safe.

    ``__cause__`` (explicit ``raise ... from e``) wins over ``__context__``
    (implicit during-handling chaining) at each link, mirroring how
    tracebacks render the chain.
    """
    seen: set[int] = set()
    node: BaseException | None = exc
    for _ in range(_MAX_CHAIN_DEPTH):
        if node is None or id(node) in seen:
            return
        seen.add(id(node))
        yield node
        if node is not exc and isinstance(node, _USER_ERROR_TYPES):
            # A user error anywhere down the chain: whatever sits below it
            # was already being handled when the bug fired — stop here.
            return
        if node.__cause__ is not None:
            node = node.__cause__
        elif node.__suppress_context__:
            # `raise ... from None`: the raiser explicitly severed the
            # chain — honoring it is what keeps a deliberate new error
            # from inheriting a handled device failure's classification.
            return
        else:
            node = node.__context__


def _one_is_device_failure(exc: BaseException) -> bool:
    """The single-link test (PR-1..5 semantics, unchanged)."""
    name = type(exc).__name__
    msg = str(exc)
    if name in ("XlaRuntimeError", "JaxRuntimeError"):
        return any(m in msg for m in _TRANSPORT_MARKERS + ("INTERNAL",))
    if isinstance(exc, ConnectionError):
        return True  # ConnectionReset/Refused/Aborted ARE transport losses
    if isinstance(exc, (RuntimeError, OSError)):
        return any(m in msg for m in _TRANSPORT_MARKERS)
    return False


def _one_is_transient(exc: BaseException) -> bool:
    name = type(exc).__name__
    msg = str(exc)
    if name in ("XlaRuntimeError", "JaxRuntimeError"):
        return (
            not any(m in msg for m in _TERMINAL_MARKERS)
            and any(m in msg for m in _TRANSIENT_MARKERS)
        )
    if isinstance(exc, ConnectionError):
        return True
    if isinstance(exc, (RuntimeError, OSError)):
        return (
            not any(m in msg for m in _TERMINAL_MARKERS)
            and any(m in msg for m in _TRANSIENT_MARKERS)
        )
    return False


def is_device_failure(exc: BaseException) -> bool:
    """True when ``exc`` (or a chained cause/context) is an accelerator loss.

    ``XlaRuntimeError`` (jaxlib) / jax's ``JaxRuntimeError`` qualify only
    when they carry a transport status (UNAVAILABLE, DEADLINE_EXCEEDED,
    ...; INTERNAL also qualifies there — runtime/compiler crashes surface
    so) — an INVALID_ARGUMENT program bug re-raises. A plain
    ``RuntimeError``/``OSError`` qualifies only on an explicit transport
    marker (ENOSPC's "No space left on device" does not). ValueError &
    friends — user errors — never do, and the chain walk will not look
    past one (a bug raised while handling a device failure is still a
    bug).
    """
    if isinstance(exc, _USER_ERROR_TYPES):
        return False
    return any(_one_is_device_failure(e) for e in _chain(exc))


def is_oom_failure(exc: BaseException) -> bool:
    """True when the failure is allocator exhaustion (RESOURCE_EXHAUSTED
    / "Out of memory") anywhere down the chain — terminal by
    classification (see ``_TERMINAL_MARKERS``), and the trigger for the
    retry ladder's memory-ledger postmortem."""
    if isinstance(exc, _USER_ERROR_TYPES):
        return False
    return any(
        any(m in str(e) for m in _OOM_MARKERS)
        and _one_is_device_failure(e)
        for e in _chain(exc)
    )


def is_transient_failure(exc: BaseException) -> bool:
    """True when ``exc`` is a device failure a bounded retry can heal.

    The retry rung of the resilience ladder keys off this: UNAVAILABLE /
    DEADLINE_EXCEEDED / ABORTED / CANCELLED and connection-shaped errors
    re-dispatch on the accelerator; INTERNAL and DATA_LOSS (still device
    failures) skip straight to the host-failover rung.
    """
    if isinstance(exc, _USER_ERROR_TYPES):
        return False
    return any(_one_is_transient(e) for e in _chain(exc))
